"""KT4xx cross-layer certifier battery.

Golden fixtures under tests/policies/ anchor each code: the corpus
certifies with zero KT401s, seeded tensor corruptions of
cert_divergent_seed.yaml must surface KT401, a hand-escalated
cert_wasted_host.yaml must surface KT402 (and a genuinely host-only
rule must not), cert_msg_variable.yaml pins KT403, and
cert_incomplete_list.yaml pins KT404. The fuzz-repro leg round-trips a
divergence through its JSON repro and the greedy minimizer.

Host-only: compiles IR and tensors with numpy, never imports jax.
"""

import json
import os

import numpy as np
import pytest

from kyverno_tpu.analysis.certify import certify_policies, certify_tensors
from kyverno_tpu.analysis.difffuzz import (
    Divergence,
    divergence_to_diagnostic,
    minimize,
    run_fuzz,
)
from kyverno_tpu.api.load import load_policies_from_path
from kyverno_tpu.models.compiler import (
    TensorDictionary,
    assemble_tensors,
    compile_segment,
)
from kyverno_tpu.models.ir import CheckOp, compile_rule_ir

POLICY_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "policies")


def _build(name, mutate_ir=None):
    """Compile one fixture to (policy, irs, tensors); ``mutate_ir`` runs
    on the IR list before segment compile (for forced escalations)."""
    p = load_policies_from_path(os.path.join(POLICY_DIR, name))[0]
    vrules = [r for r in p.spec.rules if r.has_validate()]
    irs = [compile_rule_ir(p, r, i) for i, r in enumerate(vrules)]
    if mutate_ir is not None:
        mutate_ir(irs)
    d = TensorDictionary()
    seg = compile_segment(irs, d, name=p.name)
    return p, irs, assemble_tensors([seg], d)


def _codes(result):
    return {d.code for d in result.diagnostics}


# ---------------------------------------------------------------- corpus


def test_corpus_certifies_with_zero_divergences():
    """Acceptance criterion: the certifier discharges 100% of the
    device-decided corpus — every rule certified, host, or explicitly
    KT404-counted, and no KT401 anywhere."""
    res = certify_policies(load_policies_from_path(POLICY_DIR))
    assert not res.divergences, [d.format() for d in res.divergences]
    assert res.statuses, "corpus produced no statuses"
    for key, status in res.statuses.items():
        assert status in ("certified", "incomplete", "host"), (key, status)
    counts = res.counts()
    assert counts.get("certified", 0) >= 4
    assert res.states_checked > 0


def test_corpus_incomplete_rules_all_carry_kt404():
    """KT404 is counted, never dropped: one INFO per incomplete rule."""
    res = certify_policies(load_policies_from_path(POLICY_DIR))
    incomplete = {k for k, s in res.statuses.items() if s == "incomplete"}
    flagged = {(d.policy, d.rule) for d in res.diagnostics
               if d.code == "KT404"}
    assert incomplete <= flagged


# ---------------------------------------------------- KT401: divergence


def test_seeded_op_corruption_raises_kt401():
    """Swapping the glob check's op for a boolean compare makes the
    device program disagree with the host walk on a concrete witness."""
    _, _, t = _build("cert_divergent_seed.yaml")
    t.chk_op = np.array(t.chk_op).copy()
    t.chk_op[0] = int(CheckOp.BOOL_EQ)
    res = certify_tensors(t)
    kt401 = [d for d in res.diagnostics if d.code == "KT401"]
    assert kt401, _codes(res)
    assert "device=" in kt401[0].message and "host=" in kt401[0].message


def test_seeded_nfa_unwiring_raises_kt401():
    """Detaching the check's NFA id leaves the device matcher unable to
    reproduce the host glob — a divergence, not a silent skip."""
    _, _, t = _build("cert_divergent_seed.yaml")
    t.chk_nfa = np.array(t.chk_nfa).copy()
    t.chk_nfa[0] = -1
    res = certify_tensors(t)
    assert any(d.code == "KT401" for d in res.diagnostics), _codes(res)


def test_pristine_seed_fixture_certifies_clean():
    _, _, t = _build("cert_divergent_seed.yaml")
    res = certify_tensors(t)
    assert not res.divergences, [d.format() for d in res.divergences]
    assert list(res.statuses.values()) == ["certified"]


# ------------------------------------------- KT402: wasted escalation


def test_forced_escalation_raises_kt402():
    def escalate(irs):
        irs[0].host_only = True
        irs[0].host_reason = "test: forced escalation"

    _, _, t = _build("cert_wasted_host.yaml", mutate_ir=escalate)
    res = certify_tensors(t)
    assert any(d.code == "KT402" for d in res.diagnostics), _codes(res)
    assert res.statuses[("cert-wasted-host", "pin-replica-floor")] == "host"


def test_genuine_host_rule_not_flagged_kt402():
    """sample_host_variable's variable-reference rule re-escalates when
    recompiled from scratch — the discharge probe must stay silent."""
    _, _, t = _build("sample_host_variable.yaml")
    res = certify_tensors(t)
    assert not any(d.code == "KT402" for d in res.diagnostics), _codes(res)
    assert res.statuses[("sample-host-variable", "label-matches-name")] == \
        "host"


def test_probe_discharge_flag_gates_kt402():
    def escalate(irs):
        irs[0].host_only = True
        irs[0].host_reason = "test: forced escalation"

    _, _, t = _build("cert_wasted_host.yaml", mutate_ir=escalate)
    res = certify_tensors(t, probe_discharge=False)
    assert not any(d.code == "KT402" for d in res.diagnostics)


# ------------------------------------------- KT403: message divergence


def test_variable_message_raises_kt403_but_still_certifies():
    pols = load_policies_from_path(
        os.path.join(POLICY_DIR, "cert_msg_variable.yaml"))
    res = certify_policies(pols)
    kt403 = [d for d in res.diagnostics if d.code == "KT403"]
    assert kt403 and kt403[0].policy == "cert-msg-variable"
    assert res.statuses[("cert-msg-variable", "require-priority-class")] == \
        "certified"
    assert not res.divergences


# --------------------------------------------- KT404: incompleteness


def test_list_pattern_counts_kt404_incomplete():
    pols = load_policies_from_path(
        os.path.join(POLICY_DIR, "cert_incomplete_list.yaml"))
    res = certify_policies(pols)
    kt404 = [d for d in res.diagnostics if d.code == "KT404"]
    assert kt404, _codes(res)
    assert "wildcard-path" in kt404[0].message
    assert res.statuses[("cert-incomplete-list",
                         "require-container-names")] == "incomplete"


# ------------------------------------------------ rule_filter contract


def test_rule_filter_skips_already_certified_rules():
    _, _, t = _build("cert_divergent_seed.yaml")
    res = certify_tensors(t, rule_filter=lambda ir: False)
    assert res.statuses == {}
    assert res.states_checked == 0


# -------------------------------------- fuzz repro + minimizer round-trip


def test_divergence_repro_round_trips_through_diagnostic():
    d = Divergence(
        leg="verdict", policy="fz-p", rule="r0", rule_index=3,
        device="FAIL", host="PASS",
        resource={"kind": "Pod", "spec": {"x": 1}},
        policy_docs=[{"metadata": {"name": "fz-p"}}],
        detail="unit")
    diag = divergence_to_diagnostic(d)
    assert diag.code == "KT401" and diag.policy == "fz-p"
    repro = json.loads(diag.message.split("repro: ", 1)[1])
    assert repro["resource"] == d.resource
    assert repro["policies"] == d.policy_docs
    assert repro["device"] == "FAIL" and repro["host"] == "PASS"


def test_minimizer_shrinks_to_the_witness_subtree():
    """The greedy shrinker must keep exactly the fields the reproducer
    needs and drop the noise (kind/apiVersion are pinned)."""
    resource = {
        "kind": "Pod", "apiVersion": "v1",
        "metadata": {"name": "noisy", "labels": {"a": "1", "b": "2"}},
        "spec": {"containers": [{"name": "c", "image": "nginx:latest"}],
                 "hostNetwork": True},
    }

    def reproduce(doc):
        return doc.get("spec", {}).get("hostNetwork") is True

    small = minimize(None, resource, 0, reproduce)
    assert reproduce(small)
    assert small["spec"] == {"hostNetwork": True}
    assert "metadata" not in small
    assert small["kind"] == "Pod"          # identity keys survive


@pytest.mark.slow
def test_fuzz_shakedown_has_no_divergences():
    report = run_fuzz(cases=60, seed=7, stream_leg=True)
    assert report.cases >= 60          # run_fuzz rounds up to whole batches
    assert report.ok(), [d.format() for d in report.diagnostics()]
    assert report.device_cells > 0 and report.escalated_cells >= 0
