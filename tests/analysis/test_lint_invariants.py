"""Golden diagnostics: tensor/batch invariant pass (KT3xx).

Corruptions are injected into otherwise-valid compiled artifacts, so
each test proves both directions: the clean artifact is silent and the
specific mutilation trips the specific code.
"""

import numpy as np
import pytest

from kyverno_tpu.analysis import check_batch, check_padded, check_tensors
from kyverno_tpu.api.load import load_policy
from kyverno_tpu.models.compiler import compile_tensors
from kyverno_tpu.models.flatten import flatten_batch, pad_to_buckets
from kyverno_tpu.models.ir import compile_rule_ir


@pytest.fixture()
def compiled():
    p = load_policy({
        "apiVersion": "kyverno.io/v1", "kind": "ClusterPolicy",
        "metadata": {"name": "inv"},
        "spec": {"rules": [{
            "name": "r",
            "match": {"resources": {"kinds": ["Pod"],
                                    "namespaces": ["prod-*"]}},
            "validate": {"pattern": {"spec": {
                "containers": [{"image": "!*:latest"}],
                "replicas": ">0"}}},
        }]},
    })
    return compile_tensors([compile_rule_ir(p, p.spec.rules[0], 0)])


@pytest.fixture()
def batch(compiled):
    resources = [
        {"apiVersion": "v1", "kind": "Pod",
         "metadata": {"name": "a", "namespace": "prod-1"},
         "spec": {"containers": [{"image": "nginx:1.27"}], "replicas": 2}},
        {"kind": "Pod", "metadata": {"name": "b"},
         "spec": {"containers": [{"image": "nginx:latest"},
                                 {"image": "busybox"}]}},
    ]
    return flatten_batch(resources, compiled)


def _codes(diags):
    return {d.code for d in diags}


def test_clean_tensors_and_batch_are_silent(compiled, batch):
    assert check_tensors(compiled) == []
    assert check_batch(batch) == []
    padded, n = pad_to_buckets(batch)
    assert check_padded(padded, n) == []


def test_interner_index_bound_violation_golden(batch):
    """A str_id pointing past the dictionary is exactly the bug
    pack_batch's word0 gather cannot survive — ERROR KT311."""
    V = int(batch.str_len.shape[0])
    batch.str_id[0, 0, 0] = V  # one past the last dictionary row
    diags = check_batch(batch)
    (d,) = [x for x in diags if x.code == "KT311"]
    assert d.severity.name == "ERROR"
    assert d.component == "batch.str_id"
    assert str(V) in d.message


def test_negative_str_id_below_sentinel_flagged(batch):
    batch.str_id[0, 0, 0] = -2  # -1 is the legal "no string" sentinel
    assert "KT311" in _codes(check_batch(batch))


def test_type_tag_out_of_range_flagged(batch):
    batch.type_tag[0, 0, 0] = 7
    assert "KT312" in _codes(check_batch(batch))


def test_chk_path_out_of_range_flagged(compiled):
    compiled.chk_path[0] = compiled.n_paths
    diags = check_tensors(compiled)
    assert any(d.code == "KT302" and d.component == "tensors.chk_path"
               for d in diags)


def test_nfa_id_out_of_range_flagged(compiled):
    compiled.chk_nfa[:] = len(compiled.nfa_len) + 3
    assert "KT302" in _codes(check_tensors(compiled))


def test_dtype_violation_flagged(compiled):
    compiled.chk_num_lo = compiled.chk_num_lo.astype(np.float64)
    diags = check_tensors(compiled)
    assert any(d.code == "KT301" and "chk_num_lo" in d.component
               for d in diags)


def test_padding_live_row_flagged(batch):
    padded, n = pad_to_buckets(batch)
    if padded.n == n:
        pytest.skip("batch already power-of-two on every axis")
    padded.live[-1] = True  # phantom resource in the pad region
    assert "KT313" in _codes(check_padded(padded, n))


def test_non_pow2_axis_flagged(batch):
    diags = check_padded(batch, batch.n) if batch.n & (batch.n - 1) else []
    # batch of 2 is a power of two; force the axis check directly
    if not diags:
        from dataclasses import replace

        bad = replace(batch)
        bad.__dict__["e"] = 3
        diags = check_padded(bad, bad.n)
    assert "KT313" in _codes(diags)
