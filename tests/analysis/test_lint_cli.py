"""lint CLI + ci gate + admission-hook integration.

Subprocess tests pin JAX_PLATFORMS=cpu out of caution, but the lint
path must never import jax at all — asserted explicitly below.
"""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

DEAD_POLICY = """\
apiVersion: kyverno.io/v1
kind: ClusterPolicy
metadata:
  name: injected-dead
spec:
  rules:
    - name: unreachable
      match:
        any:
          - {}
      validate:
        pattern:
          metadata:
            name: "?*"
"""


def _run(*argv, timeout=120, extra_env=None, **kw):
    env = dict(os.environ, JAX_PLATFORMS="cpu", **(extra_env or {}))
    return subprocess.run(list(argv), cwd=REPO, env=env, text=True,
                          capture_output=True, timeout=timeout, **kw)


def test_lint_self_smoke_exits_clean():
    r = _run(sys.executable, "-m", "kyverno_tpu.cli", "lint", "--self")
    assert r.returncode == 0, r.stdout + r.stderr
    assert "KT110" in r.stdout


def test_lint_sample_policies_emits_four_categories():
    """Acceptance criterion: >= 4 distinct stable codes on the seed
    sample policies."""
    r = _run(sys.executable, "-m", "kyverno_tpu.cli", "lint", "--json",
             "tests/policies")
    assert r.returncode == 0, r.stdout + r.stderr
    report = json.loads(r.stdout)
    cats = set(report["summary"]["categories"])
    assert {"KT101", "KT110", "KT202", "KT203"} <= cats
    assert len(cats) >= 4


def test_lint_fail_on_error_flips_exit_code(tmp_path):
    bad = tmp_path / "dead.yaml"
    bad.write_text(DEAD_POLICY)
    r = _run(sys.executable, "-m", "kyverno_tpu.cli", "lint", str(bad))
    assert r.returncode == 1
    assert "KT201" in r.stdout
    r = _run(sys.executable, "-m", "kyverno_tpu.cli", "lint",
             "--fail-on", "never", str(bad))
    assert r.returncode == 0


def test_lint_suppress_flag_drops_codes():
    r = _run(sys.executable, "-m", "kyverno_tpu.cli", "lint",
             "--suppress", "KT101,KT110,KT202,KT203", "tests/policies")
    assert r.returncode == 0
    assert "KT101" not in r.stdout and "KT202" not in r.stdout


def test_ci_lint_script_gates_on_injected_error(tmp_path):
    """Acceptance criterion: deploy/ci_lint.sh exits non-zero when an
    ERROR diagnostic is injected, zero on the shipped samples."""
    # trimmed fuzz + quick fleet smoke + generous timeout: the full
    # smoke chain runs >100s per invocation on a loaded CI core and
    # this test makes two.
    budget = dict(timeout=600, extra_env={"CI_LINT_FUZZ_CASES": "120",
                                          "FLEET_SMOKE_QUICK": "1"})
    clean = _run("bash", "deploy/ci_lint.sh", **budget)
    assert clean.returncode == 0, clean.stdout + clean.stderr
    bad = tmp_path / "dead.yaml"
    bad.write_text(DEAD_POLICY)
    injected = _run("bash", "deploy/ci_lint.sh", str(bad), **budget)
    assert injected.returncode != 0
    assert "KT201" in injected.stdout


def test_lint_path_never_imports_jax():
    code = ("import sys; import kyverno_tpu.cli.lint_cmd, "
            "kyverno_tpu.analysis; sys.exit(1 if 'jax' in sys.modules "
            "else 0)")
    r = _run(sys.executable, "-c", code)
    assert r.returncode == 0, "lint path imported jax"


def test_policycache_admission_lint_warn_only():
    """A policy with an ERROR diagnostic is still admitted (warn-only),
    the report lands on the cache, and the gauges are recorded."""
    import yaml

    from kyverno_tpu.api.load import load_policy
    from kyverno_tpu.runtime.metrics import registry
    from kyverno_tpu.runtime import policycache
    from kyverno_tpu.runtime.policycache import PolicyCache

    if not policycache.LINT_ON_ADMISSION:
        pytest.skip("admission lint disabled via env")

    cache = PolicyCache()
    dead = load_policy(yaml.safe_load(DEAD_POLICY))
    host = load_policy({
        "apiVersion": "kyverno.io/v1", "kind": "ClusterPolicy",
        "metadata": {"name": "host-var"},
        "spec": {"rules": [{
            "name": "r", "match": {"resources": {"kinds": ["Pod"]}},
            "validate": {"pattern": {"metadata": {
                "name": "{{request.object.spec.x}}"}}},
        }]},
    })
    cache.add(dead)
    cache.add(host)

    assert "injected-dead" in cache.lint_reports       # admitted anyway
    codes = {d.code for d in cache.lint_reports["injected-dead"].diagnostics}
    assert "KT201" in codes
    exposed = registry().expose()
    assert ('kyverno_policy_device_decidability{policy_name="host-var"} 0'
            in exposed)
    assert 'reason="variable-reference"' in exposed

    cache.remove(host)
    assert "host-var" not in cache.lint_reports
