"""Golden diagnostics: escalation-provenance pass (KT1xx)."""

from kyverno_tpu.analysis import Severity, analyze_policies
from kyverno_tpu.api.load import load_policy
from kyverno_tpu.models.ir import EscalationReason


def _policy(name, rules):
    return load_policy({
        "apiVersion": "kyverno.io/v1", "kind": "ClusterPolicy",
        "metadata": {"name": name}, "spec": {"rules": rules},
    })


def _rule(name, validate, match=None, **extra):
    r = {"name": name,
         "match": match or {"resources": {"kinds": ["Pod"]}},
         "validate": validate}
    r.update(extra)
    return r


def _find(report, code):
    return [d for d in report.diagnostics if d.code == code]


def test_variable_forced_host_check_golden():
    """A {{request...}} variable in the pattern escalates with the exact
    machine-readable reason, pinned to the pattern component."""
    p = _policy("var-host", [_rule("label-eq-name", {
        "pattern": {"metadata": {"labels": {
            "app": "{{request.object.metadata.name}}"}}}})])
    report = analyze_policies([p])
    (d,) = _find(report, "KT101")
    assert d.severity is Severity.INFO
    assert d.policy == "var-host"
    assert d.rule == "label-eq-name"
    assert d.component == "pattern"
    assert d.reason == EscalationReason.VARIABLE_REFERENCE.value
    assert report.device_decidability["var-host"] == 0.0


def test_escalation_reason_taxonomy_is_shared():
    """Each escalating construct maps to its EscalationReason value —
    the same strings record_host_rule_info exports as metric labels."""
    cases = [
        # (rule dict, expected reason, expected component)
        (_rule("foreach", {"foreach": [{"list": "request.object.spec.containers",
                                        "pattern": {"image": "*:*"}}]}),
         EscalationReason.FOREACH.value, "validate.foreach"),
        (_rule("ctx", {"pattern": {"metadata": {"name": "?*"}}},
               context=[{"name": "cm", "configMap": {"name": "x"}}]),
         EscalationReason.EXTERNAL_CONTEXT.value, "context"),
        (_rule("userinfo", {"pattern": {"metadata": {"name": "?*"}}},
               match={"resources": {"kinds": ["Pod"]},
                      "clusterRoles": ["admin"]}),
         EscalationReason.ADMISSION_CONTEXT.value, "match"),
        (_rule("wildkey", {"pattern": {"metadata": {"name": "?*"}}},
               match={"resources": {"kinds": ["Pod"],
                                    "selector": {"matchLabels": {"a*": "b"}}}}),
         EscalationReason.METACHAR_KEY.value, "match"),
        (_rule("badquant", {"pattern": {"spec": {"replicas": "<1e40Gi"}}}),
         EscalationReason.UNPARSEABLE_QUANTITY.value, "pattern"),
    ]
    for rule, reason, component in cases:
        p = _policy(f"tax-{rule['name']}", [rule])
        report = analyze_policies([p])
        (d,) = _find(report, "KT101")
        assert d.reason == reason, (rule["name"], d.reason)
        assert d.component == component, (rule["name"], d.component)


def test_fully_host_policy_warns_kt102():
    p = _policy("all-host", [_rule("r1", {
        "pattern": {"metadata": {"name": "{{request.object.spec.x}}"}}})])
    report = analyze_policies([p])
    assert _find(report, "KT102")
    assert report.device_decidability["all-host"] == 0.0


def test_decidability_score_kt110_always_emitted():
    p = _policy("half", [
        _rule("dev", {"pattern": {"metadata": {"name": "?*"}}}),
        _rule("host", {"pattern": {"metadata": {
            "name": "{{request.object.spec.x}}"}}}),
    ])
    report = analyze_policies([p])
    (d,) = _find(report, "KT110")
    assert "0.50" in d.message
    assert report.device_decidability["half"] == 0.5


def test_host_only_rule_ir_carries_reason_code():
    """The compiler itself (not just the analyzer) stamps the enum value."""
    from kyverno_tpu.models.ir import compile_rule_ir

    p = _policy("stamp", [_rule("r", {
        "pattern": {"metadata": {"name": "{{request.object.spec.x}}"}}})])
    ir = compile_rule_ir(p, p.spec.rules[0], 0)
    assert ir.host_only
    assert ir.host_reason_code == EscalationReason.VARIABLE_REFERENCE.value
