"""KT5xx feature-lane lint battery.

Synthetic repo trees under tmp_path pin each code (KT501 undeclared
read, KT502 dead declaration, KT503 direct environ bypass) and the
exclusions (tests/ never scanned but counted live, writes out of
scope). The final test runs the scanner over the real repo — the
acceptance criterion is a closed switch matrix on the shipped tree.
"""

import os
import subprocess
import sys

from kyverno_tpu.analysis.featurelint import scan_tree

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

REGISTRY = '''\
class Switch:
    def __init__(self, name, default, owner, gate):
        self.name = name

_S = Switch
REGISTRY = {
    s.name: s for s in (
        _S("KTPU_ALPHA", "1", "mod.a", "tests/test_a.py"),
        _S("KTPU_BETA", "0", "mod.b", "tests/test_b.py"),
    )
}

def enabled(name):
    return True
'''


def _tree(tmp_path, registry=REGISTRY, modules=(), tests=()):
    """Lay out a minimal scannable repo: registry + engine modules +
    optional tests/ files; returns the root path."""
    pkg = tmp_path / "kyverno_tpu"
    (pkg / "runtime").mkdir(parents=True)
    (pkg / "runtime" / "featureplane.py").write_text(registry)
    for name, body in modules:
        f = pkg / name
        f.parent.mkdir(parents=True, exist_ok=True)
        f.write_text(body)
    for name, body in tests:
        f = tmp_path / "tests" / name
        f.parent.mkdir(parents=True, exist_ok=True)
        f.write_text(body)
    return tmp_path


def _codes(diags):
    return sorted(d.code for d in diags)


def test_clean_tree_is_silent(tmp_path):
    root = _tree(tmp_path, modules=[
        ("runtime/a.py",
         'from . import featureplane\n'
         'ON = featureplane.enabled("KTPU_ALPHA")\n'),
        ("runtime/b.py",
         'from . import featureplane\n'
         'ON = featureplane.enabled("KTPU_BETA")\n'),
    ])
    assert scan_tree(root) == []


def test_all_accessor_forms_count_as_reads(tmp_path):
    """The full accessor spectrum keeps a switch live and is subject to
    KT501 — the SLO degradation plane reads via enabled_strict/raw, not
    just enabled, and those must close the matrix too."""
    root = _tree(tmp_path, modules=[
        ("runtime/a.py",
         'from . import featureplane\n'
         'ON = featureplane.enabled_strict("KTPU_ALPHA")\n'
         'RAW = featureplane.raw("KTPU_BETA")\n'),
    ])
    assert scan_tree(root) == []     # both declarations live, no KT502
    root2 = _tree(tmp_path / "second", modules=[
        ("runtime/a.py",
         'from . import featureplane\n'
         'A = featureplane.int_value("KTPU_ALPHA")\n'
         'B = featureplane.float_value("KTPU_BETA")\n'
         'G = featureplane.enabled_strict("KTPU_GHOST")\n'),
    ])
    diags = scan_tree(root2)
    assert _codes(diags) == ["KT501"]
    assert "KTPU_GHOST" in diags[0].message


def test_undeclared_read_raises_kt501(tmp_path):
    root = _tree(tmp_path, modules=[
        ("runtime/a.py",
         'from . import featureplane\n'
         'ON = featureplane.enabled("KTPU_ALPHA")\n'
         'GHOST = featureplane.enabled("KTPU_GHOST")\n'),
        ("runtime/b.py",
         'from . import featureplane\n'
         'ON = featureplane.enabled("KTPU_BETA")\n'),
    ])
    diags = scan_tree(root)
    assert _codes(diags) == ["KT501"]
    assert "KTPU_GHOST" in diags[0].message
    assert "runtime/a.py:3" in diags[0].message


def test_dead_declaration_raises_kt502(tmp_path):
    root = _tree(tmp_path, modules=[
        ("runtime/a.py",
         'from . import featureplane\n'
         'ON = featureplane.enabled("KTPU_ALPHA")\n'),
    ])
    diags = scan_tree(root)
    assert _codes(diags) == ["KT502"]
    assert "KTPU_BETA" in diags[0].message


def test_test_only_reference_keeps_switch_live(tmp_path):
    """A switch exercised only by its parity gate under tests/ is live
    for KT502 — but tests are never scanned for KT501/KT503."""
    root = _tree(
        tmp_path,
        modules=[("runtime/a.py",
                  'from . import featureplane\n'
                  'ON = featureplane.enabled("KTPU_ALPHA")\n')],
        tests=[("test_b.py",
                'import os\n'
                'os.environ["KTPU_BETA"] = "1"\n'
                'X = os.environ.get("KTPU_UNDECLARED_IN_TESTS")\n')])
    assert scan_tree(root) == []


def test_direct_environ_read_raises_kt503(tmp_path):
    root = _tree(tmp_path, modules=[
        ("runtime/a.py",
         'import os\n'
         'ON = os.environ.get("KTPU_ALPHA", "1") == "1"\n'
         'RAW = os.environ["KTPU_BETA"]\n'),
    ])
    diags = scan_tree(root)
    assert _codes(diags) == ["KT503", "KT503"]


def test_undeclared_direct_read_raises_both(tmp_path):
    root = _tree(tmp_path, modules=[
        ("runtime/a.py",
         'from . import featureplane\n'
         'import os\n'
         'A = featureplane.enabled("KTPU_ALPHA")\n'
         'B = featureplane.enabled("KTPU_BETA")\n'
         'G = os.getenv("KTPU_GHOST")\n'),
    ])
    assert _codes(scan_tree(root)) == ["KT501", "KT503"]


def test_environ_writes_are_out_of_scope(tmp_path):
    root = _tree(tmp_path, modules=[
        ("runtime/a.py",
         'import os\n'
         'from . import featureplane\n'
         'os.environ["KTPU_ALPHA"] = "1"\n'
         'os.environ.setdefault("KTPU_BETA", "0")\n'
         'A = featureplane.enabled("KTPU_ALPHA")\n'
         'B = featureplane.enabled("KTPU_BETA")\n'),
    ])
    assert scan_tree(root) == []


def test_missing_registry_is_one_error(tmp_path):
    (tmp_path / "kyverno_tpu").mkdir()
    diags = scan_tree(tmp_path)
    assert _codes(diags) == ["KT501"]
    assert "registry" in diags[0].message


def test_repo_switch_matrix_is_closed():
    """Acceptance criterion: the shipped tree has no undeclared reads,
    no dead declarations, no direct-environ bypasses."""
    diags = scan_tree(REPO)
    assert diags == [], [d.format() for d in diags]


def test_featurelint_module_cli_exits_clean():
    r = subprocess.run(
        [sys.executable, "-m", "kyverno_tpu.analysis.featurelint"],
        cwd=REPO, text=True, capture_output=True, timeout=120)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "switch matrix closed" in r.stdout
