"""Golden diagnostics: reachability / conflict pass (KT2xx)."""

from kyverno_tpu.analysis import Severity, analyze_policies
from kyverno_tpu.api.load import load_policy


def _policy(name, rules):
    return load_policy({
        "apiVersion": "kyverno.io/v1", "kind": "ClusterPolicy",
        "metadata": {"name": name}, "spec": {"rules": rules},
    })


def _find(report, code):
    return [d for d in report.diagnostics if d.code == code]


def test_unreachable_rule_golden():
    """match.any with an empty filter can never match ("match cannot be
    empty" compiles to a constant-false row) — ERROR KT201."""
    p = _policy("dead", [{
        "name": "unreachable",
        "match": {"any": [{}]},
        "validate": {"pattern": {"metadata": {"name": "?*"}}},
    }])
    report = analyze_policies([p])
    (d,) = _find(report, "KT201")
    assert d.severity is Severity.ERROR
    assert d.rule == "unreachable"
    assert d.component == "match"
    assert report.max_severity() is Severity.ERROR


def test_exclude_all_kinds_is_unreachable():
    p = _policy("excluded", [{
        "name": "r",
        "match": {"resources": {"kinds": ["Pod"]}},
        "exclude": {"resources": {"kinds": ["*"]}},
        "validate": {"pattern": {"metadata": {"name": "?*"}}},
    }])
    report = analyze_policies([p])
    (d,) = _find(report, "KT201")
    assert d.component == "exclude"


def test_empty_any_preconditions_unreachable():
    """A present-but-empty any list fails the conditions block outright
    (evaluate.go nil-vs-empty distinction) — the rule never applies."""
    p = _policy("pre", [{
        "name": "r",
        "match": {"resources": {"kinds": ["Pod"]}},
        "preconditions": {"any": []},
        "validate": {"pattern": {"metadata": {"name": "?*"}}},
    }])
    report = analyze_policies([p])
    (d,) = _find(report, "KT201")
    assert d.component == "preconditions"


def test_shadowed_anypattern_branch_golden():
    """Alternative 1 = alternative 0 plus an extra constraint: it can
    only pass when alternative 0 already passed — WARNING KT202."""
    p = _policy("shadow", [{
        "name": "host-ns",
        "match": {"resources": {"kinds": ["Pod"]}},
        "validate": {"anyPattern": [
            {"spec": {"hostNetwork": False}},
            {"spec": {"hostNetwork": False, "hostPID": False}},
        ]},
    }])
    report = analyze_policies([p])
    (d,) = _find(report, "KT202")
    assert d.severity is Severity.WARNING
    assert d.component == "anyPattern[alt=1]"
    assert "alternative 0" in d.message


def test_distinct_anypattern_branches_not_flagged():
    p = _policy("ok", [{
        "name": "r",
        "match": {"resources": {"kinds": ["Pod"]}},
        "validate": {"anyPattern": [
            {"spec": {"hostNetwork": False}},
            {"spec": {"hostPID": False}},
        ]},
    }])
    assert not _find(analyze_policies([p]), "KT202")


def test_deny_constant_true_and_false():
    true_p = _policy("deny-true", [{
        "name": "r", "match": {"resources": {"kinds": ["Pod"]}},
        "validate": {"deny": {"conditions": {"all": [
            {"key": "a", "operator": "Equals", "value": "a"}]}}},
    }])
    false_p = _policy("deny-false", [{
        "name": "r", "match": {"resources": {"kinds": ["Pod"]}},
        "validate": {"deny": {"conditions": {"all": [
            {"key": "a", "operator": "Equals", "value": "b"}]}}},
    }])
    assert _find(analyze_policies([true_p]), "KT203")
    assert _find(analyze_policies([false_p]), "KT204")


def test_content_dependent_rules_not_flagged():
    """Rules whose outcome genuinely depends on the resource fold to
    "unknown" and stay silent — the pass is sound, not heuristic."""
    p = _policy("alive", [{
        "name": "r",
        "match": {"resources": {"kinds": ["Pod"],
                                "namespaces": ["prod-*"]}},
        "preconditions": {"all": [
            {"key": "{{request.object.metadata.name}}",
             "operator": "NotEquals", "value": "skip-me"}]},
        "validate": {"deny": {"conditions": {"any": [
            {"key": "{{request.object.spec.replicas}}",
             "operator": "GreaterThan", "value": 10}]}}},
    }])
    report = analyze_policies([p])
    for code in ("KT201", "KT202", "KT203", "KT204"):
        assert not _find(report, code), code


def test_suppression_annotation_drops_codes():
    p = load_policy({
        "apiVersion": "kyverno.io/v1", "kind": "ClusterPolicy",
        "metadata": {"name": "hush", "annotations": {
            "kyverno-tpu.io/lint-suppress": "KT203, KT110"}},
        "spec": {"rules": [{
            "name": "r", "match": {"resources": {"kinds": ["Pod"]}},
            "validate": {"deny": {"conditions": {"all": [
                {"key": "a", "operator": "Equals", "value": "a"}]}}},
        }]},
    })
    report = analyze_policies([p])
    assert not report.diagnostics
