"""Manual check for the TPU XLA fusion bug in the aux stage (round 3).

The TPU backend miscompiles the aux predicate tree when it fuses into the
segment reductions: condition rows read False (or deny verdicts flip to
PASS) under jit while eager and the CPU oracle agree. ops/eval.py carries
an optimization_barrier fence on the aux row values; this script proves
the fence holds on the accelerator backend for the two known-miscompiling
fixtures.

Run on the TPU backend: `python tests/manual_tpu_fusion_check.py` (from
anywhere — the script bootstraps sys.path). Exit 0 = every jitted verdict
matrix matches eager; exit 1 = a miscompile reproduced. Kept as a manual
script (not collected by pytest) because the CI conftest forces the CPU
backend where the fusion bug does not reproduce.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

from kyverno_tpu.api.load import load_policy  # noqa: E402
from kyverno_tpu.models import CompiledPolicySet  # noqa: E402
import kyverno_tpu.ops.eval as ev  # noqa: E402

# fixture 1: deny + precondition mixed with a pattern rule — originally
# made every condition row read False under jit
FIX1_POLICIES = [
    {"apiVersion": "kyverno.io/v1", "kind": "ClusterPolicy",
     "metadata": {"name": "deny-host-ns"},
     "spec": {"rules": [{"name": "deny-privileged-ns",
        "match": {"resources": {"kinds": ["Pod"]}},
        "validate": {"deny": {"conditions": {"any": [
            {"key": "{{request.object.metadata.namespace}}",
             "operator": "Equals", "value": "kube-system"}]}}}}]}},
    {"apiVersion": "kyverno.io/v1", "kind": "ClusterPolicy",
     "metadata": {"name": "precond"},
     "spec": {"rules": [{"name": "tagged-only",
        "match": {"resources": {"kinds": ["Pod"]}},
        "preconditions": {"all": [
            {"key": "{{request.object.metadata.labels.tier}}",
             "operator": "Equals", "value": "web"}]},
        "validate": {"pattern": {"spec": {"containers": [
            {"image": "!*:latest"}]}}}}]}},
]
FIX1_RESOURCES = [
    {"apiVersion": "v1", "kind": "Pod",
     "metadata": {"name": "a", "namespace": "kube-system"},
     "spec": {"containers": [{"name": "c", "image": "nginx:1.21"}]}},
    {"apiVersion": "v1", "kind": "Pod",
     "metadata": {"name": "c", "namespace": "default",
                  "labels": {"tier": "web"}},
     "spec": {"containers": [{"name": "c", "image": "nginx:latest"}]}},
]

# fixture 2: deny-only set with bool operand, absent-key ERROR lane, and a
# scalar (null-break) spec — flipped a FAIL to PASS under jit even after
# the boolean-algebra rewrite
FIX2_POLICIES = [
    {"apiVersion": "kyverno.io/v1", "kind": "ClusterPolicy",
     "metadata": {"name": "a"},
     "spec": {"rules": [{"name": "a",
        "match": {"resources": {"kinds": ["Pod"]}},
        "validate": {"deny": {"conditions": {"any": [
            {"key": "{{request.object.spec.hostNetwork}}",
             "operator": "Equals", "value": True}]}}}}]}},
    {"apiVersion": "kyverno.io/v1", "kind": "ClusterPolicy",
     "metadata": {"name": "b"},
     "spec": {"rules": [{"name": "b",
        "match": {"resources": {"kinds": ["Pod"]}},
        "validate": {"deny": {"conditions": {"any": [
            {"key": "{{request.object.spec.nosuch}}",
             "operator": "Equals", "value": "x"}]}}}}]}},
]
FIX2_RESOURCES = [
    {"apiVersion": "v1", "kind": "Pod", "metadata": {"name": "p1"},
     "spec": "oops"},
    {"apiVersion": "v1", "kind": "Pod", "metadata": {"name": "p2"},
     "spec": {"hostNetwork": True}},
    {"apiVersion": "v1", "kind": "Pod", "metadata": {"name": "p3"},
     "spec": {}},
]

# compatibility aliases (older revisions exposed a single fixture pair)
POLICIES = FIX1_POLICIES
RESOURCES = FIX1_RESOURCES


def check(name, policies, resources) -> bool:
    cps = CompiledPolicySet([load_policy(p) for p in policies])
    b = cps.flatten(resources)
    eager = np.array(ev.build_eval_fn(cps.tensors, jit=False)(*b.device_args()))
    jitted = np.array(ev.build_eval_fn(cps.tensors, jit=True)(*b.device_args()))
    if np.array_equal(eager, jitted):
        print(f"{name} OK: jit matches eager: {jitted.tolist()}")
        return True
    print(f"{name} MISCOMPILE: eager {eager.tolist()} jit {jitted.tolist()}")
    return False


def main() -> int:
    ok = check("fixture-1", FIX1_POLICIES, FIX1_RESOURCES)
    ok &= check("fixture-2", FIX2_POLICIES, FIX2_RESOURCES)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
