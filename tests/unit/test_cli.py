"""CLI conformance: replay the reference's own test corpus.

SURVEY.md section 4 tier 4: the per-(policy, rule, resource) status tables
under /root/reference/test/cli are the cross-backend regression corpus."""

import os

import pytest

from kyverno_tpu.cli.test_cmd import run_test_file
from kyverno_tpu.cli.__main__ import main

REFERENCE_CORPORA = [
    "/root/reference/test/cli/test/simple",
    "/root/reference/test/cli/test/preconditions",
    "/root/reference/test/cli/test/variables",
    "/root/reference/test/cli/test/custom-functions",
    "/root/reference/test/cli/test/autogen",
    "/root/reference/test/cli/test-mutate",
]


@pytest.mark.parametrize("corpus", REFERENCE_CORPORA, ids=os.path.basename)
def test_reference_cli_corpus(corpus):
    mismatches = run_test_file(os.path.join(corpus, "test.yaml"), verbose=False)
    assert mismatches == 0


def test_negative_suite_fails():
    assert main(["test", "/root/reference/test/cli/test-fail/missing-policy"]) == 1


def test_apply_reports_failures(capsys):
    rc = main([
        "apply",
        "/root/reference/test/best_practices/disallow_latest_tag.yaml",
        "-r", "/root/reference/test/resources/pod_with_latest_tag.yaml",
    ])
    out = capsys.readouterr().out
    assert rc == 1
    assert "fail: 1" in out
    assert "validate-image-tag" in out


def test_validate_verb(capsys):
    rc = main(["validate", "/root/reference/test/best_practices/disallow_latest_tag.yaml"])
    assert rc == 0
    assert "is valid" in capsys.readouterr().out
