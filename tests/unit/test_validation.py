"""Validation driver tests, mirroring /root/reference/pkg/engine/validation_test.go
(inline policy+resource JSON pairs asserted pass/fail/skip)."""

import pytest

from kyverno_tpu import store
from kyverno_tpu.api.load import load_policy
from kyverno_tpu.engine.context import Context
from kyverno_tpu.engine.policy_context import PolicyContext
from kyverno_tpu.engine.response import RuleStatus
from kyverno_tpu.engine.validation import validate


def make_ctx(policy_doc, resource, old_resource=None):
    jctx = Context()
    jctx.add_resource(resource)
    return PolicyContext(
        policy=load_policy(policy_doc),
        new_resource=resource,
        old_resource=old_resource or {},
        json_context=jctx,
    )


def pod(name="test-pod", image="nginx:latest", labels=None):
    meta = {"name": name}
    if labels:
        meta["labels"] = labels
    return {
        "apiVersion": "v1",
        "kind": "Pod",
        "metadata": meta,
        "spec": {"containers": [{"name": "ctr", "image": image}]},
    }


def policy_with_rule(rule, name="test-policy"):
    return {
        "apiVersion": "kyverno.io/v1",
        "kind": "ClusterPolicy",
        "metadata": {"name": name},
        "spec": {"rules": [rule]},
    }


DISALLOW_LATEST = {
    "name": "disallow-latest-tag",
    "match": {"resources": {"kinds": ["Pod"]}},
    "validate": {
        "message": "Using a mutable image tag e.g. 'latest' is not allowed.",
        "pattern": {
            "spec": {"containers": [{"image": "!*:latest"}]}
        },
    },
}


class TestValidatePattern:
    def test_fail_latest_tag(self):
        resp = validate(make_ctx(policy_with_rule(DISALLOW_LATEST), pod()))
        assert resp.policy_response.rules[0].status is RuleStatus.FAIL
        assert "disallow-latest-tag" in resp.policy_response.rules[0].message

    def test_pass_pinned_tag(self):
        resp = validate(
            make_ctx(policy_with_rule(DISALLOW_LATEST), pod(image="nginx:1.21"))
        )
        assert resp.policy_response.rules[0].status is RuleStatus.PASS
        assert resp.policy_response.rules_applied_count == 1

    def test_non_matching_kind_produces_no_rule_response(self):
        cm = {"apiVersion": "v1", "kind": "ConfigMap", "metadata": {"name": "x"}}
        resp = validate(make_ctx(policy_with_rule(DISALLOW_LATEST), cm))
        assert resp.policy_response.rules == []
        assert resp.successful

    def test_conditional_anchor_miss_skips(self):
        rule = {
            "name": "check-host-path",
            "match": {"resources": {"kinds": ["Pod"]}},
            "validate": {
                "pattern": {
                    "spec": {"volumes": [{"(hostPath)": {"path": "!/var/run/*"}}]}
                }
            },
        }
        resp = validate(make_ctx(policy_with_rule(rule), pod()))
        # no volumes at all -> pattern fails at spec.volumes -> FAIL
        assert resp.policy_response.rules[0].status is RuleStatus.FAIL

    def test_message_variable_substitution(self):
        rule = {
            "name": "name-in-msg",
            "match": {"resources": {"kinds": ["Pod"]}},
            "validate": {
                "message": "resource {{request.object.metadata.name}} is bad",
                "pattern": {"metadata": {"labels": {"app": "?*"}}},
            },
        }
        resp = validate(make_ctx(policy_with_rule(rule), pod()))
        r = resp.policy_response.rules[0]
        assert r.status is RuleStatus.FAIL
        assert "test-pod" in r.message


class TestAnyPattern:
    RULE = {
        "name": "any-pattern",
        "match": {"resources": {"kinds": ["Pod"]}},
        "validate": {
            "message": "only nginx or redis images",
            "anyPattern": [
                {"spec": {"containers": [{"image": "nginx:*"}]}},
                {"spec": {"containers": [{"image": "redis:*"}]}},
            ],
        },
    }

    def test_pass_first(self):
        resp = validate(make_ctx(policy_with_rule(self.RULE), pod(image="nginx:1.2")))
        assert resp.policy_response.rules[0].status is RuleStatus.PASS

    def test_pass_second(self):
        resp = validate(make_ctx(policy_with_rule(self.RULE), pod(image="redis:6")))
        r = resp.policy_response.rules[0]
        assert r.status is RuleStatus.PASS
        assert "anyPattern[1]" in r.message

    def test_fail_none(self):
        resp = validate(make_ctx(policy_with_rule(self.RULE), pod(image="mysql:8")))
        r = resp.policy_response.rules[0]
        assert r.status is RuleStatus.FAIL
        assert "only nginx or redis images" in r.message


class TestDeny:
    def test_deny_fails_when_conditions_met(self):
        rule = {
            "name": "block-team-label",
            "match": {"resources": {"kinds": ["Pod"]}},
            "validate": {
                "message": "pods of team {{request.object.metadata.labels.team}} denied",
                "deny": {
                    "conditions": {
                        "any": [
                            {
                                "key": "{{request.object.metadata.labels.team}}",
                                "operator": "Equals",
                                "value": "banned",
                            }
                        ]
                    }
                },
            },
        }
        resp = validate(
            make_ctx(policy_with_rule(rule), pod(labels={"team": "banned"}))
        )
        r = resp.policy_response.rules[0]
        assert r.status is RuleStatus.FAIL
        assert "team banned denied" in r.message

        resp = validate(make_ctx(policy_with_rule(rule), pod(labels={"team": "ok"})))
        assert resp.policy_response.rules[0].status is RuleStatus.PASS

    def test_deny_bare_list_conditions(self):
        rule = {
            "name": "deny-list",
            "match": {"resources": {"kinds": ["Pod"]}},
            "validate": {
                "deny": {
                    "conditions": [
                        {
                            "key": "{{request.operation}}",
                            "operator": "Equals",
                            "value": "DELETE",
                        }
                    ]
                }
            },
        }
        ctx = make_ctx(policy_with_rule(rule), pod())
        ctx.json_context.add_json({"request": {"operation": "DELETE"}})
        resp = validate(ctx)
        assert resp.policy_response.rules[0].status is RuleStatus.FAIL


class TestPreconditions:
    def test_preconditions_not_met_skips(self):
        rule = dict(DISALLOW_LATEST)
        rule["preconditions"] = {
            "all": [
                {
                    "key": "{{request.operation}}",
                    "operator": "Equals",
                    "value": "CREATE",
                }
            ]
        }
        ctx = make_ctx(policy_with_rule(rule), pod())
        ctx.json_context.add_json({"request": {"operation": "UPDATE"}})
        resp = validate(ctx)
        assert resp.policy_response.rules[0].status is RuleStatus.SKIP
        assert resp.policy_response.rules_applied_count == 0

    def test_unresolved_precondition_var_is_empty_string(self):
        rule = dict(DISALLOW_LATEST)
        rule["preconditions"] = {
            "all": [
                {"key": "{{request.no.such.path}}", "operator": "Equals", "value": ""}
            ]
        }
        resp = validate(make_ctx(policy_with_rule(rule), pod()))
        # empty == empty -> preconditions pass -> pattern fails on :latest
        assert resp.policy_response.rules[0].status is RuleStatus.FAIL


class TestForEach:
    RULE = {
        "name": "check-images",
        "match": {"resources": {"kinds": ["Pod"]}},
        "validate": {
            "message": "images must not use latest",
            "foreach": [
                {
                    "list": "request.object.spec.containers",
                    "pattern": {"image": "!*:latest"},
                }
            ],
        },
    }

    def test_foreach_fail(self):
        resp = validate(make_ctx(policy_with_rule(self.RULE), pod()))
        r = resp.policy_response.rules[0]
        assert r.status is RuleStatus.FAIL
        assert "foreach" in r.message

    def test_foreach_pass(self):
        resp = validate(make_ctx(policy_with_rule(self.RULE), pod(image="nginx:1")))
        assert resp.policy_response.rules[0].status is RuleStatus.PASS

    def test_foreach_element_variable(self):
        rule = {
            "name": "element-var",
            "match": {"resources": {"kinds": ["Pod"]}},
            "validate": {
                "foreach": [
                    {
                        "list": "request.object.spec.containers",
                        "deny": {
                            "conditions": {
                                "any": [
                                    {
                                        "key": "{{element.image}}",
                                        "operator": "Equals",
                                        "value": "nginx:latest",
                                    }
                                ]
                            }
                        },
                    }
                ]
            },
        }
        resp = validate(make_ctx(policy_with_rule(rule), pod()))
        assert resp.policy_response.rules[0].status is RuleStatus.FAIL


class TestDeleteAndModify:
    def test_delete_request_skips_validation(self):
        ctx = make_ctx(policy_with_rule(DISALLOW_LATEST), {}, old_resource=pod())
        ctx.new_resource = {}
        resp = validate(ctx)
        # rule matches old resource but DELETE produces no rule response
        assert resp.policy_response.rules == []

    def test_modify_same_verdict_skipped(self):
        old = pod(image="nginx:latest")
        new = pod(image="nginx:latest")
        ctx = make_ctx(policy_with_rule(DISALLOW_LATEST), new, old_resource=old)
        resp = validate(ctx)
        assert resp.policy_response.rules == []

    def test_modify_verdict_change_reported(self):
        old = pod(image="nginx:1.0")
        new = pod(image="nginx:latest")
        ctx = make_ctx(policy_with_rule(DISALLOW_LATEST), new, old_resource=old)
        resp = validate(ctx)
        assert resp.policy_response.rules[0].status is RuleStatus.FAIL


class TestMockContext:
    def test_context_entry_from_mock_store(self):
        rule = {
            "name": "allowed-registries",
            "match": {"resources": {"kinds": ["Pod"]}},
            "context": [{"name": "registries", "configMap": {"name": "regs", "namespace": "default"}}],
            "validate": {
                "deny": {
                    "conditions": {
                        "all": [
                            {
                                "key": "{{registries.allowed}}",
                                "operator": "NotEquals",
                                "value": "docker.io",
                            }
                        ]
                    }
                }
            },
        }
        store.set_mock(True)
        store.set_context(
            store.Context(
                policies=[
                    store.Policy(
                        name="test-policy",
                        rules=[
                            store.Rule(
                                name="allowed-registries",
                                values={"registries.allowed": "docker.io"},
                            )
                        ],
                    )
                ]
            )
        )
        try:
            resp = validate(make_ctx(policy_with_rule(rule), pod()))
        finally:
            store.set_mock(False)
            store.set_context(store.Context())
        assert resp.policy_response.rules[0].status is RuleStatus.PASS

    def test_missing_mock_values_is_error(self):
        rule = {
            "name": "needs-context",
            "match": {"resources": {"kinds": ["Pod"]}},
            "context": [{"name": "cm", "configMap": {"name": "x", "namespace": "y"}}],
            "validate": {"pattern": {"metadata": {"name": "?*"}}},
        }
        store.set_mock(True)
        try:
            resp = validate(make_ctx(policy_with_rule(rule), pod()))
        finally:
            store.set_mock(False)
        r = resp.policy_response.rules[0]
        assert r.status is RuleStatus.ERROR
        assert resp.policy_response.rules_error_count == 1


class TestRuleChaining:
    def test_multiple_rules_all_reported(self):
        policy = {
            "apiVersion": "kyverno.io/v1",
            "kind": "ClusterPolicy",
            "metadata": {"name": "multi"},
            "spec": {
                "rules": [
                    DISALLOW_LATEST,
                    {
                        "name": "require-app-label",
                        "match": {"resources": {"kinds": ["Pod"]}},
                        "validate": {
                            "message": "label app required",
                            "pattern": {"metadata": {"labels": {"app": "?*"}}},
                        },
                    },
                ]
            },
        }
        resp = validate(make_ctx(policy, pod()))
        statuses = [r.status for r in resp.policy_response.rules]
        assert statuses == [RuleStatus.FAIL, RuleStatus.FAIL]
        assert not resp.successful
        assert resp.get_failed_rules() == ["disallow-latest-tag", "require-app-label"]
