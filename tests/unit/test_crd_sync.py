"""CRD / openapi-document schema sync (policy/crd_sync.py vs reference
pkg/openapi/crdSync.go): conversion of OpenAPI v3 CRD schemas and v2
cluster documents into the structural DSL, live registration through the
watch seam, and the end state the reference guarantees — a mutate policy
writing schema-invalid fields into a freshly-installed CRD kind is
rejected at policy admission instead of skipping validation."""

import pytest

from kyverno_tpu.api.load import load_policy
from kyverno_tpu.policy.crd_sync import (
    CrdSync,
    convert_openapi_schema,
    schemas_from_crd,
    schemas_from_openapi_v2,
)
from kyverno_tpu.policy.openapi import (
    has_schema,
    unregister_schema,
    validate_policy_mutation,
    validate_resource,
)
from kyverno_tpu.runtime.client import FakeCluster


def _crd(kind="Gadget", group="acme.io", props=None, served=True):
    return {
        "apiVersion": "apiextensions.k8s.io/v1",
        "kind": "CustomResourceDefinition",
        "metadata": {"name": f"{kind.lower()}s.{group}"},
        "spec": {
            "group": group,
            "names": {"kind": kind, "plural": f"{kind.lower()}s"},
            "versions": [{
                "name": "v1", "served": served, "storage": True,
                "schema": {"openAPIV3Schema": {
                    "type": "object",
                    "properties": {
                        "apiVersion": {"type": "string"},
                        "kind": {"type": "string"},
                        "metadata": {"type": "object",
                                     "x-kubernetes-preserve-unknown-fields": True},
                        "spec": {"type": "object", "properties": (props or {
                            "replicas": {"type": "integer"},
                            "mode": {"type": "string"},
                            "port": {"x-kubernetes-int-or-string": True},
                            "limits": {"type": "object",
                                       "additionalProperties":
                                           {"type": "string"}},
                        })},
                    },
                }},
            }],
        },
    }


@pytest.fixture(autouse=True)
def _clean_schemas():
    yield
    for kind in ("Gadget", "Widget"):
        unregister_schema(kind)


class TestConversion:
    def test_basic_shapes(self):
        s = convert_openapi_schema({
            "type": "object",
            "properties": {
                "a": {"type": "string"},
                "b": {"type": "array", "items": {"type": "integer"}},
                "c": {"type": "object",
                      "additionalProperties": {"type": "boolean"}},
                "d": {"x-kubernetes-int-or-string": True},
            }})
        assert s["type"] == "object" and not s["open"]
        assert s["fields"]["a"] == {"type": "string"}
        assert s["fields"]["b"]["items"] == {"type": "integer"}
        assert s["fields"]["c"] == {"type": "map",
                                    "values": {"type": "boolean"}}
        assert s["fields"]["d"] == {"type": "intstr"}

    def test_ref_resolution_and_cycles(self):
        defs = {
            "Inner": {"type": "object",
                      "properties": {"x": {"type": "string"},
                                     "self": {"$ref": "#/definitions/Inner"}}},
        }
        s = convert_openapi_schema({"$ref": "#/definitions/Inner"}, defs)
        assert s["fields"]["x"] == {"type": "string"}
        # the cycle bottoms out permissively instead of recursing forever
        assert s["fields"]["self"]["type"] in ("object", "any")

    def test_unknown_shapes_stay_permissive(self):
        assert convert_openapi_schema({}) == {"type": "any"}
        assert convert_openapi_schema(
            {"x-kubernetes-preserve-unknown-fields": True}) == {"type": "any"}

    def test_openapi_v2_document(self):
        doc = {"definitions": {
            "io.acme.v1.Widget": {
                "type": "object",
                "properties": {"spec": {"$ref": "#/definitions/WidgetSpec"}},
                "x-kubernetes-group-version-kind": [
                    {"group": "acme.io", "kind": "Widget", "version": "v1"}],
            },
            "WidgetSpec": {"type": "object",
                           "properties": {"size": {"type": "integer"}}},
        }}
        out = schemas_from_openapi_v2(doc)
        assert out["Widget"]["fields"]["spec"]["fields"]["size"] == \
            {"type": "integer"}


class TestCrdSync:
    def test_sync_once_registers_crd_kinds(self):
        client = FakeCluster([_crd()])
        assert not has_schema("Gadget")
        sync = CrdSync(client)
        assert sync.sync_once() >= 1
        assert has_schema("Gadget")
        assert validate_resource(
            {"kind": "Gadget", "spec": {"replicas": 3}}, "Gadget") == []
        assert validate_resource(
            {"kind": "Gadget", "spec": {"replicas": "three"}}, "Gadget")
        assert validate_resource(
            {"kind": "Gadget", "spec": {"bogus": 1}}, "Gadget")

    def test_watch_event_registers_and_unregisters(self):
        client = FakeCluster()
        sync = CrdSync(client)
        sync.run()                       # FakeCluster: global watch seam
        client.create_resource(_crd())
        assert has_schema("Gadget")
        client.delete_resource("apiextensions.k8s.io/v1",
                               "CustomResourceDefinition", "",
                               "gadgets.acme.io")
        assert not has_schema("Gadget")

    def test_openapi_document_feeds_sync(self):
        client = FakeCluster()
        client.openapi_document = {"definitions": {
            "io.acme.v1.Widget": {
                "type": "object",
                "properties": {"kind": {"type": "string"},
                               "apiVersion": {"type": "string"},
                               "metadata": {
                                   "x-kubernetes-preserve-unknown-fields": True},
                               "spec": {"type": "object", "properties": {
                                   "size": {"type": "integer"}}}},
                "x-kubernetes-group-version-kind": [
                    {"group": "acme.io", "kind": "Widget", "version": "v1"}],
            }}}
        CrdSync(client).sync_once()
        assert has_schema("Widget")
        assert validate_resource(
            {"kind": "Widget", "spec": {"size": "big"}}, "Widget")

    def test_mutate_policy_against_fresh_crd_is_schema_checked(self):
        """The reference guarantee (crdSync.go + validation.go:143): before
        the CRD lands its kind skips validation; after sync a mutate
        policy writing a schema-invalid field is rejected."""
        policy = load_policy({
            "apiVersion": "kyverno.io/v1", "kind": "ClusterPolicy",
            "metadata": {"name": "set-replicas"},
            "spec": {"rules": [{
                "name": "r",
                "match": {"resources": {"kinds": ["Gadget"]}},
                "mutate": {"patchStrategicMerge": {
                    "spec": {"replicas": "three"}}},
            }]},
        })
        assert validate_policy_mutation(policy) == []   # unknown kind: skip

        client = FakeCluster([_crd()])
        CrdSync(client).sync_once()
        errs = validate_policy_mutation(policy)
        assert errs and "replicas" in errs[0]

        # a schema-valid mutation still passes
        ok_policy = load_policy({
            "apiVersion": "kyverno.io/v1", "kind": "ClusterPolicy",
            "metadata": {"name": "set-replicas-ok"},
            "spec": {"rules": [{
                "name": "r",
                "match": {"resources": {"kinds": ["Gadget"]}},
                "mutate": {"patchStrategicMerge": {"spec": {"replicas": 3}}},
            }]},
        })
        assert validate_policy_mutation(ok_policy) == []


class TestReconcilePruning:
    def test_sync_once_prunes_deleted_crds(self):
        client = FakeCluster([_crd()])
        sync = CrdSync(client)
        sync.sync_once()
        assert has_schema("Gadget")
        client.delete_resource("apiextensions.k8s.io/v1",
                               "CustomResourceDefinition", "",
                               "gadgets.acme.io")
        sync.sync_once()                  # ticker-mode full reconcile
        assert not has_schema("Gadget")

    def test_modified_crd_losing_schema_drops_kind(self):
        client = FakeCluster()
        sync = CrdSync(client)
        sync.run()
        client.create_resource(_crd())
        assert has_schema("Gadget")
        client.update_resource(_crd(served=False))
        assert not has_schema("Gadget")

    def test_stop_makes_callbacks_inert(self):
        client = FakeCluster()
        sync = CrdSync(client)
        sync.run()
        sync.stop()
        client.create_resource(_crd())
        assert not has_schema("Gadget")
