"""Mutation tier tests, mirroring /root/reference/pkg/engine/mutation_test.go
and mutate/*_test.go semantics."""

from kyverno_tpu.api.load import load_policy
from kyverno_tpu.engine.context import Context
from kyverno_tpu.engine.force_mutate import force_mutate
from kyverno_tpu.engine.mutate.json_patch import (
    apply_patch_ops,
    create_patch,
    filter_and_sort_patches,
    generate_patches,
)
from kyverno_tpu.engine.mutate.strategic_merge import (
    merge,
    pre_process_pattern,
    strategic_merge_patch,
)
from kyverno_tpu.engine.mutation import mutate
from kyverno_tpu.engine.policy_context import PolicyContext
from kyverno_tpu.engine.response import RuleStatus


def make_ctx(policy_doc, resource):
    jctx = Context()
    jctx.add_resource(resource)
    return PolicyContext(
        policy=load_policy(policy_doc),
        new_resource=resource,
        json_context=jctx,
    )


def policy_with_rule(rule, name="test-policy"):
    return {
        "apiVersion": "kyverno.io/v1",
        "kind": "ClusterPolicy",
        "metadata": {"name": name},
        "spec": {"rules": [rule]},
    }


def pod(name="test-pod", labels=None):
    meta = {"name": name}
    if labels is not None:
        meta["labels"] = labels
    return {
        "apiVersion": "v1",
        "kind": "Pod",
        "metadata": meta,
        "spec": {"containers": [{"name": "ctr", "image": "nginx:1.21"}]},
    }


class TestJsonPatch:
    def test_apply_basic_ops(self):
        doc = {"a": 1, "b": [1, 2]}
        out = apply_patch_ops(
            doc,
            [
                {"op": "replace", "path": "/a", "value": 9},
                {"op": "add", "path": "/b/-", "value": 3},
                {"op": "remove", "path": "/b/0"},
                {"op": "add", "path": "/c/d", "value": "x"},  # ensure-path
            ],
        )
        assert out == {"a": 9, "b": [2, 3], "c": {"d": "x"}}
        assert doc == {"a": 1, "b": [1, 2]}  # input untouched

    def test_negative_index_and_missing_remove(self):
        doc = {"b": [1, 2, 3]}
        out = apply_patch_ops(
            doc,
            [
                {"op": "replace", "path": "/b/-1", "value": 99},
                {"op": "remove", "path": "/nope"},  # AllowMissingPathOnRemove
            ],
        )
        assert out == {"b": [1, 2, 99]}

    def test_create_patch_roundtrip(self):
        src = {"a": 1, "b": {"c": [1, 2, 3]}, "d": "keep"}
        dst = {"a": 2, "b": {"c": [1, 9]}, "e": True}
        ops = create_patch(src, dst)
        assert apply_patch_ops(src, ops) == dst

    def test_generate_patches_filters_status_and_metadata(self):
        src = {"metadata": {"resourceVersion": "1"}, "status": {"x": 1}, "spec": {}}
        dst = {
            "metadata": {"resourceVersion": "2", "labels": {"a": "b"}},
            "status": {"x": 2},
            "spec": {"replicas": 1},
        }
        patches = generate_patches(src, dst)
        paths = [p["path"] for p in patches]
        assert "/spec/replicas" in paths
        assert "/metadata/labels" in paths
        assert not any("/status" in p for p in paths)
        assert not any("resourceVersion" in p for p in paths)

    def test_removal_reordering(self):
        patches = [
            {"op": "remove", "path": "/a/0"},
            {"op": "remove", "path": "/a/1"},
            {"op": "remove", "path": "/a/2"},
        ]
        out = filter_and_sort_patches(patches)
        assert [p["path"] for p in out] == ["/a/2", "/a/1", "/a/0"]


class TestStrategicMerge:
    def test_map_merge_and_null_delete(self):
        base = {"a": {"x": 1, "y": 2}, "keep": True}
        patch = {"a": {"x": 9, "y": None, "z": 3}}
        assert merge(patch, base) == {"a": {"x": 9, "z": 3}, "keep": True}

    def test_list_merge_by_name(self):
        base = {"containers": [{"name": "a", "image": "old"}, {"name": "b"}]}
        patch = {"containers": [{"name": "a", "image": "new"}, {"name": "c"}]}
        out = merge(patch, base)
        assert out["containers"] == [
            {"name": "a", "image": "new"},
            {"name": "b"},
            {"name": "c"},
        ]

    def test_scalar_list_replaces(self):
        assert merge({"args": ["x"]}, {"args": ["a", "b"]}) == {"args": ["x"]}

    def test_add_anchor(self):
        # +(key): added only when missing (handleAddings)
        resource = {"metadata": {"labels": {"existing": "1"}}}
        pattern = {"metadata": {"labels": {"+(existing)": "nope", "+(new)": "added"}}}
        out = strategic_merge_patch(resource, pattern)
        assert out["metadata"]["labels"] == {"existing": "1", "new": "added"}

    def test_condition_anchor_gates_patch(self):
        pattern = {"spec": {"(hostNetwork)": True, "priority": 100}}
        with_host = {"spec": {"hostNetwork": True}}
        without = {"spec": {"hostNetwork": False}}
        assert strategic_merge_patch(with_host, pattern)["spec"]["priority"] == 100
        assert "priority" not in strategic_merge_patch(without, pattern)["spec"]

    def test_condition_anchor_missing_key_skips(self):
        pattern = {"spec": {"(hostNetwork)": True, "priority": 100}}
        res = {"spec": {}}
        assert strategic_merge_patch(res, pattern) == res

    def test_anchored_list_element_expands_by_name(self):
        # set imagePullPolicy on containers whose image is :latest
        pattern = {
            "spec": {
                "containers": [
                    {"(image)": "*:latest", "imagePullPolicy": "Always"}
                ]
            }
        }
        resource = {
            "spec": {
                "containers": [
                    {"name": "a", "image": "nginx:latest"},
                    {"name": "b", "image": "redis:6"},
                ]
            }
        }
        out = strategic_merge_patch(resource, pattern)
        by_name = {c["name"]: c for c in out["spec"]["containers"]}
        assert by_name["a"]["imagePullPolicy"] == "Always"
        assert "imagePullPolicy" not in by_name["b"]

    def test_preprocess_strips_anchor_only_patterns(self):
        pattern = {"spec": {"(hostNetwork)": False}}
        resource = {"spec": {"hostNetwork": False}}
        out = pre_process_pattern(pattern, resource)
        assert out == {}


class TestMutateDriver:
    ADD_LABEL = {
        "name": "add-label",
        "match": {"resources": {"kinds": ["Pod"]}},
        "mutate": {
            "patchStrategicMerge": {
                "metadata": {"labels": {"+(app)": "default-app"}}
            }
        },
    }

    def test_adds_missing_label(self):
        ctx = make_ctx(policy_with_rule(self.ADD_LABEL), pod(labels={}))
        resp = mutate(ctx)
        r = resp.policy_response.rules[0]
        assert r.status is RuleStatus.PASS
        assert resp.patched_resource["metadata"]["labels"]["app"] == "default-app"
        assert any(p["path"].endswith("labels") or "app" in p["path"] for p in r.patches)

    def test_existing_label_untouched_reports_skip(self):
        ctx = make_ctx(
            policy_with_rule(self.ADD_LABEL), pod(labels={"app": "mine"})
        )
        resp = mutate(ctx)
        r = resp.policy_response.rules[0]
        assert r.status is RuleStatus.SKIP
        assert resp.patched_resource["metadata"]["labels"]["app"] == "mine"

    def test_json6902_patch(self):
        rule = {
            "name": "6902",
            "match": {"resources": {"kinds": ["Pod"]}},
            "mutate": {
                "patchesJson6902": (
                    "- op: add\n"
                    "  path: /metadata/labels/env\n"
                    "  value: prod\n"
                )
            },
        }
        ctx = make_ctx(policy_with_rule(rule), pod(labels={}))
        resp = mutate(ctx)
        assert resp.policy_response.rules[0].status is RuleStatus.PASS
        assert resp.patched_resource["metadata"]["labels"]["env"] == "prod"

    def test_rule_chaining(self):
        policy = {
            "apiVersion": "kyverno.io/v1",
            "kind": "ClusterPolicy",
            "metadata": {"name": "chain"},
            "spec": {
                "rules": [
                    {
                        "name": "first",
                        "match": {"resources": {"kinds": ["Pod"]}},
                        "mutate": {
                            "patchStrategicMerge": {
                                "metadata": {"labels": {"+(stage)": "one"}}
                            }
                        },
                    },
                    {
                        "name": "second",
                        "match": {"resources": {"kinds": ["Pod"]}},
                        "mutate": {
                            "patchStrategicMerge": {
                                "metadata": {
                                    "labels": {
                                        "copied": "{{request.object.metadata.labels.stage}}"
                                    }
                                }
                            }
                        },
                    },
                ]
            },
        }
        ctx = make_ctx(policy, pod(labels={}))
        resp = mutate(ctx)
        assert [r.status for r in resp.policy_response.rules] == [
            RuleStatus.PASS,
            RuleStatus.PASS,
        ]
        labels = resp.patched_resource["metadata"]["labels"]
        assert labels["stage"] == "one"
        assert labels["copied"] == "one"  # second rule saw first rule's patch

    def test_variable_substitution_in_patch(self):
        rule = {
            "name": "var-label",
            "match": {"resources": {"kinds": ["Pod"]}},
            "mutate": {
                "patchStrategicMerge": {
                    "metadata": {
                        "labels": {"appname": "{{request.object.metadata.name}}"}
                    }
                }
            },
        }
        ctx = make_ctx(policy_with_rule(rule), pod(name="my-pod", labels={}))
        resp = mutate(ctx)
        assert resp.patched_resource["metadata"]["labels"]["appname"] == "my-pod"

    def test_preconditions_mismatch_skips(self):
        rule = dict(self.ADD_LABEL)
        rule["preconditions"] = {
            "all": [
                {"key": "{{request.operation}}", "operator": "Equals", "value": "CREATE"}
            ]
        }
        ctx = make_ctx(policy_with_rule(rule), pod(labels={}))
        ctx.json_context.add_json({"request": {"operation": "UPDATE"}})
        resp = mutate(ctx)
        assert resp.policy_response.rules[0].status is RuleStatus.SKIP

    def test_foreach_mutation(self):
        rule = {
            "name": "foreach-pull-policy",
            "match": {"resources": {"kinds": ["Pod"]}},
            "mutate": {
                "foreach": [
                    {
                        "list": "request.object.spec.containers",
                        "patchStrategicMerge": {
                            "spec": {
                                "containers": [
                                    {
                                        "(name)": "{{element.name}}",
                                        "imagePullPolicy": "IfNotPresent",
                                    }
                                ]
                            }
                        },
                    }
                ]
            },
        }
        resource = {
            "apiVersion": "v1",
            "kind": "Pod",
            "metadata": {"name": "p"},
            "spec": {
                "containers": [
                    {"name": "a", "image": "x:1"},
                    {"name": "b", "image": "y:2"},
                ]
            },
        }
        ctx = make_ctx(policy_with_rule(rule), resource)
        resp = mutate(ctx)
        r = resp.policy_response.rules[0]
        assert r.status is RuleStatus.PASS
        for c in resp.patched_resource["spec"]["containers"]:
            assert c["imagePullPolicy"] == "IfNotPresent"


class TestForceMutate:
    def test_force_mutate_ignores_preconditions(self):
        rule = {
            "name": "add-label",
            "match": {"resources": {"kinds": ["Pod"]}},
            "preconditions": {
                "all": [{"key": "x", "operator": "Equals", "value": "never"}]
            },
            "mutate": {
                "patchStrategicMerge": {"metadata": {"labels": {"forced": "yes"}}}
            },
        }
        policy = load_policy(policy_with_rule(rule))
        out = force_mutate(None, policy, pod(labels={}))
        assert out["metadata"]["labels"]["forced"] == "yes"

    def test_force_mutate_placeholder_for_unresolved_vars(self):
        rule = {
            "name": "add-var-label",
            "match": {"resources": {"kinds": ["Pod"]}},
            "mutate": {
                "patchStrategicMerge": {
                    "metadata": {"labels": {"who": "{{request.userInfo.username}}"}}
                }
            },
        }
        policy = load_policy(policy_with_rule(rule))
        out = force_mutate(None, policy, pod(labels={}))
        assert out["metadata"]["labels"]["who"] == "placeholderValue"
