"""Image verification engine vs pkg/engine/imageVerify.go semantics."""

from kyverno_tpu.api.load import load_policy
from kyverno_tpu.engine.context import Context
from kyverno_tpu.engine.image_verify import (
    StaticVerifier,
    json_pointer_to_jmespath,
    verify_and_patch_images,
)
from kyverno_tpu.engine.policy_context import PolicyContext
from kyverno_tpu.engine.response import RuleStatus
from kyverno_tpu.runtime.client import FakeCluster
from kyverno_tpu.runtime.config import ConfigData
from kyverno_tpu.runtime.events import EventGenerator
from kyverno_tpu.runtime.metrics import MetricsRegistry
from kyverno_tpu.runtime.policycache import PolicyCache
from kyverno_tpu.runtime.reports import ReportGenerator
from kyverno_tpu.runtime.webhook import MUTATING_WEBHOOK_PATH, WebhookServer

DIGEST = "sha256:" + "ab" * 32


def verify_policy(image="ghcr.io/acme/*", key="k1", attestations=None,
                  action="enforce"):
    iv = {"image": image, "key": key}
    if attestations:
        iv["attestations"] = attestations
    return load_policy({
        "apiVersion": "kyverno.io/v1",
        "kind": "ClusterPolicy",
        "metadata": {"name": "check-images"},
        "spec": {
            "validationFailureAction": action,
            "rules": [{
                "name": "verify-signature",
                "match": {"resources": {"kinds": ["Pod"]}},
                "verifyImages": [iv],
            }],
        },
    })


def pod(image="ghcr.io/acme/app:v1", name="p"):
    return {
        "apiVersion": "v1", "kind": "Pod",
        "metadata": {"name": name, "namespace": "default"},
        "spec": {"containers": [{"name": "c", "image": image}]},
    }


def run(policy, resource, verifier):
    ctx = Context()
    ctx.add_resource(resource)
    ctx.add_image_info(resource)
    return verify_and_patch_images(
        PolicyContext(policy=policy, new_resource=resource, json_context=ctx),
        verifier,
    )


def test_json_pointer_to_jmespath():
    assert (json_pointer_to_jmespath("/spec/containers/0/image")
            == "spec.containers[0].image")
    assert (json_pointer_to_jmespath("/spec/initContainers/12/image")
            == "spec.initContainers[12].image")


class TestSignatureVerification:
    def test_signed_image_passes_and_gets_digest_patch(self):
        v = StaticVerifier()
        v.sign("ghcr.io/acme/app:v1", DIGEST, key="k1")
        resp = run(verify_policy(), pod(), v)
        [rule] = resp.policy_response.rules
        assert rule.status == RuleStatus.PASS
        assert rule.patches == [{
            "op": "replace",
            "path": "/spec/containers/0/image",
            "value": f"ghcr.io/acme/app:v1@{DIGEST}",
        }]

    def test_unsigned_image_fails(self):
        resp = run(verify_policy(), pod(), StaticVerifier())
        [rule] = resp.policy_response.rules
        assert rule.status == RuleStatus.FAIL
        assert "signature verification failed" in rule.message

    def test_wrong_key_fails(self):
        v = StaticVerifier()
        v.sign("ghcr.io/acme/app:v1", DIGEST, key="other-key")
        resp = run(verify_policy(key="k1"), pod(), v)
        [rule] = resp.policy_response.rules
        assert rule.status == RuleStatus.FAIL

    def test_image_with_digest_not_repatched(self):
        image = f"ghcr.io/acme/app:v1@{DIGEST}"
        v = StaticVerifier()
        v.sign(image, DIGEST, key="k1")
        resp = run(verify_policy(), pod(image=image), v)
        [rule] = resp.policy_response.rules
        assert rule.status == RuleStatus.PASS
        assert rule.patches == []  # imageVerify.go:203 digest already set

    def test_non_matching_image_pattern_skipped(self):
        resp = run(verify_policy(image="docker.io/other/*"), pod(),
                   StaticVerifier())
        assert resp.policy_response.rules == []
        assert resp.successful

    def test_non_matching_kind_skipped(self):
        svc = {"apiVersion": "v1", "kind": "Service",
               "metadata": {"name": "s"}, "spec": {}}
        resp = run(verify_policy(), svc, StaticVerifier())
        assert resp.policy_response.rules == []


class TestAttestations:
    def _verifier(self, level="L3"):
        v = StaticVerifier()
        v.attest("ghcr.io/acme/app:v1", {
            "predicateType": "https://slsa.dev/provenance/v0.2",
            "predicate": {"buildLevel": level,
                          "builder": {"id": "gha"}},
        })
        return v

    def attest_policy(self, conditions):
        return verify_policy(attestations=[{
            "predicateType": "https://slsa.dev/provenance/v0.2",
            "conditions": conditions,
        }])

    def test_conditions_pass(self):
        policy = self.attest_policy([{"all": [
            {"key": "{{ buildLevel }}", "operator": "Equals", "value": "L3"},
            {"key": "{{ builder.id }}", "operator": "Equals", "value": "gha"},
        ]}])
        resp = run(policy, pod(), self._verifier())
        [rule] = resp.policy_response.rules
        assert rule.status == RuleStatus.PASS

    def test_conditions_fail(self):
        policy = self.attest_policy([{"all": [
            {"key": "{{ buildLevel }}", "operator": "Equals", "value": "L3"},
        ]}])
        resp = run(policy, pod(), self._verifier(level="L1"))
        [rule] = resp.policy_response.rules
        assert rule.status == RuleStatus.FAIL
        assert "attestation checks failed" in rule.message

    def test_image_context_object(self):
        # imageVerify.go:270: conditions see an ``image`` object
        policy = self.attest_policy([{"all": [
            {"key": "{{ image.tag }}", "operator": "Equals", "value": "v1"},
            {"key": "{{ image.registry }}", "operator": "Equals",
             "value": "ghcr.io"},
        ]}])
        resp = run(policy, pod(), self._verifier())
        [rule] = resp.policy_response.rules
        assert rule.status == RuleStatus.PASS

    def test_missing_attestations_error(self):
        policy = self.attest_policy([{"all": [
            {"key": "{{ buildLevel }}", "operator": "Equals", "value": "L3"},
        ]}])
        resp = run(policy, pod(), StaticVerifier())
        [rule] = resp.policy_response.rules
        assert rule.status == RuleStatus.ERROR
        assert not resp.successful


class TestWebhookIntegration:
    def make_server(self, verifier, action="enforce"):
        cache = PolicyCache()
        cache.add(verify_policy(action=action))
        cluster = FakeCluster()
        return WebhookServer(
            policy_cache=cache, config=ConfigData(), client=cluster,
            event_gen=EventGenerator(cluster), report_gen=ReportGenerator(),
            registry=MetricsRegistry(), image_verifier=verifier,
        )

    def _review(self, resource):
        return {
            "apiVersion": "admission.k8s.io/v1",
            "kind": "AdmissionReview",
            "request": {
                "uid": "u1", "kind": {"kind": "Pod"},
                "namespace": "default", "operation": "CREATE",
                "object": resource,
            },
        }

    def test_signed_pod_gets_digest_patch(self):
        import base64
        import json as json_mod

        v = StaticVerifier()
        v.sign("ghcr.io/acme/app:v1", DIGEST, key="k1")
        server = self.make_server(v)
        out = server.handle(MUTATING_WEBHOOK_PATH, self._review(pod()))
        assert out["response"]["allowed"] is True
        patches = json_mod.loads(
            base64.b64decode(out["response"]["patch"]))
        assert {"op": "replace", "path": "/spec/containers/0/image",
                "value": f"ghcr.io/acme/app:v1@{DIGEST}"} in patches

    def test_unsigned_pod_blocked_in_enforce(self):
        server = self.make_server(StaticVerifier())
        out = server.handle(MUTATING_WEBHOOK_PATH, self._review(pod()))
        assert out["response"]["allowed"] is False
        assert "image verification failed" in out["response"]["status"]["message"]

    def test_unsigned_pod_allowed_in_audit(self):
        server = self.make_server(StaticVerifier(), action="audit")
        out = server.handle(MUTATING_WEBHOOK_PATH, self._review(pod()))
        assert out["response"]["allowed"] is True
