"""Tree matcher tests: map/array recursion, anchors, skip semantics.

Scenarios mirror pkg/engine/validation_test.go fixtures (inline JSON policy
fragments asserted pass/fail/skip)."""

from kyverno_tpu.engine.validate_pattern import match_pattern


def pod(containers=None, **meta):
    return {
        "apiVersion": "v1",
        "kind": "Pod",
        "metadata": {"name": "test", **meta},
        "spec": {"containers": containers or []},
    }


class TestBasicMatch:
    def test_scalar_leaf(self):
        r = match_pattern({"a": 1}, {"a": 1})
        assert r.matched
        r = match_pattern({"a": 1}, {"a": 2})
        assert not r.matched and not r.skip

    def test_nested_map(self):
        res = {"spec": {"replicas": 3}}
        assert match_pattern(res, {"spec": {"replicas": ">2"}}).matched
        assert not match_pattern(res, {"spec": {"replicas": ">5"}}).matched

    def test_missing_key_fails(self):
        r = match_pattern({"a": 1}, {"b": 1})
        assert not r.matched

    def test_structure_mismatch(self):
        r = match_pattern({"a": [1]}, {"a": {"b": 1}})
        assert not r.matched

    def test_star_requires_presence(self):
        assert match_pattern({"a": "x"}, {"a": "*"}).matched
        assert match_pattern({"a": {"b": 1}}, {"a": "*"}).matched
        r = match_pattern({"c": 1}, {"a": "*"})
        assert not r.matched


class TestArraySemantics:
    def test_array_of_maps_all_must_match(self):
        res = pod([{"image": "nginx:1.21"}, {"image": "redis:6"}])
        pat = {"spec": {"containers": [{"image": "*:*"}]}}
        assert match_pattern(res, pat).matched

        res2 = pod([{"image": "nginx:1.21"}, {"image": "redis"}])
        assert not match_pattern(res2, pat).matched

    def test_disallow_latest_tag(self):
        pat = {"spec": {"containers": [{"image": "!*:latest"}]}}
        assert match_pattern(pod([{"image": "nginx:1.21"}]), pat).matched
        assert not match_pattern(pod([{"image": "nginx:latest"}]), pat).matched

    def test_scalar_pattern_over_array(self):
        res = {"finalizers": ["a", "b"]}
        assert match_pattern(res, {"finalizers": ["?"]}).matched
        assert not match_pattern(res, {"finalizers": ["a"]}).matched  # "b" != "a"

    def test_empty_pattern_array_fails(self):
        assert not match_pattern({"a": [1]}, {"a": []}).matched


class TestConditionAnchor:
    PAT = {
        "spec": {
            "containers": [
                {"(image)": "*:latest", "imagePullPolicy": "Always"}
            ]
        }
    }

    def test_condition_applies_and_passes(self):
        res = pod([{"image": "nginx:latest", "imagePullPolicy": "Always"}])
        assert match_pattern(res, self.PAT).matched

    def test_condition_applies_and_fails(self):
        res = pod([{"image": "nginx:latest", "imagePullPolicy": "IfNotPresent"}])
        r = match_pattern(res, self.PAT)
        assert not r.matched and not r.skip

    def test_condition_not_applicable_skips_element(self):
        # image is not :latest -> element skipped -> pattern passes
        res = pod([{"image": "nginx:1.21", "imagePullPolicy": "IfNotPresent"}])
        assert match_pattern(res, self.PAT).matched

    def test_top_level_condition_skip(self):
        # condition anchor at map level: mismatch -> whole rule skips
        pat = {"metadata": {"(name)": "prod-*"}, "spec": {"hostNetwork": False}}
        res = {"metadata": {"name": "dev-pod"}, "spec": {"hostNetwork": True}}
        r = match_pattern(res, pat)
        assert not r.matched and r.skip

    def test_top_level_condition_applies(self):
        pat = {"metadata": {"(name)": "prod-*"}, "spec": {"hostNetwork": False}}
        res = {"metadata": {"name": "prod-pod"}, "spec": {"hostNetwork": True}}
        r = match_pattern(res, pat)
        assert not r.matched and not r.skip


class TestOtherAnchors:
    def test_equality_anchor(self):
        pat = {"metadata": {"=(annotations)": {"owner": "?*"}}}
        # annotations present -> must match
        assert match_pattern({"metadata": {"annotations": {"owner": "me"}}}, pat).matched
        assert not match_pattern({"metadata": {"annotations": {"x": "y"}}}, pat).matched
        # annotations absent -> pass
        assert match_pattern({"metadata": {}}, pat).matched

    def test_negation_anchor(self):
        pat = {"spec": {"X(hostNetwork)": "null"}}
        assert match_pattern({"spec": {}}, pat).matched
        assert not match_pattern({"spec": {"hostNetwork": True}}, pat).matched

    def test_existence_anchor(self):
        pat = {"spec": {"^(containers)": [{"name": "istio-proxy"}]}}
        res = pod([{"name": "app"}, {"name": "istio-proxy"}])
        assert match_pattern(res, pat).matched
        res2 = pod([{"name": "app"}])
        assert not match_pattern(res2, pat).matched

    def test_global_anchor_skips_whole_rule(self):
        pat = {
            "spec": {
                "containers": [
                    {"<(image)": "registry.corp/*", "securityContext": {"runAsNonRoot": True}}
                ]
            }
        }
        # image from another registry -> global anchor mismatch -> skip
        res = pod([{"image": "docker.io/nginx", "securityContext": {"runAsNonRoot": False}}])
        r = match_pattern(res, pat)
        assert not r.matched and r.skip
        # matching registry -> enforced
        res2 = pod([{"image": "registry.corp/nginx", "securityContext": {"runAsNonRoot": False}}])
        r2 = match_pattern(res2, pat)
        assert not r2.matched and not r2.skip


class TestMetadataWildcardKeys:
    def test_label_key_expansion(self):
        pat = {"metadata": {"labels": {"app.kubernetes.io/*": "?*"}}}
        res = {"metadata": {"labels": {"app.kubernetes.io/name": "nginx"}}}
        assert match_pattern(res, pat).matched

    def test_label_key_expansion_no_match(self):
        pat = {"metadata": {"labels": {"app.kubernetes.io/*": "?*"}}}
        res = {"metadata": {"labels": {"team": "x"}}}
        assert not match_pattern(res, pat).matched
