"""Scenario runner: replay the reference's pkg/testrunner corpus.

SURVEY.md section 4 tier 3: YAML scenarios under
/root/reference/test/scenarios declare input.policy/input.resource and the
expected PolicyResponse for mutation, validation and generation; the
reference executes them in pkg/testrunner/scenario.go:132 runTestCase
(Mutate -> patched-resource golden compare -> Validate -> Generate with a
mock client for Namespace resources). This runner mirrors that flow and
comparison (compareRules: name, type, status, and message when the
expectation carries one) over the exact scenario list of
pkg/testrunner/testrunner_test.go.
"""

import os

import pytest
import yaml

from kyverno_tpu.api.load import load_policy
from kyverno_tpu.engine.context import Context
from kyverno_tpu.engine.generation import generate as engine_generate
from kyverno_tpu.engine.mutation import mutate as engine_mutate
from kyverno_tpu.engine.policy_context import PolicyContext
from kyverno_tpu.engine.validation import validate as engine_validate
from kyverno_tpu.runtime.client import FakeCluster
from kyverno_tpu.runtime.generate_controller import apply_generate_rule

REFERENCE_ROOT = "/root/reference"

# pkg/testrunner/testrunner_test.go:6-87 (the commented-out add_ns_quota
# scenario is excluded there too)
SCENARIOS = [
    "test/scenarios/other/scenario_mutate_endpoint.yaml",
    "test/scenarios/other/scenario_mutate_validate_qos.yaml",
    "test/scenarios/samples/best_practices/disallow_priviledged.yaml",
    "test/scenarios/other/scenario_validate_healthChecks.yaml",
    "test/scenarios/samples/best_practices/disallow_host_network_port.yaml",
    "test/scenarios/samples/best_practices/disallow_host_pid_ipc.yaml",
    "test/scenarios/other/scenario_validate_disallow_default_serviceaccount.yaml",
    "test/scenarios/other/scenario_validate_selinux_context.yaml",
    "test/scenarios/other/scenario_validate_default_proc_mount.yaml",
    "test/scenarios/other/scenario_validate_volume_whiltelist.yaml",
    "test/scenarios/samples/best_practices/disallow_bind_mounts_fail.yaml",
    "test/scenarios/samples/best_practices/disallow_bind_mounts_pass.yaml",
    "test/scenarios/samples/best_practices/disallow_sysctls.yaml",
    "test/scenarios/samples/best_practices/add_safe_to_evict.yaml",
    "test/scenarios/samples/best_practices/add_safe_to_evict2.yaml",
    "test/scenarios/samples/best_practices/add_safe_to_evict3.yaml",
    "test/scenarios/samples/more/restrict_automount_sa_token.yaml",
    "test/scenarios/samples/more/restrict_ingress_classes.yaml",
    "test/scenarios/samples/more/unknown_ingress_class.yaml",
    "test/scenarios/other/scenario_mutate_pod_spec.yaml",
]


def _ref_path(rel: str) -> str:
    return os.path.join(REFERENCE_ROOT, rel.lstrip("/"))


def _load_yaml(rel: str):
    with open(_ref_path(rel)) as f:
        return yaml.safe_load(f)


def _strip_go_zero_fields(doc):
    """Normalize Go typed-marshaling artifacts out of golden comparisons:
    the reference's strategic merge round-trips resources through typed
    structs, so zero-valued fields surface as ``null`` / ``{}`` in the
    golden files (metadata.creationTimestamp: null, spec.strategy: {},
    status: {}). The untyped engine here never invents such keys; both
    sides drop them before comparing."""
    if isinstance(doc, dict):
        # strip bottom-up so containers that only become empty after
        # stripping (e.g. metadata: {creationTimestamp: null}) drop too
        out = {}
        for k, v in doc.items():
            stripped = _strip_go_zero_fields(v)
            if stripped is not None and stripped != {}:
                out[k] = stripped
        return out
    if isinstance(doc, list):
        return [_strip_go_zero_fields(v) for v in doc]
    return doc


def _compare_response(policy_response, expected: dict, where: str) -> list[str]:
    """scenario.go:246 validateResponse + compareRules."""
    errors: list[str] = []
    if not expected:
        return errors
    exp_policy = expected.get("policy") or {}
    if exp_policy.get("name") and policy_response.policy.name != exp_policy["name"]:
        errors.append(f"{where}: policy name {policy_response.policy.name!r}"
                      f" != {exp_policy['name']!r}")
    exp_res = expected.get("resource") or {}
    for field, attr in (("kind", "kind"), ("namespace", "namespace"),
                        ("name", "name")):
        want = exp_res.get(field)
        got = getattr(policy_response.resource, attr)
        if want is not None and got != want:
            errors.append(f"{where}: resource {field} {got!r} != {want!r}")
    exp_rules = expected.get("rules") or []
    got_rules = policy_response.rules
    if len(got_rules) != len(exp_rules):
        errors.append(
            f"{where}: rule count {len(got_rules)} != {len(exp_rules)} "
            f"(got {[r.name for r in got_rules]})")
        return errors
    for got, want in zip(got_rules, exp_rules):
        if got.name != want.get("name"):
            errors.append(f"{where}: rule name {got.name!r} != "
                          f"{want.get('name')!r}")
            continue
        if want.get("type") and got.type.value != want["type"]:
            errors.append(f"{where}/{got.name}: type {got.type.value!r} != "
                          f"{want['type']!r}")
        if want.get("status") and got.status.value != want["status"]:
            errors.append(f"{where}/{got.name}: status {got.status.value!r}"
                          f" != {want['status']!r} ({got.message})")
        if want.get("message") and got.message != want["message"]:
            errors.append(f"{where}/{got.name}: message {got.message!r} != "
                          f"{want['message']!r}")
    return errors


def run_test_case(tc: dict) -> list[str]:
    """scenario.go:132 runTestCase."""
    errors: list[str] = []
    policy = load_policy(_load_yaml(tc["input"]["policy"]))
    resource = _load_yaml(tc["input"]["resource"])
    expected = tc.get("expected") or {}

    # ---- mutation
    jctx = Context()
    jctx.add_resource(resource)
    mresp = engine_mutate(PolicyContext(
        policy=policy, new_resource=resource, json_context=jctx))
    mutation = expected.get("mutation") or {}
    golden = mutation.get("patchedresource", "")
    if golden:
        want = _load_yaml(golden)
        if _strip_go_zero_fields(mresp.patched_resource) != \
                _strip_go_zero_fields(want):
            errors.append("mutation: patched resource != golden "
                          f"{golden}")
    errors += _compare_response(mresp.policy_response,
                                mutation.get("policyresponse") or {},
                                "mutation")
    if mresp.policy_response.rules:
        resource = mresp.patched_resource

    # ---- validation
    jctx = Context()
    jctx.add_resource(resource)
    vresp = engine_validate(PolicyContext(
        policy=policy, new_resource=resource, json_context=jctx))
    errors += _compare_response(vresp.policy_response,
                                (expected.get("validation") or {})
                                .get("policyresponse") or {},
                                "validation")

    # ---- generation (Namespace triggers, scenario.go:173)
    generation = expected.get("generation") or {}
    if resource.get("kind") == "Namespace" and generation:
        client = FakeCluster()
        for rel in tc["input"].get("loadresources") or []:
            client.create_resource(_load_yaml(rel))
        client.create_resource(resource)
        jctx = Context()
        jctx.add_resource(resource)
        pctx = PolicyContext(policy=policy, new_resource=resource,
                             client=client, json_context=jctx)
        gresp = engine_generate(pctx)
        errors += _compare_response(gresp.policy_response,
                                    generation.get("policyresponse") or {},
                                    "generation")
        # materialize like the generate controller, then check existence
        for rule in policy.spec.rules:
            if rule.has_generate():
                try:
                    apply_generate_rule(rule, pctx, resource, client)
                except Exception as e:
                    errors.append(f"generation: apply failed: {e}")
        ns = (resource.get("metadata") or {}).get("name", "")
        for spec in generation.get("generatedResources") or []:
            if client.get_resource("", spec.get("kind", ""),
                                   spec.get("namespace") or ns,
                                   spec.get("name", "")) is None:
                errors.append(
                    f"generation: {spec.get('kind')}/{spec.get('name')} "
                    f"not generated")
    return errors


# The selinux scenario's expectation is stale relative to the reference
# ENGINE at this snapshot: it expects pattern level: "*" to fail against
# level: "", but the reference's own unit test asserts the opposite —
# validateString("", "*", Equal) is true (pattern_test.go:19
# TestValidateString_AsteriskTest). This engine matches the reference
# engine, so the scenario is expected to fail its stale golden.
_STALE = {"test/scenarios/other/scenario_validate_selinux_context.yaml"}


@pytest.mark.parametrize(
    "scenario",
    [pytest.param(s, marks=pytest.mark.xfail(
        reason="stale golden vs pattern_test.go:19", strict=True))
     if s in _STALE else s for s in SCENARIOS],
    ids=lambda s: os.path.basename(str(s)).rsplit(".", 1)[0])
def test_reference_scenario(scenario):
    doc = _load_yaml(scenario)
    all_errors: list[str] = []
    for tc in doc.get("testcases") or [doc]:
        all_errors += run_test_case(tc)
    assert not all_errors, "\n".join(all_errors)
