"""Context store + variable substitution tests (mirrors vars_test.go and
context_test.go scenarios)."""

import pytest

from kyverno_tpu.engine.context import (
    Context,
    InvalidVariableError,
    extract_image_info,
    merge_patch,
    parse_image,
)
from kyverno_tpu.engine.variables import (
    NotResolvedReferenceError,
    VariableResolutionError,
    substitute_all,
    substitute_all_force_mutate,
    substitute_all_in_preconditions,
    substitute_references,
)


class TestMergePatch:
    def test_merge(self):
        assert merge_patch({"a": 1}, {"b": 2}) == {"a": 1, "b": 2}
        assert merge_patch({"a": {"x": 1}}, {"a": {"y": 2}}) == {"a": {"x": 1, "y": 2}}

    def test_null_deletes(self):
        assert merge_patch({"a": 1, "b": 2}, {"a": None}) == {"b": 2}

    def test_arrays_replace(self):
        assert merge_patch({"a": [1, 2]}, {"a": [3]}) == {"a": [3]}


class TestContext:
    def test_add_resource_and_query(self):
        ctx = Context()
        ctx.add_resource({"metadata": {"name": "pod-x"}})
        assert ctx.query("request.object.metadata.name") == "pod-x"

    def test_checkpoint_restore(self):
        ctx = Context()
        ctx.add_resource({"metadata": {"name": "a"}})
        ctx.checkpoint()
        ctx.add_json({"request": {"object": {"metadata": {"name": "b"}}}})
        assert ctx.query("request.object.metadata.name") == "b"
        ctx.restore()
        assert ctx.query("request.object.metadata.name") == "a"

    def test_reset_keeps_checkpoint(self):
        ctx = Context()
        ctx.add_json({"x": 1})
        ctx.checkpoint()
        ctx.add_json({"x": 2})
        ctx.reset()
        assert ctx.query("x") == 1
        ctx.add_json({"x": 3})
        ctx.reset()
        assert ctx.query("x") == 1

    def test_service_account(self):
        ctx = Context()
        ctx.add_service_account("system:serviceaccount:kube-system:builder")
        assert ctx.query("serviceAccountName") == "builder"
        assert ctx.query("serviceAccountNamespace") == "kube-system"

    def test_missing_query_raises(self):
        # fork semantics: unknown keys error (see interpreter._field)
        ctx = Context()
        with pytest.raises(InvalidVariableError):
            ctx.query("does.not.exist")

    def test_has_changed(self):
        ctx = Context()
        ctx.add_resource({"spec": {"replicas": 2}})
        ctx.add_old_resource({"spec": {"replicas": 1}})
        assert ctx.has_changed("spec.replicas") is True
        ctx2 = Context()
        ctx2.add_resource({"spec": {"replicas": 2}})
        ctx2.add_old_resource({"spec": {"replicas": 2}})
        assert ctx2.has_changed("spec.replicas") is False


class TestImageInfo:
    def test_parse_image(self):
        info = parse_image("nginx")
        assert info["registry"] == "docker.io"
        assert info["name"] == "nginx"
        assert info["tag"] == "latest"

        info = parse_image("quay.io/org/app:v1.2")
        assert info["registry"] == "quay.io"
        assert info["path"] == "org/app"
        assert info["name"] == "app"
        assert info["tag"] == "v1.2"

        info = parse_image("nginx@sha256:" + "a" * 64)
        assert info["digest"].startswith("sha256:")

    def test_extract_pod(self):
        pod = {
            "kind": "Pod",
            "spec": {
                "containers": [{"name": "c1", "image": "nginx:1.21"}],
                "initContainers": [{"name": "i1", "image": "busybox"}],
            },
        }
        images = extract_image_info(pod)
        assert images["containers"]["c1"]["tag"] == "1.21"
        assert images["initContainers"]["i1"]["name"] == "busybox"
        assert images["containers"]["c1"]["jsonPath"] == "/spec/containers/0/image"

    def test_extract_deployment(self):
        dep = {
            "kind": "Deployment",
            "spec": {"template": {"spec": {"containers": [{"name": "c", "image": "r/a:1"}]}}},
        }
        images = extract_image_info(dep)
        assert images["containers"]["c"]["jsonPath"] == "/spec/template/spec/containers/0/image"

    def test_context_images_query(self):
        ctx = Context()
        ctx.add_image_info(
            {"kind": "Pod", "spec": {"containers": [{"name": "c", "image": "nginx:latest"}]}}
        )
        assert ctx.query("images.containers.c.tag") == "latest"


class TestVariableSubstitution:
    def ctx(self):
        ctx = Context()
        ctx.add_resource(
            {
                "metadata": {"name": "mypod", "namespace": "prod", "labels": {"app": "web"}},
                "spec": {"replicas": 3},
            }
        )
        return ctx

    def test_simple_substitution(self):
        doc = {"message": "name is {{request.object.metadata.name}}"}
        out = substitute_all(self.ctx(), doc)
        assert out == {"message": "name is mypod"}

    def test_whole_string_keeps_type(self):
        doc = {"replicas": "{{request.object.spec.replicas}}"}
        out = substitute_all(self.ctx(), doc)
        assert out == {"replicas": 3}

    def test_object_substitution_in_string(self):
        doc = {"msg": "labels: {{request.object.metadata.labels}}"}
        out = substitute_all(self.ctx(), doc)
        assert out == {"msg": 'labels: {"app":"web"}'}

    def test_key_substitution(self):
        doc = {"{{request.object.metadata.name}}-suffix": 1}
        out = substitute_all(self.ctx(), doc)
        assert out == {"mypod-suffix": 1}

    def test_escaped_variable(self):
        doc = {"m": "literal \\{{not.a.var}} kept"}
        out = substitute_all(self.ctx(), doc)
        assert out == {"m": "literal {{not.a.var}} kept"}

    def test_nested_variable_resolution(self):
        ctx = self.ctx()
        ctx.add_json({"inner": "{{request.object.metadata.name}}"})
        # partial substitution loops until the nested variable resolves
        # (vars.go:388 "check for nested variables in strings"); a
        # whole-string variable returns its value verbatim (vars.go:372)
        out = substitute_all(ctx, {"m": "x-{{inner}}"})
        assert out == {"m": "x-mypod"}
        out2 = substitute_all(ctx, {"m": "{{inner}}"})
        assert out2 == {"m": "{{request.object.metadata.name}}"}

    def test_preconditions_resolver_empty_on_missing(self):
        doc = {"key": "{{unknown..bad}}"}
        out = substitute_all_in_preconditions(self.ctx(), doc)
        assert out == {"key": ""}

    def test_force_mutate_placeholders(self):
        doc = {"m": "{{anything.at.all}}", "n": "x"}
        out = substitute_all_force_mutate(None, doc)
        assert out == {"m": "placeholderValue", "n": "x"}

    def test_container_substitution_resolves_inner_vars(self):
        # traverse.go:62-78: the substituted result is itself traversed
        ctx = self.ctx()
        ctx.add_json({"cfg": {"n": "{{request.object.metadata.name}}"}})
        assert substitute_all(ctx, {"v": "{{cfg}}"}) == {"v": {"n": "mypod"}}

    def test_non_string_key_substitution_errors(self):
        from kyverno_tpu.engine.jsonutils import NonStringKeyError

        ctx = self.ctx()
        ctx.add_json({"cfg": {"n": 1}})
        with pytest.raises(NonStringKeyError):
            substitute_all(ctx, {"{{cfg}}": 1})

    def test_hyphen_variable_fails_cleanly(self):
        # hyphenated label keys must raise a resolution error, not crash
        with pytest.raises(VariableResolutionError):
            substitute_all(self.ctx(), {"m": "{{request.object.metadata.labels.app-name}}"})
        out = substitute_all_in_preconditions(
            self.ctx(), {"key": "{{request.object.metadata.labels.app-name}}"}
        )
        assert out == {"key": ""}

    def test_delete_request_rewrite(self):
        ctx = Context()
        ctx.add_json({"request": {"operation": "DELETE"}})
        ctx.add_old_resource({"metadata": {"name": "gone"}})
        out = substitute_all(ctx, {"m": "{{request.object.metadata.name}}"})
        assert out == {"m": "gone"}


class TestReferences:
    def test_relative_reference(self):
        # references are relative to the leaf's own path: ../ = sibling
        doc = {
            "validate": {
                "pattern": {
                    "spec": {"cpu": "4", "limit": "$(../cpu)"}
                }
            }
        }
        out = substitute_references(doc)
        assert out["validate"]["pattern"]["spec"]["limit"] == "4"

    def test_parent_reference(self):
        doc = {"a": {"b": "val", "c": {"d": "$(../../b)"}}}
        out = substitute_references(doc)
        assert out["a"]["c"]["d"] == "val"

    def test_reference_with_operator(self):
        doc = {"spec": {"min": "2", "check": "$(<=../min)"}}
        out = substitute_references(doc)
        assert out["spec"]["check"] == "<=2"

    def test_unresolvable_reference_raises(self):
        with pytest.raises((NotResolvedReferenceError, VariableResolutionError)):
            substitute_references({"a": "$(./nope)"})

    def test_escaped_reference(self):
        out = substitute_references({"a": "\\$(keep)"})
        assert out == {"a": "$(keep)"}
