"""Generate engine tests (filter + materialization)."""

from kyverno_tpu.api.load import load_policy
from kyverno_tpu.engine.context import Context
from kyverno_tpu.engine.generation import (
    MODE_CREATE,
    MODE_SKIP,
    MODE_UPDATE,
    apply_generate_rule,
    generate,
)
from kyverno_tpu.engine.policy_context import PolicyContext
from kyverno_tpu.engine.response import RuleStatus


class FakeClient:
    def __init__(self, resources=None):
        self.resources = resources or {}

    def get_resource(self, api_version, kind, namespace, name):
        return self.resources.get((kind, namespace, name))

    def list_resource(self, api_version, kind, namespace):
        return [v for (k, ns, _), v in self.resources.items()
                if k == kind and (not namespace or ns == namespace)]

    def get_configmap(self, namespace, name):
        return self.resources.get(("ConfigMap", namespace, name))


GEN_POLICY = {
    "apiVersion": "kyverno.io/v1",
    "kind": "ClusterPolicy",
    "metadata": {"name": "add-networkpolicy"},
    "spec": {"rules": [{
        "name": "default-deny",
        "match": {"resources": {"kinds": ["Namespace"]}},
        "generate": {
            "apiVersion": "networking.k8s.io/v1",
            "kind": "NetworkPolicy",
            "name": "default-deny",
            "namespace": "{{request.object.metadata.name}}",
            "synchronize": True,
            "data": {
                "spec": {"podSelector": {}, "policyTypes": ["Ingress", "Egress"]}
            },
        },
    }]},
}


def make_ctx(policy_doc, resource, client=None):
    jctx = Context()
    jctx.add_resource(resource)
    return PolicyContext(
        policy=load_policy(policy_doc), new_resource=resource,
        json_context=jctx, client=client,
    )


NAMESPACE = {"apiVersion": "v1", "kind": "Namespace", "metadata": {"name": "team-a"}}


class TestGenerateFilter:
    def test_matching_resource_produces_pass_row(self):
        resp = generate(make_ctx(GEN_POLICY, NAMESPACE))
        assert [r.status for r in resp.policy_response.rules] == [RuleStatus.PASS]

    def test_non_matching_kind_produces_nothing(self):
        pod = {"apiVersion": "v1", "kind": "Pod", "metadata": {"name": "p"}}
        resp = generate(make_ctx(GEN_POLICY, pod))
        assert resp.policy_response.rules == []

    def test_old_resource_match_produces_fail_row(self):
        pod = {"apiVersion": "v1", "kind": "Pod", "metadata": {"name": "p"}}
        ctx = make_ctx(GEN_POLICY, pod)
        ctx.old_resource = NAMESPACE
        resp = generate(ctx)
        assert [r.status for r in resp.policy_response.rules] == [RuleStatus.FAIL]


class TestMaterialization:
    def test_data_create_with_variables(self):
        ctx = make_ctx(GEN_POLICY, NAMESPACE, client=FakeClient())
        rule = ctx.policy.spec.rules[0]
        resource, mode = apply_generate_rule(rule, ctx, NAMESPACE, ctx.client)
        assert mode == MODE_CREATE
        assert resource["kind"] == "NetworkPolicy"
        assert resource["metadata"]["namespace"] == "team-a"  # substituted
        labels = resource["metadata"]["labels"]
        assert labels["kyverno.io/generated-by-policy"] == "add-networkpolicy"
        assert labels["kyverno.io/generated-by-name"] == "team-a"

    def test_data_update_when_target_exists(self):
        existing = {"metadata": {"resourceVersion": "42"}}
        client = FakeClient({("NetworkPolicy", "team-a", "default-deny"): existing})
        ctx = make_ctx(GEN_POLICY, NAMESPACE, client=client)
        rule = ctx.policy.spec.rules[0]
        resource, mode = apply_generate_rule(rule, ctx, NAMESPACE, client)
        assert mode == MODE_UPDATE
        assert resource["metadata"]["resourceVersion"] == "42"

    def test_clone(self):
        source = {
            "apiVersion": "v1", "kind": "Secret",
            "metadata": {"name": "regcred", "namespace": "default",
                         "resourceVersion": "7", "uid": "u1"},
            "data": {"token": "eA=="},
        }
        client = FakeClient({("Secret", "default", "regcred"): source})
        policy = {
            "apiVersion": "kyverno.io/v1",
            "kind": "ClusterPolicy",
            "metadata": {"name": "clone-secret"},
            "spec": {"rules": [{
                "name": "clone-regcred",
                "match": {"resources": {"kinds": ["Namespace"]}},
                "generate": {
                    "apiVersion": "v1", "kind": "Secret", "name": "regcred",
                    "namespace": "{{request.object.metadata.name}}",
                    "clone": {"namespace": "default", "name": "regcred"},
                },
            }]},
        }
        ctx = make_ctx(policy, NAMESPACE, client=client)
        rule = ctx.policy.spec.rules[0]
        resource, mode = apply_generate_rule(rule, ctx, NAMESPACE, client)
        assert mode == MODE_CREATE
        assert resource["data"] == {"token": "eA=="}
        assert resource["metadata"]["namespace"] == "team-a"
        assert "resourceVersion" not in resource["metadata"]
        assert "uid" not in resource["metadata"]

    def test_self_clone_skips(self):
        policy = {
            "apiVersion": "kyverno.io/v1",
            "kind": "ClusterPolicy",
            "metadata": {"name": "self-clone"},
            "spec": {"rules": [{
                "name": "r",
                "match": {"resources": {"kinds": ["Namespace"]}},
                "generate": {
                    "apiVersion": "v1", "kind": "Secret", "name": "s",
                    "namespace": "ns", "clone": {"namespace": "ns", "name": "s"},
                },
            }]},
        }
        ctx = make_ctx(policy, NAMESPACE, client=FakeClient())
        resource, mode = apply_generate_rule(
            ctx.policy.spec.rules[0], ctx, NAMESPACE, ctx.client
        )
        assert mode == MODE_SKIP and resource is None


class TestPolicyValidation:
    def test_valid_policy(self):
        from kyverno_tpu.policy.validation import validate_policy

        assert validate_policy(load_policy(GEN_POLICY)) == []

    def test_multiple_actions_invalid(self):
        from kyverno_tpu.policy.validation import validate_policy

        doc = {
            "apiVersion": "kyverno.io/v1",
            "kind": "ClusterPolicy",
            "metadata": {"name": "bad"},
            "spec": {"rules": [{
                "name": "two-actions",
                "match": {"resources": {"kinds": ["Pod"]}},
                "validate": {"pattern": {"spec": {}}},
                "mutate": {"patchStrategicMerge": {"metadata": {}}},
            }]},
        }
        errors = validate_policy(load_policy(doc))
        assert any("multiple operations" in e for e in errors)

    def test_duplicate_rule_names(self):
        from kyverno_tpu.policy.validation import validate_policy

        doc = {
            "apiVersion": "kyverno.io/v1",
            "kind": "ClusterPolicy",
            "metadata": {"name": "dup"},
            "spec": {"rules": [
                {"name": "r", "match": {"resources": {"kinds": ["Pod"]}},
                 "validate": {"pattern": {"spec": {}}}},
                {"name": "r", "match": {"resources": {"kinds": ["Pod"]}},
                 "validate": {"pattern": {"spec": {}}}},
            ]},
        }
        errors = validate_policy(load_policy(doc))
        assert any("duplicate rule name" in e for e in errors)

    def test_unknown_variable_flagged(self):
        from kyverno_tpu.policy.validation import validate_policy

        doc = {
            "apiVersion": "kyverno.io/v1",
            "kind": "ClusterPolicy",
            "metadata": {"name": "vars"},
            "spec": {"rules": [{
                "name": "r",
                "match": {"resources": {"kinds": ["Pod"]}},
                "validate": {
                    "message": "{{undefinedthing.foo}}",
                    "pattern": {"spec": {}},
                },
            }]},
        }
        errors = validate_policy(load_policy(doc))
        assert any("not defined in the rule context" in e for e in errors)
