"""Autogen (pod-controller rule generation) tests, mirroring
/root/reference/pkg/policymutation/policymutation_test.go."""

from kyverno_tpu.api.load import load_policy
from kyverno_tpu.policy.autogen import (
    can_auto_gen,
    generate_pod_controller_rules,
    mutate_policy_for_autogen,
)


def pod_policy(rule_extra=None, annotations=None):
    rule = {
        "name": "check-labels",
        "match": {"resources": {"kinds": ["Pod"]}},
        "validate": {
            "message": "label required",
            "pattern": {"metadata": {"labels": {"app": "?*"}}},
        },
    }
    rule.update(rule_extra or {})
    return {
        "apiVersion": "kyverno.io/v1",
        "kind": "ClusterPolicy",
        "metadata": {"name": "p", "annotations": annotations or {}},
        "spec": {"rules": [rule]},
    }


class TestCanAutoGen:
    def test_pod_rule_autogens(self):
        ok, controllers = can_auto_gen(pod_policy())
        assert ok and controllers == "DaemonSet,Deployment,Job,StatefulSet,CronJob"

    def test_name_match_blocks(self):
        doc = pod_policy()
        doc["spec"]["rules"][0]["match"]["resources"]["name"] = "foo"
        assert can_auto_gen(doc) == (False, "none")

    def test_mixed_kinds_block(self):
        doc = pod_policy()
        doc["spec"]["rules"][0]["match"]["resources"]["kinds"] = ["Pod", "Deployment"]
        assert can_auto_gen(doc) == (False, "none")

    def test_deny_blocks(self):
        doc = pod_policy({"validate": {"deny": {"conditions": []}}})
        assert can_auto_gen(doc) == (False, "none")


class TestGenerateRules:
    def test_pattern_wrapped_under_template(self):
        rules = generate_pod_controller_rules(pod_policy())
        by_name = {r["name"]: r for r in rules}
        assert set(by_name) == {"autogen-check-labels", "autogen-cronjob-check-labels"}

        auto = by_name["autogen-check-labels"]
        assert auto["match"]["resources"]["kinds"] == [
            "DaemonSet", "Deployment", "Job", "StatefulSet"
        ]
        assert auto["validate"]["pattern"] == {
            "spec": {"template": {"metadata": {"labels": {"app": "?*"}}}}
        }

        cron = by_name["autogen-cronjob-check-labels"]
        assert cron["match"]["resources"]["kinds"] == ["CronJob"]
        assert cron["validate"]["pattern"] == {
            "spec": {"jobTemplate": {"spec": {"template": {"metadata": {"labels": {"app": "?*"}}}}}}
        }

    def test_variables_shift_into_template(self):
        doc = pod_policy({
            "validate": {
                "message": "bad {{request.object.spec.containers[0].image}}",
                "pattern": {"spec": {"containers": [{"image": "?*"}]}},
            }
        })
        rules = generate_pod_controller_rules(doc)
        auto = next(r for r in rules if r["name"] == "autogen-check-labels")
        assert "request.object.spec.template.spec.containers" in auto["validate"]["message"]
        cron = next(r for r in rules if "cronjob" in r["name"])
        assert (
            "request.object.spec.jobTemplate.spec.template.spec.containers"
            in cron["validate"]["message"]
        )

    def test_annotation_none_disables(self):
        doc = pod_policy(
            annotations={"pod-policies.kyverno.io/autogen-controllers": "none"}
        )
        assert generate_pod_controller_rules(doc) == []

    def test_annotation_subset(self):
        doc = pod_policy(
            annotations={"pod-policies.kyverno.io/autogen-controllers": "Deployment"}
        )
        rules = generate_pod_controller_rules(doc)
        assert len(rules) == 1
        assert rules[0]["match"]["resources"]["kinds"] == ["Deployment"]

    def test_mutate_policy_defaults(self):
        policy = mutate_policy_for_autogen(load_policy(pod_policy()))
        assert policy.spec.validation_failure_action == "audit"
        assert len(policy.spec.rules) == 3
