"""Leaf comparator tests, mirroring the reference table-driven suites in
pkg/engine/validate/pattern_test.go semantics."""

import pytest

from kyverno_tpu.engine.pattern import Op, get_operator, validate_value_with_pattern as vvp


class TestOperators:
    @pytest.mark.parametrize(
        "pattern,op",
        [
            ("", Op.EQUAL),
            ("x", Op.EQUAL),
            (">=1", Op.MORE_EQUAL),
            ("<=10Gi", Op.LESS_EQUAL),
            (">5", Op.MORE),
            ("<5", Op.LESS),
            ("!latest", Op.NOT_EQUAL),
            ("1-10", Op.IN_RANGE),
            ("1!-10", Op.NOT_IN_RANGE),
            ("10Mi-20Mi", Op.IN_RANGE),
            ("10Mi!-20Mi", Op.NOT_IN_RANGE),
            ("abc-def", Op.EQUAL),  # no leading digits -> not a range
            ("1.5.7", Op.EQUAL),
        ],
    )
    def test_get_operator(self, pattern, op):
        assert get_operator(pattern) == op


class TestScalars:
    def test_bool(self):
        assert vvp(True, True)
        assert not vvp(False, True)
        assert not vvp("true", True)
        assert not vvp(1, True)

    def test_int_pattern(self):
        assert vvp(5, 5)
        assert not vvp(6, 5)
        assert vvp(5.0, 5)
        assert not vvp(5.5, 5)
        assert vvp("5", 5)
        assert not vvp("5x", 5)
        assert not vvp(True, 1)

    def test_float_pattern(self):
        assert vvp(5.5, 5.5)
        assert vvp(5, 5.0)
        assert not vvp(5, 5.5)
        assert vvp("5.5", 5.5)
        assert not vvp("abc", 5.5)

    def test_nil_pattern(self):
        assert vvp(None, None)
        assert vvp(0, None)
        assert vvp(0.0, None)
        assert vvp("", None)
        assert vvp(False, None)
        assert not vvp(1, None)
        assert not vvp({"a": 1}, None)
        assert not vvp([1], None)

    def test_map_pattern_existence_only(self):
        assert vvp({"a": 1}, {"x": "ignored"})
        assert not vvp("notamap", {"x": 1})

    def test_array_pattern_unsupported(self):
        assert not vvp([1, 2], [1, 2])


class TestStringPatterns:
    def test_wildcard_equality(self):
        assert vvp("nginx:latest", "*:latest")
        assert not vvp("nginx:1.21", "*:latest")
        assert vvp("nginx:1.21", "!*:latest")
        assert not vvp("nginx:latest", "!*:latest")
        assert vvp("anything", "*")

    def test_or_patterns(self):
        assert vvp("a", "a|b")
        assert vvp("b", "a|b")
        assert not vvp("c", "a|b")
        assert vvp("nginx:v1", "*:v1 | *:v2")
        assert vvp("nginx:v2", "*:v1 | *:v2")

    def test_and_patterns(self):
        assert vvp("nginx-prod", "nginx-* & *-prod")
        assert not vvp("nginx-dev", "nginx-* & *-prod")

    def test_numeric_comparisons(self):
        assert vvp(10, ">5")
        assert not vvp(3, ">5")
        assert vvp(5, ">=5")
        assert vvp(3, "<5")
        assert vvp(5, "<=5")
        assert not vvp(6, "<=5")
        assert vvp("10", ">5")

    def test_quantity_comparisons(self):
        assert vvp("100Mi", "<1Gi")
        assert not vvp("2Gi", "<1Gi")
        assert vvp("1024Mi", "1Gi")
        assert vvp("2", ">1500m")
        assert vvp("100m", "<1")

    def test_ranges(self):
        assert vvp(5, "1-10")
        assert vvp(1, "1-10")
        assert vvp(10, "1-10")
        assert not vvp(11, "1-10")
        assert not vvp(5, "1!-10")
        assert vvp(11, "1!-10")
        assert vvp(0, "1!-10")
        assert vvp("512Mi", "100Mi-1Gi")
        assert not vvp("2Gi", "100Mi-1Gi")
        assert vvp("2Gi", "100Mi!-1Gi")

    def test_number_string_coercion(self):
        # int value against numeric-looking string pattern: quantity compare
        assert vvp(8080, "8080")
        assert not vvp(8080, "8081")
        # value stringified for wildcard when pattern is not a quantity
        assert vvp("v1.2.3", "v1.*")
        assert vvp(None, "0")  # nil converts to "0" on the numeric path

    def test_inequality_on_strings_fails(self):
        assert not vvp("abc", ">abc")
        assert not vvp("abc", "<abc")
