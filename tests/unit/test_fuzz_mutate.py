"""Property fuzz for the mutation tier.

Mutate has no second implementation to cross-check against, so invariants
stand in for an oracle:

1. patch consistency — the RFC6902 ops the engine returns are the
   admission contract (the API server applies them to the original
   object); applying them must reproduce engine's patched resource
   exactly (generatePatches round-trip, mutate/patchesUtils.go).
2. idempotence — re-running the same strategic-merge policy over its own
   output must be a no-op (kustomize merge semantics; +() anchors only
   add when absent, so a second pass changes nothing).
3. validate agreement — the patched resource must satisfy the policy's
   own pattern when that pattern is anchor-free (what you merge is what
   you then match).
"""

import random

import pytest

from kyverno_tpu.api.load import load_policy
from kyverno_tpu.engine.context import Context
from kyverno_tpu.engine.mutate.json_patch import apply_patch_ops
from kyverno_tpu.engine.mutation import mutate
from kyverno_tpu.engine.policy_context import PolicyContext
from kyverno_tpu.engine.response import RuleStatus
from kyverno_tpu.engine.validate_pattern import match_pattern
from kyverno_tpu.utils.jsoncopy import json_copy

KEYS = ["alpha", "beta", "gamma", "labels", "mode"]
VALS = ["on", "off", "x1", "3", "250m", ""]


def rand_sm_pattern(rng, depth=0):
    """Strategic-merge pattern: maps with plain and +(add) keys. Bare keys
    stay unique — a map carrying the same key both plain and +()-anchored
    is contradictory input with no consistent fixpoint."""
    if depth >= 2 or rng.random() < 0.45:
        return rng.choice(VALS + [True, False, 7])
    out = {}
    for key in rng.sample(KEYS, rng.randint(1, 3)):
        if rng.random() < 0.4:
            key = f"+({key})"
        out[key] = rand_sm_pattern(rng, depth + 1)
    return out


def rand_resource(rng, i):
    def val(depth=0):
        if depth >= 2 or rng.random() < 0.55:
            return rng.choice(VALS + [True, 0, 5, None])
        return {rng.choice(KEYS): val(depth + 1)
                for _ in range(rng.randint(0, 3))}

    return {"apiVersion": "v1", "kind": "ConfigMap",
            "metadata": {"name": f"cm-{i}"},
            "data": {rng.choice(KEYS): val()
                     for _ in range(rng.randint(0, 3))}}


def run_mutate(policy, resource):
    jctx = Context()
    jctx.add_resource(resource)
    return mutate(PolicyContext(policy=policy, new_resource=json_copy(resource),
                                json_context=jctx))


@pytest.mark.parametrize("seed", range(1, 9))
def test_mutate_invariants(seed):
    rng = random.Random(990 + seed)
    for i in range(12):
        pattern = {"data": rand_sm_pattern(rng)}
        policy = load_policy({
            "apiVersion": "kyverno.io/v1", "kind": "ClusterPolicy",
            "metadata": {"name": f"m-{i}"},
            "spec": {"rules": [{
                "name": f"m-{i}-r",
                "match": {"resources": {"kinds": ["ConfigMap"]}},
                "mutate": {"patchStrategicMerge": pattern},
            }]},
        })
        for j in range(6):
            resource = rand_resource(rng, j)
            resp = run_mutate(policy, resource)
            statuses = [r.status for r in resp.policy_response.rules]
            if RuleStatus.ERROR in statuses:
                continue

            # 1. patch consistency
            replayed = apply_patch_ops(resource, resp.patches)
            assert replayed == resp.patched_resource, (
                f"seed={seed} patches diverge from patched resource\n"
                f"pattern={pattern}\nresource={resource}\n"
                f"patches={resp.patches}")

            # 2. idempotence
            resp2 = run_mutate(policy, resp.patched_resource)
            assert resp2.patched_resource == resp.patched_resource, (
                f"seed={seed} not idempotent\npattern={pattern}\n"
                f"first={resp.patched_resource}\n"
                f"second={resp2.patched_resource}")
            assert resp2.patches == [], (
                f"seed={seed} second pass emitted patches: {resp2.patches}")

            # 3. validate agreement (anchor-free patterns only: +() keys
            # are add-if-absent, so their value may legitimately differ)
            if "+(" not in str(pattern):
                check = match_pattern(resp.patched_resource, pattern)
                assert check.matched, (
                    f"seed={seed} merged resource fails its own pattern\n"
                    f"pattern={pattern}\npatched={resp.patched_resource}\n"
                    f"message={check.message}")
