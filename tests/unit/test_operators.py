"""Condition operator tests (mirrors variables/evaluate_test.go scenarios)."""

from kyverno_tpu.engine.operators import evaluate_condition as ev, evaluate_conditions


class TestEquals:
    def test_scalars(self):
        assert ev(True, "Equals", True)
        assert not ev(True, "Equals", False)
        assert ev(5, "Equals", 5)
        assert ev(5, "Equals", "5")
        assert ev(5.0, "Equals", 5)
        assert ev("abc", "Equals", "abc")
        assert not ev("abc", "NotEquals", "abc")
        assert ev("abc", "NotEquals", "abd")

    def test_value_is_wildcard(self):
        assert ev("nginx:latest", "Equals", "*:latest")
        assert not ev("*:latest", "Equals", "nginx:latest")  # key is not the pattern

    def test_quantity(self):
        assert ev("1Gi", "Equals", "1024Mi")
        assert ev("100m", "Equals", "0.1")

    def test_duration(self):
        assert ev("1h", "Equals", "60m")
        assert ev("1h", "Equals", 3600)

    def test_deep(self):
        assert ev({"a": [1, 2]}, "Equals", {"a": [1, 2]})
        assert not ev({"a": [1, 2]}, "Equals", {"a": [2, 1]})
        assert ev([1, "x"], "Equals", [1, "x"])

    def test_case_insensitive_operator(self):
        assert ev(5, "equals", 5)
        assert ev(5, "EQUALS", 5)


class TestInFamily:
    def test_in_string_key(self):
        assert ev("a", "In", ["a", "b"])
        assert not ev("c", "In", ["a", "b"])
        assert ev("nginx:*", "In", ["nginx:latest"])  # key is wildcard over items
        assert ev("c", "NotIn", ["a", "b"])

    def test_in_json_encoded_value(self):
        assert ev("a", "In", '["a", "b"]')
        assert not ev("c", "In", '["a", "b"]')

    def test_in_list_key_subset(self):
        assert ev(["a", "b"], "In", ["a", "b", "c"])
        assert not ev(["a", "z"], "In", ["a", "b", "c"])

    def test_anyin(self):
        assert ev(["a", "z"], "AnyIn", ["a", "b"])
        assert not ev(["y", "z"], "AnyIn", ["a", "b"])
        assert ev("a", "AnyIn", ["a", "b"])
        assert ev(5, "AnyIn", ["5", "6"])

    def test_allin(self):
        assert ev(["a", "b"], "AllIn", ["a", "b", "c"])
        assert not ev(["a", "z"], "AllIn", ["a", "b", "c"])

    def test_anynotin(self):
        assert ev(["a", "z"], "AnyNotIn", ["a", "b"])
        assert not ev(["a", "b"], "AnyNotIn", ["a", "b"])

    def test_allnotin(self):
        assert ev(["y", "z"], "AllNotIn", ["a", "b"])
        assert not ev(["a", "z"], "AllNotIn", ["a", "b"])

    def test_wildcards_in_membership(self):
        assert ev(["run*"], "AllIn", ["runc", "dockerd"])
        assert ev(["run*"], "AllNotIn", ["containerd"])

    def test_numeric_keys_sprint_coerce(self):
        # in.go:34 et al: numeric keys stringify before membership checks
        assert ev(5, "In", [5])
        assert not ev(5, "NotIn", [5])
        assert ev(5, "AllNotIn", ["4"])
        assert ev([80, 443], "AnyIn", ["80"])

    def test_single_element_key_special_case(self):
        # setExistsInArray short-circuits len(key)==1 && key[0]==value to
        # "exists" BEFORE the notIn flag applies — quirk preserved
        assert ev(["a"], "AllIn", "a")
        assert ev(["a"], "NotIn", "a")
        assert ev(["a"], "AnyNotIn", "a")
        assert ev(["a"], "AllNotIn", "a")

    def test_quantifier_boundaries(self):
        assert ev(["x", "y"], "AnyIn", ["y", "z"])
        assert not ev(["x", "y"], "AllIn", ["y", "z"])
        assert ev(["x", "y"], "AnyNotIn", ["y", "z"])
        assert not ev(["y"], "AllNotIn", ["y", "z"])


class TestNumeric:
    def test_numbers(self):
        assert ev(10, "GreaterThan", 5)
        assert not ev(5, "GreaterThan", 10)
        assert ev(5, "GreaterThanOrEquals", 5)
        assert ev(5, "LessThanOrEquals", 5)
        assert ev(3, "LessThan", 5)
        assert ev(10, "GreaterThan", "5")
        assert ev("10", "GreaterThan", 5)

    def test_quantities(self):
        assert ev("2Gi", "GreaterThan", "1Gi")
        assert ev("500Mi", "LessThan", "1Gi")
        assert ev("1Gi", "GreaterThanOrEquals", "1024Mi")

    def test_durations(self):
        assert ev("2h", "GreaterThan", "90m")
        assert ev("30m", "LessThan", "1h")
        assert ev("1h", "DurationGreaterThan", "30m")
        assert ev(7200, "DurationGreaterThan", "1h")

    def test_string_key_parse_order(self):
        # numeric.go:144: float key parse happens before quantity, so a bare
        # numeric key never quantity-compares against a suffixed value
        assert not ev("2", "LessThan", "1Gi")
        # non-crash on unparseable value against quantity key
        assert not ev("10Gi", "GreaterThan", float("inf"))


class TestAnyAll:
    def test_bare_list_is_and(self):
        conds = [
            {"key": 1, "operator": "Equals", "value": 1},
            {"key": 2, "operator": "Equals", "value": 2},
        ]
        assert evaluate_conditions(conds)
        conds[1]["value"] = 3
        assert not evaluate_conditions(conds)

    def test_any(self):
        conds = {
            "any": [
                {"key": 1, "operator": "Equals", "value": 2},
                {"key": 2, "operator": "Equals", "value": 2},
            ]
        }
        assert evaluate_conditions(conds)

    def test_all(self):
        conds = {
            "all": [
                {"key": 1, "operator": "Equals", "value": 1},
                {"key": 2, "operator": "Equals", "value": 3},
            ]
        }
        assert not evaluate_conditions(conds)

    def test_any_and_all_combined(self):
        conds = {
            "any": [{"key": 1, "operator": "Equals", "value": 1}],
            "all": [{"key": 2, "operator": "Equals", "value": 2}],
        }
        assert evaluate_conditions(conds)
        conds["all"][0]["value"] = 3
        assert not evaluate_conditions(conds)
