"""Match/exclude semantics tests (mirrors pkg/engine/utils_test.go scenarios)."""

from kyverno_tpu.api.types import Rule
from kyverno_tpu.engine.match import (
    AdmissionUserInfo,
    RequestInfo,
    check_kind,
    matches_resource_description,
)


def rule(match=None, exclude=None, name="r"):
    return Rule.from_dict({"name": name, "match": match or {}, "exclude": exclude or {}})


POD = {
    "apiVersion": "v1",
    "kind": "Pod",
    "metadata": {
        "name": "nginx",
        "namespace": "prod",
        "labels": {"app": "nginx", "tier": "web"},
        "annotations": {"owner": "team-a"},
    },
}


class TestCheckKind:
    def test_plain(self):
        assert check_kind(["Pod"], POD)
        assert check_kind(["pod"], POD)  # strings.Title normalization
        assert not check_kind(["Deployment"], POD)

    def test_star(self):
        assert check_kind(["*"], POD)

    def test_version_kind(self):
        assert check_kind(["v1/Pod"], POD)
        assert not check_kind(["v2/Pod"], POD)

    def test_group_version_kind(self):
        deploy = {"apiVersion": "apps/v1", "kind": "Deployment"}
        assert check_kind(["apps/v1/Deployment"], deploy)
        assert check_kind(["apps/*/Deployment"], deploy)
        assert not check_kind(["batch/v1/Deployment"], deploy)


class TestMatch:
    def test_kind_match(self):
        r = rule({"resources": {"kinds": ["Pod"]}})
        ok, _ = matches_resource_description(POD, r)
        assert ok

    def test_kind_no_match(self):
        r = rule({"resources": {"kinds": ["Service"]}})
        ok, _ = matches_resource_description(POD, r)
        assert not ok

    def test_name_wildcard(self):
        r = rule({"resources": {"kinds": ["Pod"], "name": "ngi*"}})
        assert matches_resource_description(POD, r)[0]
        r2 = rule({"resources": {"kinds": ["Pod"], "name": "redis*"}})
        assert not matches_resource_description(POD, r2)[0]

    def test_names_list(self):
        r = rule({"resources": {"kinds": ["Pod"], "names": ["a", "nginx"]}})
        assert matches_resource_description(POD, r)[0]

    def test_namespaces(self):
        r = rule({"resources": {"kinds": ["Pod"], "namespaces": ["prod"]}})
        assert matches_resource_description(POD, r)[0]
        r2 = rule({"resources": {"kinds": ["Pod"], "namespaces": ["dev*"]}})
        assert not matches_resource_description(POD, r2)[0]

    def test_selector(self):
        r = rule(
            {"resources": {"kinds": ["Pod"], "selector": {"matchLabels": {"app": "nginx"}}}}
        )
        assert matches_resource_description(POD, r)[0]
        r2 = rule(
            {"resources": {"kinds": ["Pod"], "selector": {"matchLabels": {"app": "redis"}}}}
        )
        assert not matches_resource_description(POD, r2)[0]

    def test_selector_wildcard(self):
        r = rule(
            {"resources": {"kinds": ["Pod"], "selector": {"matchLabels": {"app*": "?*"}}}}
        )
        assert matches_resource_description(POD, r)[0]

    def test_selector_expressions(self):
        r = rule(
            {
                "resources": {
                    "kinds": ["Pod"],
                    "selector": {
                        "matchExpressions": [
                            {"key": "tier", "operator": "In", "values": ["web", "api"]}
                        ]
                    },
                }
            }
        )
        assert matches_resource_description(POD, r)[0]

    def test_annotations(self):
        r = rule({"resources": {"kinds": ["Pod"], "annotations": {"owner": "team-*"}}})
        assert matches_resource_description(POD, r)[0]

    def test_empty_match_fails(self):
        assert not matches_resource_description(POD, rule())[0]

    def test_any_or(self):
        r = rule(
            {
                "any": [
                    {"resources": {"kinds": ["Service"]}},
                    {"resources": {"kinds": ["Pod"]}},
                ]
            }
        )
        assert matches_resource_description(POD, r)[0]

    def test_all_and(self):
        r = rule(
            {
                "all": [
                    {"resources": {"kinds": ["Pod"]}},
                    {"resources": {"namespaces": ["prod"]}},
                ]
            }
        )
        assert matches_resource_description(POD, r)[0]
        r2 = rule(
            {
                "all": [
                    {"resources": {"kinds": ["Pod"]}},
                    {"resources": {"namespaces": ["dev"]}},
                ]
            }
        )
        assert not matches_resource_description(POD, r2)[0]


class TestExclude:
    def test_exclude_namespace(self):
        r = rule(
            {"resources": {"kinds": ["Pod"]}},
            {"resources": {"namespaces": ["prod"]}},
        )
        assert not matches_resource_description(POD, r)[0]

    def test_exclude_not_matching(self):
        r = rule(
            {"resources": {"kinds": ["Pod"]}},
            {"resources": {"namespaces": ["kube-system"]}},
        )
        assert matches_resource_description(POD, r)[0]

    def test_exclude_cluster_role(self):
        r = rule(
            {"resources": {"kinds": ["Pod"]}},
            {"clusterRoles": ["cluster-admin"]},
        )
        info = RequestInfo(cluster_roles=["cluster-admin"])
        assert not matches_resource_description(POD, r, info)[0]
        info2 = RequestInfo(cluster_roles=["viewer"])
        assert matches_resource_description(POD, r, info2)[0]


class TestUserInfo:
    def test_subject_service_account(self):
        r = rule(
            {
                "resources": {"kinds": ["Pod"]},
                "subjects": [{"kind": "ServiceAccount", "namespace": "kube-system", "name": "builder"}],
            }
        )
        info = RequestInfo(
            admission_user_info=AdmissionUserInfo(
                username="system:serviceaccount:kube-system:builder"
            )
        )
        assert matches_resource_description(POD, r, info)[0]
        info2 = RequestInfo(admission_user_info=AdmissionUserInfo(username="alice"))
        assert not matches_resource_description(POD, r, info2)[0]

    def test_empty_admission_info_skips_userinfo(self):
        r = rule(
            {
                "resources": {"kinds": ["Pod"]},
                "clusterRoles": ["cluster-admin"],
            }
        )
        # background scan: no admission info -> userInfo constraint dropped
        assert matches_resource_description(POD, r)[0]

    def test_namespaced_policy(self):
        r = rule({"resources": {"kinds": ["Pod"]}})
        ok, _ = matches_resource_description(POD, r, policy_namespace="other")
        assert not ok
        ok2, _ = matches_resource_description(POD, r, policy_namespace="prod")
        assert ok2
