"""Deploy chart rendering (deploy/chart/kyverno-tpu via utils.helmlite)
and git-URL sources for `cli test`.

The chart must render the same object set as deploy/install.yaml (the
reference ships charts/kyverno as its real install path; install.yaml is
the kustomize fallback), and values must actually steer the output. The
git-source test builds a local repo and replays a test.yaml corpus from a
file:// clone — the offline shape of the reference's public-policies
regression (pkg/kyverno/test/git.go:14, Makefile:245-249)."""

import pathlib
import subprocess

import yaml

from kyverno_tpu.cli.__main__ import main as cli_main
from kyverno_tpu.utils.helmlite import render_chart

REPO = pathlib.Path(__file__).resolve().parents[2]
CHART = REPO / "deploy" / "chart" / "kyverno-tpu"


def _by_kind(docs):
    out = {}
    for doc in docs:
        out.setdefault(doc["kind"], []).append(doc)
    return out


class TestChartRendering:
    def test_renders_same_object_set_as_install_yaml(self):
        chart_docs = render_chart(CHART)
        install_docs = [d for d in yaml.safe_load_all(
            (REPO / "deploy" / "install.yaml").read_text()) if d]
        chart_kinds = {(d["kind"], d["metadata"]["name"])
                       for d in chart_docs}
        install_kinds = {(d["kind"], d["metadata"]["name"])
                         for d in install_docs}
        assert install_kinds <= chart_kinds, (
            f"missing from chart: {install_kinds - chart_kinds}")

    def test_deployment_defaults_match_install_yaml(self):
        dep = _by_kind(render_chart(CHART))["Deployment"][0]
        install_dep = [d for d in yaml.safe_load_all(
            (REPO / "deploy" / "install.yaml").read_text())
            if d and d["kind"] == "Deployment"][0]
        spec = dep["spec"]["template"]["spec"]
        want = install_dep["spec"]["template"]["spec"]
        assert dep["spec"]["replicas"] == install_dep["spec"]["replicas"]
        assert spec["containers"][0]["command"] == \
            want["containers"][0]["command"]
        assert spec["initContainers"][0]["command"] == \
            want["initContainers"][0]["command"]
        assert spec["containers"][0]["resources"] == \
            want["containers"][0]["resources"]
        assert spec["containers"][0]["livenessProbe"] == \
            want["containers"][0]["livenessProbe"]

    def test_values_steer_output(self):
        docs = render_chart(CHART, set_args=[
            "replicaCount=3", "image.repository=gcr.io/x/ktpu",
            "image.tag=v7", "webhooks.failurePolicy=Fail",
            "webhooks.timeoutSeconds=30", "createNamespace=false",
            "metricsService.create=false",
            "podLabels.team=platform",
        ])
        kinds = _by_kind(docs)
        assert "Namespace" not in kinds
        assert len(kinds["Service"]) == 1          # metrics service gone
        dep = kinds["Deployment"][0]
        assert dep["spec"]["replicas"] == 3
        container = dep["spec"]["template"]["spec"]["containers"][0]
        assert container["image"] == "gcr.io/x/ktpu:v7"
        env = {e["name"]: e["value"] for e in container["env"]}
        assert env["KTPU_DEFAULT_FAILURE_POLICY"] == "Fail"
        assert env["KTPU_WEBHOOK_TIMEOUT_S"] == "30"
        assert dep["spec"]["template"]["metadata"]["labels"]["team"] == \
            "platform"

    def test_rbac_covers_controller_api_groups(self):
        role = _by_kind(render_chart(CHART))["ClusterRole"][0]
        groups = {g for rule in role["rules"]
                  for g in rule.get("apiGroups", [])}
        for needed in ("kyverno.io", "wgpolicyk8s.io",
                       "admissionregistration.k8s.io",
                       "apiextensions.k8s.io", "coordination.k8s.io"):
            assert needed in groups, needed

    def test_cli_render_chart_command(self, capsys):
        rc = cli_main(["render-chart", str(CHART), "--set",
                       "replicaCount=2"])
        assert rc == 0
        docs = [d for d in yaml.safe_load_all(capsys.readouterr().out) if d]
        dep = [d for d in docs if d["kind"] == "Deployment"][0]
        assert dep["spec"]["replicas"] == 2


class TestGitTestSources:
    def _make_repo(self, tmp_path) -> str:
        src = tmp_path / "corpus"
        case = src / "cases" / "latest"
        case.mkdir(parents=True)
        (case / "policy.yaml").write_text(yaml.safe_dump({
            "apiVersion": "kyverno.io/v1", "kind": "ClusterPolicy",
            "metadata": {"name": "disallow-latest"},
            "spec": {"rules": [{
                "name": "no-latest",
                "match": {"resources": {"kinds": ["Pod"]}},
                "validate": {"pattern": {"spec": {"containers": [
                    {"image": "!*:latest"}]}}},
            }]}}))
        (case / "resources.yaml").write_text(yaml.safe_dump({
            "apiVersion": "v1", "kind": "Pod",
            "metadata": {"name": "bad"},
            "spec": {"containers": [{"name": "c",
                                     "image": "nginx:latest"}]}}))
        (case / "test.yaml").write_text(yaml.safe_dump({
            "name": "git-sourced",
            "policies": ["policy.yaml"],
            "resources": ["resources.yaml"],
            "results": [{"policy": "disallow-latest", "rule": "no-latest",
                         "resource": "bad", "status": "fail"}]}))
        subprocess.run(["git", "init", "-q", "-b", "main", str(src)],
                       check=True)
        subprocess.run(["git", "-C", str(src), "add", "-A"], check=True)
        subprocess.run(
            ["git", "-C", str(src), "-c", "user.email=t@t",
             "-c", "user.name=t", "commit", "-qm", "corpus"], check=True)
        return f"file://{src}"

    def test_cli_test_runs_from_git_url(self, tmp_path, capsys):
        url = self._make_repo(tmp_path)
        rc = cli_main(["test", url, "-b", "main"])
        out = capsys.readouterr().out
        assert rc == 0
        assert "1/1 passed" in out

    def test_unreachable_git_url_reports_cleanly(self, tmp_path, capsys):
        rc = cli_main(["test", f"file://{tmp_path}/nope.git"])
        assert rc == 2          # no test yamls -> distinct exit code
        err = capsys.readouterr().err
        assert "failed to clone" in err

    def test_failed_clone_fails_run_even_with_passing_local_tests(
            self, tmp_path, capsys):
        """A named-but-unfetchable corpus must go red, not silently skip
        while local tests keep the exit code green."""
        local = self._make_repo(tmp_path)[len("file://"):]
        rc = cli_main(["test", local, f"file://{tmp_path}/nope.git"])
        out = capsys.readouterr()
        assert "1/1 passed" in out.out      # local corpus ran and passed
        assert "failed to clone" in out.err
        assert rc == 1                      # but the run still fails


class TestRenderDriftGuard:
    def test_default_render_matches_vendored_golden(self):
        """The offline renderer implements a hand-rolled Go-template
        subset; a chart edit that renders differently (or wrongly) must
        fail THIS diff, not ship silently. Regenerating the golden is a
        deliberate act recorded in its header."""
        import yaml

        got = render_chart(CHART)
        golden = REPO / "deploy" / "chart" / "golden-default-render.yaml"
        with open(golden) as f:
            want = [d for d in yaml.safe_load_all(f) if d]
        assert got == want

    def test_unsupported_constructs_fail_loudly(self, tmp_path):
        """range/with/$vars/unknown functions raise instead of rendering
        as literal text that LOOKS like a valid manifest."""
        import pytest

        def chart_with(body: str):
            d = tmp_path / "c"
            (d / "templates").mkdir(parents=True, exist_ok=True)
            (d / "Chart.yaml").write_text(
                "name: t\nversion: 0.1.0\nappVersion: '1'\n")
            (d / "values.yaml").write_text("items: [a, b]\n")
            (d / "templates" / "x.yaml").write_text(body)
            return d

        for body in (
            "data:\n{{ range .Values.items }}\n- {{ . }}\n{{ end }}\n",
            "x: {{ with .Values.items }}y{{ end }}\n",
            "x: {{ $v := .Values.items }}\n",
            "x: {{ printf \"%s\" .Values.items }}\n",
            "x: {{ .Values.items | upper }}\n",
        ):
            with pytest.raises(ValueError, match="unsupported template"):
                render_chart(chart_with(body))

    def test_template_comments_render_as_nothing(self, tmp_path):
        d = tmp_path / "c"
        (d / "templates").mkdir(parents=True)
        (d / "Chart.yaml").write_text(
            "name: t\nversion: 0.1.0\nappVersion: '1'\n")
        (d / "values.yaml").write_text("x: 1\n")
        (d / "templates" / "x.yaml").write_text(
            "{{- /* a helm comment */ -}}\nv: {{ .Values.x }}\n")
        docs = render_chart(d)
        assert docs == [{"v": 1}]
