"""Batch mutate tier: byte-parity with the serial engine path.

The contract (engine/mutate/batch.py): for any policy set and document
list, ``BatchMutator.apply`` produces exactly the patches and patched
resources the serial per-policy engine chain produces — with or without
the device gate screen.
"""

import json
import random

from kyverno_tpu.api.load import load_policies_from_path, load_policy
from kyverno_tpu.engine.context import Context
from kyverno_tpu.engine.mutate.batch import (
    BatchMutator,
    fast_strategic_merge,
    merge_emit,
)
from kyverno_tpu.engine.mutate.json_patch import generate_patches
from kyverno_tpu.engine.mutate.strategic_merge import (
    ConditionError,
    GlobalConditionError,
    _has_anchor,
    _has_anchors,
    merge,
    pre_process_pattern,
    strategic_merge_patch,
)
from kyverno_tpu.engine.mutation import mutate
from kyverno_tpu.engine.policy_context import PolicyContext
from kyverno_tpu.utils.jsoncopy import json_copy


def serial_reference(policies, doc):
    """The webhook's serial chain (runtime/webhook.py _resource_mutation):
    per policy, engine mutate; patched resource feeds the next policy."""
    resource = doc
    patches = []
    for policy in policies:
        jctx = Context()
        jctx.add_resource(resource)
        resp = mutate(PolicyContext(policy=policy, new_resource=resource,
                                    json_context=jctx))
        patches.extend(resp.patches)
        if resp.patched_resource is not None:
            resource = resp.patched_resource
    return patches, resource


def assert_parity(policies, docs, **apply_kw):
    batch = BatchMutator(policies)
    results = batch.apply(docs, **apply_kw)
    for doc, got in zip(docs, results):
        want_patches, want_resource = serial_reference(policies, doc)
        assert json.dumps(got.patches) == json.dumps(want_patches), (
            f"patch divergence for {doc}\n"
            f"batch={got.patches}\nserial={want_patches}")
        assert got.patched_resource == want_resource


def pod(i, kind="Pod", labels=None):
    doc = {"apiVersion": "v1", "kind": kind,
           "metadata": {"name": f"r-{i}", "namespace": "default"},
           "spec": {"containers": [{"name": "c", "image": f"img:{i}"}]}}
    if labels:
        doc["metadata"]["labels"] = labels
    return doc


class TestReferenceCorpus:
    def test_add_default_labels_mixed_kinds(self):
        pols = [p for p in load_policies_from_path("/root/reference/test/more/")
                if p.name == "add-default-labels"]
        docs = [pod(0), pod(1, kind="Service"), pod(2, kind="Namespace"),
                pod(3, kind="Deployment"),  # not matched by the policy
                pod(4, labels={"custom-foo-label": "already-set"})]
        assert_parity(pols, docs, use_device_gate=False)
        assert_parity(pols, docs, use_device_gate=True)

    def test_whole_mutate_corpus(self):
        pols = [p for p in load_policies_from_path("/root/reference/test/more/")
                if any(r.has_mutate() for r in p.spec.rules)]
        assert pols, "corpus should contain mutate policies"
        docs = [pod(i) for i in range(8)]
        assert_parity(pols, docs, use_device_gate=False)
        assert_parity(pols, docs, use_device_gate=True)

    def test_gate_skips_unmatched_kinds(self):
        pols = [p for p in load_policies_from_path("/root/reference/test/more/")
                if p.name == "add-default-labels"]
        batch = BatchMutator(pols)
        docs = [pod(i, kind="Secret") for i in range(4)]
        for r in batch.apply(docs, use_device_gate=True):
            assert r.patches == []


class TestChaining:
    POLICIES = [
        {
            "apiVersion": "kyverno.io/v1", "kind": "ClusterPolicy",
            "metadata": {"name": "step1"},
            "spec": {"rules": [{
                "name": "tag",
                "match": {"resources": {"kinds": ["Pod"]}},
                "mutate": {"patchStrategicMerge": {
                    "metadata": {"labels": {"stage": "tagged"}}}},
            }]},
        },
        {
            "apiVersion": "kyverno.io/v1", "kind": "ClusterPolicy",
            "metadata": {"name": "step2"},
            "spec": {"rules": [{
                "name": "after-tag",
                # matches only resources rule 1 just labeled: the batch
                # tier must re-gate on the patched doc, not the original
                "match": {"resources": {"kinds": ["Pod"], "selector": {
                    "matchLabels": {"stage": "tagged"}}}},
                "mutate": {"patchStrategicMerge": {
                    "metadata": {"annotations": {"+(chained)": "yes"}}}},
            }]},
        },
    ]

    def test_patch_enables_later_rule(self):
        policies = [load_policy(p) for p in self.POLICIES]
        docs = [pod(i) for i in range(4)]
        assert_parity(policies, docs, use_device_gate=False)
        assert_parity(policies, docs, use_device_gate=True)
        # and the chain really fired: both labels and annotation landed
        got = BatchMutator(policies).apply(docs, use_device_gate=True)[0]
        assert got.patched_resource["metadata"]["labels"]["stage"] == "tagged"
        assert got.patched_resource["metadata"]["annotations"]["chained"] == "yes"


class TestMixedPlan:
    def test_engine_fallback_policy_does_not_shift_gate_columns(self):
        # policy A mixes a static rule with a variable rule -> whole policy
        # falls back to the engine and must NOT consume gate columns;
        # policy B's single gate must land on column 0
        mixed = load_policy({
            "apiVersion": "kyverno.io/v1", "kind": "ClusterPolicy",
            "metadata": {"name": "mixed"},
            "spec": {"rules": [
                {"name": "static", "match": {"resources": {"kinds": ["Pod"]}},
                 "mutate": {"patchStrategicMerge": {
                     "metadata": {"labels": {"s": "1"}}}}},
                {"name": "vars", "match": {"resources": {"kinds": ["Pod"]}},
                 "mutate": {"patchStrategicMerge": {
                     "metadata": {"labels": {"n": "{{request.object.metadata.name}}"}}}}},
            ]},
        })
        fast = load_policy({
            "apiVersion": "kyverno.io/v1", "kind": "ClusterPolicy",
            "metadata": {"name": "fast"},
            "spec": {"rules": [{
                "name": "r", "match": {"resources": {"kinds": ["Pod"]}},
                "mutate": {"patchStrategicMerge": {
                    "metadata": {"labels": {"f": "1"}}}}}]},
        })
        bm = BatchMutator([mixed, fast])
        modes = [(p.name, mode) for p, mode, _ in bm.plan]
        assert ("mixed", "engine") in modes and ("fast", "fast") in modes
        (_, _, fast_rules), = [t for t in bm.plan if t[0].name == "fast"]
        assert fast_rules[0].gate_index == 0
        docs = [pod(i) for i in range(4)]
        assert_parity([mixed, fast], docs, use_device_gate=True)

    def test_kind_only_gate_compiles_on_device(self):
        # a gate with no pattern paths at all (kind-only match) must still
        # evaluate on device, not silently fall back to host gating
        pols = [load_policy({
            "apiVersion": "kyverno.io/v1", "kind": "ClusterPolicy",
            "metadata": {"name": "kind-only"},
            "spec": {"rules": [{
                "name": "r", "match": {"resources": {"kinds": ["Pod"]}},
                "mutate": {"patchStrategicMerge": {
                    "metadata": {"labels": {"k": "1"}}}}}]},
        })]
        bm = BatchMutator(pols)
        verdicts = bm.gate_verdicts([pod(0), pod(1, kind="Secret")])
        assert verdicts is not None, "device gate must not silently degrade"
        assert verdicts[0, 0] == 1 and verdicts[1, 0] == 0  # PASS / NA


KEYS = ["alpha", "beta", "labels", "mode", "name"]
VALS = ["on", "off", "3", "250m", "", True, 7, None]


def rand_tree(rng, depth=0):
    r = rng.random()
    if depth >= 3 or r < 0.4:
        return rng.choice(VALS)
    if r < 0.55:
        return [rand_tree(rng, depth + 2) for _ in range(rng.randint(0, 3))]
    return {rng.choice(KEYS): rand_tree(rng, depth + 1)
            for _ in range(rng.randint(0, 3))}


def rand_overlay(rng, depth=0):
    """Overlay grammar: maps with plain, +(add), (condition) keys, keyed
    and plain lists, scalars — the anchor families strategic merge
    understands."""
    r = rng.random()
    if depth >= 3 or r < 0.35:
        return rng.choice(VALS)
    if r < 0.5:
        els = []
        for _ in range(rng.randint(1, 2)):
            el = {"name": rng.choice(["a", "b", "c"])}
            el[rng.choice(KEYS[:4])] = rand_overlay(rng, depth + 2)
            els.append(el)
        return els
    out = {}
    for key in rng.sample(KEYS[:4], rng.randint(1, 3)):
        kind = rng.random()
        if kind < 0.25:
            out[f"+({key})"] = rand_overlay(rng, depth + 1)
        elif kind < 0.45:
            out[f"({key})"] = rng.choice(["on", "off", "3", "?*"])
        else:
            out[key] = rand_overlay(rng, depth + 1)
    return out


class TestMergeEmitProperty:
    def test_merge_emit_matches_merge_plus_diff(self):
        rng = random.Random(2024)
        for _ in range(400):
            base = rand_tree(rng)
            patch = rand_overlay(rng)
            if not isinstance(base, dict) or not isinstance(patch, dict):
                continue
            # strip anchors for the raw-merge comparison
            patch = json.loads(json.dumps(patch).replace("+(", "").replace(
                ")\":", "\":").replace("(", "").replace(")", ""))
            want_merged = merge(patch, base)
            want_ops = generate_patches(base, want_merged)
            ops: list = []
            got_merged = merge_emit(patch, json_copy(base), "", ops)
            from kyverno_tpu.engine.mutate.json_patch import (
                filter_and_sort_patches,
            )

            assert got_merged == want_merged, (base, patch)
            assert json.dumps(filter_and_sort_patches(ops)) == json.dumps(
                want_ops), (base, patch, ops, want_ops)

    def test_fast_strategic_merge_matches_engine_pipeline(self):
        rng = random.Random(777)
        for _ in range(400):
            base = rand_tree(rng)
            overlay = rand_overlay(rng)
            if not isinstance(base, dict) or not isinstance(overlay, dict):
                continue
            try:
                want_patched = strategic_merge_patch(base, overlay)
            except Exception:
                continue
            want_ops = generate_patches(base, want_patched)
            got_patched, got_ops = fast_strategic_merge(
                json_copy(base), overlay,
                _has_anchors(overlay, _has_anchor))
            assert json.dumps(got_ops) == json.dumps(want_ops), (
                base, overlay, got_ops, want_ops)
            # on condition failure the fast path returns base unpatched
            # (same bytes as the engine's copy)
            assert got_patched == want_patched, (base, overlay)


class TestPolicyFuzzParity:
    def test_fuzzed_policies_full_parity(self):
        rng = random.Random(4242)
        for i in range(40):
            overlay = rand_overlay(rng)
            if not isinstance(overlay, dict) or not overlay:
                continue
            policy = load_policy({
                "apiVersion": "kyverno.io/v1", "kind": "ClusterPolicy",
                "metadata": {"name": f"fz-{i}"},
                "spec": {"rules": [{
                    "name": f"fz-{i}-r",
                    "match": {"resources": {"kinds": ["ConfigMap"]}},
                    "mutate": {"patchStrategicMerge": {"data": overlay}},
                }]},
            })
            docs = []
            for j in range(5):
                t = rand_tree(rng)
                docs.append({"apiVersion": "v1", "kind": "ConfigMap",
                             "metadata": {"name": f"cm-{j}"},
                             "data": t if isinstance(t, dict) else {"k": t}})
            assert_parity([policy], docs, use_device_gate=False)
