"""JMESPath dialect tests: spec behaviors + the 19 kyverno functions."""

import pytest

from kyverno_tpu.engine.jmespath import JMESPathError, search


class TestCore:
    @pytest.mark.parametrize(
        "expr,data,want",
        [
            ("a", {"a": 1}, 1),
            ("a.b.c", {"a": {"b": {"c": 42}}}, 42),
            ("a", [1], None),
            ("a[0]", {"a": [9]}, 9),
            ("a[-1]", {"a": [1, 2, 3]}, 3),
            ("a[5]", {"a": [1]}, None),
            ("a[1:3]", {"a": [0, 1, 2, 3]}, [1, 2]),
            ("a[::2]", {"a": [0, 1, 2, 3]}, [0, 2]),
            ('"weird.key"', {"weird.key": 5}, 5),
            ("@", {"x": 1}, {"x": 1}),
            ("`\"literal\"`", {}, "literal"),
            ("'raw'", {}, "raw"),
            ("`[1, 2]`", {}, [1, 2]),
        ],
    )
    def test_basics(self, expr, data, want):
        assert search(expr, data) == want

    def test_missing_key_raises_not_found(self):
        # kyverno/go-jmespath fork semantics (reference go.mod:64): a field
        # access on a map without that key is an "Unknown key" error, which
        # the variable system uses to detect unresolved variables.
        with pytest.raises(JMESPathError):
            search("a.b", {"a": {}})

    def test_projections(self):
        data = {"a": [{"b": {"c": 1}}, {"b": {"c": 2}}, {"x": 0}]}
        assert search("a[*].b.c", data) == [1, 2]
        assert search("a[]", {"a": [[{"b": 1}], [{"b": 2}]]}) == [{"b": 1}, {"b": 2}]
        assert search("a[].b", {"a": [[{"b": 1}], [{"b": 2}]]}) == [1, 2]
        assert search("a.*.c", {"a": {"x": {"c": 1}, "y": {"c": 2}}}) == [1, 2]
        assert search("a[*].b[0]", {"a": [{"b": [7]}]}) == [7]

    def test_filters(self):
        data = {"items": [{"n": "a", "v": 1}, {"n": "b", "v": 2}]}
        assert search("items[?v>`1`].n", data) == ["b"]
        assert search("items[?n=='a'].v", data) == [1]
        assert search("items[?v>=`1`] | length(@)", data) == 2

    def test_logical(self):
        assert search("a || b", {"b": 2}) == 2
        assert search("a && b", {"a": 1, "b": 2}) == 2
        assert search("!a", {"a": ""}) is True
        assert search("a == b", {"a": 1, "b": 1}) is True
        assert search("a != b", {"a": 1, "b": 1}) is False
        assert search("a < b", {"a": 1, "b": 2}) is True

    def test_multiselect(self):
        assert search("[a, b]", {"a": 1, "b": 2}) == [1, 2]
        assert search("{x: a}", {"a": 1}) == {"x": 1}
        assert search("a.[b, c]", {"a": {"b": 1, "c": 2}}) == [1, 2]

    def test_pipe_stops_projection(self):
        # projection RHS stops at the pipe: [0] applies to the whole list
        assert search("a[*].b | [0]", {"a": [{"b": 1}, {"b": 2}]}) == 1

    def test_functions(self):
        assert search("length(a)", {"a": "xyz"}) == 3
        assert search("keys(a)", {"a": {"k": 1}}) == ["k"]
        assert search("sort_by(a, &v)[0].n", {"a": [{"n": "x", "v": 2}, {"n": "y", "v": 1}]}) == "y"
        assert search("max_by(a, &v).n", {"a": [{"n": "x", "v": 2}, {"n": "y", "v": 1}]}) == "x"
        assert search("map(&b, a)", {"a": [{"b": 1}, {"b": 2}]}) == [1, 2]
        assert search("to_number('3')", {}) == 3
        assert search("starts_with(a, 'ng')", {"a": "nginx"}) is True
        assert search("merge(a, b)", {"a": {"x": 1}, "b": {"y": 2}}) == {"x": 1, "y": 2}
        assert search("not_null(a, b)", {"b": 3}) == 3

    def test_unknown_function_raises(self):
        with pytest.raises(JMESPathError):
            search("nope(a)", {"a": 1})

    def test_parse_error(self):
        with pytest.raises(JMESPathError):
            search("a.[", {})


class TestKyvernoDialect:
    @pytest.mark.parametrize(
        "expr,want",
        [
            ("compare('a', 'b')", -1),
            ("compare('b', 'a')", 1),
            ("compare('a', 'a')", 0),
            ("equal_fold('Abc', 'aBC')", True),
            ("replace('aaa', 'a', 'b', `2`)", "bba"),
            ("replace('aaa', 'a', 'b', `-1`)", "bbb"),
            ("replace_all('a-b-c', '-', '.')", "a.b.c"),
            ("to_upper('abc')", "ABC"),
            ("to_lower('ABC')", "abc"),
            ("trim('xxhixx', 'x')", "hi"),
            ("split('a,b', ',')", ["a", "b"]),
            ("regex_match('^v\\d+', 'v123')", True),
            ("regex_match('^v\\d+$', 'x1')", False),
            ("regex_replace_all('ab(\\d+)', 'ab123', 'x$1')", "x123"),
            ("regex_replace_all_literal('\\d+', 'ab123', 'N')", "abN"),
            ("label_match(`{\"a\":\"1\"}`, `{\"a\":\"1\",\"b\":\"2\"}`)", True),
            ("label_match(`{\"a\":\"1\"}`, `{\"a\":\"2\"}`)", False),
            ("add(`3`, `4`)", 7),
            ("subtract(`3`, `4`)", -1),
            ("multiply(`3`, `4`)", 12),
            ("divide(`8`, `2`)", 4),
            ("modulo(`7`, `3`)", 1),
            ("base64_encode('hello')", "aGVsbG8="),
            ("base64_decode('aGVsbG8=')", "hello"),
        ],
    )
    def test_functions(self, expr, want):
        assert search(expr, {}) == want

    def test_divide_by_zero(self):
        with pytest.raises(JMESPathError):
            search("divide(`1`, `0`)", {})

    def test_number_coercion_in_regex(self):
        assert search("regex_match('^12$', `12`)", {}) is True

    def test_missing_regex_group_expands_empty(self):
        # Go ReplaceAllString expands unknown $N to "" instead of erroring
        assert search("regex_replace_all('cost', 'cost: 10', '$9.99')", {}) == ".99: 10"
        assert search("regex_replace_all('x', 'x', '$$lit')", {}) == "$lit"

    def test_literal_replacement_keeps_dollars_and_backslashes(self):
        assert search("regex_replace_all_literal('\\d+', 'ab12', '$1\\x')", {}) == "ab$1\\x"

    def test_hyphen_identifier_is_parse_error(self):
        with pytest.raises(JMESPathError):
            search("foo-bar", {})

    def test_to_array_null_wraps(self):
        assert search("to_array(`null`)", {}) == [None]
        assert search("length(to_array(`null`))", {}) == 1
