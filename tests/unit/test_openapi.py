"""OpenAPI schema validation (pkg/openapi/validation.go semantics)."""

from kyverno_tpu.api.load import load_policy
from kyverno_tpu.policy.openapi import (
    register_schema,
    validate_policy_mutation,
    validate_resource,
)
from kyverno_tpu.runtime.webhook import (
    POLICY_VALIDATING_WEBHOOK_PATH,
    WebhookServer,
)


def mutate_policy(pattern, kinds=("Pod",)):
    return load_policy({
        "apiVersion": "kyverno.io/v1", "kind": "ClusterPolicy",
        "metadata": {"name": "m"},
        "spec": {"rules": [{
            "name": "m-r",
            "match": {"resources": {"kinds": list(kinds)}},
            "mutate": {"patchStrategicMerge": pattern},
        }]},
    })


class TestValidateResource:
    def test_valid_pod(self):
        pod = {"apiVersion": "v1", "kind": "Pod",
               "metadata": {"name": "p", "labels": {"a": "b"}},
               "spec": {"containers": [{
                   "name": "c", "image": "nginx:1.21",
                   "resources": {"requests": {"memory": "64Mi"}},
                   "ports": [{"containerPort": 80}]}]}}
        assert validate_resource(pod) == []

    def test_unknown_field(self):
        pod = {"kind": "Pod", "spec": {"containers": [
            {"name": "c", "imagePullPolice": "Always"}]}}
        errs = validate_resource(pod)
        assert any("imagePullPolice" in e and "unknown field" in e
                   for e in errs)

    def test_wrong_type(self):
        pod = {"kind": "Pod", "spec": {"hostNetwork": "yes"}}
        errs = validate_resource(pod)
        assert any("hostNetwork" in e and "boolean" in e for e in errs)

    def test_unknown_kind_skipped(self):
        assert validate_resource({"kind": "MyCRD", "whatever": 1}) == []

    def test_registered_schema(self):
        from kyverno_tpu.policy.openapi import STRING, obj

        register_schema("Gadget", obj({"kind": STRING, "apiVersion": STRING,
                                       "metadata": obj(open_=True),
                                       "size": STRING}))
        assert validate_resource({"kind": "Gadget", "size": "big"}) == []
        errs = validate_resource({"kind": "Gadget", "size": 3})
        assert any("size" in e for e in errs)


class TestValidatePolicyMutation:
    def test_valid_mutation_accepted(self):
        policy = mutate_policy({"metadata": {"labels": {"+(team)": "x"}}})
        assert validate_policy_mutation(policy) == []

    def test_schema_invalid_mutation_rejected(self):
        # writes a misspelled container field -> schema error
        policy = mutate_policy({"spec": {"containers": [
            {"name": "c", "imagePullPolice": "Always"}]}})
        errs = validate_policy_mutation(policy)
        assert errs and "imagePullPolice" in errs[0]

    def test_wrong_type_mutation_rejected(self):
        policy = mutate_policy({"spec": {"hostNetwork": "true"}})
        errs = validate_policy_mutation(policy)
        assert errs and "hostNetwork" in errs[0]

    def test_unknown_kind_mutation_skipped(self):
        policy = mutate_policy({"spec": {"anything": 1}}, kinds=("MyCRD",))
        assert validate_policy_mutation(policy) == []


class TestPolicyValidationWebhook:
    def _review(self, doc):
        return {"apiVersion": "admission.k8s.io/v1", "kind": "AdmissionReview",
                "request": {"uid": "u", "kind": {"kind": "ClusterPolicy"},
                            "operation": "CREATE", "object": doc}}

    def test_schema_invalid_policy_blocked(self):
        server = WebhookServer()
        doc = {
            "apiVersion": "kyverno.io/v1", "kind": "ClusterPolicy",
            "metadata": {"name": "bad-mutate"},
            "spec": {"rules": [{
                "name": "r",
                "match": {"resources": {"kinds": ["Pod"]}},
                "mutate": {"patchStrategicMerge": {
                    "spec": {"hostNetwork": "not-a-bool"}}},
            }]},
        }
        out = server.handle(POLICY_VALIDATING_WEBHOOK_PATH, self._review(doc))
        assert out["response"]["allowed"] is False
        assert "hostNetwork" in out["response"]["status"]["message"]

    def test_valid_policy_allowed(self):
        server = WebhookServer()
        doc = {
            "apiVersion": "kyverno.io/v1", "kind": "ClusterPolicy",
            "metadata": {"name": "good-mutate"},
            "spec": {"rules": [{
                "name": "r",
                "match": {"resources": {"kinds": ["Pod"]}},
                "mutate": {"patchStrategicMerge": {
                    "metadata": {"labels": {"+(team)": "x"}}}},
            }]},
        }
        out = server.handle(POLICY_VALIDATING_WEBHOOK_PATH, self._review(doc))
        assert out["response"]["allowed"] is True
