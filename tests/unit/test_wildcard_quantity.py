from fractions import Fraction

import pytest

from kyverno_tpu.utils.duration import DurationError, parse_duration
from kyverno_tpu.utils.quantity import QuantityError, parse_quantity
from kyverno_tpu.utils.wildcard import wildcard_match


@pytest.mark.parametrize(
    "pattern,text,want",
    [
        ("*", "", True),
        ("*", "anything", True),
        ("", "", True),
        ("", "x", False),
        ("*:*", "nginx:latest", True),
        ("*:*", "nginx", False),
        ("*:latest", "nginx:latest", True),
        ("*:latest", "nginx:1.21", False),
        ("nginx*", "nginx-deployment", True),
        ("?at", "cat", True),
        ("?at", "at", False),
        ("a*b*c", "aXXbYYc", True),
        ("a*b*c", "acb", False),
        ("*a*a*a*", "aaa", True),
        ("*.example.com", "svc.example.com", True),
        ("ab", "ab", True),
        ("a?", "ab", True),
        ("??", "a", False),
        ("kubernetes.io/*", "kubernetes.io/hostname", True),
    ],
)
def test_wildcard(pattern, text, want):
    assert wildcard_match(pattern, text) is want


@pytest.mark.parametrize(
    "s,want",
    [
        ("1", 1),
        ("100", 100),
        ("-5", -5),
        ("+5", 5),
        ("1.5", Fraction(3, 2)),
        ("100m", Fraction(1, 10)),
        ("1500m", Fraction(3, 2)),
        ("1Ki", 1024),
        ("1Mi", 1024 * 1024),
        ("2Gi", 2 * 1024**3),
        ("1k", 1000),
        ("1M", 10**6),
        ("3e2", 300),
        ("3E2", 300),
        ("1E", 10**18),
        ("0.5Gi", 2**29),
        (".5", Fraction(1, 2)),
    ],
)
def test_quantity_parse(s, want):
    assert parse_quantity(s) == Fraction(want)


@pytest.mark.parametrize("s", ["", "abc", "1.2.3", "10Xi", "1,000", "--1", "1 Gi", "mi"])
def test_quantity_invalid(s):
    with pytest.raises(QuantityError):
        parse_quantity(s)


def test_quantity_cross_suffix_compare():
    assert parse_quantity("1024Mi") == parse_quantity("1Gi")
    assert parse_quantity("0.1") == parse_quantity("100m")
    assert parse_quantity("1Gi") > parse_quantity("900M")
    assert parse_quantity("500Mi") < parse_quantity("1G")


@pytest.mark.parametrize(
    "s,want",
    [
        ("1h", 3600.0),
        ("1h30m", 5400.0),
        ("300ms", 0.3),
        ("-1.5h", -5400.0),
        ("0", 0.0),
        ("2s", 2.0),
    ],
)
def test_duration(s, want):
    assert parse_duration(s) == pytest.approx(want)


@pytest.mark.parametrize("s", ["", "1", "1d", "h", "1hh"])
def test_duration_invalid(s):
    with pytest.raises(DurationError):
        parse_duration(s)
