"""Policy-axis partitioner (models/engine.ShardedPolicySet) under churn.

The contract that makes the 2D mesh cheap to run continuously: segment
add/remove/replace must touch exactly one shard — the untouched shards
keep their CompiledPolicySet *instances* and their tensor bytes stay
identical (so cached XLA executables survive) — while the merged verdict
matrix stays bit-identical to the unsharded device lane, and the KT305
partition battery stays clean at every step.
"""

import hashlib
from dataclasses import fields

import numpy as np
import pytest

from kyverno_tpu.analysis import check_policy_shards
from kyverno_tpu.api.load import load_policy
from kyverno_tpu.models.compiler import PolicyTensors, tensor_nbytes
from kyverno_tpu.models.engine import (
    IncrementalCompiler,
    PolicyPartitioner,
    ShardedPolicySet,
    shard_policies,
)


def _policy(name, pattern, n_rules=1):
    rules = [{
        "name": f"r{j}", "match": {"resources": {"kinds": ["Pod"]}},
        "validate": {"message": "m", "pattern": pattern},
    } for j in range(n_rules)]
    return load_policy({
        "apiVersion": "kyverno.io/v1", "kind": "ClusterPolicy",
        "metadata": {"name": name},
        "spec": {"validationFailureAction": "enforce", "rules": rules},
    })


def _pod(i):
    return {"apiVersion": "v1", "kind": "Pod",
            "metadata": {"name": f"pod-{i}", "namespace": "default",
                         "labels": {"idx": str(i)}},
            "spec": {"containers": [{"name": "c",
                                     "image": ("nginx:latest" if i % 3 == 0
                                               else f"nginx:1.{i}")}],
                     "weight": (i * 7) % 160,
                     "grace": f"{(i * 13) % 400}s"}}


def _lib():
    return {
        "no-latest": _policy(
            "no-latest",
            {"spec": {"containers": [{"image": "!*:latest"}]}}),
        "weight-cap": _policy("weight-cap", {"spec": {"weight": "<=100"}}),
        "grace-cap": _policy("grace-cap", {"spec": {"grace": "<1h"}}),
        "named": _policy("named", {"metadata": {"name": "pod-?*"}}),
    }


def _tensor_digest(t: PolicyTensors) -> str:
    h = hashlib.sha256()
    for f in fields(t):
        v = getattr(t, f.name)
        if isinstance(v, np.ndarray):
            h.update(f.name.encode())
            h.update(np.ascontiguousarray(v).tobytes())
    return h.hexdigest()


def _assert_partition_clean(sps):
    diags = check_policy_shards(
        sps.full.tensors,
        [(sh.cps.tensors, sh.col_map) for sh in sps.shards])
    assert not diags, [f"{d.code} {d.component}: {d.message}"
                       for d in diags]


def _assert_device_parity(sps, docs):
    batch = sps.full.flatten(docs)
    got = sps.evaluate_device(batch)
    want = sps.full.evaluate_device(batch)
    assert got.dtype == want.dtype
    np.testing.assert_array_equal(got, want)


class TestPartitionerPlan:
    def test_balances_by_rule_count(self):
        part = PolicyPartitioner(2)
        assign = part.plan([("a", 8), ("b", 1), ("c", 1), ("d", 1),
                            ("e", 1), ("f", 1), ("g", 1), ("h", 1)])
        # the heavy key claims one shard; the light keys pile onto the
        # other until the loads cross
        load = [0, 0]
        for (_, w), s in zip([("a", 8), ("b", 1), ("c", 1), ("d", 1),
                              ("e", 1), ("f", 1), ("g", 1), ("h", 1)],
                             assign):
            load[s] += w
        assert abs(load[0] - load[1]) <= 8

    def test_sticky_across_churn(self):
        part = PolicyPartitioner(3)
        first = part.plan([(k, 2) for k in "abcdef"])
        # removing one key and adding two must not move survivors
        second = part.plan([(k, 2) for k in "abcde"] + [("x", 2), ("y", 2)])
        for key, s in zip("abcde", second):
            assert s == first["abcdef".index(key)]

    def test_dead_keys_free_their_weight(self):
        part = PolicyPartitioner(2)
        part.plan([("a", 10), ("b", 1)])
        # "a" dies; a new heavy key must land on the now-empty shard
        assign = part.plan([("b", 1), ("c", 10)])
        assert assign[0] != assign[1]

    def test_rejects_zero_shards(self):
        with pytest.raises(ValueError):
            PolicyPartitioner(0)


class TestShardedPolicySetChurn:
    def test_add_remove_replace_touch_one_shard(self):
        lib = _lib()
        docs = [_pod(i) for i in range(24)]
        inc = IncrementalCompiler()
        sps = inc.refresh_sharded(list(lib.values()), 2)
        _assert_partition_clean(sps)
        _assert_device_parity(sps, docs)

        def snapshot():
            return {sh.index: (sh.cps, _tensor_digest(sh.cps.tensors))
                    for sh in sps.shards}

        def assert_one_shard_changed(before):
            after = snapshot()
            changed = []
            for idx, (cps_b, dig_b) in before.items():
                if idx not in after:
                    changed.append(idx)
                    continue
                cps_a, dig_a = after[idx]
                if dig_a != dig_b:
                    changed.append(idx)
                else:
                    # untouched shard: same compiled instance, same bytes
                    assert cps_a is cps_b
            changed += [i for i in after if i not in before]
            assert len(set(changed)) <= 1, (
                f"churn touched shards {sorted(set(changed))}")
            assert sps.last_refresh["shards_reassembled"] <= 1

        # REPLACE in place (same key, new object)
        before = snapshot()
        lib["weight-cap"] = _policy("weight-cap",
                                    {"spec": {"weight": "<=90"}})
        sps = inc.refresh_sharded(list(lib.values()), 2, sharded=sps)
        assert_one_shard_changed(before)
        _assert_partition_clean(sps)
        _assert_device_parity(sps, docs)

        # ADD
        before = snapshot()
        lib["team-label"] = _policy(
            "team-label", {"metadata": {"labels": {"idx": "?*"}}})
        sps = inc.refresh_sharded(list(lib.values()), 2, sharded=sps)
        assert_one_shard_changed(before)
        _assert_partition_clean(sps)
        _assert_device_parity(sps, docs)

        # REMOVE
        before = snapshot()
        del lib["grace-cap"]
        sps = inc.refresh_sharded(list(lib.values()), 2, sharded=sps)
        assert_one_shard_changed(before)
        _assert_partition_clean(sps)
        _assert_device_parity(sps, docs)

    def test_col_maps_tile_the_live_rule_axis(self):
        sps = shard_policies(list(_lib().values()), 3)
        cols = np.sort(np.concatenate([sh.col_map for sh in sps.shards]))
        np.testing.assert_array_equal(
            cols, np.arange(sps.full.tensors.n_rules_live))

    def test_evaluate_resolves_host_lane(self):
        lib = _lib()
        lib["self-name"] = _policy(
            "self-name",
            {"metadata": {"name": "{{request.object.metadata.name}}"}})
        policies = list(lib.values())
        sps = shard_policies(policies, 2)
        docs = [_pod(i) for i in range(11)]
        from kyverno_tpu.models import CompiledPolicySet
        want = CompiledPolicySet(policies).evaluate(docs)
        np.testing.assert_array_equal(sps.evaluate(docs), want)

    def test_shard_tensor_bytes_report(self):
        sps = shard_policies(list(_lib().values()), 2, rule_bucket=True)
        full_bytes = tensor_nbytes(sps.full.tensors)
        per_shard = sps.shard_tensor_bytes()
        assert set(per_shard) == {sh.index for sh in sps.shards}
        # each shard holds a strict subset of the rule axis; its
        # footprint must undercut the replicated full set
        assert all(0 < b < full_bytes for b in per_shard.values())

    def test_single_shard_degenerates_to_full_layout(self):
        sps = shard_policies(list(_lib().values()), 1)
        assert len(sps.shards) == 1
        docs = [_pod(i) for i in range(7)]
        _assert_partition_clean(sps)
        _assert_device_parity(sps, docs)
