"""Unit tests for fleet/fabric.py: the CACHE_* frame codec, the hub's
epoch-scoped store (stale-put rejection, LRU bound, purge semantics),
the client's resync protocol, the content-addressed keys, and the
socket transport — all without a serving stack."""

import json
import threading

import pytest

from kyverno_tpu.fleet import fabric
from kyverno_tpu.models import Verdict
from kyverno_tpu.runtime.stream_server import (
    F_CACHE_GET,
    F_CACHE_INVALIDATE,
    F_CACHE_MISS,
    F_CACHE_OK,
    F_CACHE_PUT,
    F_ERROR,
    decode_payload,
    encode_payload,
)


# ------------------------------------------------------------- frame codec

def test_get_frame_round_trip():
    payload = fabric.encode_get(7, "decision", b"some|key")
    ftype, req_id, body = decode_payload(payload)
    assert (ftype, req_id) == (F_CACHE_GET, 7)
    assert fabric.decode_get(body) == ("decision", b"some|key")


def test_put_frame_round_trip():
    payload = fabric.encode_put(9, 42, "flatten", b"k" * 33, b"v" * 100)
    ftype, req_id, body = decode_payload(payload)
    assert (ftype, req_id) == (F_CACHE_PUT, 9)
    assert fabric.decode_put(body) == (42, "flatten", b"k" * 33,
                                       b"v" * 100)


def test_invalidate_frame_round_trip():
    payload = fabric.encode_invalidate(3, "host", b"prefix|")
    ftype, req_id, body = decode_payload(payload)
    assert (ftype, req_id) == (F_CACHE_INVALIDATE, 3)
    assert fabric.decode_invalidate(body) == ("host", b"prefix|")
    # empty tier/prefix = the wildcard purge
    _, _, body = decode_payload(fabric.encode_invalidate(4))
    assert fabric.decode_invalidate(body) == ("", b"")


# --------------------------------------------------------------------- hub

def test_hub_get_put_round_trip():
    hub = fabric.FabricHub()
    epoch, value = hub.get("decision", b"k")
    assert (epoch, value) == (0, None)
    assert hub.put("decision", b"k", b"v", epoch=0) == (0, True)
    assert hub.get("decision", b"k") == (0, b"v")
    assert hub.stats["hits"] == 1 and hub.stats["misses"] == 1


def test_hub_invalidate_purges_and_bumps_epoch():
    hub = fabric.FabricHub()
    hub.put("decision", b"a|1", b"x", epoch=0)
    hub.put("decision", b"b|1", b"y", epoch=0)
    hub.put("host", b"h", b"z", epoch=0)
    epoch, purged = hub.invalidate("decision", b"a|")
    assert (epoch, purged) == (1, 1)
    assert hub.get("decision", b"a|1")[1] is None
    assert hub.get("decision", b"b|1")[1] == b"y"
    # wildcard: every tier, every key
    epoch, purged = hub.invalidate()
    assert (epoch, purged) == (2, 2)
    assert hub.get("host", b"h")[1] is None


def test_hub_rejects_stale_epoch_put():
    """The read-compute-put race: a value computed against pre-churn
    state must not land after the invalidation that purged it."""
    hub = fabric.FabricHub()
    hub.invalidate()                      # epoch -> 1
    assert hub.put("decision", b"k", b"v", epoch=0) == (1, False)
    assert hub.get("decision", b"k")[1] is None
    assert hub.stats["stale_puts"] == 1
    assert hub.put("decision", b"k", b"v", epoch=1) == (1, True)


def test_hub_lru_bound():
    hub = fabric.FabricHub(max_entries_per_tier=4)
    for i in range(8):
        hub.put("flatten", f"k{i}".encode(), b"v", epoch=0)
    snap = hub.snapshot()
    assert snap["entries"]["flatten"] == 4
    assert hub.get("flatten", b"k0")[1] is None    # evicted
    assert hub.get("flatten", b"k7")[1] == b"v"    # retained


def test_hub_frame_errors():
    hub = fabric.FabricHub()
    # unknown frame type in the CACHE range
    ftype, _, body = decode_payload(
        hub.handle_payload(encode_payload(0x3F, 1, b"")))
    assert ftype == F_ERROR and b"unknown fabric frame" in body
    # truncated body (tier length points past the end)
    ftype, _, _ = decode_payload(
        hub.handle_payload(encode_payload(F_CACHE_GET, 2, b"\xff")))
    assert ftype == F_ERROR
    # unknown tier name
    ftype, _, _ = decode_payload(hub.handle_payload(
        fabric.encode_get(3, "no-such-tier", b"k")))
    assert ftype == F_ERROR
    assert hub.stats["errors"] == 3
    # garbage that fails payload decode entirely
    ftype, _, _ = decode_payload(hub.handle_payload(b""))
    assert ftype == F_ERROR


def test_hub_frame_protocol_replies():
    hub = fabric.FabricHub()
    ftype, req_id, _ = decode_payload(
        hub.handle_payload(fabric.encode_get(5, "decision", b"k")))
    assert (ftype, req_id) == (F_CACHE_MISS, 5)
    ftype, _, body = decode_payload(hub.handle_payload(
        fabric.encode_put(6, 0, "decision", b"k", b"v")))
    assert ftype == F_CACHE_OK and body[8] == 1          # stored
    ftype, _, body = decode_payload(
        hub.handle_payload(fabric.encode_get(7, "decision", b"k")))
    assert ftype == F_CACHE_OK and body[8:] == b"v"


# ------------------------------------------------------------------ client

def test_client_round_trip_and_resync():
    hub = fabric.FabricHub()
    c = fabric.FabricClient(hub.handle_payload, name="r0")
    assert c.sync() == 0
    assert c.put("decision", b"k", b"v") is True
    assert c.get("decision", b"k") == b"v"
    # a peer's invalidation makes this client's next put stale once...
    fabric.FabricClient(hub.handle_payload, name="r1").invalidate()
    assert c.put("decision", b"k", b"v2") is False
    assert c.stats["put_rejected"] == 1
    # ...but the rejection reply resynced the epoch: the retry lands
    assert c.put("decision", b"k", b"v2") is True
    assert c.get("decision", b"k") == b"v2"


def test_client_degrades_to_miss_on_transport_failure():
    def broken(payload):
        raise ConnectionError("down")

    c = fabric.FabricClient(broken, name="r0")
    assert c.get("decision", b"k") is None
    assert c.put("decision", b"k", b"v") is False
    assert c.invalidate() == 0
    assert c.stats["errors"] == 3


def test_invalidation_races_concurrent_gets():
    """Epoch invalidation under concurrent get/put traffic: no frame
    errors, counters stay consistent, and the store finishes coherent
    (every surviving entry readable, epoch strictly advanced)."""
    hub = fabric.FabricHub()
    clients = [fabric.FabricClient(hub.handle_payload, name=f"r{i}")
               for i in range(4)]
    for c in clients:
        c.sync()
    stop = threading.Event()
    failures = []

    def churn(c, base):
        try:
            i = 0
            while not stop.is_set():
                key = f"{base}|{i % 16}".encode()
                c.put("decision", key, b"v")
                blob = c.get("decision", key)
                assert blob in (None, b"v")   # purged or intact, never torn
                i += 1
        except Exception as e:       # pragma: no cover - failure path
            failures.append(repr(e))

    def invalidator(c):
        try:
            while not stop.is_set():
                c.invalidate("decision")
        except Exception as e:       # pragma: no cover - failure path
            failures.append(repr(e))

    threads = [threading.Thread(target=churn, args=(c, f"w{i}"))
               for i, c in enumerate(clients[:3])]
    threads.append(threading.Thread(target=invalidator,
                                    args=(clients[3],)))
    for t in threads:
        t.start()
    import time
    time.sleep(0.3)
    stop.set()
    for t in threads:
        t.join(5.0)
    assert not failures
    snap = hub.snapshot()
    assert snap["errors"] == 0
    assert snap["epoch"] == snap["invalidations"]
    assert snap["hits"] + snap["misses"] == snap["gets"]
    stale_seen = sum(c.stats["put_rejected"] for c in clients)
    assert snap["stale_puts"] == stale_seen


# ---------------------------------------------------------------- socket

def test_socket_transport_round_trip():
    hub = fabric.FabricHub()
    server = fabric.FabricSocketServer(hub)
    try:
        a = fabric.FabricClient(
            fabric.SocketTransport(server.host, server.port), name="a")
        b = fabric.FabricClient(
            fabric.SocketTransport(server.host, server.port), name="b")
        a.sync()
        b.sync()
        assert a.put("host", b"k", b"v") is True
        assert b.get("host", b"k") == b"v"      # crossed the wire
        assert b.invalidate("host") == 1
        assert a.get("host", b"k") is None
        a.close()
        b.close()
    finally:
        server.stop()


# ------------------------------------------------------------------- keys

def test_decision_key_canonicalizes_insertion_order():
    from kyverno_tpu.api.load import load_policy
    from kyverno_tpu.runtime.policycache import PolicyCache, PolicyType

    cache = PolicyCache()
    cache.add(load_policy({
        "apiVersion": "kyverno.io/v1", "kind": "ClusterPolicy",
        "metadata": {"name": "p"},
        "spec": {"validationFailureAction": "enforce", "rules": [{
            "name": "r", "match": {"resources": {"kinds": ["Pod"]}},
            "validate": {"message": "m",
                         "pattern": {"spec": {"x": "y"}}}}]},
    }))
    r1 = json.loads('{"a": 1, "b": 2}')
    r2 = json.loads('{"b": 2, "a": 1}')
    k1 = fabric.decision_key(cache, PolicyType.VALIDATE_ENFORCE, "Pod",
                             "ns", r1, {"operation": "CREATE"})
    k2 = fabric.decision_key(cache, PolicyType.VALIDATE_ENFORCE, "Pod",
                             "ns", r2, {"operation": "CREATE"})
    assert k1 == k2 and k1 is not None
    # unkeyable body (non-JSON value) -> None, same rule as local caches
    assert fabric.decision_key(cache, PolicyType.VALIDATE_ENFORCE,
                               "Pod", "ns", {"x": {1, 2}}, None) is None


def test_host_key_requires_digests():
    assert fabric.host_key((None, "rule", b"\x01")) is None
    assert fabric.host_key((b"\x01", "rule", None)) is None
    key = fabric.host_key((b"\x01", "rule", b"\x02"))
    assert key == b"01|rule|02"


# ----------------------------------------------------------- value codecs

def test_decision_codec_round_trip():
    row = [("pol", "rule", Verdict.FAIL, "nope"),
           ("pol2", "r2", Verdict.PASS, "")]
    status, out = fabric.decode_decision(
        fabric.encode_decision("attention", row))
    assert status == "attention"
    assert out == row
    assert isinstance(out[0][2], Verdict)


def test_host_verdict_codec_expires_absolutely():
    blob = fabric.encode_host_verdict(Verdict.PASS, "ok", ttl_s=30.0)
    v, m, remaining = fabric.decode_host_verdict(blob)
    assert (v, m) == (Verdict.PASS, "ok")
    assert 29.0 < remaining <= 30.0
    # published with its window already spent -> reads as expired
    _, _, remaining = fabric.decode_host_verdict(
        fabric.encode_host_verdict(Verdict.FAIL, "x", ttl_s=-1.0))
    assert remaining <= 0


def test_policyset_digest_is_order_and_process_stable():
    from kyverno_tpu.api.load import load_policy

    def mk(name):
        return load_policy({
            "apiVersion": "kyverno.io/v1", "kind": "ClusterPolicy",
            "metadata": {"name": name},
            "spec": {"validationFailureAction": "enforce", "rules": [{
                "name": "r", "match": {"resources": {"kinds": ["Pod"]}},
                "validate": {"message": "m",
                             "pattern": {"spec": {"k": name}}}}]},
        })

    a, b = mk("a"), mk("b")
    assert (fabric.policyset_digest([a, b])
            == fabric.policyset_digest([b, a]))
    assert (fabric.policyset_digest([a])
            != fabric.policyset_digest([a, b]))


def test_fabric_disabled_by_default(monkeypatch):
    monkeypatch.delenv("KTPU_FABRIC", raising=False)
    assert fabric.fabric_enabled() is False
    monkeypatch.setenv("KTPU_FABRIC", "1")
    assert fabric.fabric_enabled() is True
    monkeypatch.setenv("KTPU_FABRIC", "0")
    assert fabric.fabric_enabled() is False


def test_health_snapshot_inventories_live_objects(monkeypatch):
    monkeypatch.setenv("KTPU_FABRIC", "1")
    hub = fabric.FabricHub()
    client = fabric.FabricClient(hub.handle_payload, name="snapper")
    client.sync()
    snap = fabric.health_snapshot()
    assert snap["enabled"] is True
    assert any(c["name"] == "snapper" for c in snap.get("clients", ()))
    assert snap.get("hubs")


@pytest.mark.parametrize("tier", fabric.TIERS)
def test_all_tiers_store_independently(tier):
    hub = fabric.FabricHub()
    hub.put(tier, b"k", b"v", epoch=0)
    for other in fabric.TIERS:
        expected = b"v" if other == tier else None
        assert hub.get(other, b"k")[1] == expected
