"""Unit tests for fleet/scanparts.py: namespace partition stability,
rendezvous assignment under join/leave, per-range digest merge parity
against an unpartitioned scan, and the FleetScanCoordinator lease
protocol (assignment publication, crash takeover)."""

import time

import pytest

from kyverno_tpu.api.load import load_policy
from kyverno_tpu.fleet import scanparts
from kyverno_tpu.runtime import leaderelection as le
from kyverno_tpu.runtime.background import BackgroundScanner
from kyverno_tpu.runtime.client import FakeCluster

POLICY = load_policy({
    "apiVersion": "kyverno.io/v1", "kind": "ClusterPolicy",
    "metadata": {"name": "no-latest"},
    "spec": {"validationFailureAction": "enforce", "rules": [{
        "name": "r", "match": {"resources": {"kinds": ["Pod"]}},
        "validate": {"message": "m",
                     "pattern": {"spec": {"containers": [
                         {"image": "!*:latest"}]}}}}]},
})


def _pods(n, namespaces=6):
    out = []
    for i in range(n):
        tag = "latest" if i % 3 == 0 else f"v{i % 5}"
        out.append({"apiVersion": "v1", "kind": "Pod",
                    "metadata": {"name": f"pod-{i}",
                                 "namespace": f"team-{i % namespaces}"},
                    "spec": {"containers": [
                        {"name": "c", "image": f"nginx:{tag}"}]}})
    return out


# -------------------------------------------------------------- partitions

def test_partition_of_stable_and_in_range():
    for ns in ("", "default", "team-3", "kube-system"):
        p = scanparts.partition_of(ns, 8)
        assert 0 <= p < 8
        assert p == scanparts.partition_of(ns, 8)
    assert scanparts.partition_of("anything", 1) == 0
    assert scanparts.partition_of("anything", 0) == 0


def test_partition_resources_slices_by_owned():
    pods = _pods(30)
    n = 4
    slices = [scanparts.partition_resources(pods, {p}, n)
              for p in range(n)]
    assert sum(len(s) for s in slices) == len(pods)
    seen = {id(r) for s in slices for r in s}
    assert len(seen) == len(pods)        # disjoint, complete
    # cluster-scoped (no namespace) resources land in exactly one slice
    cluster = [{"apiVersion": "v1", "kind": "Namespace",
                "metadata": {"name": "x"}}]
    hits = [p for p in range(n)
            if scanparts.partition_resources(cluster, {p}, n)]
    assert len(hits) == 1


def test_assign_partitions_complete_and_stable_under_leave():
    members = [f"m{i}" for i in range(4)]
    n = 16
    before = scanparts.assign_partitions(members, n)
    assert sorted(p for parts in before.values() for p in parts) \
        == list(range(n))
    after = scanparts.assign_partitions(members[:-1], n)
    assert sorted(p for parts in after.values() for p in parts) \
        == list(range(n))
    # survivors keep every partition they already owned
    for m in members[:-1]:
        assert set(before[m]) <= set(after[m])
    # only the dead member's partitions moved
    moved = {p for m in members[:-1] for p in after[m]
             if p not in before[m]}
    assert moved == set(before["m3"])


def test_assign_partitions_empty_roster():
    assert scanparts.assign_partitions([], 8) == {}


def test_scan_partition_count_env(monkeypatch):
    monkeypatch.delenv("KTPU_SCAN_PARTITIONS", raising=False)
    assert scanparts.scan_partition_count() == 0
    monkeypatch.setenv("KTPU_SCAN_PARTITIONS", "6")
    assert scanparts.scan_partition_count() == 6
    monkeypatch.setenv("KTPU_SCAN_PARTITIONS", "-2")
    assert scanparts.scan_partition_count() == 0


# ----------------------------------------------------------- range digests

def test_merge_range_digests_conflict_raises():
    with pytest.raises(ValueError, match="conflicting"):
        scanparts.merge_range_digests({0: "aaaa"}, {0: "bbbb"})
    # agreement on the same range is fine (overlapping scans)
    assert scanparts.merge_range_digests({0: "aaaa"}, {0: "aaaa"}) \
        == scanparts.merge_range_digests({0: "aaaa"})


def test_partitioned_scan_digest_parity():
    """Three replicas each scanning disjoint owned ranges reproduce an
    unpartitioned scan's verdict matrix digest exactly — the fleet scan
    correctness contract."""
    n = 4
    pods = _pods(24)
    baseline = BackgroundScanner([POLICY])
    baseline.scan(pods)
    want = scanparts.merge_range_digests(
        scanparts.matrix_range_digests(baseline, n))

    assignment = scanparts.assign_partitions(["a", "b", "c"], n)
    digests = []
    for member, owned in assignment.items():
        scanner = BackgroundScanner([POLICY])
        _, d = scanparts.scan_partitions(scanner, pods, owned, n)
        assert set(d) <= set(owned)
        digests.append(d)
    assert scanparts.merge_range_digests(*digests) == want


def test_matrix_range_digests_empty_scanner():
    scanner = BackgroundScanner([POLICY])
    assert scanparts.matrix_range_digests(scanner, 4) == {}


# ------------------------------------------------------------- coordinator

def _settle(coords, rounds=3):
    for _ in range(rounds):
        for c in coords.values():
            c.tick()


def test_coordinator_assignment_and_coverage():
    cluster = FakeCluster()
    coords = {n: scanparts.FleetScanCoordinator(cluster, identity=n,
                                                n_partitions=6)
              for n in ("r0", "r1")}
    try:
        _settle(coords)
        owned = {n: set(c.owned_partitions()) for n, c in coords.items()}
        assert set().union(*owned.values()) == set(range(6))
        assert sum(len(o) for o in owned.values()) == 6
        leaders = [n for n, c in coords.items() if c.elector.is_leader()]
        assert len(leaders) == 1
        snap = coords[leaders[0]].snapshot()
        assert snap["leader"] and snap["assignments_published"] >= 1
        assert snap["assignment"]        # published roster visible
        # the assignment ConfigMap round-trips through the cluster
        cm = cluster.get_configmap("kyverno",
                                   scanparts.ASSIGNMENT_CONFIGMAP)
        assert cm["data"]["partitions"] == "6"
    finally:
        for c in coords.values():
            c.stop()


def test_coordinator_crash_takeover(monkeypatch):
    """A member that stops ticking (crash, no release) loses its member
    lease to expiry; the leader reassigns its ranges and the survivor's
    part-leases take over the expired ones — full coverage restored."""
    monkeypatch.setattr(le, "LEASE_DURATION_S", 0.15)
    monkeypatch.setattr(le, "RENEW_DEADLINE_S", 0.1)
    cluster = FakeCluster()
    coords = {n: scanparts.FleetScanCoordinator(cluster, identity=n,
                                                n_partitions=5)
              for n in ("r0", "r1", "r2")}
    try:
        _settle(coords)
        owned = {n: set(c.owned_partitions()) for n, c in coords.items()}
        assert set().union(*owned.values()) == set(range(5))
        victim = next(n for n, o in owned.items() if o)
        coords.pop(victim)               # crash: no further ticks
        time.sleep(le.LEASE_DURATION_S + 0.05)
        _settle(coords)
        owned2 = {n: set(c.owned_partitions())
                  for n, c in coords.items()}
        assert set().union(*owned2.values()) == set(range(5))
        assert sum(len(o) for o in owned2.values()) == 5
        # the orphaned ranges moved to survivors
        for p in owned[victim]:
            assert any(p in o for o in owned2.values())
    finally:
        for c in coords.values():
            c.stop()


def test_coordinator_snapshots_inventory():
    cluster = FakeCluster()
    c = scanparts.FleetScanCoordinator(cluster, identity="solo",
                                       n_partitions=3)
    try:
        c.tick()
        snaps = scanparts.coordinator_snapshots()
        assert any(s["identity"] == "solo" for s in snaps)
    finally:
        c.stop()
