"""Unit tests for fleet/router.py: rendezvous partition-map stability
under join/leave, breaker state machine, failover on replica failure /
F_ERROR replies, degraded-healthz deprioritization, and pool
exhaustion."""

import hashlib

import pytest

from kyverno_tpu.fleet.router import (
    Replica,
    ReplicaBreaker,
    ReplicaRouter,
    RouterExhausted,
    rendezvous_rank,
)
from kyverno_tpu.runtime.stream_server import (
    F_CACHE_OK,
    F_ERROR,
    encode_payload,
)

OK_REPLY = encode_payload(F_CACHE_OK, 1, b"fine")


def _digests(n):
    return [hashlib.blake2b(str(i).encode(), digest_size=16).digest()
            for i in range(n)]


def _ok_replica(name, log=None):
    def submit(payload):
        if log is not None:
            log.append(name)
        return OK_REPLY
    return Replica(name, submit)


# -------------------------------------------------------------- rendezvous

def test_rendezvous_rank_deterministic_and_total():
    names = [f"r{i}" for i in range(5)]
    d = _digests(1)[0]
    order = rendezvous_rank(names, d)
    assert sorted(order) == sorted(names)
    assert order == rendezvous_rank(list(reversed(names)), d)


def test_partition_map_stability_under_leave():
    """Removing one replica moves ONLY the digests it homed — every
    other digest keeps its winner (the rendezvous property the fabric's
    cache affinity rides on)."""
    names = [f"r{i}" for i in range(5)]
    digests = _digests(300)
    before = {d: rendezvous_rank(names, d)[0] for d in digests}
    survivors = [n for n in names if n != "r2"]
    after = {d: rendezvous_rank(survivors, d)[0] for d in digests}
    moved = [d for d in digests if before[d] != after[d]]
    assert all(before[d] == "r2" for d in moved)
    # and the displaced digests went to their previous runner-up
    for d in moved:
        assert after[d] == rendezvous_rank(names, d)[1]
    # ~1/N of the keyspace moved, not a reshuffle
    assert 0 < len(moved) < len(digests) / 2


def test_partition_map_stability_under_join():
    names = [f"r{i}" for i in range(4)]
    digests = _digests(300)
    before = {d: rendezvous_rank(names, d)[0] for d in digests}
    after = {d: rendezvous_rank(names + ["r-new"], d)[0]
             for d in digests}
    moved = [d for d in digests if before[d] != after[d]]
    assert all(after[d] == "r-new" for d in moved)
    assert 0 < len(moved) < len(digests) / 2


# ----------------------------------------------------------------- breaker

def test_breaker_opens_after_threshold_and_probes_after_cooldown():
    clock = [0.0]
    b = ReplicaBreaker(threshold=3, cooldown_s=1.0,
                       clock=lambda: clock[0])
    for _ in range(2):
        b.record(False)
    assert b.state == "closed" and b.allow()
    b.record(False)
    assert b.state == "open"
    assert not b.allow() and b.stats["rejected"] == 1
    clock[0] = 1.5                       # past cooldown: one probe
    assert b.allow() and b.state == "half_open"
    assert not b.allow()                 # the probe owns the lane
    b.record(True)
    assert b.state == "closed" and b.stats["closed"] == 1


def test_breaker_half_open_failure_reopens():
    clock = [0.0]
    b = ReplicaBreaker(threshold=1, cooldown_s=1.0,
                       clock=lambda: clock[0])
    b.record(False)
    assert b.state == "open"
    clock[0] = 1.0
    assert b.allow()
    b.record(False)                      # probe failed
    assert b.state == "open" and b.stats["opened"] == 2


# ------------------------------------------------------------------ router

def test_submit_routes_to_rendezvous_winner():
    log = []
    router = ReplicaRouter([_ok_replica(f"r{i}", log)
                            for i in range(3)])
    d = _digests(1)[0]
    assert router.submit(d, b"frame") == OK_REPLY
    assert log == [rendezvous_rank(router.members(), d)[0]]
    assert router.stats["routed"] == 1


def test_failover_on_raising_replica():
    log = []

    def die(payload):
        log.append("dead")
        raise ConnectionError("replica down")

    router = ReplicaRouter([Replica("dead", die),
                            _ok_replica("alive", log)],
                           backoff_s=0.0)
    # find a digest homed on the dead replica so failover must engage
    digest = next(d for d in _digests(64)
                  if router.rank(d)[0] == "dead")
    assert router.submit(digest, b"frame") == OK_REPLY
    assert log == ["dead", "alive"]
    assert router.stats["failovers"] == 1
    snap = router.snapshot()
    assert snap["breakers"]["dead"]["failures"] == 1


def test_f_error_reply_counts_as_replica_failure():
    def erroring(payload):
        return encode_payload(F_ERROR, 1, b"shape reject")

    router = ReplicaRouter([Replica("err", erroring),
                            _ok_replica("alive")], backoff_s=0.0)
    digest = next(d for d in _digests(64)
                  if router.rank(d)[0] == "err")
    assert router.submit(digest, b"frame") == OK_REPLY
    assert router.stats["failovers"] == 1


def test_open_breaker_skips_replica_without_submitting():
    calls = []

    def die(payload):
        calls.append(1)
        raise ConnectionError("down")

    router = ReplicaRouter([Replica("dead", die),
                            _ok_replica("alive")],
                           breaker_threshold=1, backoff_s=0.0,
                           breaker_cooldown_s=60.0)
    digest = next(d for d in _digests(64)
                  if router.rank(d)[0] == "dead")
    router.submit(digest, b"f")          # failure opens the breaker
    router.submit(digest, b"f")          # now routed around, no call
    assert len(calls) == 1
    # the open breaker demotes the home replica out of first pick
    assert router.route(digest) == "alive"
    assert router.snapshot()["breakers"]["dead"]["state"] == "open"


def test_degraded_healthz_deprioritizes_home_replica():
    log = []
    degraded = Replica("home", lambda p: (log.append("home"), OK_REPLY)[1],
                       healthz=lambda: {"status": "degraded"})
    healthy = Replica("other", lambda p: (log.append("other"),
                                          OK_REPLY)[1],
                      healthz=lambda: {"status": "ok"})
    router = ReplicaRouter([degraded, healthy], backoff_s=0.0,
                           health_ttl_s=0.0)
    digest = next(d for d in _digests(64)
                  if rendezvous_rank(["home", "other"], d)[0] == "home")
    assert router.submit(digest, b"f") == OK_REPLY
    assert log == ["other"]              # degraded home sorted last
    # route() agrees: the admittable runner-up is the pick
    assert router.route(digest) == "other"


def test_all_degraded_pool_still_answers():
    replica = Replica("only", lambda p: OK_REPLY,
                      healthz=lambda: {"status": "degraded"})
    router = ReplicaRouter([replica], backoff_s=0.0, health_ttl_s=0.0)
    assert router.submit(_digests(1)[0], b"f") == OK_REPLY
    assert router.route(_digests(1)[0]) == "only"


def test_exhaustion_raises():
    def die(payload):
        raise ConnectionError("down")

    router = ReplicaRouter([Replica("a", die), Replica("b", die)],
                           backoff_s=0.0)
    with pytest.raises(RouterExhausted):
        router.submit(_digests(1)[0], b"f")
    assert router.stats["exhausted"] == 1
    with pytest.raises(RouterExhausted):
        ReplicaRouter([]).submit(_digests(1)[0], b"f")


def test_bounded_retries():
    calls = []

    def die(payload):
        calls.append(1)
        raise ConnectionError("down")

    router = ReplicaRouter([Replica(f"r{i}", die) for i in range(5)],
                           retries=1, backoff_s=0.0)
    with pytest.raises(RouterExhausted):
        router.submit(_digests(1)[0], b"f")
    assert len(calls) == 2               # retries+1 attempts, not pool size


def test_membership_add_remove():
    router = ReplicaRouter([_ok_replica("a")])
    router.add(_ok_replica("b"))
    assert router.members() == ["a", "b"]
    router.remove("a")
    assert router.members() == ["b"]
    assert router.submit(_digests(1)[0], b"f") == OK_REPLY
