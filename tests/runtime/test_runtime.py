"""Runtime integration: policy cache, webhook admission flow over HTTP,
reports, events, background scan, generate controller."""

import json
import urllib.request

import pytest

from kyverno_tpu.api.load import load_policies_from_path, load_policy
from kyverno_tpu.runtime.background import BackgroundScanner
from kyverno_tpu.runtime.client import FakeCluster
from kyverno_tpu.runtime.config import ConfigData, parse_kinds
from kyverno_tpu.runtime.events import EventGenerator
from kyverno_tpu.runtime.generate_controller import GR_COMPLETED, GenerateController
from kyverno_tpu.runtime.metrics import MetricsRegistry
from kyverno_tpu.runtime.policycache import PolicyCache, PolicyType
from kyverno_tpu.runtime.reports import ReportGenerator
from kyverno_tpu.runtime.webhook import (
    MUTATING_WEBHOOK_PATH,
    POLICY_VALIDATING_WEBHOOK_PATH,
    VALIDATING_WEBHOOK_PATH,
    WebhookServer,
)

ENFORCE_POLICY = {
    "apiVersion": "kyverno.io/v1",
    "kind": "ClusterPolicy",
    "metadata": {"name": "disallow-latest-tag"},
    "spec": {
        "validationFailureAction": "enforce",
        "rules": [{
            "name": "validate-image-tag",
            "match": {"resources": {"kinds": ["Pod"]}},
            "validate": {
                "message": "latest tag not allowed",
                "pattern": {"spec": {"containers": [{"image": "!*:latest"}]}},
            },
        }],
    },
}

MUTATE_POLICY = {
    "apiVersion": "kyverno.io/v1",
    "kind": "ClusterPolicy",
    "metadata": {"name": "add-labels"},
    "spec": {"rules": [{
        "name": "add-team-label",
        "match": {"resources": {"kinds": ["Pod"]}},
        "mutate": {"patchStrategicMerge": {"metadata": {"labels": {"+(team)": "platform"}}}},
    }]},
}

GENERATE_POLICY = {
    "apiVersion": "kyverno.io/v1",
    "kind": "ClusterPolicy",
    "metadata": {"name": "add-networkpolicy"},
    "spec": {"rules": [{
        "name": "default-deny",
        "match": {"resources": {"kinds": ["Namespace"]}},
        "generate": {
            "apiVersion": "networking.k8s.io/v1",
            "kind": "NetworkPolicy",
            "name": "default-deny",
            "namespace": "{{request.object.metadata.name}}",
            "data": {"spec": {"podSelector": {}}},
        },
    }]},
}


def pod(name="p", image="nginx:latest", namespace="default"):
    return {
        "apiVersion": "v1", "kind": "Pod",
        "metadata": {"name": name, "namespace": namespace},
        "spec": {"containers": [{"name": "c", "image": image}]},
    }


def review(resource, operation="CREATE", namespace="default", uid="u1"):
    return {
        "apiVersion": "admission.k8s.io/v1",
        "kind": "AdmissionReview",
        "request": {
            "uid": uid,
            "kind": {"kind": resource.get("kind", "")},
            "namespace": namespace,
            "operation": operation,
            "object": resource,
            "userInfo": {"username": "alice", "groups": ["system:authenticated"]},
        },
    }


class TestPolicyCache:
    def test_kind_index(self):
        cache = PolicyCache()
        cache.add(load_policy(ENFORCE_POLICY))
        cache.add(load_policy(MUTATE_POLICY))
        assert [p.name for p in cache.get_policies(PolicyType.VALIDATE_ENFORCE, "Pod")] == [
            "disallow-latest-tag"
        ]
        assert cache.get_policies(PolicyType.VALIDATE_AUDIT, "Pod") == []
        assert [p.name for p in cache.get_policies(PolicyType.MUTATE, "Pod")] == ["add-labels"]
        assert cache.get_policies(PolicyType.MUTATE, "Service") == []

    def test_remove(self):
        cache = PolicyCache()
        policy = load_policy(MUTATE_POLICY)
        cache.add(policy)
        cache.remove(policy)
        assert cache.get_policies(PolicyType.MUTATE, "Pod") == []

    def test_namespaced_policy_scoped(self):
        doc = dict(MUTATE_POLICY, kind="Policy")
        doc["metadata"] = {"name": "ns-pol", "namespace": "team-a"}
        cache = PolicyCache()
        cache.add(load_policy(doc))
        assert cache.get_policies(PolicyType.MUTATE, "Pod", "team-a")
        assert cache.get_policies(PolicyType.MUTATE, "Pod", "team-b") == []


class TestWebhookHandlers:
    def make_server(self):
        cache = PolicyCache()
        cache.add(load_policy(ENFORCE_POLICY))
        cache.add(load_policy(MUTATE_POLICY))
        cluster = FakeCluster()
        return WebhookServer(
            policy_cache=cache, config=ConfigData(), client=cluster,
            event_gen=EventGenerator(cluster),
            report_gen=ReportGenerator(), registry=MetricsRegistry(),
        ), cluster

    def test_enforce_blocks(self):
        server, _ = self.make_server()
        out = server.handle(VALIDATING_WEBHOOK_PATH, review(pod()))
        assert out["response"]["allowed"] is False
        assert "latest tag not allowed" in out["response"]["status"]["message"]

    def test_enforce_allows_clean_pod(self):
        server, _ = self.make_server()
        out = server.handle(VALIDATING_WEBHOOK_PATH, review(pod(image="nginx:1.21")))
        assert out["response"]["allowed"] is True

    def test_mutation_patches(self):
        import base64

        server, _ = self.make_server()
        out = server.handle(MUTATING_WEBHOOK_PATH, review(pod(image="nginx:1.21")))
        assert out["response"]["allowed"] is True
        patches = json.loads(base64.b64decode(out["response"]["patch"]))
        assert any("team" in json.dumps(p) for p in patches)

    def test_resource_filter_skips(self):
        server, _ = self.make_server()
        server.config.load({"resourceFilters": "[Pod,default,*]"})
        out = server.handle(VALIDATING_WEBHOOK_PATH, review(pod()))
        assert out["response"]["allowed"] is True  # filtered, not evaluated

    def test_policy_validation_webhook(self):
        server, _ = self.make_server()
        bad = {
            "apiVersion": "kyverno.io/v1", "kind": "ClusterPolicy",
            "metadata": {"name": "bad"},
            "spec": {"rules": [{"name": "r", "match": {"resources": {"kinds": ["Pod"]}}}]},
        }
        out = server.handle(
            POLICY_VALIDATING_WEBHOOK_PATH,
            {"request": {"uid": "u", "object": bad, "operation": "CREATE"}},
        )
        assert out["response"]["allowed"] is False

    def test_generate_request_created(self):
        cache = PolicyCache()
        cache.add(load_policy(GENERATE_POLICY))
        cluster = FakeCluster()
        server = WebhookServer(policy_cache=cache, client=cluster)
        ns = {"apiVersion": "v1", "kind": "Namespace", "metadata": {"name": "team-a"}}
        out = server.handle(VALIDATING_WEBHOOK_PATH, review(ns, namespace=""))
        assert out["response"]["allowed"] is True
        grs = cluster.list_resource("kyverno.io/v1", "GenerateRequest")
        assert len(grs) == 1
        assert grs[0]["spec"]["policy"] == "add-networkpolicy"

    def test_metrics_recorded(self):
        server, _ = self.make_server()
        server.handle(VALIDATING_WEBHOOK_PATH, review(pod()))
        text = server.registry.expose()
        assert "kyverno_policy_results_total" in text
        assert "kyverno_admission_requests_total" in text


class TestWebhookHTTP:
    def test_over_http(self):
        cache = PolicyCache()
        cache.add(load_policy(ENFORCE_POLICY))
        server = WebhookServer(policy_cache=cache)
        httpd = server.run(host="127.0.0.1", port=0)
        port = httpd.server_address[1]
        try:
            body = json.dumps(review(pod())).encode()
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}{VALIDATING_WEBHOOK_PATH}",
                data=body, headers={"Content-Type": "application/json"},
            )
            with urllib.request.urlopen(req, timeout=5) as resp:
                out = json.loads(resp.read())
            assert out["response"]["allowed"] is False

            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/health/liveness", timeout=5
            ) as resp:
                assert resp.status == 200
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=5
            ) as resp:
                assert b"kyverno_admission_requests_total" in resp.read()
        finally:
            server.stop()


class TestAuditAndReports:
    def test_audit_path_feeds_reports(self):
        audit_doc = dict(ENFORCE_POLICY)
        audit_doc = json.loads(json.dumps(audit_doc))
        audit_doc["spec"]["validationFailureAction"] = "audit"
        cache = PolicyCache()
        cache.add(load_policy(audit_doc))
        reports = ReportGenerator()
        server = WebhookServer(policy_cache=cache, report_gen=reports)
        out = server.handle(VALIDATING_WEBHOOK_PATH, review(pod()))
        assert out["response"]["allowed"] is True  # audit never blocks
        server.audit_handler.run()
        server.audit_handler.drain()
        server.audit_handler.stop()
        built = reports.aggregate()
        assert len(built) == 1
        assert built[0]["kind"] == "PolicyReport"
        assert built[0]["summary"]["fail"] == 1

    def test_reports_prune_deleted_policy_and_resource(self):
        """Stored reports are rebuilt from current state: pruning a deleted
        policy/resource removes its rows instead of accumulating forever."""
        from kyverno_tpu.runtime.client import FakeCluster

        cluster = FakeCluster()
        audit_doc = json.loads(json.dumps(ENFORCE_POLICY))
        audit_doc["spec"]["validationFailureAction"] = "audit"
        cache = PolicyCache()
        cache.add(load_policy(audit_doc))
        reports = ReportGenerator(client=cluster)
        server = WebhookServer(policy_cache=cache, report_gen=reports)
        server.audit_handler.run()
        for name in ("p1", "p2"):
            server.handle(VALIDATING_WEBHOOK_PATH, review(pod(name=name)))
        server.audit_handler.drain()
        server.audit_handler.stop()
        built = reports.aggregate()
        assert built[0]["summary"]["fail"] == 2

        reports.prune_resource("Pod", "default", "p1")
        built = reports.aggregate()
        assert built[0]["summary"]["fail"] == 1
        stored = cluster.get_resource(
            "wgpolicyk8s.io/v1alpha2", "PolicyReport", "default",
            "polr-ns-default")
        assert len(stored["results"]) == 1  # replaced, not merged

        reports.prune_policy(audit_doc["metadata"]["name"])
        built = reports.aggregate()
        assert built[0]["summary"]["fail"] == 0
        stored = cluster.get_resource(
            "wgpolicyk8s.io/v1alpha2", "PolicyReport", "default",
            "polr-ns-default")
        assert stored["results"] == []


class TestConfig:
    def test_parse_kinds(self):
        filters = parse_kinds("[Event][*,kube-system,*][Node,,]")
        assert filters[0].kind == "Event"
        assert filters[1].namespace == "kube-system"
        cfg = ConfigData({"resourceFilters": "[Event][*,kube-system,*]"})
        assert cfg.to_filter("Event", "default", "x")
        assert cfg.to_filter("Pod", "kube-system", "x")
        assert not cfg.to_filter("Pod", "default", "x")


class TestBackgroundScan:
    def test_scan_snapshot(self):
        policies = load_policies_from_path("/root/reference/test/best_practices/")
        cluster = FakeCluster([pod(f"p{i}", "nginx:latest" if i % 2 else "nginx:1")
                               for i in range(10)])
        reports = ReportGenerator()
        scanner = BackgroundScanner(policies, client=cluster, report_gen=reports)
        result = scanner.scan()
        assert result.resources_scanned == 10
        # half the pods use :latest; they also violate label/resource rules
        latest_fails = sum(
            1
            for resp in result.responses
            if resp.policy_response.policy.name == "disallow-latest-tag"
            for rr in resp.policy_response.rules
            if rr.name == "validate-image-tag" and rr.status.value == "fail"
        )
        assert latest_fails == 5
        assert result.violations >= 5
        built = reports.aggregate()
        assert built and built[0]["summary"]["fail"] >= 5

    def test_background_false_policies_excluded(self):
        doc = json.loads(json.dumps(ENFORCE_POLICY))
        doc["spec"]["background"] = False
        scanner = BackgroundScanner([load_policy(doc)])
        assert scanner.policies == []


class TestGenerateController:
    def test_process_generate_request(self):
        policy = load_policy(GENERATE_POLICY)
        ns = {"apiVersion": "v1", "kind": "Namespace", "metadata": {"name": "team-a"}}
        cluster = FakeCluster([ns])
        cache = PolicyCache()
        cache.add(policy)
        server = WebhookServer(policy_cache=cache, client=cluster)
        server.handle(VALIDATING_WEBHOOK_PATH, review(ns, namespace=""))

        controller = GenerateController(cluster, {policy.name: policy})
        assert controller.sync_from_cluster() == 1
        controller.run()
        controller.drain()
        controller.stop()

        netpol = cluster.get_resource(
            "networking.k8s.io/v1", "NetworkPolicy", "team-a", "default-deny")
        assert netpol is not None
        assert netpol["metadata"]["labels"]["kyverno.io/generated-by-policy"] == (
            "add-networkpolicy"
        )
        grs = cluster.list_resource("kyverno.io/v1", "GenerateRequest")
        assert grs[0]["status"]["state"] == GR_COMPLETED


class TestMultiReplicaReportFanIn:
    def test_two_replicas_merge_into_one_report(self):
        """The round-5 'done' shape (reportrequest.go CR transport): two
        webhook replicas over ONE cluster persist their audit results as
        ReportChangeRequest CRs; the leader replica's aggregate()
        consumes them into a single merged PolicyReport and deletes the
        consumed requests."""
        from kyverno_tpu.runtime.client import FakeCluster

        cluster = FakeCluster()
        audit_doc = json.loads(json.dumps(ENFORCE_POLICY))
        audit_doc["spec"]["validationFailureAction"] = "audit"

        def replica():
            cache = PolicyCache()
            cache.add(load_policy(audit_doc))
            reports = ReportGenerator(client=cluster)
            server = WebhookServer(policy_cache=cache, client=cluster,
                                   report_gen=reports)
            server.audit_handler.run()
            return server, reports

        r1, leader_reports = replica()
        r2, _follower_reports = replica()
        try:
            # different resources admit through DIFFERENT replicas
            r1.handle(VALIDATING_WEBHOOK_PATH, review(pod(name="from-r1")))
            r2.handle(VALIDATING_WEBHOOK_PATH, review(pod(name="from-r2")))
            r1.audit_handler.drain()
            r2.audit_handler.drain()
            # persistence is async (the admission path never blocks on
            # the API): wait for both replicas' writers
            assert leader_reports.flush()
            assert _follower_reports.flush()

            # both replicas' results exist as RCR CRs on the cluster
            rcrs = cluster.list_resource("kyverno.io/v1alpha2",
                                         "ReportChangeRequest")
            names = {((r.get("results") or [{}])[0].get("resources")
                      or [{}])[0].get("name") for r in rcrs}
            assert names == {"from-r1", "from-r2"}

            # ONLY the leader aggregates: its report carries both rows
            built = leader_reports.aggregate()
            polr = [b for b in built if b["kind"] == "PolicyReport"]
            assert len(polr) == 1
            rows = {((r.get("resources") or [{}])[0].get("name"))
                    for r in polr[0]["results"]}
            assert rows == {"from-r1", "from-r2"}
            assert polr[0]["summary"]["fail"] == 2

            # consumed requests are deleted (reportcontroller.go:682)
            assert cluster.list_resource("kyverno.io/v1alpha2",
                                         "ReportChangeRequest") == []
            # and the merged PolicyReport was written to the cluster
            stored = cluster.get_resource("wgpolicyk8s.io/v1alpha2",
                                          "PolicyReport", "default",
                                          "polr-ns-default")
            assert stored is not None and len(stored["results"]) == 2
        finally:
            r1.audit_handler.stop()
            r2.audit_handler.stop()


class TestReportMergeOrdering:
    def test_local_queued_result_wins_over_own_persisted_cr(self):
        """Same (policy, rule, resource) key from two sources: an
        already-persisted CR (older, e.g. an admission PASS) and a
        locally queued result (newer, e.g. a scan FAIL). The merge is
        last-write-wins, so the fresher local result must apply after
        the cluster-listed CRs — the race behind the flaky lifecycle
        e2e (a scan FAIL vanishing under an admission PASS)."""
        from kyverno_tpu.runtime.client import FakeCluster
        from kyverno_tpu.runtime.reports import ReportGenerator

        cluster = FakeCluster()
        gen = ReportGenerator(client=cluster)

        def rcr(result, ts):
            return {
                "apiVersion": "kyverno.io/v1alpha2",
                "kind": "ReportChangeRequest",
                "metadata": {"name": "rcr-p-pod-x", "namespace": "default"},
                "results": [{
                    "policy": "p", "rule": "r", "result": result,
                    "message": "", "scored": True, "timestampNs": ts,
                    "resources": [{"kind": "Pod", "namespace": "default",
                                   "name": "x"}],
                }],
            }

        # older result persisted as a CR (as the async writer would)
        gen.add_change_request(rcr("pass", ts=100))
        assert gen.flush()
        assert cluster.list_resource("kyverno.io/v1alpha2",
                                     "ReportChangeRequest")
        # fresher result sits in the local queue at aggregate time: STOP
        # the writer first so the queue item deterministically exercises
        # the hold-aside merge (a live writer could persist it and make
        # the test pass through the cluster path regardless)
        gen.stop()
        gen._queue.append(rcr("fail", ts=200))
        built = gen.aggregate()
        rows = [r for rep in built for r in rep.get("results", [])]
        assert [r["result"] for r in rows] == ["fail"]

    def test_freshest_timestamp_wins_regardless_of_order(self):
        """The inverse interleaving: a FRESHER cluster CR must not be
        buried by a staler held-aside local item — merge is by the
        production timestamp, not application order."""
        from kyverno_tpu.runtime.client import FakeCluster
        from kyverno_tpu.runtime.reports import ReportGenerator

        cluster = FakeCluster()
        gen = ReportGenerator(client=cluster)

        def rcr(result, ts):
            return {
                "apiVersion": "kyverno.io/v1alpha2",
                "kind": "ReportChangeRequest",
                "metadata": {"name": "rcr-p-pod-x", "namespace": "default"},
                "results": [{
                    "policy": "p", "rule": "r", "result": result,
                    "message": "", "scored": True, "timestampNs": ts,
                    "resources": [{"kind": "Pod", "namespace": "default",
                                   "name": "x"}],
                }],
            }

        gen.add_change_request(rcr("fail", ts=300))   # fresher, persisted
        assert gen.flush()
        gen.stop()
        gen._queue.append(rcr("pass", ts=100))        # staler, local
        built = gen.aggregate()
        rows = [r for rep in built for r in rep.get("results", [])]
        assert [r["result"] for r in rows] == ["fail"]
        # the internal freshness key never reaches emitted report rows
        assert all("timestampNs" not in r for r in rows)
