"""CanI self-subject-access-review (pkg/auth) and backward-compatibility
migrations (pkg/backward_compatibility)."""

from kyverno_tpu.api.load import load_policy
from kyverno_tpu.runtime.auth import Auth, can_i_generate
from kyverno_tpu.runtime.client import FakeCluster
from kyverno_tpu.runtime.migrations import add_clone_labels, add_gr_labels
from kyverno_tpu.runtime.policycache import PolicyCache
from kyverno_tpu.runtime.webhook import (
    POLICY_VALIDATING_WEBHOOK_PATH,
    WebhookServer,
)

GEN_POLICY = {
    "apiVersion": "kyverno.io/v1", "kind": "ClusterPolicy",
    "metadata": {"name": "gen-np"},
    "spec": {"rules": [{
        "name": "gen-np-r",
        "match": {"resources": {"kinds": ["Namespace"]}},
        "generate": {"apiVersion": "networking.k8s.io/v1",
                     "kind": "NetworkPolicy", "name": "default-deny",
                     "namespace": "{{request.object.metadata.name}}",
                     "data": {"spec": {"podSelector": {}}}},
    }]},
}


class TestCanI:
    def test_allowed_by_default(self):
        auth = Auth(FakeCluster())
        assert auth.can_i_create("NetworkPolicy", "default")
        assert auth.can_i_update("NetworkPolicy", "default")

    def test_denied_verb(self):
        cluster = FakeCluster()
        cluster.deny_access.add(("create", "networkpolicies"))
        auth = Auth(cluster)
        assert not auth.can_i_create("NetworkPolicy", "default")
        assert auth.can_i_update("NetworkPolicy", "default")

    def test_can_i_generate_reports_missing_permission(self):
        cluster = FakeCluster()
        cluster.deny_access.add(("create", "networkpolicies"))
        errors = can_i_generate(load_policy(GEN_POLICY), cluster)
        assert errors and "create" in errors[0]

    def test_policy_webhook_rejects_unexecutable_generate(self):
        cluster = FakeCluster()
        cluster.deny_access.add(("create", "networkpolicies"))
        server = WebhookServer(policy_cache=PolicyCache(), client=cluster)
        out = server.handle(POLICY_VALIDATING_WEBHOOK_PATH, {
            "request": {"uid": "u", "kind": {"kind": "ClusterPolicy"},
                        "operation": "CREATE", "object": GEN_POLICY}})
        assert out["response"]["allowed"] is False
        assert "permission" in out["response"]["status"]["message"]

    def test_policy_webhook_accepts_executable_generate(self):
        server = WebhookServer(policy_cache=PolicyCache(),
                               client=FakeCluster())
        out = server.handle(POLICY_VALIDATING_WEBHOOK_PATH, {
            "request": {"uid": "u", "kind": {"kind": "ClusterPolicy"},
                        "operation": "CREATE", "object": GEN_POLICY}})
        assert out["response"]["allowed"] is True


class TestMigrations:
    def test_gr_labels_added(self):
        cluster = FakeCluster([{
            "apiVersion": "kyverno.io/v1", "kind": "GenerateRequest",
            "metadata": {"name": "gr-1", "namespace": "kyverno"},
            "spec": {"policy": "gen-np",
                     "resource": {"kind": "Namespace", "name": "team-a",
                                  "namespace": ""}},
        }])
        assert add_gr_labels(cluster) == 1
        gr = cluster.get_resource("kyverno.io/v1", "GenerateRequest",
                                  "kyverno", "gr-1")
        labels = gr["metadata"]["labels"]
        assert labels["generate.kyverno.io/policy-name"] == "gen-np"
        assert labels["generate.kyverno.io/resource-kind"] == "Namespace"
        # second run is a no-op
        assert add_gr_labels(cluster) == 0

    def test_clone_source_labeled(self):
        clone_policy = {
            "apiVersion": "kyverno.io/v1", "kind": "ClusterPolicy",
            "metadata": {"name": "clone-secret"},
            "spec": {"rules": [{
                "name": "clone-r",
                "match": {"resources": {"kinds": ["Namespace"]}},
                "generate": {"apiVersion": "v1", "kind": "Secret",
                             "name": "regcred", "namespace": "{{x}}",
                             "clone": {"namespace": "default",
                                       "name": "regcred"}},
            }]},
        }
        cluster = FakeCluster([
            clone_policy,
            {"apiVersion": "v1", "kind": "Secret",
             "metadata": {"name": "regcred", "namespace": "default"}},
        ])
        assert add_clone_labels(cluster) == 1
        src = cluster.get_resource("v1", "Secret", "default", "regcred")
        assert (src["metadata"]["labels"]
                ["generate.kyverno.io/clone-policy-name"] == "clone-secret")
        assert add_clone_labels(cluster) == 0
