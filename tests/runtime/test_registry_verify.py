"""RegistryVerifier against an in-process OCI registry stub.

The stub speaks the Docker Registry HTTP API v2 (manifests, blobs,
optional Bearer token auth) and the tests publish real cosign object
layouts — SimpleSigning payloads with ECDSA-P256 signature annotations
under ``sha256-<hex>.sig`` and DSSE in-toto envelopes under ``.att`` —
so the verifier exercises the exact protocol and crypto a live registry
would (/root/reference/pkg/cosign/cosign.go:30-103), not a mock trust
store. The final class drives the whole stack through the production
webhook: signed image -> digest patch, unsigned image -> block, and a
PolicyReport row either way.
"""

import base64
import hashlib
import json
import threading
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from kyverno_tpu.engine.image_verify import VerificationError
from kyverno_tpu.engine.registry_verify import (
    SIG_ANNOTATION,
    RegistryClient,
    RegistryVerifier,
    dsse_pae,
    parse_image_ref,
)
from kyverno_tpu.utils import ecdsa


class RegistryStub:
    """Docker Registry API v2 stub with cosign publishing helpers."""

    def __init__(self, require_token: bool = False):
        self.manifests = {}   # (repo, ref) -> bytes
        self.blobs = {}       # (repo, digest) -> bytes
        self.require_token = require_token
        self.token = "stub-token-123"
        self.requests = []
        self.httpd = None

    # ---------------------------------------------------------- publish

    def put_blob(self, repo: str, data: bytes) -> str:
        digest = "sha256:" + hashlib.sha256(data).hexdigest()
        self.blobs[(repo, digest)] = data
        return digest

    def put_manifest(self, repo: str, ref: str, manifest: dict) -> str:
        body = json.dumps(manifest).encode()
        digest = "sha256:" + hashlib.sha256(body).hexdigest()
        self.manifests[(repo, ref)] = body
        self.manifests[(repo, digest)] = body
        return digest

    def push_image(self, repo: str, tag: str) -> str:
        cfg = self.put_blob(repo, json.dumps(
            {"architecture": "tpu", "repo": repo, "tag": tag}).encode())
        return self.put_manifest(repo, tag, {
            "schemaVersion": 2, "config": {"digest": cfg}, "layers": [],
            "annotations": {"org.opencontainers.image.ref.name":
                            f"{repo}:{tag}"}})

    def cosign_sign(self, repo: str, digest: str, priv: int,
                    bind_digest: str | None = None) -> None:
        """Publish a cosign signature object for ``digest``."""
        payload = json.dumps({
            "critical": {
                "identity": {"docker-reference": repo},
                "image": {"docker-manifest-digest": bind_digest or digest},
                "type": "cosign container image signature"},
            "optional": None,
        }).encode()
        sig = base64.b64encode(ecdsa.sign(priv, payload)).decode()
        blob_digest = self.put_blob(repo, payload)
        tag = digest.replace("sha256:", "sha256-") + ".sig"
        self.put_manifest(repo, tag, {
            "schemaVersion": 2,
            "layers": [{"digest": blob_digest,
                        "size": len(payload),
                        "annotations": {SIG_ANNOTATION: sig}}]})

    def cosign_attest(self, repo: str, digest: str, priv: int,
                      statement: dict, bind_subject: bool = True) -> None:
        if bind_subject and "subject" not in statement:
            statement = dict(statement, subject=[
                {"name": repo,
                 "digest": {"sha256": digest.split(":", 1)[-1]}}])
        payload = json.dumps(statement).encode()
        ptype = "application/vnd.in-toto+json"
        sig = base64.b64encode(ecdsa.sign(priv, dsse_pae(ptype, payload)))
        envelope = json.dumps({
            "payloadType": ptype,
            "payload": base64.b64encode(payload).decode(),
            "signatures": [{"sig": sig.decode()}],
        }).encode()
        blob_digest = self.put_blob(repo, envelope)
        tag = digest.replace("sha256:", "sha256-") + ".att"
        manifest = json.loads(self.manifests.get(
            (repo, tag), b'{"schemaVersion": 2, "layers": []}'))
        manifest["layers"].append({"digest": blob_digest,
                                   "size": len(envelope)})
        self.put_manifest(repo, tag, manifest)

    # ------------------------------------------------------------ serving

    def start(self) -> str:
        stub = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _reply(self, code, body=b"", headers=()):
                self.send_response(code)
                for k, v in headers:
                    self.send_header(k, v)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                stub.requests.append(self.path)
                if self.path.startswith("/token"):
                    return self._reply(
                        200, json.dumps({"token": stub.token}).encode())
                if stub.require_token and \
                        self.headers.get("Authorization") != \
                        f"Bearer {stub.token}":
                    port = self.server.server_address[1]
                    return self._reply(401, b"{}", [(
                        "WWW-Authenticate",
                        f'Bearer realm="http://127.0.0.1:{port}/token",'
                        f'service="stub",scope="pull"')])
                parts = self.path.split("/")
                # /v2/<repo...>/manifests/<ref> | /v2/<repo...>/blobs/<dg>
                if len(parts) >= 5 and parts[1] == "v2":
                    kind, ref = parts[-2], parts[-1]
                    repo = "/".join(parts[2:-2])
                    if kind == "manifests":
                        body = stub.manifests.get((repo, ref))
                        if body is not None:
                            dg = "sha256:" + hashlib.sha256(body).hexdigest()
                            return self._reply(
                                200, body, [("Docker-Content-Digest", dg)])
                    elif kind == "blobs":
                        body = stub.blobs.get((repo, ref))
                        if body is not None:
                            return self._reply(200, body)
                self._reply(404, b"{}")

        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        threading.Thread(target=self.httpd.serve_forever, daemon=True).start()
        return f"127.0.0.1:{self.httpd.server_address[1]}"

    def stop(self):
        if self.httpd:
            self.httpd.shutdown()
            self.httpd.server_close()


@pytest.fixture()
def stub():
    s = RegistryStub()
    host = s.start()
    yield s, host
    s.stop()


@pytest.fixture(scope="module")
def keypair():
    priv, pub = ecdsa.generate_keypair()
    return priv, ecdsa.public_key_to_pem(pub)


def test_parse_image_ref():
    # official images normalize to the library/ namespace on Docker Hub
    assert parse_image_ref("nginx:1.21") == \
        ("docker.io", "library/nginx", "1.21", "")
    assert parse_image_ref("team/app:v1") == \
        ("docker.io", "team/app", "v1", "")
    assert parse_image_ref("ghcr.io/a/b:v2") == ("ghcr.io", "a/b", "v2", "")
    assert parse_image_ref("localhost:5000/x/y") == \
        ("localhost:5000", "x/y", "latest", "")
    r = parse_image_ref("r.io/a@sha256:" + "0" * 64)
    assert r[0] == "r.io" and r[3].startswith("sha256:")


class TestSignatureVerification:
    def _verifier(self, host):
        return RegistryVerifier(RegistryClient(plain_http=True),
                                default_registry=host)

    def test_signed_image_verifies_and_returns_digest(self, stub, keypair):
        s, host = stub
        priv, pem = keypair
        digest = s.push_image("team/app", "v1")
        s.cosign_sign("team/app", digest, priv)
        out = self._verifier(host).verify_signature(
            f"{host}/team/app:v1", key=pem)
        assert out == digest

    def test_unsigned_image_fails(self, stub, keypair):
        s, host = stub
        _, pem = keypair
        s.push_image("team/app", "v1")
        with pytest.raises(VerificationError, match="no cosign object"):
            self._verifier(host).verify_signature(
                f"{host}/team/app:v1", key=pem)

    def test_wrong_key_fails(self, stub, keypair):
        s, host = stub
        priv, _ = keypair
        other_pem = ecdsa.public_key_to_pem(ecdsa.generate_keypair()[1])
        digest = s.push_image("team/app", "v1")
        s.cosign_sign("team/app", digest, priv)
        with pytest.raises(VerificationError, match="does not match key"):
            self._verifier(host).verify_signature(
                f"{host}/team/app:v1", key=other_pem)

    def test_digest_binding_mismatch_fails(self, stub, keypair):
        """A valid signature over a DIFFERENT digest must not transfer."""
        s, host = stub
        priv, pem = keypair
        digest = s.push_image("team/app", "v1")
        s.cosign_sign("team/app", digest, priv,
                      bind_digest="sha256:" + "ab" * 32)
        with pytest.raises(VerificationError, match="binds"):
            self._verifier(host).verify_signature(
                f"{host}/team/app:v1", key=pem)

    def test_repository_override(self, stub, keypair):
        s, host = stub
        priv, pem = keypair
        digest = s.push_image("team/app", "v1")
        s.cosign_sign("mirror/sigs", digest, priv)
        out = self._verifier(host).verify_signature(
            f"{host}/team/app:v1", key=pem,
            repository=f"{host}/mirror/sigs")
        assert out == digest

    def test_cross_registry_repository_override(self, keypair):
        """Signatures stored on a DIFFERENT registry than the image."""
        priv, pem = keypair
        img_stub, sig_stub = RegistryStub(), RegistryStub()
        img_host, sig_host = img_stub.start(), sig_stub.start()
        try:
            digest = img_stub.push_image("team/app", "v1")
            sig_stub.push_image("sigs/store", "seed")  # repo exists
            sig_stub.cosign_sign("sigs/store", digest, priv)
            out = RegistryVerifier(
                RegistryClient(plain_http=True),
                default_registry=img_host).verify_signature(
                    f"{img_host}/team/app:v1", key=pem,
                    repository=f"{sig_host}/sigs/store")
            assert out == digest
            # the signature fetch went to the OTHER registry
            assert any("sigs/store" in p for p in sig_stub.requests)
        finally:
            img_stub.stop()
            sig_stub.stop()

    def test_verification_cache_skips_network(self, stub, keypair):
        s, host = stub
        priv, pem = keypair
        digest = s.push_image("team/app", "v1")
        s.cosign_sign("team/app", digest, priv)
        v = self._verifier(host)
        assert v.verify_signature(f"{host}/team/app:v1", key=pem) == digest
        before = len(s.requests)
        assert v.verify_signature(f"{host}/team/app:v1", key=pem) == digest
        assert len(s.requests) == before    # served from the TTL cache

    def test_token_auth_flow(self, keypair):
        s = RegistryStub(require_token=True)
        host = s.start()
        try:
            priv, pem = keypair
            digest = s.push_image("team/app", "v1")
            s.cosign_sign("team/app", digest, priv)
            out = RegistryVerifier(
                RegistryClient(plain_http=True),
                default_registry=host).verify_signature(
                    f"{host}/team/app:v1", key=pem)
            assert out == digest
            assert any(p.startswith("/token") for p in s.requests)
        finally:
            s.stop()


class TestAttestations:
    def test_fetch_and_verify_statements(self, stub, keypair):
        s, host = stub
        priv, pem = keypair
        digest = s.push_image("team/app", "v1")
        stmt = {"predicateType": "https://slsa.dev/provenance/v0.2",
                "predicate": {"builder": {"id": "ci"}}}
        s.cosign_attest("team/app", digest, priv, stmt)
        out = RegistryVerifier(
            RegistryClient(plain_http=True),
            default_registry=host).fetch_attestations(
                f"{host}/team/app:v1", key=pem)
        assert len(out) == 1
        assert out[0]["predicate"] == stmt["predicate"]
        assert out[0]["subject"][0]["digest"]["sha256"] == \
            digest.split(":", 1)[-1]

    def test_replayed_attestation_rejected(self, stub, keypair):
        """A key-valid attestation for image A republished under image B's
        .att tag must not verify (subject digest binding)."""
        s, host = stub
        priv, pem = keypair
        digest_a = s.push_image("team/app", "v1")
        digest_b = s.push_image("team/other", "v1")
        stmt = {"predicateType": "t", "predicate": {"ok": True},
                "subject": [{"name": "team/app",
                             "digest": {"sha256":
                                        digest_a.split(":", 1)[-1]}}]}
        # republish A's (validly signed) envelope under B's att tag
        s.cosign_attest("team/other", digest_b, priv, stmt,
                        bind_subject=False)
        with pytest.raises(VerificationError, match="subject does not"):
            RegistryVerifier(
                RegistryClient(plain_http=True),
                default_registry=host).fetch_attestations(
                    f"{host}/team/other:v1", key=pem)

    def test_bad_envelope_signature_fails(self, stub, keypair):
        s, host = stub
        priv, pem = keypair
        other_priv, _ = ecdsa.generate_keypair()
        digest = s.push_image("team/app", "v1")
        s.cosign_attest("team/app", digest, other_priv,
                        {"predicateType": "t", "predicate": {}})
        with pytest.raises(VerificationError, match="attestation signature"):
            RegistryVerifier(
                RegistryClient(plain_http=True),
                default_registry=host).fetch_attestations(
                    f"{host}/team/app:v1", key=pem)


class TestWebhookE2E:
    """The VERDICT 'done' shape: registry stub + signed/unsigned image
    -> digest patch vs block through the production HTTP webhook, and a
    PolicyReport row either way."""

    def _policy(self, host, pem):
        return {
            "apiVersion": "kyverno.io/v1", "kind": "ClusterPolicy",
            "metadata": {"name": "verify-app-images"},
            "spec": {
                "validationFailureAction": "enforce",
                "rules": [{
                    "name": "check-sig",
                    "match": {"resources": {"kinds": ["Pod"]}},
                    "verifyImages": [{
                        "image": f"{host}/team/*",
                        "key": pem,
                    }],
                }],
            },
        }

    def _post(self, port, resource):
        review = {
            "apiVersion": "admission.k8s.io/v1", "kind": "AdmissionReview",
            "request": {"uid": "u1", "kind": {"kind": "Pod"},
                        "namespace": "default", "operation": "CREATE",
                        "object": resource}}
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/mutate",
            data=json.dumps(review).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=30) as resp:
            return json.loads(resp.read())

    def test_signed_patches_unsigned_blocks_and_reports(self, stub, keypair):
        from kyverno_tpu.runtime.client import FakeCluster
        from kyverno_tpu.server import Controller

        s, host = stub
        priv, pem = keypair
        digest = s.push_image("team/app", "v1")
        s.cosign_sign("team/app", digest, priv)
        s.push_image("team/rogue", "v1")     # unsigned

        cluster = FakeCluster([self._policy(host, pem)])
        controller = Controller(
            client=cluster, serve_port=0,
            image_verifier=RegistryVerifier(RegistryClient(plain_http=True),
                                            default_registry=host))
        controller.start(host="127.0.0.1")
        try:
            port = controller._httpd.server_address[1]

            def pod(name, image):
                return {"apiVersion": "v1", "kind": "Pod",
                        "metadata": {"name": name, "namespace": "default"},
                        "spec": {"containers": [
                            {"name": "c", "image": image}]}}

            good = self._post(port, pod("good", f"{host}/team/app:v1"))
            assert good["response"]["allowed"] is True
            patch = json.loads(base64.b64decode(
                good["response"]["patch"]))
            assert any(p["value"].endswith("@" + digest) for p in patch)

            bad = self._post(port, pod("bad", f"{host}/team/rogue:v1"))
            assert bad["response"]["allowed"] is False
            assert "image verification failed" in \
                bad["response"]["status"]["message"]

            # PolicyReport rows for both outcomes
            reports = controller.report_gen.aggregate()
            results = [r for rep in reports
                       for r in rep.get("results", [])
                       if rep.get("kind", "").endswith("PolicyReport")
                       and r.get("policy") == "verify-app-images"]
            statuses = {r.get("result") or r.get("status") for r in results}
            assert "pass" in statuses and "fail" in statuses
        finally:
            controller.stop()


# ----------------------------------------------------- cert-chain path

def _ca_chain(leaf_san="dev@example.com", leaf_days=365):
    """root CA -> intermediate CA -> leaf (all ECDSA P-256), the Fulcio
    shape cosign attaches to keyless signatures."""
    import datetime

    from cryptography import x509
    from cryptography.hazmat.primitives import hashes
    from cryptography.hazmat.primitives.asymmetric import ec
    from cryptography.x509.oid import NameOID

    now = datetime.datetime.now(datetime.timezone.utc)

    def name(cn):
        return x509.Name([x509.NameAttribute(NameOID.COMMON_NAME, cn)])

    def build(cn, issuer_name, issuer_key, pub, ca, san=None, days=365):
        b = (x509.CertificateBuilder()
             .subject_name(name(cn))
             .issuer_name(issuer_name)
             .public_key(pub)
             .serial_number(x509.random_serial_number())
             .not_valid_before(now - datetime.timedelta(days=2))
             .not_valid_after(now + datetime.timedelta(days=days))
             .add_extension(
                 x509.BasicConstraints(ca=ca, path_length=None),
                 critical=True))
        if san:
            b = b.add_extension(
                x509.SubjectAlternativeName([x509.RFC822Name(san)]),
                critical=False)
        return b.sign(issuer_key, hashes.SHA256())

    root_key = ec.generate_private_key(ec.SECP256R1())
    root = build("test-root", name("test-root"), root_key,
                 root_key.public_key(), ca=True)
    int_key = ec.generate_private_key(ec.SECP256R1())
    inter = build("test-int", root.subject, root_key,
                  int_key.public_key(), ca=True)
    leaf_key = ec.generate_private_key(ec.SECP256R1())
    leaf = build("signer", inter.subject, int_key, leaf_key.public_key(),
                 ca=False, san=leaf_san, days=leaf_days)
    return root, inter, leaf, leaf_key


def _pem(*certs) -> str:
    from cryptography.hazmat.primitives import serialization

    return "".join(
        c.public_bytes(serialization.Encoding.PEM).decode() for c in certs)


def _cosign_sign_cert(stub, repo, digest, leaf_key, leaf, chain,
                      bind_digest=None):
    """Publish a keyless-style signature: cert + chain annotations."""
    from cryptography.hazmat.primitives import hashes as _h
    from cryptography.hazmat.primitives.asymmetric import ec as _ec

    from kyverno_tpu.engine.certchain import CERT_ANNOTATION, CHAIN_ANNOTATION

    payload = json.dumps({
        "critical": {
            "identity": {"docker-reference": repo},
            "image": {"docker-manifest-digest": bind_digest or digest},
            "type": "cosign container image signature"},
        "optional": None,
    }).encode()
    sig = base64.b64encode(
        leaf_key.sign(payload, _ec.ECDSA(_h.SHA256()))).decode()
    blob_digest = stub.put_blob(repo, payload)
    tag = digest.replace("sha256:", "sha256-") + ".sig"
    stub.put_manifest(repo, tag, {
        "schemaVersion": 2,
        "layers": [{"digest": blob_digest, "size": len(payload),
                    "annotations": {SIG_ANNOTATION: sig,
                                    CERT_ANNOTATION: _pem(leaf),
                                    CHAIN_ANNOTATION: _pem(*chain)
                                    if isinstance(chain, (list, tuple))
                                    else _pem(chain)}}]})


class TestCertChainVerification:
    def _verifier(self, host):
        return RegistryVerifier(RegistryClient(plain_http=True),
                                default_registry=host)

    def test_cert_chain_signed_image_verifies(self, stub):
        s, host = stub
        root, inter, leaf, leaf_key = _ca_chain()
        digest = s.push_image("team/app", "v1")
        _cosign_sign_cert(s, "team/app", digest, leaf_key, leaf, inter)
        out = self._verifier(host).verify_signature(
            "team/app:v1", roots=_pem(root), subject="dev@example.com")
        assert out == digest

    def test_subject_wildcard_matches(self, stub):
        s, host = stub
        root, inter, leaf, leaf_key = _ca_chain()
        digest = s.push_image("team/app", "v1")
        _cosign_sign_cert(s, "team/app", digest, leaf_key, leaf, inter)
        out = self._verifier(host).verify_signature(
            "team/app:v1", roots=_pem(root), subject="*@example.com")
        assert out == digest

    def test_wrong_subject_rejected(self, stub):
        s, host = stub
        root, inter, leaf, leaf_key = _ca_chain()
        digest = s.push_image("team/app", "v1")
        _cosign_sign_cert(s, "team/app", digest, leaf_key, leaf, inter)
        with pytest.raises(VerificationError, match="does not match subject"):
            self._verifier(host).verify_signature(
                "team/app:v1", roots=_pem(root), subject="ops@example.com")

    def test_untrusted_root_rejected(self, stub):
        s, host = stub
        root, inter, leaf, leaf_key = _ca_chain()
        other_root, *_ = _ca_chain()
        digest = s.push_image("team/app", "v1")
        _cosign_sign_cert(s, "team/app", digest, leaf_key, leaf, inter)
        with pytest.raises(VerificationError,
                           match="does not terminate at a trusted root"):
            self._verifier(host).verify_signature(
                "team/app:v1", roots=_pem(other_root),
                subject="dev@example.com")

    def test_expired_leaf_rejected(self, stub):
        s, host = stub
        # leaf validity window fully in the past
        root, inter, leaf, leaf_key = _ca_chain(leaf_days=-1)
        digest = s.push_image("team/app", "v1")
        _cosign_sign_cert(s, "team/app", digest, leaf_key, leaf, inter)
        with pytest.raises(VerificationError, match="validity window"):
            self._verifier(host).verify_signature(
                "team/app:v1", roots=_pem(root), subject="dev@example.com")

    def test_wrong_key_signature_rejected(self, stub):
        # the chain is valid but the payload was signed by ANOTHER key
        from cryptography.hazmat.primitives.asymmetric import ec as _ec

        s, host = stub
        root, inter, leaf, _ = _ca_chain()
        rogue = _ec.generate_private_key(_ec.SECP256R1())
        digest = s.push_image("team/app", "v1")
        _cosign_sign_cert(s, "team/app", digest, rogue, leaf, inter)
        with pytest.raises(VerificationError,
                           match="does not match certificate key"):
            self._verifier(host).verify_signature(
                "team/app:v1", roots=_pem(root), subject="dev@example.com")

    def test_no_cert_on_layer_rejected(self, stub, keypair):
        # a plain key-signed layer offers no certificate for the chain path
        s, host = stub
        priv, _ = keypair
        root, *_ = _ca_chain()
        digest = s.push_image("team/app", "v1")
        s.cosign_sign("team/app", digest, priv)
        with pytest.raises(VerificationError, match="no certificate"):
            self._verifier(host).verify_signature(
                "team/app:v1", roots=_pem(root), subject="dev@example.com")

    def test_neither_key_nor_roots_rejected(self, stub):
        s, host = stub
        s.push_image("team/app", "v1")
        with pytest.raises(VerificationError, match="public key or trust"):
            self._verifier(host).verify_signature("team/app:v1")

    def test_tampered_payload_digest_binding(self, stub):
        # valid chain + valid signature over a payload binding a DIFFERENT
        # digest: must be rejected (replay of another image's signature)
        s, host = stub
        root, inter, leaf, leaf_key = _ca_chain()
        digest = s.push_image("team/app", "v1")
        _cosign_sign_cert(s, "team/app", digest, leaf_key, leaf, inter,
                          bind_digest="sha256:" + "0" * 64)
        with pytest.raises(VerificationError, match="binds"):
            self._verifier(host).verify_signature(
                "team/app:v1", roots=_pem(root), subject="dev@example.com")


class TestWebhookE2ECertChain:
    """Policy-level keyless shape: verifyImages with roots/subject instead
    of a key, through the production controller HTTP path."""

    def test_roots_policy_verifies_and_wrong_subject_blocks(self, stub):
        from kyverno_tpu.runtime.client import FakeCluster
        from kyverno_tpu.server import Controller

        s, host = stub
        root, inter, leaf, leaf_key = _ca_chain()
        digest = s.push_image("team/app", "v1")
        _cosign_sign_cert(s, "team/app", digest, leaf_key, leaf, inter)

        def policy(subject):
            return {
                "apiVersion": "kyverno.io/v1", "kind": "ClusterPolicy",
                "metadata": {"name": "verify-keyless"},
                "spec": {
                    "validationFailureAction": "enforce",
                    "rules": [{
                        "name": "check-cert",
                        "match": {"resources": {"kinds": ["Pod"]}},
                        "verifyImages": [{
                            "image": f"{host}/team/*",
                            "roots": _pem(root),
                            "subject": subject,
                        }],
                    }],
                },
            }

        def run(subject):
            cluster = FakeCluster([policy(subject)])
            controller = Controller(
                client=cluster, serve_port=0,
                image_verifier=RegistryVerifier(
                    RegistryClient(plain_http=True), default_registry=host))
            controller.start(host="127.0.0.1")
            try:
                port = controller._httpd.server_address[1]
                review = {
                    "apiVersion": "admission.k8s.io/v1",
                    "kind": "AdmissionReview",
                    "request": {"uid": "u1", "kind": {"kind": "Pod"},
                                "namespace": "default",
                                "operation": "CREATE",
                                "object": {
                                    "apiVersion": "v1", "kind": "Pod",
                                    "metadata": {"name": "p",
                                                 "namespace": "default"},
                                    "spec": {"containers": [{
                                        "name": "c",
                                        "image": f"{host}/team/app:v1"}]}}}}
                req = urllib.request.Request(
                    f"http://127.0.0.1:{port}/mutate",
                    data=json.dumps(review).encode(),
                    headers={"Content-Type": "application/json"})
                with urllib.request.urlopen(req, timeout=30) as resp:
                    return json.loads(resp.read())
            finally:
                controller.stop()

        good = run("dev@example.com")
        assert good["response"]["allowed"] is True
        patch = json.loads(base64.b64decode(good["response"]["patch"]))
        assert any(p["value"].endswith("@" + digest) for p in patch)

        bad = run("ops@example.com")
        assert bad["response"]["allowed"] is False
        assert "image verification failed" in \
            bad["response"]["status"]["message"]


class TestCertChainHardening:
    """The trust model's sharp edges: a non-CA cert must never act as an
    issuer, and an unvalidated CN must never satisfy the subject check
    when SANs exist."""

    def _verifier(self, host):
        return RegistryVerifier(RegistryClient(plain_http=True),
                                default_registry=host)

    def test_leaf_cannot_mint_identities(self, stub):
        # attacker holds a legitimate NON-CA leaf under the trusted root
        # and uses its key to issue a rogue cert claiming dev@example.com
        import datetime

        from cryptography import x509
        from cryptography.hazmat.primitives import hashes
        from cryptography.hazmat.primitives.asymmetric import ec
        from cryptography.x509.oid import NameOID

        s, host = stub
        root, inter, atk_leaf, atk_key = _ca_chain(
            leaf_san="attacker@example.com")
        now = datetime.datetime.now(datetime.timezone.utc)
        rogue_key = ec.generate_private_key(ec.SECP256R1())
        rogue = (x509.CertificateBuilder()
                 .subject_name(x509.Name([x509.NameAttribute(
                     NameOID.COMMON_NAME, "rogue")]))
                 .issuer_name(atk_leaf.subject)
                 .public_key(rogue_key.public_key())
                 .serial_number(x509.random_serial_number())
                 .not_valid_before(now - datetime.timedelta(days=1))
                 .not_valid_after(now + datetime.timedelta(days=30))
                 .add_extension(x509.SubjectAlternativeName(
                     [x509.RFC822Name("dev@example.com")]), critical=False)
                 .sign(atk_key, hashes.SHA256()))
        digest = s.push_image("team/app", "v1")
        _cosign_sign_cert(s, "team/app", digest, rogue_key, rogue,
                          [atk_leaf, inter])
        with pytest.raises(VerificationError,
                           match="does not terminate at a trusted root"):
            self._verifier(host).verify_signature(
                "team/app:v1", roots=_pem(root), subject="dev@example.com")

    def test_cn_never_matches_when_sans_present(self, stub):
        # cert with SAN attacker@evil.io but CN dev@example.com: the CN
        # is unvalidated by CAs and must not satisfy the subject check
        from kyverno_tpu.engine import certchain

        _, _, leaf, _ = _ca_chain(leaf_san="attacker@evil.io")
        # the builder sets CN "signer"; assert SAN-present semantics via
        # cert_subjects directly (CN excluded when SANs exist)
        assert certchain.cert_subjects(leaf) == ["attacker@evil.io"]
        assert not certchain.subject_matches(leaf, "signer")


class TestKeylessAttestations:
    def test_cert_chain_attestation_verifies(self, stub):
        from cryptography.hazmat.primitives import hashes as _h
        from cryptography.hazmat.primitives.asymmetric import ec as _ec

        from kyverno_tpu.engine.certchain import (
            CERT_ANNOTATION,
            CHAIN_ANNOTATION,
        )

        s, host = stub
        root, inter, leaf, leaf_key = _ca_chain()
        digest = s.push_image("team/app", "v1")
        statement = {"predicateType": "https://slsa.dev/provenance/v1",
                     "predicate": {"builder": {"id": "ci"}},
                     "subject": [{"name": "team/app",
                                  "digest": {"sha256":
                                             digest.split(":", 1)[-1]}}]}
        payload = json.dumps(statement).encode()
        ptype = "application/vnd.in-toto+json"
        sig = base64.b64encode(leaf_key.sign(
            dsse_pae(ptype, payload), _ec.ECDSA(_h.SHA256()))).decode()
        envelope = json.dumps({
            "payloadType": ptype,
            "payload": base64.b64encode(payload).decode(),
            "signatures": [{"sig": sig}],
        }).encode()
        blob_digest = s.put_blob("team/app", envelope)
        tag = digest.replace("sha256:", "sha256-") + ".att"
        s.put_manifest("team/app", tag, {
            "schemaVersion": 2,
            "layers": [{"digest": blob_digest, "size": len(envelope),
                        "annotations": {CERT_ANNOTATION: _pem(leaf),
                                        CHAIN_ANNOTATION: _pem(inter)}}]})
        v = RegistryVerifier(RegistryClient(plain_http=True),
                             default_registry=host)
        out = v.fetch_attestations("team/app:v1", roots=_pem(root),
                                   subject="dev@example.com")
        assert out and out[0]["predicateType"].startswith("https://slsa")
        # wrong subject: rejected
        with pytest.raises(VerificationError):
            RegistryVerifier(RegistryClient(plain_http=True),
                             default_registry=host).fetch_attestations(
                "team/app:v1", roots=_pem(root), subject="ops@example.com")
