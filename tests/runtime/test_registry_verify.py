"""RegistryVerifier against an in-process OCI registry stub.

The stub speaks the Docker Registry HTTP API v2 (manifests, blobs,
optional Bearer token auth) and the tests publish real cosign object
layouts — SimpleSigning payloads with ECDSA-P256 signature annotations
under ``sha256-<hex>.sig`` and DSSE in-toto envelopes under ``.att`` —
so the verifier exercises the exact protocol and crypto a live registry
would (/root/reference/pkg/cosign/cosign.go:30-103), not a mock trust
store. The final class drives the whole stack through the production
webhook: signed image -> digest patch, unsigned image -> block, and a
PolicyReport row either way.
"""

import base64
import hashlib
import json
import threading
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from kyverno_tpu.engine.image_verify import VerificationError
from kyverno_tpu.engine.registry_verify import (
    SIG_ANNOTATION,
    RegistryClient,
    RegistryVerifier,
    dsse_pae,
    parse_image_ref,
)
from kyverno_tpu.utils import ecdsa


class RegistryStub:
    """Docker Registry API v2 stub with cosign publishing helpers."""

    def __init__(self, require_token: bool = False):
        self.manifests = {}   # (repo, ref) -> bytes
        self.blobs = {}       # (repo, digest) -> bytes
        self.require_token = require_token
        self.token = "stub-token-123"
        self.requests = []
        self.httpd = None

    # ---------------------------------------------------------- publish

    def put_blob(self, repo: str, data: bytes) -> str:
        digest = "sha256:" + hashlib.sha256(data).hexdigest()
        self.blobs[(repo, digest)] = data
        return digest

    def put_manifest(self, repo: str, ref: str, manifest: dict) -> str:
        body = json.dumps(manifest).encode()
        digest = "sha256:" + hashlib.sha256(body).hexdigest()
        self.manifests[(repo, ref)] = body
        self.manifests[(repo, digest)] = body
        return digest

    def push_image(self, repo: str, tag: str) -> str:
        cfg = self.put_blob(repo, json.dumps(
            {"architecture": "tpu", "repo": repo, "tag": tag}).encode())
        return self.put_manifest(repo, tag, {
            "schemaVersion": 2, "config": {"digest": cfg}, "layers": [],
            "annotations": {"org.opencontainers.image.ref.name":
                            f"{repo}:{tag}"}})

    def cosign_sign(self, repo: str, digest: str, priv: int,
                    bind_digest: str | None = None) -> None:
        """Publish a cosign signature object for ``digest``."""
        payload = json.dumps({
            "critical": {
                "identity": {"docker-reference": repo},
                "image": {"docker-manifest-digest": bind_digest or digest},
                "type": "cosign container image signature"},
            "optional": None,
        }).encode()
        sig = base64.b64encode(ecdsa.sign(priv, payload)).decode()
        blob_digest = self.put_blob(repo, payload)
        tag = digest.replace("sha256:", "sha256-") + ".sig"
        self.put_manifest(repo, tag, {
            "schemaVersion": 2,
            "layers": [{"digest": blob_digest,
                        "size": len(payload),
                        "annotations": {SIG_ANNOTATION: sig}}]})

    def cosign_attest(self, repo: str, digest: str, priv: int,
                      statement: dict, bind_subject: bool = True) -> None:
        if bind_subject and "subject" not in statement:
            statement = dict(statement, subject=[
                {"name": repo,
                 "digest": {"sha256": digest.split(":", 1)[-1]}}])
        payload = json.dumps(statement).encode()
        ptype = "application/vnd.in-toto+json"
        sig = base64.b64encode(ecdsa.sign(priv, dsse_pae(ptype, payload)))
        envelope = json.dumps({
            "payloadType": ptype,
            "payload": base64.b64encode(payload).decode(),
            "signatures": [{"sig": sig.decode()}],
        }).encode()
        blob_digest = self.put_blob(repo, envelope)
        tag = digest.replace("sha256:", "sha256-") + ".att"
        manifest = json.loads(self.manifests.get(
            (repo, tag), b'{"schemaVersion": 2, "layers": []}'))
        manifest["layers"].append({"digest": blob_digest,
                                   "size": len(envelope)})
        self.put_manifest(repo, tag, manifest)

    # ------------------------------------------------------------ serving

    def start(self) -> str:
        stub = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _reply(self, code, body=b"", headers=()):
                self.send_response(code)
                for k, v in headers:
                    self.send_header(k, v)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                stub.requests.append(self.path)
                if self.path.startswith("/token"):
                    return self._reply(
                        200, json.dumps({"token": stub.token}).encode())
                if stub.require_token and \
                        self.headers.get("Authorization") != \
                        f"Bearer {stub.token}":
                    port = self.server.server_address[1]
                    return self._reply(401, b"{}", [(
                        "WWW-Authenticate",
                        f'Bearer realm="http://127.0.0.1:{port}/token",'
                        f'service="stub",scope="pull"')])
                parts = self.path.split("/")
                # /v2/<repo...>/manifests/<ref> | /v2/<repo...>/blobs/<dg>
                if len(parts) >= 5 and parts[1] == "v2":
                    kind, ref = parts[-2], parts[-1]
                    repo = "/".join(parts[2:-2])
                    if kind == "manifests":
                        body = stub.manifests.get((repo, ref))
                        if body is not None:
                            dg = "sha256:" + hashlib.sha256(body).hexdigest()
                            return self._reply(
                                200, body, [("Docker-Content-Digest", dg)])
                    elif kind == "blobs":
                        body = stub.blobs.get((repo, ref))
                        if body is not None:
                            return self._reply(200, body)
                self._reply(404, b"{}")

        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        threading.Thread(target=self.httpd.serve_forever, daemon=True).start()
        return f"127.0.0.1:{self.httpd.server_address[1]}"

    def stop(self):
        if self.httpd:
            self.httpd.shutdown()
            self.httpd.server_close()


@pytest.fixture()
def stub():
    s = RegistryStub()
    host = s.start()
    yield s, host
    s.stop()


@pytest.fixture(scope="module")
def keypair():
    priv, pub = ecdsa.generate_keypair()
    return priv, ecdsa.public_key_to_pem(pub)


def test_parse_image_ref():
    # official images normalize to the library/ namespace on Docker Hub
    assert parse_image_ref("nginx:1.21") == \
        ("docker.io", "library/nginx", "1.21", "")
    assert parse_image_ref("team/app:v1") == \
        ("docker.io", "team/app", "v1", "")
    assert parse_image_ref("ghcr.io/a/b:v2") == ("ghcr.io", "a/b", "v2", "")
    assert parse_image_ref("localhost:5000/x/y") == \
        ("localhost:5000", "x/y", "latest", "")
    r = parse_image_ref("r.io/a@sha256:" + "0" * 64)
    assert r[0] == "r.io" and r[3].startswith("sha256:")


class TestSignatureVerification:
    def _verifier(self, host):
        return RegistryVerifier(RegistryClient(plain_http=True),
                                default_registry=host)

    def test_signed_image_verifies_and_returns_digest(self, stub, keypair):
        s, host = stub
        priv, pem = keypair
        digest = s.push_image("team/app", "v1")
        s.cosign_sign("team/app", digest, priv)
        out = self._verifier(host).verify_signature(
            f"{host}/team/app:v1", key=pem)
        assert out == digest

    def test_unsigned_image_fails(self, stub, keypair):
        s, host = stub
        _, pem = keypair
        s.push_image("team/app", "v1")
        with pytest.raises(VerificationError, match="no cosign object"):
            self._verifier(host).verify_signature(
                f"{host}/team/app:v1", key=pem)

    def test_wrong_key_fails(self, stub, keypair):
        s, host = stub
        priv, _ = keypair
        other_pem = ecdsa.public_key_to_pem(ecdsa.generate_keypair()[1])
        digest = s.push_image("team/app", "v1")
        s.cosign_sign("team/app", digest, priv)
        with pytest.raises(VerificationError, match="does not match key"):
            self._verifier(host).verify_signature(
                f"{host}/team/app:v1", key=other_pem)

    def test_digest_binding_mismatch_fails(self, stub, keypair):
        """A valid signature over a DIFFERENT digest must not transfer."""
        s, host = stub
        priv, pem = keypair
        digest = s.push_image("team/app", "v1")
        s.cosign_sign("team/app", digest, priv,
                      bind_digest="sha256:" + "ab" * 32)
        with pytest.raises(VerificationError, match="binds"):
            self._verifier(host).verify_signature(
                f"{host}/team/app:v1", key=pem)

    def test_repository_override(self, stub, keypair):
        s, host = stub
        priv, pem = keypair
        digest = s.push_image("team/app", "v1")
        s.cosign_sign("mirror/sigs", digest, priv)
        out = self._verifier(host).verify_signature(
            f"{host}/team/app:v1", key=pem,
            repository=f"{host}/mirror/sigs")
        assert out == digest

    def test_cross_registry_repository_override(self, keypair):
        """Signatures stored on a DIFFERENT registry than the image."""
        priv, pem = keypair
        img_stub, sig_stub = RegistryStub(), RegistryStub()
        img_host, sig_host = img_stub.start(), sig_stub.start()
        try:
            digest = img_stub.push_image("team/app", "v1")
            sig_stub.push_image("sigs/store", "seed")  # repo exists
            sig_stub.cosign_sign("sigs/store", digest, priv)
            out = RegistryVerifier(
                RegistryClient(plain_http=True),
                default_registry=img_host).verify_signature(
                    f"{img_host}/team/app:v1", key=pem,
                    repository=f"{sig_host}/sigs/store")
            assert out == digest
            # the signature fetch went to the OTHER registry
            assert any("sigs/store" in p for p in sig_stub.requests)
        finally:
            img_stub.stop()
            sig_stub.stop()

    def test_verification_cache_skips_network(self, stub, keypair):
        s, host = stub
        priv, pem = keypair
        digest = s.push_image("team/app", "v1")
        s.cosign_sign("team/app", digest, priv)
        v = self._verifier(host)
        assert v.verify_signature(f"{host}/team/app:v1", key=pem) == digest
        before = len(s.requests)
        assert v.verify_signature(f"{host}/team/app:v1", key=pem) == digest
        assert len(s.requests) == before    # served from the TTL cache

    def test_token_auth_flow(self, keypair):
        s = RegistryStub(require_token=True)
        host = s.start()
        try:
            priv, pem = keypair
            digest = s.push_image("team/app", "v1")
            s.cosign_sign("team/app", digest, priv)
            out = RegistryVerifier(
                RegistryClient(plain_http=True),
                default_registry=host).verify_signature(
                    f"{host}/team/app:v1", key=pem)
            assert out == digest
            assert any(p.startswith("/token") for p in s.requests)
        finally:
            s.stop()


class TestAttestations:
    def test_fetch_and_verify_statements(self, stub, keypair):
        s, host = stub
        priv, pem = keypair
        digest = s.push_image("team/app", "v1")
        stmt = {"predicateType": "https://slsa.dev/provenance/v0.2",
                "predicate": {"builder": {"id": "ci"}}}
        s.cosign_attest("team/app", digest, priv, stmt)
        out = RegistryVerifier(
            RegistryClient(plain_http=True),
            default_registry=host).fetch_attestations(
                f"{host}/team/app:v1", key=pem)
        assert len(out) == 1
        assert out[0]["predicate"] == stmt["predicate"]
        assert out[0]["subject"][0]["digest"]["sha256"] == \
            digest.split(":", 1)[-1]

    def test_replayed_attestation_rejected(self, stub, keypair):
        """A key-valid attestation for image A republished under image B's
        .att tag must not verify (subject digest binding)."""
        s, host = stub
        priv, pem = keypair
        digest_a = s.push_image("team/app", "v1")
        digest_b = s.push_image("team/other", "v1")
        stmt = {"predicateType": "t", "predicate": {"ok": True},
                "subject": [{"name": "team/app",
                             "digest": {"sha256":
                                        digest_a.split(":", 1)[-1]}}]}
        # republish A's (validly signed) envelope under B's att tag
        s.cosign_attest("team/other", digest_b, priv, stmt,
                        bind_subject=False)
        with pytest.raises(VerificationError, match="subject does not"):
            RegistryVerifier(
                RegistryClient(plain_http=True),
                default_registry=host).fetch_attestations(
                    f"{host}/team/other:v1", key=pem)

    def test_bad_envelope_signature_fails(self, stub, keypair):
        s, host = stub
        priv, pem = keypair
        other_priv, _ = ecdsa.generate_keypair()
        digest = s.push_image("team/app", "v1")
        s.cosign_attest("team/app", digest, other_priv,
                        {"predicateType": "t", "predicate": {}})
        with pytest.raises(VerificationError, match="attestation signature"):
            RegistryVerifier(
                RegistryClient(plain_http=True),
                default_registry=host).fetch_attestations(
                    f"{host}/team/app:v1", key=pem)


class TestWebhookE2E:
    """The VERDICT 'done' shape: registry stub + signed/unsigned image
    -> digest patch vs block through the production HTTP webhook, and a
    PolicyReport row either way."""

    def _policy(self, host, pem):
        return {
            "apiVersion": "kyverno.io/v1", "kind": "ClusterPolicy",
            "metadata": {"name": "verify-app-images"},
            "spec": {
                "validationFailureAction": "enforce",
                "rules": [{
                    "name": "check-sig",
                    "match": {"resources": {"kinds": ["Pod"]}},
                    "verifyImages": [{
                        "image": f"{host}/team/*",
                        "key": pem,
                    }],
                }],
            },
        }

    def _post(self, port, resource):
        review = {
            "apiVersion": "admission.k8s.io/v1", "kind": "AdmissionReview",
            "request": {"uid": "u1", "kind": {"kind": "Pod"},
                        "namespace": "default", "operation": "CREATE",
                        "object": resource}}
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/mutate",
            data=json.dumps(review).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=30) as resp:
            return json.loads(resp.read())

    def test_signed_patches_unsigned_blocks_and_reports(self, stub, keypair):
        from kyverno_tpu.runtime.client import FakeCluster
        from kyverno_tpu.server import Controller

        s, host = stub
        priv, pem = keypair
        digest = s.push_image("team/app", "v1")
        s.cosign_sign("team/app", digest, priv)
        s.push_image("team/rogue", "v1")     # unsigned

        cluster = FakeCluster([self._policy(host, pem)])
        controller = Controller(
            client=cluster, serve_port=0,
            image_verifier=RegistryVerifier(RegistryClient(plain_http=True),
                                            default_registry=host))
        controller.start(host="127.0.0.1")
        try:
            port = controller._httpd.server_address[1]

            def pod(name, image):
                return {"apiVersion": "v1", "kind": "Pod",
                        "metadata": {"name": name, "namespace": "default"},
                        "spec": {"containers": [
                            {"name": "c", "image": image}]}}

            good = self._post(port, pod("good", f"{host}/team/app:v1"))
            assert good["response"]["allowed"] is True
            patch = json.loads(base64.b64decode(
                good["response"]["patch"]))
            assert any(p["value"].endswith("@" + digest) for p in patch)

            bad = self._post(port, pod("bad", f"{host}/team/rogue:v1"))
            assert bad["response"]["allowed"] is False
            assert "image verification failed" in \
                bad["response"]["status"]["message"]

            # PolicyReport rows for both outcomes
            reports = controller.report_gen.aggregate()
            results = [r for rep in reports
                       for r in rep.get("results", [])
                       if rep.get("kind", "").endswith("PolicyReport")
                       and r.get("policy") == "verify-app-images"]
            statuses = {r.get("result") or r.get("status") for r in results}
            assert "pass" in statuses and "fail" in statuses
        finally:
            controller.stop()
