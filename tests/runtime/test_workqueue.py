"""Direct unit tests for runtime/workqueue.WorkerQueue (previously only
exercised indirectly through the audit handler and event generator)."""

import threading
import time

from kyverno_tpu.runtime.workqueue import WorkerQueue


def test_processes_all_items():
    seen = []
    lock = threading.Lock()

    def handler(item):
        with lock:
            seen.append(item)

    wq = WorkerQueue(handler, workers=4, name="t")
    wq.run()
    for i in range(200):
        assert wq.add(i)
    wq.drain(timeout=10.0)
    wq.stop()
    assert wq.processed == 200
    assert wq.dropped == 0
    assert sorted(seen) == list(range(200))


def test_bounded_queue_sheds_load():
    release = threading.Event()

    def handler(item):
        release.wait(5.0)

    wq = WorkerQueue(handler, workers=1, name="t", max_queued=2)
    wq.run()
    # worker grabs the first item and blocks; two fit in the queue
    results = [wq.add(i) for i in range(10)]
    dropped_before_release = wq.dropped
    release.set()
    wq.drain(timeout=10.0)
    wq.stop()
    assert results.count(False) == dropped_before_release
    assert wq.dropped >= 1
    assert wq.processed + wq.dropped == 10


def test_retry_on_handler_exception():
    attempts = {}
    lock = threading.Lock()

    def handler(item):
        with lock:
            attempts[item] = attempts.get(item, 0) + 1
            if attempts[item] < 3:
                raise RuntimeError("transient")

    wq = WorkerQueue(handler, workers=2, name="t", max_retries=3)
    wq.run()
    wq.add("a")
    wq.drain(timeout=10.0)
    wq.stop()
    assert attempts["a"] == 3
    assert wq.processed == 1


def test_retries_exhausted_item_is_not_processed():
    def handler(item):
        raise RuntimeError("permanent")

    wq = WorkerQueue(handler, workers=1, name="t", max_retries=2)
    wq.run()
    wq.add("x")
    wq.drain(timeout=10.0)
    wq.stop()
    assert wq.processed == 0


def test_drain_waits_for_in_flight_work():
    done = []

    def handler(item):
        time.sleep(0.15)
        done.append(item)

    wq = WorkerQueue(handler, workers=1, name="t")
    wq.run()
    wq.add(1)
    time.sleep(0.02)          # let the worker pick it up (queue empty)
    wq.drain(timeout=5.0)
    assert done == [1]
    wq.stop()


def test_stop_terminates_workers():
    wq = WorkerQueue(lambda item: None, workers=3, name="t")
    wq.run()
    threads = list(wq._threads)
    wq.stop()
    assert wq._threads == []
    for t in threads:
        assert not t.is_alive()
