"""Direct unit tests for runtime/workqueue.WorkerQueue (previously only
exercised indirectly through the audit handler and event generator)."""

import threading
import time

from kyverno_tpu.runtime.workqueue import WorkerQueue


def test_processes_all_items():
    seen = []
    lock = threading.Lock()

    def handler(item):
        with lock:
            seen.append(item)

    wq = WorkerQueue(handler, workers=4, name="t")
    wq.run()
    for i in range(200):
        assert wq.add(i)
    wq.drain(timeout=10.0)
    wq.stop()
    assert wq.processed == 200
    assert wq.dropped == 0
    assert sorted(seen) == list(range(200))


def test_bounded_queue_sheds_load():
    release = threading.Event()

    def handler(item):
        release.wait(5.0)

    wq = WorkerQueue(handler, workers=1, name="t", max_queued=2)
    wq.run()
    # worker grabs the first item and blocks; two fit in the queue
    results = [wq.add(i) for i in range(10)]
    dropped_before_release = wq.dropped
    release.set()
    wq.drain(timeout=10.0)
    wq.stop()
    assert results.count(False) == dropped_before_release
    assert wq.dropped >= 1
    assert wq.processed + wq.dropped == 10


def test_retry_on_handler_exception():
    attempts = {}
    lock = threading.Lock()

    def handler(item):
        with lock:
            attempts[item] = attempts.get(item, 0) + 1
            if attempts[item] < 3:
                raise RuntimeError("transient")

    wq = WorkerQueue(handler, workers=2, name="t", max_retries=3)
    wq.run()
    wq.add("a")
    wq.drain(timeout=10.0)
    wq.stop()
    assert attempts["a"] == 3
    assert wq.processed == 1


def test_retries_exhausted_item_is_not_processed():
    def handler(item):
        raise RuntimeError("permanent")

    wq = WorkerQueue(handler, workers=1, name="t", max_retries=2)
    wq.run()
    wq.add("x")
    wq.drain(timeout=10.0)
    wq.stop()
    assert wq.processed == 0


def test_drain_waits_for_in_flight_work():
    done = []

    def handler(item):
        time.sleep(0.15)
        done.append(item)

    wq = WorkerQueue(handler, workers=1, name="t")
    wq.run()
    wq.add(1)
    time.sleep(0.02)          # let the worker pick it up (queue empty)
    wq.drain(timeout=5.0)
    assert done == [1]
    wq.stop()


def test_stop_terminates_workers():
    wq = WorkerQueue(lambda item: None, workers=3, name="t")
    wq.run()
    threads = list(wq._threads)
    wq.stop()
    assert wq._threads == []
    for t in threads:
        assert not t.is_alive()


class TestShedReasons:
    """Degradation-plane tagging: every dropped enqueue carries a
    reason — backpressure ("full") vs the SLO shed hook ("slo") — so an
    operator can tell a storm from a deliberate brownout response."""

    def test_full_queue_tagged_full(self):
        release = threading.Event()
        wq = WorkerQueue(lambda item: release.wait(5.0), workers=1,
                         name="t", max_queued=1)
        wq.run()
        for i in range(6):
            wq.add(i)
        dropped = wq.dropped
        release.set()
        wq.drain(timeout=5.0)
        wq.stop()
        assert dropped >= 1
        assert wq.dropped_by_reason["full"] == dropped
        assert wq.dropped_by_reason["slo"] == 0

    def test_shed_cb_tagged_slo_and_skips_queue(self):
        shedding = [True]
        wq = WorkerQueue(lambda item: None, workers=1, name="t",
                         shed_cb=lambda: shedding[0])
        wq.run()
        assert wq.add("a") is False       # shed before the queue
        assert wq.add("b") is False
        shedding[0] = False
        assert wq.add("c") is True        # hook released: flows again
        wq.drain(timeout=5.0)
        wq.stop()
        assert wq.dropped_by_reason == {"slo": 2, "full": 0}
        assert wq.processed == 1

    def test_shed_counter_labelled_by_reason(self):
        from kyverno_tpu.runtime import metrics as metrics_mod

        reg = metrics_mod.registry()
        name = "t-shed-metric"
        before = reg.counter_value("kyverno_queue_sheds_total",
                                   {"queue": name, "reason": "slo"}) or 0
        wq = WorkerQueue(lambda item: None, workers=1, name=name,
                         shed_cb=lambda: True)
        assert wq.add("a") is False
        after = reg.counter_value("kyverno_queue_sheds_total",
                                  {"queue": name, "reason": "slo"})
        assert after == before + 1

    def test_shed_cb_exception_fails_open(self):
        def boom():
            raise RuntimeError("hook died")

        wq = WorkerQueue(lambda item: None, workers=1, name="t",
                         shed_cb=boom)
        wq.run()
        assert wq.add("a") is True        # a broken hook must not shed
        wq.drain(timeout=5.0)
        wq.stop()
        assert wq.dropped == 0
