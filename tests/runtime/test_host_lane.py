"""Host-lane resolution battery (runtime/hostlane + resolve_host_cells).

Exercises the three overlapped-resolution mechanisms — predictive
prefetch, host-verdict memoization, pool/thread fan-out — against the
one property that matters: every lane must reproduce the serial
per-resource oracle walk's verdicts AND messages bit for bit, because
the kill switches promise to restore that dataflow exactly.
"""

import time

import numpy as np
import pytest

from kyverno_tpu.api.load import load_policy
from kyverno_tpu.models import CompiledPolicySet
from kyverno_tpu.models.engine import Verdict
from kyverno_tpu.runtime import hostlane


def _host_policy(name="host-echo-name", message="name mismatch",
                 field="name"):
    return load_policy({
        "apiVersion": "kyverno.io/v1", "kind": "ClusterPolicy",
        "metadata": {"name": name},
        "spec": {"validationFailureAction": "enforce", "rules": [{
            "name": "echo",
            "match": {"resources": {"kinds": ["Pod"]}},
            "validate": {"message": message,
                         "pattern": {"metadata": {field:
                             "{{request.object.metadata." + field + "}}"}}},
        }]},
    })


def _device_policy(name="no-latest"):
    return load_policy({
        "apiVersion": "kyverno.io/v1", "kind": "ClusterPolicy",
        "metadata": {"name": name},
        "spec": {"validationFailureAction": "enforce", "rules": [{
            "name": "r",
            "match": {"resources": {"kinds": ["Pod"]}},
            "validate": {"message": "latest banned",
                         "pattern": {"spec": {"containers": [
                             {"image": "!*:latest"}]}}},
        }]},
    })


def _pod(i):
    return {"apiVersion": "v1", "kind": "Pod",
            "metadata": {"name": f"p{i}", "namespace": "default",
                         "uid": str(i)},
            "spec": {"containers": [{"name": "c", "image": f"nginx:1.{i}"}]}}


def _ctx(pod):
    return {"request": {"object": pod, "operation": "CREATE",
                        "userInfo": {"username": "t"}}}


@pytest.fixture(autouse=True)
def _fresh_memo():
    hostlane.host_cache().clear()
    yield
    hostlane.host_cache().clear()


@pytest.fixture
def cps():
    return CompiledPolicySet([_host_policy(), _device_policy(),
                              _host_policy("host-echo-ns",
                                           "ns mismatch", "namespace")])


def _serial_reference(cps, pods, contexts, rule_filter):
    """Ground truth: every switch thrown — the original serial loop."""
    with pytest.MonkeyPatch.context() as mp:
        for s in ("KTPU_HOST_PREFETCH", "KTPU_HOST_MEMO",
                  "KTPU_HOST_FANOUT"):
            mp.setenv(s, "0")
        msgs = {}
        v = cps.resolve_host_cells(
            pods, cps.evaluate_device(cps.flatten_packed(pods)).copy(),
            contexts=contexts, rule_filter=rule_filter, messages_out=msgs)
    return np.asarray(v), msgs


class TestResolveHostCells:
    @pytest.mark.parametrize("with_contexts", [False, True])
    @pytest.mark.parametrize("with_filter", [False, True])
    @pytest.mark.parametrize("with_messages", [False, True])
    def test_battery(self, cps, with_contexts, with_filter, with_messages):
        """contexts x rule_filter x messages_out, overlapped lane vs the
        serial reference."""
        pods = [_pod(i) for i in range(6)]
        contexts = [_ctx(p) for p in pods] if with_contexts else None
        host_rows = [r for r, ref in enumerate(cps.rule_refs)
                     if "echo" in ref.policy.name]
        rule_filter = set(host_rows[:1]) if with_filter else None

        want_v, want_m = _serial_reference(cps, pods, contexts, rule_filter)

        hostlane.host_cache().clear()
        msgs = {} if with_messages else None
        v = cps.evaluate_device(cps.flatten_packed(pods)).copy()
        pf = hostlane.resolver().prefetch(cps, pods, contexts=contexts,
                                          rule_filter=rule_filter)
        got = np.asarray(cps.resolve_host_cells(
            pods, v, contexts=contexts, rule_filter=rule_filter,
            messages_out=msgs, prefetch=pf))

        assert np.array_equal(got, want_v)
        if with_messages:
            assert msgs == want_m
        if with_filter:
            # cells outside the filter stay HOST for the caller
            other = [r for r in host_rows if r not in rule_filter]
            assert (got[:, other] == int(Verdict.HOST)).all()
        else:
            assert not (got == int(Verdict.HOST)).any()

    def test_copy_flag_leaves_input_untouched(self, cps):
        pods = [_pod(i) for i in range(3)]
        raw = np.asarray(cps.evaluate_device(cps.flatten_packed(pods)))
        before = raw.copy()
        resolved = cps.resolve_host_cells(pods, raw, copy=True)
        assert np.array_equal(raw, before)          # input untouched
        assert resolved is not raw
        assert not (resolved == int(Verdict.HOST)).any()

        inplace = raw.copy()
        out = cps.resolve_host_cells(pods, inplace)
        assert out is inplace                       # default: in place
        assert not (inplace == int(Verdict.HOST)).any()

    def test_prefetch_vs_post_pass_parity(self, cps, monkeypatch):
        """A prefetched join and the plain post-pass must agree cell for
        cell — over-computation may be wasted, never a verdict change."""
        monkeypatch.setenv("KTPU_HOST_MEMO", "0")
        pods = [_pod(i) for i in range(5)]

        m_post = {}
        monkeypatch.setenv("KTPU_HOST_PREFETCH", "0")
        assert hostlane.resolver().prefetch(cps, pods) is None
        v_post = cps.resolve_host_cells(
            pods, cps.evaluate_device(cps.flatten_packed(pods)).copy(),
            messages_out=m_post)

        monkeypatch.setenv("KTPU_HOST_PREFETCH", "1")
        pf = hostlane.resolver().prefetch(cps, pods)
        assert pf is not None and pf.submitted_cells > 0
        m_pre = {}
        v_pre = cps.resolve_host_cells(
            pods, cps.evaluate_device(cps.flatten_packed(pods)).copy(),
            messages_out=m_pre, prefetch=pf)
        assert pf.applied_cells > 0
        assert np.array_equal(np.asarray(v_post), np.asarray(v_pre))
        assert m_post == m_pre

    def test_fanout_parity(self, cps, monkeypatch):
        monkeypatch.setenv("KTPU_HOST_MEMO", "0")
        pods = [_pod(i) for i in range(8)]
        monkeypatch.setenv("KTPU_HOST_FANOUT", "0")
        m_serial = {}
        v_serial = cps.resolve_host_cells(
            pods, cps.evaluate_device(cps.flatten_packed(pods)).copy(),
            messages_out=m_serial)
        monkeypatch.setenv("KTPU_HOST_FANOUT", "1")
        before = hostlane.resolver().stats["fanout_batches"]
        m_fan = {}
        v_fan = cps.resolve_host_cells(
            pods, cps.evaluate_device(cps.flatten_packed(pods)).copy(),
            messages_out=m_fan)
        assert hostlane.resolver().stats["fanout_batches"] > before
        assert np.array_equal(np.asarray(v_serial), np.asarray(v_fan))
        assert m_serial == m_fan


def _memo_delta(before, after):
    return {k: after[k] - before[k] for k in ("hits", "misses", "expired")}


class TestHostVerdictMemo:
    def test_hit_after_warm(self, cps, monkeypatch):
        monkeypatch.setenv("KTPU_HOST_MEMO", "1")
        monkeypatch.setenv("KTPU_HOST_PREFETCH", "0")
        pods = [_pod(i) for i in range(4)]
        memo = hostlane.host_cache()
        t0 = dict(memo.stats())

        m1 = {}
        v1 = cps.resolve_host_cells(
            pods, cps.evaluate_device(cps.flatten_packed(pods)).copy(),
            messages_out=m1)
        cold = _memo_delta(t0, memo.stats())
        assert cold["misses"] > 0 and cold["hits"] == 0

        t1 = dict(memo.stats())
        m2 = {}
        v2 = cps.resolve_host_cells(
            pods, cps.evaluate_device(cps.flatten_packed(pods)).copy(),
            messages_out=m2)
        warm = _memo_delta(t1, memo.stats())
        assert warm["hits"] == cold["misses"]       # every cell served
        assert warm["misses"] == 0                  # no new oracle work
        assert np.array_equal(np.asarray(v1), np.asarray(v2))
        assert m1 == m2

    def test_kill_switch_bypasses_cache(self, cps, monkeypatch):
        monkeypatch.setenv("KTPU_HOST_MEMO", "0")
        memo = hostlane.host_cache()
        t0 = dict(memo.stats())
        pods = [_pod(i) for i in range(3)]
        cps.resolve_host_cells(
            pods, cps.evaluate_device(cps.flatten_packed(pods)).copy())
        d = _memo_delta(t0, memo.stats())
        assert d["hits"] == d["misses"] == len(memo) == 0

    def test_ttl_expiry(self, cps, monkeypatch):
        monkeypatch.setenv("KTPU_HOST_MEMO", "1")
        monkeypatch.setenv("KTPU_HOST_PREFETCH", "0")
        memo = hostlane.host_cache()
        monkeypatch.setattr(memo, "pure_ttl_s", 0.02)
        monkeypatch.setattr(memo, "context_ttl_s", 0.02)
        pods = [_pod(0)]
        t0 = dict(memo.stats())
        cps.resolve_host_cells(
            pods, cps.evaluate_device(cps.flatten_packed(pods)).copy())
        assert _memo_delta(t0, memo.stats())["misses"] > 0
        time.sleep(0.05)
        t1 = dict(memo.stats())
        cps.resolve_host_cells(
            pods, cps.evaluate_device(cps.flatten_packed(pods)).copy())
        d = _memo_delta(t1, memo.stats())
        assert d["expired"] > 0                     # entries aged out
        assert d["hits"] == 0                       # and did not serve

    def test_policy_swap_invalidates(self, monkeypatch):
        """Content addressing: an edited policy (same name, new raw)
        lands in a fresh key space — memoized verdicts/messages never
        cross policy content. The rule always FAILs (name vs uid) so the
        policy's own message text is what the oracle reports."""
        monkeypatch.setenv("KTPU_HOST_MEMO", "1")
        monkeypatch.setenv("KTPU_HOST_PREFETCH", "0")
        pods = [_pod(0)]
        memo = hostlane.host_cache()

        def mismatch_policy(message):
            return load_policy({
                "apiVersion": "kyverno.io/v1", "kind": "ClusterPolicy",
                "metadata": {"name": "host-name-vs-uid"},
                "spec": {"validationFailureAction": "enforce", "rules": [{
                    "name": "echo",
                    "match": {"resources": {"kinds": ["Pod"]}},
                    "validate": {"message": message,
                                 "pattern": {"metadata": {"name":
                                     "{{request.object.metadata.uid}}"}}},
                }]},
            })

        t0 = dict(memo.stats())
        cps1 = CompiledPolicySet([mismatch_policy("old wording")])
        m1 = {}
        cps1.resolve_host_cells(
            pods, cps1.evaluate_device(cps1.flatten_packed(pods)).copy(),
            messages_out=m1)
        fill = _memo_delta(t0, memo.stats())
        assert fill["misses"] > 0

        t1 = dict(memo.stats())
        cps2 = CompiledPolicySet([mismatch_policy("new wording")])
        m2 = {}
        cps2.resolve_host_cells(
            pods, cps2.evaluate_device(cps2.flatten_packed(pods)).copy(),
            messages_out=m2)
        d = _memo_delta(t1, memo.stats())
        assert d["hits"] == 0                       # nothing crossed
        assert d["misses"] > 0
        assert any("new wording" in m for m in m2.values())
        assert not any("new wording" in m for m in m1.values())


class TestShardedScanHostLane:
    def test_incremental_counts_match_full_recompute(self):
        """Per-chunk in-worker resolution: verdicts match the single-chip
        evaluate, and the incrementally-updated fails/passes equal a full
        recompute over the resolved matrix."""
        from kyverno_tpu.ops.eval import V_FAIL, V_HOST, V_PASS
        from kyverno_tpu.parallel.mesh import make_mesh, sharded_scan

        pols = [_device_policy(), _host_policy(),
                _host_policy("host-echo-uid", "uid mismatch", "uid")]
        cps = CompiledPolicySet(pols)
        pods = [_pod(i) for i in range(40)]
        mesh = make_mesh()

        verdicts, fails, passes = sharded_scan(cps, pods, mesh,
                                               chunk_size=16)
        assert not (verdicts == V_HOST).any()
        want = np.asarray(cps.evaluate(pods))
        assert np.array_equal(verdicts, want[:, :verdicts.shape[1]])
        np.testing.assert_array_equal(
            fails, (verdicts == V_FAIL).sum(axis=0))
        np.testing.assert_array_equal(
            passes, (verdicts == V_PASS).sum(axis=0))

    def test_kill_switch_parity(self, monkeypatch):
        from kyverno_tpu.parallel.mesh import make_mesh, sharded_scan

        pols = [_device_policy(), _host_policy()]
        cps = CompiledPolicySet(pols)
        pods = [_pod(i) for i in range(24)]
        mesh = make_mesh()

        v_on, f_on, p_on = sharded_scan(cps, pods, mesh, chunk_size=8)
        for s in ("KTPU_HOST_PREFETCH", "KTPU_HOST_MEMO",
                  "KTPU_HOST_FANOUT"):
            monkeypatch.setenv(s, "0")
        v_off, f_off, p_off = sharded_scan(cps, pods, mesh, chunk_size=8)
        assert np.array_equal(v_on, v_off)
        np.testing.assert_array_equal(f_on, f_off)
        np.testing.assert_array_equal(p_on, p_off)
