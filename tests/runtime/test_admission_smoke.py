"""End-to-end admission smoke: heterogeneous traffic through the webhook.

The round-5 burst numbers turned out to measure the decision cache, not
the engine (every request carried the same body). This smoke test is the
standing guard against that regression: 32 DISTINCT admissions through
the production handler must be decided with (almost) no cache hits and
with at least one decision settled entirely from the device screen.
"""

import pytest

from kyverno_tpu.api.load import load_policy
from kyverno_tpu.runtime.batch import AdmissionBatcher
from kyverno_tpu.runtime.client import FakeCluster
from kyverno_tpu.runtime.policycache import PolicyCache, PolicyType
from kyverno_tpu.runtime.webhook import VALIDATING_WEBHOOK_PATH, WebhookServer

POLICIES = [
    {
        "apiVersion": "kyverno.io/v1", "kind": "ClusterPolicy",
        "metadata": {"name": "disallow-latest-tag"},
        "spec": {"validationFailureAction": "enforce", "rules": [{
            "name": "validate-image-tag",
            "match": {"resources": {"kinds": ["Pod"]}},
            "validate": {"message": "latest tag not allowed",
                         "pattern": {"spec": {"containers": [
                             {"image": "!*:latest"}]}}},
        }]},
    },
    {
        "apiVersion": "kyverno.io/v1", "kind": "ClusterPolicy",
        "metadata": {"name": "require-name"},
        "spec": {"validationFailureAction": "enforce", "rules": [{
            "name": "check-name",
            "match": {"resources": {"kinds": ["Pod"]}},
            "validate": {"message": "name required",
                         "pattern": {"metadata": {"name": "?*"}}},
        }]},
    },
]


def _review(i: int) -> dict:
    """Distinct name, uid, and image per admission — cache-adversarial."""
    image = "nginx:latest" if i % 4 == 0 else f"nginx:1.{i}"
    return {
        "apiVersion": "admission.k8s.io/v1", "kind": "AdmissionReview",
        "request": {
            "uid": f"smoke-uid-{i}", "kind": {"kind": "Pod"},
            "namespace": "default", "operation": "CREATE",
            "object": {
                "apiVersion": "v1", "kind": "Pod",
                "metadata": {"name": f"smoke-pod-{i}",
                             "namespace": "default"},
                "spec": {"containers": [
                    {"name": "c", "image": image}]},
            },
        },
    }


def test_heterogeneous_admissions_bypass_caches_and_use_device():
    cache = PolicyCache()
    for doc in POLICIES:
        cache.add(load_policy(doc))
    batcher = AdmissionBatcher(cache, window_s=0.002, burst_threshold=1,
                               dispatch_cost_init_s=0.0,
                               oracle_cost_init_s=1.0,
                               cold_flush_fallback=False,
                               result_cache_ttl_s=0.0)
    server = WebhookServer(policy_cache=cache, client=FakeCluster(),
                           admission_batcher=batcher)
    try:
        # pre-compile the screen kernel so the first admission doesn't
        # pay XLA compilation inside its deadline
        batcher.warmup(PolicyType.VALIDATE_ENFORCE, "Pod", "default",
                       _review(0)["request"]["object"])
        n = 32
        denied = 0
        for i in range(n):
            out = server.handle(VALIDATING_WEBHOOK_PATH, _review(i))
            allowed = out["response"]["allowed"]
            assert allowed is (i % 4 != 0)
            denied += 0 if allowed else 1
        assert denied == 8

        stats = batcher.stats
        cache_hits = (stats.get("decision_cache", 0) + stats.get("cache", 0))
        # heterogeneous traffic must not be answered from caches
        assert cache_hits < 0.1 * n
        # and at least one decision must settle entirely on the device
        # (CLEAN short-circuit or fully device-answered deny)
        assert stats.get("device_decided", 0) >= 1
    finally:
        batcher.stop()


if __name__ == "__main__":
    raise SystemExit(pytest.main([__file__, "-q"]))
