"""Direct unit tests for runtime/leaderelection.LeaderElector
(previously exercised only through the controller wiring): acquisition,
renewal, expiry takeover after holder death, CAS races between two
scanner replicas, and clean release on stop — all over FakeCluster's
resourceVersion-guarded update semantics."""

import threading
import time

from kyverno_tpu.runtime import leaderelection as le
from kyverno_tpu.runtime.client import FakeCluster
from kyverno_tpu.runtime.leaderelection import LeaderElector


def _lease(cluster, name="kyverno", namespace="kyverno"):
    return cluster.get_resource("coordination.k8s.io/v1", "Lease",
                                namespace, name)


def test_first_replica_acquires():
    cluster = FakeCluster()
    a = LeaderElector(cluster, identity="scanner-a")
    assert a.try_acquire_or_renew() is True
    assert a.is_leader()
    lease = _lease(cluster)
    assert lease["spec"]["holderIdentity"] == "scanner-a"


def test_holder_renews_and_advances_renew_time():
    cluster = FakeCluster()
    a = LeaderElector(cluster, identity="scanner-a")
    assert a.try_acquire_or_renew()
    t0 = _lease(cluster)["spec"]["renewTime"]
    time.sleep(0.02)
    assert a.try_acquire_or_renew()
    assert _lease(cluster)["spec"]["renewTime"] > t0
    assert a.is_leader()


def test_non_holder_defers_while_lease_fresh():
    cluster = FakeCluster()
    a = LeaderElector(cluster, identity="scanner-a")
    b = LeaderElector(cluster, identity="scanner-b")
    assert a.try_acquire_or_renew()
    assert b.try_acquire_or_renew() is False
    assert not b.is_leader()
    assert _lease(cluster)["spec"]["holderIdentity"] == "scanner-a"


def test_takeover_after_holder_death(monkeypatch):
    """The holder stops renewing without releasing; once the lease
    expires the survivor takes over and the dead holder's next attempt
    observes the loss."""
    monkeypatch.setattr(le, "LEASE_DURATION_S", 0.1)
    cluster = FakeCluster()
    stopped = []
    a = LeaderElector(cluster, identity="scanner-a",
                      on_stopped_leading=lambda: stopped.append("a"))
    b = LeaderElector(cluster, identity="scanner-b")
    assert a.try_acquire_or_renew()
    time.sleep(0.15)                 # renewTime now past the lease
    assert b.try_acquire_or_renew() is True
    assert b.is_leader()
    assert a.try_acquire_or_renew() is False
    assert not a.is_leader()
    assert stopped == ["a"]


def test_expired_lease_race_elects_exactly_one():
    """Two replicas CAS the same expired lease concurrently: the
    resourceVersion guard must admit exactly one winner per round."""
    for seed in range(8):
        now = time.time()
        cluster = FakeCluster([{
            "apiVersion": "coordination.k8s.io/v1", "kind": "Lease",
            "metadata": {"name": "kyverno", "namespace": "kyverno"},
            "spec": {"holderIdentity": "scanner-dead",
                     "leaseDurationSeconds": 15,
                     "renewTime": now - 100.0},
        }])
        a = LeaderElector(cluster, identity="scanner-a")
        b = LeaderElector(cluster, identity="scanner-b")
        barrier = threading.Barrier(2)
        results = {}

        def race(elector, key):
            barrier.wait()
            results[key] = elector.try_acquire_or_renew()

        ta = threading.Thread(target=race, args=(a, "a"))
        tb = threading.Thread(target=race, args=(b, "b"))
        ta.start()
        tb.start()
        ta.join(5.0)
        tb.join(5.0)
        assert sum(results.values()) == 1, (seed, results)
        winner = "scanner-a" if results["a"] else "scanner-b"
        assert _lease(cluster)["spec"]["holderIdentity"] == winner


def test_stop_releases_lease_for_immediate_takeover():
    cluster = FakeCluster()
    a = LeaderElector(cluster, identity="scanner-a")
    b = LeaderElector(cluster, identity="scanner-b")
    assert a.try_acquire_or_renew()
    a.stop()
    assert not a.is_leader()
    assert _lease(cluster)["spec"]["holderIdentity"] == ""
    # no expiry wait needed: the released lease is free right now
    assert b.try_acquire_or_renew() is True


def test_multi_lease_acquire_and_held():
    cluster = FakeCluster()
    events = []
    a = LeaderElector(cluster, identity="scanner-a",
                      on_lease_acquired=lambda n: events.append(("+", n)),
                      on_lease_lost=lambda n: events.append(("-", n)))
    a.add_lease("ktpu-scan-part-0")
    a.add_lease("ktpu-scan-part-1")
    assert a.try_acquire_or_renew() is True
    assert a.held() == frozenset({"kyverno", "ktpu-scan-part-0",
                                  "ktpu-scan-part-1"})
    assert a.is_leader() and a.is_leader("ktpu-scan-part-1")
    assert ("+", "ktpu-scan-part-0") in events
    # every named lease exists in the cluster under its own name
    assert _lease(cluster, "ktpu-scan-part-0")["spec"][
        "holderIdentity"] == "scanner-a"


def test_named_lease_concurrent_acquisition_single_holder():
    """Two electors (distinct primaries) contend for one shared named
    lease concurrently: exactly one holds it per round."""
    for seed in range(6):
        cluster = FakeCluster()
        a = LeaderElector(cluster, name="primary-a", identity="a")
        b = LeaderElector(cluster, name="primary-b", identity="b")
        a.add_lease("shared-part")
        b.add_lease("shared-part")
        barrier = threading.Barrier(2)

        def race(elector):
            barrier.wait()
            elector.try_acquire_or_renew()

        ta = threading.Thread(target=race, args=(a,))
        tb = threading.Thread(target=race, args=(b,))
        ta.start()
        tb.start()
        ta.join(5.0)
        tb.join(5.0)
        holders = [e for e in (a, b) if e.is_leader("shared-part")]
        assert len(holders) == 1, seed
        # both keep their own primaries regardless of the shared race
        assert a.is_leader() and b.is_leader()


def test_named_lease_expiry_takeover(monkeypatch):
    """The holder of a named lease dies without releasing; after expiry
    the peer's next round takes it over and the dead holder observes
    the loss."""
    monkeypatch.setattr(le, "LEASE_DURATION_S", 0.1)
    cluster = FakeCluster()
    lost = []
    a = LeaderElector(cluster, name="primary-a", identity="a",
                      on_lease_lost=lost.append)
    b = LeaderElector(cluster, name="primary-b", identity="b")
    a.add_lease("shared-part")
    assert a.try_acquire_or_renew()
    assert a.is_leader("shared-part")
    b.add_lease("shared-part")
    assert b.try_acquire_or_renew()
    assert not b.is_leader("shared-part")   # lease still fresh
    time.sleep(0.15)
    assert b.try_acquire_or_renew()
    assert b.is_leader("shared-part")
    a.try_acquire_or_renew()
    assert not a.is_leader("shared-part")
    assert "shared-part" in lost
    assert a.is_leader()                    # its own primary survived


def test_drop_lease_release_enables_immediate_reacquire():
    cluster = FakeCluster()
    a = LeaderElector(cluster, name="primary-a", identity="a")
    b = LeaderElector(cluster, name="primary-b", identity="b")
    a.add_lease("shared-part")
    b.add_lease("shared-part")
    assert a.try_acquire_or_renew()
    b.try_acquire_or_renew()
    assert not b.is_leader("shared-part")
    a.drop_lease("shared-part", release=True)
    assert "shared-part" not in a.held()
    # no expiry wait: the release freed the lease right now
    assert b.try_acquire_or_renew()
    assert b.is_leader("shared-part")


def test_drop_primary_lease_rejected():
    cluster = FakeCluster()
    a = LeaderElector(cluster, identity="a")
    try:
        a.drop_lease(a.name)
    except ValueError:
        pass
    else:  # pragma: no cover - failure path
        raise AssertionError("dropping the primary lease must raise")


def test_stop_releases_all_named_leases():
    cluster = FakeCluster()
    a = LeaderElector(cluster, identity="a")
    b = LeaderElector(cluster, name="primary-b", identity="b")
    a.add_lease("part-0")
    assert a.try_acquire_or_renew()
    a.stop()
    assert a.held() == frozenset()
    b.add_lease("part-0")
    # both the primary and the named lease are free immediately
    assert b.try_acquire_or_renew()
    assert b.is_leader("part-0")


def test_run_loop_renews_and_survivor_takes_over(monkeypatch):
    """End to end on real threads with a compressed lease: the loop
    keeps the holder leading; killing its loop (no release) hands the
    lease to the survivor within a couple of lease durations."""
    monkeypatch.setattr(le, "LEASE_DURATION_S", 0.3)
    cluster = FakeCluster()
    started = []
    a = LeaderElector(cluster, identity="scanner-a",
                      on_started_leading=lambda: started.append("a"))
    b = LeaderElector(cluster, identity="scanner-b",
                      on_started_leading=lambda: started.append("b"))
    a.run(retry_period_s=0.05)
    b.run(retry_period_s=0.05)
    try:
        deadline = time.monotonic() + 3.0
        while not a.is_leader() and time.monotonic() < deadline:
            time.sleep(0.01)
        assert a.is_leader() and not b.is_leader()
        time.sleep(0.4)              # past one lease duration:
        assert a.is_leader()         # the loop renewed, no takeover
        a._stop.set()                # holder death, lease not released
        deadline = time.monotonic() + 3.0
        while not b.is_leader() and time.monotonic() < deadline:
            time.sleep(0.01)
        assert b.is_leader()
        assert started == ["a", "b"]
    finally:
        a._stop.set()
        b.stop()
