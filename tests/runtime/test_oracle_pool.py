"""Multiprocess oracle lane (runtime/oracle_pool.py).

The sandbox has one core so the pool is dormant by default; these tests
force it on (min_cores=1) to prove the spawn workers produce verdicts
identical to the inline engine, that cluster-dependent policies are
refused, and that the webhook integration blocks/admits through the pool
exactly as the inline loop does."""

import time

import pytest

from kyverno_tpu.api.load import load_policy
from kyverno_tpu.runtime.client import FakeCluster
from kyverno_tpu.runtime.oracle_pool import OraclePool, pool_safe

ENFORCE = {
    "apiVersion": "kyverno.io/v1", "kind": "ClusterPolicy",
    "metadata": {"name": "disallow-latest"},
    "spec": {"validationFailureAction": "enforce", "rules": [{
        "name": "no-latest",
        "match": {"resources": {"kinds": ["Pod"]}},
        "validate": {"message": "latest tag not allowed",
                     "pattern": {"spec": {"containers": [
                         {"image": "!*:latest"}]}}},
    }]},
}

REQUIRE_LABEL = {
    "apiVersion": "kyverno.io/v1", "kind": "ClusterPolicy",
    "metadata": {"name": "require-team"},
    "spec": {"validationFailureAction": "enforce", "rules": [{
        "name": "team",
        "match": {"resources": {"kinds": ["Pod"]}},
        "validate": {"message": "team label required",
                     "pattern": {"metadata": {"labels": {"team": "?*"}}},
                     },
    }]},
}

CONTEXT_POLICY = {
    "apiVersion": "kyverno.io/v1", "kind": "ClusterPolicy",
    "metadata": {"name": "uses-context"},
    "spec": {"rules": [{
        "name": "r",
        "match": {"resources": {"kinds": ["Pod"]}},
        "context": [{"name": "cm", "configMap": {"name": "x",
                                                 "namespace": "default"}}],
        "validate": {"pattern": {"metadata": {"name": "?*"}}},
    }]},
}


def pod(image, name="p", labels=None):
    return {"apiVersion": "v1", "kind": "Pod",
            "metadata": {"name": name, "namespace": "default",
                         **({"labels": labels} if labels else {})},
            "spec": {"containers": [{"name": "c", "image": image}]}}


def review(resource):
    return {"uid": "u1", "kind": {"kind": "Pod"}, "namespace": "default",
            "operation": "CREATE", "object": resource,
            "userInfo": {"username": "alice", "groups": ["dev"]}}


def _wait_ready(pool, generation, timeout_s=60.0):
    end = time.monotonic() + timeout_s
    while time.monotonic() < end:
        if pool.ready(generation):
            return True
        time.sleep(0.1)
    return False


def test_pool_safe_classification():
    assert pool_safe(load_policy(ENFORCE))
    assert not pool_safe(load_policy(CONTEXT_POLICY))


class TestOraclePool:
    def test_worker_verdicts_match_inline_engine(self):
        policies = [load_policy(ENFORCE), load_policy(REQUIRE_LABEL)]
        pool = OraclePool(workers=2, min_cores=1)
        assert pool.enabled
        try:
            pool.ensure(1, policies)
            assert _wait_ready(pool, 1)

            bad = pod("nginx:latest")
            out = pool.evaluate(
                ["disallow-latest", "require-team"], bad, review(bad),
                {}, [], [], [])
            assert out is not None
            results = dict(out)
            assert results["disallow-latest"][0][1] == "fail"
            assert "latest tag" in results["disallow-latest"][0][2]
            assert results["require-team"][0][1] == "fail"

            good = pod("nginx:1.21", labels={"team": "x"})
            out = dict(pool.evaluate(
                ["disallow-latest", "require-team"], good, review(good),
                {}, [], [], []))
            assert out["disallow-latest"][0][1] == "pass"
            assert out["require-team"][0][1] == "pass"
        finally:
            pool.stop()

    def test_generation_change_rebuilds(self):
        pool = OraclePool(workers=1, min_cores=1)
        try:
            pool.ensure(1, [load_policy(ENFORCE)])
            assert _wait_ready(pool, 1)
            # new generation: not ready until the background rebuild lands
            assert pool.ensure(2, [load_policy(REQUIRE_LABEL)]) is False
            assert _wait_ready(pool, 2)
            bad = pod("nginx:latest")
            out = dict(pool.evaluate(["require-team"], bad, review(bad),
                                     {}, [], [], []))
            assert out["require-team"][0][1] == "fail"
        finally:
            pool.stop()

    def test_disabled_below_core_floor(self):
        pool = OraclePool(min_cores=4096)
        assert not pool.enabled
        assert pool.ensure(1, []) is False


class TestWebhookIntegration:
    def test_admission_through_pool_blocks_and_admits(self):
        from kyverno_tpu.runtime.policycache import PolicyCache
        from kyverno_tpu.runtime.webhook import WebhookServer

        cache = PolicyCache()
        cache.add(load_policy(ENFORCE))
        cache.add(load_policy(REQUIRE_LABEL))
        server = WebhookServer(policy_cache=cache, client=FakeCluster())
        server.oracle_pool.stop()
        server.oracle_pool = OraclePool(workers=2, min_cores=1)
        try:
            generation = cache.generation
            server.oracle_pool.ensure(generation, cache.all_policies())
            assert _wait_ready(server.oracle_pool, generation)

            resp = server._resource_validation(review(pod("nginx:latest")))
            assert resp["response"]["allowed"] is False
            assert "latest tag" in resp["response"]["status"]["message"]
            assert "require-team" in resp["response"]["status"]["message"]

            ok = server._resource_validation(
                review(pod("nginx:1.21", labels={"team": "x"})))
            assert ok["response"]["allowed"] is True
            # both admissions actually went through the worker processes
            assert server.oracle_pool.hits == 2
        finally:
            server.stop()

    def test_context_policy_forces_inline(self):
        """A policy with context entries must not take the pool lane."""
        from kyverno_tpu.runtime.policycache import PolicyCache
        from kyverno_tpu.runtime.webhook import WebhookServer

        cluster = FakeCluster([{
            "apiVersion": "v1", "kind": "ConfigMap",
            "metadata": {"namespace": "default", "name": "x"},
            "data": {"k": "v"}}])
        cache = PolicyCache()
        cache.add(load_policy(ENFORCE))
        cache.add(load_policy(CONTEXT_POLICY))
        server = WebhookServer(policy_cache=cache, client=cluster)
        server.oracle_pool.stop()
        server.oracle_pool = OraclePool(workers=1, min_cores=1)
        try:
            out = server._pool_oracle(
                cache.all_policies(), pod("nginx:1.21"),
                review(pod("nginx:1.21")), "default")
            assert out is None     # refused: context policy in the set
            # and the full path still answers correctly inline
            resp = server._resource_validation(review(pod("nginx:latest")))
            assert resp["response"]["allowed"] is False
        finally:
            server.stop()


class TestAcceleratorIsolation:
    def test_workers_never_touch_the_accelerator(self, monkeypatch):
        """Spawned workers must come up with the accelerator env scrubbed
        (the sandbox's sitecustomize claims a TPU PJRT backend when it
        sees it) and without jax loaded at all."""
        from kyverno_tpu.runtime.oracle_pool import _worker_ready

        monkeypatch.setenv("PALLAS_AXON_POOL_IPS", "203.0.113.7")
        pool = OraclePool(workers=1, min_cores=1)
        try:
            pool.ensure(1, [load_policy(ENFORCE)])
            assert _wait_ready(pool, 1)
            # the parent env is restored after the spawn window
            import os
            assert os.environ["PALLAS_AXON_POOL_IPS"] == "203.0.113.7"
            info = pool._pool.submit(_worker_ready).result(timeout=30)
            assert info["policies"] == 1
            assert info["jax_platforms"] == "cpu"
            assert info["accel_env"] == {"PALLAS_AXON_POOL_IPS": None}
            assert info["jax_loaded"] is False
        finally:
            pool.stop()
