"""Incremental compilation acceptance suite (ISSUE 4 tentpole).

The property at the center: under ANY churn sequence — add / update /
remove over a 20+ policy library — the segmented splice path must
produce bit-identical verdict matrices to a from-scratch compile, the
epoch-refreshed flatten memos must splice indistinguishably from fresh
flattens, and ``KTPU_INCREMENTAL=0`` must restore the monolithic
compile exactly. Plus the KT304 regression: a corrupted splice
(mangled segment offsets) is caught by the analyzer, not served.
"""

import random

import numpy as np
import pytest

from kyverno_tpu.api.load import load_policy

PATTERN_POOL = [
    {"spec": {"containers": [{"image": "!*:latest"}]}},
    {"spec": {"containers": [{"image": "!*:dev"}]}},
    {"spec": {"weight": "<=100"}},
    {"spec": {"weight": ">10"}},
    {"spec": {"grace": "<1h"}},
    {"metadata": {"name": "pod-?*"}},
    {"metadata": {"labels": {"idx": "?*"}}},
    {"spec": {"containers": [{"name": "c?*"}]}},
]


def _policy(name, pattern, background=False):
    spec = {"validationFailureAction": "enforce", "rules": [{
        "name": "r",
        "match": {"resources": {"kinds": ["Pod"]}},
        "validate": {"message": "m", "pattern": pattern},
    }]}
    if background:
        spec["background"] = True
    return load_policy({
        "apiVersion": "kyverno.io/v1", "kind": "ClusterPolicy",
        "metadata": {"name": name}, "spec": spec,
    })


def _pod(i):
    return {"apiVersion": "v1", "kind": "Pod",
            "metadata": {"name": f"pod-{i}", "namespace": "default",
                         "labels": {"idx": str(i)}},
            "spec": {"containers": [{"name": f"c{i}",
                                     "image": ("nginx:latest" if i % 3 == 0
                                               else f"nginx:1.{i}")}],
                     "weight": (i * 7) % 160,
                     "grace": f"{(i * 13) % 400}s"}}


def _library(rng, n=22):
    return {f"pol-{i:02d}": _policy(f"pol-{i:02d}", rng.choice(PATTERN_POOL))
            for i in range(n)}


class TestRandomizedChurnParity:
    @pytest.mark.slow
    def test_incremental_matches_from_scratch_under_churn(self):
        """20+ policies, 40 random add/update/remove steps: after every
        step the incremental assembly's verdict matrix is bit-identical
        to a from-scratch CompiledPolicySet over the same policies, and
        memo rows carried across every epoch splice to the same verdicts
        as fresh flattens."""
        self._churn(steps=40, seed=0xC0FFEE)

    def test_incremental_matches_from_scratch_short(self):
        """Quick-gate slice of the same property (tier-1 runs with
        ``-m 'not slow'``): fewer steps, different seed."""
        self._churn(steps=6, seed=41)

    def _churn(self, steps: int, seed: int):
        from kyverno_tpu.models import CompiledPolicySet
        from kyverno_tpu.models.engine import IncrementalCompiler
        from kyverno_tpu.models.flatten import (
            MemoRow,
            refresh_packed_row,
            splice_packed_rows,
            split_packed_rows,
        )

        rng = random.Random(seed)
        lib = _library(rng)
        docs = [_pod(i) for i in range(8)]
        inc = IncrementalCompiler()

        cps = inc.refresh(list(lib.values()))
        memos = [MemoRow(row=r, n_paths=cps.tensors.n_paths,
                         epoch=cps.tensors.dict_epoch)
                 for r in split_packed_rows(cps.flatten_packed(docs))]

        next_id = len(lib)
        for step in range(steps):
            op = rng.choice(["add", "update", "remove"])
            if op == "add":
                name = f"pol-{next_id:02d}"
                next_id += 1
                lib[name] = _policy(name, rng.choice(PATTERN_POOL))
            elif op == "update" and lib:
                name = rng.choice(sorted(lib))
                lib[name] = _policy(name, rng.choice(PATTERN_POOL))
            elif lib and len(lib) > 3:
                del lib[rng.choice(sorted(lib))]

            policies = list(lib.values())
            cps = inc.refresh(policies)
            want = np.asarray(
                CompiledPolicySet(policies).evaluate_device(
                    CompiledPolicySet(policies).flatten_packed(docs)))
            got = np.asarray(
                cps.evaluate_device(cps.flatten_packed(docs)))
            assert got.shape == want.shape, f"step {step} ({op})"
            assert np.array_equal(got, want), f"step {step} ({op})"

            # memo rows from epoch 0 refresh forward and splice to the
            # exact same verdicts — the storm-survival property
            refreshed = []
            for m, d in zip(memos, docs):
                m2, _ext = refresh_packed_row(m, d, cps.tensors)
                assert m2 is not None, f"step {step}: memo lost lineage"
                refreshed.append(m2)
            memos = refreshed
            spliced = np.asarray(cps.evaluate_device(
                splice_packed_rows([m.row for m in memos])))
            assert np.array_equal(spliced, want), f"step {step} splice"

    def test_kill_switch_restores_monolithic_path(self, monkeypatch):
        """KTPU_INCREMENTAL=0 must put PolicyCache back on the exact
        historical compile: monolithic tensors (no segments, no rule
        bucketing, no persistent dictionary lineage) with identical
        verdicts."""
        from kyverno_tpu.runtime.policycache import PolicyCache, PolicyType

        rng = random.Random(7)
        policies = [_policy(f"p{i}", rng.choice(PATTERN_POOL))
                    for i in range(6)]
        docs = [_pod(i) for i in range(6)]

        monkeypatch.setenv("KTPU_INCREMENTAL", "0")
        cache = PolicyCache()
        for p in policies:
            cache.add(p)
        cps = cache.compiled(PolicyType.VALIDATE_ENFORCE, "Pod", "default")
        t = cps.tensors
        # legacy markers: no persistent dictionary lineage, no pow2
        # rule-bucket padding (6 rules would bucket to 8)
        assert t.dict_base is None
        assert t.n_rules_live == t.n_rules == 6

        from kyverno_tpu.models import CompiledPolicySet

        want_cps = CompiledPolicySet(cps.policies)
        assert t.fingerprint == want_cps.tensors.fingerprint
        got = np.asarray(cps.evaluate_device(cps.flatten_packed(docs)))
        want = np.asarray(
            want_cps.evaluate_device(want_cps.flatten_packed(docs)))
        assert np.array_equal(got, want)

        # flipping the switch on routes the same population through the
        # segmented path with the same verdicts
        monkeypatch.setenv("KTPU_INCREMENTAL", "1")
        cache2 = PolicyCache()
        for p in policies:
            cache2.add(p)
        cps2 = cache2.compiled(PolicyType.VALIDATE_ENFORCE, "Pod",
                               "default")
        assert cps2.tensors.dict_base is not None
        assert len(cps2.tensors.segments) == 6
        assert cps2.tensors.n_rules == 8          # pow2 bucket
        assert cps2.tensors.n_rules_live == 6
        got2 = np.asarray(cps2.evaluate_device(cps2.flatten_packed(docs)))
        assert np.array_equal(got2, want)


class TestDeltaScanParity:
    def test_delta_scan_matches_full_rescan(self):
        """Policy churn then resource churn: delta_scan's persisted
        verdict matrix stays bit-identical to a from-scratch scanner's,
        while evaluating only the changed columns / dirty rows."""
        from kyverno_tpu.runtime.background import BackgroundScanner

        mk = lambda name, pat: _policy(name, pat, background=True)  # noqa: E731
        p1 = [mk("a", PATTERN_POOL[0]), mk("b", PATTERN_POOL[2]),
              mk("c", PATTERN_POOL[4])]
        docs = [_pod(i) for i in range(10)]

        sc = BackgroundScanner(p1)
        sc.scan(docs)

        p2 = [p1[0], mk("b", {"spec": {"weight": "<=50",
                                       "newdeep": {"x": "?*"}}}),
              mk("d", PATTERN_POOL[5])]
        r1 = sc.delta_scan(p2)
        assert r1.delta and r1.cols_evaluated == 2 and r1.rows_evaluated == 0

        ref = BackgroundScanner(p2)
        ref.scan(docs)
        k_a, c_a, m_a = sc.verdict_matrix()
        k_b, c_b, m_b = ref.verdict_matrix()
        assert k_a == k_b and c_a == c_b
        assert np.array_equal(m_a, m_b)

        mod = _pod(1)
        mod["spec"]["weight"] = 155
        sc.note_resource("MODIFIED", mod)
        sc.note_resource("DELETED", _pod(2))
        sc.note_resource("ADDED", _pod(99))
        r2 = sc.delta_scan()
        assert r2.cols_evaluated == 0 and r2.rows_evaluated == 2

        docs2 = [mod if d["metadata"]["name"] == "pod-1" else d
                 for d in docs if d["metadata"]["name"] != "pod-2"]
        docs2.append(_pod(99))
        ref2 = BackgroundScanner(p2)
        ref2.scan(docs2)
        k_a, c_a, m_a = sc.verdict_matrix()
        k_b, c_b, m_b = ref2.verdict_matrix()
        assert c_a == c_b and set(k_a) == set(k_b)
        perm = [k_a.index(k) for k in k_b]
        assert np.array_equal(m_a[perm], m_b)

    def test_kill_switch_scan_fallback(self, monkeypatch):
        from kyverno_tpu.runtime.background import BackgroundScanner

        monkeypatch.setenv("KTPU_INCREMENTAL", "0")
        sc = BackgroundScanner([_policy("a", PATTERN_POOL[0],
                                        background=True)])
        sc.scan([_pod(i) for i in range(4)])
        assert sc.verdict_matrix() is None
        r = sc.delta_scan()
        assert not r.delta


class TestCorruptedSpliceCaught:
    """ISSUE 4 fix: ``kyverno-tpu lint`` validates the incremental
    tensor set — a splice with corrupted rebased offsets must trip
    KT304, never reach evaluation silently."""

    def _assembled(self):
        from kyverno_tpu.models.engine import IncrementalCompiler

        rng = random.Random(3)
        inc = IncrementalCompiler()
        cps = inc.refresh([_policy(f"p{i}", rng.choice(PATTERN_POOL))
                           for i in range(4)])
        return cps.tensors

    def test_clean_assembly_has_no_kt304(self):
        from kyverno_tpu.analysis.invariants import check_tensors

        t = self._assembled()
        assert t.segments
        assert not [d for d in check_tensors(t) if d.code == "KT304"]

    def test_shifted_rule_base_caught(self):
        import dataclasses

        from kyverno_tpu.analysis.invariants import check_tensors

        t = self._assembled()
        t.segments[1] = dataclasses.replace(t.segments[1],
                                            rule_base=t.segments[1].rule_base
                                            + 1)
        assert [d for d in check_tensors(t) if d.code == "KT304"]

    def test_cross_segment_row_reference_caught(self):
        from kyverno_tpu.analysis.invariants import check_tensors

        t = self._assembled()
        # point one of segment 0's checks at a rule owned by segment 1 —
        # exactly the corruption a mis-rebased splice would produce
        span = t.segments[0]
        lo, n = span.chk
        assert n > 0
        t.chk_rule[lo] = t.segments[1].rule_base
        diags = [d for d in check_tensors(t) if d.code == "KT304"]
        assert diags, "cross-segment rule reference must be caught"

    def test_overlapping_spans_caught(self):
        import dataclasses

        from kyverno_tpu.analysis.invariants import check_tensors

        t = self._assembled()
        lo, n = t.segments[1].chk
        t.segments[1] = dataclasses.replace(t.segments[1], chk=(lo - 1, n))
        assert [d for d in check_tensors(t) if d.code == "KT304"]

    def test_analyzer_covers_incremental_assembly(self, monkeypatch):
        """analyze_policies lints the segmented assembly whenever the
        runtime would serve it (KTPU_INCREMENTAL on)."""
        from kyverno_tpu.analysis import analyzer
        from kyverno_tpu.analysis.diagnostics import Severity

        policies = [_policy(f"p{i}", PATTERN_POOL[i]) for i in range(3)]
        report = analyzer.analyze_policies(policies)
        assert not report.by_severity(Severity.ERROR)

        seen = []
        orig = analyzer._check_incremental

        def spy(pols):
            out = orig(pols)
            seen.append(len(out))
            return out

        monkeypatch.setattr(analyzer, "_check_incremental", spy)
        analyzer.analyze_policies(policies)
        assert seen == [0]

        # with the kill switch thrown there is no segmented set to lint
        monkeypatch.setenv("KTPU_INCREMENTAL", "0")
        assert analyzer._check_incremental(policies) == []
