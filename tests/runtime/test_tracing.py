"""Span recorder, flight-recorder ring, and real histogram buckets.

Covers the PR-6 observability layer: runtime/tracing.py (trace/span
recording, ring + K-slowest eviction, Chrome export, KTPU_TRACE kill
switch), the MetricsRegistry bucket histograms + label escaping +
build_info/reset gauges, and runtime/obs_http.py routing.
"""

import json
import threading
import time

from kyverno_tpu.api.load import load_policy
from kyverno_tpu.runtime import obs_http, tracing
from kyverno_tpu.runtime.batch import CLEAN, AdmissionBatcher
from kyverno_tpu.runtime.metrics import MetricsRegistry
from kyverno_tpu.runtime.policycache import PolicyCache, PolicyType

ENFORCE = {
    "apiVersion": "kyverno.io/v1", "kind": "ClusterPolicy",
    "metadata": {"name": "disallow-latest-tag"},
    "spec": {
        "validationFailureAction": "enforce",
        "rules": [{
            "name": "validate-image-tag",
            "match": {"resources": {"kinds": ["Pod"]}},
            "validate": {"message": "latest tag not allowed",
                         "pattern": {"spec": {"containers": [
                             {"image": "!*:latest"}]}}},
        }],
    },
}


def pod(image, name="p"):
    return {"apiVersion": "v1", "kind": "Pod",
            "metadata": {"name": name, "namespace": "default"},
            "spec": {"containers": [{"name": "c", "image": image}]}}


def make_batcher(**kw):
    kw.setdefault("dispatch_cost_init_s", 0.0)
    kw.setdefault("oracle_cost_init_s", 1.0)
    kw.setdefault("cold_flush_fallback", False)
    kw.setdefault("result_cache_ttl_s", 0.0)
    cache = PolicyCache()
    cache.add(load_policy(ENFORCE))
    return AdmissionBatcher(cache, window_s=0.002, burst_threshold=1,
                            **kw), cache


# --------------------------------------------------------------- recorder


class TestRecorder:
    def test_span_recording_and_export(self):
        rec = tracing.TraceRecorder(ring_size=8)
        t = rec.start("admission", path="/validate")
        with rec.span(t, "flatten", lane="memo"):
            pass
        rec.add_span(t, "scatter", t.t_start, t.t_start + 0.001, row=0)
        rec.finish(t, allowed="True")
        [got] = rec.traces(1)
        assert got is t and t._finished
        d = t.to_dict()
        assert d["labels"]["allowed"] == "True"
        assert {s["name"] for s in d["spans"]} == {"flatten", "scatter"}
        # spans are reported relative to trace start, in t0 order
        assert [s["t0_us"] for s in d["spans"]] == sorted(
            s["t0_us"] for s in d["spans"])

    def test_lane_provenance_stamped_at_start(self, monkeypatch):
        rec = tracing.TraceRecorder()
        t = rec.start("flush")
        assert t.labels["lanes"] == "all-on"
        monkeypatch.setenv("KTPU_HOST_PREFETCH", "0")
        t2 = rec.start("flush")
        assert "host_prefetch=off" in t2.labels["lanes"]

    def test_kill_switch_disables_recording(self, monkeypatch):
        monkeypatch.setenv("KTPU_TRACE", "0")
        rec = tracing.TraceRecorder()
        assert rec.start("admission") is None
        # every instrumentation idiom tolerates the None trace
        with rec.span(None, "flatten") as s:
            assert s is None
        assert rec.add_span(None, "x", 0.0, 1.0) is None
        rec.finish(None)
        assert rec.traces() == []

    def test_ring_keeps_last_n(self):
        rec = tracing.TraceRecorder(ring_size=4, keep_slowest=2)
        for i in range(10):
            t = rec.start("admission", i=i)
            rec.finish(t)
        ring = rec.traces(10)
        assert len(ring) == 4
        # newest first
        assert [t.labels["i"] for t in ring] == [9, 8, 7, 6]

    def test_slowest_heap_keeps_k_slowest(self):
        rec = tracing.TraceRecorder(ring_size=2, keep_slowest=3)
        durations = [0.004, 0.001, 0.010, 0.002, 0.006, 0.003]
        for i, d in enumerate(durations):
            t = rec.start("admission", i=i)
            # synthesize the duration instead of sleeping
            t.t_start = time.perf_counter() - d
            rec.finish(t)
        kept = {t.labels["i"] for t in rec.slowest(10)}
        # the three slowest survive even though the ring holds only 2
        assert kept == {2, 4, 0}

    def test_max_spans_cap_counts_drops(self):
        rec = tracing.TraceRecorder(max_spans=4)
        t = rec.start("flush")
        for i in range(10):
            rec.add_span(t, f"s{i}", 0.0, 1.0)
        assert len(t.spans) == 4
        assert t.spans_dropped == 6

    def test_chrome_export_round_trips(self):
        rec = tracing.TraceRecorder()
        for i in range(3):
            t = rec.start("admission", i=i)
            with rec.span(t, "flatten"):
                pass
            with rec.span(t, "scatter"):
                pass
            rec.finish(t)
        blob = json.dumps(rec.chrome_trace(10))
        doc = json.loads(blob)
        events = doc["traceEvents"]
        assert len(events) == 3 * 3       # one trace event + two spans each
        for e in events:
            assert e["ph"] == "X"
            assert e["ts"] >= 0 and e["dur"] >= 0
        # per-trace (pid) the span timestamps are monotonic in emit order
        by_pid: dict = {}
        for e in events:
            if e["tid"] != 0:
                by_pid.setdefault(e["pid"], []).append(e["ts"])
        for ts in by_pid.values():
            assert ts == sorted(ts)

    def test_contextvar_binding(self):
        rec = tracing.TraceRecorder()
        t = rec.start("admission")
        assert tracing.current() is None or tracing.current() is not t
        with tracing.active(t):
            assert tracing.current() is t
            tok = tracing.bind(None)
            assert tracing.current() is None
            tracing.unbind(tok)
            assert tracing.current() is t

    def test_adopted_spans_counted_once(self):
        """A flush span adopted into many waiter traces must observe the
        stage histogram exactly once."""
        from kyverno_tpu.runtime import metrics as metrics_mod

        reg = metrics_mod.registry()
        rec = tracing.TraceRecorder()
        flush = rec.start("flush")
        rec.add_span(flush, "flatten", 0.0, 0.25)
        rec.finish(flush)
        rec.feed_metrics()

        key = frozenset({"stage": "flatten", "kind": "admission"}.items())

        def count():
            h = reg._histograms.get(
                "kyverno_stage_duration_seconds", {}).get(key)
            return h[0] if h else 0

        before = count()
        for _ in range(3):
            w = rec.start("admission")
            w.adopt_spans(flush.spans)
            rec.finish(w)
        rec.feed_metrics()
        # the flush already counted it under kind="flush"; the waiters
        # must not re-count the shared span at all
        assert count() == before


# ----------------------------------------------------- pipeline tracing


class TestPipelineTraces:
    def test_single_admission_trace_covers_stages(self):
        """Acceptance: one screened admission yields a retrievable trace
        covering flatten -> coalesce -> dispatch -> host-lane -> scatter
        with lane/cache provenance."""
        rec = tracing.recorder()
        rec.clear()
        batcher, _ = make_batcher()
        try:
            t = rec.start("admission", path="/validate")
            with tracing.active(t):
                status, _ = batcher.screen(
                    PolicyType.VALIDATE_ENFORCE, "Pod", "default",
                    pod("nginx:1.21"))
            rec.finish(t)
            assert status == CLEAN
            names = t.stage_names()
            assert {"coalesce_wait", "flatten",
                    "host_resolve", "scatter"} <= names
            assert ("device_dispatch" in names) or ("xla_compile" in names)
            by_name = {s.name: s for s in t.spans}
            assert by_name["coalesce_wait"].labels["lane"] in (
                "device", "fallback")
            assert by_name["flatten"].labels["lane"] in (
                "memo", "kill_switch")
            assert t.labels["lanes"] == "all-on"
        finally:
            batcher.stop()

    def test_concurrent_flushes_well_nested_spans(self):
        """Concurrent screens produce, per trace and per thread lane,
        well-nested spans: any two either disjoint or contained — never
        partially overlapping."""
        rec = tracing.recorder()
        rec.clear()
        batcher, _ = make_batcher()
        try:
            # warm the shape bucket so the burst takes the async path
            batcher.screen(PolicyType.VALIDATE_ENFORCE, "Pod", "default",
                           pod("warm:1"))
            traces = []
            lock = threading.Lock()

            def one(i):
                t = rec.start("admission", i=i)
                with tracing.active(t):
                    batcher.screen(PolicyType.VALIDATE_ENFORCE, "Pod",
                                   "default", pod(f"img:{i}", name=f"n{i}"))
                rec.finish(t)
                with lock:
                    traces.append(t)

            threads = [threading.Thread(target=one, args=(i,))
                       for i in range(8)]
            for th in threads:
                th.start()
            for th in threads:
                th.join()
            assert len(traces) == 8
            for t in traces:
                assert t.spans, f"trace {t.trace_id} recorded no spans"
                by_tid: dict = {}
                for s in t.spans:
                    by_tid.setdefault(s.tid, []).append(s)
                for spans in by_tid.values():
                    spans.sort(key=lambda s: (s.t0, -s.t1))
                    for a in range(len(spans)):
                        for b in range(a + 1, len(spans)):
                            sa, sb = spans[a], spans[b]
                            disjoint = sb.t0 >= sa.t1
                            nested = sb.t1 <= sa.t1
                            assert disjoint or nested, (
                                f"partial overlap {sa.name}/{sb.name}")
                # no orphan spans: every span inside the trace window
                for s in t.spans:
                    assert s.t0 >= t.t_start - 1e-6
                    assert s.t1 <= t.t_end + 1e-6
        finally:
            batcher.stop()

    def test_trace_off_bit_identical_verdicts(self, monkeypatch):
        resources = [pod(f"nginx:{i}", name=f"r{i}") for i in range(6)]
        resources += [pod("bad:latest", name="bad")]

        def run():
            batcher, _ = make_batcher()
            try:
                return [batcher.screen(PolicyType.VALIDATE_ENFORCE, "Pod",
                                       "default", r) for r in resources]
            finally:
                batcher.stop()

        on = run()
        monkeypatch.setenv("KTPU_TRACE", "0")
        off = run()
        assert on == off
        # and with tracing off, nothing new entered the global recorder
        rec = tracing.recorder()
        rec.clear()
        run()
        assert rec.traces(100) == []


# ---------------------------------------------------------- metrics/http


class TestHistogramBuckets:
    def test_cumulative_buckets_and_inf(self):
        reg = MetricsRegistry()
        reg.set_buckets("d", (0.1, 1.0, 10.0))
        for v in (0.05, 0.5, 0.5, 5.0, 50.0):
            reg.observe("d", {"stage": "s"}, v)
        exp = reg.expose()
        assert '# TYPE d histogram' in exp
        assert 'd_bucket{stage="s",le="0.1"} 1' in exp
        assert 'd_bucket{stage="s",le="1"} 3' in exp
        assert 'd_bucket{stage="s",le="10"} 4' in exp
        assert 'd_bucket{stage="s",le="+Inf"} 5' in exp
        assert 'd_count{stage="s"} 5' in exp
        assert 'd_sum{stage="s"}' in exp

    def test_value_on_bound_lands_in_that_bucket(self):
        reg = MetricsRegistry()
        reg.set_buckets("d", (1.0, 2.0))
        reg.observe("d", None, 1.0)
        assert 'd_bucket{le="1"} 1' in reg.expose()

    def test_count_sum_callers_unchanged(self):
        reg = MetricsRegistry()
        reg.observe("kyverno_admission_review_duration_seconds",
                    {"operation": "CREATE"}, 0.25)
        exp = reg.expose()
        assert ('kyverno_admission_review_duration_seconds_count'
                '{operation="CREATE"} 1') in exp
        assert ('kyverno_admission_review_duration_seconds_sum'
                '{operation="CREATE"} 0.25') in exp

    def test_quantile_from_buckets(self):
        reg = MetricsRegistry()
        reg.set_buckets("d", (1.0, 2.0, 4.0))
        for v in (0.5, 1.5, 1.5, 3.0):
            reg.observe("d", None, v)
        q = reg.histogram_quantile("d", 0.5)
        assert 1.0 <= q <= 2.0

    def test_label_escaping(self):
        reg = MetricsRegistry()
        reg.inc_counter("c", {"policy": 'we"ird\npol\\icy'})
        exp = reg.expose()
        assert r'policy="we\"ird\npol\\icy"' in exp
        # the rendered line must survive a strict line-format parse
        line = next(l for l in exp.splitlines() if l.startswith("c{"))
        assert line.endswith("} 1")

    def test_build_info_and_reset_gauges(self):
        reg = MetricsRegistry()
        exp = reg.expose()
        assert "kyverno_tpu_build_info{" in exp
        assert 'engine="jax"' in exp
        assert "kyverno_metrics_last_reset_timestamp_seconds" in exp
        reg.inc_counter("c", {})
        reg.reset()
        exp2 = reg.expose()
        assert "kyverno_tpu_build_info{" in exp2     # survives reset
        assert "kyverno_metrics_last_reset_timestamp_seconds" in exp2


class TestObsHttp:
    def test_routing(self):
        status, body, ctype = obs_http.handle_obs_get("/metrics")
        assert status == 200 and ctype.startswith("text/plain")
        assert b"kyverno_tpu_build_info" in body
        status, body, ctype = obs_http.handle_obs_get("/healthz")
        assert status == 200
        doc = json.loads(body)
        assert doc["status"] == "ok" and "lanes" in doc
        assert obs_http.handle_obs_get("/nope") is None

    def test_route_normalization(self):
        # duplicate and trailing slashes (reverse-proxy artifacts) must
        # land on the same route as the canonical path
        for path in ("//healthz", "/healthz/", "//healthz//",
                     "///healthz"):
            out = obs_http.handle_obs_get(path)
            assert out is not None, path
            status, body, _ = out
            assert status == 200 and json.loads(body)["status"] == "ok"
        for path in ("//metrics", "/metrics/", "//metrics//"):
            status, body, ctype = obs_http.handle_obs_get(path)
            assert status == 200 and ctype.startswith("text/plain")
        out = obs_http.handle_obs_get("//debug//traces?n=1")
        assert out is not None and out[0] == 200
        # normalization must not invent routes
        assert obs_http.handle_obs_get("/healthz/x") is None
        assert obs_http.handle_obs_get("/health//z") is None

    def test_lane_switches_include_stream_and_donate(self):
        lanes = tracing.killswitch_lanes()
        assert lanes.get("stream") == "on"
        assert lanes.get("donate") == "on"

    def test_debug_traces_params(self):
        rec = tracing.recorder()
        rec.clear()
        for i in range(5):
            t = rec.start("admission", i=i)
            rec.finish(t)
        _, body, _ = obs_http.handle_obs_get("/debug/traces?n=2")
        doc = json.loads(body)
        assert len(doc["traces"]) == 2
        _, body, _ = obs_http.handle_obs_get(
            "/debug/traces?n=3&format=chrome")
        doc = json.loads(body)
        assert "traceEvents" in doc
        _, body, _ = obs_http.handle_obs_get("/debug/traces?n=bogus")
        assert len(json.loads(body)["traces"]) == 5   # bad n -> default
