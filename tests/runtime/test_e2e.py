"""End-to-end: the assembled Controller over real HTTP, and deploy-manifest
sanity (the L9 tier of SURVEY.md section 4 — e2e without a kind cluster:
FakeCluster is the API server, the HTTP surface is real)."""

import json
import pathlib
import urllib.request

import yaml

from kyverno_tpu.runtime.client import FakeCluster
from kyverno_tpu.runtime.webhookconfig import VALIDATING_WEBHOOK_CONFIG
from kyverno_tpu.server import Controller

ENFORCE_POLICY = {
    "apiVersion": "kyverno.io/v1", "kind": "ClusterPolicy",
    "metadata": {"name": "disallow-latest-tag"},
    "spec": {
        "validationFailureAction": "enforce",
        "background": True,
        "rules": [{
            "name": "validate-image-tag",
            "match": {"resources": {"kinds": ["Pod"]}},
            "validate": {"message": "latest tag not allowed",
                         "pattern": {"spec": {"containers": [
                             {"image": "!*:latest"}]}}},
        }],
    },
}


def review(resource):
    return {"apiVersion": "admission.k8s.io/v1", "kind": "AdmissionReview",
            "request": {"uid": "u1", "kind": {"kind": "Pod"},
                        "namespace": "default", "operation": "CREATE",
                        "object": resource}}


def pod(image):
    return {"apiVersion": "v1", "kind": "Pod",
            "metadata": {"name": "p", "namespace": "default"},
            "spec": {"containers": [{"name": "c", "image": image}]}}


class TestControllerE2E:
    def test_full_lifecycle(self):
        cluster = FakeCluster([ENFORCE_POLICY, pod("nginx:latest")])
        controller = Controller(client=cluster, serve_port=0)
        controller.start(host="127.0.0.1")
        try:
            port = controller._httpd.server_address[1]

            def post(path, body):
                req = urllib.request.Request(
                    f"http://127.0.0.1:{port}{path}",
                    data=json.dumps(body).encode(),
                    headers={"Content-Type": "application/json"})
                # generous timeout: the suite may share the host with
                # other CPU-heavy work (observed flake at 5s under load)
                with urllib.request.urlopen(req, timeout=30) as resp:
                    return json.loads(resp.read())

            # enforce blocks over the wire
            out = post("/validate", review(pod("nginx:latest")))
            assert out["response"]["allowed"] is False
            out = post("/validate", review(pod("nginx:1.21")))
            assert out["response"]["allowed"] is True

            # leader tasks registered the webhooks (leader = only replica)
            controller.elector.try_acquire_or_renew()
            controller._start_leader_tasks()
            assert cluster.get_resource(
                "admissionregistration.k8s.io/v1",
                "ValidatingWebhookConfiguration", "",
                VALIDATING_WEBHOOK_CONFIG) is not None

            # background scan over the stored snapshot reports a violation
            result = controller.run_background_scan()
            assert result.violations >= 1
            reports = cluster.list_resource(
                "wgpolicyk8s.io/v1alpha2", "PolicyReport")
            assert reports and any(
                r["summary"]["fail"] >= 1 for r in reports)
        finally:
            controller.stop()


class TestDeployManifests:
    MANIFEST_DIR = pathlib.Path(__file__).resolve().parents[2] / "deploy"

    def _docs(self, name):
        with open(self.MANIFEST_DIR / name) as f:
            return [d for d in yaml.safe_load_all(f) if d]

    def test_crds_parse_and_cover_api_types(self):
        docs = self._docs("crds.yaml")
        kinds = {d["spec"]["names"]["kind"] for d in docs}
        assert kinds >= {"ClusterPolicy", "Policy", "GenerateRequest",
                         "PolicyReport", "ClusterPolicyReport",
                         "ReportChangeRequest"}
        for d in docs:
            assert d["kind"] == "CustomResourceDefinition"
            assert d["spec"]["versions"][0]["schema"]

    def test_install_wires_the_controller(self):
        docs = self._docs("install.yaml")
        by_kind = {}
        for d in docs:
            by_kind.setdefault(d["kind"], []).append(d)
        assert set(by_kind) >= {"Namespace", "ServiceAccount", "ClusterRole",
                                "ClusterRoleBinding", "ConfigMap", "Service",
                                "Deployment"}
        [dep] = by_kind["Deployment"]
        spec = dep["spec"]["template"]["spec"]
        assert spec["initContainers"][0]["command"][-1] == "--init-only"
        [ctr] = spec["containers"]
        ports = {p["name"]: p["containerPort"] for p in ctr["ports"]}
        assert ports == {"https": 9443, "metrics": 8000}
        # the webhook Service must target the serving port
        svc = next(s for s in by_kind["Service"]
                   if s["metadata"]["name"] == "kyverno-svc")
        assert svc["spec"]["ports"][0]["targetPort"] == 9443
        # SelfSubjectAccessReview permission present for CanI checks
        [role] = by_kind["ClusterRole"]
        assert any("selfsubjectaccessreviews" in r.get("resources", [])
                   for r in role["rules"])
