"""Full-process wiring: leader election, webhook registration, monitor,
init cleanup, end-to-end controller lifecycle against a FakeCluster."""

import json
import urllib.request

from kyverno_tpu.runtime.client import FakeCluster
from kyverno_tpu.runtime.leaderelection import LeaderElector
from kyverno_tpu.runtime.webhookconfig import (
    IDLE_DEADLINE_S,
    MUTATING_WEBHOOK_CONFIG,
    Monitor,
    Register,
)
from kyverno_tpu.server import Controller, init_cleanup

ENFORCE_POLICY = {
    "apiVersion": "kyverno.io/v1",
    "kind": "ClusterPolicy",
    "metadata": {"name": "disallow-latest-tag"},
    "spec": {
        "validationFailureAction": "enforce",
        "rules": [{
            "name": "validate-image-tag",
            "match": {"resources": {"kinds": ["Pod"]}},
            "validate": {
                "message": "latest tag not allowed",
                "pattern": {"spec": {"containers": [{"image": "!*:latest"}]}},
            },
        }],
    },
}


class TestRegisterAndMonitor:
    def test_register_check_remove(self):
        cluster = FakeCluster()
        register = Register(cluster)
        assert register.check() is False
        register.register()
        assert register.check() is True
        assert cluster.get_resource(
            "admissionregistration.k8s.io/v1", "MutatingWebhookConfiguration",
            "", MUTATING_WEBHOOK_CONFIG) is not None
        register.remove()
        assert register.check() is False

    def test_monitor_re_registers_after_idle_deadline(self):
        import time

        cluster = FakeCluster()
        register = Register(cluster)
        register.register()
        monitor = Monitor(register)
        monitor.set_time(time.monotonic() - IDLE_DEADLINE_S - 1)
        monitor.check_once()
        assert monitor.re_registrations == 1
        assert register.check() is True

    def test_monitor_restores_deleted_webhooks(self):
        cluster = FakeCluster()
        register = Register(cluster)
        register.register()
        register.remove()
        monitor = Monitor(register)
        monitor.check_once()
        assert register.check() is True


class TestLeaderElection:
    def test_single_leader(self):
        cluster = FakeCluster()
        a = LeaderElector(cluster, identity="a")
        b = LeaderElector(cluster, identity="b")
        assert a.try_acquire_or_renew() is True
        assert b.try_acquire_or_renew() is False
        assert a.is_leader() and not b.is_leader()

    def test_failover_after_release(self):
        cluster = FakeCluster()
        a = LeaderElector(cluster, identity="a")
        b = LeaderElector(cluster, identity="b")
        a.try_acquire_or_renew()
        a.stop()
        assert b.try_acquire_or_renew() is True

    def test_callbacks(self):
        cluster = FakeCluster()
        events = []
        a = LeaderElector(cluster, identity="a",
                          on_started_leading=lambda: events.append("start"))
        a.try_acquire_or_renew()
        assert events == ["start"]

    def test_cas_prevents_split_brain(self):
        """Two replicas observing the same expired lease must not both win:
        the loser's update carries a stale resourceVersion -> 409 -> lost
        election (client-go lease semantics)."""
        import copy

        cluster = FakeCluster()
        a = LeaderElector(cluster, identity="a")
        a.try_acquire_or_renew()
        # force expiry
        lease = cluster.get_resource(
            "coordination.k8s.io/v1", "Lease", "kyverno", "kyverno")
        lease["spec"]["renewTime"] = 0
        cluster.update_resource(lease)
        stale = cluster.get_resource(
            "coordination.k8s.io/v1", "Lease", "kyverno", "kyverno")

        class StaleFirstRead:
            """b's view: first get returns the pre-race snapshot."""

            def __init__(self, inner, snapshot):
                self._inner, self._snap, self._used = inner, snapshot, False

            def get_resource(self, *args):
                if not self._used:
                    self._used = True
                    return copy.deepcopy(self._snap)
                return self._inner.get_resource(*args)

            def __getattr__(self, name):
                return getattr(self._inner, name)

        b = LeaderElector(StaleFirstRead(cluster, stale), identity="b")
        # a renews first (wins the race, bumps the resourceVersion)...
        assert a.try_acquire_or_renew() is True
        # ...then b writes against its stale read -> conflict -> loses
        assert b.try_acquire_or_renew() is False
        assert a.is_leader() and not b.is_leader()
        holder = cluster.get_resource(
            "coordination.k8s.io/v1", "Lease", "kyverno", "kyverno"
        )["spec"]["holderIdentity"]
        assert holder == "a"


class TestControllerLifecycle:
    def test_end_to_end(self):
        cluster = FakeCluster([ENFORCE_POLICY])
        controller = Controller(client=cluster, serve_port=0)
        controller.start(host="127.0.0.1")
        try:
            assert controller.elector.is_leader()
            # leader registered the webhooks
            assert controller.register.check() is True

            port = controller.webhook._httpd.server_address[1]
            review = {
                "request": {
                    "uid": "u1",
                    "kind": {"kind": "Pod"},
                    "namespace": "default",
                    "operation": "CREATE",
                    "object": {
                        "apiVersion": "v1", "kind": "Pod",
                        "metadata": {"name": "p", "namespace": "default"},
                        "spec": {"containers": [{"name": "c", "image": "nginx:latest"}]},
                    },
                    "userInfo": {"username": "alice"},
                },
            }
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/validate",
                data=json.dumps(review).encode(),
                headers={"Content-Type": "application/json"},
            )
            with urllib.request.urlopen(req, timeout=5) as resp:
                out = json.loads(resp.read())
            assert out["response"]["allowed"] is False

            scan = controller.run_background_scan()
            assert scan.resources_scanned == 0  # no Pods stored in cluster
        finally:
            controller.stop()

    def test_init_cleanup(self):
        cluster = FakeCluster()
        register = Register(cluster)
        register.register()
        cluster.create_resource({
            "apiVersion": "kyverno.io/v1alpha2", "kind": "ReportChangeRequest",
            "metadata": {"name": "stale", "namespace": "kyverno"},
        })
        init_cleanup(cluster)
        assert register.check() is False
        assert cluster.list_resource("kyverno.io/v1alpha2", "ReportChangeRequest") == []
