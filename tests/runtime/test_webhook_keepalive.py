"""Webhook HTTP/1.1 keep-alive: connection reuse under concurrency.

The kube-apiserver holds webhook connections open and pipelines
admissions over them; these tests pin the transport contract — reused
connections serve multiple POSTs, responses carry a correct
Content-Length, and concurrent requests over distinct persistent
connections never bleed into each other's responses."""

import http.client
import json
import threading

from kyverno_tpu.api.load import load_policy
from kyverno_tpu.runtime.batch import AdmissionBatcher
from kyverno_tpu.runtime.client import FakeCluster
from kyverno_tpu.runtime.policycache import PolicyCache
from kyverno_tpu.runtime.webhook import (VALIDATING_WEBHOOK_PATH,
                                         WebhookServer)

ENFORCE = {
    "apiVersion": "kyverno.io/v1", "kind": "ClusterPolicy",
    "metadata": {"name": "disallow-latest-tag"},
    "spec": {
        "validationFailureAction": "enforce",
        "rules": [{
            "name": "validate-image-tag",
            "match": {"resources": {"kinds": ["Pod"]}},
            "validate": {"message": "latest tag not allowed",
                         "pattern": {"spec": {"containers": [
                             {"image": "!*:latest"}]}}},
        }],
    },
}


def review_body(image, uid):
    return json.dumps({
        "apiVersion": "admission.k8s.io/v1", "kind": "AdmissionReview",
        "request": {"uid": uid, "kind": {"kind": "Pod"},
                    "namespace": "default", "operation": "CREATE",
                    "object": {"apiVersion": "v1", "kind": "Pod",
                               "metadata": {"name": "p",
                                            "namespace": "default"},
                               "spec": {"containers": [
                                   {"name": "c", "image": image}]}}},
    }).encode()


def start_server():
    cache = PolicyCache()
    cache.add(load_policy(ENFORCE))
    batcher = AdmissionBatcher(cache, window_s=0.002, burst_threshold=1,
                               dispatch_cost_init_s=0.0,
                               oracle_cost_init_s=1.0,
                               cold_flush_fallback=False,
                               result_cache_ttl_s=0.0)
    server = WebhookServer(policy_cache=cache, client=FakeCluster(),
                           admission_batcher=batcher)
    httpd = server.run(host="127.0.0.1", port=0)
    port = httpd.server_address[1]
    return server, batcher, port


class TestKeepAlive:
    def test_connection_reuse_many_posts(self):
        server, batcher, port = start_server()
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
        try:
            for i in range(12):
                image = "nginx:latest" if i % 2 else "nginx:1.21"
                body = review_body(image, uid=f"reuse-{i}")
                conn.request("POST", VALIDATING_WEBHOOK_PATH, body,
                             {"Content-Type": "application/json"})
                resp = conn.getresponse()
                assert resp.status == 200
                # HTTP/1.1 reuse requires an exact Content-Length
                payload = resp.read()
                assert int(resp.headers["Content-Length"]) == len(payload)
                out = json.loads(payload)
                assert out["response"]["uid"] == f"reuse-{i}"
                assert out["response"]["allowed"] == (i % 2 == 0)
            # one TCP connection served all twelve
            assert conn.sock is not None
        finally:
            conn.close()
            server.stop()
            batcher.stop()

    def test_concurrent_connections_no_bleed(self):
        server, batcher, port = start_server()
        n_conns, n_reqs = 8, 6
        errors: list = []

        def worker(ci):
            conn = http.client.HTTPConnection("127.0.0.1", port,
                                              timeout=30)
            try:
                for ri in range(n_reqs):
                    uid = f"c{ci}-r{ri}"
                    deny = (ci + ri) % 2 == 1
                    image = "nginx:latest" if deny else "nginx:1.21"
                    conn.request("POST", VALIDATING_WEBHOOK_PATH,
                                 review_body(image, uid),
                                 {"Content-Type": "application/json"})
                    resp = conn.getresponse()
                    payload = resp.read()
                    out = json.loads(payload)
                    # the uid round-trips: a cross-request bleed would
                    # hand this connection another request's response
                    if out["response"]["uid"] != uid:
                        errors.append((uid, out["response"]["uid"]))
                    if out["response"]["allowed"] != (not deny):
                        errors.append((uid, "verdict", deny,
                                       out["response"]["allowed"]))
                    if int(resp.headers["Content-Length"]) != len(payload):
                        errors.append((uid, "content-length"))
            except Exception as exc:  # surface, don't hang the join
                errors.append((ci, repr(exc)))
            finally:
                conn.close()

        threads = [threading.Thread(target=worker, args=(ci,))
                   for ci in range(n_conns)]
        try:
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=60)
            assert not errors, errors[:5]
        finally:
            server.stop()
            batcher.stop()

    def test_obs_get_on_keepalive_connection(self):
        # GET (obs surface) and POST (admissions) interleave on one
        # persistent connection without desync
        server, batcher, port = start_server()
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
        try:
            for i in range(3):
                conn.request("POST", VALIDATING_WEBHOOK_PATH,
                             review_body("nginx:1.21", f"mix-{i}"),
                             {"Content-Type": "application/json"})
                resp = conn.getresponse()
                assert resp.status == 200
                assert (json.loads(resp.read())["response"]["uid"]
                        == f"mix-{i}")
                conn.request("GET", "//healthz")
                resp = conn.getresponse()
                body = resp.read()
                assert resp.status == 200
                assert json.loads(body)["status"] == "ok"
        finally:
            conn.close()
            server.stop()
            batcher.stop()
