"""Unit battery for runtime/sloactions: the hysteresis state machine,
action engagement diffs, shed ranking, the generation-guarded pool
circuit, and the guarded pool submission path — all on injected clocks
and synthetic policy/attribution state, no serving stack."""

import pytest

from kyverno_tpu.runtime import sloactions
from kyverno_tpu.runtime.sloactions import (POOL_TIMEOUT_DEFAULT_S,
                                            DegradationController,
                                            PoolCircuit, pool_evaluate)

DEG = {"degraded": True}
OK = {"degraded": False}


class Clock:
    def __init__(self, t: float = 100.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


@pytest.fixture
def armed(monkeypatch):
    """Master + all four rungs on, second-scale hysteresis."""
    for k, v in {"KTPU_SLO_ACTIONS": "1", "KTPU_SLO_SHED": "1",
                 "KTPU_SLO_GEOMETRY": "1", "KTPU_SLO_HOSTBOUND": "1",
                 "KTPU_SLO_SCALE_HINTS": "1",
                 "KTPU_SLO_DEGRADE_AFTER_S": "1.0",
                 "KTPU_SLO_RECOVER_AFTER_S": "2.0",
                 "KTPU_SLO_MIN_DWELL_S": "0.5"}.items():
        monkeypatch.setenv(k, v)
    yield monkeypatch


def _degrade(c: DegradationController, clk: Clock) -> None:
    """Drive a fresh controller into the degraded state."""
    c.tick(OK)
    c.tick(DEG)                      # streak starts
    clk.advance(1.2)                 # > degrade_after and > min dwell
    c.tick(DEG)
    assert c.state == "degraded"


class TestHysteresis:
    def test_degrade_needs_sustained_signal(self, armed):
        clk = Clock()
        c = DegradationController(clock=clk)
        c.tick(DEG)                  # first sighting: streak = 0
        assert c.state == "healthy"
        clk.advance(0.5)
        c.tick(DEG)                  # 0.5s < degrade_after 1.0
        assert c.state == "healthy"
        clk.advance(0.6)
        c.tick(DEG)                  # 1.1s sustained
        assert c.state == "degraded"
        assert c.stats["degraded_entered"] == 1

    def test_recover_slow(self, armed):
        clk = Clock()
        c = DegradationController(clock=clk)
        _degrade(c, clk)
        clk.advance(0.6)
        c.tick(OK)                   # healthy streak starts
        clk.advance(1.0)
        c.tick(OK)                   # 1.0s < recover_after 2.0
        assert c.state == "degraded"
        clk.advance(1.1)
        c.tick(OK)                   # 2.1s sustained
        assert c.state == "healthy"
        assert c.stats["recovered"] == 1

    def test_flap_suppressed_by_min_dwell(self, armed):
        armed.setenv("KTPU_SLO_RECOVER_AFTER_S", "0.0")
        armed.setenv("KTPU_SLO_MIN_DWELL_S", "5.0")
        clk = Clock()
        c = DegradationController(clock=clk)
        c.tick(OK)
        clk.advance(5.1)             # dwell applies to BOTH directions:
        c.tick(DEG)                  # serve it out healthy first
        clk.advance(1.2)
        c.tick(DEG)
        assert c.state == "degraded"
        clk.advance(1.0)
        c.tick(OK)                   # recover_after met, dwell not
        assert c.state == "degraded"
        clk.advance(4.5)             # dwell 5.5s > 5.0 now
        c.tick(OK)
        assert c.state == "healthy"

    def test_interrupted_streak_resets(self, armed):
        clk = Clock()
        c = DegradationController(clock=clk)
        c.tick(DEG)
        clk.advance(0.8)
        c.tick(OK)                   # signal clears mid-streak
        clk.advance(0.5)
        c.tick(DEG)                  # new streak from scratch
        assert c.state == "healthy"

    def test_state_seconds_accounted_in_both_states(self, armed):
        clk = Clock()
        c = DegradationController(clock=clk)
        c.tick(OK)
        clk.advance(2.0)
        _degrade(c, clk)
        clk.advance(3.0)
        c.tick(DEG)
        rep = c.report()
        assert rep["state_seconds"]["healthy"] > 0
        assert rep["state_seconds"]["degraded"] >= 3.0

    def test_idle_ticks_still_account(self, armed):
        """The slo_degraded_flushes evidence gap: time accrues on
        snapshotless ticks too, not just when a flush fires."""
        clk = Clock()
        c = DegradationController(clock=clk)
        _degrade(c, clk)
        for _ in range(5):
            clk.advance(0.5)
            c.tick(DEG)              # nothing flushing, still counted
        assert c.report()["state_seconds"]["degraded"] >= 2.5

    def test_transitions_carry_timestamps(self, armed):
        clk = Clock()
        c = DegradationController(clock=clk)
        _degrade(c, clk)
        clk.advance(0.6)
        c.tick(OK)
        clk.advance(2.1)
        c.tick(OK)
        states = [t["state"] for t in c.transitions]
        assert states == ["degraded", "healthy"]
        assert all("enter_t" in t for t in c.transitions)
        assert "exit_t" in c.transitions[0]


class TestActionEngagement:
    def test_ladder_engages_and_exits(self, armed):
        clk = Clock()
        c = DegradationController(clock=clk)
        assert c.active_actions() == []
        _degrade(c, clk)
        assert c.active_actions() == list(sloactions.ACTIONS)
        clk.advance(0.6)
        c.tick(OK)
        clk.advance(2.1)
        c.tick(OK)
        assert c.active_actions() == []
        entered = [e["action"] for e in c.action_log
                   if e["event"] == "enter"]
        exited = [e["action"] for e in c.action_log
                  if e["event"] == "exit"]
        assert entered == exited == list(sloactions.ACTIONS)
        assert all("t" in e for e in c.action_log)

    def test_per_action_switch_respected(self, armed):
        armed.setenv("KTPU_SLO_GEOMETRY", "0")
        clk = Clock()
        c = DegradationController(clock=clk)
        _degrade(c, clk)
        assert "geometry" not in c.active_actions()
        assert "shed" in c.active_actions()

    def test_master_kill_mid_episode(self, armed):
        clk = Clock()
        c = DegradationController(clock=clk)
        _degrade(c, clk)
        assert c.active_actions()
        armed.setenv("KTPU_SLO_ACTIONS", "0")
        # the gate is live: consults stop immediately, before any tick
        assert c.active_actions() == []
        clk.advance(0.1)
        c.tick(DEG)                  # next tick stands the ladder down
        assert not c._engaged
        assert [e["event"] for e in c.action_log[-4:]] == ["exit"] * 4

    def test_master_off_never_engages(self, armed):
        armed.setenv("KTPU_SLO_ACTIONS", "0")
        clk = Clock()
        c = DegradationController(clock=clk)
        _degrade(c, clk)             # state machine still runs...
        assert c.action_log == []    # ...but annotate-only: no actions
        assert c.report()["enabled"] is False


class _Spec:
    def __init__(self, action):
        self.validation_failure_action = action


class _Pol:
    def __init__(self, name, action="enforce"):
        self.name = name
        self.spec = _Spec(action)


class _Cache:
    def __init__(self, policies):
        self._policies = policies
        self.generation = 1

    def snapshot(self):
        return self.generation, list(self._policies)


class TestShed:
    def _controller(self, monkeypatch, policies, impact, sevs,
                    shed_max="2"):
        monkeypatch.setenv("KTPU_SLO_SHED_MAX", shed_max)
        monkeypatch.setattr(sloactions, "_attribution_impact",
                            lambda: impact)
        clk = Clock()
        c = DegradationController(clock=clk)
        monkeypatch.setattr(c, "_lint_severities",
                            lambda gen, pols: sevs)
        c.attach(_Cache(policies))
        return c, clk

    def test_least_impact_sheds_first(self, armed):
        pols = [_Pol("a"), _Pol("b"), _Pol("c")]
        c, clk = self._controller(
            armed, pols, impact={"a": 5, "b": 1, "c": 9}, sevs={})
        _degrade(c, clk)
        assert c.shed == ["b", "a"]  # capped at 2, impact ascending
        assert c.shed_active_names() == frozenset({"b", "a"})

    def test_error_severity_never_sheds(self, armed):
        pols = [_Pol("a"), _Pol("b")]
        c, clk = self._controller(
            armed, pols, impact={}, sevs={"a": 2})   # a is ERROR-flagged
        _degrade(c, clk)
        assert c.shed == ["b"]

    def test_audit_policies_not_candidates(self, armed):
        pols = [_Pol("a", action="audit"), _Pol("b")]
        c, clk = self._controller(armed, pols, impact={}, sevs={})
        _degrade(c, clk)
        assert c.shed == ["b"]       # audit never blocks, never sheds

    def test_generation_churn_recomputes(self, armed):
        pols = [_Pol("a"), _Pol("b")]
        c, clk = self._controller(
            armed, pols, impact={"a": 1, "b": 5}, sevs={})
        _degrade(c, clk)
        assert c.shed == ["a", "b"]
        before = c.stats["shed_recomputes"]
        c._policy_cache.generation = 2
        c._policy_cache._policies = [_Pol("b")]
        clk.advance(0.1)
        c.tick(DEG)
        assert c.stats["shed_recomputes"] == before + 1
        assert c.shed == ["b"]

    def test_shed_set_rides_log_entries(self, armed):
        pols = [_Pol("a")]
        c, clk = self._controller(armed, pols, impact={}, sevs={},
                                  shed_max="1")
        _degrade(c, clk)
        clk.advance(0.6)
        c.tick(OK)
        clk.advance(2.1)
        c.tick(OK)                   # recovered: shed cleared...
        assert c.shed == []
        logged = [e for e in c.action_log if e["action"] == "shed"]
        # ...but both the enter and the exit record what was shed
        assert all(e.get("shed") == ["a"] for e in logged)

    def test_empty_without_cache(self, armed):
        clk = Clock()
        c = DegradationController(clock=clk)
        _degrade(c, clk)
        assert c.shed == []
        assert c.shed_active_names() == frozenset()


class TestPoolCircuit:
    @pytest.fixture
    def breaker_env(self, armed):
        armed.setenv("KTPU_SLO_BREAKER_THRESHOLD", "2")
        armed.setenv("KTPU_SLO_BREAKER_COOLDOWN_S", "10.0")
        return armed

    def test_opens_on_threshold(self, breaker_env):
        clk = Clock()
        cb = PoolCircuit(clock=clk)
        assert cb.allow(1)
        cb.record(False, 1)
        assert cb.state == "closed"
        cb.record(False, 1)
        assert cb.state == "open"
        assert not cb.allow(1)
        assert cb.stats == {"opened": 1, "closed": 0, "probes": 0,
                            "rejected": 1, "failures": 2}

    def test_half_open_single_probe_then_close(self, breaker_env):
        clk = Clock()
        cb = PoolCircuit(clock=clk)
        cb.record(False, 1)
        cb.record(False, 1)
        clk.advance(10.1)
        assert cb.allow(1)           # cooldown expired: the probe
        assert cb.state == "half_open"
        assert not cb.allow(1)       # exactly one probe owns the lane
        cb.record(True, 1)
        assert cb.state == "closed"
        assert cb.allow(1)

    def test_half_open_failure_reopens(self, breaker_env):
        clk = Clock()
        cb = PoolCircuit(clock=clk)
        cb.record(False, 1)
        cb.record(False, 1)
        clk.advance(10.1)
        assert cb.allow(1)
        cb.record(False, 1)          # probe failed
        assert cb.state == "open"
        assert cb.stats["opened"] == 2

    def test_generation_change_probes_before_cooldown(self, breaker_env):
        clk = Clock()
        cb = PoolCircuit(clock=clk)
        cb.record(False, 1)
        cb.record(False, 1)
        assert not cb.allow(1)       # same generation: wait out cooldown
        assert cb.allow(2)           # rebuilt pool: immediate probe
        assert cb.state == "half_open"

    def test_stale_generation_probe_cannot_close(self, breaker_env):
        clk = Clock()
        cb = PoolCircuit(clock=clk)
        cb.record(False, 1)
        cb.record(False, 1)
        assert cb.allow(2)           # probing generation 2
        cb.record(True, 3)           # success against a *newer* pool
        assert cb.state == "half_open"   # proves nothing: stay probing
        cb.record(True, 2)           # the probed generation succeeds
        assert cb.state == "closed"

    def test_noop_when_disabled(self, monkeypatch):
        monkeypatch.setenv("KTPU_SLO_ACTIONS", "0")
        cb = PoolCircuit()
        for _ in range(10):
            cb.record(False, 1)
        assert cb.state == "closed"
        assert cb.allow(1)


class TestPoolEvaluate:
    @pytest.fixture(autouse=True)
    def fresh_singletons(self):
        sloactions.circuit().reset()
        sloactions.controller().reset()
        yield
        sloactions.circuit().reset()
        sloactions.controller().reset()

    def test_master_off_is_the_legacy_call(self, monkeypatch):
        monkeypatch.setenv("KTPU_SLO_ACTIONS", "0")
        calls = []

        def submit(timeout_s):
            calls.append(timeout_s)
            return None              # a miss must NOT retry when off

        assert pool_evaluate(None, 1, submit) is None
        assert calls == [POOL_TIMEOUT_DEFAULT_S]

    def test_miss_retries_with_backoff(self, armed):
        armed.setenv("KTPU_SLO_POOL_RETRIES", "1")
        calls = []

        def submit(timeout_s):
            calls.append(timeout_s)
            return ["hit"] if len(calls) == 2 else None

        assert pool_evaluate(None, 7, submit) == ["hit"]
        assert len(calls) == 2
        assert sloactions.circuit().state == "closed"

    def test_open_circuit_sheds_submission(self, armed):
        armed.setenv("KTPU_SLO_BREAKER_THRESHOLD", "1")
        armed.setenv("KTPU_SLO_BREAKER_COOLDOWN_S", "60.0")
        armed.setenv("KTPU_SLO_POOL_RETRIES", "0")
        assert pool_evaluate(None, 1, lambda t: None) is None
        assert sloactions.circuit().state == "open"
        calls = []
        assert pool_evaluate(None, 1,
                             lambda t: calls.append(t)) is None
        assert calls == []           # rejected without touching the pool

    def test_submit_exception_counts_as_miss(self, armed):
        armed.setenv("KTPU_SLO_POOL_RETRIES", "0")

        def submit(timeout_s):
            raise RuntimeError("worker died")

        assert pool_evaluate(None, 1, submit) is None
        assert sloactions.circuit().stats["failures"] == 1


class TestConsultSurfaces:
    @pytest.fixture
    def engaged(self, armed):
        clk = Clock()
        c = DegradationController(clock=clk)
        armed.setattr(sloactions, "_controller", c)
        _degrade(c, clk)
        return c

    def test_geometry_profile(self, armed, engaged):
        armed.setenv("KTPU_SLO_WINDOW_FACTOR", "0.25")
        armed.setenv("KTPU_SLO_PAD_FLOOR", "8")
        assert sloactions.window_scale() == 0.25
        assert sloactions.effective_pad_floor(64) == 8
        assert sloactions.effective_pad_floor(4) == 4   # never raises

    def test_geometry_identity_when_healthy(self, armed, monkeypatch):
        monkeypatch.setattr(sloactions, "_controller",
                            DegradationController(clock=Clock()))
        assert sloactions.window_scale() == 1.0
        assert sloactions.effective_pad_floor(64) == 64

    def test_fanout_bound(self, armed, engaged):
        armed.setenv("KTPU_SLO_FANOUT_MAX", "2")
        assert sloactions.fanout_bound() == 2
        armed.setenv("KTPU_SLO_HOSTBOUND", "0")
        assert sloactions.fanout_bound() is None

    def test_scale_hint_tracks_burn(self, armed, engaged):
        engaged.tick({"degraded": True,
                      "burn_rate": {"short": 2.3, "long": 1.1}})
        hint = engaged.scale_hint()
        assert hint["replicas_delta"] == 3    # ceil(2.3), clamped [1,4]

    def test_manifest_record_shape(self, armed, engaged):
        rec = engaged.manifest_record()
        assert rec["state"] == "degraded"
        assert rec["actions_active"] == list(sloactions.ACTIONS)
        assert set(rec["state_seconds"]) == {"healthy", "degraded"}
        assert rec["transitions"][-1]["state"] == "degraded"
