"""Fleet observability plane: attribution, propagation, SLO, profiling.

Covers the PR-8 layer: bounded per-policy attribution
(runtime/metrics.py record_policy_verdicts / record_policy_verdict_matrix
/ attribution_snapshot), W3C-style trace propagation
(runtime/tracing.py make_traceparent / parse_traceparent /
adopt_remote_id + the stream-frame carriage), the SLO watchdog
(runtime/slo.py), the /debug/policies and /debug/profile endpoints, the
report/event metric wiring, and the concurrent-scrape race against the
recorder's deferred settle.
"""

import json
import os
import threading

import numpy as np
import pytest

from kyverno_tpu.runtime import metrics as metrics_mod
from kyverno_tpu.runtime import obs_http, tracing
from kyverno_tpu.runtime.metrics import MetricsRegistry
from kyverno_tpu.runtime.slo import SLOWatchdog, watchdog


@pytest.fixture(autouse=True)
def _fresh_attrib_state():
    metrics_mod.attrib_state().reset()
    yield
    metrics_mod.attrib_state().reset()


class _Ref:
    def __init__(self, policy, rule):
        self.policy = type("P", (), {"name": policy})()
        self.rule = type("R", (), {"name": rule})()


# ------------------------------------------------------------ attribution


class TestAttribution:
    def test_topk_overflow_folds_to_other(self):
        os.environ["KTPU_ATTRIB_TOP_K"] = "2"
        try:
            reg = MetricsRegistry()
            for p in ("pa", "pb", "pc"):
                metrics_mod.record_policy_verdicts(
                    reg, [(p, "r", "FAIL", 2)], lane="flush")
            assert reg.counter_value(
                "kyverno_policy_verdicts_total",
                {"policy": "pa", "rule": "r", "verdict": "FAIL",
                 "lane": "flush"}) == 2
            assert reg.counter_value(
                "kyverno_policy_verdicts_total",
                {"policy": "__other__", "rule": "__other__",
                 "verdict": "FAIL", "lane": "flush"}) == 2
            snap = metrics_mod.attribution_snapshot()
            assert snap["labelled_pairs"] == 2
            assert snap["tracked_pairs"] == 3
            assert snap["other_cells"] == 2
            # exact totals survive for the suppressed pair
            assert snap["overflow"] == [
                {"policy": "pc", "rule": "r", "total": 2}]
        finally:
            os.environ.pop("KTPU_ATTRIB_TOP_K", None)

    def test_killswitch_noops(self):
        os.environ["KTPU_ATTRIB"] = "0"
        try:
            reg = MetricsRegistry()
            metrics_mod.record_policy_verdicts(
                reg, [("p", "r", "PASS", 1)], lane="flush")
            metrics_mod.record_policy_flush_latency(reg, {"p"}, 0.01)
            assert reg.series_count("kyverno_policy_verdicts_total") == 0
            assert metrics_mod.attribution_snapshot()["tracked_pairs"] == 0
        finally:
            os.environ.pop("KTPU_ATTRIB", None)

    def test_matrix_feed_vectorized(self):
        reg = MetricsRegistry()
        refs = [_Ref("p0", "r0"), _Ref("p1", "r1")]
        from kyverno_tpu.models.engine import Verdict

        v = np.array([[Verdict.PASS, Verdict.FAIL],
                      [Verdict.PASS, Verdict.PASS],
                      [Verdict.NOT_APPLICABLE, Verdict.FAIL]], dtype=np.int32)
        metrics_mod.record_policy_verdict_matrix(reg, refs, v, lane="scan")
        assert reg.counter_value(
            "kyverno_policy_verdicts_total",
            {"policy": "p0", "rule": "r0", "verdict": "PASS",
             "lane": "scan"}) == 2
        assert reg.counter_value(
            "kyverno_policy_verdicts_total",
            {"policy": "p1", "rule": "r1", "verdict": "FAIL",
             "lane": "scan"}) == 2

    def test_tenant_rollup_bounded(self):
        reg = MetricsRegistry()
        st = metrics_mod.attrib_state()
        for i in range(metrics_mod._MAX_TENANTS + 5):
            metrics_mod.record_policy_verdicts(
                reg, [("p", "r", "PASS", 1)], lane="flush",
                namespace=f"ns-{i}")
        assert len(st.tenants) <= metrics_mod._MAX_TENANTS + 1
        assert st.tenants[metrics_mod.ATTRIB_OTHER]["PASS"] == 5

    def test_flush_latency_histogram(self):
        reg = MetricsRegistry()
        metrics_mod.record_policy_verdicts(
            reg, [("p", "r", "PASS", 1)], lane="flush")
        for _ in range(10):
            metrics_mod.record_policy_flush_latency(reg, {"p"}, 0.002)
        q = reg.histogram_quantile("kyverno_policy_latency_seconds", 0.99,
                                   {"policy": "p"})
        assert q is not None and 0.0 < q <= 0.01

    def test_debug_policies_endpoint(self):
        reg = metrics_mod.registry()
        metrics_mod.record_policy_verdicts(
            reg, [("ep", "er", "PASS", 4)], lane="flush", namespace="nsx")
        status, body, ctype = obs_http.handle_obs_get("/debug/policies?n=5")
        assert status == 200 and ctype == "application/json"
        payload = json.loads(body)
        assert payload["attrib_enabled"] is True
        rows = {(r["policy"], r["rule"]): r for r in payload["policies"]}
        assert rows[("ep", "er")]["verdicts"]["PASS"] == 4
        assert payload["tenants"]["nsx"]["PASS"] == 4


# ------------------------------------------------------------ propagation


class TestPropagation:
    def test_roundtrip_native_id(self):
        rec = tracing.TraceRecorder(ring_size=8)
        t = rec.start("admission")
        tp = tracing.make_traceparent(t)
        assert tp is not None and tp.startswith("00-")
        assert tracing.parse_traceparent(tp) == t.trace_id
        rec.finish(t)

    def test_parse_rejects_malformed(self):
        assert tracing.parse_traceparent(None) is None
        assert tracing.parse_traceparent("") is None
        assert tracing.parse_traceparent("garbage") is None
        assert tracing.parse_traceparent("00-zz-11-01") is None
        assert tracing.parse_traceparent("00-" + "0" * 32
                                         + "-0000000000000000-01") is None

    def test_foreign_w3c_id_passthrough(self):
        foreign = "00-" + "ab" * 16 + "-00f067aa0ba902b7-01"
        assert tracing.parse_traceparent(foreign) == "ab" * 16

    def test_adopt_remote_id(self):
        rec = tracing.TraceRecorder(ring_size=8)
        a = rec.start("client")
        b = rec.start("server")
        assert tracing.adopt_remote_id(
            b, tracing.parse_traceparent(tracing.make_traceparent(a)))
        assert b.trace_id == a.trace_id
        assert b.labels.get("remote") == "1"
        rec.finish(a)
        rec.finish(b)

    def test_propagate_killswitch(self):
        rec = tracing.TraceRecorder(ring_size=8)
        t = rec.start("admission")
        os.environ["KTPU_PROPAGATE"] = "0"
        try:
            assert tracing.make_traceparent(t) is None
            assert not tracing.adopt_remote_id(t, "deadbeef")
        finally:
            os.environ.pop("KTPU_PROPAGATE", None)
        rec.finish(t)

    def test_frame_carriage(self):
        from kyverno_tpu.runtime import stream_server as ss

        tp = "00-" + "cd" * 16 + "-0000000000000007-01"
        p = ss.encode_payload(ss.F_ADMIT_JSON, 42, b"{}", traceparent=tp)
        ftype, req_id, body, got = ss.decode_payload_ex(p)
        assert (ftype, req_id, body, got) == (ss.F_ADMIT_JSON, 42, b"{}",
                                              tp)
        # legacy 3-tuple decode strips the context
        assert ss.decode_payload(p) == (ss.F_ADMIT_JSON, 42, b"{}")
        # frames without the bit decode unchanged; response/error frames
        # never grow a prefix even when a traceparent is passed
        plain = ss.encode_payload(ss.F_ADMIT_ROW, 7, b"x")
        assert ss.decode_payload_ex(plain) == (ss.F_ADMIT_ROW, 7, b"x",
                                               None)
        verdict = ss.encode_payload(ss.F_VERDICT, 9, b"v", traceparent=tp)
        assert ss.decode_payload_ex(verdict) == (ss.F_VERDICT, 9, b"v",
                                                 None)
        err = ss.encode_payload(ss.F_ERROR, 3, b"e")
        assert ss.decode_payload_ex(err) == (ss.F_ERROR, 3, b"e", None)


# -------------------------------------------------------------------- SLO


class TestSLOWatchdog:
    def test_degraded_needs_both_windows_and_min_samples(self):
        w = SLOWatchdog()
        os.environ["KTPU_SLO_BUDGET_S"] = "0.01"
        try:
            for _ in range(4):                # below min samples (8)
                w.observe(0.05)
            assert not w.snapshot()["degraded"]
            for _ in range(8):
                w.observe(0.05)
            snap = w.snapshot()
            assert snap["degraded"]
            assert snap["burn_rate"]["short"] >= 1.0
            assert snap["burn_rate"]["long"] >= 1.0
        finally:
            os.environ.pop("KTPU_SLO_BUDGET_S", None)

    def test_fast_admissions_stay_ok(self):
        w = SLOWatchdog()
        for _ in range(64):
            w.observe(0.001)
        snap = w.snapshot()
        assert not snap["degraded"]
        assert snap["burn_rate"]["short"] < 0.01

    def test_killswitch(self):
        w = SLOWatchdog()
        os.environ["KTPU_SLO"] = "0"
        try:
            w.observe(100.0)
            assert w.snapshot() == {"enabled": False, "degraded": False}
            assert w.stats["observed"] == 0
        finally:
            os.environ.pop("KTPU_SLO", None)

    def test_annotation_and_cache(self):
        w = SLOWatchdog()
        assert w.annotation() is None
        os.environ["KTPU_SLO_BUDGET_S"] = "0.001"
        try:
            for _ in range(16):
                w.observe(0.05)
            ann = w.annotation()
            assert ann is not None and ann["slo"] == "degraded"
            first = w.cached_snapshot(max_age_s=60.0)
            assert w.cached_snapshot(max_age_s=60.0) is first
        finally:
            os.environ.pop("KTPU_SLO_BUDGET_S", None)

    def test_gauges_exported(self):
        w = watchdog()
        w.clear()
        for _ in range(16):
            w.observe(0.002)
        w.snapshot()
        reg = metrics_mod.registry()
        assert reg.gauge_value("kyverno_slo_admission_p99_seconds",
                               {"window": "short"}) is not None
        assert reg.gauge_value("kyverno_slo_degraded") == 0.0
        assert reg.gauge_value("kyverno_slo_budget_seconds") == 10.0
        w.clear()

    def test_healthz_degraded_verdict(self):
        w = watchdog()
        w.clear()
        for _ in range(16):
            w.observe(0.05)
        os.environ["KTPU_SLO_BUDGET_S"] = "0.001"
        try:
            status, body, _ = obs_http.handle_obs_get("/healthz")
            health = json.loads(body)
            assert health["status"] == "degraded"
            assert health["slo"]["degraded"] is True
            assert "streams" in health and \
                "open_streams" in health["streams"]
        finally:
            os.environ.pop("KTPU_SLO_BUDGET_S", None)
            w.clear()


# -------------------------------------------------------------- profiling


class TestProfiling:
    def test_capture_single_flight(self):
        from kyverno_tpu.runtime.profiling import ProfileCaptureService

        svc = ProfileCaptureService()
        out = svc.start(0.05)
        assert out["status"] == "capturing"
        busy = svc.start(0.05)
        assert busy["status"] == "busy"
        # wait for the window to close
        import time

        deadline = time.monotonic() + 10.0
        while svc.status()["capturing"] and time.monotonic() < deadline:
            time.sleep(0.02)
        st = svc.status()
        assert not st["capturing"]
        assert st["last"]["log_dir"].startswith("/")

    def test_endpoint_routing(self):
        status, body, _ = obs_http.handle_obs_get("/debug/profile")
        assert status == 200
        payload = json.loads(body)
        assert payload["status"] == "idle"
        assert "device_memory" in payload
        status, body, _ = obs_http.handle_obs_get(
            "/debug/profile?seconds=abc")
        assert status == 400

    def test_device_memory_snapshot_never_raises(self):
        from kyverno_tpu.runtime.profiling import device_memory_snapshot

        out = device_memory_snapshot(update_metrics=False)
        assert isinstance(out, dict)


# ----------------------------------------------------- report/event wiring


class TestPipelineWiring:
    def test_report_queue_depth_gauges(self):
        from kyverno_tpu.runtime.reports import ReportGenerator

        gen = ReportGenerator(client=None)
        gen.add_change_request({"apiVersion": "kyverno.io/v1alpha2",
                                "kind": "ReportChangeRequest",
                                "metadata": {"name": "x"}, "results": []})
        reg = metrics_mod.registry()
        assert reg.gauge_value("kyverno_report_pending_results") >= 1
        assert reg.gauge_value("kyverno_report_queue_depth") == 0

    def test_event_counters(self):
        from kyverno_tpu.runtime.client import FakeCluster
        from kyverno_tpu.runtime.events import EventGenerator, EventInfo

        reg = metrics_mod.registry()
        before = reg.counter_total("kyverno_events_emitted_total")
        gen = EventGenerator(FakeCluster())
        gen.run()
        try:
            gen.add(EventInfo(kind="Pod", name="p", namespace="default",
                              reason="PolicyApplied", message="m"))
            gen.drain(5.0)
        finally:
            gen.stop()
        assert reg.counter_total("kyverno_events_emitted_total") \
            == before + 1


# -------------------------------------------------- concurrent scrape race


class TestScrapeRace:
    def test_concurrent_scrapes_vs_settle_and_admissions(self):
        """/metrics scrapes racing feed_metrics() and span production:
        counters stay monotone, no scrape errors, no lost spans, and
        adopted (shared flush) spans histogram exactly once."""
        rec = tracing.TraceRecorder(ring_size=4096)
        reg = metrics_mod.registry()       # feed_metrics settles here
        n_threads, n_traces = 4, 50
        before_flat = reg.histogram_count(
            "kyverno_stage_duration_seconds", {"stage": "flatten"})
        before_scat = reg.histogram_count(
            "kyverno_stage_duration_seconds", {"stage": "scatter"})
        errors: list = []

        def produce(k):
            try:
                for i in range(n_traces):
                    t = rec.start("admission", worker=str(k))
                    rec.add_span(t, "flatten", 0.0, 0.001)
                    sp = rec.add_span(t, "scatter", 0.001, 0.002)
                    # adopted spans (the shared-flush-span shape) must
                    # histogram once even when two traces carry them
                    t2 = rec.start("admission", worker=f"{k}-adopt")
                    if t2 is not None and sp is not None:
                        t2.adopt_spans([sp])
                    rec.finish(t)
                    rec.finish(t2)
            except Exception as exc:   # pragma: no cover
                errors.append(exc)

        def scrape():
            try:
                last = 0.0
                for _ in range(40):
                    rec.feed_metrics()
                    cur = reg.histogram_count(
                        "kyverno_stage_duration_seconds")
                    assert cur >= last, "counter went backwards"
                    last = cur
            except Exception as exc:   # pragma: no cover
                errors.append(exc)

        producers = [threading.Thread(target=produce, args=(k,))
                     for k in range(n_threads)]
        scrapers = [threading.Thread(target=scrape) for _ in range(2)]
        for th in producers + scrapers:
            th.start()
        for th in producers + scrapers:
            th.join()
        assert not errors
        rec.feed_metrics()
        # no lost spans: every started trace settled
        assert rec.stats["started"] == 2 * n_threads * n_traces
        assert rec.stats["finished"] == 2 * n_threads * n_traces
        # no double-count of adopted flush spans: one flatten + one
        # scatter observation per primary trace, exactly once each
        flat = reg.histogram_count(
            "kyverno_stage_duration_seconds", {"stage": "flatten"})
        scat = reg.histogram_count(
            "kyverno_stage_duration_seconds", {"stage": "scatter"})
        assert flat - before_flat == n_threads * n_traces
        assert scat - before_scat == n_threads * n_traces
