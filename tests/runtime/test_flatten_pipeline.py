"""Pipelined admission/scan dataflow: flatten-row memo, splice parity,
async dispatch, and the KTPU_FLATTEN_PIPELINE kill-switch.

The contract under test is bit-for-bit honesty: the pipelined dataflow
(memoized rows spliced into fresh batches, chunked flattens merged,
windows flattened during device flight) must produce verdicts identical
to the serial flatten-then-eval path, and the kill-switch must drop
every layer back to that serial path at once.
"""

import threading

import numpy as np
import pytest

from kyverno_tpu.api.load import load_policy
from kyverno_tpu.models import CompiledPolicySet, Verdict
from kyverno_tpu.models.flatten import (
    merge_packed,
    pipeline_enabled,
    split_packed_rows,
    splice_packed_rows,
)
from kyverno_tpu.runtime.batch import ATTENTION, CLEAN, AdmissionBatcher
from kyverno_tpu.runtime.policycache import PolicyCache, PolicyType
from kyverno_tpu.runtime.resourcecache import FlattenRowCache


def _policy(name="p", kinds=("Pod",), pattern=None):
    return load_policy({
        "apiVersion": "kyverno.io/v1", "kind": "ClusterPolicy",
        "metadata": {"name": name},
        "spec": {"validationFailureAction": "enforce", "rules": [{
            "name": "r",
            "match": {"resources": {"kinds": list(kinds)}},
            "validate": {"message": "m", "pattern": pattern or {
                "spec": {"containers": [{"image": "!*:latest"}]}}},
        }]},
    })


# mixed-shape policy set: string globs, numeric bounds, durations —
# exercises every dictionary value lane the splice OR-merge touches
POLICIES = [
    _policy("no-latest"),
    _policy("weight-cap", pattern={"spec": {"weight": "<=100"}}),
    _policy("grace", pattern={"spec": {"grace": "<1h"}}),
]


def _pod(i):
    return {"apiVersion": "v1", "kind": "Pod",
            "metadata": {"name": f"pod-{i}", "namespace": "default",
                         "labels": {"idx": str(i)}},
            "spec": {"containers": [{"name": "c",
                                     "image": ("nginx:latest" if i % 3 == 0
                                               else f"nginx:1.{i}")}],
                     "weight": (i * 7) % 160,
                     "frac": i + 0.5,
                     "grace": f"{(i * 13) % 400}s"}}


@pytest.fixture(scope="module")
def cps():
    return CompiledPolicySet(POLICIES)


class TestSplitSplice:
    def test_round_trip_is_bit_identical_per_row(self, cps):
        """split → splice of every row of one batch reproduces verdicts
        exactly, and unpadded content byte-for-byte."""
        docs = [_pod(i) for i in range(16)]
        batch = cps.flatten_packed(docs)
        rows = split_packed_rows(batch)
        assert len(rows) == 16
        spliced = splice_packed_rows(rows)
        v_direct = np.asarray(cps.evaluate_device(batch))
        v_spliced = np.asarray(cps.evaluate_device(spliced))
        assert np.array_equal(v_direct, v_spliced)

    def test_splice_across_batches(self, cps):
        """Rows memoized from DIFFERENT source batches splice into one
        batch whose verdicts match flattening those resources together —
        the actual memo-hit shape in _flatten_flush."""
        docs_a = [_pod(i) for i in range(0, 8)]
        docs_b = [_pod(i) for i in range(8, 16)]
        rows_a = split_packed_rows(cps.flatten_packed(docs_a))
        rows_b = split_packed_rows(cps.flatten_packed(docs_b))
        # interleave: hit, miss, hit, miss ...
        rows = [r for pair in zip(rows_a, rows_b) for r in pair]
        docs = [d for pair in zip(docs_a, docs_b) for d in pair]
        v_spliced = np.asarray(cps.evaluate_device(splice_packed_rows(rows)))
        v_direct = np.asarray(cps.evaluate_device(cps.flatten_packed(docs)))
        assert np.array_equal(v_direct, v_spliced)

    def test_merge_packed_matches_whole_batch_flatten(self, cps):
        """The chunked multi-worker flatten's merge: independently
        flattened chunks concatenate to the whole batch's verdicts."""
        docs = [_pod(i) for i in range(24)]
        chunks = [cps.flatten_packed(docs[i:i + 7])
                  for i in range(0, 24, 7)]
        merged = merge_packed(chunks)
        assert merged.n == 24
        v_merged = np.asarray(cps.evaluate_device(merged))
        v_direct = np.asarray(cps.evaluate_device(cps.flatten_packed(docs)))
        assert np.array_equal(v_direct, v_merged)

    def test_merge_single_chunk_is_identity(self, cps):
        batch = cps.flatten_packed([_pod(1), _pod(2)])
        assert merge_packed([batch]) is batch


class TestFlattenRowCache:
    def test_digest_canonicalizes_key_order(self):
        a = {"kind": "Pod", "spec": {"x": 1, "y": 2}}
        b = {"spec": {"y": 2, "x": 1}, "kind": "Pod"}
        assert FlattenRowCache.digest(a) == FlattenRowCache.digest(b)
        assert FlattenRowCache.digest(a) != FlattenRowCache.digest(
            {"kind": "Pod", "spec": {"x": 1, "y": 3}})

    def test_digest_unserializable_is_none_and_counts_miss(self):
        cache = FlattenRowCache()
        d = FlattenRowCache.digest({"spec": {"x": object()}})
        assert d is None
        assert cache.get("fp", d) is None
        assert cache.stats()["misses"] == 1
        cache.put("fp", None, "row")     # silently skipped
        assert len(cache) == 0

    def test_lru_eviction_and_counters(self):
        cache = FlattenRowCache(max_rows=4)
        digs = [FlattenRowCache.digest({"i": i}) for i in range(6)]
        for i in range(4):
            cache.put("fp", digs[i], f"row{i}")
        assert cache.get("fp", digs[0]) == "row0"    # refresh 0
        cache.put("fp", digs[4], "row4")             # evicts 1 (LRU)
        cache.put("fp", digs[5], "row5")             # evicts 2
        assert len(cache) == 4
        assert cache.get("fp", digs[1]) is None
        assert cache.get("fp", digs[2]) is None
        assert cache.get("fp", digs[0]) == "row0"
        s = cache.stats()
        assert s["hits"] == 2 and s["misses"] == 2

    def test_fingerprint_partitions_key_space(self):
        """Rows stored under one tensor-set fingerprint are invisible to
        another — the structural stale-row invalidation."""
        cache = FlattenRowCache()
        d = FlattenRowCache.digest({"kind": "Pod"})
        cache.put("fp-old", d, "old-row")
        assert cache.get("fp-new", d) is None
        assert cache.get("fp-old", d) == "old-row"


class TestFingerprint:
    def test_path_dictionary_changes_fingerprint(self):
        a = CompiledPolicySet([_policy("a", pattern={"spec": {"x": "<1"}})])
        b = CompiledPolicySet([_policy("a", pattern={"spec": {"y": "<1"}})])
        assert a.tensors.fingerprint != b.tensors.fingerprint

    def test_value_only_recompile_keeps_fingerprint(self):
        a = CompiledPolicySet([_policy("a", pattern={"spec": {"x": "<1"}})])
        b = CompiledPolicySet([_policy("a", pattern={"spec": {"x": "<9"}})])
        assert a.tensors.fingerprint == b.tensors.fingerprint


class TestFlattenerCacheBound:
    def test_cache_is_bounded_across_distinct_path_dicts(self):
        """Regression for the old mutable-default ``_cache={}``: compiling
        many policy sets with genuinely different path dictionaries must
        not grow the flattener-handle cache without bound."""
        import kyverno_tpu.models.native_flatten as nf

        with nf._flattener_lock:
            nf._flattener_cache.clear()
        sets = [CompiledPolicySet([_policy(
            "p", pattern={"spec": {f"field{i}": "<10"}})])
            for i in range(nf._FLATTENER_CACHE_CAP + 3)]
        for s in sets:
            nf._flattener_for(s.tensors)
        with nf._flattener_lock:
            assert len(nf._flattener_cache) <= nf._FLATTENER_CACHE_CAP

    def test_same_fingerprint_shares_one_handle(self):
        import kyverno_tpu.models.native_flatten as nf

        a = CompiledPolicySet([_policy("a", pattern={"spec": {"z": "<1"}})])
        b = CompiledPolicySet([_policy("a", pattern={"spec": {"z": "<5"}})])
        assert nf._flattener_for(a.tensors) is nf._flattener_for(b.tensors)


class TestEvaluatePipelined:
    def test_parity_with_serial_evaluate(self, cps):
        docs = [_pod(i) for i in range(300)]
        v_pipe = np.asarray(cps.evaluate_pipelined(docs, chunk=64))
        v_serial = np.concatenate([
            np.asarray(cps.evaluate(docs[i:i + 64]))
            for i in range(0, len(docs), 64)])
        assert np.array_equal(v_pipe, v_serial)

    def test_kill_switch_forces_serial_and_matches(self, cps, monkeypatch):
        docs = [_pod(i) for i in range(150)]
        v_on = np.asarray(cps.evaluate_pipelined(docs, chunk=64))
        monkeypatch.setenv("KTPU_FLATTEN_PIPELINE", "0")
        assert not pipeline_enabled()
        v_off = np.asarray(cps.evaluate_pipelined(docs, chunk=64))
        assert np.array_equal(v_on, v_off)

    def test_small_input_takes_direct_path(self, cps):
        docs = [_pod(i) for i in range(5)]
        v = np.asarray(cps.evaluate_pipelined(docs, chunk=64))
        assert np.array_equal(v, np.asarray(cps.evaluate(docs)))


class TestChunkedFlatten:
    def test_chunked_flatten_verdict_parity(self, cps, monkeypatch):
        from kyverno_tpu.models.native_flatten import flatten_packed_chunks

        # force multi-chunk even on single-core boxes — the point is the
        # merge, not the wall clock
        monkeypatch.setenv("KTPU_FLATTEN_WORKERS", "2")
        docs = [_pod(i) for i in range(700)]
        chunked = flatten_packed_chunks(cps.tensors, docs, chunk=256)
        direct = cps.flatten_packed(docs)
        assert chunked.n == direct.n
        v_a = np.asarray(cps.evaluate_device(chunked))
        v_b = np.asarray(cps.evaluate_device(direct))
        assert np.array_equal(v_a, v_b)


def _make_batcher(**kw):
    kw.setdefault("dispatch_cost_init_s", 0.0)
    kw.setdefault("oracle_cost_init_s", 1.0)
    kw.setdefault("cold_flush_fallback", False)
    kw.setdefault("result_cache_ttl_s", 0.0)
    cache = PolicyCache()
    # device-decidable policies only: a host-only rule would escalate
    # every screen to ATTENTION and mask the memo-path assertions
    for doc in POLICIES[:2]:
        cache.add(doc)
    return AdmissionBatcher(cache, window_s=0.002, burst_threshold=1,
                            **kw), cache


class TestBatcherPipeline:
    def test_memoized_screen_matches_first_screen(self):
        """Second screen of the same body is served through the row memo
        (hit counter moves) and returns the identical status + rows."""
        batcher, _ = _make_batcher()
        try:
            res = _pod(4)   # weight 28, grace 52s, image nginx:1.4 → CLEAN
            first = batcher.screen(PolicyType.VALIDATE_ENFORCE, "Pod",
                                   "default", res)
            second = batcher.screen(PolicyType.VALIDATE_ENFORCE, "Pod",
                                    "default", res)
            assert first == second
            assert first[0] == CLEAN
            with batcher._lock:
                hits = batcher.stats.get("flatten_cache_hit_rows", 0)
                misses = batcher.stats.get("flatten_cache_miss_rows", 0)
            assert hits >= 1
            assert misses >= 1
        finally:
            batcher.stop()

    def test_memoized_violation_still_flagged(self):
        batcher, _ = _make_batcher()
        try:
            res = _pod(3)   # nginx:latest → ATTENTION both times
            first = batcher.screen(PolicyType.VALIDATE_ENFORCE, "Pod",
                                   "default", res)
            second = batcher.screen(PolicyType.VALIDATE_ENFORCE, "Pod",
                                    "default", res)
            assert first[0] == ATTENTION and second[0] == ATTENTION
            assert first[1] == second[1]
        finally:
            batcher.stop()

    def test_kill_switch_screen_parity(self, monkeypatch):
        """With the pipeline off the batcher must fall back to the plain
        flatten + sync dispatch and still produce the same decisions."""
        monkeypatch.setenv("KTPU_FLATTEN_PIPELINE", "0")
        batcher, _ = _make_batcher()
        try:
            assert batcher.screen(PolicyType.VALIDATE_ENFORCE, "Pod",
                                  "default", _pod(4))[0] == CLEAN
            assert batcher.screen(PolicyType.VALIDATE_ENFORCE, "Pod",
                                  "default", _pod(3))[0] == ATTENTION
            with batcher._lock:
                assert "flatten_cache_hit_rows" not in batcher.stats
                assert "flatten_cache_miss_rows" not in batcher.stats
        finally:
            batcher.stop()

    def test_recompile_invalidates_memoized_rows(self):
        """Policy swap that MOVES the path dictionary: rows memoized under
        the old tensors must not splice into the new set's batches. The
        new policy flags what the old one cleared."""
        batcher, cache = _make_batcher()
        try:
            res = _pod(4)   # weight 28: clean under <=100
            assert batcher.screen(PolicyType.VALIDATE_ENFORCE, "Pod",
                                  "default", res)[0] == CLEAN
            strict = _policy("weight-floor",
                             pattern={"spec": {"weight": ">100",
                                               "tier": "gold"}})
            cache.add(strict)
            status, rows = batcher.screen(PolicyType.VALIDATE_ENFORCE,
                                          "Pod", "default", res)
            assert status == ATTENTION
            assert any(p == "weight-floor" and v != Verdict.PASS
                       for p, _, v, _ in rows)
        finally:
            batcher.stop()

    def test_warmup_seeds_memo_and_shapes(self):
        batcher, cache = _make_batcher()
        try:
            res = _pod(7)
            batcher.warmup(PolicyType.VALIDATE_ENFORCE, "Pod", "default",
                           res, batch_sizes=(1, 2))
            cps = cache.compiled(PolicyType.VALIDATE_ENFORCE, "Pod",
                                 "default")
            with batcher._lock:
                assert batcher._seen_shapes.get(cps)
            if pipeline_enabled():
                assert len(batcher._row_cache) >= 1
        finally:
            batcher.stop()


class TestScanPipeline:
    def test_background_scan_parity(self, monkeypatch):
        from kyverno_tpu.parallel.mesh import DEFAULT_CHUNK
        from kyverno_tpu.runtime.background import BackgroundScanner

        n = DEFAULT_CHUNK + 64    # force the chunked/pipelined branch
        resources = [_pod(i) for i in range(n)]
        pipe = BackgroundScanner(POLICIES).scan(resources)
        monkeypatch.setenv("KTPU_FLATTEN_PIPELINE", "0")
        serial = BackgroundScanner(POLICIES).scan(resources)
        assert pipe.resources_scanned == serial.resources_scanned == n
        assert pipe.rules_evaluated == serial.rules_evaluated
        assert pipe.violations == serial.violations
        pipe_rows = sorted(
            (r.policy_response.policy.name, r.policy_response.resource.name,
             tuple((x.name, x.status) for x in r.policy_response.rules))
            for r in pipe.responses)
        serial_rows = sorted(
            (r.policy_response.policy.name, r.policy_response.resource.name,
             tuple((x.name, x.status) for x in r.policy_response.rules))
            for r in serial.responses)
        assert pipe_rows == serial_rows
