"""Regression tests for the round-4 advisor findings (ADVICE.md r4).

Each test pins the exact failure mode the advisor described:

1. ``pool_safe`` must reject policies whose *foreach* entries carry
   context loads — workers have no cluster client, so such policies
   error in the pool and an enforce policy would deny admissions that
   pass inline.
2. ``ResourceCache._ensure_informer`` must not hold the cache lock while
   calling ``client.ensure_informer``: a WatchHub with an already-synced
   reflector replays ``on_sync`` synchronously, which re-acquires the
   same non-reentrant lock — a permanent deadlock of the admission
   thread.
3. ``RegistryClient.manifest`` must compute the digest from the manifest
   bytes, never trust the registry's Docker-Content-Digest header (a
   compromised registry could claim a signed digest for unsigned bytes).
4. A non-410 ERROR watch frame (e.g. a 500 Status) is a server-side
   failure, not a clean close: the reflector must back off and escalate
   to a re-list instead of hot-looping zero-delay reconnects.
"""

import threading
import time

from kyverno_tpu.api.load import load_policy
from kyverno_tpu.runtime.oracle_pool import pool_safe
from kyverno_tpu.runtime.resourcecache import ResourceCache
from kyverno_tpu.runtime.watch import Reflector


def _policy(rule_extra: dict) -> dict:
    rule = {
        "name": "r",
        "match": {"resources": {"kinds": ["Pod"]}},
        "validate": {"message": "m",
                     "pattern": {"spec": {"hostPID": "false"}}},
    }
    rule.update(rule_extra)
    return {
        "apiVersion": "kyverno.io/v1", "kind": "ClusterPolicy",
        "metadata": {"name": "p"},
        "spec": {"rules": [rule]},
    }


class TestPoolSafeForeachContext:
    def test_plain_policy_is_safe(self):
        assert pool_safe(load_policy(_policy({})))

    def test_rule_context_rejected(self):
        p = load_policy(_policy({"context": [{
            "name": "cm", "configMap": {"name": "c", "namespace": "d"}}]}))
        assert not pool_safe(p)

    def test_validate_foreach_context_rejected(self):
        p = load_policy(_policy({"validate": {"foreach": [{
            "list": "request.object.spec.containers",
            "context": [{"name": "cm",
                         "configMap": {"name": "c", "namespace": "d"}}],
            "pattern": {"image": "*:latest"},
        }]}}))
        assert not pool_safe(p)

    def test_mutate_foreach_context_rejected(self):
        p = load_policy(_policy({"validate": None, "mutate": {"foreach": [{
            "list": "request.object.spec.containers",
            "context": [{"name": "cm",
                         "configMap": {"name": "c", "namespace": "d"}}],
            "patchStrategicMerge": {"x": "y"},
        }]}}))
        assert not pool_safe(p)

    def test_contextless_foreach_stays_safe(self):
        p = load_policy(_policy({"validate": {"foreach": [{
            "list": "request.object.spec.containers",
            "pattern": {"image": "!*:latest"},
        }]}}))
        assert pool_safe(p)


class _SyncReplayClient:
    """ensure_informer replays on_sync synchronously — the WatchHub
    behavior when a synced reflector for the GVK already exists (another
    consumer, e.g. CrdSync, registered it first)."""

    def __init__(self, items):
        self.items = items

    def ensure_informer(self, api_version, kind, on_event=None, on_sync=None):
        if on_sync is not None:
            on_sync(self.items)          # synchronous replay

        class _Refl:
            @staticmethod
            def wait_synced(timeout_s=10.0):
                return True

        return _Refl()

    def get_resource(self, *a):          # pragma: no cover - not reached
        raise AssertionError("informer-synced lookup must not GET")


class TestEnsureInformerNoDeadlock:
    def test_synchronous_sync_replay_does_not_deadlock(self):
        ns = {"apiVersion": "v1", "kind": "Namespace",
              "metadata": {"name": "prod", "labels": {"env": "prod"}}}
        cache = ResourceCache(_SyncReplayClient([ns]))
        out = {}

        def lookup():
            out["labels"] = cache.get_namespace_labels("prod")

        t = threading.Thread(target=lookup, daemon=True)
        t.start()
        t.join(5.0)
        assert not t.is_alive(), "ensure_informer replay deadlocked the cache"
        assert out["labels"] == {"env": "prod"}


class _ErrorFrameClient:
    """list succeeds; every watch stream yields one non-410 ERROR frame."""

    def __init__(self):
        self.lists = 0
        self.watches = 0

    def list_response(self, api_version, kind, namespace):
        self.lists += 1
        return {"metadata": {"resourceVersion": str(self.lists)}, "items": []}

    def watch_stream(self, api_version, kind, namespace,
                     resource_version=None, stop=None):
        self.watches += 1
        yield "ERROR", {"kind": "Status", "code": 500,
                        "message": "etcdserver: leader changed"}


class TestNon410ErrorFrame:
    def test_error_frame_backs_off_and_relists(self):
        client = _ErrorFrameClient()
        refl = Reflector(client, "v1", "Pod",
                         backoff_base_s=0.005, backoff_cap_s=0.05,
                         max_watch_failures=2)
        refl.start()
        try:
            deadline = time.monotonic() + 5.0
            while time.monotonic() < deadline and client.lists < 2:
                time.sleep(0.01)
            # persistent 500s escalated to a re-list (not a hot loop that
            # never leaves the watch phase)
            assert client.lists >= 2
            # and the reconnects were bounded by backoff: in the elapsed
            # window a zero-delay hot loop would make thousands of watch
            # calls; the backed-off loop stays in the low tens
            assert client.watches < 200
        finally:
            refl.stop()


class TestLateJoinerReplayIsCurrent:
    def test_replay_includes_events_since_last_list(self):
        """A subscriber joining an already-synced shared reflector must be
        replayed the watch-maintained state (list + events since), not the
        stale last list — otherwise objects created after the list read
        back as confirmed absences in the late joiner."""
        from kyverno_tpu.runtime.watch import WatchHub

        class _Client:
            def __init__(self):
                self.stream_open = threading.Event()
                self.release = threading.Event()

            def list_response(self, api_version, kind, namespace):
                return {"metadata": {"resourceVersion": "1"},
                        "items": [{"metadata": {"name": "a"}}]}

            def watch_stream(self, api_version, kind, namespace,
                             resource_version=None, stop=None):
                yield "ADDED", {"metadata": {"name": "b",
                                             "resourceVersion": "2"}}
                self.stream_open.set()
                self.release.wait(5.0)

        client = _Client()
        hub = WatchHub(client)
        try:
            hub.ensure("v1", "Pod", on_sync=lambda items: None)
            assert client.stream_open.wait(5.0)
            seen = {}
            hub.ensure("v1", "Pod",
                       on_sync=lambda items: seen.setdefault(
                           "names", sorted((o.get("metadata") or {})["name"]
                                           for o in items)))
            # the replay carries BOTH the listed object and the one that
            # arrived via watch after the list
            assert seen.get("names") == ["a", "b"]
        finally:
            client.release.set()
            hub.stop()


class TestManifestDigestFromBytes:
    def test_lying_digest_header_rejected(self):
        import hashlib
        import json as _json

        from kyverno_tpu.engine.registry_verify import (
            RegistryClient, VerificationError)

        body = _json.dumps({"schemaVersion": 2, "layers": []}).encode()
        good = "sha256:" + hashlib.sha256(body).hexdigest()
        evil = "sha256:" + "0" * 64

        class _Client(RegistryClient):
            def __init__(self, header):
                super().__init__()
                self.header = header

            def _get(self, registry, path, accept=None, _retried=False):
                return body, {"Docker-Content-Digest": self.header}

        # honest header: digest comes back equal to the content hash
        _, digest = _Client(good).manifest("r.io", "a/b", "latest")
        assert digest == good
        # lying header: hard failure, never the claimed digest
        try:
            _Client(evil).manifest("r.io", "a/b", "latest")
        except VerificationError as e:
            assert "does not match" in str(e)
        else:
            raise AssertionError("lying Docker-Content-Digest accepted")
