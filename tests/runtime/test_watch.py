"""Streaming watch transport against a mock HTTP apiserver.

The mock speaks the real k8s watch protocol — list responses carrying
``metadata.resourceVersion``, chunked ``?watch=true`` streams of
newline-delimited JSON frames, resumable via resourceVersion, bookmarks,
and 410 Gone when the resume window is compacted away — so these tests
exercise the same transport a live deployment uses
(/root/reference/pkg/resourcecache/resourcecache.go:42 CreateGVKInformer
+ client-go reflector semantics)."""

import json
import threading
import time
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from kyverno_tpu.runtime.client import RestClient, RestConfig
from kyverno_tpu.runtime.resourcecache import ResourceCache

PLURALS = {"Namespace": "namespaces", "ConfigMap": "configmaps",
           "Pod": "pods"}


class MockAPIServer:
    """In-memory apiserver: CRUD + list + watch with event history."""

    def __init__(self):
        self.lock = threading.Condition()
        self.store = {}           # (plural, ns, name) -> obj
        self.rv = 0
        self.min_rv = 0           # events with rv <= min_rv are compacted
        self.events = []          # (rv, plural, frame_dict)
        self.list_count = 0
        self.get_count = 0
        self.watch_count = 0
        self.drop_generation = 0  # bump to close all open watch streams
        self.httpd = None

    # ------------------------------------------------------------ state

    def upsert(self, kind, obj, event=None):
        plural = PLURALS[kind]
        meta = obj.setdefault("metadata", {})
        key = (plural, meta.get("namespace", ""), meta.get("name", ""))
        with self.lock:
            self.rv += 1
            meta["resourceVersion"] = str(self.rv)
            ev = event or ("MODIFIED" if key in self.store else "ADDED")
            self.store[key] = obj
            self.events.append((self.rv, plural, {"type": ev, "object": obj}))
            self.lock.notify_all()
        return obj

    def delete(self, kind, namespace, name):
        plural = PLURALS[kind]
        with self.lock:
            obj = self.store.pop((plural, namespace or "", name), None)
            if obj is not None:
                self.rv += 1
                obj["metadata"]["resourceVersion"] = str(self.rv)
                self.events.append(
                    (self.rv, plural, {"type": "DELETED", "object": obj}))
                self.lock.notify_all()

    def bookmark(self, kind):
        plural = PLURALS[kind]
        with self.lock:
            self.rv += 1
            self.events.append((self.rv, plural, {
                "type": "BOOKMARK",
                "object": {"kind": kind,
                           "metadata": {"resourceVersion": str(self.rv)}}}))
            self.lock.notify_all()

    def compact(self):
        """Forget all event history (resume from any old rv -> 410)."""
        with self.lock:
            self.min_rv = self.rv
            self.events.clear()

    def drop_watches(self):
        with self.lock:
            self.drop_generation += 1
            self.lock.notify_all()

    def reset_counters(self):
        with self.lock:
            self.list_count = self.get_count = self.watch_count = 0

    # ---------------------------------------------------------- serving

    def start(self):
        server = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):
                pass

            def do_GET(self):
                parsed = urllib.parse.urlparse(self.path)
                q = urllib.parse.parse_qs(parsed.query)
                segs = [s for s in parsed.path.split("/") if s]
                # /api/v1/... (core) or /apis/{group}/{version}/...
                segs = segs[3:] if segs and segs[0] == "apis" else segs[2:]
                if len(segs) == 2 and segs[0] == "namespaces":
                    return self._get_one(("namespaces", "", segs[1]))
                if len(segs) == 4 and segs[0] == "namespaces":
                    return self._get_one((segs[2], segs[1], segs[3]))
                if len(segs) == 3 and segs[0] == "namespaces":
                    plural, ns = segs[2], segs[1]
                elif len(segs) == 1:
                    plural, ns = segs[0], ""
                else:
                    self.send_error(404)
                    return
                if q.get("watch", ["false"])[0] == "true":
                    return self._watch(plural, ns, q)
                return self._list(plural, ns)

            def _json(self, code, doc):
                body = json.dumps(doc).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _get_one(self, key):
                with server.lock:
                    server.get_count += 1
                    obj = server.store.get(key)
                if obj is None:
                    self.send_error(404)
                else:
                    self._json(200, obj)

            def _list(self, plural, ns):
                with server.lock:
                    server.list_count += 1
                    items = [o for (p, n, _), o in sorted(server.store.items())
                             if p == plural and (not ns or n == ns)]
                    rv = str(server.rv)
                self._json(200, {"kind": "List", "apiVersion": "v1",
                                 "metadata": {"resourceVersion": rv},
                                 "items": items})

            def _watch(self, plural, ns, q):
                since = int(q.get("resourceVersion", ["0"])[0] or 0)
                deadline = time.monotonic() + min(
                    30.0, float(q.get("timeoutSeconds", ["30"])[0]))
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Transfer-Encoding", "chunked")
                self.end_headers()

                def frame(doc):
                    body = (json.dumps(doc) + "\n").encode()
                    self.wfile.write(f"{len(body):x}\r\n".encode()
                                     + body + b"\r\n")
                    self.wfile.flush()

                with server.lock:
                    server.watch_count += 1
                    gen = server.drop_generation
                    if since and since < server.min_rv:
                        frame({"type": "ERROR", "object": {
                            "kind": "Status", "code": 410,
                            "reason": "Expired"}})
                        self.wfile.write(b"0\r\n\r\n")
                        return
                    cursor = 0
                    while time.monotonic() < deadline:
                        if server.drop_generation != gen:
                            break   # before draining: a dropped stream
                                    # must not deliver post-drop events
                        while cursor < len(server.events):
                            rv, p, f = server.events[cursor]
                            cursor += 1
                            if p == plural and rv > since:
                                ons = ((f["object"].get("metadata") or {})
                                       .get("namespace", ""))
                                if not ns or f["type"] == "BOOKMARK" \
                                        or ons == ns:
                                    server.lock.release()
                                    try:
                                        frame(f)
                                    finally:
                                        server.lock.acquire()
                        server.lock.wait(0.25)
                try:
                    self.wfile.write(b"0\r\n\r\n")
                except OSError:
                    pass

        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.httpd.daemon_threads = True
        threading.Thread(target=self.httpd.serve_forever, daemon=True).start()
        return f"http://127.0.0.1:{self.httpd.server_address[1]}"

    def stop(self):
        if self.httpd is not None:
            self.httpd.shutdown()
            self.httpd.server_close()


def _ns(name, labels=None):
    return {"apiVersion": "v1", "kind": "Namespace",
            "metadata": {"name": name, "labels": labels or {}}}


def _cm(ns, name, data):
    return {"apiVersion": "v1", "kind": "ConfigMap",
            "metadata": {"namespace": ns, "name": name}, "data": data}


def _wait(pred, timeout_s=5.0):
    end = time.monotonic() + timeout_s
    while time.monotonic() < end:
        if pred():
            return True
        time.sleep(0.02)
    return False


@pytest.fixture()
def api():
    server = MockAPIServer()
    url = server.start()
    client = RestClient(RestConfig(server=url))
    yield server, client
    client.stop_informers()
    server.stop()


class TestWatchTransport:
    def test_informer_sync_events_and_zero_polling(self, api):
        server, client = api
        server.upsert("Namespace", _ns("default", {"team": "a"}))
        server.upsert("ConfigMap", _cm("default", "ctx", {"k": "1"}))
        cache = ResourceCache(client)

        assert cache.get_namespace_labels("default") == {"team": "a"}
        assert cache.get_configmap("default", "ctx")["data"] == {"k": "1"}
        # confirmed absence without a GET: informer state is complete
        assert cache.get_namespace_labels("nope") == {}

        # live update flows through the watch stream
        server.upsert("Namespace", _ns("default", {"team": "b"}))
        assert _wait(lambda: cache.get_namespace_labels("default")
                     == {"team": "b"})
        server.delete("Namespace", "", "default")
        assert _wait(lambda: cache.get_namespace_labels("default") == {})

        # steady state: no polling GETs/LISTs at all
        server.reset_counters()
        for _ in range(200):
            cache.get_namespace_labels("default")
            cache.get_configmap("default", "ctx")
            cache.get_configmap("default", "missing")
        assert server.list_count == 0
        assert server.get_count == 0

    def test_resume_after_connection_drop(self, api):
        server, client = api
        server.upsert("Namespace", _ns("a"))
        cache = ResourceCache(client)
        assert cache.get("v1", "Namespace", "", "a") is not None
        refl = cache._informed[("v1", "Namespace")]
        assert _wait(lambda: server.watch_count >= 1)

        server.drop_watches()
        assert _wait(lambda: server.watch_count >= 2)   # reconnected
        server.upsert("Namespace", _ns("b", {"x": "1"}))
        # the reflector reconnects from its last rv and replays the missed
        # event — no re-list (syncs stays 1)
        assert _wait(lambda: cache.get_namespace_labels("b") == {"x": "1"})
        assert refl.syncs == 1
        assert refl.reconnects >= 1

    def test_410_gone_triggers_relist(self, api):
        server, client = api
        server.upsert("Namespace", _ns("a"))
        cache = ResourceCache(client)
        assert cache.get("v1", "Namespace", "", "a") is not None
        refl = cache._informed[("v1", "Namespace")]

        # compact history, mutate state, then kill the stream: the resume
        # rv is now ancient -> ERROR 410 -> full re-list
        server.upsert("Namespace", _ns("stale"))
        server.compact()
        server.delete("Namespace", "", "stale")
        server.upsert("Namespace", _ns("fresh"))
        server.compact()
        server.drop_watches()
        assert _wait(lambda: refl.syncs >= 2, timeout_s=10)
        assert _wait(lambda: cache.get("v1", "Namespace", "", "fresh")
                     is not None)
        # an object deleted during the outage must not survive the re-list
        assert cache.get("v1", "Namespace", "", "stale") is None

    def test_bookmark_advances_resume_point(self, api):
        server, client = api
        server.upsert("Namespace", _ns("a"))
        cache = ResourceCache(client)
        cache.get("v1", "Namespace", "", "a")
        refl = cache._informed[("v1", "Namespace")]
        before = int(refl.last_resource_version)
        server.bookmark("Namespace")
        assert _wait(
            lambda: int(refl.last_resource_version or 0) > before)

    def test_request_retry_on_transient_errors(self):
        """RestClient retries 503s with backoff (client-go default set)."""
        fails = {"n": 2}

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_GET(self):
                if fails["n"] > 0:
                    fails["n"] -= 1
                    self.send_error(503)
                    return
                body = json.dumps({"kind": "Namespace",
                                   "metadata": {"name": "x"}}).encode()
                self.send_response(200)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        threading.Thread(target=httpd.serve_forever, daemon=True).start()
        try:
            client = RestClient(RestConfig(
                server=f"http://127.0.0.1:{httpd.server_address[1]}"),
                retries=3, retry_backoff_s=0.01)
            out = client.get_resource("v1", "Namespace", "", "x")
            assert out == {"kind": "Namespace", "metadata": {"name": "x"}}
            assert fails["n"] == 0
        finally:
            httpd.shutdown()
            httpd.server_close()


class TestGenerateWatch:
    def test_generate_requests_flow_from_watch(self, api):
        """A pending GenerateRequest created on the apiserver reaches the
        controller's queue through the watch stream, no polling."""
        from kyverno_tpu.runtime.generate_controller import GenerateController

        server, client = api
        PLURALS["GenerateRequest"] = "generaterequests"
        ctrl = GenerateController(client, {})
        assert ctrl.watch_cluster()
        server.reset_counters()
        server.upsert("GenerateRequest", {
            "apiVersion": "kyverno.io/v1", "kind": "GenerateRequest",
            "metadata": {"namespace": "kyverno", "name": "gr1"},
            "spec": {"policy": "p", "resource": {}},
            "status": {"state": "Pending"},
        })
        assert _wait(lambda: ctrl.queue.qsize() >= 1
                     if hasattr(ctrl.queue, "qsize") else len(ctrl.queue) >= 1)
        assert server.get_count == 0


class TestCrdSyncOverWatch:
    def test_fresh_crd_schema_arrives_via_stream(self, api):
        """A CRD installed after startup reaches the schema store through
        the watch transport — no polling (crdSync.go over our reflector)."""
        from kyverno_tpu.policy.crd_sync import CrdSync
        from kyverno_tpu.policy.openapi import has_schema, unregister_schema
        from tests.unit.test_crd_sync import _crd

        server, client = api
        PLURALS["CustomResourceDefinition"] = "customresourcedefinitions"
        sync = CrdSync(client)
        try:
            sync.run()
            assert not has_schema("Gadget")
            server.reset_counters()
            server.upsert("CustomResourceDefinition", _crd())
            assert _wait(lambda: has_schema("Gadget"))
            assert server.get_count == 0
            server.delete("CustomResourceDefinition", "", "gadgets.acme.io")
            assert _wait(lambda: not has_schema("Gadget"))
        finally:
            sync.stop()
            unregister_schema("Gadget")
