"""Admission micro-batcher: device screen + oracle fallback lane."""

import threading

from kyverno_tpu.api.load import load_policy
from kyverno_tpu.models import Verdict
from kyverno_tpu.runtime.batch import ATTENTION, CLEAN, AdmissionBatcher
from kyverno_tpu.runtime.client import FakeCluster
from kyverno_tpu.runtime.policycache import PolicyCache, PolicyType
from kyverno_tpu.runtime.webhook import VALIDATING_WEBHOOK_PATH, WebhookServer

ENFORCE = {
    "apiVersion": "kyverno.io/v1", "kind": "ClusterPolicy",
    "metadata": {"name": "disallow-latest-tag"},
    "spec": {
        "validationFailureAction": "enforce",
        "rules": [{
            "name": "validate-image-tag",
            "match": {"resources": {"kinds": ["Pod"]}},
            "validate": {"message": "latest tag not allowed",
                         "pattern": {"spec": {"containers": [
                             {"image": "!*:latest"}]}}},
        }],
    },
}


def pod(image, name="p"):
    return {"apiVersion": "v1", "kind": "Pod",
            "metadata": {"name": name, "namespace": "default"},
            "spec": {"containers": [{"name": "c", "image": image}]}}


def review(resource):
    return {"apiVersion": "admission.k8s.io/v1", "kind": "AdmissionReview",
            "request": {"uid": "u", "kind": {"kind": "Pod"},
                        "namespace": "default", "operation": "CREATE",
                        "object": resource}}


def make_batcher(burst_threshold=1, **kw):
    # burst_threshold=1 + a cost model that always favors the device:
    # forces the screen lane so single-request tests exercise it; router
    # behavior is tested separately below
    kw.setdefault("dispatch_cost_init_s", 0.0)
    kw.setdefault("oracle_cost_init_s", 1.0)
    kw.setdefault("cold_flush_fallback", False)
    # cache behavior is tested explicitly in TestScreenResultCache;
    # everything else wants each screen to really reach the device
    kw.setdefault("result_cache_ttl_s", 0.0)
    cache = PolicyCache()
    cache.add(load_policy(ENFORCE))
    return AdmissionBatcher(cache, window_s=0.002,
                            burst_threshold=burst_threshold, **kw), cache


class TestBatcher:
    def test_clean_resource_screens_clean(self):
        batcher, _ = make_batcher()
        try:
            status, row = batcher.screen(
                PolicyType.VALIDATE_ENFORCE, "Pod", "default",
                pod("nginx:1.21"))
            assert status == CLEAN
            assert row == [("disallow-latest-tag", "validate-image-tag",
                            Verdict.PASS, "")]
        finally:
            batcher.stop()

    def test_violating_resource_needs_attention(self):
        batcher, _ = make_batcher()
        try:
            status, row = batcher.screen(
                PolicyType.VALIDATE_ENFORCE, "Pod", "default",
                pod("nginx:latest"))
            assert status == ATTENTION
            assert (("disallow-latest-tag", "validate-image-tag",
                     Verdict.FAIL, "") in row)
        finally:
            batcher.stop()

    def test_no_policies_is_clean(self):
        batcher = AdmissionBatcher(PolicyCache(), window_s=0.001,
                                   burst_threshold=1,
                                   dispatch_cost_init_s=0.0,
                                   oracle_cost_init_s=1.0,
                                   cold_flush_fallback=False)
        try:
            status, row = batcher.screen(
                PolicyType.VALIDATE_ENFORCE, "Pod", "default",
                pod("nginx:1.21"))
            assert (status, row) == (CLEAN, [])
        finally:
            batcher.stop()

    def test_concurrent_requests_share_one_device_eval(self):
        batcher, cache = make_batcher()
        cps = cache.compiled(PolicyType.VALIDATE_ENFORCE, "Pod", "default")
        evals = []
        orig = cps.evaluate_device

        def counting(batch):
            evals.append(batch.n)
            return orig(batch)

        cps.evaluate_device = counting
        try:
            results = [None] * 16
            barrier = threading.Barrier(16)

            def worker(i):
                barrier.wait()
                results[i] = batcher.screen(
                    PolicyType.VALIDATE_ENFORCE, "Pod", "default",
                    pod("nginx:1.21", name=f"p{i}"))

            threads = [threading.Thread(target=worker, args=(i,))
                       for i in range(16)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            assert all(s == CLEAN for s, _ in results)
            # 16 concurrent requests coalesced into very few device batches
            assert sum(evals) == 16
            assert len(evals) <= 4, evals
        finally:
            batcher.stop()


class TestLatencyRouter:
    """Low arrival rate -> ORACLE immediately; a burst -> device lane."""

    def test_lone_request_routes_to_oracle(self):
        from kyverno_tpu.runtime.batch import ORACLE

        batcher, cache = make_batcher(burst_threshold=4)
        cps = cache.compiled(PolicyType.VALIDATE_ENFORCE, "Pod", "default")
        evals = []
        orig = cps.evaluate_device
        cps.evaluate_device = lambda b: (evals.append(b.n), orig(b))[1]
        try:
            status, row = batcher.screen(
                PolicyType.VALIDATE_ENFORCE, "Pod", "default",
                pod("nginx:1.21"))
            assert status == ORACLE
            assert row == []
            assert evals == []          # the device was never touched
            assert batcher.stats["oracle"] == 1
        finally:
            batcher.stop()

    def test_burst_routes_to_device(self):
        batcher, cache = make_batcher(burst_threshold=4)
        cps = cache.compiled(PolicyType.VALIDATE_ENFORCE, "Pod", "default")
        evals = []
        orig = cps.evaluate_device
        cps.evaluate_device = lambda b: (evals.append(b.n), orig(b))[1]
        try:
            results = [None] * 16
            barrier = threading.Barrier(16)

            def worker(i):
                barrier.wait()
                results[i] = batcher.screen(
                    PolicyType.VALIDATE_ENFORCE, "Pod", "default",
                    pod("nginx:1.21", name=f"p{i}"))

            threads = [threading.Thread(target=worker, args=(i,))
                       for i in range(16)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            # the first arrivals below the threshold go oracle; once the
            # rate estimator sees the burst, the rest share device batches
            assert batcher.stats["device"] > 0
            assert batcher.stats["device"] + batcher.stats["oracle"] == 16
            # batches are bucket-padded, so eval rows >= routed items
            assert sum(evals) >= batcher.stats["device"]
            assert all(s in (CLEAN, "oracle") for s, _ in results)
        finally:
            batcher.stop()

    def test_straggler_joins_forming_batch(self):
        batcher, cache = make_batcher(burst_threshold=100)  # rate never trips
        cps = cache.compiled(PolicyType.VALIDATE_ENFORCE, "Pod", "default")
        key = (int(PolicyType.VALIDATE_ENFORCE), "Pod", "default", id(cps))
        from concurrent.futures import Future
        from kyverno_tpu.runtime.batch import _Bucket

        try:
            # simulate a batch already forming for this bucket
            with batcher._lock:
                bucket = batcher._buckets[key] = _Bucket(cps)
                bucket.items.append((pod("nginx:1.21", "seed"), None,
                                     Future()))
                batcher._lock.notify()
            status, _ = batcher.screen(
                PolicyType.VALIDATE_ENFORCE, "Pod", "default",
                pod("nginx:1.21", "straggler"))
            assert status == CLEAN  # joined the device batch, not oracle
        finally:
            batcher.stop()


class TestCostModel:
    def test_expensive_device_routes_oracle_and_probes(self):
        import time as _t

        batcher, _ = make_batcher(
            burst_threshold=1, dispatch_cost_init_s=10.0,
            oracle_cost_init_s=0.001, probe_interval_s=0.0)
        try:
            t0 = _t.perf_counter()
            status, row = batcher.screen(
                PolicyType.VALIDATE_ENFORCE, "Pod", "default",
                pod("nginx:1.21"))
            elapsed = _t.perf_counter() - t0
            from kyverno_tpu.runtime.batch import ORACLE

            assert status == ORACLE and row == []
            # the shadow probe fired but never blocked the request
            assert batcher.stats["probe"] == 1
            assert elapsed < 1.0
            deadline = _t.monotonic() + 10
            while not batcher._seen_shapes and _t.monotonic() < deadline:
                _t.sleep(0.01)
            assert batcher._seen_shapes  # the shadow flush really ran
        finally:
            batcher.stop()

    def test_flush_updates_dispatch_cost_ema(self):
        batcher, _ = make_batcher(burst_threshold=1)
        try:
            # first screen: compile flush (EMA untouched), second: measured
            for _ in range(2):
                batcher.screen(PolicyType.VALIDATE_ENFORCE, "Pod",
                               "default", pod("nginx:1.21"))
            assert batcher._dispatch_cost != 0.0  # EMA moved off the init
        finally:
            batcher.stop()

    def test_pad_to_buckets_verdict_parity(self):
        from kyverno_tpu.models import CompiledPolicySet
        from kyverno_tpu.models.flatten import pad_to_buckets

        cps = CompiledPolicySet([load_policy(ENFORCE)])
        resources = [pod("nginx:latest"), pod("nginx:1.21"), pod("a:b")]
        batch = cps.flatten(resources)
        padded, n = pad_to_buckets(batch)
        assert n == 3 and padded.n == 4
        v1 = cps.evaluate_device(batch)
        v2 = cps.evaluate_device(padded)
        assert (v1 == v2[:3]).all()


class TestWebhookScreenPath:
    def make_server(self, burst_threshold=1, **kw):
        # same cost-model forcing as make_batcher: without it the router
        # would send every test admission to the oracle and the screened
        # paths (_record_screen_results, hybrid merge) would lose coverage
        kw.setdefault("dispatch_cost_init_s", 0.0)
        kw.setdefault("oracle_cost_init_s", 1.0)
        kw.setdefault("cold_flush_fallback", False)
        cache = PolicyCache()
        cache.add(load_policy(ENFORCE))
        batcher = AdmissionBatcher(cache, window_s=0.002,
                                   burst_threshold=burst_threshold, **kw)
        server = WebhookServer(policy_cache=cache, client=FakeCluster(),
                               admission_batcher=batcher)
        return server, batcher

    def test_clean_pod_admitted_via_screen(self):
        server, batcher = self.make_server()
        try:
            out = server.handle(VALIDATING_WEBHOOK_PATH,
                                review(pod("nginx:1.21")))
            assert out["response"]["allowed"] is True
            # the screen recorded the PASS result in metrics
            assert "kyverno_policy_results_total" in server.registry.expose()
        finally:
            batcher.stop()

    def test_clean_pod_short_circuits_without_oracle(self):
        import kyverno_tpu.runtime.webhook as webhook_mod

        server, batcher = self.make_server()
        ran = []
        orig_validate = webhook_mod.engine_validate

        def counting(pctx):
            ran.append(pctx.policy.name)
            return orig_validate(pctx)

        webhook_mod.engine_validate = counting
        try:
            # pre-compile the screen kernel: a cold compile would blow
            # the screen deadline and (correctly) fall back to the oracle
            batcher.warmup(PolicyType.VALIDATE_ENFORCE, "Pod", "default",
                           pod("nginx:1.21"))
            out = server.handle(VALIDATING_WEBHOOK_PATH,
                                review(pod("nginx:1.21")))
            assert out["response"]["allowed"] is True
            # every rule PASSed on device: the decision is CLEAN without
            # any inline oracle run, and counted as device-decided
            assert ran == []
            assert batcher.stats.get("device_decided", 0) == 1
        finally:
            webhook_mod.engine_validate = orig_validate
            batcher.stop()

    def test_violating_pod_blocked_with_oracle_message(self):
        server, batcher = self.make_server()
        try:
            out = server.handle(VALIDATING_WEBHOOK_PATH,
                                review(pod("nginx:latest")))
            assert out["response"]["allowed"] is False
            # faithful message comes from the oracle lane
            assert "latest tag not allowed" in (
                out["response"]["status"]["message"])
        finally:
            batcher.stop()

    def test_hybrid_merge_runs_oracle_only_for_bad_policies(self):
        # two enforce policies: one passes on device, one fails — the
        # oracle must re-run only the failing one, and the passing one's
        # result must come from the screen row
        import kyverno_tpu.runtime.webhook as webhook_mod

        second = {
            "apiVersion": "kyverno.io/v1", "kind": "ClusterPolicy",
            "metadata": {"name": "require-name"},
            "spec": {
                "validationFailureAction": "enforce",
                "rules": [{
                    "name": "has-name",
                    "match": {"resources": {"kinds": ["Pod"]}},
                    "validate": {"message": "name required",
                                 "pattern": {"metadata": {"name": "?*"}}},
                }],
            },
        }
        cache = PolicyCache()
        cache.add(load_policy(ENFORCE))
        cache.add(load_policy(second))
        batcher = AdmissionBatcher(cache, window_s=0.002, burst_threshold=1,
                                   dispatch_cost_init_s=0.0,
                                   oracle_cost_init_s=1.0,
                                   cold_flush_fallback=False)
        server = WebhookServer(policy_cache=cache, client=FakeCluster(),
                               admission_batcher=batcher)
        ran = []
        orig_validate = webhook_mod.engine_validate

        def counting(pctx):
            ran.append(pctx.policy.name)
            return orig_validate(pctx)

        webhook_mod.engine_validate = counting
        try:
            out = server.handle(VALIDATING_WEBHOOK_PATH,
                                review(pod("nginx:latest")))
            assert out["response"]["allowed"] is False
            assert "latest tag not allowed" in (
                out["response"]["status"]["message"])
            # the failing rule's message is static, so the deny comes
            # straight from the device verdicts — NO oracle at all;
            # require-name was cleared by the screen row
            assert ran == []
            # ...and both results were still recorded
            exposed = server.registry.expose()
            assert "require-name" in exposed
            assert "disallow-latest-tag" in exposed
        finally:
            webhook_mod.engine_validate = orig_validate
            batcher.stop()

    @staticmethod
    def _varmsg_policy(message):
        return {
            "apiVersion": "kyverno.io/v1", "kind": "ClusterPolicy",
            "metadata": {"name": "varmsg-latest"},
            "spec": {
                "validationFailureAction": "enforce",
                "rules": [{
                    "name": "no-latest",
                    "match": {"resources": {"kinds": ["Pod"]}},
                    "validate": {
                        "message": message,
                        "pattern": {"spec": {"containers": [
                            {"image": "!*:latest"}]}},
                    },
                }],
            },
        }

    def _deny_with_counting_oracle(self, policy_doc):
        import kyverno_tpu.runtime.webhook as webhook_mod

        cache = PolicyCache()
        cache.add(load_policy(policy_doc))
        batcher = AdmissionBatcher(cache, window_s=0.002, burst_threshold=1,
                                   dispatch_cost_init_s=0.0,
                                   oracle_cost_init_s=1.0,
                                   cold_flush_fallback=False,
                                   result_cache_ttl_s=0.0)
        server = WebhookServer(policy_cache=cache, client=FakeCluster(),
                               admission_batcher=batcher)
        ran = []
        orig_validate = webhook_mod.engine_validate

        def counting(pctx):
            ran.append(pctx.policy.name)
            return orig_validate(pctx)

        webhook_mod.engine_validate = counting
        try:
            out = server.handle(VALIDATING_WEBHOOK_PATH,
                                review(pod("nginx:latest")))
            return out, ran, batcher
        finally:
            webhook_mod.engine_validate = orig_validate
            batcher.stop()

    def test_request_resolvable_variable_message_denies_device_side(self):
        # a failing rule whose {{variables}} all substitute from the
        # admission context (request.*, the resource) is denied straight
        # from the device row with the substituted text — no oracle
        out, ran, batcher = self._deny_with_counting_oracle(
            self._varmsg_policy(
                "{{ request.object.metadata.name }} uses latest"))
        assert out["response"]["allowed"] is False
        assert ran == []
        msg = out["response"]["status"]["message"]
        assert "{{" not in msg
        assert "p uses latest" in msg       # substituted, not template
        assert batcher.stats.get("device_deny", 0) == 1

    def test_cluster_state_variable_message_still_runs_oracle(self):
        # a message variable the admission context cannot resolve
        # (cluster state / unknown key) keeps the oracle authoritative
        out, ran, _ = self._deny_with_counting_oracle(
            self._varmsg_policy(
                "{{ request.userInfo.username }} not allowed"))
        assert out["response"]["allowed"] is False
        # review() carries no userInfo, so substitution fails -> oracle
        assert ran == ["varmsg-latest"]

    def test_oracle_routed_admission_still_correct(self):
        # production default: lone requests route to the CPU oracle; both
        # verdicts must be identical to the screened path
        server, batcher = self.make_server(burst_threshold=4)
        try:
            ok = server.handle(VALIDATING_WEBHOOK_PATH,
                               review(pod("nginx:1.21")))
            bad = server.handle(VALIDATING_WEBHOOK_PATH,
                                review(pod("nginx:latest")))
            assert ok["response"]["allowed"] is True
            assert bad["response"]["allowed"] is False
            assert batcher.stats["oracle"] >= 2
        finally:
            batcher.stop()


class TestCircuitBreaker:
    def test_screen_timeouts_open_circuit_and_inflate_cost(self):
        """Consecutive screen timeouts must (a) feed the dispatch-cost EMA
        the measured wait and (b) open the breaker so later requests take
        the oracle immediately instead of joining a failing lane."""
        import time

        from kyverno_tpu.runtime.batch import ORACLE

        batcher, _ = make_batcher(dispatch_cost_init_s=0.001)
        batcher.circuit_cooldown_s = 30.0
        cps = batcher.policy_cache.compiled(
            PolicyType.VALIDATE_ENFORCE, "Pod", "default")
        # mark the shape warm so the adaptive (short) timeout applies,
        # and make every flush hang past it
        batcher._seen_shapes[cps] = {(1, 1, 1)}
        batcher._flush = lambda *a, **k: time.sleep(0.4)
        try:
            with batcher.admission_in_flight(), batcher.admission_in_flight():
                for _ in range(batcher.circuit_timeout_threshold):
                    batcher.screen(PolicyType.VALIDATE_ENFORCE, "Pod",
                                   "default", pod("nginx:1.21"),
                                   timeout_s=0.05)
            assert batcher.stats.get("screen_timeout", 0) >= 3
            assert batcher._dispatch_cost >= 0.05
            assert batcher.stats.get("circuit_open", 0) >= 1
            # breaker open: the next request routes to the oracle without
            # enqueueing anything
            status, _ = batcher.screen(PolicyType.VALIDATE_ENFORCE, "Pod",
                                       "default", pod("nginx:1.21"))
            assert status == ORACLE
        finally:
            batcher.stop()


class TestScreenResultCache:
    def test_identical_resource_hits_cache(self):
        batcher, _ = make_batcher(result_cache_ttl_s=5.0)
        try:
            first = batcher.screen(PolicyType.VALIDATE_ENFORCE, "Pod",
                                   "default", pod("nginx:latest"))
            second = batcher.screen(PolicyType.VALIDATE_ENFORCE, "Pod",
                                    "default", pod("nginx:latest"))
            assert second == first
            assert batcher.stats.get("cache", 0) == 1
            # a different resource misses
            batcher.screen(PolicyType.VALIDATE_ENFORCE, "Pod",
                           "default", pod("nginx:1.21"))
            assert batcher.stats.get("cache", 0) == 1
        finally:
            batcher.stop()

    def test_cache_expires(self):
        import time

        batcher, _ = make_batcher(result_cache_ttl_s=0.05)
        try:
            batcher.screen(PolicyType.VALIDATE_ENFORCE, "Pod",
                           "default", pod("nginx:latest"))
            time.sleep(0.08)
            batcher.screen(PolicyType.VALIDATE_ENFORCE, "Pod",
                           "default", pod("nginx:latest"))
            assert batcher.stats.get("cache", 0) == 0
        finally:
            batcher.stop()

    def test_policy_change_rotates_cache_key(self):
        # a recompile changes the CompiledPolicySet identity, so stale
        # rows can never serve a new policy generation
        batcher, cache = make_batcher(result_cache_ttl_s=60.0)
        try:
            s1, _ = batcher.screen(PolicyType.VALIDATE_ENFORCE, "Pod",
                                   "default", pod("nginx:latest"))
            from kyverno_tpu.api.load import load_policy as _lp

            cache.add(_lp({
                "apiVersion": "kyverno.io/v1", "kind": "ClusterPolicy",
                "metadata": {"name": "second"},
                "spec": {"validationFailureAction": "enforce", "rules": [{
                    "name": "r2",
                    "match": {"resources": {"kinds": ["Pod"]}},
                    "validate": {"message": "m",
                                 "pattern": {"metadata": {"name": "?*"}}},
                }]},
            }))
            s2, row2 = batcher.screen(PolicyType.VALIDATE_ENFORCE, "Pod",
                                      "default", pod("nginx:latest"))
            assert batcher.stats.get("cache", 0) == 0   # no stale hit
            assert {t[0] for t in row2} >= {"second"}
        finally:
            batcher.stop()

    def test_request_identity_keys_the_cache(self):
        # same resource, different requester -> must not share a row
        # (oracle-lane outcomes can depend on userInfo/operation)
        batcher, _ = make_batcher(result_cache_ttl_s=60.0)
        try:
            batcher.screen(PolicyType.VALIDATE_ENFORCE, "Pod", "default",
                           pod("nginx:latest"),
                           env={"operation": "CREATE",
                                "userInfo": {"username": "alice"}})
            batcher.screen(PolicyType.VALIDATE_ENFORCE, "Pod", "default",
                           pod("nginx:latest"),
                           env={"operation": "CREATE",
                                "userInfo": {"username": "bob"}})
            assert batcher.stats.get("cache", 0) == 0
            # identical identity DOES hit
            batcher.screen(PolicyType.VALIDATE_ENFORCE, "Pod", "default",
                           pod("nginx:latest"),
                           env={"operation": "CREATE",
                                "userInfo": {"username": "alice"}})
            assert batcher.stats.get("cache", 0) == 1
        finally:
            batcher.stop()

    def test_oracle_lane_results_populate_cache(self):
        # a webhook admission that ran the ORACLE lane seeds the cache:
        # the repeat admission is served without any engine work
        import kyverno_tpu.runtime.webhook as webhook_mod

        cache = PolicyCache()
        cache.add(load_policy(ENFORCE))
        batcher = AdmissionBatcher(cache, window_s=0.002,
                                   burst_threshold=100,   # force ORACLE
                                   result_cache_ttl_s=60.0)
        server = WebhookServer(policy_cache=cache, client=FakeCluster(),
                               admission_batcher=batcher)
        ran = []
        orig_validate = webhook_mod.engine_validate

        def counting(pctx):
            ran.append(pctx.policy.name)
            return orig_validate(pctx)

        webhook_mod.engine_validate = counting
        try:
            out1 = server.handle(VALIDATING_WEBHOOK_PATH,
                                 review(pod("nginx:latest")))
            assert out1["response"]["allowed"] is False
            assert ran == ["disallow-latest-tag"]   # oracle ran once
            out2 = server.handle(VALIDATING_WEBHOOK_PATH,
                                 review(pod("nginx:latest")))
            assert out2["response"]["allowed"] is False
            # repeat was served from cache (the webhook's decision cache
            # sits above the screen-row cache) — no second oracle run
            assert ran == ["disallow-latest-tag"]
            assert batcher.stats.get("decision_cache", 0) == 1
            assert out2["response"]["status"]["message"] == (
                out1["response"]["status"]["message"])
        finally:
            webhook_mod.engine_validate = orig_validate
            batcher.stop()


class TestCoalescing:
    """Cross-request coalescing: concurrently-waiting DISTINCT admissions
    flush as one padded device batch, and each request's future resolves
    to ITS OWN verdict row."""

    def test_distinct_concurrent_admissions_share_one_flush(self):
        cache = PolicyCache()
        cache.add(load_policy(ENFORCE))
        # a window long enough that every worker enqueues before the
        # flush fires — the coalescing claim is exactly "one flush"
        batcher = AdmissionBatcher(cache, window_s=0.05, burst_threshold=1,
                                   dispatch_cost_init_s=0.0,
                                   oracle_cost_init_s=1.0,
                                   cold_flush_fallback=False,
                                   result_cache_ttl_s=0.0)
        try:
            n = 12
            pods = [pod("nginx:latest" if i % 3 == 0 else "nginx:1.21",
                        name=f"pod-{i}") for i in range(n)]
            # pay the cold XLA compile off the clock, for the EXACT shape
            # bucket this flush will hit (the dictionary dim depends on
            # batch content, so warmup with a repeated body compiles a
            # different bucket): a cold compile can exceed the screen
            # deadline and timeout the round
            cps = cache.compiled(PolicyType.VALIDATE_ENFORCE, "Pod",
                                 "default")
            warm, _ = batcher._pad_admission(cps.flatten_packed(pods))
            cps.evaluate_device(warm)
            evals = []
            orig = cps.evaluate_device
            cps.evaluate_device = lambda b: (evals.append(b.n), orig(b))[1]
            results = [None] * n
            barrier = threading.Barrier(n)

            def worker(i):
                barrier.wait()
                results[i] = batcher.screen(
                    PolicyType.VALIDATE_ENFORCE, "Pod", "default",
                    pods[i])

            threads = [threading.Thread(target=worker, args=(i,))
                       for i in range(n)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            # ONE coalesced device flush for all 12 waiting admissions
            # (padded up to the admission pad floor)
            assert evals == [16]
            for i, (status, row) in enumerate(results):
                if i % 3 == 0:
                    assert status == ATTENTION
                    assert ("disallow-latest-tag", "validate-image-tag",
                            Verdict.FAIL, "") in row
                else:
                    assert status == CLEAN
                    assert row == [("disallow-latest-tag",
                                    "validate-image-tag", Verdict.PASS, "")]
        finally:
            batcher.stop()

    def test_full_queue_flushes_before_window_elapses(self):
        # adaptive window: a queue at max_batch must not sit out the
        # remaining window
        import time as _t

        cache = PolicyCache()
        cache.add(load_policy(ENFORCE))
        batcher = AdmissionBatcher(cache, window_s=1.5, max_batch=8,
                                   burst_threshold=1,
                                   dispatch_cost_init_s=0.0,
                                   oracle_cost_init_s=1.0,
                                   cold_flush_fallback=False,
                                   result_cache_ttl_s=0.0)
        try:
            n = 8
            pods = [pod("nginx:1.21", name=f"pod-{i}") for i in range(n)]
            # compile the exact flush shape off the clock (see note above)
            cps = cache.compiled(PolicyType.VALIDATE_ENFORCE, "Pod",
                                 "default")
            warm, _ = batcher._pad_admission(cps.flatten_packed(pods))
            cps.evaluate_device(warm)
            results = [None] * n
            barrier = threading.Barrier(n)

            def worker(i):
                barrier.wait()
                results[i] = batcher.screen(
                    PolicyType.VALIDATE_ENFORCE, "Pod", "default",
                    pods[i])

            threads = [threading.Thread(target=worker, args=(i,))
                       for i in range(n)]
            t0 = _t.monotonic()
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            elapsed = _t.monotonic() - t0
            # the router may divert one request to the oracle lane as a
            # cost probe; everything screened must come back CLEAN
            statuses = [s for s, _ in results]
            assert statuses.count(CLEAN) >= n - 1
            assert elapsed < 1.0            # did not wait the 1.5s window
            assert batcher.stats.get("flush_early_full", 0) >= 1
        finally:
            batcher.stop()


class TestFlushInstrumentation:
    """Per-flush observability: verdict histogram, per-rule flag counts,
    escalation reasons — in batcher.stats AND the metrics registry."""

    def test_flush_stats_histogram_and_escalation_reasons(self):
        batcher, _ = make_batcher()
        try:
            batcher.screen(PolicyType.VALIDATE_ENFORCE, "Pod", "default",
                           pod("nginx:1.21"))
            batcher.screen(PolicyType.VALIDATE_ENFORCE, "Pod", "default",
                           pod("nginx:latest"))
            cells = batcher.stats.get("flush_cells", {})
            assert cells.get("PASS", 0) >= 1
            assert cells.get("FAIL", 0) >= 1
            assert batcher.stats.get("esc_clean", 0) >= 1
            assert batcher.stats.get("esc_device_fail", 0) >= 1
            flagged = batcher.stats.get("flagged_rules", {})
            assert flagged.get("validate-image-tag", 0) >= 1
        finally:
            batcher.stop()

    def test_flush_metrics_recorded_in_registry(self):
        from kyverno_tpu.runtime import metrics as metrics_mod

        batcher, _ = make_batcher()
        try:
            batcher.screen(PolicyType.VALIDATE_ENFORCE, "Pod", "default",
                           pod("nginx:latest"))
            exposed = metrics_mod.registry().expose()
            assert "kyverno_admission_flush_batch_size_count" in exposed
            assert "kyverno_admission_screen_escalations_total" in exposed
            assert 'reason="device_fail"' in exposed
        finally:
            batcher.stop()


class TestDecisionCacheReports:
    def test_cache_hit_reemits_report_rows_across_reconcile(self):
        """Regression (round-5 gap): a decision-cache hit skipped report
        emission, so a reconcile inside the hit window lost the
        resource's rows until the TTL expired. The hit must re-emit."""
        from kyverno_tpu.runtime.reports import ReportGenerator

        cache = PolicyCache()
        cache.add(load_policy(ENFORCE))
        batcher = AdmissionBatcher(cache, window_s=0.002,
                                   burst_threshold=100,   # force ORACLE
                                   result_cache_ttl_s=60.0)
        reports = ReportGenerator()
        server = WebhookServer(policy_cache=cache, client=FakeCluster(),
                               report_gen=reports,
                               admission_batcher=batcher)
        try:
            out1 = server.handle(VALIDATING_WEBHOOK_PATH,
                                 review(pod("nginx:latest")))
            assert out1["response"]["allowed"] is False

            def rows():
                return {(r["policy"], r["rule"], r["result"],
                         r.get("message", ""))
                        for rep in reports.aggregate()
                        for r in rep.get("results", [])}

            first = rows()
            assert any(p == "disallow-latest-tag"
                       and r == "validate-image-tag" and res == "fail"
                       and "latest tag not allowed" in msg
                       for p, r, res, msg in first)

            reports.reconcile()             # mid-hit-window rebuild
            assert rows() == set()          # state really was dropped

            out2 = server.handle(VALIDATING_WEBHOOK_PATH,
                                 review(pod("nginx:latest")))
            assert out2["response"]["allowed"] is False
            assert batcher.stats.get("decision_cache", 0) == 1
            # the cached decision re-emitted its rows — identical to the
            # oracle-produced first pass
            assert rows() == first
        finally:
            batcher.stop()


class TestAuditScreenPath:
    AUDIT_PASSING = {
        "apiVersion": "kyverno.io/v1", "kind": "ClusterPolicy",
        "metadata": {"name": "audit-no-host-pid"},
        "spec": {"validationFailureAction": "audit", "rules": [{
            "name": "no-host-pid",
            "match": {"resources": {"kinds": ["Pod"]}},
            "validate": {"message": "hostPID forbidden",
                         "pattern": {"spec": {"hostPID": "!true"}}},
        }]},
    }

    def _audit_rows(self, with_batcher: bool, n: int = 6):
        """Aggregate report rows after auditing n pods (half violating),
        through the device screen or the pure oracle."""
        import json as _json

        from kyverno_tpu.runtime.reports import ReportGenerator

        audit_latest = _json.loads(_json.dumps(ENFORCE))
        audit_latest["spec"]["validationFailureAction"] = "audit"
        cache = PolicyCache()
        cache.add(load_policy(audit_latest))
        cache.add(load_policy(self.AUDIT_PASSING))
        reports = ReportGenerator()
        batcher = None
        if with_batcher:
            batcher = AdmissionBatcher(cache, window_s=0.002,
                                       burst_threshold=1,
                                       dispatch_cost_init_s=0.0,
                                       oracle_cost_init_s=1.0,
                                       cold_flush_fallback=False,
                                       result_cache_ttl_s=0.0)
        server = WebhookServer(policy_cache=cache, report_gen=reports,
                               admission_batcher=batcher)
        try:
            for i in range(n):
                image = "nginx:latest" if i % 2 else "nginx:1.21"
                server._process_audit({
                    "uid": "u", "kind": {"kind": "Pod"},
                    "namespace": "default", "operation": "CREATE",
                    "object": pod(image, name=f"p{i}")})
            rows = set()
            for rep in reports.aggregate():
                for r in rep.get("results", []):
                    res = (r.get("resources") or [{}])[0]
                    rows.add((r["policy"], r["rule"], r["result"],
                              res.get("name"), r.get("message", "")))
            if with_batcher:
                assert batcher.stats["device"] > 0      # screen engaged
            return rows
        finally:
            if batcher is not None:
                batcher.stop()

    def test_screened_audit_report_rows_identical_to_oracle(self):
        """VERDICT round-5 'done': device-screened audit must produce
        report rows identical to the per-request oracle — policy, rule,
        result, resource, AND message."""
        want = self._audit_rows(with_batcher=False)
        got = self._audit_rows(with_batcher=True)
        assert want and got == want
