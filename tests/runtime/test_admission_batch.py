"""Admission micro-batcher: device screen + oracle fallback lane."""

import threading

from kyverno_tpu.api.load import load_policy
from kyverno_tpu.models import Verdict
from kyverno_tpu.runtime.batch import ATTENTION, CLEAN, AdmissionBatcher
from kyverno_tpu.runtime.client import FakeCluster
from kyverno_tpu.runtime.policycache import PolicyCache, PolicyType
from kyverno_tpu.runtime.webhook import VALIDATING_WEBHOOK_PATH, WebhookServer

ENFORCE = {
    "apiVersion": "kyverno.io/v1", "kind": "ClusterPolicy",
    "metadata": {"name": "disallow-latest-tag"},
    "spec": {
        "validationFailureAction": "enforce",
        "rules": [{
            "name": "validate-image-tag",
            "match": {"resources": {"kinds": ["Pod"]}},
            "validate": {"message": "latest tag not allowed",
                         "pattern": {"spec": {"containers": [
                             {"image": "!*:latest"}]}}},
        }],
    },
}


def pod(image, name="p"):
    return {"apiVersion": "v1", "kind": "Pod",
            "metadata": {"name": name, "namespace": "default"},
            "spec": {"containers": [{"name": "c", "image": image}]}}


def review(resource):
    return {"apiVersion": "admission.k8s.io/v1", "kind": "AdmissionReview",
            "request": {"uid": "u", "kind": {"kind": "Pod"},
                        "namespace": "default", "operation": "CREATE",
                        "object": resource}}


def make_batcher():
    cache = PolicyCache()
    cache.add(load_policy(ENFORCE))
    return AdmissionBatcher(cache, window_s=0.002), cache


class TestBatcher:
    def test_clean_resource_screens_clean(self):
        batcher, _ = make_batcher()
        try:
            status, row = batcher.screen(
                PolicyType.VALIDATE_ENFORCE, "Pod", "default",
                pod("nginx:1.21"))
            assert status == CLEAN
            assert row == [("disallow-latest-tag", "validate-image-tag",
                            Verdict.PASS)]
        finally:
            batcher.stop()

    def test_violating_resource_needs_attention(self):
        batcher, _ = make_batcher()
        try:
            status, row = batcher.screen(
                PolicyType.VALIDATE_ENFORCE, "Pod", "default",
                pod("nginx:latest"))
            assert status == ATTENTION
            assert (("disallow-latest-tag", "validate-image-tag",
                     Verdict.FAIL) in row)
        finally:
            batcher.stop()

    def test_no_policies_is_clean(self):
        batcher = AdmissionBatcher(PolicyCache(), window_s=0.001)
        try:
            status, row = batcher.screen(
                PolicyType.VALIDATE_ENFORCE, "Pod", "default",
                pod("nginx:1.21"))
            assert (status, row) == (CLEAN, [])
        finally:
            batcher.stop()

    def test_concurrent_requests_share_one_device_eval(self):
        batcher, cache = make_batcher()
        cps = cache.compiled(PolicyType.VALIDATE_ENFORCE, "Pod", "default")
        evals = []
        orig = cps.evaluate_device

        def counting(batch):
            evals.append(batch.n)
            return orig(batch)

        cps.evaluate_device = counting
        try:
            results = [None] * 16
            barrier = threading.Barrier(16)

            def worker(i):
                barrier.wait()
                results[i] = batcher.screen(
                    PolicyType.VALIDATE_ENFORCE, "Pod", "default",
                    pod("nginx:1.21", name=f"p{i}"))

            threads = [threading.Thread(target=worker, args=(i,))
                       for i in range(16)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            assert all(s == CLEAN for s, _ in results)
            # 16 concurrent requests coalesced into very few device batches
            assert sum(evals) == 16
            assert len(evals) <= 4, evals
        finally:
            batcher.stop()


class TestWebhookScreenPath:
    def make_server(self):
        cache = PolicyCache()
        cache.add(load_policy(ENFORCE))
        batcher = AdmissionBatcher(cache, window_s=0.002)
        server = WebhookServer(policy_cache=cache, client=FakeCluster(),
                               admission_batcher=batcher)
        return server, batcher

    def test_clean_pod_admitted_via_screen(self):
        server, batcher = self.make_server()
        try:
            out = server.handle(VALIDATING_WEBHOOK_PATH,
                                review(pod("nginx:1.21")))
            assert out["response"]["allowed"] is True
            # the screen recorded the PASS result in metrics
            assert "kyverno_policy_results_total" in server.registry.expose()
        finally:
            batcher.stop()

    def test_violating_pod_blocked_with_oracle_message(self):
        server, batcher = self.make_server()
        try:
            out = server.handle(VALIDATING_WEBHOOK_PATH,
                                review(pod("nginx:latest")))
            assert out["response"]["allowed"] is False
            # faithful message comes from the oracle lane
            assert "latest tag not allowed" in (
                out["response"]["status"]["message"])
        finally:
            batcher.stop()
