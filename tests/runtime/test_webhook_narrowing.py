"""Dynamic per-policy webhook narrowing (configmanager.go:455-757) and
policy-change reconciliation (policy_controller.go:541-573)."""

from kyverno_tpu.api.load import load_policy
from kyverno_tpu.runtime.client import FakeCluster
from kyverno_tpu.runtime.policycache import PolicyCache
from kyverno_tpu.runtime.webhookconfig import (
    MUTATING_WEBHOOK_CONFIG,
    VALIDATING_WEBHOOK_CONFIG,
    Register,
    WebhookConfigManager,
    _gvk_to_gvr,
)
from kyverno_tpu.server import Controller


def policy(name, kinds=("Pod",), action="validate", failure_policy="Fail",
           timeout=None, generate_kind=None):
    rule = {"name": f"{name}-r", "match": {"resources": {"kinds": list(kinds)}}}
    if action == "validate":
        rule["validate"] = {"pattern": {"metadata": {"name": "?*"}}}
    elif action == "mutate":
        rule["mutate"] = {"patchStrategicMerge": {"metadata": {
            "labels": {"+(x)": "y"}}}}
    elif action == "generate":
        rule["generate"] = {"apiVersion": "v1", "kind": generate_kind,
                            "name": "g", "namespace": "default",
                            "data": {"spec": {}}}
    spec = {"rules": [rule], "failurePolicy": failure_policy}
    if timeout is not None:
        spec["webhookTimeoutSeconds"] = timeout
    return load_policy({
        "apiVersion": "kyverno.io/v1", "kind": "ClusterPolicy",
        "metadata": {"name": name}, "spec": spec,
    })


def test_gvk_to_gvr():
    assert _gvk_to_gvr("Pod") == ("", "v1", "pods")
    assert _gvk_to_gvr("apps/v1/Deployment") == ("apps", "v1", "deployments")
    assert _gvk_to_gvr("v1/Pod") == ("", "v1", "pods")
    assert _gvk_to_gvr("NetworkPolicy") == (
        "networking.k8s.io", "v1", "networkpolicies")
    assert _gvk_to_gvr("PodExecOptions") == ("", "v1", "pods/exec")
    assert _gvk_to_gvr("MyCustomThing") == ("", "*", "mycustomthings")


class TestBuildWebhooks:
    def mgr(self):
        client = FakeCluster()
        return WebhookConfigManager(client, Register(client)), client

    def test_pod_only_policy_narrows_to_pods(self):
        mgr, _ = self.mgr()
        hooks = mgr.build_webhooks([policy("p1", kinds=("Pod",))])
        validate_fail = next(w for w in hooks
                             if w.kind == "Validating"
                             and w.failure_policy == "Fail")
        assert validate_fail.rule()["resources"] == ["pods"]
        # no mutate rules at all -> no mutate webhook entry
        mutate_fail = next(w for w in hooks
                           if w.kind == "Mutating" and w.failure_policy == "Fail")
        assert mutate_fail.rule() is None

    def test_second_policy_widens(self):
        mgr, _ = self.mgr()
        hooks = mgr.build_webhooks([
            policy("p1", kinds=("Pod",)),
            policy("p2", kinds=("apps/v1/Deployment",)),
        ])
        validate_fail = next(w for w in hooks
                             if w.kind == "Validating"
                             and w.failure_policy == "Fail")
        rule = validate_fail.rule()
        assert set(rule["resources"]) == {"pods", "deployments"}
        assert set(rule["apiGroups"]) == {"", "apps"}

    def test_failure_policy_variants_split(self):
        mgr, _ = self.mgr()
        hooks = mgr.build_webhooks([
            policy("p1", kinds=("Pod",), failure_policy="Ignore"),
            policy("p2", kinds=("Service",), failure_policy="Fail"),
        ])
        ignore = next(w for w in hooks if w.kind == "Validating"
                      and w.failure_policy == "Ignore")
        fail = next(w for w in hooks if w.kind == "Validating"
                    and w.failure_policy == "Fail")
        assert ignore.rule()["resources"] == ["pods"]
        assert fail.rule()["resources"] == ["services"]

    def test_wildcard_policy_forces_wide_open(self):
        mgr, _ = self.mgr()
        hooks = mgr.build_webhooks([
            policy("p1", kinds=("Pod",)),
            policy("pw", kinds=("*",)),
        ])
        for w in hooks:
            assert w.rule()["resources"] == ["*/*"]

    def test_mutate_policy_populates_mutating_webhook(self):
        mgr, _ = self.mgr()
        hooks = mgr.build_webhooks([policy("m1", kinds=("Pod",),
                                           action="mutate")])
        mutate_fail = next(w for w in hooks if w.kind == "Mutating"
                           and w.failure_policy == "Fail")
        assert mutate_fail.rule()["resources"] == ["pods"]

    def test_generate_kinds_in_both_webhooks(self):
        mgr, _ = self.mgr()
        hooks = mgr.build_webhooks([policy(
            "g1", kinds=("Namespace",), action="generate",
            generate_kind="NetworkPolicy")])
        for w in hooks:
            if w.failure_policy == "Fail":
                assert set(w.rule()["resources"]) == {
                    "namespaces", "networkpolicies"}

    def test_webhook_timeout_takes_max(self):
        mgr, _ = self.mgr()
        hooks = mgr.build_webhooks([
            policy("p1", kinds=("Pod",), timeout=25),
            policy("p2", kinds=("Service",), timeout=12),
        ])
        validate_fail = next(w for w in hooks if w.kind == "Validating"
                             and w.failure_policy == "Fail")
        assert validate_fail.max_timeout == 25

    def test_sync_writes_configs(self):
        mgr, client = self.mgr()
        mgr.sync([policy("p1", kinds=("Pod",))])
        cfg = client.get_resource("admissionregistration.k8s.io/v1",
                                  "ValidatingWebhookConfiguration", "",
                                  VALIDATING_WEBHOOK_CONFIG)
        assert cfg is not None
        [entry] = cfg["webhooks"]
        assert entry["rules"][0]["resources"] == ["pods"]
        mcfg = client.get_resource("admissionregistration.k8s.io/v1",
                                   "MutatingWebhookConfiguration", "",
                                   MUTATING_WEBHOOK_CONFIG)
        assert mcfg is not None and mcfg["webhooks"] == []


class TestPolicyChangeReconciliation:
    def test_policy_cr_create_updates_cache_and_webhooks(self):
        cluster = FakeCluster()
        controller = Controller(client=cluster)
        # a policy CR appears in the cluster (as if admitted by the webhook)
        cluster.create_resource(policy("p1", kinds=("Pod",)).raw)
        cached = controller.policy_cache.all_policies()
        assert [p.name for p in cached] == ["p1"]
        cfg = cluster.get_resource("admissionregistration.k8s.io/v1",
                                   "ValidatingWebhookConfiguration", "",
                                   VALIDATING_WEBHOOK_CONFIG)
        # Pod + the autogen pod-controller kinds, nothing else
        assert set(cfg["webhooks"][0]["rules"][0]["resources"]) == {
            "pods", "deployments", "daemonsets", "statefulsets", "jobs",
            "cronjobs"}
        # a Service policy widens the narrowed rules without restart
        cluster.create_resource(policy("p2", kinds=("Service",)).raw)
        cfg = cluster.get_resource("admissionregistration.k8s.io/v1",
                                   "ValidatingWebhookConfiguration", "",
                                   VALIDATING_WEBHOOK_CONFIG)
        assert "services" in cfg["webhooks"][0]["rules"][0]["resources"]

    def test_scan_sees_policy_added_after_start(self):
        cluster = FakeCluster()
        controller = Controller(client=cluster)
        cluster.create_resource({
            "apiVersion": "v1", "kind": "Pod",
            "metadata": {"name": "bad", "namespace": "default"},
            "spec": {"containers": [{"name": "c", "image": "nginx:latest"}]},
        })
        doc = {
            "apiVersion": "kyverno.io/v1", "kind": "ClusterPolicy",
            "metadata": {"name": "no-latest"},
            "spec": {"background": True, "rules": [{
                "name": "no-latest",
                "match": {"resources": {"kinds": ["Pod"]}},
                "validate": {"pattern": {"spec": {"containers": [
                    {"image": "!*:latest"}]}}},
            }]},
        }
        cluster.create_resource(doc)
        assert controller._scan_kick.is_set()  # scan re-queued
        result = controller.run_background_scan()
        assert result.violations >= 1

    def test_policy_delete_prunes_reports(self):
        cluster = FakeCluster()
        controller = Controller(client=cluster)
        doc = policy("p1", kinds=("Pod",)).raw
        cluster.create_resource(doc)
        controller.report_gen.add_result(
            namespace="default", policy="p1", rule="p1-r",
            kind="Pod", name="x", status="fail",
        ) if hasattr(controller.report_gen, "add_result") else None
        cluster.delete_resource("kyverno.io/v1", "ClusterPolicy", "", "p1")
        assert controller.policy_cache.all_policies() == []
