"""Resource cache (pkg/resourcecache): zero synchronous GETs on the
steady-state admission path, watch-driven freshness."""

from kyverno_tpu.api.load import load_policy
from kyverno_tpu.runtime.client import FakeCluster
from kyverno_tpu.runtime.policycache import PolicyCache
from kyverno_tpu.runtime.resourcecache import ResourceCache
from kyverno_tpu.runtime.webhook import VALIDATING_WEBHOOK_PATH, WebhookServer


class CountingCluster(FakeCluster):
    def __init__(self, *a, **kw):
        super().__init__(*a, **kw)
        self.get_calls = 0

    def get_resource(self, api_version, kind, namespace, name):
        self.get_calls += 1
        return super().get_resource(api_version, kind, namespace, name)


NS_POLICY = {
    "apiVersion": "kyverno.io/v1", "kind": "ClusterPolicy",
    "metadata": {"name": "env-selector"},
    "spec": {
        "validationFailureAction": "enforce",
        "rules": [{
            "name": "env-rule",
            "match": {"resources": {
                "kinds": ["Pod"],
                "namespaceSelector": {"matchLabels": {"env": "prod"}}}},
            "validate": {"message": "no hostPID in prod",
                         "pattern": {"spec": {"hostPID": "false"}}},
        }],
    },
}


def review(resource, namespace="default"):
    return {"apiVersion": "admission.k8s.io/v1", "kind": "AdmissionReview",
            "request": {"uid": "u", "kind": {"kind": "Pod"},
                        "namespace": namespace, "operation": "CREATE",
                        "object": resource}}


def pod(namespace="default", host_pid=False):
    return {"apiVersion": "v1", "kind": "Pod",
            "metadata": {"name": "p", "namespace": namespace},
            "spec": {"hostPID": host_pid, "containers": [
                {"name": "c", "image": "nginx:1.21"}]}}


class TestResourceCache:
    def test_read_through_then_cached(self):
        cluster = CountingCluster([{
            "apiVersion": "v1", "kind": "Namespace",
            "metadata": {"name": "prod", "labels": {"env": "prod"}}}])
        cache = ResourceCache(cluster)
        assert cache.get_namespace_labels("prod") == {"env": "prod"}
        base = cluster.get_calls
        for _ in range(10):
            assert cache.get_namespace_labels("prod") == {"env": "prod"}
        assert cluster.get_calls == base  # zero GETs once cached

    def test_watch_event_refreshes(self):
        cluster = CountingCluster([{
            "apiVersion": "v1", "kind": "Namespace",
            "metadata": {"name": "prod", "labels": {"env": "prod"}}}])
        cache = ResourceCache(cluster)
        cache.get_namespace_labels("prod")
        cluster.update_resource({
            "apiVersion": "v1", "kind": "Namespace",
            "metadata": {"name": "prod", "labels": {"env": "staging"}}})
        base = cluster.get_calls
        assert cache.get_namespace_labels("prod") == {"env": "staging"}
        assert cluster.get_calls == base  # updated via watch, not a GET

    def test_absence_cached(self):
        cluster = CountingCluster()
        cache = ResourceCache(cluster)
        assert cache.get("v1", "Namespace", "", "ghost") is None
        base = cluster.get_calls
        assert cache.get("v1", "Namespace", "", "ghost") is None
        assert cluster.get_calls == base

    def test_deleted_resource_drops(self):
        ns = {"apiVersion": "v1", "kind": "Namespace",
              "metadata": {"name": "prod", "labels": {"env": "prod"}}}
        cluster = CountingCluster([ns])
        cache = ResourceCache(cluster)
        assert cache.get_namespace_labels("prod")
        cluster.delete_resource("v1", "Namespace", "", "prod")
        assert cache.get_namespace_labels("prod") == {}


class TestAdmissionHotPath:
    def test_steady_state_admission_does_no_gets(self):
        cluster = CountingCluster([{
            "apiVersion": "v1", "kind": "Namespace",
            "metadata": {"name": "prod", "labels": {"env": "prod"}}}])
        cache = PolicyCache()
        cache.add(load_policy(NS_POLICY))
        server = WebhookServer(policy_cache=cache, client=cluster)

        out = server.handle(VALIDATING_WEBHOOK_PATH,
                            review(pod("prod", host_pid=True), "prod"))
        assert out["response"]["allowed"] is False  # selector matched

        base = cluster.get_calls
        for _ in range(20):
            out = server.handle(VALIDATING_WEBHOOK_PATH,
                                review(pod("prod"), "prod"))
            assert out["response"]["allowed"] is True
        assert cluster.get_calls == base  # zero synchronous GETs steady-state

    def test_namespace_label_change_visible(self):
        cluster = CountingCluster([{
            "apiVersion": "v1", "kind": "Namespace",
            "metadata": {"name": "prod", "labels": {"env": "prod"}}}])
        cache = PolicyCache()
        cache.add(load_policy(NS_POLICY))
        server = WebhookServer(policy_cache=cache, client=cluster)
        out = server.handle(VALIDATING_WEBHOOK_PATH,
                            review(pod("prod", host_pid=True), "prod"))
        assert out["response"]["allowed"] is False
        # namespace drops the selector label -> rule no longer matches
        cluster.update_resource({
            "apiVersion": "v1", "kind": "Namespace",
            "metadata": {"name": "prod", "labels": {"env": "dev"}}})
        out = server.handle(VALIDATING_WEBHOOK_PATH,
                            review(pod("prod", host_pid=True), "prod"))
        assert out["response"]["allowed"] is True
