"""Systematic race harness over the controller's shared state.

The reference leans on Go's race detector in CI (SURVEY.md section 5);
CPython has no TSan, so this is the systematic analogue: a reusable
harness that releases N threads through a barrier into mixed read/write
workloads against one shared component, collects every exception, joins
with a deadlock timeout, and then checks the component's invariants.
Races in CPython manifest as exceptions (dict mutated during iteration,
KeyError on check-then-act), torn/stale aggregates, or deadlocks — all
three are what the harness asserts against. Each scenario pins a pairing
that actually runs concurrently in the controller.
"""

import threading
import time

import numpy as np
import pytest

from kyverno_tpu.api.load import load_policy


def race(workers, duration_s: float = 1.0, join_timeout_s: float = 15.0):
    """Run each callable in ``workers`` in a loop for ``duration_s``,
    all released simultaneously. Returns the list of exceptions raised
    (empty = clean run); fails the test on deadlock."""
    barrier = threading.Barrier(len(workers))
    stop = time.monotonic() + duration_s
    errors: list[BaseException] = []
    lock = threading.Lock()

    def runner(fn):
        barrier.wait()
        i = 0
        while time.monotonic() < stop:
            try:
                fn(i)
            except BaseException as e:  # noqa: BLE001 - harness collects all
                with lock:
                    errors.append(e)
                return
            i += 1

    threads = [threading.Thread(target=runner, args=(fn,), daemon=True)
               for fn in workers]
    for t in threads:
        t.start()
    for t in threads:
        t.join(join_timeout_s)
    assert not any(t.is_alive() for t in threads), "deadlock: thread stuck"
    return errors


def _policy(name, image_pat="!*:latest"):
    return load_policy({
        "apiVersion": "kyverno.io/v1", "kind": "ClusterPolicy",
        "metadata": {"name": name},
        "spec": {"validationFailureAction": "enforce", "rules": [{
            "name": "r",
            "match": {"resources": {"kinds": ["Pod"]}},
            "validate": {"pattern": {"spec": {"containers": [
                {"image": image_pat}]}}},
        }]},
    })


def _pod(i):
    return {"apiVersion": "v1", "kind": "Pod",
            "metadata": {"name": f"p{i}", "namespace": "default"},
            "spec": {"containers": [{"name": "c", "image": "nginx:1.21"}]}}


class TestPolicyCacheRaces:
    def test_reload_during_compiled_lookups(self):
        """The controller recompiles tensors on policy change while the
        webhook resolves compiled() for in-flight admissions."""
        from kyverno_tpu.runtime.policycache import PolicyCache, PolicyType

        cache = PolicyCache()
        cache.add(_policy("base"))

        def admit(i):
            cps = cache.compiled(PolicyType.VALIDATE_ENFORCE, "Pod",
                                 "default")
            assert cps is not None
            # compiled sets must always be internally consistent:
            # rule_refs tracks the live rows; n_rules may carry pow2
            # bucket padding on the incremental path
            assert len(cps.rule_refs) == int(cps.tensors.n_rules_live)
            assert int(cps.tensors.n_rules) >= int(cps.tensors.n_rules_live)

        def churn(i):
            p = _policy(f"churn-{i % 4}")
            cache.add(p)
            cache.remove(p)

        errors = race([admit, admit, churn, churn], duration_s=1.5)
        assert not errors, errors[:3]


class TestAdmissionBatcherRaces:
    def test_screens_against_policy_churn_and_stop(self):
        """Concurrent screens race the flush worker, the policy cache
        generation change, and a late stop()."""
        from kyverno_tpu.runtime.batch import AdmissionBatcher
        from kyverno_tpu.runtime.policycache import PolicyCache, PolicyType

        cache = PolicyCache()
        cache.add(_policy("base"))
        batcher = AdmissionBatcher(cache, window_s=0.002, burst_threshold=1,
                                   dispatch_cost_init_s=0.0,
                                   oracle_cost_init_s=1.0,
                                   cold_flush_fallback=False)

        def screen(i):
            with batcher.admission_in_flight():
                status, row = batcher.screen(
                    PolicyType.VALIDATE_ENFORCE, "Pod", "default", _pod(i),
                    timeout_s=5.0)
            assert status in ("clean", "attention", "oracle")

        def churn(i):
            p = _policy(f"extra-{i % 3}", image_pat="!*:dev")
            cache.add(p)
            time.sleep(0.002)
            cache.remove(p)

        try:
            errors = race([screen, screen, screen, churn], duration_s=1.5)
            assert not errors, errors[:3]
        finally:
            batcher.stop()
        # stopped batcher answers instead of hanging
        status, _ = batcher.screen(PolicyType.VALIDATE_ENFORCE, "Pod",
                                   "default", _pod(0), timeout_s=1.0)
        assert status == "attention"


class TestResourceCacheRaces:
    def test_gets_vs_watch_events_vs_invalidate(self):
        from kyverno_tpu.runtime.client import FakeCluster
        from kyverno_tpu.runtime.resourcecache import ResourceCache

        cluster = FakeCluster([{
            "apiVersion": "v1", "kind": "Namespace",
            "metadata": {"name": "prod", "labels": {"env": "prod"}}}])
        cache = ResourceCache(cluster)

        def reader(i):
            labels = cache.get_namespace_labels("prod")
            # a watch-maintained entry is some complete published state —
            # exactly the key set a writer produced with a well-formed
            # value — never a torn/partial dict (invalidate may yield {})
            assert labels == {} or set(labels) == {"env"}, labels
            if labels:
                v = labels["env"]
                assert v == "prod" or (
                    v.startswith("v") and v[1:].isdigit()), labels

        def writer(i):
            ns = cluster.get_resource("v1", "Namespace", "", "prod")
            ns["metadata"]["labels"] = {"env": f"v{i % 5}"}
            cluster.update_resource(ns)

        def invalidator(i):
            cache.invalidate("Namespace", "", "prod")

        errors = race([reader, reader, writer, invalidator], duration_s=1.5)
        assert not errors, errors[:3]


class TestWatchHubRaces:
    def test_concurrent_ensure_shares_one_reflector(self):
        """Many consumers ensuring the same GVK must converge on one
        reflector and every callback must survive concurrent fan-out."""
        from kyverno_tpu.runtime.client import FakeCluster
        from kyverno_tpu.runtime.watch import WatchHub

        class ListingFake(FakeCluster):
            def list_response(self, api_version, kind, namespace=""):
                return {"items": self.list_resource(api_version, kind,
                                                    namespace),
                        "metadata": {"resourceVersion": "1"}}

            def watch_stream(self, *a, stop=None, **kw):
                # quiet stream: yields nothing, ends after a beat
                time.sleep(0.01)
                return iter(())

        hub = WatchHub(ListingFake())
        seen = []

        def ensure(i):
            refl = hub.ensure("v1", "ConfigMap",
                              on_sync=lambda items: seen.append(len(items)))
            assert refl.wait_synced(5.0)

        try:
            errors = race([ensure] * 6, duration_s=1.0)
            assert not errors, errors[:3]
            with hub._lock:
                assert len(hub._reflectors) == 1
        finally:
            hub.stop()


class TestReportPipelineRaces:
    def test_concurrent_add_and_aggregate(self):
        from kyverno_tpu.engine.response import (
            EngineResponse,
            PolicyResponse,
            PolicySpecSummary,
            ResourceSpec,
            RuleResponse,
            RuleStatus,
            RuleType,
        )
        from kyverno_tpu.runtime.client import FakeCluster
        from kyverno_tpu.runtime.reports import ReportGenerator

        gen = ReportGenerator(FakeCluster())

        def add(i):
            resp = EngineResponse(policy_response=PolicyResponse(
                policy=PolicySpecSummary(name=f"pol-{i % 3}"),
                resource=ResourceSpec(kind="Pod", namespace="default",
                                      name=f"p{i % 7}")))
            resp.policy_response.rules.append(RuleResponse(
                name="r", type=RuleType.VALIDATION,
                status=RuleStatus.PASS if i % 2 else RuleStatus.FAIL))
            gen.add(resp)

        def aggregate(i):
            for report in gen.aggregate():
                summary = report.get("summary") or {}
                results = report.get("results") or []
                # aggregate must be self-consistent, not torn
                assert summary.get("pass", 0) + summary.get("fail", 0) + \
                    summary.get("skip", 0) + summary.get("error", 0) + \
                    summary.get("warn", 0) == len(results)

        errors = race([add, add, aggregate], duration_s=1.5)
        assert not errors, errors[:3]


class TestDeviceScreenRaces:
    def test_concurrent_packed_eval_same_compiled_set(self):
        """Multiple flush threads sharing one CompiledPolicySet must get
        identical verdicts for identical inputs (jit cache, flattener
        context, and blob cache are shared state)."""
        from kyverno_tpu.models import CompiledPolicySet

        cps = CompiledPolicySet([_policy("p1"), _policy("p2", "!*:dev")])
        pods = [_pod(i) for i in range(16)]
        want = cps.evaluate_device(cps.flatten_packed(pods))
        results = []
        lock = threading.Lock()

        def evaluate(i):
            got = cps.evaluate_device(cps.flatten_packed(pods))
            with lock:
                results.append(got)

        errors = race([evaluate] * 4, duration_s=1.5)
        assert not errors, errors[:3]
        for got in results:
            assert np.array_equal(got, want)


class TestDecisionCacheRaces:
    def test_concurrent_admissions_vs_policy_churn(self):
        """The round-5 caches under fire: HTTP-less webhook admissions
        (decision cache + screen-row cache) racing policy reloads (which
        rotate the cache generation) and audit processing (audit memo).
        Invariant: verdicts never cross policy generations — a pod that
        violates the CURRENT policy set is never allowed."""
        from kyverno_tpu.runtime.batch import AdmissionBatcher
        from kyverno_tpu.runtime.client import FakeCluster
        from kyverno_tpu.runtime.policycache import PolicyCache
        from kyverno_tpu.runtime.webhook import (
            VALIDATING_WEBHOOK_PATH,
            WebhookServer,
        )

        cache = PolicyCache()
        cache.add(_policy("block-latest"))
        batcher = AdmissionBatcher(cache, window_s=0.002, burst_threshold=1,
                                   dispatch_cost_init_s=0.0,
                                   oracle_cost_init_s=1.0,
                                   cold_flush_fallback=False,
                                   result_cache_ttl_s=5.0)
        server = WebhookServer(policy_cache=cache, client=FakeCluster(),
                               admission_batcher=batcher)
        server.audit_handler.run()

        def review(i):
            bad = i % 2
            return {"request": {
                "uid": "u", "kind": {"kind": "Pod"},
                "namespace": "default", "operation": "CREATE",
                "object": {"apiVersion": "v1", "kind": "Pod",
                           "metadata": {"name": f"p{i % 5}",
                                        "namespace": "default"},
                           "spec": {"containers": [{
                               "name": "c",
                               "image": "nginx:latest" if bad
                               else "nginx:1.21"}]}}}}, bad

        def admit(i):
            body, bad = review(i)
            out = server.handle(VALIDATING_WEBHOOK_PATH, body)
            # the enforce policy (present in every generation) must
            # always deny :latest — cached or not
            if bad:
                assert out["response"]["allowed"] is False
            else:
                assert out["response"]["allowed"] is True

        def churn(i):
            # add/remove a SEMANTICALLY DISTINCT policy: generations must
            # rotate every cache key, and a stale cross-generation verdict
            # would be observably wrong (':dev' rejection appearing or
            # vanishing), not coincidentally identical
            extra = _policy(f"extra-{i % 2}", image_pat="!*:dev")
            cache.add(extra)
            cache.remove(extra)

        def audit(i):
            body, _ = review(i)
            server._process_audit(body["request"])

        try:
            errors = race([admit, admit, admit, churn, audit],
                          duration_s=1.5)
        finally:
            server.audit_handler.stop()
            batcher.stop()
        assert not errors, errors[:3]
        # staleness probe: the ':dev'-blocking policy is GONE now, so a
        # ':dev' pod must be allowed — a decision cached under a
        # generation that still had the policy must not leak forward
        probe = {"request": {
            "uid": "u", "kind": {"kind": "Pod"},
            "namespace": "default", "operation": "CREATE",
            "object": {"apiVersion": "v1", "kind": "Pod",
                       "metadata": {"name": "p0", "namespace": "default"},
                       "spec": {"containers": [{
                           "name": "c", "image": "nginx:dev"}]}}}}
        out = server.handle(VALIDATING_WEBHOOK_PATH, probe)
        assert out["response"]["allowed"] is True


class TestReportWriterRaces:
    def test_writer_vs_aggregate_vs_flush(self):
        """The async RCR writer, leader aggregation, and flush running
        concurrently: no exceptions, no deadlock, and every produced
        result eventually lands in exactly one report row (freshest
        per key)."""
        from kyverno_tpu.runtime.client import FakeCluster
        from kyverno_tpu.runtime.reports import ReportGenerator

        gen = ReportGenerator(FakeCluster())

        def rcr(i):
            return {
                "apiVersion": "kyverno.io/v1alpha2",
                "kind": "ReportChangeRequest",
                "metadata": {"name": f"rcr-pol-pod-p{i % 7}",
                             "namespace": "default"},
                "results": [{
                    "policy": "pol", "rule": "r",
                    "result": "fail" if i % 2 else "pass",
                    "message": "", "scored": True,
                    "timestampNs": time.time_ns(),
                    "resources": [{"kind": "Pod", "namespace": "default",
                                   "name": f"p{i % 7}"}],
                }],
            }

        def add(i):
            gen.add_change_request(rcr(i))

        def aggregate(i):
            for report in gen.aggregate():
                summary = report.get("summary") or {}
                results = report.get("results") or []
                assert sum(summary.values()) == len(results)

        def flush(i):
            gen.flush(timeout_s=0.5)

        try:
            errors = race([add, add, aggregate, flush], duration_s=1.5)
            # worker errors are the root cause — report them FIRST
            assert not errors, errors[:3]
            # quiesce, then the final aggregate holds one row per key
            assert gen.flush()
            reports = gen.aggregate()
            rows = [r for rep in reports for r in rep.get("results", [])]
            keys = [(r["policy"], r["rule"],
                     r["resources"][0]["name"]) for r in rows]
            assert len(keys) == len(set(keys))
            assert len(keys) <= 7
        finally:
            gen.stop()


class TestFlattenPipelineRaces:
    def test_concurrent_memoized_flushes_vs_policy_swap(self):
        """The pipelined flush path under fire: concurrent screens whose
        windows splice memoized flatten rows, racing policy-cache swaps
        that MOVE the path dictionary (new tensor fingerprint) mid-burst.
        Invariant: a pod violating the always-present policy is never
        screened CLEAN — a stale memo row spliced across a recompile
        would be exactly that failure. Post-churn probes then prove both
        directions of invalidation: memoized-clean rows stay clean once
        the structurally-different policy is gone, and re-adding it flags
        the same memoized body."""
        from kyverno_tpu.runtime.batch import ATTENTION, CLEAN, AdmissionBatcher
        from kyverno_tpu.runtime.policycache import PolicyCache, PolicyType

        cache = PolicyCache()
        cache.add(_policy("block-latest"))
        batcher = AdmissionBatcher(cache, window_s=0.002, burst_threshold=1,
                                   dispatch_cost_init_s=0.0,
                                   oracle_cost_init_s=1.0,
                                   cold_flush_fallback=False,
                                   result_cache_ttl_s=0.0)

        def pod(i, bad):
            # small name space: repeated bodies → real memo hits
            return {"apiVersion": "v1", "kind": "Pod",
                    "metadata": {"name": f"p{i % 4}", "namespace": "default"},
                    "spec": {"containers": [{
                        "name": "c",
                        "image": "nginx:latest" if bad else "nginx:1.21"}]}}

        def screen(i):
            bad = i % 2 == 1
            status, _ = batcher.screen(PolicyType.VALIDATE_ENFORCE, "Pod",
                                       "default", pod(i, bad))
            if bad:
                # block-latest exists in EVERY policy generation; a CLEAN
                # here means a verdict crossed generations or a stale
                # memo row spliced into a fresh batch
                assert status != CLEAN

        def churn(i):
            # structurally different pattern → the combined tensor set's
            # path dictionary (and fingerprint) changes on every swap,
            # churning the memo key space under the screen workers
            extra = _policy(f"extra-{i % 2}", image_pat="!*:dev")
            cache.add(extra)
            cache.remove(extra)

        try:
            errors = race([screen, screen, screen, churn], duration_s=1.5)
        finally:
            batcher.stop()
        assert not errors, errors[:3]

        # quiescent probes on a fresh batcher sharing the same cache:
        # the swap policy is gone, so a ':dev' body memoized clean (or
        # flagged) under some mid-burst generation must screen CLEAN now
        probe = AdmissionBatcher(cache, window_s=0.002, burst_threshold=1,
                                 dispatch_cost_init_s=0.0,
                                 oracle_cost_init_s=1.0,
                                 cold_flush_fallback=False,
                                 result_cache_ttl_s=0.0)
        try:
            dev = {"apiVersion": "v1", "kind": "Pod",
                   "metadata": {"name": "probe", "namespace": "default"},
                   "spec": {"containers": [{"name": "c",
                                            "image": "nginx:dev"}]}}
            assert probe.screen(PolicyType.VALIDATE_ENFORCE, "Pod",
                                "default", dev)[0] == CLEAN
            # now re-add the ':dev' blocker: the row just memoized CLEAN
            # lives under the OLD fingerprint, so the same body must be
            # re-flattened and flagged under the new tensor set
            cache.add(_policy("block-dev", image_pat="!*:dev"))
            assert probe.screen(PolicyType.VALIDATE_ENFORCE, "Pod",
                                "default", dev)[0] == ATTENTION
        finally:
            probe.stop()


class TestIncrementalChurnRaces:
    def test_segment_recompiles_vs_coalesced_flushes(self):
        """The incremental-compilation path (ISSUE 4) under fire: one
        thread adds/updates/removes policies — each step recompiles only
        the touched segment and advances the shared dictionary epoch —
        while coalesced admissions flush through the epoch-refreshed
        memo splice. Invariants: no exceptions/deadlock; the enforce
        policy present in EVERY generation never screens a violating pod
        CLEAN (a stale-segment splice would be exactly that); and once
        quiesced, the incremental compiled set's verdicts are
        bit-identical to a from-scratch full recompile of the same
        policies."""
        from kyverno_tpu.models import CompiledPolicySet
        from kyverno_tpu.runtime.batch import CLEAN, AdmissionBatcher
        from kyverno_tpu.runtime.policycache import PolicyCache, PolicyType

        cache = PolicyCache()
        cache.add(_policy("block-latest"))
        batcher = AdmissionBatcher(cache, window_s=0.002, burst_threshold=1,
                                   dispatch_cost_init_s=0.0,
                                   oracle_cost_init_s=1.0,
                                   cold_flush_fallback=False,
                                   result_cache_ttl_s=0.0)

        def pod(i, bad):
            return {"apiVersion": "v1", "kind": "Pod",
                    "metadata": {"name": f"p{i % 4}", "namespace": "default"},
                    "spec": {"containers": [{
                        "name": "c",
                        "image": "nginx:latest" if bad else "nginx:1.21"}]}}

        def screen(i):
            bad = i % 2 == 1
            status, _ = batcher.screen(PolicyType.VALIDATE_ENFORCE, "Pod",
                                       "default", pod(i, bad))
            if bad:
                assert status != CLEAN

        def churn(i):
            # update: same name, new object → that ONE segment recompiles
            # and is spliced against the others' cached row ranges;
            # add/remove shifts every later segment's rebased offsets
            cache.add(_policy("churn-upd", image_pat=f"!*:v{i % 3}"))
            extra = _policy(f"churn-{i % 3}", image_pat="!*:dev")
            cache.add(extra)
            cache.remove(extra)

        try:
            errors = race([screen, screen, screen, churn], duration_s=1.5)
        finally:
            batcher.stop()
        assert not errors, errors[:3]

        # quiesced parity: whatever generation won, the served splice
        # must equal a monolithic from-scratch compile of those policies
        cps = cache.compiled(PolicyType.VALIDATE_ENFORCE, "Pod", "default")
        assert cps is not None and cps.tensors.segments
        docs = [pod(i, i % 2 == 1) for i in range(8)]
        got = cps.evaluate_device(cps.flatten_packed(docs))
        fresh = CompiledPolicySet(cps.policies)
        want = fresh.evaluate_device(fresh.flatten_packed(docs))
        assert got.shape == want.shape
        assert np.array_equal(got, want)


class TestHostMemoRaces:
    def test_flushes_vs_policy_swap_invalidating_host_memo(self):
        """The host-lane memo (ISSUE 5) under fire: concurrent screens
        whose flushes resolve HOST cells — prefetched, memoized, fanned
        out — racing policy-cache swaps that re-content a host-only
        policy (same name, new raw) and therefore rotate its memo key
        space mid-burst. Invariants: no exceptions/deadlock; a pod that
        violates the host rule in EVERY generation is never screened
        CLEAN (a memoized verdict crossing a policy swap would be
        exactly that); and at quiescence a fresh resolution reports the
        FINAL policy content's message — nothing memoized under an older
        wording leaks forward."""
        from kyverno_tpu.runtime import hostlane
        from kyverno_tpu.runtime.batch import CLEAN, AdmissionBatcher
        from kyverno_tpu.runtime.policycache import PolicyCache, PolicyType

        def host_policy(message):
            # name vs uid never match, so this rule FAILs for every pod
            # below in every generation; only the message wording moves
            return load_policy({
                "apiVersion": "kyverno.io/v1", "kind": "ClusterPolicy",
                "metadata": {"name": "host-name-vs-uid"},
                "spec": {"validationFailureAction": "enforce", "rules": [{
                    "name": "echo",
                    "match": {"resources": {"kinds": ["Pod"]}},
                    "validate": {"message": message,
                                 "pattern": {"metadata": {"name":
                                     "{{request.object.metadata.uid}}"}}},
                }]},
            })

        cache = PolicyCache()
        cache.add(_policy("block-latest"))
        cache.add(host_policy("swapgen-0"))
        batcher = AdmissionBatcher(cache, window_s=0.002, burst_threshold=1,
                                   dispatch_cost_init_s=0.0,
                                   oracle_cost_init_s=1.0,
                                   cold_flush_fallback=False,
                                   result_cache_ttl_s=0.0)
        hostlane.host_cache().clear()

        def pod(i):
            # small body space: repeated bodies → real host-memo hits
            return {"apiVersion": "v1", "kind": "Pod",
                    "metadata": {"name": f"p{i % 4}", "namespace": "default",
                                 "uid": f"u{i % 4}"},
                    "spec": {"containers": [{"name": "c",
                                             "image": "nginx:1.21"}]}}

        def screen(i):
            status, _ = batcher.screen(PolicyType.VALIDATE_ENFORCE, "Pod",
                                       "default", pod(i))
            assert status != CLEAN

        def swap(i):
            cache.add(host_policy(f"swapgen-{i % 3 + 1}"))

        try:
            errors = race([screen, screen, screen, swap], duration_s=1.5)
        finally:
            batcher.stop()
        assert not errors, errors[:3]

        # quiescent content-crossing probe: one final deterministic swap,
        # then a fresh resolution of a body the memo served all burst —
        # the message must carry the final wording, never an older one
        cache.add(host_policy("swapgen-final"))
        cps = cache.compiled(PolicyType.VALIDATE_ENFORCE, "Pod", "default")
        body = pod(0)
        msgs: dict = {}
        v = cps.resolve_host_cells(
            [body], cps.evaluate_device(cps.flatten_packed([body])).copy(),
            messages_out=msgs)
        from kyverno_tpu.models.engine import Verdict

        assert not (np.asarray(v) == int(Verdict.HOST)).any()
        swapped = [m for m in msgs.values() if "swapgen-" in m]
        assert swapped and all("swapgen-final" in m for m in swapped), msgs
