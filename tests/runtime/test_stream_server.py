"""Streaming admission plane: wire codec, continuous batching, donation."""

import json
import os
import threading
import time

import numpy as np
import pytest

from kyverno_tpu.api.load import load_policy
from kyverno_tpu.models import Verdict
from kyverno_tpu.models.flatten import (decode_packed_block,
                                        decode_packed_row,
                                        encode_packed_block,
                                        encode_packed_row,
                                        graft_packed_rows,
                                        grow_dict_headroom,
                                        splice_packed_rows)
from kyverno_tpu.runtime.batch import ATTENTION, CLEAN, AdmissionBatcher
from kyverno_tpu.runtime.client import FakeCluster
from kyverno_tpu.runtime.policycache import PolicyCache, PolicyType
from kyverno_tpu.runtime.stream_server import (StreamClient, StreamServer,
                                               flatten_block_for_wire,
                                               flatten_rows_for_wire)
from kyverno_tpu.runtime.webhook import (VALIDATING_WEBHOOK_PATH,
                                         WebhookServer)

ENFORCE = {
    "apiVersion": "kyverno.io/v1", "kind": "ClusterPolicy",
    "metadata": {"name": "disallow-latest-tag"},
    "spec": {
        "validationFailureAction": "enforce",
        "rules": [{
            "name": "validate-image-tag",
            "match": {"resources": {"kinds": ["Pod"]}},
            "validate": {"message": "latest tag not allowed",
                         "pattern": {"spec": {"containers": [
                             {"image": "!*:latest"}]}}},
        }],
    },
}


def pod(image, name="p"):
    return {"apiVersion": "v1", "kind": "Pod",
            "metadata": {"name": name, "namespace": "default"},
            "spec": {"containers": [{"name": "c", "image": image}]}}


def review(resource, uid="u"):
    return {"apiVersion": "admission.k8s.io/v1", "kind": "AdmissionReview",
            "request": {"uid": uid, "kind": {"kind": "Pod"},
                        "namespace": "default", "operation": "CREATE",
                        "object": resource}}


def make_stack(continuous=True, **kw):
    kw.setdefault("dispatch_cost_init_s", 0.0)
    kw.setdefault("oracle_cost_init_s", 1.0)
    kw.setdefault("cold_flush_fallback", False)
    kw.setdefault("result_cache_ttl_s", 0.0)
    cache = PolicyCache()
    cache.add(load_policy(ENFORCE))
    batcher = AdmissionBatcher(cache, window_s=0.002, burst_threshold=1,
                               continuous=continuous, **kw)
    server = WebhookServer(policy_cache=cache, client=FakeCluster(),
                           admission_batcher=batcher)
    cps = cache.compiled(PolicyType.VALIDATE_ENFORCE, "Pod", "default")
    return cache, batcher, server, cps


class TestWireCodec:
    def test_row_round_trip(self):
        _, batcher, _, cps = make_stack()
        try:
            rows = flatten_rows_for_wire(cps, [pod("nginx:1.21"),
                                               pod("redis:latest")])
            for row in rows:
                blob = encode_packed_row(row)
                back, off = decode_packed_row(blob)
                assert off == len(blob)
                assert np.array_equal(back.cells, row.cells)
                assert int(back.bmeta) == int(row.bmeta)
                assert np.array_equal(back.str_bytes, row.str_bytes)
                assert np.array_equal(back.dictv, row.dictv)
        finally:
            batcher.stop()

    def test_block_round_trip_and_verdict_equivalence(self):
        _, batcher, _, cps = make_stack()
        try:
            resources = [pod("nginx:1.21"), pod("nginx:latest")]
            block = flatten_block_for_wire(cps, resources)
            blob = encode_packed_block(block)
            back, off = decode_packed_block(blob)
            assert off == len(blob)
            ref = np.asarray(cps.evaluate_device(block))
            got = np.asarray(cps.evaluate_device(back))
            assert np.array_equal(ref, got)
        finally:
            batcher.stop()

    def test_decoded_rows_splice_like_originals(self):
        _, batcher, _, cps = make_stack()
        try:
            resources = [pod("nginx:1.21"), pod("redis:6"),
                         pod("nginx:latest")]
            rows = flatten_rows_for_wire(cps, resources)
            wired = [decode_packed_row(encode_packed_row(r))[0]
                     for r in rows]
            ref = np.asarray(cps.evaluate_device(
                splice_packed_rows(rows)))
            got = np.asarray(cps.evaluate_device(
                splice_packed_rows(wired)))
            assert np.array_equal(ref, got)
        finally:
            batcher.stop()


class TestGraft:
    def test_graft_into_headroom_matches_full_flatten(self):
        _, batcher, _, cps = make_stack()
        try:
            base = [pod("nginx:1.21"), pod("nginx:latest")]
            late = [pod("redis:latest"), pod("redis:6")]
            raw = cps.flatten_packed(base)
            v_used = int(raw.dictv.shape[0])
            padded, _ = AdmissionBatcher._pad_admission(raw)
            padded = grow_dict_headroom(padded, v_used // 4 + 1)
            assert padded.n >= len(base) + len(late)
            late_rows = flatten_rows_for_wire(cps, late)
            n = graft_packed_rows(padded, late_rows, len(base), v_used)
            assert n == len(late)
            ref = np.asarray(cps.evaluate_device(
                cps.flatten_packed(base + late)))
            got = np.asarray(cps.evaluate_device(padded))
            assert np.array_equal(ref[:len(base) + len(late)],
                                  got[:len(base) + len(late)])
        finally:
            batcher.stop()

    def test_graft_rejects_overflow_without_mutation(self):
        _, batcher, _, cps = make_stack()
        try:
            base = [pod("nginx:1.21")]
            raw = cps.flatten_packed(base)
            padded, _ = AdmissionBatcher._pad_admission(raw)
            # v_used == full table: a row with ANY fresh string must be
            # rejected and must leave the batch untouched
            v_full = int(padded.dictv.shape[0])
            fresh = flatten_rows_for_wire(
                cps, [pod("completely-new-image:tag-xyz",
                          name="unseen-name")])
            before = padded.cells.copy()
            n = graft_packed_rows(padded, fresh, 1, v_full)
            assert n == 0
            assert np.array_equal(padded.cells, before)
        finally:
            batcher.stop()


class TestScreenRow:
    def test_screen_row_matches_screen(self):
        _, batcher, _, cps = make_stack()
        try:
            for image, want in ((("nginx:1.21"), CLEAN),
                                (("nginx:latest"), ATTENTION)):
                resource = pod(image)
                ref_status, ref_row = batcher.screen(
                    PolicyType.VALIDATE_ENFORCE, "Pod", "default",
                    resource)
                row = flatten_rows_for_wire(cps, [resource])[0]
                status, vrow = batcher.screen_row(
                    PolicyType.VALIDATE_ENFORCE, "Pod", "default", row)
                assert status == ref_status == want
                assert vrow == ref_row
        finally:
            batcher.stop()

    def test_screen_row_shape_mismatch_escalates(self):
        _, batcher, _, cps = make_stack()
        try:
            row = flatten_rows_for_wire(cps, [pod("nginx:1.21")])[0]
            bad = row.__class__(cells=row.cells[:-1], bmeta=row.bmeta,
                                str_bytes=row.str_bytes, dictv=row.dictv)
            status, vrow = batcher.screen_row(
                PolicyType.VALIDATE_ENFORCE, "Pod", "default", bad)
            assert (status, vrow) == (ATTENTION, [])
            assert batcher.stats.get("stream_shape_reject") == 1
        finally:
            batcher.stop()

    def test_wire_rows_count_in_stats(self):
        _, batcher, _, cps = make_stack()
        try:
            row = flatten_rows_for_wire(cps, [pod("nginx:1.21")])[0]
            batcher.screen_row(PolicyType.VALIDATE_ENFORCE, "Pod",
                               "default", row)
            assert batcher.stats.get("stream_rows", 0) >= 1
            assert batcher.stats.get("stream_wire_rows", 0) >= 1
        finally:
            batcher.stop()


class TestEvaluateBlock:
    def test_block_verdicts_match_webhook(self):
        _, batcher, server, cps = make_stack()
        try:
            resources = [pod("nginx:1.21"), pod("nginx:latest")]
            block = flatten_block_for_wire(cps, resources)
            results = batcher.evaluate_block(
                PolicyType.VALIDATE_ENFORCE, "Pod", "default", block)
            assert [st for st, _ in results] == [CLEAN, ATTENTION]
            for resource, (_, vrow) in zip(resources, results):
                out = server.handle(VALIDATING_WEBHOOK_PATH,
                                    review(resource))
                allowed = out["response"]["allowed"]
                denies = any(v is Verdict.FAIL for _, _, v, _ in vrow)
                assert allowed == (not denies)
        finally:
            batcher.stop()

    def test_block_path_does_no_reintern(self):
        _, batcher, _, cps = make_stack()
        try:
            block = flatten_block_for_wire(
                cps, [pod("nginx:1.21"), pod("nginx:latest")])
            # warm the shape, then measure the steady-state dispatch
            batcher.evaluate_block(PolicyType.VALIDATE_ENFORCE, "Pod",
                                   "default", block)
            before = (batcher.stats.get("stream_reintern_rows", 0),
                      batcher.stats.get("flatten_cache_miss_rows", 0))
            batcher.evaluate_block(PolicyType.VALIDATE_ENFORCE, "Pod",
                                   "default", block)
            after = (batcher.stats.get("stream_reintern_rows", 0),
                     batcher.stats.get("flatten_cache_miss_rows", 0))
            assert after == before
            assert batcher.stats.get("stream_blocks", 0) >= 2
        finally:
            batcher.stop()


class TestContinuousParity:
    def _drive(self, continuous, env):
        os.environ.update(env)
        try:
            _, batcher, _, cps = make_stack(continuous=continuous)
            try:
                images = [f"repo/app-{i}:latest" if i % 3 == 0
                          else f"repo/app-{i}:v1" for i in range(24)]
                results = [None] * len(images)
                threads = []

                def one(i):
                    # deadline-free with a generous budget: these lanes
                    # compare verdict ROWS, and a cold first flush paying
                    # XLA compilation on a loaded core can overrun the
                    # 2.5s admission deadline, turning one screen into a
                    # bail-to-oracle (ATTENTION, []) that has nothing to
                    # do with window semantics. Deadline behavior has its
                    # own coverage.
                    results[i] = batcher.screen(
                        PolicyType.VALIDATE_ENFORCE, "Pod", "default",
                        pod(images[i], name=f"p{i}"),
                        timeout_s=60.0, deadline_free=True)

                for i in range(len(images)):
                    t = threading.Thread(target=one, args=(i,))
                    t.start()
                    threads.append(t)
                for t in threads:
                    t.join()
                return results
            finally:
                batcher.stop()
        finally:
            for k in env:
                os.environ.pop(k, None)

    def test_stream_off_restores_window_semantics(self):
        on = self._drive(True, {})
        off = self._drive(True, {"KTPU_STREAM": "0"})
        window = self._drive(False, {})
        # all three lanes must agree on every verdict row
        for a, b, c in zip(on, off, window):
            assert a == b == c

    def test_stream_off_never_late_joins(self):
        _ = self._drive(True, {"KTPU_STREAM": "0"})
        # fresh batcher in _drive — assert via a dedicated run
        os.environ["KTPU_STREAM"] = "0"
        try:
            _, batcher, _, cps = make_stack(continuous=True)
            try:
                rows = flatten_rows_for_wire(cps, [pod("nginx:1.21")])
                for _ in range(8):
                    batcher.screen_row(PolicyType.VALIDATE_ENFORCE,
                                       "Pod", "default", rows[0])
                assert "stream_late_join_rows" not in batcher.stats
            finally:
                batcher.stop()
        finally:
            os.environ.pop("KTPU_STREAM", None)


class TestDonation:
    def test_donation_parity_and_host_buffer_intact(self):
        from kyverno_tpu.models.engine import DONATION_STATS
        _, batcher, _, cps = make_stack()
        try:
            block = flatten_block_for_wire(
                cps, [pod("nginx:1.21"), pod("nginx:latest")])
            blob, shp = block.packed_blob()
            snapshot = np.asarray(blob).copy()
            ref = np.asarray(cps.evaluate_device(block))
            before = DONATION_STATS["dispatches"]
            got = np.asarray(
                cps.evaluate_device_async(block, donate=True).get())
            assert DONATION_STATS["dispatches"] == before + 1
            assert np.array_equal(ref, got)
            # donation consumes the DEVICE copy only: the host-side blob
            # the batch caches must be bit-identical after the call
            assert np.array_equal(np.asarray(block.packed_blob()[0]),
                                  snapshot)
        finally:
            batcher.stop()

    def test_donate_kill_switch(self):
        from kyverno_tpu.models.engine import DONATION_STATS
        os.environ["KTPU_DONATE"] = "0"
        try:
            _, batcher, _, cps = make_stack()
            try:
                block = flatten_block_for_wire(cps, [pod("nginx:1.21")])
                before = DONATION_STATS["dispatches"]
                cps.evaluate_device_async(block, donate=True).get()
                assert DONATION_STATS["dispatches"] == before
            finally:
                batcher.stop()
        finally:
            os.environ.pop("KTPU_DONATE", None)


class TestEndToEnd:
    @pytest.mark.parametrize("transport", ["socket", "grpc"])
    def test_stream_matches_webhook(self, transport):
        if transport == "grpc":
            pytest.importorskip("grpc")
        _, batcher, server, cps = make_stack()
        ss = StreamServer(server, batcher, None,
                          transport=transport).start()
        cl = StreamClient(ss.port, transport=ss.transport_name)
        try:
            # JSON frames delegate to webhook.handle — exact parity
            for image in ("nginx:1.21", "nginx:latest"):
                direct = server.handle(VALIDATING_WEBHOOK_PATH,
                                       review(pod(image)))
                streamed = cl.admit_json(review(pod(image)))
                assert streamed["response"] == direct["response"]
            # columnar rows agree on allow/deny
            rows = flatten_rows_for_wire(cps, [pod("nginx:1.21"),
                                               pod("nginx:latest")])
            assert cl.admit_row("Pod", "default", rows[0])["allowed"]
            denied = cl.admit_row("Pod", "default", rows[1])
            assert not denied["allowed"]
            assert denied["verdicts"] == [
                ["disallow-latest-tag", "validate-image-tag",
                 int(Verdict.FAIL), ""]]
            # block frame
            block = flatten_block_for_wire(cps, [pod("nginx:1.21"),
                                                 pod("nginx:latest")])
            out = cl.admit_block("Pod", "default", block)
            assert [r["allowed"] for r in out["rows"]] == [True, False]
        finally:
            cl.close()
            ss.stop()
            batcher.stop()

    def test_socket_pipelined_burst(self):
        _, batcher, server, cps = make_stack()
        ss = StreamServer(server, batcher, None,
                          transport="socket").start()
        cl = StreamClient(ss.port, transport="socket")
        try:
            rows = flatten_rows_for_wire(cps, [pod("nginx:1.21"),
                                               pod("nginx:latest")])
            ids = [cl.submit_row("Pod", "default", rows[i % 2])
                   for i in range(48)]
            outs = [cl.result(i, timeout=30.0) for i in ids]
            assert [o["allowed"] for o in outs] == [i % 2 == 0
                                                   for i in range(48)]
        finally:
            cl.close()
            ss.stop()
            batcher.stop()

    def test_unknown_frame_type_errors(self):
        from kyverno_tpu.runtime.stream_server import (StreamAdmissionPlane,
                                                       decode_payload,
                                                       encode_payload,
                                                       F_ERROR)
        _, batcher, server, _ = make_stack()
        try:
            plane = StreamAdmissionPlane(server, batcher, None)
            resp = plane.handle_payload(
                encode_payload(0x42, 7, b""), "test")
            ftype, req_id, body = decode_payload(resp)
            assert ftype == F_ERROR
            assert req_id == 7
        finally:
            batcher.stop()
