"""Direct unit tests for runtime/watch (Reflector, WatchHub,
decode_watch_line) against a scripted in-memory client — the transport
suite (test_watch.py) covers the HTTP path; these pin the state-machine
semantics: rv tracking, bookmark handling, 410 re-list, hub fan-out and
late-subscriber replay."""

import threading
import time

from kyverno_tpu.runtime.watch import Reflector, WatchHub, decode_watch_line


def _obj(name, ns="default", rv="1", kind=None):
    o = {"metadata": {"name": name, "namespace": ns,
                      "resourceVersion": rv}}
    if kind:
        o["kind"] = kind
    return o


class ScriptedClient:
    """list_response/watch_stream client: each watch_stream call pops
    the next script (a list of (type, obj) frames). When more scripts
    remain the stream closes cleanly after its frames (forcing a
    reconnect); the last script blocks until stop is set (steady
    state, no reconnect churn)."""

    def __init__(self, items=None, scripts=None, rv="10"):
        self.items = items or []
        self.scripts = list(scripts or [])
        self.rv = rv
        self.lists = 0
        self.watch_calls = 0

    def list_response(self, api_version, kind, namespace=""):
        self.lists += 1
        return {"items": [dict(i) for i in self.items],
                "metadata": {"resourceVersion": self.rv}}

    def watch_stream(self, api_version, kind, namespace="",
                     resource_version=None, stop=None):
        self.watch_calls += 1
        self.last_rv_seen = resource_version
        script = self.scripts.pop(0) if self.scripts else []
        for frame in script:
            yield frame
        if self.scripts:
            return      # clean close; the reflector reconnects
        while stop is not None and not stop.is_set():
            time.sleep(0.01)


def _wait(pred, timeout=5.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.005)
    return False


def test_reflector_list_primes_and_defaults_gvk():
    client = ScriptedClient(items=[_obj("a"), _obj("b")])
    synced = []
    refl = Reflector(client, "v1", "Pod",
                     on_sync=lambda items: synced.append(items),
                     backoff_base_s=0.01)
    refl.start()
    assert refl.wait_synced(5.0)
    refl.stop()
    assert len(synced) == 1
    assert [o["metadata"]["name"] for o in synced[0]] == ["a", "b"]
    # list items omit kind/apiVersion; the reflector restores them
    assert all(o["kind"] == "Pod" for o in synced[0])
    assert all(o["apiVersion"] == "v1" for o in synced[0])
    assert refl.last_resource_version == "10"


def test_reflector_events_advance_rv_and_skip_bookmarks():
    client = ScriptedClient(scripts=[[
        ("ADDED", _obj("a", rv="11")),
        ("BOOKMARK", _obj("", rv="12")),
        ("MODIFIED", _obj("a", rv="13")),
    ]])
    events = []
    refl = Reflector(client, "v1", "Pod",
                     on_event=lambda t, o: events.append((t, o)),
                     backoff_base_s=0.01)
    refl.start()
    assert _wait(lambda: len(events) == 2)
    refl.stop()
    assert [t for t, _ in events] == ["ADDED", "MODIFIED"]
    # bookmarks checkpoint rv without reaching consumers
    assert refl.last_resource_version == "13"
    assert all(o["kind"] == "Pod" for _, o in events)


def test_reflector_410_gone_triggers_relist():
    client = ScriptedClient(scripts=[
        [("ERROR", {"code": 410})],       # first watch: rv too old
        [("ADDED", _obj("late", rv="21"))],
    ])
    events = []
    refl = Reflector(client, "v1", "Pod",
                     on_event=lambda t, o: events.append(t),
                     backoff_base_s=0.01)
    refl.start()
    assert _wait(lambda: client.lists >= 2 and events)
    refl.stop()
    assert refl.syncs >= 2


def test_reflector_watch_resumes_from_last_rv():
    client = ScriptedClient(scripts=[
        [("ADDED", _obj("a", rv="42"))],  # then clean close: reconnect
        [],
    ])
    refl = Reflector(client, "v1", "Pod", backoff_base_s=0.01)
    refl.start()
    assert _wait(lambda: client.watch_calls >= 2)
    refl.stop()
    # the reconnect resumed from the event's rv, not the list's
    assert client.last_rv_seen == "42"


def test_decode_watch_line():
    t, o = decode_watch_line(
        b'{"type":"ADDED","object":{"metadata":{"name":"x"}}}')
    assert t == "ADDED" and o["metadata"]["name"] == "x"
    assert decode_watch_line(b"") is None
    assert decode_watch_line(b"   \n") is None
    assert decode_watch_line(b"not json") is None
    t, o = decode_watch_line(b'{"type":"ERROR","object":{"code":410}}')
    assert t == "ERROR" and o["code"] == 410


def test_hub_ensure_is_idempotent_per_gvk():
    client = ScriptedClient(items=[_obj("a")])
    hub = WatchHub(client)
    r1 = hub.ensure("v1", "Pod", on_event=lambda t, o: None)
    r2 = hub.ensure("v1", "Pod", on_event=lambda t, o: None)
    other = hub.ensure("v1", "Service")
    assert r1 is r2
    assert other is not r1
    hub.stop()


def test_hub_late_subscriber_gets_watch_maintained_state():
    client = ScriptedClient(items=[_obj("a", rv="1")], scripts=[[
        ("ADDED", _obj("b", rv="11")),
        ("DELETED", _obj("a", rv="12")),
    ]])
    hub = WatchHub(client)
    first_events = []
    refl = hub.ensure("v1", "Pod",
                      on_event=lambda t, o: first_events.append(t))
    assert refl.wait_synced(5.0)
    assert _wait(lambda: len(first_events) == 2)

    # late joiner: replay must reflect list + every event since —
    # "b" added, "a" deleted — not the stale list
    late = []
    hub.ensure("v1", "Pod", on_sync=lambda items: late.append(items))
    assert _wait(lambda: late)
    names = sorted(o["metadata"]["name"] for o in late[0])
    assert names == ["b"]
    hub.stop()


def test_hub_fans_events_to_all_subscribers():
    release = threading.Event()

    class GatedClient(ScriptedClient):
        def watch_stream(self, *a, **kw):
            release.wait(5.0)
            yield from super().watch_stream(*a, **kw)

    client = GatedClient(scripts=[[("ADDED", _obj("x", rv="2"))]])
    hub = WatchHub(client)
    got_a, got_b = [], []
    hub.ensure("v1", "Pod", on_event=lambda t, o: got_a.append(t))
    hub.ensure("v1", "Pod", on_event=lambda t, o: got_b.append(t))
    release.set()
    assert _wait(lambda: got_a and got_b)
    hub.stop()
    assert got_a == ["ADDED"] and got_b == ["ADDED"]
