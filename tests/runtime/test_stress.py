"""Fault-injection / stress tier (SURVEY.md section 4.6).

The reference's LitmusChaos experiment (litmuschaos/pod_cpu_hog) hogs the
controller's CPU and asserts the webhook still enforces. The in-process
analogue: saturate every core with busy-loop threads while hammering the
HTTP webhook with concurrent mixed admissions — every verdict must still
be correct and the server must stay within the reference's 10s admission
budget. Plus the monitor's self-healing path: webhook configs deleted out
from under the controller are re-registered after the idle deadline."""

import concurrent.futures
import json
import threading
import time
import urllib.request

from kyverno_tpu.api.load import load_policy
from kyverno_tpu.runtime.client import FakeCluster
from kyverno_tpu.runtime.policycache import PolicyCache
from kyverno_tpu.runtime.webhook import VALIDATING_WEBHOOK_PATH, WebhookServer
from kyverno_tpu.runtime.webhookconfig import (
    VALIDATING_WEBHOOK_CONFIG,
    Monitor,
    Register,
)

ENFORCE = {
    "apiVersion": "kyverno.io/v1", "kind": "ClusterPolicy",
    "metadata": {"name": "disallow-latest-tag"},
    "spec": {
        "validationFailureAction": "enforce",
        "rules": [{
            "name": "validate-image-tag",
            "match": {"resources": {"kinds": ["Pod"]}},
            "validate": {"message": "latest tag not allowed",
                         "pattern": {"spec": {"containers": [
                             {"image": "!*:latest"}]}}},
        }],
    },
}


def review(i, image):
    return {"apiVersion": "admission.k8s.io/v1", "kind": "AdmissionReview",
            "request": {"uid": f"u{i}", "kind": {"kind": "Pod"},
                        "namespace": "default", "operation": "CREATE",
                        "object": {"apiVersion": "v1", "kind": "Pod",
                                   "metadata": {"name": f"p{i}",
                                                "namespace": "default"},
                                   "spec": {"containers": [
                                       {"name": "c", "image": image}]}}}}


def test_webhook_enforces_under_cpu_hog():
    cache = PolicyCache()
    cache.add(load_policy(ENFORCE))
    server = WebhookServer(policy_cache=cache, client=FakeCluster())
    httpd = server.run(host="127.0.0.1", port=0)
    port = httpd.server_address[1]

    stop = threading.Event()

    # GIL-sharing busy loops (a Python-thread CPU hog is harsher than the
    # litmus OS-level hog: it contends for the same interpreter lock the
    # handlers need); shrink the switch interval so the server still gets
    # scheduled the way OS preemption would provide
    import sys

    old_interval = sys.getswitchinterval()
    sys.setswitchinterval(0.0005)

    def burn():
        x = 0
        while not stop.is_set():
            x = (x * 31 + 7) % 1_000_003
        return x

    hogs = [threading.Thread(target=burn, daemon=True) for _ in range(4)]
    for h in hogs:
        h.start()

    def admit(i):
        image = "nginx:latest" if i % 3 == 0 else "nginx:1.21"
        body = json.dumps(review(i, image)).encode()
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}{VALIDATING_WEBHOOK_PATH}", data=body,
            headers={"Content-Type": "application/json"})
        t0 = time.monotonic()
        with urllib.request.urlopen(req, timeout=10) as resp:
            out = json.loads(resp.read())
        return i, out["response"]["allowed"], time.monotonic() - t0

    try:
        with concurrent.futures.ThreadPoolExecutor(8) as ex:
            results = list(ex.map(admit, range(60)))
    finally:
        stop.set()
        sys.setswitchinterval(old_interval)
        server.stop()

    lat = sorted(r[2] for r in results)
    for i, allowed, _ in results:
        assert allowed == (i % 3 != 0), f"wrong verdict under load for {i}"
    # reference admission budget: 10s webhook timeout
    assert lat[-1] < 10.0, f"p100 latency {lat[-1]:.1f}s exceeds the budget"


def test_monitor_reregisters_deleted_webhooks():
    """monitor.go:16-40: no admissions for 5 idle intervals -> the monitor
    re-registers deleted webhook configurations."""
    cluster = FakeCluster()
    register = Register(cluster)
    register.register()
    assert register.check()

    # a cluster admin deletes the configs out from under the controller
    register.remove()
    assert not register.check()

    monitor = Monitor(register)
    monitor.set_time(time.time() - 1000)  # far past the re-register deadline
    monitor.check_once()
    assert register.check(), "monitor did not self-heal the webhook configs"
