"""Dry-run service: planted blast radius, quiescence, HTTP surface."""

import json

import pytest

from kyverno_tpu.api.load import load_policy
from kyverno_tpu.runtime import obs_http
from kyverno_tpu.runtime.background import BackgroundScanner
from kyverno_tpu.workload import dryrun as dryrun_mod
from kyverno_tpu.workload.dryrun import (DRYRUN_SCHEMA_VERSION,
                                         DryRunDisabled, dry_run,
                                         set_scan_source)


@pytest.fixture(autouse=True)
def _isolate_scan_source():
    prev = dryrun_mod.scan_source()
    yield
    set_scan_source(prev)


def _pod(ns, name, app, tag):
    return {"apiVersion": "v1", "kind": "Pod",
            "metadata": {"name": name, "namespace": ns,
                         "labels": {"app": app}},
            "spec": {"containers": [{
                "name": "main", "image": f"registry.local/{app}:{tag}"}]}}


def _baseline_doc():
    return {"apiVersion": "kyverno.io/v1", "kind": "ClusterPolicy",
            "metadata": {"name": "no-latest"},
            "spec": {"validationFailureAction": "enforce",
                     "background": True, "rules": [{
                         "name": "no-latest",
                         "match": {"resources": {"kinds": ["Pod"]}},
                         "validate": {"message": "latest tag banned",
                                      "pattern": {"spec": {"containers": [
                                          {"image": "!*:latest"}]}}}}]}}


def _candidate_doc(name="block-app3", pattern=None, message="app-3 banned"):
    return {"apiVersion": "kyverno.io/v1", "kind": "ClusterPolicy",
            "metadata": {"name": name},
            "spec": {"validationFailureAction": "enforce",
                     "background": True, "rules": [{
                         "name": "r0",
                         "match": {"resources": {"kinds": ["Pod"]}},
                         "validate": {"message": message,
                                      "pattern": pattern or {
                                          "metadata": {"labels": {
                                              "app": "!app-3"}}}}}]}}


# Planted corpus: 3 app-3 pods (2 in ns-a, 1 in ns-b), 2 :latest pods.
CORPUS = [
    _pod("ns-a", "p0", "app-0", "v1"),
    _pod("ns-a", "p1", "app-3", "v1"),
    _pod("ns-a", "p2", "app-3", "latest"),
    _pod("ns-a", "p3", "app-1", "v2"),
    _pod("ns-b", "p4", "app-3", "v1"),
    _pod("ns-b", "p5", "app-2", "latest"),
    _pod("ns-b", "p6", "app-0", "v3"),
]


def _scanner():
    s = BackgroundScanner([load_policy(_baseline_doc())])
    s.scan(CORPUS)
    return s


def test_planted_blast_radius_counts_and_samples():
    scanner = _scanner()
    report = dry_run(_candidate_doc(), scanner=scanner, sample_limit=2)
    assert report["schema_version"] == DRYRUN_SCHEMA_VERSION
    assert report["policy"] == "block-app3"
    assert report["compile_lane"] == "incremental_isolated"
    assert report["resources_evaluated"] == len(CORPUS)
    # brand-new policy name: no baseline columns in the matrix
    assert report["baseline_present"] is False
    assert report["newly_failing"] == 3
    assert sorted(report["newly_failing_resources"]) == [
        "Pod/ns-a/p1", "Pod/ns-a/p2", "Pod/ns-b/p4"]
    assert report["per_namespace"] == {
        "ns-a": {"newly_failing": 2, "newly_passing": 0},
        "ns-b": {"newly_failing": 1, "newly_passing": 0}}
    assert len(report["samples"]) == 2
    assert all(s["rule"] == "r0" and "app-3" in s["message"]
               for s in report["samples"])
    dec = report["device_decidability"]
    assert dec["rules"] == 1
    assert dec["device_decidable"] + dec["host_only"] == dec["rules"]


def test_loosened_same_name_policy_reports_newly_passing():
    scanner = _scanner()
    # the live matrix FAILs the two :latest pods for "no-latest";
    # a loosened candidate under the same name flips them to passing
    loose = _candidate_doc(name="no-latest",
                           pattern={"spec": {"containers": [
                               {"image": "*"}]}},
                           message="anything goes")
    report = dry_run(loose, scanner=scanner)
    assert report["baseline_present"] is True
    assert report["newly_failing"] == 0
    assert report["newly_passing"] == 2
    assert sorted(report["newly_passing_resources"]) == [
        "Pod/ns-a/p2", "Pod/ns-b/p5"]
    assert report["still_failing"] == 0


def test_dry_run_leaves_scan_state_untouched():
    scanner = _scanner()
    before_fp = scanner.state_fingerprint()
    keys_b, cols_b, mat_b = scanner.verdict_matrix()
    dry_run(_candidate_doc(), scanner=scanner)
    dry_run(_candidate_doc(name="no-latest"), scanner=scanner)
    assert scanner.state_fingerprint() == before_fp
    keys_a, cols_a, mat_a = scanner.verdict_matrix()
    assert keys_a == keys_b and cols_a == cols_b
    assert mat_a.tobytes() == mat_b.tobytes()
    # the isolated candidate segment must not join the live cache
    assert not any(str(k).startswith("candidate:")
                   for k in scanner._inc._segments)


def test_gate_blocks_dry_run(monkeypatch):
    scanner = _scanner()
    monkeypatch.setenv("KTPU_DRYRUN", "0")
    with pytest.raises(DryRunDisabled):
        dry_run(_candidate_doc(), scanner=scanner)


def test_no_corpus_raises_value_error():
    with pytest.raises(ValueError, match="no scan corpus"):
        dry_run(_candidate_doc(), scanner=BackgroundScanner([]))


def test_explicit_resources_override_corpus():
    report = dry_run(_candidate_doc(), scanner=None,
                     resources=[_pod("x", "only", "app-3", "v1")])
    assert report["compile_lane"] == "one_shot"
    assert report["resources_evaluated"] == 1
    assert report["newly_failing"] == 1


# ------------------------------------------------------------ HTTP surface


def _post(body):
    return obs_http.handle_obs_post("/debug/dryrun", body)


def test_obs_post_full_report_via_registered_source():
    set_scan_source(_scanner())
    status, body, ctype = _post(json.dumps(
        {"policy": _candidate_doc(), "sample_limit": 1}).encode())
    assert status == 200 and ctype == "application/json"
    report = json.loads(body)
    assert report["newly_failing"] == 3
    assert len(report["samples"]) == 1


def test_obs_post_error_paths(monkeypatch):
    assert _post(b"{not json")[0] == 400
    assert _post(json.dumps({"nope": 1}).encode())[0] == 400
    # no scan source registered -> 503 service unavailable
    set_scan_source(None)
    status, body, _ = _post(json.dumps(
        {"policy": _candidate_doc()}).encode())
    assert status == 503 and b"corpus" in body
    monkeypatch.setenv("KTPU_DRYRUN", "0")
    assert _post(json.dumps({"policy": _candidate_doc()}).encode())[0] \
        == 403
    # non-dryrun POST paths fall through to the caller's routes
    assert obs_http.handle_obs_post("/mutate", b"{}") is None


def test_obs_get_dryrun_status():
    set_scan_source(_scanner())
    status, body, _ = obs_http.handle_obs_get("/debug/dryrun")
    assert status == 200
    payload = json.loads(body)
    assert payload["schema_version"] == DRYRUN_SCHEMA_VERSION
    assert payload["enabled"] is True
    assert payload["scan_source"] is True
    assert "POST" in payload["usage"]


def test_debug_payloads_carry_schema_version():
    for path in ("/debug/traces", "/debug/policies"):
        status, body, _ = obs_http.handle_obs_get(path)
        assert status == 200
        assert json.loads(body)["schema_version"] == \
            obs_http.DEBUG_SCHEMA_VERSION
