"""Replay driver: cross-leg verdict parity, gating, manifests."""

import pytest

from kyverno_tpu.api.load import load_policy
from kyverno_tpu.workload.replay import (MANIFEST_SCHEMA_VERSION,
                                         ReplayDisabled, ReplayDriver,
                                         build_stack, diff_manifests,
                                         run_manifest)
from kyverno_tpu.workload.trace import synthesize


def _policy_docs():
    return [
        {"apiVersion": "kyverno.io/v1", "kind": "ClusterPolicy",
         "metadata": {"name": "disallow-latest"},
         "spec": {"validationFailureAction": "enforce",
                  "background": True, "rules": [{
                      "name": "no-latest",
                      "match": {"resources": {"kinds": ["Pod"]}},
                      "validate": {"message": "latest tag banned",
                                   "pattern": {"spec": {"containers": [
                                       {"image": "!*:latest"}]}}}}]}},
        {"apiVersion": "kyverno.io/v1", "kind": "ClusterPolicy",
         "metadata": {"name": "require-team"},
         "spec": {"validationFailureAction": "enforce",
                  "background": True, "rules": [{
                      "name": "has-team",
                      "match": {"resources": {"kinds": ["Pod"]}},
                      "validate": {"message": "team label required",
                                   "pattern": {"metadata": {"labels": {
                                       "team": "?*"}}}}}]}},
    ]


@pytest.fixture(scope="module")
def stack():
    return build_stack([load_policy(d) for d in _policy_docs()])


@pytest.fixture(scope="module")
def trace():
    # mixed verdicts by construction: every 4th body template ships a
    # :latest image, so parity is checked on a non-trivial stream
    return synthesize(events=48, namespaces=3, name_pool=10,
                      distinct_bodies=8, seed=13)


def test_admission_leg_parity_and_capture(stack, trace):
    drv = ReplayDriver.from_stack(stack)
    results = {leg: drv.run(trace, leg, workers=4)
               for leg in ("webhook", "stream_json", "stream_row",
                           "stream_block")}
    digests = {r["verdict_digest"] for r in results.values()}
    assert len(digests) == 1, results
    web = results["webhook"]
    assert web["denied"] > 0                # mixed stream, not vacuous
    assert web["processed"] == web["events"] == len(
        [e for e in trace.events if e.op != "POLICY"])
    assert web["dropped"] == 0 and not web["errors"]
    assert web["latency_ms_p99"] >= web["latency_ms_p50"] >= 0
    assert web["queue_depth_max"] >= 1      # open loop: backlog visible
    assert results["stream_row"]["failing_resources"] == \
        web["failing_resources"]


def test_background_leg_matches_admission_failures(stack, trace):
    drv = ReplayDriver.from_stack(stack)
    web = drv.run(trace, "webhook", workers=4)
    bg = drv.run(trace, "background")
    assert bg["processed"] == bg["events"]
    assert bg["delta_scans"] >= 1
    assert bg["reflector_syncs"] >= 1
    # the persisted verdict matrix and the per-event admission stream
    # must agree on which live resources violate
    assert bg["failing_resources"] == web["failing_resources"]
    assert bg["violations"] > 0


def test_background_leg_policy_churn_runs_delta_scans():
    pols = [load_policy(_policy_docs()[0])]
    stack = build_stack(pols)
    churn_doc = _policy_docs()[1]
    tr = synthesize(events=60, namespaces=2, name_pool=8,
                    distinct_bodies=6, policy_docs=[churn_doc],
                    policy_churn_every=20, seed=21)
    assert any(e.op == "POLICY" for e in tr.events)
    drv = ReplayDriver.from_stack(stack)
    bg = drv.run(tr, "background")
    assert bg["delta_scans"] >= 2           # per POLICY event + final
    # the churned-in policy's columns joined the matrix
    _, cols, _ = stack["scanner"].verdict_matrix()
    assert any(c[0] == churn_doc["metadata"]["name"] for c in cols)


def test_arrival_faithful_mode_honors_trace_clock(stack):
    tr = synthesize(events=12, namespaces=2, base_rate=60.0, seed=8)
    drv = ReplayDriver.from_stack(stack)
    out = drv.run(tr, "stream_json", speed=1.0, workers=4)
    assert out["processed"] == out["events"]
    # dispatcher can't finish before the last scheduled arrival
    assert out["duration_s"] >= tr.events[-1].ts * 0.9


def test_replay_gate_blocks_injection(stack, trace, monkeypatch):
    monkeypatch.setenv("KTPU_REPLAY", "0")
    drv = ReplayDriver.from_stack(stack)
    with pytest.raises(ReplayDisabled):
        drv.run(trace, "webhook")
    with pytest.raises(ReplayDisabled):
        drv.run(trace, "background")


def test_unknown_leg_rejected(stack, trace):
    drv = ReplayDriver.from_stack(stack)
    with pytest.raises(ValueError, match="leg"):
        drv.run(trace, "carrier-pigeon")


def test_run_manifest_and_diff(stack, trace, tmp_path):
    import json

    drv = ReplayDriver.from_stack(stack)
    a = drv.run(trace, "stream_json", workers=4)
    b = drv.run(trace, "stream_json", workers=4)
    path = str(tmp_path / "run.json")
    ma = run_manifest(trace, [a], path=path, note="A")
    mb = run_manifest(trace, [b], note="B")
    assert ma["schema_version"] == MANIFEST_SCHEMA_VERSION
    assert ma["trace"]["digest"] == trace.content_digest()
    # per-event verdict maps stay out of the persisted manifest
    assert "verdicts" not in ma["legs"]["stream_json"]
    on_disk = json.load(open(path))
    assert on_disk["legs"]["stream_json"]["verdict_digest"] == \
        a["verdict_digest"]

    diff = diff_manifests(ma, mb)
    assert diff["same_trace"] is True
    assert diff["legs"]["stream_json"]["verdict_parity"] is True
    assert "latency_ms_p99_delta" in diff["legs"]["stream_json"]

    bad = dict(mb, schema_version=MANIFEST_SCHEMA_VERSION + 1)
    with pytest.raises(ValueError, match="schema_version"):
        diff_manifests(ma, bad)
