"""Chaos harness tests: the injectors restore what they disturb, the
brownout pool honors the miss contract, the replay manifest carries the
SLO record, and one full (small) scenario closes the loop end to end
— the latter marked slow; deploy/chaos_smoke.py is the CI gate."""

import os
import time

import pytest

from kyverno_tpu.workload import chaos


class _Webhook:
    """Minimal stand-in exposing the one method the latency injector
    wraps."""

    calls = 0

    def _resource_validation(self, request):
        type(self).calls += 1
        return ("verdict", request)


class TestInjectors:
    def test_inject_latency_wraps_and_restores(self):
        w = _Webhook()
        orig = w._resource_validation
        with chaos.inject_latency(w, 0.02):
            t0 = time.monotonic()
            out = w._resource_validation("req")
            assert time.monotonic() - t0 >= 0.02
            assert out == ("verdict", "req")     # delegates faithfully
        # instance shadow removed: back to the class method
        assert w._resource_validation.__func__ is orig.__func__

    def test_inject_latency_restores_on_error(self):
        w = _Webhook()
        try:
            with chaos.inject_latency(w, 0.0):
                raise RuntimeError("scenario died")
        except RuntimeError:
            pass
        assert "_resource_validation" not in vars(w)

    def test_brownout_pool_misses_within_timeout(self):
        pool = chaos.BrownoutPool(latency_s=10.0)
        t0 = time.monotonic()
        assert pool.evaluate_payload([], {}, {}, timeout_s=0.05) is None
        assert time.monotonic() - t0 < 1.0       # burns timeout, not 10s
        assert pool.ready(1) and pool.enabled
        assert pool.stats["misses"] == 1

    def test_env_overrides_restore_absence_and_value(self):
        os.environ["KTPU_CHAOS_T_PRESENT"] = "orig"
        os.environ.pop("KTPU_CHAOS_T_ABSENT", None)
        with chaos.env_overrides({"KTPU_CHAOS_T_PRESENT": "changed",
                                  "KTPU_CHAOS_T_ABSENT": "set"}):
            assert os.environ["KTPU_CHAOS_T_PRESENT"] == "changed"
            assert os.environ["KTPU_CHAOS_T_ABSENT"] == "set"
        assert os.environ.pop("KTPU_CHAOS_T_PRESENT") == "orig"
        assert "KTPU_CHAOS_T_ABSENT" not in os.environ

    def test_fast_env_declared_switches_only(self):
        from kyverno_tpu.runtime.featureplane import REGISTRY

        env = chaos.fast_env()
        assert env["KTPU_SLO_ACTIONS"] == "1"
        assert chaos.fast_env(actions="0")["KTPU_SLO_ACTIONS"] == "0"
        undeclared = [k for k in env if k not in REGISTRY]
        assert undeclared == [], undeclared

    def test_shrunk_lease_restores_constants(self):
        from kyverno_tpu.runtime import leaderelection as le

        saved = (le.LEASE_DURATION_S, le.RENEW_DEADLINE_S,
                 le.RETRY_PERIOD_S)
        with chaos.shrunk_lease(duration_s=0.6):
            assert le.LEASE_DURATION_S == 0.6
            assert le.RENEW_DEADLINE_S < 0.6
        assert (le.LEASE_DURATION_S, le.RENEW_DEADLINE_S,
                le.RETRY_PERIOD_S) == saved

    def test_inject_replica_loss_takeover(self):
        results = {}
        with chaos.inject_replica_loss(results):
            pass
        assert results["first_leader"] == "scanner-a"
        assert results["race_single_leader"]
        assert results["takeover"]
        assert results["takeover_s"] < 5.0


class TestManifestSlo:
    def test_run_manifest_carries_explicit_slo(self, tmp_path):
        from kyverno_tpu.workload.replay import (MANIFEST_SCHEMA_VERSION,
                                                 run_manifest)
        from kyverno_tpu.workload.trace import synthesize

        tr = synthesize(events=8, seed=3)
        leg = {"leg": "webhook", "events": 8, "verdict_digest": "d0"}
        slo = {"enabled": True, "state": "degraded", "shed": ["p"],
               "actions_active": ["shed"], "action_log": []}
        m = run_manifest(tr, [leg], path=str(tmp_path / "m.json"),
                         slo=slo)
        assert m["schema_version"] == MANIFEST_SCHEMA_VERSION >= 2
        assert m["slo"]["state"] == "degraded"

    def test_run_manifest_autocaptures_controller(self):
        from kyverno_tpu.runtime import sloactions
        from kyverno_tpu.workload.replay import run_manifest
        from kyverno_tpu.workload.trace import synthesize

        sloactions.controller().reset()
        tr = synthesize(events=8, seed=3)
        m = run_manifest(tr, [{"leg": "webhook", "events": 8,
                               "verdict_digest": "d0"}])
        assert m["slo"]["state"] == "healthy"
        assert m["slo"]["shed"] == []

    def test_diff_manifests_flags_slo_incomparability(self):
        from kyverno_tpu.workload.replay import (diff_manifests,
                                                 run_manifest)
        from kyverno_tpu.workload.trace import synthesize

        tr = synthesize(events=8, seed=3)
        leg = {"leg": "webhook", "events": 8, "verdict_digest": "d0"}
        healthy = {"enabled": True, "state": "healthy", "shed": [],
                   "actions_active": [], "action_log": []}
        shedding = {"enabled": True, "state": "degraded", "shed": ["p"],
                    "actions_active": ["shed"], "action_log": []}
        ma = run_manifest(tr, [leg], slo=healthy)
        mb = run_manifest(tr, [leg], slo=healthy)
        mc = run_manifest(tr, [leg], slo=shedding)
        assert diff_manifests(ma, mb)["slo"]["comparable"] is True
        d = diff_manifests(ma, mc)
        assert d["slo"]["comparable"] is False
        assert d["slo"]["b"]["shed"] == ["p"]


@pytest.mark.slow
class TestScenarioEndToEnd:
    def test_arrival_storm_closes_the_loop(self):
        rep = chaos.run_scenario("arrival_storm", events=24,
                                 delay_s=0.35, workers=6)
        assert rep["ok"], rep["checks"]
        assert rep["checks"]["recovery_digest_matches"]
        entered = [e for e in rep["action_log"] if e["event"] == "enter"]
        assert entered and all("t" in e for e in rep["action_log"])
        assert rep["manifest"]["slo"]["state"] == "healthy"

    def test_killswitch_restores_annotate_only(self):
        rep = chaos.run_scenario("arrival_storm", events=24,
                                 delay_s=0.35, workers=6, actions="0")
        assert rep["ok"], rep["checks"]
        assert rep["checks"]["no_actions_engaged"]
        assert rep["checks"]["episode_digest_matches"]
