"""Workload trace schema, synthesizer, and flight-ring importer."""

import json

import pytest

from kyverno_tpu.workload.trace import (TRACE_SCHEMA_VERSION, WorkloadTrace,
                                        body_digest, import_flight_ring,
                                        synthesize)


def test_jsonl_roundtrip_preserves_identity(tmp_path):
    tr = synthesize(events=150, namespaces=3, name_pool=20,
                    distinct_bodies=8, seed=5)
    path = str(tmp_path / "t.jsonl")
    tr.write_jsonl(path)
    back = WorkloadTrace.read_jsonl(path)
    assert back.content_digest() == tr.content_digest()
    assert back.meta == tr.meta
    assert len(back.events) == len(tr.events)
    assert back.bodies == tr.bodies


def test_bodies_stored_once_per_digest(tmp_path):
    tr = synthesize(events=300, namespaces=2, name_pool=6,
                    distinct_bodies=3, update_fraction=0.4, seed=1)
    # bounded name pool x tiny template pool: the body store must be
    # far smaller than the event stream (repeated-body distribution)
    assert len(tr.bodies) < len(tr.events) / 3
    path = str(tmp_path / "t.jsonl")
    tr.write_jsonl(path)
    body_lines = [ln for ln in open(path)
                  if json.loads(ln).get("t") == "body"]
    assert len(body_lines) == len(tr.bodies)


def test_schema_version_mismatch_rejected(tmp_path):
    path = str(tmp_path / "bad.jsonl")
    with open(path, "w") as f:
        f.write(json.dumps({"t": "hdr", "schema_version":
                            TRACE_SCHEMA_VERSION + 1, "meta": {}}) + "\n")
    with pytest.raises(ValueError, match="schema_version"):
        WorkloadTrace.read_jsonl(path)


def test_synthesizer_is_deterministic():
    a = synthesize(events=200, seed=9)
    b = synthesize(events=200, seed=9)
    c = synthesize(events=200, seed=10)
    assert a.content_digest() == b.content_digest()
    assert a.content_digest() != c.content_digest()


def test_zipf_namespace_skew():
    tr = synthesize(events=2000, namespaces=6, zipf_s=1.2, seed=2)
    by_ns = tr.stats()["by_namespace"]
    # rank-0 namespace dominates; the tail is thin
    assert by_ns["team-0"] > by_ns["team-5"] * 2
    assert by_ns["team-0"] > len(tr.events) / 6


def test_storm_windows_are_denser():
    tr = synthesize(events=1200, storm_period=400, storm_duty=0.25,
                    storm_factor=10.0, base_rate=100.0, seed=3)
    dts_storm, dts_calm = [], []
    for i in range(1, len(tr.events)):
        dt = tr.events[i].ts - tr.events[i - 1].ts
        (dts_storm if (i % 400) < 100 else dts_calm).append(dt)
    mean = lambda xs: sum(xs) / len(xs)  # noqa: E731
    assert mean(dts_storm) * 3 < mean(dts_calm)


def test_policy_churn_interleaves():
    doc = {"apiVersion": "kyverno.io/v1", "kind": "ClusterPolicy",
           "metadata": {"name": "p"}, "spec": {"rules": []}}
    tr = synthesize(events=300, policy_docs=[doc],
                    policy_churn_every=100, seed=4)
    pol_events = [e for e in tr.events if e.op == "POLICY"]
    assert len(pol_events) == 2
    assert all(tr.body_of(e)["kind"] == "ClusterPolicy"
               for e in pol_events)
    # churn rides the same clock as the resource stream
    ts = [e.ts for e in tr.events]
    assert ts == sorted(ts)


def test_delete_removes_only_live_names():
    tr = synthesize(events=800, delete_fraction=0.2, seed=6)
    live = set()
    for ev in tr.events:
        key = (ev.namespace, ev.name)
        if ev.op == "CREATE":
            live.add(key)
        elif ev.op == "UPDATE":
            assert key in live
        elif ev.op == "DELETE":
            assert key in live
            live.discard(key)


def test_body_digest_is_content_addressed():
    a = {"kind": "Pod", "metadata": {"name": "x"}}
    b = {"metadata": {"name": "x"}, "kind": "Pod"}
    assert body_digest(a) == body_digest(b)
    assert body_digest(a) != body_digest(
        {"kind": "Pod", "metadata": {"name": "y"}})


class _RingTrace:
    """Shape-compatible stand-in for a tracing.Trace in the flight ring."""

    def __init__(self, kind, t_wall, labels=None):
        self.kind = kind
        self.t_wall = t_wall
        self.labels = labels or {}
        self.trace_id = f"id-{t_wall}"


def test_flight_ring_import_preserves_order_and_ops():
    ring = [
        _RingTrace("admission", 100.0, {"kind": "Pod", "namespace": "a",
                                        "operation": "CREATE",
                                        "uid": "u1"}),
        _RingTrace("scan", 100.5),                      # filtered out
        _RingTrace("stream_admission", 101.0,
                   {"kind": "Pod", "namespace": "b",
                    "operation": "UPDATE", "uid": "u2"}),
        _RingTrace("admission", 102.25,
                   {"kind": "Deployment", "namespace": "a",
                    "operation": "DELETE", "uid": "u3"}),
    ]
    tr = import_flight_ring(traces=ring)
    assert tr.meta["reconstructed"] is True
    assert [e.op for e in tr.events] == ["CREATE", "UPDATE", "DELETE"]
    assert [e.ts for e in tr.events] == [0.0, 1.0, 2.25]
    assert tr.events[2].kind == "Deployment"
    # reconstructed bodies resolve through the body store like any other
    assert tr.body_of(tr.events[0])["metadata"]["uid"] == "u1"
