"""Byte-parity: native flattener vs the pure-Python reference.

Every array of the FlatBatch produced by native/ktpu_flatten.cpp must equal
flatten_batch's output exactly — including interning order, phantom slots,
null-break chains, numeric/duration decomposition and host-lane flags —
over the full adversarial cross-check corpus.
"""

import dataclasses

import numpy as np
import pytest

from kyverno_tpu.api.load import load_policies_from_path, load_policy
from kyverno_tpu.models import CompiledPolicySet
from kyverno_tpu.models.flatten import BATCH_ARRAYS, DICT_ARRAYS, flatten_batch
from kyverno_tpu.models.native_flatten import NativeFlattener, native_available

from test_cross_check import ADVERSARIAL_POLICIES, SYNTHETIC_POLICIES, corpus  # noqa: F401

pytestmark = pytest.mark.skipif(
    not native_available(), reason="native flattener not built"
)


@pytest.fixture(scope="module")
def tensors():
    try:
        policies = load_policies_from_path(
            "/root/reference/test/best_practices/")
    except FileNotFoundError:
        pytest.skip("reference policy corpus not present")
    policies += [load_policy(doc) for doc in SYNTHETIC_POLICIES]
    policies += [load_policy(doc) for doc in ADVERSARIAL_POLICIES]
    return CompiledPolicySet(policies).tensors


def assert_batches_equal(got, want):
    assert got.n == want.n and got.e == want.e
    for name in BATCH_ARRAYS + DICT_ARRAYS + ("num_val", "elem0"):
        g, w = getattr(got, name), getattr(want, name)
        assert g.dtype == w.dtype, name
        assert g.shape == w.shape, (name, g.shape, w.shape)
        if not np.array_equal(g, w):
            bad = np.argwhere(np.asarray(g) != np.asarray(w))[:5]
            raise AssertionError(f"{name} differs at {bad.tolist()}")
    assert got.strings == want.strings


def test_native_parity_corpus(tensors, corpus):  # noqa: F811
    native = NativeFlattener(tensors)
    got = native.flatten(corpus)
    assert got is not None
    want = flatten_batch(corpus, tensors)
    assert_batches_equal(got, want)


def test_native_parity_edge_values(tensors):
    resources = [
        # deep numeric / quantity / duration strings
        {"apiVersion": "v1", "kind": "Pod",
         "metadata": {"name": "edge", "namespace": "prod",
                      "annotations": {"timeout": "1h30m", "mem": "0.1",
                                      "team": "α-unicode- "}},
         "spec": {"containers": [
             {"name": "c", "image": "nginx:latest",
              "resources": {"requests": {"memory": "64Mi", "cpu": 0.5},
                            "limits": {"memory": "1e3", "cpu": 2}}},
             {"name": "d", "image": "x" * 80},  # > STR_LEN -> host lane
         ]}},
        # null leaves, scalar-through, empty containers
        {"apiVersion": "v1", "kind": "Pod",
         "metadata": {"name": None, "labels": {"tier": "web"}},
         "spec": {"containers": [], "hostNetwork": "not-a-bool"}},
        # non-dict spec: null-break chains
        {"apiVersion": "v1", "kind": "Pod", "metadata": {"name": "nb"},
         "spec": "oops"},
        # Namespace kind: effective-namespace synthetic path
        {"apiVersion": "v1", "kind": "Namespace", "metadata": {"name": "ns1"}},
        # floats that exercise Go scientific formatting + big ints
        {"apiVersion": "v1", "kind": "Pod",
         "metadata": {"name": "nums", "annotations": {"mem": "2Gi"}},
         "spec": {"containers": [{"name": "n", "ports": [
             {"containerPort": 10.25}, {"containerPort": 2 ** 70},
             {"containerPort": -3}, {"containerPort": 1e-7},
         ]}]}},
        # binary-repr artifact float: host lane on both tiers
        {"apiVersion": "v1", "kind": "Pod", "metadata": {"name": "f"},
         "spec": {"replicas": 0.1 + 0.2}},
        # >36-digit number part (exact even after the exponent): host lane
        # with empty numeric lanes on both tiers
        {"apiVersion": "v1", "kind": "Pod", "metadata": {"name": "cap",
         "annotations": {"mem": "0.0000000000000000000000000000000000001e31",
                         "big": "9" * 40}},
         "spec": {}},
        # unicode whitespace / digits: parse differs under unicode rules ->
        # host lane with empty numeric lanes on both tiers
        {"apiVersion": "v1", "kind": "Pod",
         "metadata": {"name": "u", "annotations": {
             "timeout": " 30s", "mem": "６４4Mi",
             "ctl": "\x1c5s"}},
         "spec": {}},
    ]
    native = NativeFlattener(tensors)
    got = native.flatten(resources)
    assert got is not None
    want = flatten_batch(resources, tensors)
    assert_batches_equal(got, want)


def test_native_parity_requests_envelope(tensors):
    resources = [
        {"apiVersion": "v1", "kind": "Pod", "metadata": {"name": "a"},
         "spec": {"hostPID": True}},
        {"apiVersion": "v1", "kind": "Pod", "metadata": {"name": "b"},
         "spec": {}},
    ]
    requests = [
        {"operation": "CREATE", "namespace": "prod",
         "userInfo": {"username": "alice", "groups": ["dev"]}},
        None,
    ]
    native = NativeFlattener(tensors)
    got = native.flatten(resources, requests=requests)
    assert got is not None
    want = flatten_batch(resources, tensors, requests=requests)
    assert_batches_equal(got, want)


def test_fields_covered():
    """BATCH_ARRAYS/DICT_ARRAYS + the host-side i64 sources cover every
    FlatBatch field, so the parity loop can't silently skip a new one."""
    from kyverno_tpu.models.flatten import FlatBatch

    field_names = {f.name for f in dataclasses.fields(FlatBatch)}
    checked = set(BATCH_ARRAYS + DICT_ARRAYS) | {
        "num_val", "elem0", "strings", "n", "e", "dur_val"}
    missing = field_names - checked
    assert not missing, f"parity test misses FlatBatch fields: {missing}"
