"""Host-lane ceiling on the bench library corpus.

BENCH_r03 measured host_cell_pct 7.55 on the 250-policy library; without
a CI ceiling a compiler regression could silently dump half the rule set
to the CPU oracle and every throughput number would quietly collapse
while tests stayed green. This pins both the cell-level and rule-level
ceilings with headroom above the measured value."""

import numpy as np

from kyverno_tpu.models import CompiledPolicySet, Verdict


def test_library_host_lane_ceiling():
    from bench import _library_250, mixed_resource

    cps = CompiledPolicySet(_library_250())
    host_rules = int(cps.tensors.rule_host_only.sum())
    n_rules = int(cps.tensors.n_rules)
    # measured r03/r04: 42 of 286 rules host-only (context/variable rules)
    assert host_rules / n_rules <= 0.20, (
        f"{host_rules}/{n_rules} rules compile host-only — device coverage "
        f"regressed")

    resources = [mixed_resource(i) for i in range(512)]
    verdicts = cps.evaluate_device(cps.flatten_packed(resources))
    host_pct = 100 * float((np.asarray(verdicts) == Verdict.HOST).mean())
    # measured 7.55% (BENCH_r03 config 3); ceiling leaves headroom for
    # corpus drift but catches a systemic routing regression
    assert host_pct <= 10.0, (
        f"host_cell_pct {host_pct:.2f} exceeds the 10% ceiling")
