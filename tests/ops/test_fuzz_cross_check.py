"""Property fuzz: random policies x random resources, device vs oracle.

The curated corpus (test_cross_check.py) covers every operator family by
construction; this fuzzer covers the space BETWEEN the curated cases —
randomly composed patterns (nested maps, arrays, anchors, operator
prefixes, ranges, compound |/& patterns), match/exclude blocks and
conditions, against randomly shaped resources. Seeded and deterministic:
any (policy, resource) disagreement on a non-HOST cell is a real bug with
a reproducible seed.
"""

import random

import numpy as np
import pytest

from kyverno_tpu.api.load import load_policy
from kyverno_tpu.models import CompiledPolicySet, Verdict

from test_cross_check import oracle_matrix

KEYS = ["alpha", "beta", "gamma", "delta", "data", "mode", "size"]
VALUES = ["on", "off", "fast", "slow-lane", "x1", "", "3", "250m", "1Gi",
          "2.5", "true", "us-east*", "pod-?2"]
SCALARS = [True, False, 0, 1, 7, 250, -3, 2.5, 0.1, "on", "off", "3",
           "100Mi", "x1", "", None]


def rand_leaf_pattern(rng):
    r = rng.random()
    if r < 0.30:
        v = rng.choice(VALUES)
        if rng.random() < 0.3:
            v = rng.choice(["*", "?*", "*-lane", "x?", "!off", "!*fast*"])
        return v
    if r < 0.45:
        op = rng.choice([">", ">=", "<", "<=", "!"])
        return f"{op}{rng.choice(['1', '5', '250m', '0.5', '1Gi'])}"
    if r < 0.52:
        return f"{rng.randint(0, 5)}-{rng.randint(5, 100)}"
    if r < 0.62:
        return " | ".join(rng.choice(VALUES) for _ in range(2))
    if r < 0.68:
        return " & ".join(rng.choice([">1", "<=250m", "?*", "on"])
                          for _ in range(2))
    if r < 0.72:
        # mixed compound / number-part-no-quantity operands: host-only
        # constructs must still agree via the oracle fallback
        return rng.choice(["on & off | ok", "0*", "!1x2", ">1x"])
    if r < 0.78:
        return None  # null pattern (validateValueWithNilPattern)
    if r < 0.86:
        return rng.choice([True, False])
    if r < 0.93:
        return rng.randint(0, 100)
    return rng.choice([0.25, 2.5, 9.0])


def rand_pattern(rng, depth=0):
    if depth >= 2 or rng.random() < 0.4:
        return rand_leaf_pattern(rng)
    if rng.random() < 0.25:
        return [rand_pattern(rng, depth + 1)]
    out = {}
    for _ in range(rng.randint(1, 3)):
        key = rng.choice(KEYS)
        if rng.random() < 0.3:
            kind = rng.choice(["(", "^(", "=(", "X(", "<("])
            key = f"{kind}{key})"
        out[key] = rand_pattern(rng, depth + 1)
    return out


def rand_condition(rng):
    key_field = rng.choice(KEYS)
    op = rng.choice(["Equals", "NotEquals", "In", "NotIn", "AnyIn",
                     "GreaterThan", "LessThanOrEquals",
                     "DurationGreaterThan"])
    if op in ("In", "NotIn", "AnyIn"):
        value = rng.choice([
            [rng.choice(VALUES) for _ in range(2)],
            rng.choice(["on", "x*", "pod-?2"]),
        ])
    elif op == "DurationGreaterThan":
        value = rng.choice(["30s", "2m", 45])
    else:
        value = rng.choice(SCALARS[:-1])
    return {"key": f"{{{{ request.object.data.{key_field} }}}}",
            "operator": op, "value": value}


def rand_policy(rng, i):
    rule = {"name": f"fz-{i}",
            "match": {"resources": {"kinds": [rng.choice(
                ["Pod", "ConfigMap", "*"])]}}}
    r = rng.random()
    if r < 0.36:
        rule["validate"] = {"pattern": {"data": rand_pattern(rng)}}
    elif r < 0.50:
        rule["validate"] = {"anyPattern": [
            {"data": rand_pattern(rng)}
            for _ in range(rng.randint(2, 3))]}
    elif r < 0.68:
        rule["validate"] = {"deny": {"conditions": {
            rng.choice(["any", "all"]): [rand_condition(rng)
                                         for _ in range(rng.randint(1, 2))]}}}
    elif r < 0.92:
        rule["preconditions"] = {"all": [rand_condition(rng)]}
        rule["validate"] = {"pattern": {"data": rand_pattern(rng)}}
    else:
        # foreach rules are host-only in the device IR (ir.py "foreach
        # rules"); the fuzz proves the compiler routes them to HOST and
        # the oracle evaluates the generated shapes without divergence
        rule["validate"] = {"foreach": [{
            "list": "request.object.data.items",
            "pattern": {"element": rand_pattern(rng)},
        }]}
    if rng.random() < 0.3:
        rule["exclude"] = {"resources": {
            "names": [rng.choice(["cm-1*", "pod-?2", "x*"])]}}
    return load_policy({
        "apiVersion": "kyverno.io/v1", "kind": "ClusterPolicy",
        "metadata": {"name": f"fuzz-{i}"},
        "spec": {"rules": [rule]}})


def rand_value(rng, depth=0):
    if depth >= 2 or rng.random() < 0.6:
        return rng.choice(SCALARS)
    if rng.random() < 0.3:
        return [rand_value(rng, depth + 1) for _ in range(rng.randint(0, 3))]
    return {rng.choice(KEYS): rand_value(rng, depth + 1)
            for _ in range(rng.randint(0, 3))}


def rand_resource(rng, i):
    data = {rng.choice(KEYS): rand_value(rng)
            for _ in range(rng.randint(0, 4))}
    if rng.random() < 0.4:
        data["items"] = [rand_value(rng, depth=1)
                         for _ in range(rng.randint(0, 3))]
    return {
        "apiVersion": "v1",
        "kind": rng.choice(["Pod", "ConfigMap", "Secret"]),
        "metadata": {"name": f"{rng.choice(['pod', 'cm', 'x'])}-{i % 40}"},
        "data": data,
    }


@pytest.mark.parametrize("seed", list(range(1, 65)) + [70, 114, 142])
def test_fuzz_device_matches_oracle(seed):
    # 70/114/142: an extended 256-seed sweep found anchors nested under
    # an ABSENT equality anchor over-failing on device (the cond-row
    # chain-failure mask ignored the =() guard bits); pinned forever
    rng = random.Random(20260730 + seed)
    policies = [rand_policy(rng, i) for i in range(10)]
    resources = [rand_resource(rng, i) for i in range(40)]
    cps = CompiledPolicySet(policies)
    # compiler guard: every foreach rule must have taken the host lane
    for r, ref in enumerate(cps.rule_refs):
        if ref.rule.validation is not None and ref.rule.validation.foreach:
            assert cps.tensors.rule_host_only[r], "foreach must be host-only"
    batch = cps.flatten(resources)
    device = np.asarray(cps.evaluate_device(batch))
    oracle = oracle_matrix(cps, resources)

    mismatches = []
    for b in range(len(resources)):
        for r in range(cps.tensors.n_rules):
            got = Verdict(device[b, r])
            if got == Verdict.HOST:
                continue
            if got != Verdict(oracle[b, r]):
                ref = cps.rule_refs[r]
                mismatches.append(
                    (seed, b, ref.policy.name,
                     Verdict(oracle[b, r]).name, got.name,
                     ref.policy.raw["spec"]["rules"][0], resources[b]))
    assert not mismatches, f"{len(mismatches)}; first: {mismatches[0]}"


def deep_pattern(rng, depth=0):
    """Depth-3, anchor-dense grammar: the round-5 sweep that found the
    gated-list presence hole, the global-anchor-in-array skip semantics,
    the existence-under-equality guard, and the order-dependent
    multi-anchor levels — all shapes the depth-2 grammar cannot emit."""
    if depth >= 3 or rng.random() < 0.3:
        return rand_leaf_pattern(rng)
    if rng.random() < 0.2:
        return [deep_pattern(rng, depth + 1)]
    out = {}
    for _ in range(rng.randint(1, 3)):
        key = rng.choice(KEYS)
        if rng.random() < 0.45:
            kind = rng.choice(["(", "^(", "=(", "X(", "<(", "=(", "<("])
            key = f"{kind}{key})"
        out[key] = deep_pattern(rng, depth + 1)
    return out


# 16 fresh seeds + every seed that ever found a divergence
@pytest.mark.parametrize("seed", list(range(1, 17))
                         + [46, 76, 83, 119, 190, 222])
def test_deep_fuzz_device_matches_oracle(seed):
    rng = random.Random(77000 + seed)
    policies = [load_policy({
        "apiVersion": "kyverno.io/v1", "kind": "ClusterPolicy",
        "metadata": {"name": f"deep-{i}"},
        "spec": {"rules": [{
            "name": f"dz-{i}",
            "match": {"resources": {"kinds": [rng.choice(
                ["Pod", "ConfigMap", "*"])]}},
            "validate": {"pattern": {"data": deep_pattern(rng)}}}]}})
        for i in range(10)]
    resources = [rand_resource(rng, i) for i in range(40)]
    cps = CompiledPolicySet(policies)
    device = np.asarray(cps.evaluate_device(cps.flatten(resources)))
    oracle = oracle_matrix(cps, resources)
    mismatches = []
    for b in range(len(resources)):
        for r in range(cps.tensors.n_rules):
            got = Verdict(device[b, r])
            if got == Verdict.HOST:
                continue
            if got != Verdict(oracle[b, r]):
                mismatches.append((seed, b, r, Verdict(oracle[b, r]).name,
                                   got.name))
    assert not mismatches, f"{len(mismatches)}; first: {mismatches[0]}"
