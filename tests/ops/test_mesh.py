"""Sharded evaluation over the virtual 8-device CPU mesh."""

import jax
import numpy as np
import pytest

from kyverno_tpu.api.load import load_policies_from_path, load_policy
from kyverno_tpu.models import CompiledPolicySet, Verdict
from kyverno_tpu.parallel import (
    make_mesh,
    mesh_from_env,
    parse_mesh_shape,
    sharded_scan,
)
from kyverno_tpu.parallel.mesh import (
    data_axis_size,
    is_2d,
    policy_axis_size,
    sharded_eval_fn,
)


@pytest.fixture(scope="module")
def cps():
    return CompiledPolicySet(
        load_policies_from_path("/root/reference/test/best_practices/")
    )


def make_pod(i: int) -> dict:
    return {
        "apiVersion": "v1",
        "kind": "Pod",
        "metadata": {"name": f"p{i}"},
        "spec": {"containers": [
            {"name": "c", "image": "nginx:latest" if i % 2 else "nginx:1.21"}
        ]},
    }


def test_sharded_scan_matches_single_device(cps):
    assert len(jax.devices()) == 8, "conftest must force 8 virtual devices"
    mesh = make_mesh()
    resources = [make_pod(i) for i in range(21)]  # deliberately non-multiple
    verdicts, fails, passes = sharded_scan(cps, resources, mesh)
    assert verdicts.shape[0] == 21

    # sharded_scan resolves HOST cells via the oracle, so compare against
    # the full single-chip evaluate (device + oracle)
    single = cps.evaluate(resources)
    assert (verdicts == single).all()
    assert not (verdicts == Verdict.HOST).any()

    want_fails = (single == Verdict.FAIL).sum(axis=0)
    np.testing.assert_array_equal(fails, want_fails)


def test_sharded_scan_chunked_pipeline(cps):
    """Snapshots beyond chunk_size stream through the flatten/eval
    pipeline; results must equal the unchunked scan."""
    mesh = make_mesh()
    resources = [make_pod(i) for i in range(50)]
    chunked, cf, cp_ = sharded_scan(cps, resources, mesh, chunk_size=16)
    whole, wf, wp = sharded_scan(cps, resources, mesh)
    assert (chunked == whole).all()
    np.testing.assert_array_equal(cf, wf)
    np.testing.assert_array_equal(cp_, wp)


def test_sharded_scan_resolves_host_lane():
    """A policy set containing host-only rules (variables in the pattern)
    must still produce their verdicts from a mesh scan — HOST cells resolve
    through the CPU oracle and the pass/fail counts include them."""
    from kyverno_tpu.api.load import load_policy

    device_rule = {
        "name": "no-latest",
        "match": {"resources": {"kinds": ["Pod"]}},
        "validate": {"pattern": {"spec": {"containers": [{"image": "!*:latest"}]}}},
    }
    host_rule = {
        "name": "name-is-itself",
        "match": {"resources": {"kinds": ["Pod"]}},
        "validate": {"pattern": {"metadata": {
            "name": "{{request.object.metadata.name}}"
        }}},
    }
    cps = CompiledPolicySet([load_policy({
        "apiVersion": "kyverno.io/v1",
        "kind": "ClusterPolicy",
        "metadata": {"name": "mixed-lanes"},
        "spec": {"rules": [device_rule, host_rule]},
    })])
    assert bool(cps.tensors.rule_host_only[1])

    resources = [make_pod(i) for i in range(13)]
    verdicts, fails, passes = sharded_scan(cps, resources, make_mesh())

    assert not (verdicts == Verdict.HOST).any()
    # the host rule passes every pod (name == itself after substitution)
    assert int(passes[1]) == len(resources)
    # counts were recomputed over the resolved matrix
    np.testing.assert_array_equal(fails, (verdicts == Verdict.FAIL).sum(axis=0))
    # and the whole matrix matches the single-chip full evaluate
    np.testing.assert_array_equal(verdicts, cps.evaluate(resources))


def test_mutate_gate_screen_on_mesh():
    """The batched mutate tier's gate matrix (match/exclude/preconditions
    screened as empty-pattern validate rules) evaluated SHARDED over the
    mesh must agree byte-for-byte with the single-device gate_verdicts —
    the round-5 evidence that the mutate screen is mesh-correct."""
    from kyverno_tpu.api.load import load_policy
    from kyverno_tpu.engine.mutate.batch import BatchMutator
    sel_policy = load_policy({
        "apiVersion": "kyverno.io/v1", "kind": "ClusterPolicy",
        "metadata": {"name": "annotate-bench-apps"},
        "spec": {"rules": [{
            "name": "annotate",
            "match": {"resources": {"kinds": ["Pod"], "selector": {
                "matchLabels": {"app.kubernetes.io/name": "bench"}}}},
            "mutate": {"patchStrategicMerge": {
                "metadata": {"annotations": {"+(bench/tier)": "gated"}}}},
        }]},
    })
    bm = BatchMutator([sel_policy])
    assert bm._gate_cps is not None

    def pod(i):
        p = make_pod(i)
        if i % 3 == 0:
            p["metadata"]["labels"] = {"app.kubernetes.io/name": "bench"}
        return p

    resources = [pod(i) for i in range(37)]       # ragged vs the mesh
    want = bm.gate_verdicts(resources)
    assert want is not None
    # selector rules are host-lane on device; the single-device path
    # resolved them — the gate really distinguishes the labeled subset
    gated = {i for i in range(37) if i % 3 == 0}
    passing = {int(b) for b, r in zip(*np.nonzero(want == Verdict.PASS))}
    assert passing == gated

    # the mesh path IS the public scan entry — no hand-rolled pipeline
    got, _, _ = sharded_scan(bm._gate_cps, resources, make_mesh())
    np.testing.assert_array_equal(got, want)


# ------------------------------------------------------- 2D (policy, data)


def _mixed_policies():
    """Synthetic mixed-lane corpus: device globs, numeric bounds, and a
    host-lane variable pattern — no /root/reference dependency."""
    def policy(name, pattern):
        return load_policy({
            "apiVersion": "kyverno.io/v1", "kind": "ClusterPolicy",
            "metadata": {"name": name},
            "spec": {"validationFailureAction": "enforce", "rules": [{
                "name": "r", "match": {"resources": {"kinds": ["Pod"]}},
                "validate": {"message": "m", "pattern": pattern},
            }]},
        })
    out = [policy(f"weight-{i}", {"spec": {"weight": f"<={30 + 20 * i}"}})
           for i in range(4)]
    out.append(policy("no-latest",
                      {"spec": {"containers": [{"image": "!*:latest"}]}}))
    out.append(policy("self-name",
                      {"metadata": {
                          "name": "{{request.object.metadata.name}}"}}))
    return out


def _mixed_pod(i):
    p = make_pod(i)
    p["spec"]["weight"] = (i * 17) % 120
    return p


class TestMeshShapeGrammar:
    def test_unset_and_1d_select_the_1d_mesh(self):
        assert parse_mesh_shape("", 8) is None
        assert parse_mesh_shape("1", 8) is None
        assert parse_mesh_shape("1d", 8) is None

    def test_auto_factors_the_device_count(self):
        assert parse_mesh_shape("auto", 8) == (2, 4)
        assert parse_mesh_shape("auto", 4) == (2, 2)
        assert parse_mesh_shape("auto", 16) == (4, 4)
        # no even pow2 split: everything stays on the data axis
        assert parse_mesh_shape("auto", 3) == (1, 3)

    def test_explicit_shape_must_multiply_out(self):
        assert parse_mesh_shape("2x4", 8) == (2, 4)
        with pytest.raises(ValueError, match="devices"):
            parse_mesh_shape("2x2", 8)
        with pytest.raises(ValueError, match="PxD"):
            parse_mesh_shape("garbage", 8)
        with pytest.raises(ValueError, match=">= 1"):
            parse_mesh_shape("0x8", 8)


class TestMakeMesh2D:
    def test_default_stays_1d(self, monkeypatch):
        monkeypatch.delenv("KTPU_MESH_SHAPE", raising=False)
        mesh = make_mesh()
        assert not is_2d(mesh)
        assert mesh.axis_names == ("data",)
        assert policy_axis_size(mesh) == 1
        assert data_axis_size(mesh) == 8
        assert mesh_from_env() is None

    def test_env_selects_2d(self, monkeypatch):
        monkeypatch.setenv("KTPU_MESH_SHAPE", "2x4")
        mesh = mesh_from_env()
        assert mesh is not None and is_2d(mesh)
        assert tuple(mesh.devices.shape) == (2, 4)
        assert policy_axis_size(mesh) == 2
        assert data_axis_size(mesh) == 4

    def test_explicit_shape_overrides_env(self, monkeypatch):
        monkeypatch.delenv("KTPU_MESH_SHAPE", raising=False)
        mesh = make_mesh(shape=(4, 2))
        assert tuple(mesh.devices.shape) == (4, 2)
        assert mesh.axis_names == ("policy", "data")

    def test_1d_program_refuses_2d_mesh(self):
        cps = CompiledPolicySet(_mixed_policies()[:1])
        with pytest.raises(ValueError, match="2D"):
            sharded_eval_fn(cps, make_mesh(shape=(2, 4)))


class Test2DScanParity:
    def test_2d_scan_matches_1d_and_unsharded(self):
        from kyverno_tpu.models.engine import shard_policies

        policies = _mixed_policies()
        cps = CompiledPolicySet(policies)
        resources = [_mixed_pod(i) for i in range(23)]  # ragged
        want = cps.evaluate(resources)

        v1, f1, p1 = sharded_scan(cps, resources, make_mesh())
        np.testing.assert_array_equal(v1, want)

        sps = shard_policies(policies, 2)
        v2, f2, p2 = sharded_scan(sps, resources, make_mesh(shape=(2, 4)))
        assert v2.dtype == v1.dtype
        np.testing.assert_array_equal(v2, want)
        np.testing.assert_array_equal(f2, f1)
        np.testing.assert_array_equal(p2, p1)
        assert not (v2 == Verdict.HOST).any()

    def test_plain_cps_wrapped_on_the_fly(self):
        policies = _mixed_policies()
        cps = CompiledPolicySet(policies)
        resources = [_mixed_pod(i) for i in range(9)]
        got, _, _ = sharded_scan(cps, resources, make_mesh(shape=(4, 2)))
        np.testing.assert_array_equal(got, cps.evaluate(resources))

    def test_2d_chunked_pipeline_parity(self):
        from kyverno_tpu.models.engine import shard_policies

        policies = _mixed_policies()
        sps = shard_policies(policies, 2)
        resources = [_mixed_pod(i) for i in range(50)]
        mesh = make_mesh(shape=(2, 4))
        chunked, cf, cp_ = sharded_scan(sps, resources, mesh, chunk_size=16)
        whole, wf, wp = sharded_scan(sps, resources, mesh)
        np.testing.assert_array_equal(chunked, whole)
        np.testing.assert_array_equal(cf, wf)
        np.testing.assert_array_equal(cp_, wp)

    def test_mesh_geometry_observable(self):
        from kyverno_tpu.models.engine import shard_policies
        from kyverno_tpu.runtime import metrics as metrics_mod

        reg = metrics_mod.registry()
        make_mesh(shape=(2, 4))
        assert reg.gauge_value("kyverno_mesh_shape",
                               {"axis": "policy"}) == 2.0
        assert reg.gauge_value("kyverno_mesh_shape",
                               {"axis": "data"}) == 4.0
        sps = shard_policies(_mixed_policies(), 2)
        for shard, n in sps.shard_rule_counts().items():
            assert reg.gauge_value("kyverno_mesh_shard_rules",
                                   {"shard": str(shard)}) == float(n)
        snap = metrics_mod.mesh_geometry_snapshot()
        assert snap["axes"] == {"policy": 2, "data": 4}
        assert snap["shard_rules"] == {
            str(k): v for k, v in sps.shard_rule_counts().items()}
        # a 1D rebuild replaces the axis map (no stale policy axis)
        make_mesh()
        assert metrics_mod.mesh_geometry_snapshot()["axes"] == {"data": 8}
