"""Sharded evaluation over the virtual 8-device CPU mesh."""

import jax
import numpy as np
import pytest

from kyverno_tpu.api.load import load_policies_from_path
from kyverno_tpu.models import CompiledPolicySet, Verdict
from kyverno_tpu.parallel import make_mesh, sharded_scan


@pytest.fixture(scope="module")
def cps():
    return CompiledPolicySet(
        load_policies_from_path("/root/reference/test/best_practices/")
    )


def make_pod(i: int) -> dict:
    return {
        "apiVersion": "v1",
        "kind": "Pod",
        "metadata": {"name": f"p{i}"},
        "spec": {"containers": [
            {"name": "c", "image": "nginx:latest" if i % 2 else "nginx:1.21"}
        ]},
    }


def test_sharded_scan_matches_single_device(cps):
    assert len(jax.devices()) == 8, "conftest must force 8 virtual devices"
    mesh = make_mesh()
    resources = [make_pod(i) for i in range(21)]  # deliberately non-multiple
    verdicts, fails, passes = sharded_scan(cps, resources, mesh)
    assert verdicts.shape[0] == 21

    # sharded_scan resolves HOST cells via the oracle, so compare against
    # the full single-chip evaluate (device + oracle)
    single = cps.evaluate(resources)
    assert (verdicts == single).all()
    assert not (verdicts == Verdict.HOST).any()

    want_fails = (single == Verdict.FAIL).sum(axis=0)
    np.testing.assert_array_equal(fails, want_fails)


def test_sharded_scan_chunked_pipeline(cps):
    """Snapshots beyond chunk_size stream through the flatten/eval
    pipeline; results must equal the unchunked scan."""
    mesh = make_mesh()
    resources = [make_pod(i) for i in range(50)]
    chunked, cf, cp_ = sharded_scan(cps, resources, mesh, chunk_size=16)
    whole, wf, wp = sharded_scan(cps, resources, mesh)
    assert (chunked == whole).all()
    np.testing.assert_array_equal(cf, wf)
    np.testing.assert_array_equal(cp_, wp)


def test_sharded_scan_resolves_host_lane():
    """A policy set containing host-only rules (variables in the pattern)
    must still produce their verdicts from a mesh scan — HOST cells resolve
    through the CPU oracle and the pass/fail counts include them."""
    from kyverno_tpu.api.load import load_policy

    device_rule = {
        "name": "no-latest",
        "match": {"resources": {"kinds": ["Pod"]}},
        "validate": {"pattern": {"spec": {"containers": [{"image": "!*:latest"}]}}},
    }
    host_rule = {
        "name": "name-is-itself",
        "match": {"resources": {"kinds": ["Pod"]}},
        "validate": {"pattern": {"metadata": {
            "name": "{{request.object.metadata.name}}"
        }}},
    }
    cps = CompiledPolicySet([load_policy({
        "apiVersion": "kyverno.io/v1",
        "kind": "ClusterPolicy",
        "metadata": {"name": "mixed-lanes"},
        "spec": {"rules": [device_rule, host_rule]},
    })])
    assert bool(cps.tensors.rule_host_only[1])

    resources = [make_pod(i) for i in range(13)]
    verdicts, fails, passes = sharded_scan(cps, resources, make_mesh())

    assert not (verdicts == Verdict.HOST).any()
    # the host rule passes every pod (name == itself after substitution)
    assert int(passes[1]) == len(resources)
    # counts were recomputed over the resolved matrix
    np.testing.assert_array_equal(fails, (verdicts == Verdict.FAIL).sum(axis=0))
    # and the whole matrix matches the single-chip full evaluate
    np.testing.assert_array_equal(verdicts, cps.evaluate(resources))
