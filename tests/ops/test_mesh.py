"""Sharded evaluation over the virtual 8-device CPU mesh."""

import jax
import numpy as np
import pytest

from kyverno_tpu.api.load import load_policies_from_path
from kyverno_tpu.models import CompiledPolicySet, Verdict
from kyverno_tpu.parallel import make_mesh, sharded_scan


@pytest.fixture(scope="module")
def cps():
    return CompiledPolicySet(
        load_policies_from_path("/root/reference/test/best_practices/")
    )


def make_pod(i: int) -> dict:
    return {
        "apiVersion": "v1",
        "kind": "Pod",
        "metadata": {"name": f"p{i}"},
        "spec": {"containers": [
            {"name": "c", "image": "nginx:latest" if i % 2 else "nginx:1.21"}
        ]},
    }


def test_sharded_scan_matches_single_device(cps):
    assert len(jax.devices()) == 8, "conftest must force 8 virtual devices"
    mesh = make_mesh()
    resources = [make_pod(i) for i in range(21)]  # deliberately non-multiple
    verdicts, fails, passes = sharded_scan(cps, resources, mesh)
    assert verdicts.shape[0] == 21

    # sharded_scan resolves HOST cells via the oracle, so compare against
    # the full single-chip evaluate (device + oracle)
    single = cps.evaluate(resources)
    assert (verdicts == single).all()
    assert not (verdicts == Verdict.HOST).any()

    want_fails = (single == Verdict.FAIL).sum(axis=0)
    np.testing.assert_array_equal(fails, want_fails)


def test_sharded_scan_chunked_pipeline(cps):
    """Snapshots beyond chunk_size stream through the flatten/eval
    pipeline; results must equal the unchunked scan."""
    mesh = make_mesh()
    resources = [make_pod(i) for i in range(50)]
    chunked, cf, cp_ = sharded_scan(cps, resources, mesh, chunk_size=16)
    whole, wf, wp = sharded_scan(cps, resources, mesh)
    assert (chunked == whole).all()
    np.testing.assert_array_equal(cf, wf)
    np.testing.assert_array_equal(cp_, wp)


def test_sharded_scan_resolves_host_lane():
    """A policy set containing host-only rules (variables in the pattern)
    must still produce their verdicts from a mesh scan — HOST cells resolve
    through the CPU oracle and the pass/fail counts include them."""
    from kyverno_tpu.api.load import load_policy

    device_rule = {
        "name": "no-latest",
        "match": {"resources": {"kinds": ["Pod"]}},
        "validate": {"pattern": {"spec": {"containers": [{"image": "!*:latest"}]}}},
    }
    host_rule = {
        "name": "name-is-itself",
        "match": {"resources": {"kinds": ["Pod"]}},
        "validate": {"pattern": {"metadata": {
            "name": "{{request.object.metadata.name}}"
        }}},
    }
    cps = CompiledPolicySet([load_policy({
        "apiVersion": "kyverno.io/v1",
        "kind": "ClusterPolicy",
        "metadata": {"name": "mixed-lanes"},
        "spec": {"rules": [device_rule, host_rule]},
    })])
    assert bool(cps.tensors.rule_host_only[1])

    resources = [make_pod(i) for i in range(13)]
    verdicts, fails, passes = sharded_scan(cps, resources, make_mesh())

    assert not (verdicts == Verdict.HOST).any()
    # the host rule passes every pod (name == itself after substitution)
    assert int(passes[1]) == len(resources)
    # counts were recomputed over the resolved matrix
    np.testing.assert_array_equal(fails, (verdicts == Verdict.FAIL).sum(axis=0))
    # and the whole matrix matches the single-chip full evaluate
    np.testing.assert_array_equal(verdicts, cps.evaluate(resources))


def test_mutate_gate_screen_on_mesh():
    """The batched mutate tier's gate matrix (match/exclude/preconditions
    screened as empty-pattern validate rules) evaluated SHARDED over the
    mesh must agree byte-for-byte with the single-device gate_verdicts —
    the round-5 evidence that the mutate screen is mesh-correct."""
    from kyverno_tpu.api.load import load_policy
    from kyverno_tpu.engine.mutate.batch import BatchMutator
    sel_policy = load_policy({
        "apiVersion": "kyverno.io/v1", "kind": "ClusterPolicy",
        "metadata": {"name": "annotate-bench-apps"},
        "spec": {"rules": [{
            "name": "annotate",
            "match": {"resources": {"kinds": ["Pod"], "selector": {
                "matchLabels": {"app.kubernetes.io/name": "bench"}}}},
            "mutate": {"patchStrategicMerge": {
                "metadata": {"annotations": {"+(bench/tier)": "gated"}}}},
        }]},
    })
    bm = BatchMutator([sel_policy])
    assert bm._gate_cps is not None

    def pod(i):
        p = make_pod(i)
        if i % 3 == 0:
            p["metadata"]["labels"] = {"app.kubernetes.io/name": "bench"}
        return p

    resources = [pod(i) for i in range(37)]       # ragged vs the mesh
    want = bm.gate_verdicts(resources)
    assert want is not None
    # selector rules are host-lane on device; the single-device path
    # resolved them — the gate really distinguishes the labeled subset
    gated = {i for i in range(37) if i % 3 == 0}
    passing = {int(b) for b, r in zip(*np.nonzero(want == Verdict.PASS))}
    assert passing == gated

    # the mesh path IS the public scan entry — no hand-rolled pipeline
    got, _, _ = sharded_scan(bm._gate_cps, resources, make_mesh())
    np.testing.assert_array_equal(got, want)
