"""Sharded evaluation over the virtual 8-device CPU mesh."""

import jax
import numpy as np
import pytest

from kyverno_tpu.api.load import load_policies_from_path
from kyverno_tpu.models import CompiledPolicySet, Verdict
from kyverno_tpu.parallel import make_mesh, sharded_scan


@pytest.fixture(scope="module")
def cps():
    return CompiledPolicySet(
        load_policies_from_path("/root/reference/test/best_practices/")
    )


def make_pod(i: int) -> dict:
    return {
        "apiVersion": "v1",
        "kind": "Pod",
        "metadata": {"name": f"p{i}"},
        "spec": {"containers": [
            {"name": "c", "image": "nginx:latest" if i % 2 else "nginx:1.21"}
        ]},
    }


def test_sharded_scan_matches_single_device(cps):
    assert len(jax.devices()) == 8, "conftest must force 8 virtual devices"
    mesh = make_mesh()
    resources = [make_pod(i) for i in range(21)]  # deliberately non-multiple
    verdicts, fails, passes = sharded_scan(cps, resources, mesh)
    assert verdicts.shape[0] == 21

    single = cps.evaluate_device(cps.flatten(resources))
    assert (verdicts == single).all()

    # report aggregation counts (over the padded batch; padding rows are
    # NOT_APPLICABLE so they do not count)
    want_fails = (single == Verdict.FAIL).sum(axis=0)
    np.testing.assert_array_equal(fails, want_fails)
