"""Glob NFA kernel vs the host wildcard oracle (utils/wildcard.py)."""

import numpy as np
import pytest

from kyverno_tpu.models.compiler import NFA_STATES, STR_LEN, _compile_glob
from kyverno_tpu.ops.glob import glob_match_matrix
from kyverno_tpu.utils.wildcard import wildcard_match

PATTERNS = [
    "*", "?*", "*:latest", "!ignored", "nginx:*", "*:*", "a*b*c", "???",
    "exact", "", "*.yaml", "a?c", "**", "*a*", "registry.io/*/img:*",
]

STRINGS = [
    "", "a", "abc", "nginx:latest", "nginx:1.21", "exact", "exact!",
    "aXbYc", "abcabc", "x.yaml", "yaml", "registry.io/team/img:v1",
    "a:b:c", "latest", ":latest", "aaa",
]


@pytest.fixture(scope="module")
def match_matrix():
    rows = [_compile_glob(p) for p in PATTERNS]
    assert all(r is not None for r in rows)
    nfa_char = np.stack([r[0] for r in rows])
    nfa_star = np.stack([r[1] for r in rows])
    nfa_q = np.stack([r[2] for r in rows])
    nfa_len = np.array([r[3] for r in rows], dtype=np.int32)
    str_bytes = np.zeros((len(STRINGS), STR_LEN), dtype=np.uint8)
    str_len = np.zeros(len(STRINGS), dtype=np.int32)
    for i, s in enumerate(STRINGS):
        bs = s.encode()
        str_bytes[i, : len(bs)] = np.frombuffer(bs, dtype=np.uint8)
        str_len[i] = len(bs)
    return np.asarray(
        glob_match_matrix(nfa_char, nfa_star, nfa_q, nfa_len, str_bytes, str_len)
    )


def test_matches_wildcard_oracle(match_matrix):
    mismatches = []
    for i, pattern in enumerate(PATTERNS):
        for j, s in enumerate(STRINGS):
            want = wildcard_match(pattern, s)
            got = bool(match_matrix[i, j])
            if want != got:
                mismatches.append((pattern, s, want, got))
    assert not mismatches, mismatches


def test_long_pattern_rejected():
    assert _compile_glob("x" * NFA_STATES) is None
    assert _compile_glob("é*") is None
