"""Packed transfer format: pack/unpack parity, native packed flatten
parity, and device-eval equivalence against the unpacked lane path.

The packed form (flatten.PACKED_BATCH_ARRAYS) is the transfer boundary for
every device dispatch — admission screens, mutate gates, background scans,
the mesh path — so a bit drifting here silently corrupts verdicts
everywhere. unpack(pack(x)) must reproduce the 22 evaluation lanes
byte-for-byte, and the C++ emitter (ktpu_flatten_packed) must agree with
the Python packer exactly.
"""

import json

import numpy as np
import pytest

from kyverno_tpu.api.load import load_policy
from kyverno_tpu.models import CompiledPolicySet
from kyverno_tpu.models.flatten import (
    BATCH_ARRAYS,
    DICT_ARRAYS,
    ELEM0_CAP,
    PackedBatch,
    flatten_batch,
    pack_batch,
    pad_to_buckets_packed,
    unpack_batch,
)
from kyverno_tpu.models.native_flatten import flatten_packed_fast, native_available
from kyverno_tpu.ops.eval import build_eval_fn, build_eval_fn_packed

LANES = BATCH_ARRAYS + DICT_ARRAYS


def _policy(pattern, name="p", kinds=("Pod",), **rule_extra):
    rule = {
        "name": "r",
        "match": {"resources": {"kinds": list(kinds)}},
        "validate": {"pattern": pattern},
        **rule_extra,
    }
    return load_policy({
        "apiVersion": "kyverno.io/v1",
        "kind": "ClusterPolicy",
        "metadata": {"name": name},
        "spec": {"rules": [rule]},
    })


# a pattern that tracks numeric, bool, glob, and list paths on device
_PATTERN = {
    "metadata": {"labels": {"tier": "?*"}},
    "spec": {
        "replicas": ">1",
        "hostNetwork": False,
        "containers": [{"image": "*:*", "resources": {
            "requests": {"memory": "<=1Gi"}}}],
    },
}

# duration lanes are exercised by the aux (deny-condition) program
_DENY_TTL = load_policy({
    "apiVersion": "kyverno.io/v1",
    "kind": "ClusterPolicy",
    "metadata": {"name": "deny-long-ttl"},
    "spec": {"rules": [{
        "name": "r",
        "match": {"resources": {"kinds": ["Pod"]}},
        "validate": {"deny": {"conditions": {"any": [
            {"key": "{{ request.object.spec.ttl }}",
             "operator": "DurationGreaterThan", "value": "45m"},
        ]}}},
    }]},
})

# resources exercising every lane class: ints, floats, quantities,
# durations, bools, unicode (host lane), deep lists, absent chains
_RESOURCES = [
    {"kind": "Pod", "metadata": {"labels": {"tier": "web"}},
     "spec": {"replicas": 3, "ttl": "30m", "hostNetwork": False,
              "containers": [{"image": "nginx:1.21",
                              "resources": {"requests": {"memory": "512Mi"}}}]}},
    {"kind": "Pod", "metadata": {"labels": {"tier": "db"}},
     "spec": {"replicas": 1.5, "ttl": "90m", "hostNetwork": True,
              "containers": [{"image": "redis:6",
                              "resources": {"requests": {"memory": "2Gi"}}},
                             {"image": "redis:7"}]}},
    {"kind": "Pod", "metadata": {},
     "spec": {"replicas": "2", "ttl": "0",
              "containers": []}},
    {"kind": "Pod", "metadata": {"labels": {"tier": "٣"}},   # arabic digit
     "spec": {"replicas": -7, "ttl": "1h30m",
              "containers": [{"image": "a"}]}},
    {"kind": "Service", "metadata": {"labels": {"tier": "x" * 80}},
     "spec": {"replicas": 10**40, "ttl": "2h",
              "containers": [{"image": "b:latest"}]}},
    {"kind": "Pod", "metadata": {"labels": {"tier": "0.25"}},
     "spec": {"replicas": 0, "ttl": "-5s", "hostNetwork": False,
              "containers": [{"image": "c", "resources": {
                  "requests": {"memory": "100m"}}}]}},
]


@pytest.fixture(scope="module")
def cps():
    out = CompiledPolicySet([_policy(_PATTERN), _DENY_TTL])
    # the fixture is only meaningful if the lanes actually reach the
    # device program — both rules must compile off the host lane
    assert not out.tensors.rule_host_only.any()
    return out


def test_pack_unpack_lane_parity(cps):
    fb = flatten_batch(_RESOURCES, cps.tensors)
    packed = pack_batch(fb)
    lanes = unpack_batch(*packed, xp=np)
    for name, got in zip(LANES, lanes):
        want = getattr(fb, name)
        if name == "host_flag":
            # packing may legitimately widen the host set (elem0 caps,
            # lost long-string values) but never narrow it
            assert (np.asarray(got) | want == np.asarray(got)).all(), name
            continue
        assert np.array_equal(np.asarray(got), want), name


def test_native_packed_matches_python_pack(cps):
    if not native_available():
        pytest.skip("native flattener unavailable")
    fb = flatten_batch(_RESOURCES, cps.tensors)
    want = pack_batch(fb)
    pb = flatten_packed_fast(cps.tensors, _RESOURCES)
    assert isinstance(pb, PackedBatch)
    for name, w, g in zip(("cells", "bmeta", "str_bytes", "dictv"),
                          want, pb.packed_args()):
        assert np.array_equal(np.asarray(w), np.asarray(g)), name


def test_native_packed_json_input_identical(cps):
    if not native_available():
        pytest.skip("native flattener unavailable")
    via_dicts = flatten_packed_fast(cps.tensors, _RESOURCES)
    js = json.dumps(_RESOURCES).encode()
    via_json = flatten_packed_fast(cps.tensors, json_docs=js,
                                   n_docs=len(_RESOURCES))
    for a, b in zip(via_dicts.packed_args(), via_json.packed_args()):
        assert np.array_equal(np.asarray(a), np.asarray(b))


def test_pyobject_walk_matches_json_parse(cps):
    """The PyObject direct-walk entry (no serialization) must be
    byte-identical to serialize-then-parse for every lane class in the
    corpus — including the unicode/host-lane and huge-int rows."""
    import kyverno_tpu.models.native_flatten as nf

    if not native_available() or nf._pylib is None:
        pytest.skip("PyObject flatten entry unavailable")
    ctx = nf._flattener_for(cps.tensors)
    via_py = ctx._flatten_packed_py(_RESOURCES, None, 16)
    assert via_py is not None
    js = json.dumps(_RESOURCES).encode()
    via_json = ctx.flatten_packed(json_docs=js, n_docs=len(_RESOURCES))
    for name, a, b in zip(("cells", "bmeta", "str_bytes", "dictv"),
                          via_py.packed_args(), via_json.packed_args()):
        assert np.array_equal(np.asarray(a), np.asarray(b)), name


def test_pyobject_walk_nonfinite_float_falls_back(cps):
    """Non-finite floats can't ride the direct walk (json.dumps would
    emit Infinity, which the JSON grammar rejects) — the wrapper must
    still return a usable batch via the pure-Python fallback, with the
    resource on the host lane."""
    bad = dict(_RESOURCES[0], spec=dict(_RESOURCES[0]["spec"],
                                        replicas=float("inf")))
    pb = flatten_packed_fast(cps.tensors, [bad])
    assert pb is not None
    assert (np.asarray(pb.bmeta)[0] >> 16) & 1 == 1   # host lane


def test_threaded_flatten_byte_parity(cps, monkeypatch):
    """The thread-sharded packed flatten (json_docs path, forced via
    KTPU_FLATTEN_THREADS) must reproduce the sequential interning order
    and every output byte."""
    import kyverno_tpu.models.native_flatten as nf

    if not native_available():
        pytest.skip("native flattener unavailable")
    resources = [_RESOURCES[i % len(_RESOURCES)] for i in range(300)]
    # vary names so the dictionary grows across shard boundaries
    resources = [dict(r, metadata=dict(r.get("metadata") or {},
                                       name=f"r-{i}"))
                 for i, r in enumerate(resources)]
    js = json.dumps(resources).encode()
    ctx = nf._flattener_for(cps.tensors)
    monkeypatch.setenv("KTPU_FLATTEN_THREADS", "4")
    thr = ctx.flatten_packed(json_docs=js, n_docs=len(resources))
    monkeypatch.setenv("KTPU_FLATTEN_THREADS", "1")
    seq = ctx.flatten_packed(json_docs=js, n_docs=len(resources))
    for name, a, b in zip(("cells", "bmeta", "str_bytes", "dictv"),
                          thr.packed_args(), seq.packed_args()):
        assert np.array_equal(np.asarray(a), np.asarray(b)), name


def test_packed_eval_matches_unpacked(cps):
    fb = flatten_batch(_RESOURCES, cps.tensors)
    want = np.asarray(build_eval_fn(cps.tensors)(*fb.device_args()))
    got = np.asarray(build_eval_fn_packed(cps.tensors)(*pack_batch(fb)))
    assert np.array_equal(want, got)


def test_blob_roundtrip_and_eval(cps):
    pb = flatten_packed_fast(cps.tensors, _RESOURCES)
    blob, (B, P, E, V) = pb.packed_blob()
    assert blob.dtype == np.uint32
    assert blob.size == B * P * E * 2 + B + V * 5 + V * 16
    want = cps.evaluate(_RESOURCES)           # full engine (oracle-resolved)
    got = cps.resolve_host_cells(_RESOURCES, cps.evaluate_device(pb))
    assert np.array_equal(want, got)


def test_to_flat_roundtrip(cps):
    if not native_available():
        pytest.skip("native flattener unavailable")
    fb = flatten_batch(_RESOURCES, cps.tensors)
    flat = flatten_packed_fast(cps.tensors, _RESOURCES).to_flat()
    for name in LANES + ("num_val",):
        if name == "host_flag":
            continue
        assert np.array_equal(getattr(flat, name), getattr(fb, name)), name
    assert flat.strings == fb.strings


def test_elem0_overflow_takes_host_lane(cps):
    big = {"kind": "Pod", "metadata": {"labels": {"tier": "t"}},
           "spec": {"replicas": 1, "ttl": "1s",
                    "containers": [{"image": f"i{k}"}
                                   for k in range(ELEM0_CAP + 4)]}}
    fb = flatten_batch([big], cps.tensors, max_slots=ELEM0_CAP + 8)
    cells, bmeta, *_ = pack_batch(fb)
    assert (bmeta[0] >> 16) & 1 == 1          # host bit set
    # and the full engine still answers correctly via the oracle
    verdicts = cps.evaluate([big])
    assert verdicts.shape == (1, len(cps.rule_refs))


def test_pad_to_buckets_packed_dead_rows(cps):
    pb = flatten_packed_fast(cps.tensors, _RESOURCES[:3])
    padded, n0 = pad_to_buckets_packed(pb)
    assert n0 == 3
    assert padded.cells.shape[0] == 4
    assert padded.bmeta[3] == 0               # dead row: not live
    v_pad = cps.evaluate_device(padded)[:n0]
    v_raw = cps.evaluate_device(pb)
    assert np.array_equal(v_pad, v_raw)


def test_library_corpus_packed_equivalence():
    """Every policy in the bundled bench library evaluates identically
    through the packed path and the unpacked lane path."""
    import sys
    sys.path.insert(0, "/root/repo")
    from bench import _library_250, mixed_resource

    cps = CompiledPolicySet(_library_250())
    resources = [mixed_resource(i) for i in range(256)]
    fb = cps.flatten(resources)
    want = np.asarray(cps.eval_fn(*fb.device_args()))
    got = cps.evaluate_device(cps.flatten_packed(resources))
    assert np.array_equal(want, got)
