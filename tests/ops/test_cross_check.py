"""TPU tier vs CPU oracle: every device verdict must equal the oracle's.

This is the conformance harness from SURVEY.md section 4 ("same test.yaml,
two backends, diff the verdict matrices"): the best_practices policy corpus
plus synthetic anchor-heavy policies are evaluated against a randomized pod
corpus on both tiers; any disagreement on a device-lane cell is a bug.
"""

import random

import numpy as np
import pytest

from kyverno_tpu.api.load import load_policies_from_path, load_policy
from kyverno_tpu.engine.context import Context
from kyverno_tpu.engine.policy_context import PolicyContext
from kyverno_tpu.engine.response import RuleStatus
from kyverno_tpu.engine.validation import validate as oracle_validate
from kyverno_tpu.models import CompiledPolicySet, Verdict

_STATUS_TO_VERDICT = {
    RuleStatus.PASS: Verdict.PASS,
    RuleStatus.FAIL: Verdict.FAIL,
    RuleStatus.WARN: Verdict.PASS,
    RuleStatus.ERROR: Verdict.ERROR,
    RuleStatus.SKIP: Verdict.SKIP,
}


def oracle_matrix(cps: CompiledPolicySet, resources: list[dict]) -> np.ndarray:
    out = np.zeros((len(resources), cps.tensors.n_rules), dtype=np.int8)
    for b, resource in enumerate(resources):
        for policy in cps.policies:
            jctx = Context()
            jctx.add_resource(resource)
            resp = oracle_validate(
                PolicyContext(policy=policy, new_resource=resource, json_context=jctx)
            )
            statuses = {rr.name: rr.status for rr in resp.policy_response.rules}
            for ref in cps.rule_refs:
                if ref.policy is policy and ref.rule.name in statuses:
                    out[b, ref.rule_index] = _STATUS_TO_VERDICT[statuses[ref.rule.name]]
    return out


def random_pod(rng: random.Random) -> dict:
    def maybe(p, v, default=None):
        return v if rng.random() < p else default

    containers = []
    for i in range(rng.randint(0, 3)):
        c = {"name": rng.choice(["web", "app", "sidecar", ""]) or f"c{i}"}
        image = rng.choice(
            ["nginx:latest", "nginx:1.21", "redis", "registry.io/a/b:v2", "busybox:stable"]
        )
        if rng.random() < 0.9:
            c["image"] = image
        if rng.random() < 0.4:
            c["securityContext"] = {}
            if rng.random() < 0.7:
                c["securityContext"]["privileged"] = rng.random() < 0.5
            if rng.random() < 0.5:
                c["securityContext"]["allowPrivilegeEscalation"] = rng.random() < 0.5
        if rng.random() < 0.5:
            res = {}
            if rng.random() < 0.8:
                res["requests"] = {
                    k: v
                    for k, v in (
                        ("memory", maybe(0.8, rng.choice(["64Mi", "1Gi", "100M"]))),
                        ("cpu", maybe(0.7, rng.choice(["100m", "1", "0.5"]))),
                    )
                    if v
                }
            if rng.random() < 0.6:
                res["limits"] = {
                    k: v
                    for k, v in (("memory", maybe(0.8, rng.choice(["128Mi", "2Gi"]))),)
                    if v
                }
            c["resources"] = res
        if rng.random() < 0.3:
            ports = []
            for _ in range(rng.randint(0, 2)):
                port = {"containerPort": rng.randint(1, 65535)}
                if rng.random() < 0.4:
                    port["hostPort"] = rng.randint(1, 65535)
                ports.append(port)
            c["ports"] = ports
        containers.append(c)

    pod = {
        "apiVersion": "v1",
        "kind": rng.choice(["Pod", "Pod", "Pod", "Service", "Deployment"]),
        "metadata": {"name": f"pod-{rng.randint(0, 999)}"},
        "spec": {},
    }
    if containers or rng.random() < 0.8:
        pod["spec"]["containers"] = containers
    if rng.random() < 0.4:
        labels = {}
        if rng.random() < 0.7:
            labels["app.kubernetes.io/name"] = rng.choice(["x", ""])
        if rng.random() < 0.5:
            labels["app.kubernetes.io/component"] = "api"
        pod["metadata"]["labels"] = labels
    if rng.random() < 0.3:
        pod["spec"]["hostNetwork"] = rng.random() < 0.5
    if rng.random() < 0.2:
        pod["spec"]["hostPID"] = rng.random() < 0.5
    if rng.random() < 0.3:
        vols = []
        for i in range(rng.randint(0, 2)):
            vol = {"name": f"v{i}"}
            if rng.random() < 0.5:
                vol["hostPath"] = {"path": "/var/run/docker.sock"}
            else:
                vol["emptyDir"] = {}
            vols.append(vol)
        pod["spec"]["volumes"] = vols
    if rng.random() < 0.2:
        pod["spec"]["securityContext"] = (
            {"sysctls": [{"name": "net.core.somaxconn", "value": "1024"}]}
            if rng.random() < 0.5
            else {}
        )
    return pod


SYNTHETIC_POLICIES = [
    # element gates: containers with :latest images must pull Always
    {
        "apiVersion": "kyverno.io/v1",
        "kind": "ClusterPolicy",
        "metadata": {"name": "synthetic-gate"},
        "spec": {"rules": [{
            "name": "latest-pull-always",
            "match": {"resources": {"kinds": ["Pod"]}},
            "validate": {"pattern": {"spec": {"containers": [
                {"(image)": "*:latest", "imagePullPolicy": "Always"}
            ]}}},
        }]},
    },
    # anyPattern
    {
        "apiVersion": "kyverno.io/v1",
        "kind": "ClusterPolicy",
        "metadata": {"name": "synthetic-anypattern"},
        "spec": {"rules": [{
            "name": "nginx-or-redis",
            "match": {"resources": {"kinds": ["Pod"]}},
            "validate": {"anyPattern": [
                {"spec": {"containers": [{"image": "nginx:*"}]}},
                {"spec": {"containers": [{"image": "redis*"}]}},
            ]},
        }]},
    },
    # numeric operators + ranges + compound
    {
        "apiVersion": "kyverno.io/v1",
        "kind": "ClusterPolicy",
        "metadata": {"name": "synthetic-numeric"},
        "spec": {"rules": [{
            "name": "port-range",
            "match": {"resources": {"kinds": ["Pod"]}},
            "validate": {"pattern": {"spec": {"containers": [
                {"ports": [{"containerPort": "1024-65535"}]}
            ]}}},
        }]},
    },
    # condition anchor at map level
    {
        "apiVersion": "kyverno.io/v1",
        "kind": "ClusterPolicy",
        "metadata": {"name": "synthetic-cond"},
        "spec": {"rules": [{
            "name": "hostnetwork-requires-label",
            "match": {"resources": {"kinds": ["Pod"]}},
            "validate": {"pattern": {
                "spec": {"(hostNetwork)": True},
                "metadata": {"labels": {"app.kubernetes.io/name": "?*"}},
            }},
        }]},
    },
]


@pytest.fixture(scope="module")
def corpus():
    rng = random.Random(20260729)
    return [random_pod(rng) for _ in range(120)]


@pytest.fixture(scope="module")
def policy_set():
    policies = load_policies_from_path("/root/reference/test/best_practices/")
    policies += [load_policy(doc) for doc in SYNTHETIC_POLICIES]
    return CompiledPolicySet(policies)


def test_device_lane_compiles_most_rules(policy_set):
    hosts = [r for r in policy_set.rule_irs if r.host_only]
    assert len(hosts) <= 2, [(h.rule_name, h.host_reason) for h in hosts]


def test_cross_check_verdicts(policy_set, corpus):
    batch = policy_set.flatten(corpus)
    device = policy_set.evaluate_device(batch)
    oracle = oracle_matrix(policy_set, corpus)

    mismatches = []
    for b in range(len(corpus)):
        for r in range(policy_set.tensors.n_rules):
            got = Verdict(device[b, r])
            if got == Verdict.HOST:
                continue  # host lane defers to the oracle by construction
            want = Verdict(oracle[b, r])
            if got != want:
                ref = policy_set.rule_refs[r]
                mismatches.append(
                    (b, ref.policy.name, ref.rule.name, want.name, got.name,
                     corpus[b])
                )
    assert not mismatches, f"{len(mismatches)} mismatches; first: {mismatches[0]}"


def test_full_evaluate_matches_oracle(policy_set, corpus):
    verdicts = policy_set.evaluate(corpus[:30])
    oracle = oracle_matrix(policy_set, corpus[:30])
    assert (verdicts == oracle).all()
