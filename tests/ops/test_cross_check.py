"""TPU tier vs CPU oracle: every device verdict must equal the oracle's.

This is the conformance harness from SURVEY.md section 4 ("same test.yaml,
two backends, diff the verdict matrices"): the best_practices policy corpus
plus synthetic anchor-heavy policies are evaluated against a randomized pod
corpus on both tiers; any disagreement on a device-lane cell is a bug.
"""

import random

import numpy as np
import pytest

from kyverno_tpu.api.load import load_policies_from_path, load_policy
from kyverno_tpu.engine.context import Context
from kyverno_tpu.engine.policy_context import PolicyContext
from kyverno_tpu.engine.response import RuleStatus
from kyverno_tpu.engine.validation import validate as oracle_validate
from kyverno_tpu.models import CompiledPolicySet, Verdict

_STATUS_TO_VERDICT = {
    RuleStatus.PASS: Verdict.PASS,
    RuleStatus.FAIL: Verdict.FAIL,
    RuleStatus.WARN: Verdict.PASS,
    RuleStatus.ERROR: Verdict.ERROR,
    RuleStatus.SKIP: Verdict.SKIP,
}


def oracle_matrix(cps: CompiledPolicySet, resources: list[dict]) -> np.ndarray:
    out = np.zeros((len(resources), cps.tensors.n_rules), dtype=np.int8)
    for b, resource in enumerate(resources):
        for policy in cps.policies:
            jctx = Context()
            jctx.add_resource(resource)
            resp = oracle_validate(
                PolicyContext(policy=policy, new_resource=resource, json_context=jctx)
            )
            statuses = {rr.name: rr.status for rr in resp.policy_response.rules}
            for ref in cps.rule_refs:
                if ref.policy is policy and ref.rule.name in statuses:
                    out[b, ref.rule_index] = _STATUS_TO_VERDICT[statuses[ref.rule.name]]
    return out


def random_pod(rng: random.Random) -> dict:
    def maybe(p, v, default=None):
        return v if rng.random() < p else default

    containers = []
    for i in range(rng.randint(0, 3)):
        c = {"name": rng.choice(["web", "app", "sidecar", ""]) or f"c{i}"}
        image = rng.choice(
            ["nginx:latest", "nginx:1.21", "redis", "registry.io/a/b:v2", "busybox:stable"]
        )
        if rng.random() < 0.9:
            c["image"] = image
        if rng.random() < 0.4:
            c["securityContext"] = {}
            if rng.random() < 0.7:
                c["securityContext"]["privileged"] = rng.random() < 0.5
            if rng.random() < 0.5:
                c["securityContext"]["allowPrivilegeEscalation"] = rng.random() < 0.5
        if rng.random() < 0.5:
            res = {}
            if rng.random() < 0.8:
                res["requests"] = {
                    k: v
                    for k, v in (
                        ("memory", maybe(0.8, rng.choice(["64Mi", "1Gi", "100M"]))),
                        ("cpu", maybe(0.7, rng.choice(["100m", "1", "0.5"]))),
                    )
                    if v
                }
            if rng.random() < 0.6:
                res["limits"] = {
                    k: v
                    for k, v in (("memory", maybe(0.8, rng.choice(["128Mi", "2Gi"]))),)
                    if v
                }
            c["resources"] = res
        if rng.random() < 0.3:
            ports = []
            for _ in range(rng.randint(0, 2)):
                port = {"containerPort": rng.randint(1, 65535)}
                if rng.random() < 0.4:
                    port["hostPort"] = rng.randint(1, 65535)
                ports.append(port)
            c["ports"] = ports
        containers.append(c)

    pod = {
        "apiVersion": "v1",
        "kind": rng.choice(["Pod", "Pod", "Pod", "Service", "Deployment"]),
        "metadata": {"name": f"pod-{rng.randint(0, 999)}"},
        "spec": {},
    }
    if pod["kind"] == "Deployment":
        pod["apiVersion"] = "apps/v1"
        if rng.random() < 0.7:
            pod["spec"]["replicas"] = rng.randint(0, 10)
    if rng.random() < 0.7:
        pod["metadata"]["namespace"] = rng.choice(
            ["default", "prod", "prod-eu", "dev", "kube-system"]
        )
    if containers or rng.random() < 0.8:
        pod["spec"]["containers"] = containers
    if rng.random() < 0.6:
        labels = {}
        if rng.random() < 0.7:
            labels["app.kubernetes.io/name"] = rng.choice(["x", ""])
        if rng.random() < 0.5:
            labels["app.kubernetes.io/component"] = "api"
        if rng.random() < 0.6:
            labels["tier"] = rng.choice(["web", "db", "cache", ""])
        if rng.random() < 0.4:
            labels["env"] = rng.choice(["prod", "dev"])
        pod["metadata"]["labels"] = labels
    if rng.random() < 0.5:
        ann = {}
        if rng.random() < 0.6:
            ann["team"] = rng.choice(["alpha", "alpha-eu", "beta", ""])
        if rng.random() < 0.5:
            ann["timeout"] = rng.choice(["30s", "2m", "1h30m", "0", "soon", "90"])
        if rng.random() < 0.5:
            ann["mem"] = rng.choice(["512Mi", "2Gi", "100M", "1e3", "lots"])
        pod["metadata"]["annotations"] = ann
    if rng.random() < 0.3:
        pod["spec"]["hostNetwork"] = rng.random() < 0.5
    if rng.random() < 0.2:
        pod["spec"]["hostPID"] = rng.random() < 0.5
    if rng.random() < 0.3:
        vols = []
        for i in range(rng.randint(0, 2)):
            vol = {"name": f"v{i}"}
            if rng.random() < 0.5:
                vol["hostPath"] = {"path": "/var/run/docker.sock"}
            else:
                vol["emptyDir"] = {}
            vols.append(vol)
        pod["spec"]["volumes"] = vols
    if rng.random() < 0.2:
        pod["spec"]["securityContext"] = (
            {"sysctls": [{"name": "net.core.somaxconn", "value": "1024"}]}
            if rng.random() < 0.5
            else {}
        )
    return pod


SYNTHETIC_POLICIES = [
    # element gates: containers with :latest images must pull Always
    {
        "apiVersion": "kyverno.io/v1",
        "kind": "ClusterPolicy",
        "metadata": {"name": "synthetic-gate"},
        "spec": {"rules": [{
            "name": "latest-pull-always",
            "match": {"resources": {"kinds": ["Pod"]}},
            "validate": {"pattern": {"spec": {"containers": [
                {"(image)": "*:latest", "imagePullPolicy": "Always"}
            ]}}},
        }]},
    },
    # anyPattern
    {
        "apiVersion": "kyverno.io/v1",
        "kind": "ClusterPolicy",
        "metadata": {"name": "synthetic-anypattern"},
        "spec": {"rules": [{
            "name": "nginx-or-redis",
            "match": {"resources": {"kinds": ["Pod"]}},
            "validate": {"anyPattern": [
                {"spec": {"containers": [{"image": "nginx:*"}]}},
                {"spec": {"containers": [{"image": "redis*"}]}},
            ]},
        }]},
    },
    # numeric operators + ranges + compound
    {
        "apiVersion": "kyverno.io/v1",
        "kind": "ClusterPolicy",
        "metadata": {"name": "synthetic-numeric"},
        "spec": {"rules": [{
            "name": "port-range",
            "match": {"resources": {"kinds": ["Pod"]}},
            "validate": {"pattern": {"spec": {"containers": [
                {"ports": [{"containerPort": "1024-65535"}]}
            ]}}},
        }]},
    },
    # condition anchor at map level
    {
        "apiVersion": "kyverno.io/v1",
        "kind": "ClusterPolicy",
        "metadata": {"name": "synthetic-cond"},
        "spec": {"rules": [{
            "name": "hostnetwork-requires-label",
            "match": {"resources": {"kinds": ["Pod"]}},
            "validate": {"pattern": {
                "spec": {"(hostNetwork)": True},
                "metadata": {"labels": {"app.kubernetes.io/name": "?*"}},
            }},
        }]},
    },
]


def _cp(name: str, rule: dict, *, kind: str = "ClusterPolicy",
        namespace: str | None = None) -> dict:
    """One-rule (Cluster)Policy document for the adversarial corpus."""
    meta = {"name": name}
    if namespace:
        meta["namespace"] = namespace
    rule = dict(rule)
    rule.setdefault("name", name)
    rule.setdefault("match", {"resources": {"kinds": ["Pod"]}})
    rule.setdefault("validate", {"pattern": {"spec": {"hostPID": False}}})
    return {"apiVersion": "kyverno.io/v1", "kind": kind,
            "metadata": meta, "spec": {"rules": [rule]}}


# Adversarial corpus for the aux lanes (VERDICT r2 item 2): deny conditions in
# every operator family, preconditions any/all, exclude blocks, match.any/all,
# annotations/selector/name/namespace matching, namespaced Policy objects.
# Reference semantics: pkg/engine/utils.go:265 (match/exclude),
# pkg/engine/variables/evaluate.go:11-67 + operator/*.go (conditions).
ADVERSARIAL_POLICIES = [
    # --- deny lanes ---------------------------------------------------------
    _cp("adv-deny-static-any", {"validate": {"deny": {"conditions": {"any": [
        {"key": 1, "operator": "Equals", "value": 2},
        {"key": "{{ request.object.spec.hostNetwork }}",
         "operator": "Equals", "value": True},
    ]}}}}),
    _cp("adv-deny-all", {"validate": {"deny": {"conditions": {"all": [
        {"key": "{{ request.object.spec.hostNetwork }}",
         "operator": "Equals", "value": True},
        {"key": "{{ request.object.metadata.namespace }}",
         "operator": "NotEquals", "value": "kube-system"},
    ]}}}}),
    _cp("adv-deny-in", {"validate": {"deny": {"conditions": {"any": [
        {"key": "{{ request.object.metadata.namespace }}",
         "operator": "In", "value": ["prod", "dev"]},
    ]}}}}),
    _cp("adv-deny-notin", {"validate": {"deny": {"conditions": {"any": [
        {"key": "{{ request.object.metadata.namespace }}",
         "operator": "NotIn", "value": ["prod", "prod-eu"]},
    ]}}}}),
    _cp("adv-deny-anyin-glob", {"validate": {"deny": {"conditions": {"any": [
        {"key": "{{ request.object.metadata.name }}",
         "operator": "AnyIn", "value": "pod-1*"},
    ]}}}}),
    _cp("adv-deny-allin", {"validate": {"deny": {"conditions": {"any": [
        {"key": "{{ request.object.metadata.labels.tier }}",
         "operator": "AllIn", "value": ["web", "db"]},
    ]}}}}),
    _cp("adv-deny-anynotin", {"validate": {"deny": {"conditions": {"any": [
        {"key": "{{ request.object.metadata.labels.tier }}",
         "operator": "AnyNotIn", "value": ["web"]},
    ]}}}}),
    _cp("adv-deny-gt", {
        "match": {"resources": {"kinds": ["Deployment"]}},
        "validate": {"deny": {"conditions": {"any": [
            {"key": "{{ request.object.spec.replicas }}",
             "operator": "GreaterThan", "value": 3},
        ]}}}}),
    _cp("adv-deny-le-quantity", {"validate": {"deny": {"conditions": {"any": [
        {"key": "{{ request.object.metadata.annotations.mem }}",
         "operator": "LessThanOrEquals", "value": "1Gi"},
    ]}}}}),
    _cp("adv-deny-duration", {"validate": {"deny": {"conditions": {"any": [
        {"key": "{{ request.object.metadata.annotations.timeout }}",
         "operator": "DurationGreaterThan", "value": "45s"},
    ]}}}}),
    _cp("adv-deny-dur-lt-num", {"validate": {"deny": {"conditions": {"any": [
        {"key": "{{ request.object.metadata.annotations.timeout }}",
         "operator": "DurationLessThanOrEquals", "value": 120},
    ]}}}}),
    _cp("adv-deny-ge", {
        "match": {"resources": {"kinds": ["Deployment"]}},
        "validate": {"deny": {"conditions": {"any": [
            {"key": "{{ request.object.spec.replicas }}",
             "operator": "GreaterThanOrEquals", "value": 8},
        ]}}}}),
    _cp("adv-pre-lt", {"preconditions": {"all": [
        {"key": "{{ request.object.metadata.annotations.mem }}",
         "operator": "LessThan", "value": "1500Mi"},
    ]}}),
    _cp("adv-deny-dur-ge", {"validate": {"deny": {"conditions": {"any": [
        {"key": "{{ request.object.metadata.annotations.timeout }}",
         "operator": "DurationGreaterThanOrEquals", "value": "2m"},
    ]}}}}),
    _cp("adv-pre-dur-lt", {"preconditions": {"any": [
        {"key": "{{ request.object.metadata.annotations.timeout }}",
         "operator": "DurationLessThan", "value": "10m"},
    ]}}),
    _cp("adv-deny-in-nonstr", {"validate": {"deny": {"conditions": {"any": [
        {"key": "{{ request.object.metadata.name }}",
         "operator": "In", "value": 7},
    ]}}}}),
    _cp("adv-deny-unknown-op", {"validate": {"deny": {"conditions": {"any": [
        {"key": "{{ request.object.metadata.name }}",
         "operator": "Frobnicates", "value": "x"},
    ]}}}}),
    # --- precondition lanes -------------------------------------------------
    _cp("adv-pre-any", {"preconditions": {"any": [
        {"key": "{{ request.object.metadata.labels.tier }}",
         "operator": "Equals", "value": "web"},
        {"key": "{{ request.object.metadata.labels.tier }}",
         "operator": "Equals", "value": "db"},
    ]}}),
    _cp("adv-pre-all", {"preconditions": {"all": [
        {"key": "{{ request.object.metadata.labels.env }}",
         "operator": "Equals", "value": "prod"},
        {"key": "{{ request.object.metadata.namespace }}",
         "operator": "NotEquals", "value": "kube-system"},
    ]}}),
    _cp("adv-pre-legacy-list", {"preconditions": [
        {"key": "{{ request.object.metadata.labels.tier }}",
         "operator": "NotEquals", "value": ""},
    ]}),
    _cp("adv-pre-empty-any", {"preconditions": {"any": []}}),
    _cp("adv-pre-in", {"preconditions": {"all": [
        {"key": "{{ request.object.metadata.namespace }}",
         "operator": "In", "value": ["prod", "prod-eu", "dev"]},
    ]}}),
    # --- match variants -----------------------------------------------------
    _cp("adv-match-any-multi", {"match": {"any": [
        {"resources": {"kinds": ["Pod"], "names": ["pod-1*"]}},
        {"resources": {"kinds": ["Service"]}},
    ]}}),
    _cp("adv-match-all", {"match": {"all": [
        {"resources": {"kinds": ["Pod"]}},
        {"resources": {"namespaces": ["prod*"]}},
    ]}}),
    _cp("adv-match-annotations", {"match": {"resources": {
        "kinds": ["Pod"], "annotations": {"team": "alpha*"}}}}),
    _cp("adv-match-selector", {"match": {"resources": {
        "kinds": ["Pod"], "selector": {"matchLabels": {"tier": "web"}}}}}),
    _cp("adv-match-selector-glob", {"match": {"resources": {
        "kinds": ["Pod"], "selector": {"matchLabels": {"tier": "?*"}}}}}),
    _cp("adv-match-expressions", {"match": {"resources": {
        "kinds": ["Pod"], "selector": {"matchExpressions": [
            {"key": "tier", "operator": "In", "values": ["web", "db"]},
            {"key": "env", "operator": "NotIn", "values": ["dev"]},
        ]}}}}),
    _cp("adv-match-exists", {"match": {"resources": {
        "kinds": ["Pod"], "selector": {"matchExpressions": [
            {"key": "env", "operator": "Exists"},
            {"key": "tier", "operator": "DoesNotExist"},
        ]}}}}),
    _cp("adv-match-name-wild", {"match": {"resources": {
        "kinds": ["Pod"], "name": "pod-?*"}}}),
    _cp("adv-match-names", {"match": {"resources": {
        "kinds": ["Pod"], "names": ["pod-1", "pod-2*", "pod-3?"]}}}),
    _cp("adv-match-namespaces", {"match": {"resources": {
        "kinds": ["Pod"], "namespaces": ["prod", "kube-*"]}}}),
    _cp("adv-match-version-kind", {"match": {"resources": {
        "kinds": ["v1/Pod"]}}}),
    _cp("adv-match-gvk", {"match": {"resources": {
        "kinds": ["apps/v1/Deployment"]}}}),
    _cp("adv-match-star-kind", {"match": {"resources": {"kinds": ["*"]}},
        "validate": {"pattern": {"metadata": {"name": "?*"}}}}),
    # --- exclude variants ---------------------------------------------------
    _cp("adv-exclude-names", {"exclude": {"resources": {
        "names": ["pod-1*", "pod-2?"]}}}),
    _cp("adv-exclude-ns", {"exclude": {"resources": {
        "namespaces": ["kube-system"]}}}),
    _cp("adv-exclude-selector", {"exclude": {"resources": {
        "selector": {"matchLabels": {"tier": "web"}}}}}),
    _cp("adv-exclude-any-multi", {"exclude": {"any": [
        {"resources": {"names": ["pod-1*"]}},
        {"resources": {"namespaces": ["dev"]}},
    ]}}),
    _cp("adv-exclude-all", {"exclude": {"all": [
        {"resources": {"names": ["pod-*"]}},
        {"resources": {"namespaces": ["prod"]}},
    ]}}),
    # --- namespaced Policy --------------------------------------------------
    _cp("adv-ns-policy", {}, kind="Policy", namespace="prod"),
    # --- combined -----------------------------------------------------------
    _cp("adv-combined", {
        "match": {"resources": {"kinds": ["Pod"], "namespaces": ["prod*", "dev"]}},
        "exclude": {"resources": {"selector": {"matchLabels": {"env": "dev"}}}},
        "preconditions": {"all": [
            {"key": "{{ request.object.metadata.labels.tier }}",
             "operator": "NotEquals", "value": ""},
        ]},
        "validate": {"deny": {"conditions": {"any": [
            {"key": "{{ request.object.metadata.labels.tier }}",
             "operator": "In", "value": ["cache"]},
        ]}}}}),
]


@pytest.fixture(scope="module")
def corpus():
    rng = random.Random(20260729)
    return [random_pod(rng) for _ in range(120)]


@pytest.fixture(scope="module")
def policy_set():
    policies = load_policies_from_path("/root/reference/test/best_practices/")
    policies += [load_policy(doc) for doc in SYNTHETIC_POLICIES]
    policies += [load_policy(doc) for doc in ADVERSARIAL_POLICIES]
    return CompiledPolicySet(policies)


def test_device_lane_compiles_most_rules(policy_set):
    # every adversarial policy must compile to the device lane; only the
    # known host-only best-practices stragglers may remain on host
    hosts = {r.rule_name for r in policy_set.rule_irs if r.host_only}
    adv_rules = {doc["spec"]["rules"][0]["name"] for doc in ADVERSARIAL_POLICIES}
    assert not (hosts & adv_rules), sorted(hosts & adv_rules)
    assert len(hosts) <= 2, [
        (h.rule_name, h.host_reason) for h in policy_set.rule_irs if h.host_only
    ]


def test_adversarial_corpus_is_broad(policy_set):
    """Every AuxOp appears in the compiled aux program (VERDICT r2 item 2)."""
    from kyverno_tpu.models.ir import AuxOp

    assert len(ADVERSARIAL_POLICIES) >= 30
    present = set(int(v) for v in policy_set.tensors.ax_op)
    missing = [op.name for op in AuxOp if int(op) not in present]
    assert not missing, f"AuxOps never exercised: {missing}"


def test_cross_check_verdicts(policy_set, corpus):
    batch = policy_set.flatten(corpus)
    device = policy_set.evaluate_device(batch)
    oracle = oracle_matrix(policy_set, corpus)

    mismatches = []
    for b in range(len(corpus)):
        for r in range(policy_set.tensors.n_rules):
            got = Verdict(device[b, r])
            if got == Verdict.HOST:
                continue  # host lane defers to the oracle by construction
            want = Verdict(oracle[b, r])
            if got != want:
                ref = policy_set.rule_refs[r]
                mismatches.append(
                    (b, ref.policy.name, ref.rule.name, want.name, got.name,
                     corpus[b])
                )
    assert not mismatches, f"{len(mismatches)} mismatches; first: {mismatches[0]}"


def test_full_evaluate_matches_oracle(policy_set, corpus):
    verdicts = policy_set.evaluate(corpus[:30])
    oracle = oracle_matrix(policy_set, corpus[:30])
    assert (verdicts == oracle).all()


def test_global_anchor_under_absent_equality_anchor():
    """{=(mode): {<(g): pattern}} with mode ABSENT: the equality anchor
    makes the whole subtree vacuous — the nested global anchor is never
    reached, so the rule must PASS (not fail, not skip). Device and
    oracle must agree on every structural variant (fuzz seed 70)."""
    def both(pattern, resource):
        pol = load_policy({
            "apiVersion": "kyverno.io/v1", "kind": "ClusterPolicy",
            "metadata": {"name": "p"}, "spec": {"rules": [{
                "name": "r", "match": {"resources": {"kinds": ["*"]}},
                "validate": {"pattern": pattern}}]},
        })
        cps = CompiledPolicySet([pol])
        device = Verdict(
            np.asarray(cps.evaluate_device(cps.flatten([resource])))[0, 0])
        ctx = Context()
        ctx.add_resource(resource)
        resp = oracle_validate(PolicyContext(
            policy=pol, new_resource=resource, json_context=ctx))
        return device, resp.policy_response.rules[0].status.value

    res = {"apiVersion": "v1", "kind": "Secret", "metadata": {"name": "x"},
           "data": {"gamma": [True]}}
    # absent =(mode): vacuous subtree, nested global never evaluated
    device, oracle = both({"data": {"=(mode)": {"<(data)": "<1"}}}, res)
    assert (device, oracle) == (Verdict.PASS, "pass")
    # present =(gamma): the nested global IS evaluated and fails the rule
    device, oracle = both({"data": {"=(gamma)": {"<(data)": "<1"}}}, res)
    assert oracle == "fail" and device in (Verdict.FAIL, Verdict.HOST)
    # ancestor above the eq anchor absent: plain FAIL on both lanes
    device, oracle = both({"stuff": {"=(mode)": {"<(data)": "<1"}}}, res)
    assert oracle == "fail" and device in (Verdict.FAIL, Verdict.HOST)
    # eq key present but scalar: structural FAIL before the anchor runs
    res2 = {"apiVersion": "v1", "kind": "Secret", "metadata": {"name": "x"},
            "data": {"mode": "scalar"}}
    device, oracle = both({"data": {"=(mode)": {"<(data)": "<1"}}}, res2)
    assert oracle == "fail" and device in (Verdict.FAIL, Verdict.HOST)
    # the eq-anchored key's PARENT is a scalar: the chain null-breaks AT
    # the guarded depth — the guard must NOT rescue it (the reference
    # type-mismatches on the parent before the anchor is considered)
    res3 = {"apiVersion": "v1", "kind": "Secret", "metadata": {"name": "x"},
            "data": "scalar"}
    device, oracle = both({"data": {"=(mode)": {"<(data)": "<1"}}}, res3)
    assert oracle == "fail" and device in (Verdict.FAIL, Verdict.HOST)
