"""Test harness: force an 8-device virtual CPU mesh before JAX is imported.

Multi-chip hardware is not available in CI; sharding tests run against
XLA's host-platform device partitioning instead, which exercises the same
pjit/shard_map partitioning logic.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"  # override any preset accelerator
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# the sandbox registers its TPU backend via sitecustomize and pins the
# platform programmatically, which beats the env var — pin it back
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _tracer_leak_guard(request):
    """Run every kernel test under jax.check_tracer_leaks: a helper that
    stashes a traced value on a module global (an easy bug to write in
    ops/ refactors) escapes unit assertions — the leaked tracer only
    explodes much later, in an unrelated test's trace. Scoped to
    tests/ops/ where everything traces; host-side suites skip the check
    because it makes tracing measurably slower."""
    path = getattr(request.node, "fspath", None)
    in_ops = path is not None and f"{os.sep}ops{os.sep}" in str(path)
    if not in_ops:
        yield
        return
    with jax.check_tracer_leaks():
        yield
