"""Test harness: force an 8-device virtual CPU mesh before JAX is imported.

Multi-chip hardware is not available in CI; sharding tests run against
XLA's host-platform device partitioning instead, which exercises the same
pjit/shard_map partitioning logic.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"  # override any preset accelerator
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# the sandbox registers its TPU backend via sitecustomize and pins the
# platform programmatically, which beats the env var — pin it back
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import pytest  # noqa: E402


# ---------------------------------------------------------------------------
# Reference-corpus gating. These suites replay the upstream Kyverno
# fixture corpus from /root/reference (policies, resources, golden
# verdicts). CI images without that checkout used to report them as 44
# failures + 6 fixture errors; skip them explicitly — with the reason —
# so a red run means a real regression, not a missing mount. The list is
# curated by exact nodeid (a handful fail indirectly, e.g. on assertion
# counts over the missing corpus, so a FileNotFoundError hook is not
# enough). test_scenarios.py's own _STALE bookkeeping is untouched: we
# only add a skip mark, never an xfail.
REFERENCE_ROOT = "/root/reference"

_REFERENCE_NODEIDS = frozenset((
    "tests/ops/test_cross_check.py::test_adversarial_corpus_is_broad",
    "tests/ops/test_cross_check.py::test_cross_check_verdicts",
    "tests/ops/test_cross_check.py::test_device_lane_compiles_most_rules",
    "tests/ops/test_cross_check.py::test_full_evaluate_matches_oracle",
    "tests/ops/test_mesh.py::test_sharded_scan_chunked_pipeline",
    "tests/ops/test_mesh.py::test_sharded_scan_matches_single_device",
    "tests/runtime/test_registry_verify.py::TestCertChainHardening::"
    "test_cn_never_matches_when_sans_present",
    "tests/runtime/test_registry_verify.py::TestCertChainHardening::"
    "test_leaf_cannot_mint_identities",
    "tests/runtime/test_registry_verify.py::TestCertChainVerification::"
    "test_cert_chain_signed_image_verifies",
    "tests/runtime/test_registry_verify.py::TestCertChainVerification::"
    "test_expired_leaf_rejected",
    "tests/runtime/test_registry_verify.py::TestCertChainVerification::"
    "test_no_cert_on_layer_rejected",
    "tests/runtime/test_registry_verify.py::TestCertChainVerification::"
    "test_subject_wildcard_matches",
    "tests/runtime/test_registry_verify.py::TestCertChainVerification::"
    "test_tampered_payload_digest_binding",
    "tests/runtime/test_registry_verify.py::TestCertChainVerification::"
    "test_untrusted_root_rejected",
    "tests/runtime/test_registry_verify.py::TestCertChainVerification::"
    "test_wrong_key_signature_rejected",
    "tests/runtime/test_registry_verify.py::TestCertChainVerification::"
    "test_wrong_subject_rejected",
    "tests/runtime/test_registry_verify.py::TestKeylessAttestations::"
    "test_cert_chain_attestation_verifies",
    "tests/runtime/test_registry_verify.py::TestWebhookE2ECertChain::"
    "test_roots_policy_verifies_and_wrong_subject_blocks",
    "tests/runtime/test_runtime.py::TestBackgroundScan::test_scan_snapshot",
    "tests/unit/test_batch_mutate.py::TestReferenceCorpus::"
    "test_add_default_labels_mixed_kinds",
    "tests/unit/test_batch_mutate.py::TestReferenceCorpus::"
    "test_gate_skips_unmatched_kinds",
    "tests/unit/test_batch_mutate.py::TestReferenceCorpus::"
    "test_whole_mutate_corpus",
    "tests/unit/test_cli.py::test_apply_reports_failures",
    "tests/unit/test_cli.py::test_negative_suite_fails",
    "tests/unit/test_cli.py::test_reference_cli_corpus[autogen]",
    "tests/unit/test_cli.py::test_reference_cli_corpus[custom-functions]",
    "tests/unit/test_cli.py::test_reference_cli_corpus[preconditions]",
    "tests/unit/test_cli.py::test_reference_cli_corpus[simple]",
    "tests/unit/test_cli.py::test_reference_cli_corpus[test-mutate]",
    "tests/unit/test_cli.py::test_reference_cli_corpus[variables]",
    "tests/unit/test_cli.py::test_validate_verb",
    "tests/unit/test_scenarios.py::test_reference_scenario[add_safe_to_evict2]",
    "tests/unit/test_scenarios.py::test_reference_scenario[add_safe_to_evict3]",
    "tests/unit/test_scenarios.py::test_reference_scenario[add_safe_to_evict]",
    "tests/unit/test_scenarios.py::"
    "test_reference_scenario[disallow_bind_mounts_fail]",
    "tests/unit/test_scenarios.py::"
    "test_reference_scenario[disallow_bind_mounts_pass]",
    "tests/unit/test_scenarios.py::"
    "test_reference_scenario[disallow_host_network_port]",
    "tests/unit/test_scenarios.py::"
    "test_reference_scenario[disallow_host_pid_ipc]",
    "tests/unit/test_scenarios.py::"
    "test_reference_scenario[disallow_priviledged]",
    "tests/unit/test_scenarios.py::test_reference_scenario[disallow_sysctls]",
    "tests/unit/test_scenarios.py::"
    "test_reference_scenario[restrict_automount_sa_token]",
    "tests/unit/test_scenarios.py::"
    "test_reference_scenario[restrict_ingress_classes]",
    "tests/unit/test_scenarios.py::"
    "test_reference_scenario[scenario_mutate_endpoint]",
    "tests/unit/test_scenarios.py::"
    "test_reference_scenario[scenario_mutate_pod_spec]",
    "tests/unit/test_scenarios.py::"
    "test_reference_scenario[scenario_mutate_validate_qos]",
    "tests/unit/test_scenarios.py::"
    "test_reference_scenario[scenario_validate_default_proc_mount]",
    "tests/unit/test_scenarios.py::"
    "test_reference_scenario[scenario_validate_disallow_default_serviceaccount]",
    "tests/unit/test_scenarios.py::"
    "test_reference_scenario[scenario_validate_healthChecks]",
    "tests/unit/test_scenarios.py::"
    "test_reference_scenario[scenario_validate_volume_whiltelist]",
    "tests/unit/test_scenarios.py::"
    "test_reference_scenario[unknown_ingress_class]",
))


def pytest_collection_modifyitems(config, items):
    if os.path.isdir(REFERENCE_ROOT):
        return
    skip = pytest.mark.skip(
        reason=f"reference fixture corpus not mounted at {REFERENCE_ROOT}")
    rootdir = str(config.rootpath)
    for item in items:
        nodeid = item.nodeid
        # normalize: invocations from the repo root yield tests/...::id
        # already, but running inside tests/ drops the prefix.
        if not nodeid.startswith("tests/"):
            rel = os.path.relpath(str(item.fspath), rootdir)
            nodeid = rel + nodeid[nodeid.find("::"):] if "::" in nodeid \
                else rel
        if nodeid in _REFERENCE_NODEIDS:
            item.add_marker(skip)


@pytest.fixture(autouse=True)
def _tracer_leak_guard(request):
    """Run every kernel test under jax.check_tracer_leaks: a helper that
    stashes a traced value on a module global (an easy bug to write in
    ops/ refactors) escapes unit assertions — the leaked tracer only
    explodes much later, in an unrelated test's trace. Scoped to
    tests/ops/ where everything traces; host-side suites skip the check
    because it makes tracing measurably slower."""
    path = getattr(request.node, "fspath", None)
    in_ops = path is not None and f"{os.sep}ops{os.sep}" in str(path)
    if not in_ops:
        yield
        return
    with jax.check_tracer_leaks():
        yield
