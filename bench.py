"""Benchmark: policy x resource validations/sec on one chip.

Replays BASELINE.md config [2]: the best_practices validate corpus
(~13 policies / 17 rules) against a synthetic Pod batch, steady-state
device throughput (the background-scan replay regime — flatten once,
evaluate repeatedly, as the scanner does per interval over a snapshot).

Prints ONE json line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}
vs_baseline is measured / 100k — the north-star target from BASELINE.json
(the reference publishes no numbers; see BASELINE.md).
"""

import json
import sys
import time

import numpy as np


def make_pod(i: int) -> dict:
    imgs = ["nginx:latest", "nginx:1.21", "redis:6", "registry.io/a/b:v2"]
    c = {
        "name": f"c{i % 3}",
        "image": imgs[i % 4],
    }
    if i % 3:
        c["resources"] = {
            "requests": {"memory": "64Mi", "cpu": "100m"},
            "limits": {"memory": "128Mi"},
        }
    if i % 5 == 0:
        c["securityContext"] = {"privileged": i % 2 == 0}
    pod = {
        "apiVersion": "v1",
        "kind": "Pod",
        "metadata": {"name": f"pod-{i}"},
        "spec": {"containers": [c]},
    }
    if i % 4 == 0:
        pod["metadata"]["labels"] = {
            "app.kubernetes.io/name": "bench",
            "app.kubernetes.io/component": "api",
        }
    if i % 7 == 0:
        pod["spec"]["volumes"] = [{"name": "v", "emptyDir": {}}]
    return pod


def main() -> None:
    from kyverno_tpu.api.load import load_policies_from_path
    from kyverno_tpu.models import CompiledPolicySet

    policies = load_policies_from_path("/root/reference/test/best_practices/")
    cps = CompiledPolicySet(policies)

    batch_size = 4096
    resources = [make_pod(i) for i in range(batch_size)]

    t0 = time.monotonic()
    batch = cps.flatten(resources)
    flatten_s = time.monotonic() - t0

    args = batch.device_args()

    fn = cps.eval_fn
    out = fn(*args)
    out.block_until_ready()  # compile + first run

    # steady state
    n_iters = 10
    t0 = time.monotonic()
    for _ in range(n_iters):
        out = fn(*args)
    out.block_until_ready()
    device_s = (time.monotonic() - t0) / n_iters

    n_rules = int(cps.tensors.n_rules)
    n_device_rules = int((~cps.tensors.rule_host_only).sum())
    validations = batch_size * n_rules
    device_rate = validations / device_s
    # end-to-end rate for a fresh snapshot (flatten amortized once per scan)
    e2e_rate = validations / (device_s + flatten_s / 1)

    verdicts = np.array(out)
    result = {
        "metric": "policy-rule x resource validations/sec (device, steady state)",
        "value": round(device_rate),
        "unit": "validations/sec",
        "vs_baseline": round(device_rate / 100_000, 3),
        "detail": {
            "batch": batch_size,
            "rules": n_rules,
            "device_rules": n_device_rules,
            "device_s_per_batch": round(device_s, 5),
            "flatten_s": round(flatten_s, 3),
            "e2e_rate_with_flatten": round(e2e_rate),
            "verdict_histogram": {
                str(k): int(v)
                for k, v in zip(*np.unique(verdicts, return_counts=True))
            },
        },
    }
    print(json.dumps(result))


if __name__ == "__main__":
    sys.exit(main())
