"""Benchmark: the five BASELINE.md configs on one chip.

Primary metric (the JSON line's "value") stays config [2] — the
best_practices validate corpus against a 4096-Pod batch, steady-state
device throughput — for continuity with BENCH_r01/r02. The "configs"
detail reports all five BASELINE configs:

  [1] disallow-latest-tag x 1 Pod          admission latency (ms, p50/p99)
  [2] best_practices x 4096 Pods           device validations/s + e2e
  [3] ~250-policy library x 10k resources  device validations/s, host %
  [4] mutate strategic-merge x 50k         CPU-tier mutations/s (honest:
                                           the mutate path is host-side)
  [5] 1M-resource background-scan replay   e2e validations/s, chunked
                                           parallel flatten + pipelined eval

vs_baseline is value / 100k — the north-star target from BASELINE.json
(the reference publishes no numbers; see BASELINE.md).
"""

import concurrent.futures
import json
import statistics
import sys
import threading
import time

import numpy as np


def make_pod(i: int) -> dict:
    imgs = ["nginx:latest", "nginx:1.21", "redis:6", "registry.io/a/b:v2"]
    c = {
        "name": f"c{i % 3}",
        "image": imgs[i % 4],
    }
    if i % 3:
        c["resources"] = {
            "requests": {"memory": "64Mi", "cpu": "100m"},
            "limits": {"memory": "128Mi"},
        }
    if i % 5 == 0:
        c["securityContext"] = {"privileged": i % 2 == 0}
    pod = {
        "apiVersion": "v1",
        "kind": "Pod",
        "metadata": {"name": f"pod-{i}"},
        "spec": {"containers": [c]},
    }
    if i % 4 == 0:
        pod["metadata"]["labels"] = {
            "app.kubernetes.io/name": "bench",
            "app.kubernetes.io/component": "api",
        }
    if i % 7 == 0:
        pod["spec"]["volumes"] = [{"name": "v", "emptyDir": {}}]
    return pod


def make_deployment(i: int) -> dict:
    return {
        "apiVersion": "apps/v1",
        "kind": "Deployment",
        "metadata": {"name": f"dep-{i}", "namespace": "default"},
        "spec": {
            "replicas": (i % 5) + 1,
            "selector": {"matchLabels": {"app": f"a{i % 9}"}},
            "template": {
                "metadata": {"labels": {"app": f"a{i % 9}"}},
                "spec": make_pod(i)["spec"],
            },
        },
    }


def make_service(i: int) -> dict:
    return {
        "apiVersion": "v1",
        "kind": "Service",
        "metadata": {"name": f"svc-{i}"},
        "spec": {"ports": [{"port": 80 + (i % 1000)}],
                 "type": "ClusterIP" if i % 3 else "LoadBalancer"},
    }


def mixed_resource(i: int) -> dict:
    r = i % 10
    if r < 6:
        return make_pod(i)
    if r < 9:
        return make_deployment(i)
    return make_service(i)


def _library_250():
    """~250-policy library synthesized from the reference test fixtures
    (BASELINE config [3]; the public kyverno/policies repo is not in-image,
    so the in-repo corpora are cloned with varied names/operands)."""
    from kyverno_tpu.api.load import load_policies_from_path, load_policy

    base = []
    for d in ("best_practices", "more", "policy/validate"):
        try:
            base += load_policies_from_path(f"/root/reference/test/{d}/")
        except Exception:
            pass
    docs = [p.raw for p in base if p.raw]
    out = []
    i = 0
    while len(out) < 250:
        doc = json.loads(json.dumps(docs[i % len(docs)]))
        doc.setdefault("metadata", {})["name"] = (
            f"{doc['metadata'].get('name', 'p')}-v{i // len(docs)}")
        try:
            out.append(load_policy(doc))
        except Exception:
            pass
        i += 1
        if i > 1000:
            break
    return out


def _percentiles(lats):
    lats = sorted(lats)
    p99_idx = min(len(lats) - 1, -(-99 * len(lats) // 100) - 1)  # nearest-rank
    return (round(statistics.median(lats), 2), round(lats[p99_idx], 2))


def bench_config1(jax):
    """disallow-latest-tag x 1 Pod: single-request admission latency through
    the production webhook path over real HTTP. The latency router
    (runtime/batch.py) sends lone requests straight to the CPU oracle; the
    device screen engages only when a burst forms, so a single kubectl
    apply never pays the device round trip."""
    import http.client

    from kyverno_tpu.api.load import load_policies_from_path
    from kyverno_tpu.runtime.batch import AdmissionBatcher
    from kyverno_tpu.runtime.client import FakeCluster
    from kyverno_tpu.runtime.policycache import PolicyCache, PolicyType
    from kyverno_tpu.runtime.webhook import (
        VALIDATING_WEBHOOK_PATH,
        WebhookServer,
    )

    pols = [p for p in load_policies_from_path(
        "/root/reference/test/best_practices/")
        if p.name == "disallow-latest-tag"]
    for p in pols:
        p.spec.validation_failure_action = "enforce"
    cache = PolicyCache()
    for p in pols:
        cache.add(p)
    batcher = AdmissionBatcher(cache)
    server = WebhookServer(policy_cache=cache, client=FakeCluster(),
                           admission_batcher=batcher)
    httpd = server.run(host="127.0.0.1", port=0)
    port = httpd.server_address[1]
    body = json.dumps({
        "apiVersion": "admission.k8s.io/v1", "kind": "AdmissionReview",
        "request": {"uid": "bench", "kind": {"kind": "Pod"},
                    "namespace": "default", "operation": "CREATE",
                    "object": make_pod(1)},
    }).encode()
    headers = {"Content-Type": "application/json"}

    def connect():
        import socket

        c = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
        c.connect()
        c.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        return c

    def post(conn):
        # persistent keep-alive connection, like the API server's
        conn.request("POST", VALIDATING_WEBHOOK_PATH, body, headers)
        return json.loads(conn.getresponse().read())

    try:
        conn = connect()
        allowed = post(conn)["response"]["allowed"]  # warm + probe
        for _ in range(10):
            post(conn)
        lats = []
        for _ in range(200):
            t0 = time.perf_counter()
            post(conn)
            lats.append((time.perf_counter() - t0) * 1e3)
        conn.close()
        p50, p99 = _percentiles(lats)

        # burst shape: 16 workers x 32 requests on persistent connections;
        # the router decides oracle-vs-device from measured costs
        burst_lats = []

        def worker():
            c = connect()
            for _ in range(32):
                t0 = time.perf_counter()
                post(c)
                burst_lats.append((time.perf_counter() - t0) * 1e3)
            c.close()

        threads = [threading.Thread(target=worker) for _ in range(16)]
        t0 = time.monotonic()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        burst_s = time.monotonic() - t0
        bp50, bp99 = _percentiles(burst_lats)
        routing_small = dict(batcher.stats)
    finally:
        server.stop()
        batcher.stop()

    # library-scale burst: with ~250 enforce policies the per-request CPU
    # oracle costs tens of ms, so the cost model flips bursts onto the
    # device screen and the hybrid merge only runs the oracle for policies
    # with a FAIL/ERROR/HOST cell
    lib = _library_250()
    for p in lib:
        p.spec.validation_failure_action = "enforce"
    lib_cache = PolicyCache()
    for p in lib:
        lib_cache.add(p)
    lib_batcher = AdmissionBatcher(lib_cache)
    lib_server = WebhookServer(policy_cache=lib_cache, client=FakeCluster(),
                               admission_batcher=lib_batcher)
    lib_httpd = lib_server.run(host="127.0.0.1", port=0)
    lib_port = lib_httpd.server_address[1]
    lib_batcher.warmup(  # controller startup does this (server.py)
        PolicyType.VALIDATE_ENFORCE, "Pod", "default", make_pod(1))
    def run_burst(port, n_threads=16, per_thread=16):
        """(seq_p50, p50, p99, req_per_s, n): one sequential warm pass,
        then n_threads workers of per_thread requests each on persistent
        keep-alive connections. Shared by the cached and nocache runs so
        the comparison can never drift methodologically."""
        import socket

        def worker(out):
            c = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
            c.connect()
            c.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            for _ in range(per_thread):
                t0 = time.perf_counter()
                c.request("POST", VALIDATING_WEBHOOK_PATH, body, headers)
                c.getresponse().read()
                out.append((time.perf_counter() - t0) * 1e3)
            c.close()

        lats: list = []
        worker(lats)                # sequential warm pass
        seq_p50, _ = _percentiles(lats)
        lats = []
        workers = [threading.Thread(target=worker, args=(lats,))
                   for _ in range(n_threads)]
        t0 = time.monotonic()
        for t in workers:
            t.start()
        for t in workers:
            t.join()
        burst_s = time.monotonic() - t0
        p50_, p99_ = _percentiles(lats)
        return seq_p50, p50_, p99_, round(len(lats) / burst_s), len(lats)

    try:
        seq_p50, lp50, lp99, lib_rps, lib_n = run_burst(lib_port)
        routing_lib = dict(lib_batcher.stats)
    finally:
        lib_server.stop()
        lib_batcher.stop()

    # transparency run: the same burst with the result cache OFF measures
    # the raw device-screen + direct-deny pipeline (every request pays
    # routing + screen/oracle work; nothing is served from cache)
    nc_batcher = AdmissionBatcher(lib_cache, result_cache_ttl_s=0.0)
    nc_server = WebhookServer(policy_cache=lib_cache, client=FakeCluster(),
                              admission_batcher=nc_batcher)
    nc_httpd = nc_server.run(host="127.0.0.1", port=0)
    nc_batcher.warmup(
        PolicyType.VALIDATE_ENFORCE, "Pod", "default", make_pod(1))
    try:
        nc_seq_p50, ncp50, ncp99, nc_rps, nc_n = run_burst(
            nc_httpd.server_address[1])
        routing_nc = dict(nc_batcher.stats)
    finally:
        nc_server.stop()
        nc_batcher.stop()

    # audit burst: the same 250-policy library in audit mode, drained
    # through the queue (validate_audit.go's 10 workers). Audit has no
    # latency budget, so the screen engages deadline-free and identical
    # repeats dedup via the TTL memo (ResourceManager analogue,
    # pkg/policy/existing.go:125). The oracle-only figure processes the
    # same queue with the screen disabled.
    audit_lib = _library_250()
    for p in audit_lib:
        p.spec.validation_failure_action = "audit"
    # ONE policy cache for both lanes: the compiled tensors/XLA artifacts
    # hang off it, and a fresh cache per lane would recompile on the
    # real chip (~20-40s per shape) for no measurement value
    audit_cache = PolicyCache()
    for p in audit_lib:
        audit_cache.add(p)

    def drain_audit(with_screen: bool, n: int = 256) -> float:
        batcher = AdmissionBatcher(audit_cache) if with_screen else None
        server = WebhookServer(policy_cache=audit_cache, client=FakeCluster(),
                               admission_batcher=batcher)
        if with_screen:
            batcher.warmup(PolicyType.VALIDATE_AUDIT, "Pod", "default",
                           make_pod(1))
        req_obj = {"uid": "a", "kind": {"kind": "Pod"},
                   "namespace": "default", "operation": "CREATE",
                   "object": make_pod(1)}
        server.audit_handler.run()
        try:
            server._process_audit(dict(req_obj))    # warm both lanes
            t0 = time.monotonic()
            for _ in range(n):
                server.audit_handler.add(dict(req_obj))
            server.audit_handler.drain(timeout=600)
            return time.monotonic() - t0
        finally:
            server.audit_handler.stop()
            if batcher is not None:
                batcher.stop()

    audit_n = 256
    screened_s = drain_audit(True, audit_n)
    oracle_s = drain_audit(False, audit_n)
    audit_burst = {
        "n": audit_n, "policies": len(audit_lib),
        "screened_req_per_s": round(audit_n / screened_s),
        "oracle_req_per_s": round(audit_n / oracle_s),
        "speedup": round(oracle_s / screened_s, 1),
    }

    return {
        "latency_ms_p50": p50,
        "latency_ms_p99": p99,
        "n_iters": len(lats),
        "allowed": allowed,
        "burst": {"n": len(burst_lats), "concurrency": 16,
                  "latency_ms_p50": bp50, "latency_ms_p99": bp99,
                  "req_per_s": round(len(burst_lats) / burst_s),
                  "routing": routing_small},
        "burst_library_250": {
            "n": lib_n, "concurrency": 16,
            "seq_latency_ms_p50": seq_p50,
            "latency_ms_p50": lp50, "latency_ms_p99": lp99,
            "req_per_s": lib_rps,
            "routing": routing_lib},
        "burst_library_250_nocache": {
            "n": nc_n, "concurrency": 16,
            "seq_latency_ms_p50": nc_seq_p50,
            "latency_ms_p50": ncp50, "latency_ms_p99": ncp99,
            "req_per_s": nc_rps,
            "routing": routing_nc},
        "audit_burst_library_250": audit_burst,
        "path": "HTTP POST /validate (production handler, latency-routed)",
    }


def _timed_steady_state(fn, dblob, shp, n_iters: int) -> tuple[float, np.ndarray]:
    """(seconds per eval, warmup verdicts) — honestly timed: tunnel
    backends can report block_until_ready before execution finishes, so
    the timed region ends with a real D2H of a device-side scalar
    reduction of the LAST output. One device stream executes programs in
    submission order, so that byte-sized readback proves every queued
    eval completed without coupling the measurement to the link's
    multi-MB transfer weather."""
    out = fn(dblob, *shp)
    verdicts = np.asarray(out)         # compile + first run, forced
    int(out.astype("int32").sum())     # warm the reduction kernel too
    t0 = time.monotonic()
    outs = [fn(dblob, *shp) for _ in range(n_iters)]
    int(outs[-1].astype("int32").sum())
    device_s = (time.monotonic() - t0) / n_iters
    return device_s, verdicts


def bench_config2(jax):
    """best_practices x 4096: steady-state device throughput (pipelined
    dispatch over device-resident args — the background-scan regime) and
    e2e with a fresh flatten."""
    from kyverno_tpu.api.load import load_policies_from_path
    from kyverno_tpu.models import CompiledPolicySet

    cps = CompiledPolicySet(
        load_policies_from_path("/root/reference/test/best_practices/"))
    B = 4096
    resources = [make_pod(i) for i in range(B)]

    cps.flatten_packed(resources[:8])  # warm the native flattener
    t0 = time.monotonic()
    batch = cps.flatten_packed(resources)
    blob, shp = batch.packed_blob()
    flatten_s = time.monotonic() - t0

    fn = cps.blob_eval_fn
    dblob = jax.device_put(blob)
    dblob.block_until_ready()
    device_s, verdicts = _timed_steady_state(fn, dblob, shp, n_iters=30)

    n_rules = int(cps.tensors.n_rules)
    validations = B * n_rules
    return {
        "batch": B,
        "rules": n_rules,
        "device_rules": int((~cps.tensors.rule_host_only).sum()),
        "device_s_per_batch": round(device_s, 5),
        "flatten_s": round(flatten_s, 3),
        "device_rate": round(validations / device_s),
        "e2e_rate_with_flatten": round(validations / (device_s + flatten_s)),
        "verdict_histogram": {
            str(k): int(v)
            for k, v in zip(*np.unique(verdicts, return_counts=True))
        },
    }


def bench_config3(jax):
    """250-policy library x 10k mixed resources, device lane."""
    from kyverno_tpu.models import CompiledPolicySet

    cps = CompiledPolicySet(_library_250())
    B = 10_000
    resources = [mixed_resource(i) for i in range(B)]
    cps.flatten_packed(resources[:8])  # warm the native flattener
    t0 = time.monotonic()
    batch = cps.flatten_packed(resources)
    blob, shp = batch.packed_blob()
    flatten_s = time.monotonic() - t0

    fn = cps.blob_eval_fn
    dblob = jax.device_put(blob)
    dblob.block_until_ready()
    device_s, verdicts = _timed_steady_state(fn, dblob, shp, n_iters=5)

    from kyverno_tpu.models.engine import Verdict

    n_rules = int(cps.tensors.n_rules)
    host_cells = int((verdicts == Verdict.HOST).sum())
    return {
        "policies": len(cps.policies),
        "rules": n_rules,
        "device_rules": int((~cps.tensors.rule_host_only).sum()),
        "batch": B,
        "flatten_s": round(flatten_s, 3),
        "device_s_per_batch": round(device_s, 5),
        "device_rate": round(B * n_rules / device_s),
        "e2e_rate_with_flatten": round(B * n_rules / (device_s + flatten_s)),
        "host_cell_pct": round(100 * host_cells / verdicts.size, 2),
    }


def bench_config4(jax):
    """Mutate strategic-merge batch (add-default-labels x 50k docs) through
    the batched mutate tier (engine/mutate/batch.py): device gate screen +
    single-pass merge/patch emission. Patch bytes are asserted identical to
    the serial engine chain on a 1k sample."""
    import json as _json

    from kyverno_tpu.api.load import load_policies_from_path
    from kyverno_tpu.engine.context import Context
    from kyverno_tpu.engine.mutate.batch import BatchMutator
    from kyverno_tpu.engine.mutation import mutate
    from kyverno_tpu.engine.policy_context import PolicyContext

    pols = [p for p in load_policies_from_path("/root/reference/test/more/")
            if p.name == "add-default-labels"]
    if not pols:
        return {"error": "add-default-labels fixture not found"}
    policy = pols[0]

    # the fixture matches Pod/Service/Namespace, so the batch runs over
    # Pods — the kind the policy actually patches
    n = 50_000
    docs = [make_pod(i) for i in range(n)]
    bm = BatchMutator(pols)
    bm.apply(docs[:64])   # warm

    # best-of-2: this tier is pure CPU and the sandbox host is shared,
    # so single draws swing ~2x (same policy as config 5's runs)
    def timed_apply(m, corpus):
        t0 = time.monotonic()
        result = m.apply(corpus)
        return time.monotonic() - t0, result

    draws = [timed_apply(bm, docs) for _ in range(2)]
    dt, out = min(draws, key=lambda t: t[0])

    # byte-parity vs the serial engine chain on a 1k sample
    mismatches = 0
    for doc, got in zip(docs[:1000], out[:1000]):
        jctx = Context()
        jctx.add_resource(doc)
        resp = mutate(PolicyContext(policy=policy, new_resource=doc,
                                    json_context=jctx))
        if _json.dumps(got.patches) != _json.dumps(resp.patches):
            mismatches += 1

    # selector-gated phase: a label-selector gate has real predicate work,
    # so the measured router may ship the screen to the device; only
    # matching docs (15% of the mixed corpus: 60% Pods x 1-in-4 labeled)
    # reach the CPU merge
    from kyverno_tpu.api.load import load_policy

    sel_policy = load_policy({
        "apiVersion": "kyverno.io/v1", "kind": "ClusterPolicy",
        "metadata": {"name": "annotate-bench-apps"},
        "spec": {"rules": [{
            "name": "annotate",
            "match": {"resources": {"kinds": ["Pod"], "selector": {
                "matchLabels": {"app.kubernetes.io/name": "bench"}}}},
            "mutate": {"patchStrategicMerge": {
                "metadata": {"annotations": {"+(bench/tier)": "gated"}}}},
        }]},
    })
    bm2 = BatchMutator([sel_policy], min_gate_batch=64)
    mixed = [mixed_resource(i) for i in range(n)]
    bm2.apply(mixed[:256])   # calibrates the gate lane choice
    if bm2._gate_choice:
        # device lane chosen: pre-compile every chunk-shape bucket the
        # timed run will use (8192-chunks + the tail bucket)
        bm2.gate_verdicts(mixed)
    draws2 = [timed_apply(bm2, mixed) for _ in range(2)]
    dt2, out2 = min(draws2, key=lambda t: t[0])

    return {
        "n_docs": n,
        "target_docs": 50_000,
        "mutations_per_s": round(n / dt),
        "mutations_per_s_runs": [round(n / d) for d, _ in draws],
        "patched": sum(1 for r in out if r.patches),
        "parity_sample": 1000,
        "parity_mismatches": mismatches,
        "tier": "single-pass CPU merge, auto-gated (kind-only gate -> host)",
        "selector_gated_mixed": {
            "n_docs": n,
            "mutations_per_s": round(n / dt2),
            "mutations_per_s_runs": [round(n / d) for d, _ in draws2],
            "patched": sum(1 for r in out2 if r.patches),
            "gate_lane": ("device" if bm2._gate_choice else "host"),
            "tier": "selector gate, measured lane choice + single-pass merge",
        },
    }


def bench_config5(jax):
    """Background-scan replay: 1M-resource snapshot through the full
    pipeline — native flatten of chunk N+1 overlapping the single-blob
    transfer + device eval of chunk N, with per-rule counts reduced on
    device (readback is bytes, not the [B, R] verdict matrix).

    Scanner-faithful semantics: policies with ``background: false`` are
    excluded exactly as BackgroundScanner does (runtime/background.py:71,
    mirroring canBackgroundProcess, pkg/policy/policy_controller.go:181)
    — round 4 ran select-secrets (apiCall context, background: false),
    which flagged every row host-lane without ever paying to resolve it.
    Any HOST rows that remain are now resolved through the batched
    oracle INSIDE the timed region, and the device-only vs resolved
    timings are reported separately."""
    from kyverno_tpu.api.load import load_policies_from_path
    from kyverno_tpu.models import CompiledPolicySet
    from kyverno_tpu.ops.eval import build_scan_fn_blob

    all_policies = load_policies_from_path(
        "/root/reference/test/best_practices/")
    policies = [p for p in all_policies if p.spec.background]
    cps = CompiledPolicySet(policies)
    n_rules = int(cps.tensors.n_rules)
    scan_fn = build_scan_fn_blob(cps.tensors)

    chunk = 131_072                    # measured sweet spot: halves the
    n_chunks = 8                       # per-chunk dispatch latency count
    total = chunk * n_chunks           # 1,048,576 resources

    # snapshot synthesis is corpus setup, not scan work — untimed. The
    # chunks are pre-serialized JSON arrays: a real background scan's
    # input IS wire bytes (the apiserver list response), so the timed
    # region starts where a deployment's would — at the byte stream.
    snapshots = [
        json.dumps([make_pod(c * chunk + j) for j in range(chunk)]).encode()
        for c in range(n_chunks)
    ]

    def flatten_chunk(js: bytes):
        return cps.flatten_packed(json_docs=js, n_docs=chunk).packed_blob()

    # warm: compile the scan kernel AND the accumulation ops on a
    # representative chunk shape (first-run compiles inside the timed
    # region would be mislabeled as link weather)
    blob, shp = flatten_chunk(snapshots[0])
    wf, _, wh = scan_fn(blob, *shp)
    int(np.asarray((wf + wf).sum() + wh.sum()))

    # the scan pipeline: a worker thread flattens ahead (the native
    # flattener parses the JSON bytes with the GIL released) while the
    # main thread streams blobs onto the device. Counts accumulate ON
    # device chunk over chunk and the single forced readback happens
    # INSIDE the timed region — tunnel backends can report
    # block_until_ready before execution finishes, so only a real D2H
    # proves the work is done
    def one_scan() -> tuple[float, float, int, int]:
        """(total_s, device_s, fail_cells, host_rows) — host-flagged rows
        are resolved INSIDE the timed region: the kernel counts only
        non-host rows, and every flagged row's full verdict row comes
        from the CPU oracle (models/engine.py resolve_host_cells), the
        same work BackgroundScanner.scan pays. No device re-eval, so
        nothing compiles in the timed region; the host maps stack on
        device and read back in ONE transfer (the tunnel charges ~145ms
        per array); flagged documents regenerate from the synthetic
        corpus instead of re-parsing a whole chunk's JSON."""
        from kyverno_tpu.models import Verdict

        t0 = time.monotonic()
        acc_fails = None
        host_maps = []                 # device-resident [B] bool per chunk
        with concurrent.futures.ThreadPoolExecutor(max_workers=2) as ex:
            for blob, shp in ex.map(flatten_chunk, snapshots):
                f, _, h = scan_fn(blob, *shp)
                host_maps.append(h)
                acc_fails = f if acc_fails is None else acc_fails + f
        fails = int(np.asarray(acc_fails).sum())  # forces the whole chain
        acc_host = host_maps[0].sum()
        for h in host_maps[1:]:
            acc_host = acc_host + h.sum()
        host_rows = int(np.asarray(acc_host))     # scalar readback
        device_s = time.monotonic() - t0
        if host_rows:
            # only now pull the bitmaps — ONE stacked transfer, and only
            # when there is something to resolve
            host_all = np.asarray(jax.numpy.concatenate(host_maps))
            n_r = int(cps.tensors.n_rules)
            for c in range(n_chunks):
                idx = np.flatnonzero(host_all[c * chunk:(c + 1) * chunk])
                if not idx.size:
                    continue
                flagged = [make_pod(c * chunk + int(i)) for i in idx]
                verdicts = np.full((len(flagged), n_r),
                                   int(Verdict.HOST), dtype=np.int32)
                cps.resolve_host_cells(flagged, verdicts)
                fails += int((verdicts == Verdict.FAIL).sum())
        return time.monotonic() - t0, device_s, fails, host_rows

    # the tunnel's bandwidth swings ~3x run to run (shared link); three
    # runs with the best reported (and all recorded) measures the
    # pipeline rather than one draw of link weather
    runs = [one_scan(), one_scan(), one_scan()]
    dt, device_s, fails, host_rows = min(runs)
    return {
        "resources": total,
        "chunk": chunk,
        "rules": n_rules,
        "policies_scanned": len(policies),
        "policies_filtered_background_false": len(all_policies) - len(policies),
        "scan_s": round(dt, 2),
        "device_scan_s": round(device_s, 2),
        "scan_s_runs": [round(r[0], 2) for r in runs],
        "e2e_rate": round(total * n_rules / dt),
        "device_rate": round(total * n_rules / device_s),
        "fail_cells": fails,
        "host_rows_resolved": host_rows,
    }


def main() -> None:
    import jax

    configs = {}
    for name, f in (("1_single_pod_latency", bench_config1),
                    ("2_best_practices_4096", bench_config2),
                    ("3_library_250x10k", bench_config3),
                    ("4_mutate_50k", bench_config4),
                    ("5_scan_1M", bench_config5)):
        try:
            configs[name] = f(jax)
        except Exception as e:  # a config failure must not hide the rest
            configs[name] = {"error": f"{type(e).__name__}: {e}"}

    c2 = configs.get("2_best_practices_4096", {})
    device_rate = c2.get("device_rate", 0)
    result = {
        "metric": "policy-rule x resource validations/sec (device, steady state)",
        "value": device_rate,
        "unit": "validations/sec",
        "vs_baseline": round(device_rate / 100_000, 3),
        "detail": {"configs": configs},
    }
    print(json.dumps(result))


if __name__ == "__main__":
    sys.exit(main())
