"""Benchmark: the five BASELINE.md configs on one chip.

Primary metric (the JSON line's "value") stays config [2] — the
best_practices validate corpus against a 4096-Pod batch, steady-state
device throughput — for continuity with BENCH_r01/r02. The "configs"
detail reports all five BASELINE configs:

  [1] disallow-latest-tag x 1 Pod          admission latency (ms, p50/p99)
  [2] best_practices x 4096 Pods           device validations/s + e2e
  [3] ~250-policy library x 10k resources  device validations/s, host %
  [4] mutate strategic-merge x 50k         CPU-tier mutations/s (honest:
                                           the mutate path is host-side)
  [5] 1M-resource background-scan replay   e2e validations/s, chunked
                                           parallel flatten + pipelined eval

vs_baseline is value / 100k — the north-star target from BASELINE.json
(the reference publishes no numbers; see BASELINE.md).

Measurement methodology (round 6): every admission-burst lane draws from
a pool of DISTINCT resources (varied names/uids/images/labels) unless it
is explicitly labeled a cache-path lane, and every latency/throughput
number is reported next to the routing and cache-hit counters that
produced it. Round 5's headline burst number was a cache artifact —
16x16 identical bodies meant most requests were decision-cache hits;
the honest no-cache figure was 4x lower. The cached lanes are kept (a
Deployment scaling N replicas IS a repeated-body burst) but they are
labeled as such and never the headline. See BENCH.md.
"""

import concurrent.futures
import json
import os
import statistics
import sys
import threading
import time

import numpy as np


def make_pod(i: int) -> dict:
    imgs = ["nginx:latest", "nginx:1.21", "redis:6", "registry.io/a/b:v2"]
    c = {
        "name": f"c{i % 3}",
        "image": imgs[i % 4],
    }
    if i % 3:
        c["resources"] = {
            "requests": {"memory": "64Mi", "cpu": "100m"},
            "limits": {"memory": "128Mi"},
        }
    if i % 5 == 0:
        c["securityContext"] = {"privileged": i % 2 == 0}
    pod = {
        "apiVersion": "v1",
        "kind": "Pod",
        "metadata": {"name": f"pod-{i}"},
        "spec": {"containers": [c]},
    }
    if i % 4 == 0:
        pod["metadata"]["labels"] = {
            "app.kubernetes.io/name": "bench",
            "app.kubernetes.io/component": "api",
        }
    if i % 7 == 0:
        pod["spec"]["volumes"] = [{"name": "v", "emptyDir": {}}]
    return pod


def make_deployment(i: int) -> dict:
    return {
        "apiVersion": "apps/v1",
        "kind": "Deployment",
        "metadata": {"name": f"dep-{i}", "namespace": "default"},
        "spec": {
            "replicas": (i % 5) + 1,
            "selector": {"matchLabels": {"app": f"a{i % 9}"}},
            "template": {
                "metadata": {"labels": {"app": f"a{i % 9}"}},
                "spec": make_pod(i)["spec"],
            },
        },
    }


def make_service(i: int) -> dict:
    return {
        "apiVersion": "v1",
        "kind": "Service",
        "metadata": {"name": f"svc-{i}"},
        "spec": {"ports": [{"port": 80 + (i % 1000)}],
                 "type": "ClusterIP" if i % 3 else "LoadBalancer"},
    }


def mixed_resource(i: int) -> dict:
    r = i % 10
    if r < 6:
        return make_pod(i)
    if r < 9:
        return make_deployment(i)
    return make_service(i)


# --------------------------------------------------------------- libraries
# Every corpus loader falls back to an in-repo synthesized library when
# /root/reference is not mounted, so the bench measures the same code
# paths in any environment. Outputs carry a "library" field naming the
# source so numbers from different sources are never compared blindly.

LIBRARY_SOURCE = {}     # config label -> "reference" | "synthetic"


def _synth_policy_docs(n: int = 250) -> list:
    """Synthesized ~n-policy validate library with a production-shaped
    mix (all device/host routing classes are represented):

      - static-message deny material (disallow-latest, require-requests):
        device-lane patterns whose FAIL message needs no variable
        substitution, so an ATTENTION row denies straight from the row
      - variable-message denies ({{ request.object.* }}): device-lane
        patterns whose message substitutes from the admission request
      - all-pass hygiene rules (require-name, container-name): the CLEAN
        short-circuit material
      - Deployment/Service rules: exercise kind routing on mixed corpora
      - a small host-lane slice ({{variable}} inside the pattern): rules
        the device cannot score, resolved by the batched flush oracle
        (they are pool-safe: no context entries)
    """
    docs = []
    k = 0
    while len(docs) < n and k <= 40 * n:
        f = k % 25
        if f < 8:
            docs.append({
                "apiVersion": "kyverno.io/v1", "kind": "ClusterPolicy",
                "metadata": {"name": f"disallow-latest-tag-v{k}"},
                "spec": {"rules": [{
                    "name": "validate-image-tag",
                    "match": {"resources": {"kinds": ["Pod"]}},
                    "validate": {
                        "message": f"latest tag not allowed (check {k})",
                        "pattern": {"spec": {"containers": [
                            {"image": "!*:latest"}]}}},
                }]},
            })
        elif f < 13:
            docs.append({
                "apiVersion": "kyverno.io/v1", "kind": "ClusterPolicy",
                "metadata": {"name": f"require-requests-v{k}"},
                "spec": {"rules": [{
                    "name": "check-requests",
                    "match": {"resources": {"kinds": ["Pod"]}},
                    "validate": {
                        "message": f"memory requests required (check {k})",
                        "pattern": {"spec": {"containers": [
                            {"resources": {"requests": {
                                "memory": "?*"}}}]}}},
                }]},
            })
        elif f < 17:
            docs.append({
                "apiVersion": "kyverno.io/v1", "kind": "ClusterPolicy",
                "metadata": {"name": f"require-name-v{k}"},
                "spec": {"rules": [{
                    "name": "check-name",
                    "match": {"resources": {"kinds": ["Pod"]}},
                    "validate": {"message": f"name required ({k})",
                                 "pattern": {"metadata": {"name": "?*"}}},
                }]},
            })
        elif f < 19:
            docs.append({
                "apiVersion": "kyverno.io/v1", "kind": "ClusterPolicy",
                "metadata": {"name": f"deny-latest-named-v{k}"},
                "spec": {"rules": [{
                    "name": "named-latest",
                    "match": {"resources": {"kinds": ["Pod"]}},
                    "validate": {
                        "message": ("{{ request.object.metadata.name }}"
                                    f" must not use latest ({k})"),
                        "pattern": {"spec": {"containers": [
                            {"image": "!*:latest"}]}}},
                }]},
            })
        elif f < 21:
            docs.append({
                "apiVersion": "kyverno.io/v1", "kind": "ClusterPolicy",
                "metadata": {"name": f"deployment-selector-v{k}"},
                "spec": {"rules": [{
                    "name": "has-selector",
                    "match": {"resources": {"kinds": ["Deployment"]}},
                    "validate": {"message": f"selector required ({k})",
                                 "pattern": {"spec": {"selector": {
                                     "matchLabels": {"app": "?*"}}}}},
                }]},
            })
        elif f < 23:
            docs.append({
                "apiVersion": "kyverno.io/v1", "kind": "ClusterPolicy",
                "metadata": {"name": f"service-no-external-v{k}"},
                "spec": {"rules": [{
                    "name": "no-externalname",
                    "match": {"resources": {"kinds": ["Service"]}},
                    "validate": {"message": f"ExternalName banned ({k})",
                                 "pattern": {"spec": {
                                     "type": "!ExternalName"}}},
                }]},
            })
        elif f < 24:
            docs.append({
                "apiVersion": "kyverno.io/v1", "kind": "ClusterPolicy",
                "metadata": {"name": f"container-named-v{k}"},
                "spec": {"rules": [{
                    "name": "container-name",
                    "match": {"resources": {"kinds": ["Pod"]}},
                    "validate": {"message": f"container name required ({k})",
                                 "pattern": {"spec": {"containers": [
                                     {"name": "?*"}]}}},
                }]},
            })
        elif k % 150 == 24:
            # host-lane slice, kept small: each pod row carries one HOST
            # cell per such policy and every cell costs a CPU-oracle rule
            # evaluation to resolve
            docs.append({
                "apiVersion": "kyverno.io/v1", "kind": "ClusterPolicy",
                "metadata": {"name": f"host-echo-name-v{k}"},
                "spec": {"rules": [{
                    "name": "echo-name",
                    "match": {"resources": {"kinds": ["Pod"]}},
                    "validate": {
                        "message": f"name mismatch ({k})",
                        "pattern": {"metadata": {"name":
                                    "{{request.object.metadata.name}}"}}},
                }]},
            })
        k += 1
    return docs[:n]


def _load_reference(dirs) -> list:
    from kyverno_tpu.api.load import load_policies_from_path

    base = []
    for d in dirs:
        try:
            base += load_policies_from_path(f"/root/reference/test/{d}/")
        except Exception:
            pass
    return base


def _library_250():
    """~250-policy library (BASELINE config [3]): cloned with varied
    names from the reference test fixtures when mounted, else the
    in-repo synthesized library (_synth_policy_docs)."""
    from kyverno_tpu.api.load import load_policy

    base = _load_reference(("best_practices", "more", "policy/validate"))
    docs = [p.raw for p in base if p.raw]
    if not docs:
        LIBRARY_SOURCE["library_250"] = "synthetic"
        return [load_policy(d) for d in _synth_policy_docs(250)]
    LIBRARY_SOURCE["library_250"] = "reference"
    out = []
    i = 0
    while len(out) < 250:
        doc = json.loads(json.dumps(docs[i % len(docs)]))
        doc.setdefault("metadata", {})["name"] = (
            f"{doc['metadata'].get('name', 'p')}-v{i // len(docs)}")
        try:
            out.append(load_policy(doc))
        except Exception:
            pass
        i += 1
        if i > 1000:
            break
    return out


def _best_practices_policies():
    """best_practices corpus (configs [1], [2], [5]); synthesized
    device-lane subset when the reference tree is not mounted."""
    from kyverno_tpu.api.load import load_policy

    base = _load_reference(("best_practices",))
    if base:
        LIBRARY_SOURCE["best_practices"] = "reference"
        return base
    LIBRARY_SOURCE["best_practices"] = "synthetic"
    docs = [d for d in _synth_policy_docs(250)
            if "host-echo" not in d["metadata"]["name"]][:12]
    return [load_policy(d) for d in docs]


def _percentiles(lats):
    lats = sorted(lats)
    p99_idx = min(len(lats) - 1, -(-99 * len(lats) // 100) - 1)  # nearest-rank
    return (round(statistics.median(lats), 2), round(lats[p99_idx], 2))


# ------------------------------------------------------------- admission


def _admission_body(i: int, salt: str = "") -> bytes:
    """One DISTINCT admission review: unique name + uid, image/labels/
    resources varying with i (make_pod), so neither the decision cache,
    the screen-result cache nor the audit memo can serve it from an
    earlier request with a different body."""
    pod = make_pod(i)
    pod["metadata"]["name"] = f"pod-{salt}{i}"
    return json.dumps({
        "apiVersion": "admission.k8s.io/v1", "kind": "AdmissionReview",
        "request": {"uid": f"uid-{salt}{i}", "kind": {"kind": "Pod"},
                    "namespace": "default", "operation": "CREATE",
                    "object": pod},
    }).encode()


def _counter_delta(before: dict, after: dict) -> dict:
    """Numeric counter deltas (nested histogram dicts are skipped)."""
    out = {}
    for k, v in after.items():
        if isinstance(v, (int, float)):
            d = v - before.get(k, 0)
            if d:
                out[k] = round(d, 4) if isinstance(d, float) else d
    return out


def _lane_report(label, lats, burst_s, seq_p50, routing, concurrency):
    """One burst lane: latency next to the routing/cache counters that
    produced it, so a cache-fed number can never masquerade as pipeline
    throughput."""
    p50, p99 = _percentiles(lats)
    n = len(lats)
    cache_hits = routing.get("decision_cache", 0) + routing.get("cache", 0)
    return {
        "lane": label,
        "n": n, "concurrency": concurrency,
        "seq_latency_ms_p50": seq_p50,
        "latency_ms_p50": p50, "latency_ms_p99": p99,
        "req_per_s": round(n / burst_s),
        "cache_hits": cache_hits,
        "cache_hit_pct": round(100 * cache_hits / max(n, 1), 1),
        # requests decided from the device screen row without the inline
        # oracle (CLEAN short-circuits + fully direct denies); the
        # per-policy message counter is routing.device_deny
        "device_resolved_decisions": routing.get("device_decided", 0),
        "routing": routing,
    }


def _decidability_summary(policies) -> dict:
    """Static per-policy device-decidability (analysis KT110 scores)
    reported next to the measured routing counters: the analyzer's
    prediction of how much of the library the device lattice can decide,
    against which the observed device_decided/host split can be read."""
    from kyverno_tpu.analysis import analyze_policies

    scores = analyze_policies(policies,
                              include_tensors=False).device_decidability
    vals = list(scores.values()) or [1.0]
    return {
        "policies": len(scores),
        "mean": round(sum(vals) / len(vals), 4),
        "fully_device": sum(1 for v in vals if v == 1.0),
        "fully_host": sum(1 for v in vals if v == 0.0),
        "min": round(min(vals), 4),
    }


def bench_config1(jax):
    """disallow-latest-tag x 1 Pod: single-request admission latency through
    the production webhook path over real HTTP. The latency router
    (runtime/batch.py) sends lone requests straight to the CPU oracle; the
    device screen engages only when a burst forms, so a single kubectl
    apply never pays the device round trip."""
    import http.client
    import socket

    from kyverno_tpu.api.load import load_policy
    from kyverno_tpu.runtime.batch import AdmissionBatcher
    from kyverno_tpu.runtime.client import FakeCluster
    from kyverno_tpu.runtime.policycache import PolicyCache, PolicyType
    from kyverno_tpu.runtime.webhook import (
        VALIDATING_WEBHOOK_PATH,
        WebhookServer,
    )

    pols = [p for p in _best_practices_policies()
            if p.name == "disallow-latest-tag"]
    if not pols:
        pols = [load_policy(_synth_policy_docs(1)[0])]
    for p in pols:
        p.spec.validation_failure_action = "enforce"
    cache = PolicyCache()
    for p in pols:
        cache.add(p)
    batcher = AdmissionBatcher(cache)
    server = WebhookServer(policy_cache=cache, client=FakeCluster(),
                           admission_batcher=batcher)
    httpd = server.run(host="127.0.0.1", port=0)
    port = httpd.server_address[1]
    body = json.dumps({
        "apiVersion": "admission.k8s.io/v1", "kind": "AdmissionReview",
        "request": {"uid": "bench", "kind": {"kind": "Pod"},
                    "namespace": "default", "operation": "CREATE",
                    "object": make_pod(1)},
    }).encode()
    headers = {"Content-Type": "application/json"}

    def connect(port):
        c = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
        c.connect()
        c.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        return c

    def post(conn, b=body):
        # persistent keep-alive connection, like the API server's
        conn.request("POST", VALIDATING_WEBHOOK_PATH, b, headers)
        return json.loads(conn.getresponse().read())

    try:
        conn = connect(port)
        allowed = post(conn)["response"]["allowed"]  # warm + probe
        for _ in range(10):
            post(conn)
        lats = []
        for _ in range(200):
            t0 = time.perf_counter()
            post(conn)
            lats.append((time.perf_counter() - t0) * 1e3)
        conn.close()
        p50, p99 = _percentiles(lats)

        # burst shape: 16 workers x 32 DISTINCT requests on persistent
        # connections; the router decides oracle-vs-device from measured
        # costs
        burst_lats = []
        burst_bodies = [_admission_body(i, salt="s") for i in range(16 * 32)]

        def worker(w):
            c = connect(port)
            for j in range(32):
                b = burst_bodies[w * 32 + j]
                t0 = time.perf_counter()
                post(c, b)
                burst_lats.append((time.perf_counter() - t0) * 1e3)
            c.close()

        threads = [threading.Thread(target=worker, args=(w,))
                   for w in range(16)]
        t0 = time.monotonic()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        burst_s = time.monotonic() - t0
        bp50, bp99 = _percentiles(burst_lats)
        routing_small = dict(batcher.stats)
    finally:
        server.stop()
        batcher.stop()

    # library-scale burst: with ~250 enforce policies the per-request CPU
    # oracle costs tens of ms, so the cost model flips bursts onto the
    # device screen; ATTENTION rows with static or request-substitutable
    # messages deny straight from the device row, fully-PASS rows
    # short-circuit CLEAN, and residual host-lane cells resolve inside
    # the flush's single batched oracle pass
    lib = _library_250()
    for p in lib:
        p.spec.validation_failure_action = "enforce"
    lib_cache = PolicyCache()
    for p in lib:
        lib_cache.add(p)

    N_THREADS, PER_THREAD = 16, 16

    def run_burst(port, batcher, bodies, warm_pools):
        """(seq_p50, lats, burst_s, routing_delta): explicit warmup, then
        the timed burst. Warmup is off the clock on purpose: a sequential
        pass over the first pool JITs the single-request path, then one
        concurrent round per pool compiles every heterogeneous flush
        shape the timed burst will hit — an XLA compile paid inline blows
        the screen deadline and opens the circuit breaker, which is
        startup weather, not steady-state routing (the controller's
        warmup() exists to pay it before traffic). If warmup did trip
        the breaker, the cooldown is waited out so the timed region
        starts with the breaker closed. Shared by every lane so cached
        and cache-adversarial runs can never drift methodologically —
        only the body pools differ."""
        def post_slice(bods, out):
            c = connect(port)
            for b in bods:
                t0 = time.perf_counter()
                c.request("POST", VALIDATING_WEBHOOK_PATH, b, headers)
                c.getresponse().read()
                out.append((time.perf_counter() - t0) * 1e3)
            c.close()

        def concurrent_round(pool, out):
            workers = [threading.Thread(
                target=post_slice,
                args=(pool[w * PER_THREAD:(w + 1) * PER_THREAD], out))
                for w in range(N_THREADS)]
            t0 = time.monotonic()
            for t in workers:
                t.start()
            for t in workers:
                t.join()
            return time.monotonic() - t0

        warm_lats: list = []
        post_slice(warm_pools[0][:32], warm_lats)
        seq_p50, _ = _percentiles(warm_lats)
        pre = dict(batcher.stats)
        for pool in warm_pools:
            concurrent_round(pool, [])
        tripped = _counter_delta(pre, dict(batcher.stats))
        if tripped.get("circuit_open") or tripped.get("screen_timeout"):
            time.sleep(batcher.circuit_cooldown_s + 0.2)

        before = dict(batcher.stats)
        lats: list = []
        burst_s = concurrent_round(bodies, lats)
        return (seq_p50, lats, burst_s,
                _counter_delta(before, dict(batcher.stats)))

    n_bodies = N_THREADS * PER_THREAD
    distinct = [_admission_body(i, salt="lib") for i in range(n_bodies)]
    distinct_warm = [
        [_admission_body(i, salt=f"w{r}") for i in range(n_bodies)]
        for r in range(2)]
    fixed = [body] * n_bodies

    lanes = {}
    # headline lane: cache-adversarial — every request is a distinct
    # resource, nothing can be served from a cache hit
    lib_batcher = AdmissionBatcher(lib_cache)
    lib_server = WebhookServer(policy_cache=lib_cache, client=FakeCluster(),
                               admission_batcher=lib_batcher)
    lib_httpd = lib_server.run(host="127.0.0.1", port=0)
    lib_batcher.warmup(  # controller startup does this (server.py)
        PolicyType.VALIDATE_ENFORCE, "Pod", "default", make_pod(1))
    try:
        seq_p50, lats, bs, routing = run_burst(
            lib_httpd.server_address[1], lib_batcher,
            distinct, distinct_warm)
        lanes["burst_library_250"] = _lane_report(
            "cache-adversarial: distinct names/uids/images/labels",
            lats, bs, seq_p50, routing, N_THREADS)
        # cache-path lane on the SAME server: one fixed body repeated —
        # the repeated-identical-body regime (a Deployment scaling N
        # replicas). Kept for continuity with r05's headline, but
        # labeled: its throughput is decision-cache throughput, not
        # pipeline throughput.
        seq_p50, lats, bs, routing = run_burst(
            lib_httpd.server_address[1], lib_batcher,
            fixed, [[body] * 32])
        lanes["burst_library_250_fixed_body"] = _lane_report(
            "cache path: one body repeated (r05 methodology)",
            lats, bs, seq_p50, routing, N_THREADS)
    finally:
        lib_server.stop()
        lib_batcher.stop()

    # transparency lane: distinct bodies AND all result/decision caching
    # off — the raw screen + direct-deny + flush-resolution pipeline with
    # every request paying full routing
    nc_batcher = AdmissionBatcher(lib_cache, result_cache_ttl_s=0.0)
    nc_server = WebhookServer(policy_cache=lib_cache, client=FakeCluster(),
                              admission_batcher=nc_batcher)
    nc_httpd = nc_server.run(host="127.0.0.1", port=0)
    nc_batcher.warmup(
        PolicyType.VALIDATE_ENFORCE, "Pod", "default", make_pod(1))
    try:
        seq_p50, lats, bs, routing = run_burst(
            nc_httpd.server_address[1], nc_batcher,
            [_admission_body(i, salt="nc") for i in range(n_bodies)],
            [[_admission_body(i, salt=f"ncw{r}") for i in range(n_bodies)]
             for r in range(2)])
        lanes["burst_library_250_nocache"] = _lane_report(
            "cache-adversarial + caches disabled (ttl=0)",
            lats, bs, seq_p50, routing, N_THREADS)
    finally:
        nc_server.stop()
        nc_batcher.stop()

    # audit burst: the same 250-policy library in audit mode, drained
    # through the queue (validate_audit.go's 10 workers). Audit has no
    # latency budget, so the screen engages deadline-free. The default
    # lanes drain DISTINCT resources; the memo lane repeats one body and
    # is labeled — its rate is TTL-memo throughput (ResourceManager
    # analogue, pkg/policy/existing.go:125), not evaluation throughput.
    audit_lib = _library_250()
    for p in audit_lib:
        p.spec.validation_failure_action = "audit"
    # ONE policy cache for both lanes: the compiled tensors/XLA artifacts
    # hang off it, and a fresh cache per lane would recompile on the
    # real chip (~20-40s per shape) for no measurement value
    audit_cache = PolicyCache()
    for p in audit_lib:
        audit_cache.add(p)

    def drain_audit(with_screen: bool, objs) -> tuple:
        """(seconds, routing_delta) for draining ``objs`` through the
        audit queue."""
        batcher = AdmissionBatcher(audit_cache) if with_screen else None
        server = WebhookServer(policy_cache=audit_cache, client=FakeCluster(),
                               admission_batcher=batcher)
        if with_screen:
            batcher.warmup(PolicyType.VALIDATE_AUDIT, "Pod", "default",
                           make_pod(1))
        server.audit_handler.run()
        try:
            server._process_audit({  # warm both lanes off the clock
                "uid": "warm", "kind": {"kind": "Pod"},
                "namespace": "default", "operation": "CREATE",
                "object": make_pod(10_001)})
            before = dict(batcher.stats) if batcher else {}
            t0 = time.monotonic()
            for i, obj in enumerate(objs):
                server.audit_handler.add({
                    "uid": f"a{i}", "kind": {"kind": obj["kind"]},
                    "namespace": "default", "operation": "CREATE",
                    "object": obj})
            server.audit_handler.drain(timeout=600)
            dt = time.monotonic() - t0
            routing = (_counter_delta(before, dict(batcher.stats))
                       if batcher else {})
            return dt, routing
        finally:
            server.audit_handler.stop()
            if batcher is not None:
                batcher.stop()

    audit_n = 256
    audit_objs = [make_pod(i) for i in range(audit_n)]      # distinct
    screened_s, audit_routing = drain_audit(True, audit_objs)
    oracle_s, _ = drain_audit(False, audit_objs)
    memo_s, memo_routing = drain_audit(True, [make_pod(1)] * audit_n)
    audit_burst = {
        "n": audit_n, "policies": len(audit_lib),
        "lane": "cache-adversarial: distinct resources",
        "screened_req_per_s": round(audit_n / screened_s),
        "oracle_req_per_s": round(audit_n / oracle_s),
        "speedup": round(oracle_s / screened_s, 1),
        "routing": audit_routing,
        "memo_fixed_body": {
            "lane": "memo path: one body repeated (TTL memo hits)",
            "req_per_s": round(audit_n / memo_s),
            "memo_hits": memo_routing.get("audit_memo", 0),
            "routing": memo_routing,
        },
    }

    out = {
        "latency_ms_p50": p50,
        "latency_ms_p99": p99,
        "n_iters": len(lats),
        "allowed": allowed,
        "library": LIBRARY_SOURCE.get("library_250", "reference"),
        "burst": {"lane": "distinct bodies, 1-policy set",
                  "n": len(burst_lats), "concurrency": 16,
                  "latency_ms_p50": bp50, "latency_ms_p99": bp99,
                  "req_per_s": round(len(burst_lats) / burst_s),
                  "routing": _counter_delta({}, routing_small)},
        "audit_burst_library_250": audit_burst,
        "device_decidability_library_250": _decidability_summary(lib),
        "path": "HTTP POST /validate (production handler, latency-routed)",
    }
    out.update(lanes)
    return out


def _timed_steady_state(fn, dblob, shp, n_iters: int) -> tuple[float, np.ndarray]:
    """(seconds per eval, warmup verdicts) — honestly timed: tunnel
    backends can report block_until_ready before execution finishes, so
    the timed region ends with a real D2H of a device-side scalar
    reduction of the LAST output. One device stream executes programs in
    submission order, so that byte-sized readback proves every queued
    eval completed without coupling the measurement to the link's
    multi-MB transfer weather."""
    out = fn(dblob, *shp)
    verdicts = np.asarray(out)         # compile + first run, forced
    int(out.astype("int32").sum())     # warm the reduction kernel too
    t0 = time.monotonic()
    outs = [fn(dblob, *shp) for _ in range(n_iters)]
    int(outs[-1].astype("int32").sum())
    device_s = (time.monotonic() - t0) / n_iters
    return device_s, verdicts


def bench_config2(jax):
    """best_practices x 4096: steady-state device throughput and e2e with
    a fresh flatten, measured over BOTH dataflows: the serial loop
    (flatten window, then eval it, repeat — the pre-pipeline admission
    flush) and the pipelined one (a prefetch thread flattens window k+1
    while the device scores window k, async dispatch, one materialization
    per window). ``e2e_rate_with_flatten`` is the pipelined dataflow —
    the rate the runtime actually sustains; ``e2e_rate_serial`` keeps the
    old definition for comparison, and the per-stage seconds are printed
    beside both so the overlap is auditable."""
    import concurrent.futures

    from kyverno_tpu.models import CompiledPolicySet
    from kyverno_tpu.models.flatten import pad_to_buckets_packed

    cps = CompiledPolicySet(_best_practices_policies())
    B = 4096
    W = 8                               # flush windows per measured run
    resources = [make_pod(i) for i in range(B)]
    windows = [[make_pod(w * B + i) for i in range(B)] for w in range(W)]

    cps.flatten_packed(resources[:8])  # warm the native flattener
    t0 = time.monotonic()
    batch = cps.flatten_packed(resources)
    blob, shp = batch.packed_blob()
    flatten_s = time.monotonic() - t0

    fn = cps.blob_eval_fn
    dblob = jax.device_put(blob)
    dblob.block_until_ready()
    device_s, verdicts = _timed_steady_state(fn, dblob, shp, n_iters=30)

    # window flatten pads to pow2 buckets (the admission path's shape
    # bucketing) so all W windows share one compiled kernel — without it
    # each window's dictionary size V is its own XLA compile
    def flatten_window(w):
        return pad_to_buckets_packed(cps.flatten_packed(w))[0]

    warm = flatten_window(windows[0])
    np.asarray(cps.evaluate_device(warm))          # compile the bucket

    # serial dataflow: each window pays flatten THEN eval on the critical
    # path (what _flush did before async dispatch)
    serial_flatten_s = serial_device_s = 0.0
    serial_verdicts = []
    t0 = time.monotonic()
    for w in windows:
        t1 = time.monotonic()
        wb = flatten_window(w)
        serial_flatten_s += time.monotonic() - t1
        t1 = time.monotonic()
        serial_verdicts.append(np.asarray(cps.evaluate_device(wb)))
        serial_device_s += time.monotonic() - t1
    serial_s = time.monotonic() - t0

    # pipelined dataflow: double-buffered — flatten of window k+1 runs on
    # the prefetch thread (native parse, GIL released) while window k's
    # dispatch is in flight; window k-1 materializes in the same shadow
    pipe_verdicts = [None] * W
    t0 = time.monotonic()
    with concurrent.futures.ThreadPoolExecutor(max_workers=1) as ex:
        pending = ex.submit(flatten_window, windows[0])
        in_flight = []                  # [(window index, AsyncVerdicts)]
        for k in range(W):
            wb = pending.result()
            if k + 1 < W:
                pending = ex.submit(flatten_window, windows[k + 1])
            in_flight.append((k, cps.evaluate_device_async(wb)))
            if len(in_flight) > 1:
                j, h = in_flight.pop(0)
                pipe_verdicts[j] = h.get()
        for j, h in in_flight:
            pipe_verdicts[j] = h.get()
    pipe_s = time.monotonic() - t0

    parity = all(np.array_equal(a, b)
                 for a, b in zip(serial_verdicts, pipe_verdicts))

    # tracing overhead A/B (acceptance: <=2% with tracing on): the
    # instrumented evaluate_pipelined dataflow at this config's window
    # geometry (W windows of B rows, one trace per window, spans on
    # flatten / dispatch / host resolve) with the recorder on (default)
    # vs the KTPU_TRACE=0 kill switch. Estimator: interleaved pairs,
    # best-of-2 per lane per pair, median of the per-pair ratios —
    # pairing cancels machine drift and the median rejects the multi-ms
    # scheduler excursions that swamp a percent-level effect in means.
    trace_docs = [p for w in windows for p in w]

    def tracing_run(flag: str) -> float:
        os.environ["KTPU_TRACE"] = flag
        best = float("inf")
        for _ in range(2):
            t1 = time.monotonic()
            np.asarray(cps.evaluate_pipelined(trace_docs, chunk=B))
            best = min(best, time.monotonic() - t1)
        return best

    prev = os.environ.pop("KTPU_TRACE", None)
    try:
        os.environ["KTPU_TRACE"] = "1"
        v_on = np.asarray(cps.evaluate_pipelined(trace_docs, chunk=B))
        os.environ["KTPU_TRACE"] = "0"
        v_off = np.asarray(cps.evaluate_pipelined(trace_docs, chunk=B))
        ratios, trace_on, trace_off = [], [], []
        for i in range(8):
            if i % 2:                    # alternate pair order
                off_s = tracing_run("0")
                on_s = tracing_run("1")
            else:
                on_s = tracing_run("1")
                off_s = tracing_run("0")
            ratios.append(on_s / off_s)
            trace_on.append(on_s)
            trace_off.append(off_s)
    finally:
        os.environ.pop("KTPU_TRACE", None)
        if prev is not None:
            os.environ["KTPU_TRACE"] = prev
    trace_on_s, trace_off_s = min(trace_on), min(trace_off)
    trace_overhead_pct = (statistics.median(ratios) - 1) * 100

    # attribution overhead A/B (acceptance: <=2%): the same interleaved-
    # pairs estimator over the same pipelined dataflow, toggling the
    # KTPU_ATTRIB lane — with attribution on, every drained chunk feeds
    # the vectorized per-policy verdict matrix into the bounded registry
    def attrib_run(flag: str) -> float:
        os.environ["KTPU_ATTRIB"] = flag
        best = float("inf")
        for _ in range(2):
            t1 = time.monotonic()
            np.asarray(cps.evaluate_pipelined(trace_docs, chunk=B))
            best = min(best, time.monotonic() - t1)
        return best

    prev = os.environ.pop("KTPU_ATTRIB", None)
    try:
        os.environ["KTPU_ATTRIB"] = "1"
        av_on = np.asarray(cps.evaluate_pipelined(trace_docs, chunk=B))
        os.environ["KTPU_ATTRIB"] = "0"
        av_off = np.asarray(cps.evaluate_pipelined(trace_docs, chunk=B))
        a_ratios, a_on, a_off = [], [], []
        for i in range(8):
            if i % 2:
                off_s = attrib_run("0")
                on_s = attrib_run("1")
            else:
                on_s = attrib_run("1")
                off_s = attrib_run("0")
            a_ratios.append(on_s / off_s)
            a_on.append(on_s)
            a_off.append(off_s)
    finally:
        os.environ.pop("KTPU_ATTRIB", None)
        if prev is not None:
            os.environ["KTPU_ATTRIB"] = prev
    attrib_overhead_pct = (statistics.median(a_ratios) - 1) * 100

    n_rules = int(cps.tensors.n_rules)
    validations = B * n_rules
    return {
        "batch": B,
        "rules": n_rules,
        "library": LIBRARY_SOURCE.get("best_practices", "reference"),
        "device_rules": int((~cps.tensors.rule_host_only).sum()),
        "device_decidability": _decidability_summary(cps.policies),
        "device_s_per_batch": round(device_s, 5),
        "flatten_s": round(flatten_s, 3),
        "device_rate": round(validations / device_s),
        # pipelined e2e over W fresh windows — the headline dataflow
        "e2e_rate_with_flatten": round(W * validations / pipe_s),
        "e2e_rate_serial": round(W * validations / serial_s),
        "pipeline": {
            "windows": W,
            "serial_s": round(serial_s, 3),
            "serial_flatten_s": round(serial_flatten_s, 3),
            "serial_device_s": round(serial_device_s, 3),
            "pipelined_s": round(pipe_s, 3),
            "overlap_s_saved": round(serial_s - pipe_s, 3),
            "speedup": round(serial_s / pipe_s, 3),
            "verdict_parity": parity,
        },
        "tracing": {
            "on_s": round(trace_on_s, 4),
            "off_s": round(trace_off_s, 4),
            "overhead_pct": round(trace_overhead_pct, 2),
            "within_2pct": trace_overhead_pct <= 2.0,
            "verdict_parity": bool(np.array_equal(v_on, v_off)),
        },
        "attribution": {
            "on_s": round(min(a_on), 4),
            "off_s": round(min(a_off), 4),
            "overhead_pct": round(attrib_overhead_pct, 2),
            "within_2pct": attrib_overhead_pct <= 2.0,
            "verdict_parity": bool(np.array_equal(av_on, av_off)),
        },
        "verdict_histogram": {
            str(k): int(v)
            for k, v in zip(*np.unique(verdicts, return_counts=True))
        },
    }


def bench_config3(jax):
    """250-policy library x 10k mixed resources: device lane PLUS the
    batched CPU-oracle resolution of every residual HOST cell INSIDE the
    timed region — device_rate alone would silently drop host-lane rules
    (round 5 reported 7.55% of cells as HOST and never paid to resolve
    them), so the honest end-to-end figure is e2e_rate_with_resolution."""
    from kyverno_tpu.models import CompiledPolicySet

    cps = CompiledPolicySet(_library_250())
    B = 10_000
    resources = [mixed_resource(i) for i in range(B)]
    cps.flatten_packed(resources[:8])  # warm the native flattener
    t0 = time.monotonic()
    batch = cps.flatten_packed(resources)
    blob, shp = batch.packed_blob()
    flatten_s = time.monotonic() - t0

    fn = cps.blob_eval_fn
    dblob = jax.device_put(blob)
    dblob.block_until_ready()
    device_s, verdicts = _timed_steady_state(fn, dblob, shp, n_iters=5)

    from kyverno_tpu.models.engine import Verdict

    n_rules = int(cps.tensors.n_rules)
    host_cells = int((verdicts == Verdict.HOST).sum())

    # resolve the HOST cells the way a deployment must: one batched
    # oracle pass, timed — config [3] is "validate the library against
    # 10k resources", not "validate the device-scorable subset"
    t0 = time.monotonic()
    resolved = cps.resolve_host_cells(resources, verdicts, copy=True)
    resolve_s = time.monotonic() - t0
    residual = int((resolved == Verdict.HOST).sum())

    return {
        "policies": len(cps.policies),
        "rules": n_rules,
        "library": LIBRARY_SOURCE.get("library_250", "reference"),
        "device_rules": int((~cps.tensors.rule_host_only).sum()),
        "batch": B,
        "flatten_s": round(flatten_s, 3),
        "device_s_per_batch": round(device_s, 5),
        "device_rate": round(B * n_rules / device_s),
        "e2e_rate_with_flatten": round(B * n_rules / (device_s + flatten_s)),
        "host_cell_pct": round(100 * host_cells / verdicts.size, 2),
        "host_cells_resolved": host_cells - residual,
        "host_cells_residual": residual,
        "resolve_s": round(resolve_s, 3),
        "e2e_rate_with_resolution": round(
            B * n_rules / (device_s + flatten_s + resolve_s)),
    }


def bench_config4(jax):
    """Mutate strategic-merge batch (add-default-labels x 50k docs) through
    the batched mutate tier (engine/mutate/batch.py): device gate screen +
    single-pass merge/patch emission. Patch bytes are asserted identical to
    the serial engine chain on a 1k sample."""
    import json as _json

    from kyverno_tpu.api.load import load_policies_from_path, load_policy
    from kyverno_tpu.engine.context import Context
    from kyverno_tpu.engine.mutate.batch import BatchMutator
    from kyverno_tpu.engine.mutation import mutate
    from kyverno_tpu.engine.policy_context import PolicyContext

    try:
        pols = [p for p in
                load_policies_from_path("/root/reference/test/more/")
                if p.name == "add-default-labels"]
    except Exception:
        pols = []
    if not pols:
        # reference tree not mounted: the same fixture, inline
        pols = [load_policy({
            "apiVersion": "kyverno.io/v1", "kind": "ClusterPolicy",
            "metadata": {"name": "add-default-labels"},
            "spec": {"rules": [{
                "name": "add-labels",
                "match": {"resources": {
                    "kinds": ["Pod", "Service", "Namespace"]}},
                "mutate": {"patchStrategicMerge": {"metadata": {"labels": {
                    "+(app.kubernetes.io/managed-by)": "kyverno"}}}},
            }]},
        })]
    policy = pols[0]

    # the fixture matches Pod/Service/Namespace, so the batch runs over
    # Pods — the kind the policy actually patches
    n = 50_000
    docs = [make_pod(i) for i in range(n)]
    bm = BatchMutator(pols)
    bm.apply(docs[:64])   # warm

    # best-of-2: this tier is pure CPU and the sandbox host is shared,
    # so single draws swing ~2x (same policy as config 5's runs)
    def timed_apply(m, corpus):
        t0 = time.monotonic()
        result = m.apply(corpus)
        return time.monotonic() - t0, result

    draws = [timed_apply(bm, docs) for _ in range(2)]
    dt, out = min(draws, key=lambda t: t[0])

    # byte-parity vs the serial engine chain on a 1k sample
    mismatches = 0
    for doc, got in zip(docs[:1000], out[:1000]):
        jctx = Context()
        jctx.add_resource(doc)
        resp = mutate(PolicyContext(policy=policy, new_resource=doc,
                                    json_context=jctx))
        if _json.dumps(got.patches) != _json.dumps(resp.patches):
            mismatches += 1

    # selector-gated phase: a label-selector gate has real predicate work,
    # so the measured router may ship the screen to the device; only
    # matching docs (15% of the mixed corpus: 60% Pods x 1-in-4 labeled)
    # reach the CPU merge
    sel_policy = load_policy({
        "apiVersion": "kyverno.io/v1", "kind": "ClusterPolicy",
        "metadata": {"name": "annotate-bench-apps"},
        "spec": {"rules": [{
            "name": "annotate",
            "match": {"resources": {"kinds": ["Pod"], "selector": {
                "matchLabels": {"app.kubernetes.io/name": "bench"}}}},
            "mutate": {"patchStrategicMerge": {
                "metadata": {"annotations": {"+(bench/tier)": "gated"}}}},
        }]},
    })
    bm2 = BatchMutator([sel_policy], min_gate_batch=64)
    mixed = [mixed_resource(i) for i in range(n)]
    bm2.apply(mixed[:256])   # calibrates the gate lane choice
    if bm2._gate_choice:
        # device lane chosen: pre-compile every chunk-shape bucket the
        # timed run will use (8192-chunks + the tail bucket)
        bm2.gate_verdicts(mixed)
    draws2 = [timed_apply(bm2, mixed) for _ in range(2)]
    dt2, out2 = min(draws2, key=lambda t: t[0])

    return {
        "n_docs": n,
        "target_docs": 50_000,
        "mutations_per_s": round(n / dt),
        "mutations_per_s_runs": [round(n / d) for d, _ in draws],
        "patched": sum(1 for r in out if r.patches),
        "parity_sample": 1000,
        "parity_mismatches": mismatches,
        "tier": "single-pass CPU merge, auto-gated (kind-only gate -> host)",
        "selector_gated_mixed": {
            "n_docs": n,
            "mutations_per_s": round(n / dt2),
            "mutations_per_s_runs": [round(n / d) for d, _ in draws2],
            "patched": sum(1 for r in out2 if r.patches),
            "gate_lane": ("device" if bm2._gate_choice else "host"),
            "tier": "selector gate, measured lane choice + single-pass merge",
        },
    }


def bench_config5(jax):
    """Background-scan replay: 1M-resource snapshot through the full
    pipeline — native flatten of chunk N+1 overlapping the single-blob
    transfer + device eval of chunk N, with per-rule counts reduced on
    device (readback is bytes, not the [B, R] verdict matrix).

    Scanner-faithful semantics: policies with ``background: false`` are
    excluded exactly as BackgroundScanner does (runtime/background.py:71,
    mirroring canBackgroundProcess, pkg/policy/policy_controller.go:181)
    — round 4 ran select-secrets (apiCall context, background: false),
    which flagged every row host-lane without ever paying to resolve it.
    Any HOST rows that remain are now resolved through the batched
    oracle INSIDE the timed region, and the device-only vs resolved
    timings are reported separately."""
    from kyverno_tpu.models import CompiledPolicySet
    from kyverno_tpu.ops.eval import build_scan_fn_blob

    all_policies = _best_practices_policies()
    policies = [p for p in all_policies if p.spec.background]
    cps = CompiledPolicySet(policies)
    n_rules = int(cps.tensors.n_rules)
    scan_fn = build_scan_fn_blob(cps.tensors)

    chunk = 131_072                    # measured sweet spot: halves the
    n_chunks = 8                       # per-chunk dispatch latency count
    total = chunk * n_chunks           # 1,048,576 resources

    # snapshot synthesis is corpus setup, not scan work — untimed. The
    # chunks are pre-serialized JSON arrays: a real background scan's
    # input IS wire bytes (the apiserver list response), so the timed
    # region starts where a deployment's would — at the byte stream.
    snapshots = [
        json.dumps([make_pod(c * chunk + j) for j in range(chunk)]).encode()
        for c in range(n_chunks)
    ]

    def flatten_chunk(js: bytes):
        return cps.flatten_packed(json_docs=js, n_docs=chunk).packed_blob()

    # warm: compile the scan kernel AND the accumulation ops on a
    # representative chunk shape (first-run compiles inside the timed
    # region would be mislabeled as link weather)
    blob, shp = flatten_chunk(snapshots[0])
    wf, _, wh = scan_fn(blob, *shp)
    int(np.asarray((wf + wf).sum() + wh.sum()))

    # the scan pipeline: a worker thread flattens ahead (the native
    # flattener parses the JSON bytes with the GIL released) while the
    # main thread streams blobs onto the device. Counts accumulate ON
    # device chunk over chunk and the single forced readback happens
    # INSIDE the timed region — tunnel backends can report
    # block_until_ready before execution finishes, so only a real D2H
    # proves the work is done
    def one_scan() -> tuple[float, float, int, int]:
        """(total_s, device_s, fail_cells, host_rows) — host-flagged rows
        are resolved INSIDE the timed region: the kernel counts only
        non-host rows, and every flagged row's full verdict row comes
        from the CPU oracle (models/engine.py resolve_host_cells), the
        same work BackgroundScanner.scan pays. No device re-eval, so
        nothing compiles in the timed region; the host maps stack on
        device and read back in ONE transfer (the tunnel charges ~145ms
        per array); flagged documents regenerate from the synthetic
        corpus instead of re-parsing a whole chunk's JSON."""
        from kyverno_tpu.models import Verdict

        t0 = time.monotonic()
        acc_fails = None
        host_maps = []                 # device-resident [B] bool per chunk
        flat_s: list[float] = []       # per-chunk flatten seconds (workers)

        def timed_flatten(js: bytes):
            t = time.monotonic()
            out = flatten_chunk(js)
            flat_s.append(time.monotonic() - t)
            return out

        with concurrent.futures.ThreadPoolExecutor(max_workers=2) as ex:
            for blob, shp in ex.map(timed_flatten, snapshots):
                f, _, h = scan_fn(blob, *shp)
                host_maps.append(h)
                acc_fails = f if acc_fails is None else acc_fails + f
        t_wait = time.monotonic()
        fails = int(np.asarray(acc_fails).sum())  # forces the whole chain
        acc_host = host_maps[0].sum()
        for h in host_maps[1:]:
            acc_host = acc_host + h.sum()
        host_rows = int(np.asarray(acc_host))     # scalar readback
        device_s = time.monotonic() - t0
        # pipeline accounting: stage seconds sum to more than the wall
        # exactly when flatten ran in the device stream's shadow
        stages = {
            "flatten_s": round(sum(flat_s), 2),
            "device_wait_s": round(time.monotonic() - t_wait, 2),
            "overlap_s_saved": round(
                max(0.0, sum(flat_s) + (time.monotonic() - t_wait)
                    - device_s), 2),
        }
        if host_rows:
            # only now pull the bitmaps — ONE stacked transfer, and only
            # when there is something to resolve
            host_all = np.asarray(jax.numpy.concatenate(host_maps))
            n_r = int(cps.tensors.n_rules)
            for c in range(n_chunks):
                idx = np.flatnonzero(host_all[c * chunk:(c + 1) * chunk])
                if not idx.size:
                    continue
                flagged = [make_pod(c * chunk + int(i)) for i in idx]
                verdicts = np.full((len(flagged), n_r),
                                   int(Verdict.HOST), dtype=np.int32)
                cps.resolve_host_cells(flagged, verdicts)
                fails += int((verdicts == Verdict.FAIL).sum())
        return time.monotonic() - t0, device_s, fails, host_rows, stages

    # the tunnel's bandwidth swings ~3x run to run (shared link); three
    # runs with the best reported (and all recorded) measures the
    # pipeline rather than one draw of link weather
    runs = [one_scan(), one_scan(), one_scan()]
    dt, device_s, fails, host_rows, stages = min(runs,
                                                 key=lambda r: r[0])
    return {
        "resources": total,
        "chunk": chunk,
        "rules": n_rules,
        "library": LIBRARY_SOURCE.get("best_practices", "reference"),
        "policies_scanned": len(policies),
        "policies_filtered_background_false": len(all_policies) - len(policies),
        "scan_s": round(dt, 2),
        "device_scan_s": round(device_s, 2),
        "stages": stages,
        "scan_s_runs": [round(r[0], 2) for r in runs],
        "e2e_rate": round(total * n_rules / dt),
        "device_rate": round(total * n_rules / device_s),
        "fail_cells": fails,
        "host_rows_resolved": host_rows,
    }


def bench_config6(jax):
    """Policy-update storm (round 7): the ~250-policy library absorbing
    N single-policy updates while admissions keep flowing. Three
    measurements, each printed beside the counters that produced it:

      - readmission latency: after every update, the SAME resource set
        re-screens through the splice path (segment recompile + epoch-
        refreshed flatten memos); p50/p99 over all storm rounds
      - compile cost: per-update incremental splice seconds
        (PolicyCache.compile_totals) vs the same storm on the
        KTPU_INCREMENTAL=0 full-recompile path
      - delta background scan: one policy updated -> re-evaluate only
        that policy's rule columns against memoized rows, vs a
        from-scratch full rescan of the snapshot

    Memo survival is measured across the storm (after one warm fill
    pass): append-only updates must keep > 90% of flatten rows alive
    (the acceptance bar), counted by the row cache itself."""
    from kyverno_tpu.api.load import load_policy
    from kyverno_tpu.models import CompiledPolicySet
    from kyverno_tpu.runtime.background import BackgroundScanner
    from kyverno_tpu.runtime.batch import AdmissionBatcher
    from kyverno_tpu.runtime.policycache import PolicyCache, PolicyType

    N_UPDATES = 8

    def updated(policy, k: int):
        """Single-policy update, append-only: the replacement keeps the
        name but validates a fresh path, so the shared dictionary only
        appends (the storm shape that keeps memos alive)."""
        return load_policy({
            "apiVersion": "kyverno.io/v1", "kind": "ClusterPolicy",
            "metadata": {"name": policy.name},
            "spec": {"validationFailureAction": "enforce", "rules": [{
                "name": "storm-rule",
                "match": {"resources": {"kinds": ["Pod"]}},
                "validate": {"message": f"storm update {k}",
                             "pattern": {"spec": {"storm":
                                                  {f"gen{k}": "?*"}}}},
            }]},
        })

    lib = _library_250()
    for p in lib:
        p.spec.validation_failure_action = "enforce"
    targets = [lib[(i * 37) % len(lib)] for i in range(N_UPDATES)]

    pods = [make_pod(i) for i in range(48)]
    N_THREADS = 6
    per = len(pods) // N_THREADS

    def storm_lane():
        """Run the identical storm — warm fill, then per-update screens —
        against a fresh PolicyCache/AdmissionBatcher under whatever
        KTPU_INCREMENTAL mode is in effect. Returns the latencies and
        every counter that produced them."""
        cache = PolicyCache()
        for p in lib:
            cache.add(p)
        batcher = AdmissionBatcher(cache, window_s=0.002,
                                   burst_threshold=1,
                                   dispatch_cost_init_s=0.0,
                                   oracle_cost_init_s=1.0,
                                   cold_flush_fallback=False,
                                   result_cache_ttl_s=0.0)

        def screen_round(out: list):
            def worker(w):
                for pod in pods[w * per:(w + 1) * per]:
                    t0 = time.perf_counter()
                    batcher.screen(PolicyType.VALIDATE_ENFORCE, "Pod",
                                   "default", pod, timeout_s=60.0)
                    out.append((time.perf_counter() - t0) * 1e3)
            threads = [threading.Thread(target=worker, args=(w,))
                       for w in range(N_THREADS)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()

        try:
            batcher.warmup(PolicyType.VALIDATE_ENFORCE, "Pod", "default",
                           make_pod(1))
            screen_round([])       # warm fill: memo + XLA, off the clock
            memo_before = dict(batcher._row_cache.stats())
            compile_before = dict(cache.compile_totals)
            lats: list = []
            rewarm_s: list = []
            t_storm = time.monotonic()
            for k, target in enumerate(targets):
                prev = batcher.stats.get("rewarm", 0)
                t_up = time.monotonic()
                cache.add(updated(target, k))
                # the policy-change listener re-warms the new tensor
                # set's flush shapes off the admission path; readmission
                # is measured AFTER it lands — the deployment sequence
                # (watch event -> rewarm -> traffic). The rewarm seconds
                # are reported beside the latencies: that is the cold
                # compile the listener absorbed.
                deadline = time.monotonic() + 120
                while time.monotonic() < deadline:
                    if (batcher.stats.get("rewarm", 0) > prev
                            and not batcher._rewarm_pending):
                        break
                    time.sleep(0.005)
                rewarm_s.append(time.monotonic() - t_up)
                screen_round(lats)
            storm_s = time.monotonic() - t_storm
            memo_after = dict(batcher._row_cache.stats())
            routing = dict(batcher.stats)
        finally:
            batcher.stop()
        return {
            "lats": lats, "storm_s": storm_s, "routing": routing,
            "rewarm_s": rewarm_s,
            "memo_before": memo_before, "memo_after": memo_after,
            "compile_totals": _counter_delta(compile_before,
                                             dict(cache.compile_totals)),
            "cache": cache,
        }

    # ---- incremental lane: memoized splice path (the default)
    inc_lane = storm_lane()
    inc_totals = inc_lane["compile_totals"]
    memo_before, memo_after = inc_lane["memo_before"], inc_lane["memo_after"]
    d_hits = memo_after["hits"] - memo_before["hits"]
    d_miss = memo_after["misses"] - memo_before["misses"]
    survival = d_hits / max(d_hits + d_miss, 1)
    lats, storm_s, routing = (inc_lane["lats"], inc_lane["storm_s"],
                              inc_lane["routing"])
    p50, p99 = _percentiles(lats)
    cache = inc_lane["cache"]

    # ---- full-recompile lane: the SAME storm, kill switch thrown —
    # every update moves the fingerprint, so memos evict and each round's
    # first flush pays a cold flatten + compile
    os.environ["KTPU_INCREMENTAL"] = "0"
    try:
        full_lane = storm_lane()
        full_totals = full_lane["compile_totals"]
        full_p50, full_p99 = _percentiles(full_lane["lats"])
        fm_hits = (full_lane["memo_after"]["hits"]
                   - full_lane["memo_before"]["hits"])
        fm_miss = (full_lane["memo_after"]["misses"]
                   - full_lane["memo_before"]["misses"])
        full_cps = full_lane["cache"].compiled(PolicyType.VALIDATE_ENFORCE,
                                               "Pod", "default")
    finally:
        del os.environ["KTPU_INCREMENTAL"]

    # post-storm parity spot check: the served splice vs the monolithic
    # compile of the same final library (both already compiled above)
    inc_cps = cache.compiled(PolicyType.VALIDATE_ENFORCE, "Pod", "default")
    sample = pods[:32]
    parity = bool(np.array_equal(
        inc_cps.evaluate_device(inc_cps.flatten_packed(sample)),
        full_cps.evaluate_device(full_cps.flatten_packed(sample))))

    inc_per_update = inc_totals.get("incremental_s", 0.0) / max(
        inc_totals.get("incremental_n", 1), 1)
    full_per_update = full_totals.get("full_s", 0.0) / max(
        full_totals.get("full_n", 1), 1)

    # ---- delta background scan vs full rescan on the same snapshot
    scan_pols = [p for p in lib if p.spec.background]
    snapshot = [make_pod(i) for i in range(2048)]
    sc = BackgroundScanner(scan_pols)
    t0 = time.monotonic()
    sc.scan(snapshot)
    full_scan_s = time.monotonic() - t0
    upd_pols = [updated(scan_pols[0], 99) if p is scan_pols[0] else p
                for p in scan_pols]
    t0 = time.monotonic()
    delta_res = sc.delta_scan(upd_pols)
    delta_scan_s = time.monotonic() - t0
    t0 = time.monotonic()
    BackgroundScanner(upd_pols).scan(snapshot)
    rescan_s = time.monotonic() - t0

    return {
        "library": LIBRARY_SOURCE.get("library_250", "reference"),
        "policies": len(lib),
        "updates": N_UPDATES,
        "readmission": {
            "lane": "48 distinct pods re-screened after every update, "
                    "caches ttl=0 (splice path, not result cache)",
            "n": len(lats), "concurrency": N_THREADS,
            "latency_ms_p50": p50, "latency_ms_p99": p99,
            "storm_s": round(storm_s, 2),
            "rewarm_s_per_update": round(
                sum(inc_lane["rewarm_s"]) / max(len(inc_lane["rewarm_s"]),
                                                1), 3),
            "routing": {k: v for k, v in routing.items()
                        if isinstance(v, (int, float))},
            "full_recompile_lane": {
                "lane": "same storm, KTPU_INCREMENTAL=0: fingerprint "
                        "moves every update, memos evict",
                "latency_ms_p50": full_p50, "latency_ms_p99": full_p99,
                "storm_s": round(full_lane["storm_s"], 2),
                "rewarm_s_per_update": round(
                    sum(full_lane["rewarm_s"])
                    / max(len(full_lane["rewarm_s"]), 1), 3),
                "memo_hits": fm_hits, "memo_misses": fm_miss,
                "routing": {k: v for k, v in full_lane["routing"].items()
                            if isinstance(v, (int, float))},
            },
            "p99_speedup_vs_full": round(full_p99 / max(p99, 1e-9), 1),
        },
        "compile": {
            "incremental_s_per_update": round(inc_per_update, 4),
            "full_s_per_update": round(full_per_update, 4),
            "speedup": round(full_per_update / max(inc_per_update, 1e-9), 1),
            "incremental_counters": inc_totals,
            "full_counters": full_totals,
            "post_storm_verdict_parity": parity,
        },
        "memo_survival": {
            "ratio": round(survival, 4),
            "target": "> 0.90 across append-only updates",
            "met": survival > 0.90,
            "hits": d_hits, "misses": d_miss,
            "extended_rows": memo_after["extended"] - memo_before["extended"],
            "row_cache": memo_after,
        },
        "background_scan": {
            "snapshot": len(snapshot),
            "policies_scanned": len(scan_pols),
            "full_scan_s": round(full_scan_s, 2),
            "delta_scan_s": round(delta_scan_s, 2),
            "full_rescan_s": round(rescan_s, 2),
            "speedup_vs_rescan": round(rescan_s / max(delta_scan_s, 1e-9), 1),
            "cols_evaluated": delta_res.cols_evaluated,
            "rows_evaluated": delta_res.rows_evaluated,
            "delta_counters": dict(sc.delta_stats),
        },
    }


def bench_config7(jax):
    """Host-heavy mix (round 8): a library where >= 30% of the rules are
    host-only ({{request.object.*}} inside the pattern), so the
    CPU-oracle tail — not the device lattice — dominates the dataflow.
    A/B of the same flatten -> async dispatch -> resolve chain:

      - serial lane: every KTPU_HOST_* kill switch thrown, i.e. the old
        dataflow — device verdicts materialize first, then the serial
        per-resource oracle walk resolves the HOST cells on the caller's
        thread
      - overlapped lane: dispatch-time predictive prefetch + host-verdict
        memo + fan-out (runtime/hostlane), cold pass then warm pass

    Two traffic shapes: a repeated-body pool (24 distinct bodies drawn
    1536 times — the admission-coalescing case the memo exists for) and
    a distinct-body pool (memo-adversarial: every body unique, only
    prefetch overlap and fan-out can help). Verdict AND message parity
    between the lanes is asserted, not reported — a fast wrong answer
    fails the config. Acceptance: overlapped+memoized >= 2x the serial
    tail on repeated-body traffic."""
    from kyverno_tpu.api.load import load_policy
    from kyverno_tpu.models import CompiledPolicySet
    from kyverno_tpu.runtime import hostlane

    # 10 host-only + 20 device rules = 33% host-only
    N_HOST, N_DEVICE = 10, 20
    docs = []
    for k in range(N_HOST):
        docs.append({
            "apiVersion": "kyverno.io/v1", "kind": "ClusterPolicy",
            "metadata": {"name": f"host-echo-name-{k}"},
            "spec": {"rules": [{
                "name": "echo-name",
                "match": {"resources": {"kinds": ["Pod"]}},
                "validate": {
                    "message": f"name mismatch ({k})",
                    "pattern": {"metadata": {"name":
                                "{{request.object.metadata.name}}"}}},
            }]},
        })
    for k in range(N_DEVICE):
        if k % 2:
            docs.append({
                "apiVersion": "kyverno.io/v1", "kind": "ClusterPolicy",
                "metadata": {"name": f"disallow-latest-{k}"},
                "spec": {"rules": [{
                    "name": "validate-image-tag",
                    "match": {"resources": {"kinds": ["Pod"]}},
                    "validate": {"message": f"latest tag banned ({k})",
                                 "pattern": {"spec": {"containers": [
                                     {"image": "!*:latest"}]}}},
                }]},
            })
        else:
            docs.append({
                "apiVersion": "kyverno.io/v1", "kind": "ClusterPolicy",
                "metadata": {"name": f"require-name-{k}"},
                "spec": {"rules": [{
                    "name": "check-name",
                    "match": {"resources": {"kinds": ["Pod"]}},
                    "validate": {"message": f"name required ({k})",
                                 "pattern": {"metadata": {"name": "?*"}}},
                }]},
            })
    cps = CompiledPolicySet([load_policy(d) for d in docs])
    n_live = int(cps.tensors.n_rules_live)
    host_rules = int(np.asarray(
        cps.tensors.rule_host_only[:n_live]).sum())

    bodies = [make_pod(i) for i in range(24)]
    repeated = [bodies[i % len(bodies)] for i in range(1536)]
    distinct = [make_pod(10_000 + i) for i in range(768)]

    SWITCHES = ("KTPU_HOST_PREFETCH", "KTPU_HOST_MEMO",
                "KTPU_HOST_FANOUT")

    def set_switches(val):
        saved = {s: os.environ.get(s) for s in SWITCHES}
        for s in SWITCHES:
            os.environ[s] = val
        return saved

    def restore(saved):
        for s, v in saved.items():
            if v is None:
                os.environ.pop(s, None)
            else:
                os.environ[s] = v

    def lane(resources):
        """One timed pass of the shared dataflow: flatten, async device
        dispatch, dispatch-time prefetch (None with the switch thrown),
        then resolve_host_cells joining prefetch + post-pass. The kill
        switches alone pick serial vs overlapped."""
        r = hostlane.resolver()
        before = dict(r.stats)
        memo_before = dict(hostlane.host_cache().stats())
        msgs: dict = {}
        t0 = time.monotonic()
        batch = cps.flatten_packed(resources)
        handle = cps.evaluate_device_async(batch)
        pf = r.prefetch(cps, resources)
        v = cps.resolve_host_cells(resources, handle.get(),
                                   messages_out=msgs, prefetch=pf)
        dt = time.monotonic() - t0
        counters = _counter_delta(before, dict(r.stats))
        memo_d = _counter_delta(memo_before,
                                dict(hostlane.host_cache().stats()))
        counters["host_prefetch_cells"] = counters.pop(
            "prefetch_submitted", 0)
        counters["host_memo_hit"] = memo_d.get("hits", 0)
        counters["host_memo_miss"] = memo_d.get("misses", 0)
        counters["host_resolve_overlap_s"] = round(
            pf.overlap_s(), 4) if pf is not None else 0.0
        return dt, np.asarray(v), msgs, counters

    cps.flatten_packed(repeated[:8])   # warm the native flattener

    saved = set_switches("0")
    try:
        lane(repeated[:48])            # XLA + oracle warm, off the clock
        serial_rep_s, v_ser_rep, m_ser_rep, c_serial = lane(repeated)
        serial_dist_s, v_ser_dist, m_ser_dist, _ = lane(distinct)
    finally:
        restore(saved)

    saved = set_switches("1")
    try:
        hostlane.host_cache().clear()
        cold_s, v_cold, m_cold, c_cold = lane(repeated)
        warm_s, v_warm, m_warm, c_warm = lane(repeated)
        dist_s, v_dist, m_dist, c_dist = lane(distinct)
    finally:
        restore(saved)

    # parity is load-bearing: the overlapped lanes must reproduce the
    # serial tail's verdicts AND oracle messages bit for bit
    if not (np.array_equal(v_ser_rep, v_cold)
            and np.array_equal(v_ser_rep, v_warm)
            and np.array_equal(v_ser_dist, v_dist)):
        raise AssertionError("host-lane verdict parity violated")
    if not (m_ser_rep == m_cold == m_warm and m_ser_dist == m_dist):
        raise AssertionError("host-lane message parity violated")

    speedup_cold = serial_rep_s / max(cold_s, 1e-9)
    speedup_warm = serial_rep_s / max(warm_s, 1e-9)
    return {
        "policies": N_HOST + N_DEVICE,
        "rules": n_live,
        "host_rules": host_rules,
        "host_rule_pct": round(100 * host_rules / n_live, 1),
        "verdict_parity": True,
        "message_parity": True,
        "serial_lane_counters": c_serial,
        "repeated_pool": {
            "resources": len(repeated),
            "distinct_bodies": len(bodies),
            "serial_tail_s": round(serial_rep_s, 3),
            "overlapped_cold_s": round(cold_s, 3),
            "overlapped_warm_s": round(warm_s, 3),
            "speedup_cold": round(speedup_cold, 1),
            "speedup_warm": round(speedup_warm, 1),
            "target": ">= 2.0x overlapped+memoized vs serial tail",
            "met": speedup_warm >= 2.0,
            "counters_cold": c_cold,
            "counters_warm": c_warm,
        },
        "distinct_pool": {
            "resources": len(distinct),
            "serial_tail_s": round(serial_dist_s, 3),
            "overlapped_s": round(dist_s, 3),
            "speedup": round(serial_dist_s / max(dist_s, 1e-9), 1),
            "counters": c_dist,
        },
    }


def bench_config9(jax):
    """Streaming plane (round 10): open-loop Poisson load. Closed-loop
    benches understate queueing — a slow server slows its own clients —
    so here arrivals are released by a Poisson clock regardless of
    completions and latency is measured FROM THE SCHEDULED ARRIVAL,
    making queue wait visible. Two lanes over the same device dataflow:

      - webhook lane: distinct JSON AdmissionReview bodies over real
        HTTP keep-alive connections, result cache off (no-cache) — the
        per-request JSON parse + flatten + re-intern tax
      - stream lane: pre-tokenized columnar rows over the streaming
        frame protocol into the continuous batcher — rows splice
        device-ready, zero re-parse/re-intern

    A rate step is *sustained* when achieved/offered >= the ratio floor
    with p99 well inside the 10s webhook deadline and no transport
    errors; saturation is the highest sustained offered rate (the sweep
    stops at the first unsustained step — open loop past saturation only
    grows backlog). Verdict parity between the lanes is asserted on a
    sample, not reported. Acceptance: stream saturation >= 2x the
    webhook no-cache saturation."""
    import http.client
    import queue as queue_mod
    import random
    import socket

    from kyverno_tpu.api.load import load_policy
    from kyverno_tpu.runtime.batch import AdmissionBatcher
    from kyverno_tpu.runtime.client import FakeCluster
    from kyverno_tpu.runtime.policycache import PolicyCache, PolicyType
    from kyverno_tpu.runtime.stream_server import (StreamClient,
                                                   StreamServer,
                                                   flatten_block_for_wire,
                                                   flatten_rows_for_wire)
    from kyverno_tpu.runtime.webhook import (
        VALIDATING_WEBHOOK_PATH,
        WebhookServer,
    )

    # device-only library: every rule decidable on the lattice, so the
    # webhook path and the columnar row path (which never takes the
    # host-lane detour) must agree exactly
    docs = []
    for k in range(4):
        docs.append({
            "apiVersion": "kyverno.io/v1", "kind": "ClusterPolicy",
            "metadata": {"name": f"disallow-latest-{k}"},
            "spec": {"validationFailureAction": "enforce", "rules": [{
                "name": "validate-image-tag",
                "match": {"resources": {"kinds": ["Pod"]}},
                "validate": {"message": f"latest tag banned ({k})",
                             "pattern": {"spec": {"containers": [
                                 {"image": "!*:latest"}]}}},
            }]},
        })
        docs.append({
            "apiVersion": "kyverno.io/v1", "kind": "ClusterPolicy",
            "metadata": {"name": f"require-name-{k}"},
            "spec": {"validationFailureAction": "enforce", "rules": [{
                "name": "check-name",
                "match": {"resources": {"kinds": ["Pod"]}},
                "validate": {"message": f"name required ({k})",
                             "pattern": {"metadata": {"name": "?*"}}},
            }]},
        })
    pols = [load_policy(d) for d in docs]

    def stack():
        cache = PolicyCache()
        for p in pols:
            cache.add(p)
        batcher = AdmissionBatcher(cache, window_s=0.004,
                                   burst_threshold=1,
                                   dispatch_cost_init_s=0.0,
                                   oracle_cost_init_s=1.0,
                                   cold_flush_fallback=False,
                                   result_cache_ttl_s=0.0,
                                   continuous=True)
        server = WebhookServer(policy_cache=cache, client=FakeCluster(),
                               admission_batcher=batcher)
        return cache, batcher, server

    headers = {"Content-Type": "application/json"}
    N_WORKERS = 24
    SUSTAIN_RATIO = 0.85
    P99_CEIL_MS = 2_500.0          # "well inside" the 10s deadline
    RATES = (25, 50, 100, 200, 400, 800, 1600, 3200)

    def open_loop(rate, payloads, submit_factory, seed):
        """One offered-rate step. A dispatcher thread releases work on
        the Poisson clock into an unbounded queue (sampling its depth at
        every release); workers drain it, so server backlog shows up as
        latency-from-scheduled-arrival and as queue depth, never as a
        slower arrival process."""
        rng = random.Random(seed)
        sched, t = [], 0.0
        for _ in payloads:
            t += rng.expovariate(rate)
            sched.append(t)
        q: queue_mod.Queue = queue_mod.Queue()
        lock = threading.Lock()
        lats: list = []
        errors: list = []
        depths: list = []

        def worker():
            submit, done = submit_factory()
            try:
                while True:
                    item = q.get()
                    if item is None:
                        return
                    arrival, payload = item
                    try:
                        submit(payload)
                        lat = (time.perf_counter() - arrival) * 1e3
                        with lock:
                            lats.append(lat)
                    except Exception as exc:
                        with lock:
                            errors.append(repr(exc))
            finally:
                done()

        workers = [threading.Thread(target=worker)
                   for _ in range(N_WORKERS)]
        for w in workers:
            w.start()
        t0 = time.perf_counter()
        for s, payload in zip(sched, payloads):
            delay = t0 + s - time.perf_counter()
            if delay > 0:
                time.sleep(delay)
            q.put((t0 + s, payload))
            depths.append(q.qsize())
        for _ in workers:
            q.put(None)
        for w in workers:
            w.join()
        span = max(time.perf_counter() - t0, 1e-9)
        achieved = len(lats) / span
        p50, p99 = _percentiles(lats or [0.0])
        return {
            "offered_per_s": rate,
            "n": len(payloads),
            "achieved_per_s": round(achieved, 1),
            "achieved_ratio": round(achieved / rate, 3),
            "latency_ms_p50": p50,
            "latency_ms_p99": p99,
            "queue_depth_max": max(depths, default=0),
            "queue_depth_mean": round(
                sum(depths) / max(len(depths), 1), 1),
            "errors": len(errors),
        }

    def sustained(step):
        return (step["achieved_ratio"] >= SUSTAIN_RATIO
                and step["latency_ms_p99"] <= P99_CEIL_MS
                and step["errors"] == 0)

    def sweep(submit_factory, payloads_for):
        steps, sat = [], 0.0
        for ri, rate in enumerate(RATES):
            n = min(512, max(96, rate))
            step = open_loop(rate, payloads_for(ri, n),
                             submit_factory, seed=77 + ri)
            # one retry per rate: an inline XLA compile of a
            # first-seen flush bucket mid-step snowballs the open-loop
            # backlog — that is startup weather (the shape is warm for
            # the retry), not steady-state capacity
            if not sustained(step):
                step = open_loop(rate, payloads_for(ri + 100, n),
                                 submit_factory, seed=177 + ri)
                step["retried"] = True
            step["sustained"] = sustained(step)
            steps.append(step)
            if step["sustained"]:
                sat = float(rate)
            else:
                break
        return sat, steps

    # per-policy attribution across the sweep: reset top-K membership so
    # this config's 8 policies claim labelled slots even after earlier
    # configs (the 250-policy library alone saturates the default 64)
    from kyverno_tpu.runtime import metrics as metrics_mod
    metrics_mod.attrib_state().reset()
    reg = metrics_mod.registry()

    # ---------------- webhook lane (no-cache: distinct bodies) --------
    _, batcher_w, server_w = stack()
    httpd = server_w.run(host="127.0.0.1", port=0)
    port = httpd.server_address[1]

    def conn_factory():
        c = http.client.HTTPConnection("127.0.0.1", port, timeout=60)
        c.connect()
        c.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)

        def submit(body):
            c.request("POST", VALIDATING_WEBHOOK_PATH, body, headers)
            c.getresponse().read()

        return submit, c.close

    try:
        # warm off the clock: a gentle round JITs the single-flush
        # shapes, then overload rounds drive the backlog that grows
        # flushes to the max-batch buckets the high-rate steps hit
        for wi, (wr, wn) in enumerate(((100, 96), (800, 256),
                                       (800, 256))):
            open_loop(wr, [_admission_body(wi * 10_000 + i,
                                           salt=f"wwarm{wi}-")
                           for i in range(wn)], conn_factory, seed=wi)
        before_w = dict(batcher_w.stats)
        sat_webhook, webhook_steps = sweep(
            conn_factory,
            lambda ri, n: [_admission_body(ri * 100_000 + i, salt="wol-")
                           for i in range(n)])
        webhook_counters = _counter_delta(before_w, dict(batcher_w.stats))
    finally:
        server_w.stop()
        batcher_w.stop()

    # ---------------- stream lane (pre-tokenized columnar rows) -------
    cache_s, batcher_s, server_s = stack()
    ss = StreamServer(server_s, batcher_s, cache_s).start()
    client = StreamClient(ss.port, transport=ss.transport_name)
    cps = cache_s.compiled(PolicyType.VALIDATE_ENFORCE, "Pod", "default")

    def rows_for(base, n):
        # tokenized OFF the clock: the columnar contract is that the
        # client ships device-ready rows and the server only splices
        return flatten_rows_for_wire(
            cps, [make_pod(base + i) for i in range(n)])

    def stream_factory():
        def submit(row):
            out = client.admit_row("Pod", "default", row, timeout=60.0)
            if "status" not in out:
                raise RuntimeError(f"bad stream response: {out}")

        return submit, lambda: None

    try:
        for wi, (wr, wn) in enumerate(((100, 96), (800, 256),
                                       (800, 256))):
            open_loop(wr, rows_for(900_000 + wi * 1000, wn),
                      stream_factory, seed=wi)
        before_s = dict(batcher_s.stats)
        sat_stream, stream_steps = sweep(
            stream_factory,
            lambda ri, n: rows_for((ri + 1) * 100_000, n))
        stream_counters = _counter_delta(before_s, dict(batcher_s.stats))

        # block granularity: the zero-copy transfer format — the server
        # pads and dispatches the client's own tokenization, so the
        # wire/re-intern counters must NOT move (steady-state zero-copy
        # proof); blocks are tokenized off the clock like the rows
        blocks = [flatten_block_for_wire(
            cps, [make_pod(700_000 + bi * 64 + i) for i in range(64)])
            for bi in range(12)]
        blk_before = dict(batcher_s.stats)
        blk_rows = 0
        t0 = time.perf_counter()
        for blk in blocks:
            blk_rows += len(client.admit_block("Pod", "default",
                                               blk)["rows"])
        blk_s = max(time.perf_counter() - t0, 1e-9)
        blk_delta = _counter_delta(blk_before, dict(batcher_s.stats))
        block_mode = {
            "blocks": len(blocks), "rows": blk_rows,
            "rows_per_s": round(blk_rows / blk_s),
            "reintern_rows": blk_delta.get("stream_reintern_rows", 0),
            "row_rebuilds": blk_delta.get("stream_wire_rows", 0),
            "zero_copy_ok": (blk_delta.get("stream_reintern_rows", 0)
                             == blk_delta.get("stream_wire_rows", 0)
                             == 0),
            "counters": blk_delta,
        }

        # verdict parity: the same pods through the in-process webhook
        # path and as columnar rows must land the same allow/deny
        reviews = [json.loads(_admission_body(i, salt="par-"))
                   for i in range(48)]
        wh = [server_s.handle(VALIDATING_WEBHOOK_PATH,
                              r)["response"]["allowed"] for r in reviews]
        st = [client.admit_row("Pod", "default", row)["allowed"]
              for row in flatten_rows_for_wire(
                  cps, [r["request"]["object"] for r in reviews])]
        if wh != st:
            bad = [i for i, (a, b) in enumerate(zip(wh, st)) if a != b]
            raise AssertionError(
                f"stream/webhook verdict parity violated at {bad[:8]}")
    finally:
        client.close()
        ss.stop()
        batcher_s.stop()

    # per-policy p99 alongside the sweep, read off the attribution
    # histograms the flush path fed during the offered-rate steps
    # (every policy participating in a flush observes its wall time)
    per_policy_p99_ms = {}
    for p in pols:
        q = reg.histogram_quantile("kyverno_policy_latency_seconds",
                                   0.99, {"policy": p.name})
        if q is not None:
            per_policy_p99_ms[p.name] = round(q * 1e3, 3)

    return {
        "policies": len(pols),
        "workers": N_WORKERS,
        "transport": ss.transport_name,
        "sustain_ratio": SUSTAIN_RATIO,
        "p99_ceiling_ms": P99_CEIL_MS,
        "verdict_parity": {"n": 48, "ok": True,
                           "denied": sum(1 for a in wh if not a)},
        "webhook_lane": {"saturation_per_s": sat_webhook,
                         "steps": webhook_steps,
                         "counters": webhook_counters},
        "stream_lane": {"saturation_per_s": sat_stream,
                        "steps": stream_steps,
                        "counters": stream_counters},
        "block_mode": block_mode,
        "per_policy_p99_ms": per_policy_p99_ms,
        "stream_vs_webhook": round(
            sat_stream / max(sat_webhook, 1e-9), 2),
        "target": ">= 2x webhook no-cache saturation, p99 well inside "
                  "the 10s deadline",
        "met": sat_stream >= 2 * sat_webhook > 0,
    }


def bench_config10(jax):
    """Workload plane (round 11): trace replay + rollout dry-run. One
    synthesized churn trace — Poisson arrivals with create storms, Zipf
    namespace skew, a bounded name pool so whole bodies repeat — plays
    through every admission leg of one serving stack at max speed, and
    cross-leg verdict parity is asserted on the digest (not sampled:
    every event, every leg). A larger trace then drives the background
    leg through the real watch machinery (Reflector -> WatchHub ->
    note_resource -> delta scans at policy-churn boundaries) to build a
    10k-plus-row verdict matrix, and a candidate policy dry-runs against
    that corpus with quiescence asserted fingerprint-for-fingerprint.
    Acceptance: all four admission legs verdict-identical, the dry-run
    touches >= 10k resources without moving the scan state."""
    from kyverno_tpu.api.load import load_policy
    from kyverno_tpu.workload.dryrun import dry_run
    from kyverno_tpu.workload.replay import (ReplayDriver, build_stack,
                                             run_manifest)
    from kyverno_tpu.workload.trace import synthesize

    docs = [
        {"apiVersion": "kyverno.io/v1", "kind": "ClusterPolicy",
         "metadata": {"name": "disallow-latest"},
         "spec": {"validationFailureAction": "enforce",
                  "background": True, "rules": [{
                      "name": "validate-image-tag",
                      "match": {"resources": {"kinds": ["Pod"]}},
                      "validate": {"message": "latest tag banned",
                                   "pattern": {"spec": {"containers": [
                                       {"image": "!*:latest"}]}}}}]}},
        {"apiVersion": "kyverno.io/v1", "kind": "ClusterPolicy",
         "metadata": {"name": "require-team-label"},
         "spec": {"validationFailureAction": "enforce",
                  "background": True, "rules": [{
                      "name": "check-team",
                      "match": {"resources": {"kinds": ["Pod"]}},
                      "validate": {"message": "team label required",
                                   "pattern": {"metadata": {"labels": {
                                       "team": "?*"}}}}}]}},
    ]
    pols = [load_policy(d) for d in docs]

    # -------- admission legs: full-digest parity on one small trace ---
    tr = synthesize(events=120, namespaces=4, name_pool=24,
                    distinct_bodies=12, storm_factor=8.0,
                    storm_period=40, seed=42)
    stack = build_stack(pols)
    drv = ReplayDriver.from_stack(stack)
    legs = {}
    for leg in ("webhook", "stream_json", "stream_row", "stream_block"):
        legs[leg] = drv.run(tr, leg, workers=8)
    digests = {r["verdict_digest"] for r in legs.values()}
    if len(digests) != 1:
        raise AssertionError(
            "cross-leg verdict parity violated: "
            f"{ {leg: r['verdict_digest'] for leg, r in legs.items()} }")
    manifest = run_manifest(tr, list(legs.values()), note="bench10")
    stack["batcher"].stop()

    # -------- background leg: 10k-plus corpus through the watch path --
    churn = dict(docs[0], metadata={"name": "disallow-latest"})
    big = synthesize(events=13_000, namespaces=8, zipf_s=1.1,
                     distinct_bodies=48, update_fraction=0.12,
                     delete_fraction=0.02, storm_factor=6.0,
                     storm_period=1000, policy_docs=[churn],
                     policy_churn_every=4000, seed=7)
    bstack = build_stack(pols)
    bdrv = ReplayDriver.from_stack(bstack)
    bg = bdrv.run(big, "background")
    scanner = bstack["scanner"]
    corpus_rows = len(scanner._state["keys"])

    # -------- rollout dry-run against the replayed corpus -------------
    candidate = {
        "apiVersion": "kyverno.io/v1", "kind": "ClusterPolicy",
        "metadata": {"name": "block-app-3"},
        "spec": {"validationFailureAction": "enforce",
                 "background": True, "rules": [{
                     "name": "no-app-3",
                     "match": {"resources": {"kinds": ["Pod"]}},
                     "validate": {"message": "app-3 template frozen",
                                  "pattern": {"metadata": {"labels": {
                                      "app": "!app-3"}}}}}]},
    }
    fp_before = scanner.state_fingerprint()
    report = dry_run(candidate, scanner=scanner)
    quiescent = scanner.state_fingerprint() == fp_before
    bstack["batcher"].stop()

    slim = {leg: {k: r[k] for k in ("events", "duration_s",
                                    "achieved_per_s", "latency_ms_p50",
                                    "latency_ms_p99", "queue_depth_max",
                                    "denied")}
            for leg, r in legs.items()}
    met = (len(digests) == 1 and legs["webhook"]["denied"] > 0
           and corpus_rows >= 10_000 and quiescent
           and report["resources_evaluated"] == corpus_rows)
    return {
        "policies": len(pols),
        "trace": tr.stats(),
        "verdict_digest": next(iter(digests)),
        "admission_legs": slim,
        "manifest_trace_digest": manifest["trace"]["digest"],
        "background_leg": {k: bg[k] for k in (
            "events", "duration_s", "achieved_per_s", "delta_scans",
            "rows_evaluated", "cols_evaluated", "violations",
            "reflector_syncs")},
        "corpus_rows": corpus_rows,
        "dryrun": {k: report[k] for k in (
            "policy", "compile_lane", "resources_evaluated",
            "newly_failing", "newly_passing", "duration_s")},
        "dryrun_quiescent": quiescent,
        "target": "4-leg verdict parity on the full digest; dry-run over "
                  ">= 10k replayed rows with zero scan-state movement",
        "met": met,
    }


def bench_config11(jax):
    """Chaos/storm suite (round 12): the SLO loop closed under faults.
    Four scenarios — arrival storm, policy-churn storm, oracle-pool
    brownout, replica/scanner loss — each run as baseline -> fault
    episode -> recovery against a fresh serving stack with the
    degradation ladder armed (tight budgets so seconds-long faults trip
    the multi-window watchdog). Every scenario must show the controller
    degrading, acting, and recovering on its own: episode p99 inside
    the derived degraded budget, the degraded gauge back at 0 without a
    restart, actions logged with enter/exit timestamps in the run
    manifest, any verdict drift covered by a reported shed set, and the
    post-recovery digest bit-identical to the undisturbed baseline. A
    fifth leg re-runs the arrival storm with KTPU_SLO_ACTIONS=0 and
    asserts annotate-only behavior: no actions engage and even the
    episode digest matches. Acceptance: all four scenarios green plus
    the kill-switch parity leg."""
    from kyverno_tpu.workload.chaos import run_scenario, run_suite

    suite = run_suite(events=40, delay_s=0.4, workers=6)
    parity = run_scenario("arrival_storm", events=40, delay_s=0.4,
                          workers=6, actions="0")

    scen = {}
    for name, r in suite["scenarios"].items():
        scen[name] = {
            "ok": r["ok"],
            "checks": r["checks"],
            "p99_ms": {"baseline": r["baseline_p99_ms"],
                       "episode": r["episode_p99_ms"],
                       "recovery": r["recovery_p99_ms"]},
            "p99_budget_ms": r["p99_budget_ms"],
            "shed": r["shed"],
            "actions": sorted({e["action"] for e in r["action_log"]}),
        }
    met = suite["ok"] and parity["ok"]
    return {
        "scenarios": scen,
        "killswitch_parity": {"ok": parity["ok"],
                              "checks": parity["checks"]},
        "target": "4 chaos scenarios degrade/act/recover with digest "
                  "parity; KTPU_SLO_ACTIONS=0 restores annotate-only",
        "met": met,
    }


def _mesh_library(n_policies: int = 256, rules_per: int = 8) -> list:
    """>= 2k-rule synthetic library for the mesh A/B: every policy is a
    distinct segment (the partitioner's unit), each carrying
    ``rules_per`` device-lane pattern rules that actually discriminate
    on the trace generator's Pod bodies (images, labels, names), plus a
    thin host-lane slice so the 2D path exercises oracle resolution."""
    from kyverno_tpu.api.load import load_policy

    shapes = [
        lambda k: {"spec": {"containers": [{"image": "!*:latest"}]}},
        lambda k: {"metadata": {"labels": {"app": "?*"}}},
        lambda k: {"metadata": {"labels": {"team": "?*"}}},
        lambda k: {"metadata": {"name": "app-?*"}},
        lambda k: {"spec": {"containers": [{"name": "?*"}]}},
        lambda k: {"spec": {"containers": [{"image": "registry.local/*"}]}},
        lambda k: {"metadata": {"namespace": "team-*"}},
        lambda k: {"spec": {"containers": [
            {"image": f"!*:v{k % 7}"}]}},
    ]
    out = []
    for i in range(n_policies):
        if i % 64 == 63:
            rules = [{
                "name": "echo-name",
                "match": {"resources": {"kinds": ["Pod"]}},
                "validate": {"message": f"host echo {i}",
                             "pattern": {"metadata": {"name":
                                 "{{request.object.metadata.name}}"}}},
            }]
        else:
            rules = [{
                "name": f"r{j}",
                "match": {"resources": {"kinds": ["Pod"]}},
                "validate": {"message": f"p{i} r{j}",
                             "pattern": shapes[(i + j) % len(shapes)](i + j)},
            } for j in range(rules_per)]
        out.append(load_policy({
            "apiVersion": "kyverno.io/v1", "kind": "ClusterPolicy",
            "metadata": {"name": f"mesh-lib-{i}"},
            "spec": {"validationFailureAction": "enforce",
                     "rules": rules}}))
    return out


def bench_config12(jax):
    """2D mesh A/B (round 12): segment-aligned policy sharding. The
    macro corpus comes from the workload plane — the round-11 trace
    generator's live set after a churn trace replays (create/update/
    delete applied in order) — and a >= 2k-rule synthetic library scans
    it three ways: unsharded single-device, 1D data mesh (policy
    tensors replicated on every device), and the 2D ``(policy, data)``
    mesh (auto-factored geometry, per-shard tensors only on their own
    row). Acceptance: all three verdict digests identical, and each
    policy shard's device-resident tensor bytes within the
    ``1/policy_shards`` budget (x2 for the pow2 rule bucket) of the
    replicated 1D footprint. On hosts without enough devices for a
    policy axis the A/B still runs (degenerate (1, N) grid) but the
    footprint leg reports ``degraded``."""
    import hashlib

    from kyverno_tpu.models.compiler import tensor_nbytes
    from kyverno_tpu.models.engine import IncrementalCompiler
    from kyverno_tpu.parallel import make_mesh, sharded_scan
    from kyverno_tpu.parallel.mesh import parse_mesh_shape
    from kyverno_tpu.workload.trace import synthesize

    policies = _mesh_library()

    # macro corpus: the live set a churn trace leaves behind
    tr = synthesize(events=3000, namespaces=8, zipf_s=1.1,
                    distinct_bodies=64, update_fraction=0.2,
                    delete_fraction=0.05, storm_factor=6.0,
                    storm_period=500, seed=12)
    live = {}
    for ev in tr.events:
        key = (ev.namespace, ev.kind, ev.name)
        if ev.op == "DELETE":
            live.pop(key, None)
        elif ev.op in ("CREATE", "UPDATE"):
            live[key] = tr.bodies[ev.digest]
    corpus = list(live.values())

    inc = IncrementalCompiler()
    cps = inc.refresh(policies)
    live_rules = cps.tensors.n_rules_live

    def digest(v):
        return hashlib.sha256(
            np.ascontiguousarray(v).tobytes()).hexdigest()[:16]

    t0 = time.perf_counter()
    v0 = np.asarray(cps.evaluate(corpus))
    t_unsharded = time.perf_counter() - t0

    mesh1 = make_mesh()
    t0 = time.perf_counter()
    v1, _, _ = sharded_scan(cps, corpus, mesh1)
    t_1d = time.perf_counter() - t0

    n_dev = len(jax.devices())
    shape = parse_mesh_shape("auto", n_dev) or (1, n_dev)
    mesh2 = make_mesh(shape=shape)
    sps = inc.refresh_sharded(policies, shape[0])
    t0 = time.perf_counter()
    v2, _, _ = sharded_scan(sps, corpus, mesh2)
    t_2d = time.perf_counter() - t0

    digests = {digest(v0), digest(v1), digest(v2)}

    full_bytes = tensor_nbytes(cps.tensors)
    shard_bytes = sps.shard_tensor_bytes()
    max_shard = max(shard_bytes.values())
    # the pow2 rule bucket can at most double a shard's rule axis, and
    # the dictionary-scale tables (paths, NFA) replicate per shard
    budget = 1.0 if shape[0] == 1 else (2.0 / shape[0] + 0.35)
    footprint_ok = (shape[0] == 1) or (max_shard / full_bytes <= budget)

    met = (len(digests) == 1 and footprint_ok
           and corpus and live_rules >= 2000)
    return {
        "devices": n_dev,
        "mesh_shape": list(shape),
        "library": {"policies": len(policies), "rules": live_rules},
        "corpus_rows": len(corpus),
        "trace": tr.stats(),
        "verdict_digest": next(iter(digests)) if len(digests) == 1
        else sorted(digests),
        "scan_s": {"unsharded": round(t_unsharded, 3),
                   "mesh_1d": round(t_1d, 3),
                   "mesh_2d": round(t_2d, 3)},
        "rows_per_s_2d": round(len(corpus) / t_2d, 1),
        "tensor_bytes": {
            "full_replicated_per_device": full_bytes,
            "per_shard": {str(k): v for k, v in shard_bytes.items()},
            "max_shard_over_full": round(max_shard / full_bytes, 4),
            "budget": round(budget, 4),
            "degraded": shape[0] == 1,
        },
        "shard_rules": {str(k): v
                        for k, v in sps.shard_rule_counts().items()},
        "target": "unsharded/1D/2D verdict digests identical over a "
                  ">=2k-rule library; per-shard tensor bytes within the "
                  "1/policy_shards (+pow2/dictionary slack) budget",
        "met": bool(met),
    }


def bench_config13(jax):
    """Fleet fabric A/B (round 13): multi-replica serving + partitioned
    scanning. Admission leg: one repeat-heavy trace (no update/delete
    churn, bounded name pool, so decision keys repeat) plays through a
    1-replica and a 3-replica in-process fleet (build_fleet_stacks: one
    shared FabricHub, digest-affinity router) with KTPU_FABRIC=1 — the
    verdict digests must be identical, and a third run with no-affinity
    routing (repeats land on *different* replicas, only the shared
    fabric can serve them) must show a cross-replica hit rate > 0.

    Scan leg: replicas model separate nodes, so each member's owned
    ranges are scanned on an isolated scanner and timed serially; fleet
    wall-clock is max(T_member) — the slowest node gates the sweep —
    and aggregate throughput is total rows over that. This is the
    honest model for a fleet (no GIL-contended fake threads inflating
    or deflating the number). Acceptance: 1-vs-3 verdict digests
    identical on both legs, >= 2.5x aggregate scan throughput at 3
    members, cross-replica hit rate > 0."""
    from kyverno_tpu.fleet import scanparts
    from kyverno_tpu.runtime.background import BackgroundScanner
    from kyverno_tpu.workload.replay import (build_fleet_stacks,
                                             run_fleet,
                                             stop_fleet_stacks)
    from kyverno_tpu.workload.trace import synthesize

    from kyverno_tpu.api.load import load_policy

    docs = [
        {"apiVersion": "kyverno.io/v1", "kind": "ClusterPolicy",
         "metadata": {"name": "disallow-latest"},
         "spec": {"validationFailureAction": "enforce",
                  "background": True, "rules": [{
                      "name": "validate-image-tag",
                      "match": {"resources": {"kinds": ["Pod"]}},
                      "validate": {"message": "latest tag banned",
                                   "pattern": {"spec": {"containers": [
                                       {"image": "!*:latest"}]}}}}]}},
        {"apiVersion": "kyverno.io/v1", "kind": "ClusterPolicy",
         "metadata": {"name": "require-team-label"},
         "spec": {"validationFailureAction": "enforce",
                  "background": True, "rules": [{
                      "name": "check-team",
                      "match": {"resources": {"kinds": ["Pod"]}},
                      "validate": {"message": "team label required",
                                   "pattern": {"metadata": {"labels": {
                                       "team": "?*"}}}}}]}},
    ]
    pols = [load_policy(d) for d in docs]

    # -------- admission leg: 1-vs-3 replica parity + shared-cache hits
    tr = synthesize(events=400, namespaces=6, distinct_bodies=8,
                    update_fraction=0.0, delete_fraction=0.0,
                    name_pool=6, seed=13)
    saved_fabric = os.environ.pop("KTPU_FABRIC", None)
    os.environ["KTPU_FABRIC"] = "1"
    try:
        runs = {}
        for label, replicas, affinity in (("r1", 1, True),
                                          ("r3", 3, True),
                                          ("r3_spread", 3, False)):
            fleet = build_fleet_stacks(pols, replicas=replicas)
            try:
                runs[label] = run_fleet(tr, fleet, workers=8,
                                        affinity=affinity)
            finally:
                stop_fleet_stacks(fleet)
    finally:
        if saved_fabric is None:
            os.environ.pop("KTPU_FABRIC", None)
        else:
            os.environ["KTPU_FABRIC"] = saved_fabric
    admission_digests = {r["verdict_digest"] for r in runs.values()}
    hit_rate = runs["r3_spread"]["fabric_hit_rate"]

    # -------- scan leg: leader-partitioned sweep vs one replica -------
    scan_pols = _mesh_library(n_policies=48, rules_per=8)
    # 24 ranges over 3 members lands each member within ~4% of a third
    # of the rows (the scan clock is linear in the pow2-padded row
    # bucket, so the slowest member must stay under the next bucket)
    n_parts, members = 24, ["fleet-0", "fleet-1", "fleet-2"]
    corpus = []
    for i in range(5760):
        ns = f"team-{i % 288}"
        tag = "latest" if i % 4 == 3 else f"v{i % 7}"
        corpus.append({"apiVersion": "v1", "kind": "Pod",
                       "metadata": {"name": f"pod-{i}", "namespace": ns,
                                    "labels": {"app": f"app-{i % 9}",
                                               "team": ns}},
                       "spec": {"containers": [
                           {"name": "c", "image": f"nginx:{tag}"}]}})

    single = BackgroundScanner(scan_pols)
    single.scan(corpus)                      # compile warm-up
    t0 = time.perf_counter()
    single.scan(corpus)
    t_single = time.perf_counter() - t0
    base_digest = scanparts.merge_range_digests(
        scanparts.matrix_range_digests(single, n_parts))

    assignment = scanparts.assign_partitions(members, n_parts)
    member_times, member_rows, digests = {}, {}, []
    for member in members:
        owned = assignment[member]
        mine = scanparts.partition_resources(corpus, owned, n_parts)
        scanner = BackgroundScanner(scan_pols)
        scanner.scan(mine)                   # per-shape compile warm-up
        # clock the scan itself, symmetric with the single baseline;
        # range digesting is bookkeeping on both sides, not sweep time
        t0 = time.perf_counter()
        scanner.scan(mine)
        member_times[member] = time.perf_counter() - t0
        member_rows[member] = len(mine)
        digests.append(scanparts.matrix_range_digests(
            scanner, n_parts, owned=owned))
    fleet_digest = scanparts.merge_range_digests(*digests)
    t_fleet = max(member_times.values())     # slowest node gates
    speedup = t_single / t_fleet

    met = (len(admission_digests) == 1 and hit_rate > 0
           and fleet_digest == base_digest and speedup >= 2.5
           and runs["r1"]["denied"] > 0
           and not any(r["errors"] for r in runs.values()))
    return {
        "admission": {
            "trace": tr.stats(),
            "verdict_digest": next(iter(admission_digests))
            if len(admission_digests) == 1 else sorted(admission_digests),
            "legs": {label: {
                "replicas": r["replicas"],
                "achieved_per_s": r["achieved_per_s"],
                "latency_ms_p50": r["latency_ms_p50"],
                "latency_ms_p99": r["latency_ms_p99"],
                "denied": r["denied"],
                "fabric_hits": r["fabric_hits"],
                "fabric_hit_rate": r["fabric_hit_rate"],
                "router": {k: r["router"][k] for k in (
                    "routed", "failovers", "exhausted")},
            } for label, r in runs.items()},
            "cross_replica_hit_rate": hit_rate,
        },
        "scan": {
            "library_rules": 48 * 8,
            "corpus_rows": len(corpus),
            "partitions": n_parts,
            "members": len(members),
            "rows_per_member": member_rows,
            "scan_s": {"single": round(t_single, 3),
                       "fleet_max_member": round(t_fleet, 3),
                       "per_member": {m: round(t, 3)
                                      for m, t in member_times.items()}},
            "aggregate_rows_per_s": {
                "single": round(len(corpus) / t_single, 1),
                "fleet": round(len(corpus) / t_fleet, 1)},
            "speedup": round(speedup, 2),
            "digest_parity": fleet_digest == base_digest,
            "verdict_range_digest": base_digest,
        },
        "target": "1-vs-3 replica verdict digests identical; >= 2.5x "
                  "aggregate scan throughput at 3 members; "
                  "cross-replica cache hit rate > 0",
        "met": bool(met),
    }


def main() -> None:
    import jax

    # KTPU_BENCH_CONFIGS=1,3 runs a subset (dev convenience; the default
    # — unset — runs all five, and published numbers always come from a
    # full run)
    from kyverno_tpu.runtime import featureplane

    only = {s for s in featureplane.raw("KTPU_BENCH_CONFIGS").split(",")
            if s.strip()}
    configs = {}
    for name, f in (("1_single_pod_latency", bench_config1),
                    ("2_best_practices_4096", bench_config2),
                    ("3_library_250x10k", bench_config3),
                    ("4_mutate_50k", bench_config4),
                    ("5_scan_1M", bench_config5),
                    ("6_policy_update_storm", bench_config6),
                    ("7_host_heavy_mix", bench_config7),
                    ("9_streaming_open_loop", bench_config9),
                    ("10_trace_replay", bench_config10),
                    ("11_chaos_storm", bench_config11),
                    ("12_mesh_2d", bench_config12),
                    ("13_fleet_fabric", bench_config13)):
        if only and name.split("_")[0] not in only:
            continue
        try:
            configs[name] = f(jax)
        except Exception as e:  # a config failure must not hide the rest
            configs[name] = {"error": f"{type(e).__name__}: {e}"}

    c2 = configs.get("2_best_practices_4096", {})
    device_rate = c2.get("device_rate", 0)
    result = {
        "metric": "policy-rule x resource validations/sec (device, steady state)",
        "value": device_rate,
        "unit": "validations/sec",
        "vs_baseline": round(device_rate / 100_000, 3),
        "detail": {"configs": configs},
    }
    print(json.dumps(result))


if __name__ == "__main__":
    sys.exit(main())
