"""kubectl-kyverno style CLI: apply, test, validate.

Mirrors /root/reference/pkg/kyverno (cobra CLI; verbs at main.go:27-30).
Run as ``python -m kyverno_tpu.cli <verb> ...``.
"""
