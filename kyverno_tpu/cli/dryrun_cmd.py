"""``dryrun`` verb: blast radius of a candidate policy before rollout.

Two modes share the report schema (workload/dryrun.py):

- ``--url http://host:port`` POSTs the candidate to a running server's
  ``/debug/dryrun`` — the report reflects the server's *live* scan
  corpus.
- offline (default): the corpus comes from ``--trace`` (a workload
  JSONL trace replayed to its final resource set — CREATE/UPDATE upsert,
  DELETE removes) or ``--corpus`` (a JSON list of resource bodies), and
  evaluation runs in-process.

Exit code: 0 when the candidate newly fails nothing, 1 when it has a
blast radius (so a rollout pipeline can gate on it), 2 on usage/load
errors. Requires KTPU_DRYRUN=1 (the default) in the evaluating process.
"""

from __future__ import annotations

import json
import sys


def _load_candidate(path: str) -> dict:
    from ..api.load import load_policies_from_path

    policies = load_policies_from_path(path)
    if len(policies) != 1:
        raise ValueError(f"{path}: expected exactly one policy, "
                         f"found {len(policies)}")
    return policies[0].raw


def _corpus_from_trace(path: str) -> list[dict]:
    from ..workload.trace import WorkloadTrace

    tr = WorkloadTrace.read_jsonl(path)
    live: dict[tuple, dict] = {}
    for ev in tr.events:
        if ev.op == "POLICY":
            continue
        key = (ev.kind, ev.namespace, ev.name)
        if ev.op == "DELETE":
            live.pop(key, None)
        else:
            live[key] = tr.body_of(ev)
    return list(live.values())


def run(args) -> int:
    try:
        doc = _load_candidate(args.policy)
    except (OSError, ValueError) as e:
        print(f"dryrun: {e}", file=sys.stderr)
        return 2

    if args.url:
        import urllib.request

        req = urllib.request.Request(
            args.url.rstrip("/") + "/debug/dryrun",
            data=json.dumps({"policy": doc,
                             "sample_limit": args.samples}).encode(),
            headers={"Content-Type": "application/json"}, method="POST")
        try:
            with urllib.request.urlopen(req, timeout=args.timeout) as resp:
                report = json.loads(resp.read())
        except Exception as e:
            detail = ""
            body = getattr(e, "read", lambda: b"")()
            if body:
                detail = f": {body.decode('utf-8', 'replace')[:200]}"
            print(f"dryrun: {args.url}: {e}{detail}", file=sys.stderr)
            return 2
    else:
        from ..workload.dryrun import DryRunDisabled, dry_run

        try:
            if args.trace:
                resources = _corpus_from_trace(args.trace)
            elif args.corpus:
                with open(args.corpus) as f:
                    resources = json.load(f)
            else:
                print("dryrun: offline mode needs --trace or --corpus "
                      "(or point --url at a running server)",
                      file=sys.stderr)
                return 2
            report = dry_run(doc, resources=resources,
                             sample_limit=args.samples)
        except DryRunDisabled as e:
            print(f"dryrun: {e}", file=sys.stderr)
            return 2
        except (OSError, ValueError) as e:
            print(f"dryrun: {e}", file=sys.stderr)
            return 2

    if args.json:
        print(json.dumps(report, indent=2, sort_keys=True))
    else:
        dd = report.get("device_decidability") or {}
        print(f"dryrun: {report.get('policy')} over "
              f"{report.get('resources_evaluated')} resources: "
              f"{report.get('newly_failing')} newly failing, "
              f"{report.get('newly_passing')} newly passing "
              f"(device fraction "
              f"{dd.get('device_fraction', 1.0)})")
        for ns, counts in sorted(
                (report.get("per_namespace") or {}).items()):
            print(f"  {ns or '<cluster>'}: "
                  f"+{counts.get('newly_failing', 0)} failing, "
                  f"-{counts.get('newly_passing', 0)} passing")
        for s in report.get("samples") or []:
            print(f"  sample: {s['namespace']}/{s['name']} "
                  f"rule={s['rule']}: {s['message']}")
    return 1 if report.get("newly_failing") else 0


def register(subparsers) -> None:
    p = subparsers.add_parser(
        "dryrun", help="blast-radius report for a candidate policy "
        "(no live decisions touched)")
    p.add_argument("policy", help="candidate policy YAML (one policy)")
    p.add_argument("--url", default="",
                   help="running server base URL; POSTs /debug/dryrun "
                   "against its live scan corpus")
    p.add_argument("--trace", default="",
                   help="workload JSONL trace; its final live set is "
                   "the corpus (offline mode)")
    p.add_argument("--corpus", default="",
                   help="JSON file with a list of resource bodies "
                   "(offline mode)")
    p.add_argument("--samples", type=int, default=5,
                   help="sample violating resources in the report")
    p.add_argument("--timeout", type=float, default=60.0,
                   help="HTTP timeout for --url mode")
    p.add_argument("--json", action="store_true",
                   help="emit the full report as JSON")
    p.set_defaults(func=run)
