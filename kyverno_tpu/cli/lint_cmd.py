"""``lint`` verb: static analysis over policy YAML (kyverno_tpu/analysis).

Host-only by construction — compiles rule IR and tensors with numpy and
never imports jax, so it runs in CI images without the accelerator
stack. Exit code: 0 clean (relative to ``--fail-on``), 1 diagnostics at
or above the threshold, 2 usage/load errors.
"""

from __future__ import annotations

import json
import os
import sys

from ..analysis import Severity, analyze_policies, parse_suppressions
from ..api.load import load_policies_from_path

_FAIL_LEVELS = {
    "error": Severity.ERROR,
    "warning": Severity.WARNING,
    "info": Severity.INFO,
    "never": None,
}

# --self target: the analyzer lints the policies its own test battery
# ships, proving the CLI wiring end to end with no arguments
SELF_POLICY_DIR = "tests/policies"


def _self_dir() -> str:
    if os.path.isdir(SELF_POLICY_DIR):
        return SELF_POLICY_DIR
    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    return os.path.join(repo, SELF_POLICY_DIR)


def run(args) -> int:
    paths = list(args.paths)
    if args.self_check:
        paths.append(_self_dir())
    if not paths:
        print("requires at least one policy path (or --self)",
              file=sys.stderr)
        return 2

    policies = []
    for path in paths:
        try:
            policies.extend(load_policies_from_path(path))
        except OSError as e:
            print(f"lint: cannot load {path}: {e}", file=sys.stderr)
            return 2
    if not policies:
        print("lint: no policies found", file=sys.stderr)
        return 2

    suppress = parse_suppressions(args.suppress or "")
    report = analyze_policies(policies,
                              include_tensors=not args.no_tensors,
                              suppress=suppress)

    certify_counts = None
    if args.certify:
        from ..analysis.certify import certify_policies

        cert = certify_policies(policies)
        report.diagnostics += [d for d in cert.diagnostics
                               if d.code not in suppress]
        certify_counts = cert.counts()
        certify_counts["states_checked"] = cert.states_checked
        certify_counts["escalation_cells"] = cert.escalation_cells

    if args.json:
        out = report.to_dict()
        if certify_counts is not None:
            out["certification"] = certify_counts
        print(json.dumps(out, indent=2, sort_keys=True))
    else:
        for d in sorted(report.diagnostics,
                        key=lambda d: (-d.severity, d.policy, d.rule, d.code)):
            print(d.format())
        counts = {s: len(report.by_severity(s)) for s in Severity}
        print(f"lint: {len(policies)} policies, "
              f"{counts[Severity.ERROR]} errors, "
              f"{counts[Severity.WARNING]} warnings, "
              f"{counts[Severity.INFO]} info")
        if certify_counts is not None:
            summary = ", ".join(
                f"{k}={v}" for k, v in sorted(certify_counts.items()))
            print(f"certify: {summary}")

    threshold = _FAIL_LEVELS[args.fail_on]
    if threshold is None:
        return 0
    worst = report.max_severity()
    return 1 if worst is not None and worst >= threshold else 0


def register(subparsers) -> None:
    p = subparsers.add_parser(
        "lint", help="statically analyze policies (escalation provenance, "
        "reachability, tensor invariants)")
    p.add_argument("paths", nargs="*", help="policy YAML files/directories")
    p.add_argument("--json", action="store_true",
                   help="emit the full report as JSON")
    p.add_argument("--fail-on", choices=sorted(_FAIL_LEVELS), default="error",
                   help="minimum severity that makes the exit code "
                   "non-zero (default: error)")
    p.add_argument("--suppress", default="",
                   help="comma-separated diagnostic codes to drop "
                   "(e.g. KT202,KT110)")
    p.add_argument("--no-tensors", action="store_true",
                   help="skip the PolicyTensors invariant pass")
    p.add_argument("--certify", action="store_true",
                   help="run the KT4xx cross-layer certifier (device "
                   "tensor program vs host IR walk over an abstract "
                   "resource domain)")
    p.add_argument("--self", dest="self_check", action="store_true",
                   help="lint the repo's own sample policies "
                   f"({SELF_POLICY_DIR}) as a smoke check")
    p.set_defaults(func=run)
