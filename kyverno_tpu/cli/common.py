"""Shared CLI engine driver (mirrors /root/reference/pkg/kyverno/common/
common.go:447 ApplyPolicyOnResource): Mutate -> Validate -> Generate filter
against one (policy, resource), offline, exactly like the server path."""

from __future__ import annotations

from dataclasses import dataclass, field

from ..engine.context import Context
from ..engine.generation import generate
from ..engine.mutation import mutate
from ..engine.policy_context import PolicyContext
from ..engine.response import EngineResponse, RuleStatus
from ..engine.validation import validate
from ..engine.json_context_loader import variable_to_json


@dataclass
class ResultCounts:
    """common.go ResultCounts: pass/fail/warn/error/skip tallies."""

    pass_: int = 0
    fail: int = 0
    warn: int = 0
    error: int = 0
    skip: int = 0

    def count(self, status: RuleStatus) -> None:
        if status is RuleStatus.PASS:
            self.pass_ += 1
        elif status is RuleStatus.FAIL:
            self.fail += 1
        elif status is RuleStatus.WARN:
            self.warn += 1
        elif status is RuleStatus.ERROR:
            self.error += 1
        elif status is RuleStatus.SKIP:
            self.skip += 1


@dataclass
class ApplyResult:
    mutate_response: EngineResponse | None = None
    validate_response: EngineResponse | None = None
    generate_response: EngineResponse | None = None

    @property
    def responses(self) -> list[EngineResponse]:
        return [
            r
            for r in (self.mutate_response, self.validate_response, self.generate_response)
            if r is not None
        ]


def apply_policy_on_resource(
    policy,
    resource: dict,
    variables: dict[str, str] | None = None,
    namespace_labels_map: dict[str, dict[str, str]] | None = None,
    rc: ResultCounts | None = None,
) -> ApplyResult:
    """common.go:447 ApplyPolicyOnResource."""
    variables = variables or {}
    namespace_labels_map = namespace_labels_map or {}
    result = ApplyResult()

    namespace = (resource.get("metadata") or {}).get("namespace", "")
    namespace_labels = namespace_labels_map.get(namespace, {})

    ctx = Context()
    if variables.get("request.operation") == "DELETE":
        ctx.add_old_resource(resource)
    else:
        ctx.add_resource(resource)
    for key, value in variables.items():
        ctx.add_json(variable_to_json(key, value))
    try:
        ctx.add_image_info(resource)
    except Exception:
        pass

    has_mutate = any(r.has_mutate() for r in policy.spec.rules)
    has_validate = any(r.has_validate() for r in policy.spec.rules)
    has_generate = any(r.has_generate() for r in policy.spec.rules)

    patched = resource
    if has_mutate:
        mutate_ctx = PolicyContext(
            policy=policy, new_resource=resource, json_context=ctx,
            namespace_labels=namespace_labels,
        )
        result.mutate_response = mutate(mutate_ctx)
        patched = result.mutate_response.patched_resource or resource
        if rc is not None:
            for r in result.mutate_response.policy_response.rules:
                rc.count(r.status)

    if has_validate:
        validate_ctx = PolicyContext(
            policy=policy, new_resource=patched, json_context=ctx,
            namespace_labels=namespace_labels,
        )
        result.validate_response = validate(validate_ctx)
        if rc is not None:
            for r in result.validate_response.policy_response.rules:
                rc.count(r.status)

    if has_generate:
        generate_ctx = PolicyContext(
            policy=policy, new_resource=resource, json_context=ctx,
            namespace_labels=namespace_labels,
        )
        result.generate_response = generate(generate_ctx)
        if rc is not None:
            for r in result.generate_response.policy_response.rules:
                rc.count(r.status)

    return result
