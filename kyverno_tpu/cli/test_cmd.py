"""``test`` verb: declarative snapshot tests.

Mirrors /root/reference/pkg/kyverno/test/test_command.go: a ``test.yaml``
declares policies, resources, optional variables file, and expected
per-(policy, rule, resource) statuses; the engine replays them and diffs.
"""

from __future__ import annotations

import os
import sys

import yaml

from .. import store
from ..api.load import load_policies_from_path, load_resources
from ..engine.response import RuleStatus
from ..policy.autogen import mutate_policy_for_autogen
from .common import apply_policy_on_resource
from .values import Values, load_values_file

TEST_FILE_NAMES = ("test.yaml", "kyverno-test.yaml")


def run(args) -> int:
    failures = 0
    ran = 0
    for test_dir in args.paths or ["."]:
        if is_git_url(test_dir):
            test_dir = clone_git_source(test_dir, args.git_branch)
            if test_dir is None:
                # a corpus the caller named but we couldn't fetch is a
                # failure, not a silent skip — CI must go red
                failures += 1
                continue
        for test_file in _find_test_files(test_dir):
            ran += 1
            failures += run_test_file(test_file, verbose=not args.quiet)
    if ran == 0:
        print("no test yamls available", file=sys.stderr)
        return 2
    return 1 if failures else 0


def is_git_url(path: str) -> bool:
    """Git sources the way the reference CLI takes them
    (pkg/kyverno/test/git.go:14 — the public-policies regression replay
    clones https URLs; file:// and .git paths work offline)."""
    return (path.startswith(("https://", "http://", "git://", "ssh://",
                             "file://"))
            or path.endswith(".git"))


def clone_git_source(url: str, branch: str = "") -> str | None:
    """Shallow-clone a git test source into a temp dir; returns the
    checkout path (cleaned up at process exit), or None on failure."""
    import atexit
    import shutil
    import subprocess
    import tempfile

    dest = tempfile.mkdtemp(prefix="kyverno-test-git-")
    atexit.register(shutil.rmtree, dest, ignore_errors=True)
    cmd = ["git", "clone", "--depth", "1"]
    if branch:
        cmd += ["--branch", branch]
    cmd += [url, dest]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=300)
    except subprocess.CalledProcessError as e:
        stderr = e.stderr.decode(errors="replace").strip()
        print(f"failed to clone {url}: {stderr}", file=sys.stderr)
        return None
    except (OSError, subprocess.TimeoutExpired) as e:
        print(f"failed to clone {url}: {e}", file=sys.stderr)
        return None
    return dest


def _find_test_files(root: str) -> list[str]:
    if os.path.isfile(root):
        return [root]
    out = []
    for dirpath, _, filenames in os.walk(root):
        for name in filenames:
            if name in TEST_FILE_NAMES:
                out.append(os.path.join(dirpath, name))
    return sorted(out)


def run_test_file(path: str, verbose: bool = True) -> int:
    """Returns the number of mismatched results."""
    base = os.path.dirname(path)
    with open(path) as f:
        doc = yaml.safe_load(f) or {}

    policies = []
    for rel in doc.get("policies") or []:
        policies.extend(load_policies_from_path(os.path.join(base, rel)))
    resources = []
    for rel in doc.get("resources") or []:
        resources.extend(load_resources(os.path.join(base, rel)))

    values = Values()
    if doc.get("variables"):
        values = load_values_file(os.path.join(base, doc["variables"]))

    policies = [mutate_policy_for_autogen(p) for p in policies]

    # build actual results table (test_command.go:347 buildPolicyResults);
    # records carry namespace/kind so same-named resources are distinct
    records: list[dict] = []
    store.set_mock(True)
    values.install_mock_store()
    try:
        for resource in resources:
            res_meta = resource.get("metadata") or {}
            res_name = res_meta.get("name", "")
            for policy in policies:
                result = apply_policy_on_resource(
                    policy,
                    resource,
                    variables=values.for_resource(policy.name, res_name),
                    namespace_labels_map=values.namespace_selectors,
                )
                patched = (
                    result.mutate_response.patched_resource
                    if result.mutate_response is not None else None
                )
                for resp in result.responses:
                    for rr in resp.policy_response.rules:
                        records.append({
                            "policy": policy.name,
                            "policy_ns": policy.namespace,
                            "rule": rr.name,
                            "resource": res_name,
                            "namespace": res_meta.get("namespace", ""),
                            "kind": resource.get("kind", ""),
                            "type": rr.type,
                            "status": rr.status.value,
                            "patched": patched,
                        })
    finally:
        store.set_mock(False)
        store.set_context(store.Context())

    def lookup(policy: str, rule: str, resource: str, namespace: str, kind: str):
        for r in records:
            if r["rule"] != rule or r["resource"] != resource:
                continue
            if r["policy"] != policy and f"{r['policy_ns']}/{r['policy']}" != policy:
                continue
            if namespace and r["namespace"] and r["namespace"] != namespace:
                continue
            if kind and r["kind"] and r["kind"] != kind:
                continue
            return r
        return None

    mismatches = 0
    rows = []
    for want in doc.get("results") or []:
        want_status = want.get("status") or want.get("result") or ""
        base_key = (
            want.get("policy", ""), want.get("rule", ""), want.get("resource", ""),
            want.get("namespace", ""), want.get("kind", ""),
        )
        # a rule absent from the response means "didn't match" -> skip; an
        # autogen twin's result substitutes (test_command.go:391-407)
        record = None
        for prefix in ("", "autogen-", "autogen-cronjob-"):
            record = lookup(base_key[0], prefix + base_key[1], *base_key[2:])
            if record is not None:
                break
        got_status = record["status"] if record else "skip"

        if want.get("patchedResource") and record is not None:
            got_status = _check_patched_resource(base, want, record)

        ok = got_status == want_status
        mismatches += 0 if ok else 1
        rows.append((base_key[:3], want_status, got_status, ok))

    if verbose:
        print(f"\nTest: {doc.get('name', path)} ({path})")
        for (policy, rule, resource), want, got, ok in rows:
            mark = "Pass" if ok else f"Fail (got {got or 'no result'!r})"
            print(f"  {policy} / {rule} / {resource} -> {want}: {mark}")
        total = len(rows)
        print(f"  {total - mismatches}/{total} passed")
    return mismatches


def _check_patched_resource(base, want, record) -> str:
    """test_command.go:534: mutate rule outcome = skip if the rule skipped,
    else the patchedResource comparison decides pass/fail."""
    if record["status"] == "skip":
        return "skip"
    try:
        with open(os.path.join(base, want["patchedResource"])) as f:
            expected = yaml.safe_load(f)
    except OSError:
        return "error"
    return "pass" if record["patched"] == expected else "fail"


def register(subparsers) -> None:
    p = subparsers.add_parser("test", help="run declarative policy tests")
    p.add_argument("paths", nargs="*",
                   help="dirs containing test.yaml, or git URLs to clone "
                        "(https://..., file://..., *.git)")
    p.add_argument("-b", "--git-branch", default="",
                   help="branch to clone when a path is a git URL")
    p.add_argument("-q", "--quiet", action="store_true")
    p.set_defaults(func=run)
