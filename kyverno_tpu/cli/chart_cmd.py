"""``render-chart``: offline ``helm template`` for the deploy chart.

The chart (deploy/chart/kyverno-tpu) is standard Helm — where helm is
available, ``helm template`` renders it identically; this command covers
air-gapped environments via utils.helmlite's template subset."""

from __future__ import annotations

import sys

import yaml


def run(args) -> int:
    from ..utils.helmlite import render_chart

    try:
        docs = render_chart(args.chart, set_args=args.set or [],
                            release_name=args.release_name,
                            release_namespace=args.namespace)
    except Exception as e:
        print(f"render failed: {e}", file=sys.stderr)
        return 1
    out = "---\n".join(
        yaml.safe_dump(doc, default_flow_style=False, sort_keys=False)
        for doc in docs)
    print(out, end="")
    return 0


def register(subparsers) -> None:
    p = subparsers.add_parser(
        "render-chart",
        help="render the Helm deploy chart to manifests (helm template)")
    p.add_argument("chart", nargs="?", default="deploy/chart/kyverno-tpu",
                   help="chart directory")
    p.add_argument("--set", action="append", metavar="key=value",
                   help="override a value (repeatable)")
    p.add_argument("--release-name", default="kyverno-tpu")
    p.add_argument("-n", "--namespace", default="")
    p.set_defaults(func=run)
