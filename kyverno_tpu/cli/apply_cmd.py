"""``apply`` verb (mirrors /root/reference/pkg/kyverno/apply/apply_command.go)."""

from __future__ import annotations

import json
import os
import sys

import yaml

from .. import store
from ..api.load import load_policies_from_path, load_resources
from ..engine.response import RuleStatus
from .common import ResultCounts, apply_policy_on_resource
from .values import Values, load_values_file, parse_set


def run(args) -> int:
    if not args.policies:
        print("requires at least one policy path", file=sys.stderr)
        return 2
    if not args.resource:
        print("resource file(s) required (-r)", file=sys.stderr)
        return 2

    values = Values()
    if args.values_file:
        values = load_values_file(args.values_file)
    if args.set:
        values.set_values = parse_set(args.set)

    policies = []
    for path in args.policies:
        policies.extend(load_policies_from_path(path))
    resources = []
    for path in args.resource:
        resources.extend(load_resources(path))
    if args.namespace:
        resources = [
            r for r in resources
            if (r.get("metadata") or {}).get("namespace", "") == args.namespace
        ]

    # autogen mutation of incoming policies (common.go:177 MutatePolicy)
    from ..policy.autogen import mutate_policy_for_autogen

    policies = [mutate_policy_for_autogen(p) for p in policies]

    store.set_mock(True)
    values.install_mock_store()
    rc = ResultCounts()
    mutated_resources = []
    try:
        for resource in resources:
            patched = resource
            for policy in policies:
                result = apply_policy_on_resource(
                    policy,
                    patched,
                    variables=values.for_resource(
                        policy.name, (resource.get("metadata") or {}).get("name", "")
                    ),
                    namespace_labels_map=values.namespace_selectors,
                    rc=rc,
                )
                if result.mutate_response is not None:
                    patched = result.mutate_response.patched_resource or patched
                vr = result.validate_response
                if vr is not None:
                    for r in vr.policy_response.rules:
                        if r.status in (RuleStatus.FAIL, RuleStatus.ERROR):
                            res_meta = resource.get("metadata") or {}
                            print(
                                f"policy {policy.name} -> resource "
                                f"{res_meta.get('namespace', 'default')}/"
                                f"{resource.get('kind')}/{res_meta.get('name')}"
                                f" failed: \n{_indent(r.message)}"
                            )
            mutated_resources.append(patched)
    finally:
        store.set_mock(False)
        store.set_context(store.Context())

    if args.output:
        _write_mutated(mutated_resources, args.output)
    elif any(p != r for p, r in zip(mutated_resources, resources)):
        for patched in mutated_resources:
            print("---")
            print(yaml.safe_dump(patched, sort_keys=False).rstrip())

    print(
        f"\npass: {rc.pass_}, fail: {rc.fail}, warn: {rc.warn}, "
        f"error: {rc.error}, skip: {rc.skip}"
    )
    if args.policy_report:
        print(json.dumps(_policy_report(rc)))
    return 1 if rc.fail or rc.error else 0


def _indent(msg: str) -> str:
    return "\n".join("  " + line for line in (msg or "").splitlines()) or "  (no message)"


def _write_mutated(resources: list[dict], output: str) -> None:
    if os.path.isdir(output):
        for resource in resources:
            name = (resource.get("metadata") or {}).get("name", "resource")
            path = os.path.join(output, f"{name}.yaml")
            with open(path, "w") as f:
                yaml.safe_dump(resource, f, sort_keys=False)
    else:
        with open(output, "w") as f:
            for resource in resources:
                f.write("---\n")
                yaml.safe_dump(resource, f, sort_keys=False)


def _policy_report(rc: ResultCounts) -> dict:
    """--policy-report summary (wgpolicyk8s.io/v1alpha2 shape)."""
    return {
        "apiVersion": "wgpolicyk8s.io/v1alpha2",
        "kind": "ClusterPolicyReport",
        "metadata": {"name": "clusterpolicyreport"},
        "summary": {
            "pass": rc.pass_,
            "fail": rc.fail,
            "warn": rc.warn,
            "error": rc.error,
            "skip": rc.skip,
        },
    }


def register(subparsers) -> None:
    p = subparsers.add_parser("apply", help="applies policies on resources")
    p.add_argument("policies", nargs="*", help="policy YAML paths")
    p.add_argument("-r", "--resource", action="append", default=[],
                   help="path to resource files")
    p.add_argument("-o", "--output", default="",
                   help="prints mutated resources to file/directory")
    p.add_argument("-s", "--set", default="", help="variables key=value[,k=v]")
    p.add_argument("-f", "--values-file", default="",
                   help="file containing values for policy variables")
    p.add_argument("--policy-report", action="store_true",
                   help="emit a PolicyReport summary")
    p.add_argument("-n", "--namespace", default="", help="namespace filter")
    p.set_defaults(func=run)
