"""--set / --values-file handling (mirrors /root/reference/pkg/kyverno/common
Values types at common.go:48-75 and GetVariable)."""

from __future__ import annotations

from dataclasses import dataclass, field

import yaml

from .. import store


@dataclass
class Values:
    global_values: dict[str, str] = field(default_factory=dict)
    # policy -> resource -> values
    resource_values: dict[str, dict[str, dict[str, str]]] = field(default_factory=dict)
    # policy -> rule -> values (feeds the mock context store)
    rule_values: dict[str, dict[str, dict[str, str]]] = field(default_factory=dict)
    # namespace -> labels (for namespaceSelector matching)
    namespace_selectors: dict[str, dict[str, str]] = field(default_factory=dict)
    set_values: dict[str, str] = field(default_factory=dict)

    def for_resource(self, policy_name: str, resource_name: str) -> dict[str, str]:
        out = dict(self.global_values)
        out.update(
            self.resource_values.get(policy_name, {}).get(resource_name, {})
        )
        out.update(self.set_values)
        return out

    def install_mock_store(self) -> None:
        """Wire rule-level values into the mock context store
        (store.GetPolicyRuleFromContext consumed by LoadContext)."""
        policies = []
        for policy_name, rules in self.rule_values.items():
            policies.append(
                store.Policy(
                    name=policy_name,
                    rules=[
                        store.Rule(name=rule_name, values=values)
                        for rule_name, values in rules.items()
                    ],
                )
            )
        store.set_context(store.Context(policies=policies))


def parse_set(expr: str) -> dict[str, str]:
    """-s a=b,c=d"""
    out: dict[str, str] = {}
    if not expr:
        return out
    for pair in expr.split(","):
        if not pair.strip():
            continue
        if "=" not in pair:
            raise ValueError(f"invalid --set variable: {pair!r} (want key=value)")
        key, value = pair.split("=", 1)
        out[key.strip()] = value.strip()
    return out


def load_values_file(path: str) -> Values:
    with open(path) as f:
        doc = yaml.safe_load(f) or {}
    values = Values(global_values={
        k: str(v) for k, v in (doc.get("globalValues") or {}).items()
    })
    for policy in doc.get("policies") or []:
        name = policy.get("name", "")
        for resource in policy.get("resources") or []:
            values.resource_values.setdefault(name, {})[resource.get("name", "")] = {
                k: str(v) for k, v in (resource.get("values") or {}).items()
            }
        for rule in policy.get("rules") or []:
            values.rule_values.setdefault(name, {})[rule.get("name", "")] = {
                k: str(v) for k, v in (rule.get("values") or {}).items()
            }
    for selector in doc.get("namespaceSelector") or []:
        values.namespace_selectors[selector.get("name", "")] = dict(
            selector.get("labels") or {}
        )
    return values
