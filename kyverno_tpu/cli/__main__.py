"""CLI dispatcher (mirrors /root/reference/pkg/kyverno/main.go:18 CLI())."""

from __future__ import annotations

import argparse
import sys

from .. import __version__
from . import (apply_cmd, chart_cmd, dryrun_cmd, lint_cmd, test_cmd,
               validate_cmd)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="kyverno-tpu",
        description="TPU-native Kubernetes policy engine CLI",
    )
    parser.add_argument("--version", action="version", version=__version__)
    subparsers = parser.add_subparsers(dest="command")
    apply_cmd.register(subparsers)
    dryrun_cmd.register(subparsers)
    lint_cmd.register(subparsers)
    test_cmd.register(subparsers)
    validate_cmd.register(subparsers)
    chart_cmd.register(subparsers)
    # `version` verb parity (pkg/kyverno/version/command.go)
    version_p = subparsers.add_parser("version", help="print version")
    version_p.set_defaults(func=lambda _a: print(f"Version: {__version__}") or 0)

    args = parser.parse_args(argv)
    if not getattr(args, "func", None):
        parser.print_help()
        return 2
    return args.func(args)


if __name__ == "__main__":
    sys.exit(main())
