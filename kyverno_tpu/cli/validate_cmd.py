"""``validate`` verb: structural validation of policy YAMLs (the same
policy.Validate the /policyvalidate webhook runs; pkg/kyverno/validate)."""

from __future__ import annotations

import sys

from ..api.load import load_policies_from_path
from ..policy.validation import validate_policy


def run(args) -> int:
    if not args.policies:
        print("requires at least one policy path", file=sys.stderr)
        return 2
    rc = 0
    for path in args.policies:
        try:
            policies = load_policies_from_path(path)
        except Exception as e:
            print(f"Policy {path} is invalid: failed to load: {e}")
            rc = 1
            continue
        for policy in policies:
            errors = validate_policy(policy)
            if errors:
                rc = 1
                print(f"Policy {policy.name} is invalid:")
                for err in errors:
                    print(f"  - {err}")
            else:
                print(f"Policy {policy.name} is valid.")
    return rc


def register(subparsers) -> None:
    p = subparsers.add_parser("validate", help="validate policy YAML structure")
    p.add_argument("policies", nargs="*", help="policy YAML paths")
    p.set_defaults(func=run)
