"""kyverno-tpu: a TPU-native policy engine with the capabilities of Kyverno.

Declarative validate / mutate / generate / verifyImages policies over
Kubernetes resources. The core compiles the policy set into flat pattern
tensors and evaluates the policy x resource matrix as a vectorized NFA under
JAX/XLA; a faithful pure-Python tier behind the same ``engine.Backend``
interface is the correctness oracle and fallback lane.

Layer map (mirrors SURVEY.md section 1):
  - ``kyverno_tpu.api``       policy CRD types + loaders (L0)
  - ``kyverno_tpu.engine``    pure policy engine, CPU oracle tier (L3)
  - ``kyverno_tpu.models``    policy IR + compiler -> pattern tensors
  - ``kyverno_tpu.ops``       JAX/pallas kernels (wildcard NFA, verdicts)
  - ``kyverno_tpu.parallel``  mesh sharding of the policy x resource matrix
  - ``kyverno_tpu.runtime``   webhook server, controllers, reports, metrics
  - ``kyverno_tpu.cli``       apply / test / validate commands
"""

__version__ = "0.1.0"
