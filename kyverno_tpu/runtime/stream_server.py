"""Streaming admission plane: bidirectional frames, continuous batching.

The webhook plane pays an HTTP request/response + JSON walk per
admission. This module is the columnar front door: a client holds ONE
bidirectional stream open and pipelines admission frames down it;
responses come back tagged with the request id, in completion order.
Arriving admissions join the currently-forming padded batch (the
batcher's ``continuous=True`` late-join graft), so a pipelined burst
coalesces into far fewer device dispatches than the same burst over
HTTP keep-alive.

Two transports share one payload codec, selected at startup:

* **gRPC** (``grpcio`` importable — it is baked into the image): a
  generic ``/ktpu.StreamAdmission/Admit`` stream-stream method with
  identity (de)serializers — each message IS a payload, no protobuf
  schema compilation step.
* **framed socket**: the same payload behind a ``u32`` little-endian
  length prefix on a plain TCP socket, for environments without grpc.

``KTPU_STREAM_TRANSPORT=grpc|socket|auto`` overrides the selection.

Payload layout (both transports, little-endian)::

    u8 ftype | u64 req_id | body

    ftype may carry F_TRACE_BIT (0x40) on admission frames, in which
    case body is prefixed with u16 tplen|traceparent (cross-process
    trace context; see runtime/tracing.py). Frames without the bit
    decode exactly as before.

    F_ADMIT_JSON  body = AdmissionReview JSON (utf-8)
    F_ADMIT_ROW   body = u16 klen|kind|u16 nslen|ns|encode_packed_row
    F_ADMIT_BLOCK body = u16 klen|kind|u16 nslen|ns|encode_packed_block
    F_VERDICT     body = response JSON (utf-8)
    F_ERROR       body = error message (utf-8)

The three admission kinds trade generality for copies:

* JSON frames delegate to ``WebhookServer.handle`` — verdicts AND
  messages are exact-parity with the webhook by construction (same
  code path, minus HTTP).
* ROW frames carry a client-tokenized ``PackedRow``; the server
  splices it into the forming batch without re-parsing (it pays one
  (bytes, len)-keyed re-intern at the splice).
* BLOCK frames carry a whole client-tokenized ``PackedBatch`` that is
  already the device transfer format: zero per-row re-intern, zero row
  rebuild, dispatched with input-buffer donation.
"""

from __future__ import annotations

import json
import queue
import socket
import struct
import threading
import time
from concurrent.futures import ThreadPoolExecutor

from ..models import Verdict
from . import featureplane, tracing
from .batch import ATTENTION, CLEAN
from .policycache import PolicyType
from .webhook import VALIDATING_WEBHOOK_PATH

F_ADMIT_JSON = 0x01
F_ADMIT_ROW = 0x02
F_ADMIT_BLOCK = 0x03
F_VERDICT = 0x81
F_ERROR = 0x7F

# Fleet verdict-fabric frames (fleet/fabric.py) share this codec: the
# request types live below 0x40 and the reply types above 0x80 so
# neither collides with F_TRACE_BIT masking (only _TRACEABLE admission
# types honor the bit) or with F_ERROR's numeric bit pattern. Bodies
# are tier/key/value encodings owned by fleet/fabric.py.
F_CACHE_GET = 0x10
F_CACHE_PUT = 0x11
F_CACHE_INVALIDATE = 0x12
F_CACHE_OK = 0x82
F_CACHE_MISS = 0x83

# Optional trace-context carriage: admission frames may set this bit on
# ftype, in which case the body is prefixed with ``u16 tplen|traceparent``
# (runtime/tracing.py W3C-style rendering). The bit is only honored when
# the masked type is an admission frame, so F_ERROR (0x7F, which has the
# bit set numerically) and foreign frame types decode unchanged; servers
# that predate the bit reject flagged frames as unknown types rather than
# mis-parsing them.
F_TRACE_BIT = 0x40
_TRACEABLE = (F_ADMIT_JSON, F_ADMIT_ROW, F_ADMIT_BLOCK)

_PAYLOAD_HDR = struct.Struct("<BQ")
_LEN_PREFIX = struct.Struct("<I")
_U16 = struct.Struct("<H")

GRPC_METHOD = "/ktpu.StreamAdmission/Admit"

MAX_FRAME_BYTES = 64 * 1024 * 1024  # defensive bound on one frame


def transport_preference() -> str:
    """grpc | socket | auto (the startup selection knob)."""
    return featureplane.raw("KTPU_STREAM_TRANSPORT")


# ------------------------------------------------------------------ codec


def encode_payload(ftype: int, req_id: int, body: bytes,
                   traceparent: str | None = None) -> bytes:
    if traceparent and ftype in _TRACEABLE:
        tp = traceparent.encode("ascii")
        return b"".join((_PAYLOAD_HDR.pack(ftype | F_TRACE_BIT, req_id),
                         _U16.pack(len(tp)), tp, body))
    return _PAYLOAD_HDR.pack(ftype, req_id) + body


def decode_payload_ex(payload: bytes) -> tuple[int, int, bytes, str | None]:
    """(ftype, req_id, body, traceparent-or-None). Raises ValueError on a
    short payload. A flagged frame whose trace prefix is truncated keeps
    its raw (flagged) ftype and body — the caller's unknown-type path
    then rejects it with the req_id intact instead of losing the frame
    to a parse exception."""
    if len(payload) < _PAYLOAD_HDR.size:
        raise ValueError(f"short payload: {len(payload)} bytes")
    ftype, req_id = _PAYLOAD_HDR.unpack_from(payload, 0)
    off = _PAYLOAD_HDR.size
    tp = None
    if ftype & F_TRACE_BIT and (ftype & ~F_TRACE_BIT) in _TRACEABLE:
        if len(payload) >= off + _U16.size:
            (tplen,) = _U16.unpack_from(payload, off)
            if len(payload) >= off + _U16.size + tplen:
                ftype &= ~F_TRACE_BIT
                off += _U16.size
                tp = bytes(payload[off:off + tplen]).decode(
                    "ascii", "replace")
                off += tplen
    return ftype, req_id, payload[off:], tp


def decode_payload(payload: bytes) -> tuple[int, int, bytes]:
    """(ftype, req_id, body). Raises ValueError on a short payload."""
    ftype, req_id, body, _ = decode_payload_ex(payload)
    return ftype, req_id, body


def _encode_scoped(kind: str, namespace: str, blob: bytes) -> bytes:
    k = kind.encode("utf-8")
    ns = namespace.encode("utf-8")
    return b"".join((_U16.pack(len(k)), k, _U16.pack(len(ns)), ns, blob))


def _decode_scoped(body: bytes) -> tuple[str, str, bytes, int]:
    """(kind, namespace, rest, rest_offset_into_body)."""
    (klen,) = _U16.unpack_from(body, 0)
    off = _U16.size
    kind = bytes(body[off:off + klen]).decode("utf-8")
    off += klen
    (nslen,) = _U16.unpack_from(body, off)
    off += _U16.size
    namespace = bytes(body[off:off + nslen]).decode("utf-8")
    off += nslen
    return kind, namespace, body, off


def encode_row_frame(req_id: int, kind: str, namespace: str, row,
                     traceparent: str | None = None) -> bytes:
    from ..models.flatten import encode_packed_row

    return encode_payload(F_ADMIT_ROW, req_id,
                          _encode_scoped(kind, namespace,
                                         encode_packed_row(row)),
                          traceparent=traceparent)


def encode_block_frame(req_id: int, kind: str, namespace: str,
                       block, traceparent: str | None = None) -> bytes:
    from ..models.flatten import encode_packed_block

    return encode_payload(F_ADMIT_BLOCK, req_id,
                          _encode_scoped(kind, namespace,
                                         encode_packed_block(block)),
                          traceparent=traceparent)


def encode_json_frame(req_id: int, review: dict,
                      traceparent: str | None = None) -> bytes:
    return encode_payload(F_ADMIT_JSON, req_id,
                          json.dumps(review).encode("utf-8"),
                          traceparent=traceparent)


def decode_verdict_frame(payload: bytes) -> tuple[int, dict]:
    """(req_id, decoded response) for one server reply frame. F_ERROR
    raises RuntimeError with the server's message; any other frame type
    raises ValueError. In-process consumers (the replay driver's stream
    legs, tests) share this instead of re-implementing the unwrap."""
    ftype, req_id, body, _ = decode_payload_ex(payload)
    if ftype == F_VERDICT:
        return req_id, json.loads(body)
    if ftype == F_ERROR:
        raise RuntimeError(body.decode("utf-8", "replace"))
    raise ValueError(f"unexpected reply frame type {ftype:#x}")


# ------------------------------------------------------- client-side prep


def flatten_rows_for_wire(cps, resources: list[dict]):
    """Client-side tokenization for ROW frames: flatten against the
    compiled set's schema and split into per-resource PackedRows (each
    with a private rebased string table, ready to re-intern anywhere)."""
    from ..models.flatten import split_packed_rows

    return split_packed_rows(cps.flatten_packed(resources))


def flatten_block_for_wire(cps, resources: list[dict]):
    """Client-side tokenization for a BLOCK frame: one PackedBatch that
    is already the server's device transfer format."""
    return cps.flatten_packed(resources)


# ------------------------------------------------------------------ plane


class StreamAdmissionPlane:
    """Transport-independent frame handler.

    One instance serves every connection/stream of a server; it owns no
    sockets — transports call :meth:`handle_payload` from their worker
    pools and write back whatever it returns.
    """

    def __init__(self, webhook, batcher, policy_cache,
                 ptype: PolicyType = PolicyType.VALIDATE_ENFORCE):
        self.webhook = webhook
        self.batcher = batcher
        self.policy_cache = policy_cache
        self.ptype = ptype
        self.stats: dict = {}
        self._lock = threading.Lock()

    # -- helpers

    def _note(self, key: str, n: int = 1) -> None:
        with self._lock:
            self.stats[key] = self.stats.get(key, 0) + n

    @staticmethod
    def _row_response(status: str, vrow) -> dict:
        escalate = (status != CLEAN and not vrow) or any(
            t[2] in (Verdict.HOST, Verdict.ERROR) for t in vrow)
        denied = any(t[2] is Verdict.FAIL for t in vrow)
        return {
            "status": status,
            "allowed": not escalate and not denied,
            "escalate": escalate,
            "verdicts": [[pn, rn, int(v), msg] for pn, rn, v, msg in vrow],
        }

    def handle_payload(self, payload: bytes, transport: str) -> bytes:
        """Decode one admission frame, run it, return the response
        payload. Never raises — errors come back as F_ERROR frames."""
        t_in = time.perf_counter()
        req_id = 0
        rec = tracing.recorder()
        trace = rec.start("stream_admission", transport=transport)
        tok = tracing.bind(trace)
        ftype_name = "unknown"
        rows = 1
        error = False
        try:
            ftype, req_id, body, tp = decode_payload_ex(payload)
            if tp:
                tracing.adopt_remote_id(trace,
                                        tracing.parse_traceparent(tp))
            rec.add_span(trace, "stream_ingest", t_in, time.perf_counter(),
                         bytes=len(payload), transport=transport)
            if ftype == F_ADMIT_JSON:
                ftype_name = "json"
                review = json.loads(body)
                out = self.webhook.handle(VALIDATING_WEBHOOK_PATH, review)
                self._note("json_frames")
                return encode_payload(F_VERDICT, req_id,
                                      json.dumps(out).encode("utf-8"))
            if ftype == F_ADMIT_ROW:
                ftype_name = "row"
                from ..models.flatten import decode_packed_row

                kind, namespace, buf, off = _decode_scoped(body)
                row, _ = decode_packed_row(buf, off)
                if trace is not None:
                    trace.labels.update(kind=kind, namespace=namespace)
                status, vrow = self.batcher.screen_row(
                    self.ptype, kind, namespace, row)
                self._note("row_frames")
                return encode_payload(
                    F_VERDICT, req_id,
                    json.dumps(self._row_response(status, vrow))
                    .encode("utf-8"))
            if ftype == F_ADMIT_BLOCK:
                ftype_name = "block"
                from ..models.flatten import decode_packed_block

                kind, namespace, buf, off = _decode_scoped(body)
                block, _ = decode_packed_block(buf, off)
                if trace is not None:
                    trace.labels.update(kind=kind, namespace=namespace)
                results = self.batcher.evaluate_block(
                    self.ptype, kind, namespace, block)
                if results is None:
                    error = True
                    self._note("block_errors")
                    return encode_payload(F_ERROR, req_id,
                                          b"block evaluation failed")
                rows = max(1, len(results))
                self._note("block_frames")
                self._note("block_rows", len(results))
                out = {"rows": [self._row_response(st, vr)
                                for st, vr in results]}
                return encode_payload(F_VERDICT, req_id,
                                      json.dumps(out).encode("utf-8"))
            error = True
            return encode_payload(F_ERROR, req_id,
                                  f"unknown frame type {ftype:#x}"
                                  .encode("utf-8"))
        except Exception as exc:  # codec/handler failure — never raise
            error = True
            self._note("frame_errors")
            return encode_payload(F_ERROR, req_id,
                                  f"{type(exc).__name__}: {exc}"
                                  .encode("utf-8"))
        finally:
            tracing.unbind(tok)
            rec.finish(trace)
            if ftype_name in ("row", "block") and not error:
                # JSON frames route through webhook._handle, which
                # already feeds the watchdog; row/block frames are the
                # only admissions that bypass it
                try:
                    from .slo import watchdog

                    watchdog().observe(time.perf_counter() - t_in)
                except Exception:
                    pass
            try:
                from . import metrics as metrics_mod

                reg = metrics_mod.registry()
                metrics_mod.record_stream_frame(
                    reg, ftype_name, transport,
                    seconds=time.perf_counter() - t_in, rows=rows,
                    error=error)
                if ftype_name == "row" and not error:
                    metrics_mod.record_stream_zero_copy(reg, wire_rows=1)
                elif ftype_name == "block" and not error:
                    metrics_mod.record_stream_zero_copy(reg,
                                                        block_rows=rows,
                                                        donated=1)
            except Exception:
                pass


# ------------------------------------------------------------- transports


def _read_exact(sock: socket.socket, n: int) -> bytes | None:
    """Read exactly n bytes or None on EOF."""
    chunks = []
    while n:
        chunk = sock.recv(min(n, 1 << 20))
        if not chunk:
            return None
        chunks.append(chunk)
        n -= len(chunk)
    return b"".join(chunks)


def _set_open_streams(delta: int, counter=[0],
                      lock=threading.Lock()) -> None:
    try:
        from . import metrics as metrics_mod

        with lock:
            counter[0] += delta
            n = counter[0]
        metrics_mod.record_stream_gauges(metrics_mod.registry(),
                                         open_streams=n)
    except Exception:
        pass


class _SocketTransport:
    """Length-prefixed frames over TCP; one reader thread per
    connection, responses written in completion order under a per-
    connection write lock (frames interleave safely — req_id pairs
    them back up client-side)."""

    name = "socket"

    def __init__(self, plane: StreamAdmissionPlane, host: str, port: int,
                 workers: int = 16):
        self._plane = plane
        self._srv = socket.create_server((host, port))
        self._port = self._srv.getsockname()[1]
        self._pool = ThreadPoolExecutor(max_workers=workers,
                                        thread_name_prefix="ktpu-stream")
        self._accept_thread: threading.Thread | None = None
        self._stopped = threading.Event()

    @property
    def port(self) -> int:
        return self._port

    def start(self) -> None:
        self._accept_thread = threading.Thread(target=self._accept_loop,
                                               daemon=True,
                                               name="ktpu-stream-accept")
        self._accept_thread.start()

    def _accept_loop(self) -> None:
        while not self._stopped.is_set():
            try:
                conn, _ = self._srv.accept()
            except OSError:
                return
            threading.Thread(target=self._serve_conn, args=(conn,),
                             daemon=True).start()

    def _serve_conn(self, conn: socket.socket) -> None:
        conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        wlock = threading.Lock()
        _set_open_streams(+1)

        def _respond(payload: bytes) -> None:
            try:
                resp = self._plane.handle_payload(payload, "socket")
                with wlock:
                    conn.sendall(_LEN_PREFIX.pack(len(resp)) + resp)
            except OSError:
                pass

        try:
            while not self._stopped.is_set():
                hdr = _read_exact(conn, _LEN_PREFIX.size)
                if hdr is None:
                    return
                (ln,) = _LEN_PREFIX.unpack(hdr)
                if ln > MAX_FRAME_BYTES:
                    return
                payload = _read_exact(conn, ln)
                if payload is None:
                    return
                # hand off immediately: the reader keeps draining so a
                # pipelined burst is concurrently in flight — that
                # concurrency is what the continuous batcher coalesces
                self._pool.submit(_respond, payload)
        except OSError:
            pass
        finally:
            _set_open_streams(-1)
            try:
                conn.close()
            except OSError:
                pass

    def stop(self) -> None:
        self._stopped.set()
        try:
            self._srv.close()
        except OSError:
            pass
        self._pool.shutdown(wait=False)


class _GrpcTransport:
    """Bidirectional stream-stream RPC with identity serializers: each
    gRPC message is one payload (no length prefix — HTTP/2 frames it).
    Requests fan out to a worker pool so pipelined messages on one
    stream process concurrently; responses yield in completion order."""

    name = "grpc"

    def __init__(self, plane: StreamAdmissionPlane, host: str, port: int,
                 workers: int = 16):
        import grpc

        self._plane = plane
        self._pool = ThreadPoolExecutor(max_workers=workers,
                                        thread_name_prefix="ktpu-grpc")
        self._server = grpc.server(
            ThreadPoolExecutor(max_workers=workers))
        service = GRPC_METHOD.rsplit("/", 2)[1]
        method = GRPC_METHOD.rsplit("/", 1)[1]
        handler = grpc.method_handlers_generic_handler(service, {
            method: grpc.stream_stream_rpc_method_handler(
                self._admit,
                request_deserializer=None,
                response_serializer=None),
        })
        self._server.add_generic_rpc_handlers((handler,))
        self._port = self._server.add_insecure_port(f"{host}:{port}")

    @property
    def port(self) -> int:
        return self._port

    def start(self) -> None:
        self._server.start()

    def _admit(self, request_iterator, context):
        out_q: queue.Queue = queue.Queue()
        sentinel = object()
        _set_open_streams(+1)

        def _one(payload: bytes) -> None:
            try:
                out_q.put(self._plane.handle_payload(payload, "grpc"))
            except Exception as exc:
                out_q.put(encode_payload(
                    F_ERROR, 0, f"{type(exc).__name__}: {exc}"
                    .encode("utf-8")))

        def _pump() -> None:
            futs = []
            try:
                for payload in request_iterator:
                    futs.append(self._pool.submit(_one, payload))
            except Exception:
                pass
            for f in futs:
                try:
                    f.result()
                except Exception:
                    pass
            out_q.put(sentinel)

        threading.Thread(target=_pump, daemon=True,
                         name="ktpu-grpc-pump").start()
        try:
            while True:
                item = out_q.get()
                if item is sentinel:
                    return
                yield item
        finally:
            _set_open_streams(-1)

    def stop(self) -> None:
        self._server.stop(grace=0.5)
        self._pool.shutdown(wait=False)


class StreamServer:
    """Transport-selecting front door for the streaming plane.

    ``transport`` = "grpc" | "socket" | "auto" (default: the
    ``KTPU_STREAM_TRANSPORT`` env knob, itself defaulting to auto —
    grpc when importable, else the framed socket)."""

    def __init__(self, webhook, batcher, policy_cache,
                 host: str = "127.0.0.1", port: int = 0,
                 transport: str | None = None,
                 ptype: PolicyType = PolicyType.VALIDATE_ENFORCE,
                 workers: int = 16):
        self.plane = StreamAdmissionPlane(webhook, batcher, policy_cache,
                                          ptype=ptype)
        choice = transport or transport_preference()
        self._transport = None
        if choice in ("auto", "grpc"):
            try:
                self._transport = _GrpcTransport(self.plane, host, port,
                                                 workers=workers)
            except Exception:
                if choice == "grpc":
                    raise
        if self._transport is None:
            self._transport = _SocketTransport(self.plane, host, port,
                                               workers=workers)

    @property
    def transport_name(self) -> str:
        return self._transport.name

    @property
    def port(self) -> int:
        return self._transport.port

    def start(self) -> "StreamServer":
        self._transport.start()
        return self

    def stop(self) -> None:
        self._transport.stop()


# ------------------------------------------------------------------ client


class StreamClient:
    """Pipelining client for both transports.

    ``submit_*`` returns a req_id immediately; :meth:`result` blocks for
    that response. ``admit_*`` are the submit+wait conveniences. Thread-
    safe; a single instance can keep hundreds of admissions in flight —
    that open-loop pipelining is what the round-10 bench drives."""

    def __init__(self, port: int, host: str = "127.0.0.1",
                 transport: str = "socket"):
        self.transport = transport
        self._lock = threading.Lock()
        self._next_id = 1
        self._waiters: dict[int, queue.Queue] = {}
        # req_id -> (caller's trace, t_submit, t_sent): client-side span
        # bookkeeping so result() can split queue wait from service time
        self._traces: dict[int, tuple] = {}
        if transport == "grpc":
            import grpc

            self._channel = grpc.insecure_channel(f"{host}:{port}")
            self._call = self._channel.stream_stream(
                GRPC_METHOD, request_serializer=None,
                response_deserializer=None)
            self._sendq: queue.Queue = queue.Queue()

            def _feed():
                while True:
                    item = self._sendq.get()
                    if item is None:
                        return
                    yield item

            self._responses = self._call(_feed())
        else:
            self._sock = socket.create_connection((host, port))
            self._sock.setsockopt(socket.IPPROTO_TCP,
                                  socket.TCP_NODELAY, 1)
            self._wlock = threading.Lock()
        self._reader = threading.Thread(target=self._read_loop,
                                        daemon=True,
                                        name="ktpu-stream-client")
        self._reader.start()

    # -- low-level

    def _register(self) -> tuple[int, queue.Queue]:
        with self._lock:
            req_id = self._next_id
            self._next_id += 1
            q: queue.Queue = queue.Queue(maxsize=1)
            self._waiters[req_id] = q
        return req_id, q

    def _send(self, payload: bytes) -> None:
        if self.transport == "grpc":
            self._sendq.put(payload)
        else:
            with self._wlock:
                self._sock.sendall(_LEN_PREFIX.pack(len(payload))
                                   + payload)

    def _read_loop(self) -> None:
        try:
            if self.transport == "grpc":
                for payload in self._responses:
                    self._dispatch(bytes(payload))
            else:
                while True:
                    hdr = _read_exact(self._sock, _LEN_PREFIX.size)
                    if hdr is None:
                        return
                    (ln,) = _LEN_PREFIX.unpack(hdr)
                    payload = _read_exact(self._sock, ln)
                    if payload is None:
                        return
                    self._dispatch(payload)
        except Exception:
            # connection torn down — wake every waiter with an error
            with self._lock:
                waiters = list(self._waiters.values())
                self._waiters.clear()
            for q in waiters:
                q.put((F_ERROR, b"connection closed"))

    def _dispatch(self, payload: bytes) -> None:
        ftype, req_id, body = decode_payload(payload)
        with self._lock:
            q = self._waiters.get(req_id)
        if q is not None:
            q.put((ftype, bytes(body)))

    def _track(self, req_id: int, t_submit: float) -> None:
        trace = tracing.current()
        if trace is not None:
            with self._lock:
                self._traces[req_id] = (trace, t_submit,
                                        time.perf_counter())

    # -- public API

    def submit_json(self, review: dict) -> int:
        req_id, _ = self._register()
        t0 = time.perf_counter()
        self._send(encode_json_frame(
            req_id, review,
            traceparent=tracing.make_traceparent(tracing.current())))
        self._track(req_id, t0)
        return req_id

    def submit_row(self, kind: str, namespace: str, row) -> int:
        req_id, _ = self._register()
        t0 = time.perf_counter()
        self._send(encode_row_frame(
            req_id, kind, namespace, row,
            traceparent=tracing.make_traceparent(tracing.current())))
        self._track(req_id, t0)
        return req_id

    def submit_block(self, kind: str, namespace: str, block) -> int:
        req_id, _ = self._register()
        t0 = time.perf_counter()
        self._send(encode_block_frame(
            req_id, kind, namespace, block,
            traceparent=tracing.make_traceparent(tracing.current())))
        self._track(req_id, t0)
        return req_id

    def result(self, req_id: int, timeout: float = 30.0) -> dict:
        """Blocking response fetch; raises RuntimeError on an F_ERROR
        frame or timeout."""
        with self._lock:
            q = self._waiters.get(req_id)
        if q is None:
            # response may already have been dispatched and consumed, or
            # the id was never issued
            raise RuntimeError(f"unknown or already-consumed req_id "
                               f"{req_id}")
        try:
            ftype, body = q.get(timeout=timeout)
        except queue.Empty:
            raise RuntimeError(f"stream response timeout (req {req_id})")
        finally:
            with self._lock:
                self._waiters.pop(req_id, None)
                tracked = self._traces.pop(req_id, None)
            if tracked is not None:
                trace, t_submit, t_sent = tracked
                rec = tracing.recorder()
                rec.add_span(trace, "client_enqueue", t_submit, t_sent,
                             req_id=str(req_id),
                             transport=self.transport)
                rec.add_span(trace, "client_service", t_sent,
                             time.perf_counter(), req_id=str(req_id),
                             transport=self.transport)
        if ftype == F_ERROR:
            raise RuntimeError(body.decode("utf-8", "replace"))
        return json.loads(body)

    def admit_json(self, review: dict, timeout: float = 30.0) -> dict:
        return self.result(self.submit_json(review), timeout=timeout)

    def admit_row(self, kind: str, namespace: str, row,
                  timeout: float = 30.0) -> dict:
        return self.result(self.submit_row(kind, namespace, row),
                           timeout=timeout)

    def admit_block(self, kind: str, namespace: str, block,
                    timeout: float = 30.0) -> dict:
        return self.result(self.submit_block(kind, namespace, block),
                           timeout=timeout)

    def close(self) -> None:
        if self.transport == "grpc":
            try:
                self._sendq.put(None)
                self._call = None
                self._channel.close()
            except Exception:
                pass
        else:
            try:
                self._sock.close()
            except OSError:
                pass
