"""Streaming watch: the client-go reflector/informer analogue.

Mirrors /root/reference/pkg/resourcecache/resourcecache.go:42
(CreateGVKInformer) and the client-go Reflector it delegates to: per-GVK
``list`` to prime state, then a chunked ``?watch=true`` stream resumed
from the last seen resourceVersion, with bookmark handling, exponential
backoff on transport errors, and a full re-list on 410 Gone (the
apiserver's "your resourceVersion is too old"). Consumers register
callbacks; steady state does zero polling GETs.
"""

from __future__ import annotations

import json
import random
import threading


class Reflector:
    """List+watch loop for one (apiVersion, kind, namespace) — the
    client-go reflector. ``on_sync(items)`` fires after every full list
    (initial sync and 410-triggered re-lists); ``on_event(type, obj)``
    fires per watch event (ADDED/MODIFIED/DELETED)."""

    def __init__(self, client, api_version: str, kind: str,
                 namespace: str = "", on_event=None, on_sync=None,
                 backoff_base_s: float = 0.2, backoff_cap_s: float = 30.0,
                 max_watch_failures: int = 5):
        self.client = client
        self.api_version = api_version
        self.kind = kind
        self.namespace = namespace
        self.on_event = on_event or (lambda t, o: None)
        self.on_sync = on_sync or (lambda items: None)
        self.backoff_base_s = backoff_base_s
        self.backoff_cap_s = backoff_cap_s
        self.max_watch_failures = max_watch_failures
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.last_resource_version: str | None = None
        self.syncs = 0
        self.reconnects = 0
        self._synced = threading.Event()

    # ------------------------------------------------------------ control

    def start(self) -> "Reflector":
        self._thread = threading.Thread(
            target=self._run, daemon=True,
            name=f"reflector-{self.kind}/{self.namespace or '*'}")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()

    def wait_synced(self, timeout_s: float = 10.0) -> bool:
        return self._synced.wait(timeout_s)

    # ------------------------------------------------------------- loop

    def _run(self) -> None:
        failures = 0
        while not self._stop.is_set():
            try:
                self._list_then_watch()
                failures = 0            # clean stop
            except _Relist:
                failures = 0            # 410: re-list promptly
            except Exception:
                failures += 1           # LIST failed (or watch gave up)
            if self._stop.is_set():
                return
            delay = min(self.backoff_cap_s,
                        self.backoff_base_s * (2 ** min(failures, 8)))
            self._stop.wait(delay * (0.5 + random.random() / 2))

    def _list_then_watch(self) -> None:
        doc = self.client.list_response(
            self.api_version, self.kind, self.namespace)
        items = list((doc or {}).get("items") or [])
        # list items omit kind/apiVersion; restore them (client-go does
        # the same via the list's GVK minus the "List" suffix)
        for it in items:
            it.setdefault("kind", self.kind)
            it.setdefault("apiVersion", self.api_version)
        rv = ((doc or {}).get("metadata") or {}).get("resourceVersion")
        self.last_resource_version = rv
        self.syncs += 1
        self.on_sync(items)
        self._synced.set()

        # watch loop: transport errors resume from the last seen rv (the
        # client-go behavior — a network blip must not re-list the world);
        # only 410 Gone or persistent watch failure escalates to a re-list
        watch_failures = 0
        while not self._stop.is_set():
            try:
                gone = self._watch_once()
                watch_failures = 0
            except Exception:
                watch_failures += 1
                self.reconnects += 1
                if watch_failures > self.max_watch_failures:
                    raise _Relist() from None
                self._stop.wait(
                    min(5.0, self.backoff_base_s * (2 ** watch_failures))
                    * (0.5 + random.random() / 2))
                continue
            if self._stop.is_set():
                return
            self.reconnects += 1
            if gone:
                raise _Relist()
            # clean server close: reconnect from the last rv

    def _watch_once(self) -> bool:
        """One watch connection; returns True on 410 Gone."""
        for ev_type, obj in self.client.watch_stream(
                self.api_version, self.kind, self.namespace,
                resource_version=self.last_resource_version,
                stop=self._stop):
            if ev_type == "ERROR":
                if (obj or {}).get("code") == 410:
                    return True
                # a non-410 Status (e.g. a 500) is a server-side failure,
                # not a clean close: surface it as a watch failure so the
                # outer loop backs off and eventually escalates to a
                # re-list, instead of hot-looping zero-delay reconnects
                raise _WatchError(
                    f"watch ERROR frame: {json.dumps(obj)[:200]}")
            rv = ((obj or {}).get("metadata") or {}).get("resourceVersion")
            if rv:
                self.last_resource_version = rv
            if ev_type == "BOOKMARK":
                continue          # rv checkpoint only, no state change
            obj.setdefault("kind", self.kind)
            obj.setdefault("apiVersion", self.api_version)
            self.on_event(ev_type, obj)
        return False


class _Relist(Exception):
    """410 Gone: restart from a fresh list."""


class _WatchError(Exception):
    """Server-sent non-410 ERROR frame: retry the watch with backoff."""


class WatchHub:
    """Per-GVK reflector registry — the ResourceCache's informer factory
    (resourcecache.go CreateGVKInformer). ensure() is idempotent; all
    callbacks for a GVK share one reflector/stream."""

    def __init__(self, client):
        self.client = client
        self._lock = threading.Lock()
        self._reflectors: dict[tuple, Reflector] = {}
        self._callbacks: dict[tuple, list] = {}
        # watch-maintained object map per key ((ns, name) -> obj), kept
        # current by _fan_event — a late subscriber's replay must reflect
        # every event since the last list, not the stale list itself
        self._state: dict[tuple, dict] = {}
        # serializes state mutation + callback delivery with the replay in
        # ensure(): without it a replay captured at state vN could be
        # delivered AFTER event N+1 reached the same subscriber, and a
        # wholesale-replacing on_sync would clobber the newer event.
        # RLock so an (ill-advised) ensure() from inside a callback
        # degrades to a stale-replay, not a deadlock.
        self._deliver_lock = threading.RLock()

    @staticmethod
    def _obj_key(obj: dict) -> tuple:
        meta = obj.get("metadata") or {}
        return (meta.get("namespace", ""), meta.get("name", ""))

    def ensure(self, api_version: str, kind: str, namespace: str = "",
               on_event=None, on_sync=None) -> Reflector:
        key = (api_version, kind, namespace or "")
        with self._lock:
            cbs = self._callbacks.setdefault(key, [])
            if on_event or on_sync:
                cbs.append((on_event, on_sync))
            refl = self._reflectors.get(key)
            started = refl is not None
            if refl is None:
                refl = Reflector(
                    self.client, api_version, kind, namespace,
                    on_event=lambda t, o, k=key: self._fan_event(k, t, o),
                    on_sync=lambda items, k=key: self._fan_sync(k, items),
                )
                self._reflectors[key] = refl
        if started and on_sync is not None:
            # joining an already-running reflector: replay the CURRENT
            # watch-maintained state (list + every event since) so
            # "missing key = confirmed absence" consumers start complete;
            # the delivery lock orders the replay before any later event
            with self._deliver_lock:
                state = self._state.get(key)
                if state is not None:
                    try:
                        on_sync(list(state.values()))
                    except Exception:
                        pass
        if not started:
            refl.start()
        return refl

    def _fan_event(self, key, ev_type, obj) -> None:
        with self._deliver_lock:
            state = self._state.get(key)
            if state is not None and ev_type in (
                    "ADDED", "MODIFIED", "DELETED"):
                if ev_type == "DELETED":
                    state.pop(self._obj_key(obj), None)
                else:
                    state[self._obj_key(obj)] = obj
            for on_event, _ in list(self._callbacks.get(key, [])):
                if on_event is not None:
                    try:
                        on_event(ev_type, obj)
                    except Exception:
                        pass

    def _fan_sync(self, key, items) -> None:
        with self._deliver_lock:
            self._state[key] = {self._obj_key(o): o for o in items}
            for _, on_sync in list(self._callbacks.get(key, [])):
                if on_sync is not None:
                    try:
                        on_sync(items)
                    except Exception:
                        pass

    def stop(self) -> None:
        with self._lock:
            for refl in self._reflectors.values():
                refl.stop()
            self._reflectors.clear()
            self._callbacks.clear()
            self._state.clear()


def decode_watch_line(line: bytes):
    """One newline-delimited watch frame -> (type, object) or None.

    ERROR frames carry a Status object; its code surfaces so the
    reflector can distinguish 410 Gone from other failures."""
    line = line.strip()
    if not line:
        return None
    try:
        frame = json.loads(line)
    except ValueError:
        return None
    ev_type = frame.get("type", "")
    obj = frame.get("object") or {}
    return ev_type, obj
