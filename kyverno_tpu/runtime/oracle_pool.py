"""Multiprocess CPU-oracle lane: multicore scaling for admission bursts.

The reference gets a goroutine per admission request and all host cores
for free (pkg/webhooks/server.go:233); CPython's GIL serializes our
oracle, so a 16-way burst on an 8-core host still evaluates one policy
at a time. This pool runs the per-request enforce loop in *spawned*
worker processes (spawn, never fork: the parent holds initialized
TPU/JAX state that must not leak into children; workers import only the
jax-free engine modules).

Scope is deliberately narrow and safe:

- engages only when the host has enough cores to win
  (``os.cpu_count() >= MIN_CORES``) — on the 1-core sandbox it stays
  dormant and the inline path is untouched;
- only *cluster-independent* policies are eligible (no ``context:``
  entries, no API calls): workers have no cluster client, so anything
  needing one stays inline. Namespace labels and RBAC roles resolve in
  the parent and travel as plain data;
- any pool failure — pickling, worker crash, timeout — falls back to
  the inline oracle for that request. Wrong-way cost is latency only.

Policy sets ship to workers once per generation via the pool
initializer; a policy-cache change rebuilds the pool in the background
(policy updates are rare; admission keeps the old pool until the new
one is warm).
"""

from __future__ import annotations

import multiprocessing
import os
import threading
from concurrent.futures import ProcessPoolExecutor

MIN_CORES = 4

# worker-side state (one policy set per generation)
_worker_policies: list = []


def _worker_init(policy_raws: list[dict]) -> None:
    global _worker_policies
    # keep any accidental jax import CPU-only inside workers (the oracle
    # path never imports jax; this is a backstop, not a dependency)
    os.environ["JAX_PLATFORMS"] = "cpu"
    from ..api.load import load_policy

    _worker_policies = [load_policy(raw) for raw in policy_raws]


# env vars that make a freshly spawned interpreter claim accelerator
# state (the sandbox's sitecustomize registers a TPU PJRT backend when it
# sees these). Workers are pure-CPU oracle processes: they must never
# race the parent for the chip. The scrub happens in a per-worker
# launcher script — NOT by mutating the parent's os.environ, which other
# threads (e.g. a first jax backend init on the admission path) could
# read mid-window.
_ACCEL_ENV = ("PALLAS_AXON_POOL_IPS",)


def _make_worker_launcher() -> str:
    """Write a launcher that scrubs accelerator env and execs the real
    interpreter; ``multiprocessing.set_executable`` points spawns at it."""
    import stat
    import sys
    import tempfile

    lines = ["#!/bin/sh", "export JAX_PLATFORMS=cpu"]
    lines += [f"unset {key}" for key in _ACCEL_ENV]
    lines.append(f'exec "{sys.executable}" "$@"')
    fd, path = tempfile.mkstemp(prefix="ktpu-oracle-worker-", suffix=".sh")
    with os.fdopen(fd, "w") as f:
        f.write("\n".join(lines) + "\n")
    os.chmod(path, os.stat(path).st_mode | stat.S_IXUSR)
    return path


def _worker_evaluate(names: list[str], resource: dict, request: dict,
                     ns_labels: dict, roles: list, cluster_roles: list,
                     exclude_group_role: list):
    """Run the enforce oracle for the named policies in this worker.
    Returns [(policy_name, [(rule_name, status_value, message), ...])]."""
    from ..engine.context import Context
    from ..engine.match import AdmissionUserInfo, RequestInfo
    from ..engine.policy_context import PolicyContext
    from ..engine.validation import validate as oracle_validate

    ctx = Context()
    ctx.add_request(request)
    if resource:
        ctx.add_resource(resource)
    if request.get("oldObject"):
        ctx.add_old_resource(request["oldObject"])
    user_info = request.get("userInfo") or {}
    ctx.add_user_info({"roles": roles, "clusterRoles": cluster_roles,
                       "userInfo": user_info})
    username = user_info.get("username", "")
    if username:
        ctx.add_service_account(username)
    try:
        ctx.add_image_info(resource)
    except Exception:
        pass

    wanted = set(names)
    pctx = PolicyContext(
        new_resource=resource,
        old_resource=request.get("oldObject") or {},
        json_context=ctx, namespace_labels=ns_labels,
        exclude_group_role=exclude_group_role,
        admission_info=RequestInfo(
            roles=roles, cluster_roles=cluster_roles,
            admission_user_info=AdmissionUserInfo(
                username=username, uid=user_info.get("uid", ""),
                groups=user_info.get("groups") or [])),
    )
    out = []
    for policy in _worker_policies:
        if policy.name not in wanted:
            continue
        pctx.policy = policy
        resp = oracle_validate(pctx)
        out.append((policy.name,
                    [(r.name, r.status.value, r.message)
                     for r in resp.policy_response.rules]))
    return out


def pool_safe(policy) -> bool:
    """True when every rule of the policy evaluates without a cluster
    client: no context entries (ConfigMap/APICall loads) at the rule
    level OR inside foreach entries — validate foreach carries its own
    ``context:`` list loaded per-iteration (ForEach.context), and a
    worker has no client/resource_cache to serve it."""
    for rule in policy.spec.rules:
        if rule.context:
            return False
        for fe in list(rule.validation.foreach) + list(rule.mutation.foreach):
            if fe.context:
                return False
    return True


class OraclePool:
    """Process pool over the current enforce policy set."""

    def __init__(self, workers: int | None = None,
                 min_cores: int = MIN_CORES,
                 miss_threshold: int = 3, miss_cooldown_s: float = 30.0):
        cores = os.cpu_count() or 1
        self.enabled = cores >= min_cores
        self.workers = workers or max(2, min(8, cores - 1))
        self._pool: ProcessPoolExecutor | None = None
        self._generation = -1
        self._building: int | None = None
        self._lock = threading.Lock()
        self._ctx = multiprocessing.get_context("spawn")
        self._launcher: str | None = None
        self.hits = 0
        self.misses = 0
        # lane breaker: consecutive timeouts/errors take the lane out for
        # a cooldown instead of adding a flat timeout to every admission
        self.miss_threshold = miss_threshold
        self.miss_cooldown_s = miss_cooldown_s
        self._consecutive_misses = 0
        self._disabled_until = 0.0
        # backlog guard: abandoned (timed-out) tasks keep running in the
        # workers; don't queue more than the pool can plausibly drain
        self._inflight = 0

    # ------------------------------------------------------------ lifecycle

    def ensure(self, generation: int, policies: list) -> bool:
        """Make sure workers hold ``policies`` (by generation). Returns
        True when the pool is ready for that generation; a miss kicks a
        BACKGROUND rebuild and returns False — spawning workers costs
        seconds and must never block an admission request."""
        if not self.enabled:
            return False
        with self._lock:
            if self._pool is not None and self._generation == generation:
                return True
            if self._building is not None:
                return False
            self._building = generation
            raws = [p.raw for p in policies]

        def build():
            try:
                # workers spawn through the env-scrubbing launcher, so no
                # child can claim the parent's accelerator and the
                # parent's environment is never touched
                if self._launcher is None:
                    self._launcher = _make_worker_launcher()
                self._ctx.set_executable(self._launcher)
                pool = ProcessPoolExecutor(
                    max_workers=self.workers, mp_context=self._ctx,
                    initializer=_worker_init, initargs=(raws,))
                import concurrent.futures as cf

                warm = [pool.submit(_worker_ready)
                        for _ in range(self.workers)]
                cf.wait(warm, timeout=120)
            except Exception:
                with self._lock:
                    self._building = None
                return
            with self._lock:
                old, self._pool = self._pool, pool
                self._generation = generation
                self._building = None
            if old is not None:
                old.shutdown(wait=False, cancel_futures=True)

        threading.Thread(target=build, name="oracle-pool-build",
                         daemon=True).start()
        return False

    def ready(self, generation: int) -> bool:
        with self._lock:
            return self._pool is not None and self._generation == generation

    def evaluate(self, names: list[str], resource: dict, request: dict,
                 ns_labels: dict, roles: list, cluster_roles: list,
                 exclude_group_role: list, timeout_s: float = 3.0):
        """Submit one admission's enforce loop; returns the serialized
        results or None (caller falls back inline). Consecutive misses
        open a cooldown breaker; a broken executor (worker OOM-kill)
        drops the pool so ensure() rebuilds it."""
        import time

        with self._lock:
            pool = self._pool
            if (pool is None
                    or time.monotonic() < self._disabled_until
                    or self._inflight >= 2 * self.workers):
                return None
            self._inflight += 1
        broken = False
        try:
            fut = pool.submit(_worker_evaluate, names, resource, request,
                              ns_labels, roles, cluster_roles,
                              exclude_group_role)
            out = fut.result(timeout=timeout_s)
            with self._lock:
                self.hits += 1
                self._consecutive_misses = 0
            return out
        except Exception as e:
            fut = locals().get("fut")
            if fut is not None:
                fut.cancel()        # a queued (not yet running) task dies
            from concurrent.futures.process import BrokenProcessPool

            broken = isinstance(e, BrokenProcessPool)
            with self._lock:
                self.misses += 1
                self._consecutive_misses += 1
                if self._consecutive_misses >= self.miss_threshold:
                    self._disabled_until = (time.monotonic()
                                            + self.miss_cooldown_s)
                    self._consecutive_misses = 0
                if broken and self._pool is pool:
                    # executor is dead; next ensure() rebuilds
                    self._pool = None
                    self._generation = -1
            if broken:
                pool.shutdown(wait=False, cancel_futures=True)
            return None
        finally:
            with self._lock:
                self._inflight -= 1

    def evaluate_payload(self, names: list[str], resource: dict,
                         payload: dict | None, timeout_s: float = 3.0):
        """Host-lane fan-out entry (runtime/hostlane._pool_resolve):
        unpack an admission context payload — the
        models/engine._request_policy_context shape ``{"request",
        "namespace_labels", "roles", "cluster_roles",
        "exclude_group_role"}`` — into the worker call. Same
        None-on-miss contract as :meth:`evaluate`."""
        payload = payload or {}
        return self.evaluate(
            names, resource, payload.get("request") or {},
            payload.get("namespace_labels") or {},
            payload.get("roles") or [],
            payload.get("cluster_roles") or [],
            payload.get("exclude_group_role") or [],
            timeout_s=timeout_s)

    def stop(self) -> None:
        with self._lock:
            pool, self._pool = self._pool, None
        if pool is not None:
            pool.shutdown(wait=False, cancel_futures=True)


def _worker_ready() -> dict:
    """Warm-up no-op: forces worker spawn + module import + policy load.
    Returns the worker's accelerator-relevant env for test assertions."""
    import sys

    return {
        "policies": len(_worker_policies),
        "jax_platforms": os.environ.get("JAX_PLATFORMS"),
        "accel_env": {k: os.environ.get(k) for k in _ACCEL_ENV},
        "jax_loaded": "jax" in sys.modules,
    }
