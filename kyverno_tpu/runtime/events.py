"""Event generator: buffered, rate-limited emitter of Kubernetes Events.

Mirrors /root/reference/pkg/event/controller.go: a bounded queue (1000)
drained by worker threads that write Event objects through the client;
separate sources for policy-controller / admission / generate emitters.
"""

from __future__ import annotations

import time
from dataclasses import dataclass

from .workqueue import WorkerQueue

# event reasons (pkg/event/reason.go)
POLICY_VIOLATION = "PolicyViolation"
POLICY_APPLIED = "PolicyApplied"
POLICY_FAILED = "PolicyFailed"
POLICY_SKIPPED = "PolicySkipped"


@dataclass
class EventInfo:
    kind: str = ""
    name: str = ""
    namespace: str = ""
    reason: str = ""
    message: str = ""
    source: str = "kyverno-admission"


class EventGenerator:
    """controller.go:54 NewEventGenerator: Add() enqueues, workers drain."""

    def __init__(self, client, max_queued: int = 1000, workers: int = 3):
        self.client = client
        self._wq = WorkerQueue(self._emit, workers, name="event",
                               max_queued=max_queued)
        self.emitted = 0

    @property
    def dropped(self) -> int:
        return self._wq.dropped

    def add(self, *infos: EventInfo) -> None:
        d0 = self._wq.dropped
        for info in infos:
            if info.name:
                self._wq.add(info)
        dropped = self._wq.dropped - d0
        if dropped:
            try:
                from . import metrics as metrics_mod

                metrics_mod.record_events(metrics_mod.registry(),
                                          dropped=dropped)
            except Exception:
                pass

    def run(self) -> None:
        self._wq.run()

    def stop(self) -> None:
        self._wq.stop()

    def drain(self, timeout: float = 5.0) -> None:
        self._wq.drain(timeout)

    def _emit(self, info: EventInfo) -> None:
        event = {
            "apiVersion": "v1",
            "kind": "Event",
            "metadata": {
                "name": f"{info.name}.{int(time.time() * 1e6):x}",
                "namespace": info.namespace or "default",
            },
            "involvedObject": {
                "kind": info.kind,
                "name": info.name,
                "namespace": info.namespace,
            },
            "reason": info.reason,
            "message": info.message,
            "source": {"component": info.source},
            "type": "Warning" if info.reason == POLICY_VIOLATION else "Normal",
        }
        self.client.create_resource(event)
        self.emitted += 1
        try:
            from . import metrics as metrics_mod

            metrics_mod.record_events(metrics_mod.registry(), emitted=1)
        except Exception:
            pass


def events_for_engine_response(resp, generate_success_events: bool = False) -> list[EventInfo]:
    """pkg/event helpers: violations on the resource, applied on success."""
    from ..engine.response import RuleStatus

    out = []
    pr = resp.policy_response
    for rule in pr.rules:
        if rule.status is RuleStatus.FAIL:
            out.append(EventInfo(
                kind=pr.resource.kind, name=pr.resource.name,
                namespace=pr.resource.namespace, reason=POLICY_VIOLATION,
                message=f"policy {pr.policy.name}/{rule.name} fail: {rule.message}",
            ))
        elif rule.status is RuleStatus.PASS and generate_success_events:
            out.append(EventInfo(
                kind=pr.resource.kind, name=pr.resource.name,
                namespace=pr.resource.namespace, reason=POLICY_APPLIED,
                message=f"policy {pr.policy.name}/{rule.name} applied",
            ))
    return out
