"""Generate controller: consume GenerateRequest documents, materialize
dependent resources, keep them in sync.

Mirrors /root/reference/pkg/generate (generate_controller.go workqueue,
processGR generate.go:32, applyGenerate :114, status updates status.go) and
the cleanup controller's stale-GR GC (pkg/generate/cleanup).
"""

from __future__ import annotations

import time

from .client import ConflictError
from .workqueue import WorkerQueue

from ..engine.context import Context
from ..engine.generation import (
    MODE_CREATE,
    MODE_SKIP,
    MODE_UPDATE,
    GenerateError,
    apply_generate_rule,
)
from ..engine.match import matches_resource_description
from ..engine.policy_context import PolicyContext

GR_PENDING = "Pending"
GR_COMPLETED = "Completed"
GR_FAILED = "Failed"


class GenerateController:
    """generate_controller.go:76 NewController (workqueue, default 10
    workers at cmd/kyverno/main.go:80)."""

    def __init__(self, client, policies_by_name: dict, workers: int = 10):
        self.client = client
        self.policies = policies_by_name
        self._wq = WorkerQueue(self._handle, workers, name="generate")

    @property
    def queue(self):
        return self._wq.queue

    @property
    def processed(self) -> int:
        return self._wq.processed

    def _handle(self, gr: dict) -> None:
        try:
            self.process_gr(gr)
        except Exception as e:
            self._update_status(gr, GR_FAILED, str(e))

    # ------------------------------------------------------------ intake

    def enqueue(self, gr: dict) -> None:
        self._wq.add(gr)

    def sync_from_cluster(self) -> int:
        """Pick up pending GenerateRequests from the store."""
        n = 0
        for gr in self.client.list_resource("kyverno.io/v1", "GenerateRequest"):
            if ((gr.get("status") or {}).get("state")) == GR_PENDING:
                self.enqueue(gr)
                n += 1
        return n

    def watch_cluster(self) -> bool:
        """Event-driven intake: pending GenerateRequests enqueue straight
        off the watch stream (generaterequest informer in the reference's
        main.go wiring) — after the initial sync the controller never
        polls. Returns False when the client offers no watch transport."""
        def on_event(ev_type: str, gr: dict) -> None:
            if gr.get("kind") != "GenerateRequest":
                return
            if ev_type in ("ADDED", "MODIFIED") and (
                    (gr.get("status") or {}).get("state")) == GR_PENDING:
                self.enqueue(gr)

        def on_sync(items: list[dict]) -> None:
            # initial list and 410-triggered re-lists: GRs created before
            # the watch anchored arrive here, not as events
            for gr in items:
                if ((gr.get("status") or {}).get("state")) == GR_PENDING:
                    self.enqueue(gr)

        if hasattr(self.client, "ensure_informer"):
            self.client.ensure_informer("kyverno.io/v1", "GenerateRequest",
                                        on_event=on_event, on_sync=on_sync)
            return True
        if hasattr(self.client, "watch"):
            self.client.watch(on_event)
            return True
        return False

    # ------------------------------------------------------------ workers

    def run(self) -> None:
        self._wq.run()

    def stop(self) -> None:
        self._wq.stop()

    def drain(self, timeout: float = 5.0) -> None:
        self._wq.drain(timeout)

    # ------------------------------------------------------------ sync

    def process_gr(self, gr: dict) -> None:
        """generate.go:32 processGR -> applyGenerate."""
        spec = gr.get("spec") or {}
        policy = self.policies.get(spec.get("policy", ""))
        if policy is None:
            self._update_status(gr, GR_FAILED, "policy not found")
            return

        trigger_ref = spec.get("resource") or {}
        trigger = self.client.get_resource(
            trigger_ref.get("apiVersion", ""), trigger_ref.get("kind", ""),
            trigger_ref.get("namespace", ""), trigger_ref.get("name", ""),
        )
        if trigger is None:
            self._update_status(gr, GR_FAILED, "trigger resource not found")
            return

        jctx = Context()
        jctx.add_resource(trigger)
        user_info = ((spec.get("context") or {}).get("userInfo")) or {}
        if user_info:
            jctx.add_json({"request": {"userInfo": user_info}})
        pctx = PolicyContext(
            policy=policy, new_resource=trigger, client=self.client,
            json_context=jctx,
        )

        generated = []
        for rule in policy.spec.rules:
            if not rule.has_generate():
                continue
            ok, _ = matches_resource_description(
                trigger, rule, policy_namespace=policy.namespace)
            if not ok:
                continue
            try:
                resource, mode = apply_generate_rule(rule, pctx, trigger, self.client)
            except GenerateError as e:
                self._update_status(gr, GR_FAILED, str(e))
                return
            if mode == MODE_SKIP or resource is None:
                continue
            if mode == MODE_CREATE:
                try:
                    self.client.create_resource(resource)
                except ConflictError:
                    # AlreadyExists: another worker created it first — the
                    # reference falls through to update (generate.go applyRule)
                    self.client.update_resource(resource)
            elif mode == MODE_UPDATE:
                self.client.update_resource(resource)
            meta = resource.get("metadata") or {}
            generated.append({
                "kind": resource.get("kind", ""),
                "namespace": meta.get("namespace", ""),
                "name": meta.get("name", ""),
            })

        self._update_status(gr, GR_COMPLETED, "", generated)

    def synchronize(self) -> int:
        """generate_controller.go:221: re-run completed GRs whose rules have
        synchronize=true so downstream resources track their sources."""
        n = 0
        for gr in self.client.list_resource("kyverno.io/v1", "GenerateRequest"):
            if ((gr.get("status") or {}).get("state")) != GR_COMPLETED:
                continue
            policy = self.policies.get(((gr.get("spec") or {}).get("policy")) or "")
            if policy is None:
                continue
            if any(
                r.has_generate() and r.generation.synchronize
                for r in policy.spec.rules
            ):
                self.enqueue(gr)
                n += 1
        return n

    def cleanup_stale(self, max_age_s: float = 3600.0) -> int:
        """pkg/generate/cleanup: GC GenerateRequests stuck Failed longer
        than max_age_s (fresh failures keep their retry window)."""
        now = time.time()
        n = 0
        for gr in self.client.list_resource("kyverno.io/v1", "GenerateRequest"):
            status = gr.get("status") or {}
            if status.get("state") != GR_FAILED:
                continue
            failed_at = status.get("failedAt", 0)
            if now - failed_at < max_age_s:
                continue
            meta = gr.get("metadata") or {}
            self.client.delete_resource(
                "kyverno.io/v1", "GenerateRequest",
                meta.get("namespace", ""), meta.get("name", ""))
            n += 1
        return n

    def _update_status(self, gr: dict, state: str, message: str = "",
                       generated: list | None = None) -> None:
        """status.go: state transitions recorded on the GR document."""
        gr = dict(gr)
        gr["status"] = {"state": state}
        if state == GR_FAILED:
            gr["status"]["failedAt"] = time.time()
        if message:
            gr["status"]["message"] = message
        if generated:
            gr["status"]["generatedResources"] = generated
        self.client.update_resource(gr)
