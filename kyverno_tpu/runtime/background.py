"""Background scan: validate existing resources against the policy set.

Mirrors /root/reference/pkg/policy (processExistingResources,
existing.go:20) with the TPU twist: instead of the reference's serial
per-resource loop on 2 workers, the whole snapshot is flattened once and
scored as a policy x resource matrix on device (CompiledPolicySet), with
the CPU oracle lane for host-only rules — the mesh-scale replay of
BASELINE.md config [5]. Results feed the report pipeline.

Delta scanning (KTPU_INCREMENTAL, default on): the scanner persists the
verdict matrix between passes, keyed by (resource key) x (policy, rule).
A policy change re-evaluates only the changed segments' rule *columns*
against the memoized flatten rows (assembled as a sub-set over the same
append-only dictionary, so the rows splice unchanged); a resource watch
event re-evaluates only that dirty *row* against the full set. Everything
else is spliced from the persisted matrix, and only the affected
responses re-enter the report pipeline (ReportGenerator's freshest-wins
store merges them). ``KTPU_INCREMENTAL=0`` restores the full-rescan path
exactly.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field

import numpy as np

from ..engine.response import (
    EngineResponse,
    PolicyResponse,
    PolicySpecSummary,
    ResourceSpec,
    RuleResponse,
    RuleStatus,
    RuleType,
)
from ..models import CompiledPolicySet, Verdict
from . import tracing
from .reports import ReportGenerator

_VERDICT_TO_STATUS = {
    Verdict.PASS: RuleStatus.PASS,
    Verdict.FAIL: RuleStatus.FAIL,
    Verdict.SKIP: RuleStatus.SKIP,
    Verdict.ERROR: RuleStatus.ERROR,
}


@dataclass
class ScanResult:
    resources_scanned: int = 0
    rules_evaluated: int = 0
    violations: int = 0
    duration_s: float = 0.0
    responses: list[EngineResponse] = field(default_factory=list)
    # delta-pass accounting: what the incremental path actually evaluated
    # (a full pass leaves these at the trivial values)
    delta: bool = False
    cols_evaluated: int = 0
    rows_evaluated: int = 0


class ResourceManager:
    """existing.go:125 ResourceManager: TTL'd dedup of scanned resources."""

    def __init__(self, ttl_s: float = 3600.0):
        self.ttl_s = ttl_s
        self._seen: dict[str, float] = {}

    def process_resource(self, policy: str, kind: str, namespace: str,
                         name: str, rv: str) -> bool:
        key = f"{policy}/{kind}/{namespace}/{name}/{rv}"
        now = time.monotonic()
        stamp = self._seen.get(key)
        if stamp is not None and now - stamp < self.ttl_s:
            return False
        self._seen[key] = now
        return True

    def drop(self) -> None:
        self._seen.clear()


class BackgroundScanner:
    """PolicyController's scan half (policy_controller.go:119 + existing.go)."""

    def __init__(self, policies: list, client=None,
                 report_gen: ReportGenerator | None = None, mesh=None):
        self.client = client
        self.report_gen = report_gen
        if mesh is None:
            # mesh selection plumbing: KTPU_MESH_SHAPE picks the scan
            # geometry for callers that don't pass a mesh explicitly.
            # Unset (the default) keeps the historical single-device
            # path bit-for-bit — the env read gates the jax-importing
            # mesh build.
            from . import featureplane

            if featureplane.raw("KTPU_MESH_SHAPE").strip():
                from ..parallel.mesh import mesh_from_env

                mesh = mesh_from_env()
        self.mesh = mesh
        self.resource_manager = ResourceManager()
        from ..models.compiler import incremental_enabled
        self._inc = None
        if incremental_enabled():
            from ..models.engine import IncrementalCompiler

            self._inc = IncrementalCompiler()
        # 2D (policy, data) mesh: the policy-axis decomposition lives
        # here and refreshes with the population (models/engine)
        self._sharded = None
        # persisted scan state between passes (delta scanning): row keys
        # in scan order, resource bodies, flatten-row memos, and the
        # verdict matrix as per-(policy, rule) columns — column keying
        # survives rule-axis relayout across policy churn
        self._state: dict | None = None
        self._events: list[tuple[str, dict]] = []
        self.delta_stats = {"full_scans": 0, "delta_scans": 0,
                            "cols_evaluated": 0, "rows_evaluated": 0}
        self._obs = None
        # fleet fabric client (fleet/fabric.attach_stack); a policy
        # refresh that recompiles or drops segments purges the shared
        # tiers fleet-wide
        self._fabric = None
        self._apply_policies(policies)

    def serve_observability(self, host: str = "127.0.0.1",
                            port: int = 9464):
        """Start the standalone /metrics /healthz /debug/traces
        listener (runtime/obs_http.ObservabilityServer) — scanner-only
        processes have no webhook port to scrape. Port 0 picks a free
        port (read it back from the returned server's ``server_port``).
        Idempotent per scanner."""
        if self._obs is None:
            from .obs_http import ObservabilityServer
            from ..workload.dryrun import set_scan_source

            # a scanner exposing an obs port is the natural dry-run
            # corpus: POST /debug/dryrun evaluates against its state
            set_scan_source(self)
            self._obs = ObservabilityServer(host=host, port=port)
            self._obs.start()
        return self._obs

    def stop_observability(self) -> None:
        if self._obs is not None:
            self._obs.stop()
            self._obs = None

    # -------------------------------------------------------- policy feed

    def _apply_policies(self, policies: list) -> dict:
        self.policies = [p for p in policies if p.spec.background]
        if self._mesh_is_2d():
            from ..models.engine import ShardedPolicySet
            from ..parallel.mesh import policy_axis_size

            if self._sharded is None:
                # reuse the scanner's IncrementalCompiler so the full
                # set and the shard slices share one segment cache
                self._sharded = ShardedPolicySet(
                    policy_axis_size(self.mesh), compiler=self._inc)
            self._sharded.refresh(self.policies)
            self.cps = self._sharded.full
            info = dict(self._sharded.compiler.last_refresh)
            info["shards"] = dict(self._sharded.last_refresh)
            return info
        if self._inc is not None:
            self.cps = self._inc.refresh(self.policies)
            return self._inc.last_refresh
        self.cps = CompiledPolicySet(self.policies)
        return {}

    def _mesh_is_2d(self) -> bool:
        if self.mesh is None:
            return False
        from ..parallel.mesh import is_2d

        return is_2d(self.mesh)

    def update_policies(self, policies: list) -> dict:
        """Replace the scanned policy set. With incremental compilation
        only segments whose policy object changed recompile; the refresh
        summary (recompiled/dropped keys) seeds the next delta pass —
        and, with a fabric attached, drives fleet-wide invalidation of
        the shared tiers (a pure-reuse refresh purges nothing)."""
        refresh = self._apply_policies(policies)
        if self._fabric is not None:
            from ..fleet import fabric as fabric_mod

            fabric_mod.publish_refresh(self._fabric, refresh)
        return refresh

    def note_resource(self, event: str, resource: dict) -> None:
        """Resource watch feed: the row goes dirty for the next delta
        pass (DELETED rows are dropped from the matrix)."""
        self._events.append((event, resource))

    @staticmethod
    def _res_key(resource: dict) -> tuple:
        meta = resource.get("metadata") or {}
        return (resource.get("kind", ""), meta.get("namespace", ""),
                meta.get("name", ""))

    def kinds(self) -> list[str]:
        out: list[str] = []
        for ir in self.cps.rule_irs:
            for kind in ir.kinds:
                bare = kind.split("/")[-1]
                if bare not in out:
                    out.append(bare)
        return out

    def snapshot(self) -> list[dict]:
        """getResourcesPerNamespace via the client (existing.go:214)."""
        if self.client is None:
            return []
        resources = []
        for kind in self.kinds():
            if kind == "*":
                continue
            resources.extend(self.client.list_resource("", kind))
        return resources

    # --------------------------------------------------------- full scan

    def scan(self, resources: list[dict] | None = None) -> ScanResult:
        rec = tracing.recorder()
        tr = rec.start("scan")
        tok = tracing.bind(tr) if tr is not None else None
        try:
            return self._scan(resources, rec, tr)
        finally:
            if tok is not None:
                tracing.unbind(tok)
            rec.finish(tr)

    def _scan(self, resources, rec, tr) -> ScanResult:
        start = time.monotonic()
        resources = resources if resources is not None else self.snapshot()
        if tr is not None:
            tr.labels["resources"] = len(resources)
        result = ScanResult(resources_scanned=len(resources))
        self.delta_stats["full_scans"] += 1
        # a full pass supersedes any pending row dirt
        self._events.clear()
        if not resources:
            if self._inc is not None and self.mesh is None:
                self._state = {"keys": [], "resources": {}, "memos": {},
                               "cols": {}}
            return result

        memos = None
        e0 = time.perf_counter()
        if self.mesh is not None:
            from ..parallel import sharded_scan

            # a 2D mesh scans the policy-axis decomposition (per-shard
            # tensors); the 1D mesh keeps the replicated full set
            src = self._sharded if self._sharded is not None else self.cps
            verdicts, _, _ = sharded_scan(src, resources, self.mesh)
            scan_lane = "mesh"
        elif self._inc is not None:
            # flatten chunk-wise and keep the split rows: the same single
            # flatten both scores this pass and seeds the delta state
            verdicts, memos = self._scan_rows(resources)
            scan_lane = "incremental"
        else:
            from ..models.flatten import pipeline_enabled
            from ..parallel.mesh import DEFAULT_CHUNK

            if len(resources) <= DEFAULT_CHUNK:
                verdicts = self.cps.evaluate(resources)
                scan_lane = "single"
            elif pipeline_enabled():
                # scan-chunk prefetch: flatten chunk k+1 while the device
                # scores chunk k (KTPU_FLATTEN_PIPELINE=0 falls back to
                # the serial chunk loop below)
                verdicts = self.cps.evaluate_pipelined(resources,
                                                      chunk=DEFAULT_CHUNK)
                scan_lane = "pipelined"
            else:
                # chunk huge snapshots so flatten memory stays bounded
                import numpy as _np

                verdicts = _np.concatenate([
                    self.cps.evaluate(resources[i:i + DEFAULT_CHUNK])
                    for i in range(0, len(resources), DEFAULT_CHUNK)])
                scan_lane = "serial_chunks"
        rec.add_span(tr, "scan_evaluate", e0, time.perf_counter(),
                     lane=scan_lane, rows=len(resources))

        r0 = time.perf_counter()
        for b, resource in enumerate(resources):
            per_policy = self._row_responses(
                resource, lambda ref, b=b: verdicts[b, ref.rule_index],
                self.cps.rule_refs, result)
            result.responses.extend(per_policy.values())
        rec.add_span(tr, "scan_responses", r0, time.perf_counter(),
                     violations=result.violations)

        if memos is not None:
            keys = [self._res_key(r) for r in resources]
            self._state = {
                "keys": keys,
                "resources": dict(zip(keys, resources)),
                "memos": memos,
                "cols": {(ref.policy.name, ref.rule.name):
                         np.asarray(verdicts)[:, ref.rule_index].astype(
                             np.int8)
                         for ref in self.cps.rule_refs},
            }

        if self.report_gen is not None:
            self.report_gen.add(*result.responses)
        result.duration_s = time.monotonic() - start
        return result

    def _scan_rows(self, resources: list[dict]):
        """Chunked flatten + device eval that also returns the split
        flatten rows as epoch-stamped memos (one flatten serves both).

        Host-lane cells resolve per chunk — prefetch dispatched before
        the blocking device eval, memoized post-pass after — so the
        incremental scan reports precondition/variable rules exactly
        like the full-scan paths instead of dropping them, and repeat
        scans of unchanged bodies answer from the host-verdict memo."""
        from ..models.flatten import MemoRow, split_packed_rows
        from ..parallel.mesh import DEFAULT_CHUNK
        from .hostlane import resolver

        tensors = self.cps.tensors
        has_host = bool(np.asarray(
            tensors.rule_host_only[:tensors.n_rules_live]).any())
        chunks = []
        memos: dict[tuple, object] = {}
        for i in range(0, len(resources), DEFAULT_CHUNK):
            chunk = resources[i:i + DEFAULT_CHUNK]
            batch = self.cps.flatten_packed(chunk)
            pf = resolver().prefetch(self.cps, chunk) if has_host else None
            v = np.asarray(self.cps.evaluate_device(batch))
            if pf is not None or (v == int(Verdict.HOST)).any():
                v = self.cps.resolve_host_cells(chunk, v, prefetch=pf)
            chunks.append(v)
            for r, row in zip(chunk, split_packed_rows(batch)):
                memos[self._res_key(r)] = MemoRow(
                    row=row, n_paths=tensors.n_paths,
                    epoch=tensors.dict_epoch)
        return np.concatenate(chunks), memos

    def _row_responses(self, resource: dict, verdict_of, rule_refs,
                       result: ScanResult,
                       policy_filter: set | None = None) -> dict:
        """One resource's per-policy EngineResponses (the response shape
        both the full and the delta pass emit, so report rows merge)."""
        meta = resource.get("metadata") or {}
        per_policy: dict[str, EngineResponse] = {}
        for ref in rule_refs:
            if policy_filter is not None and \
                    ref.policy.name not in policy_filter:
                continue
            verdict = Verdict(verdict_of(ref))
            if verdict is Verdict.NOT_APPLICABLE:
                continue
            status = _VERDICT_TO_STATUS.get(verdict)
            if status is None:
                continue
            result.rules_evaluated += 1
            if status is RuleStatus.FAIL:
                result.violations += 1
            resp = per_policy.get(ref.policy.name)
            if resp is None:
                resp = EngineResponse(policy_response=PolicyResponse(
                    policy=PolicySpecSummary(name=ref.policy.name),
                    resource=ResourceSpec(
                        kind=resource.get("kind", ""),
                        api_version=resource.get("apiVersion", ""),
                        namespace=meta.get("namespace", ""),
                        name=meta.get("name", ""),
                    ),
                ))
                per_policy[ref.policy.name] = resp
            resp.policy_response.rules.append(RuleResponse(
                name=ref.rule.name, type=RuleType.VALIDATION, status=status,
                message=f"validation rule '{ref.rule.name}' "
                        f"{'passed' if status is RuleStatus.PASS else status.value}",
            ))
        return per_policy

    # -------------------------------------------------------- delta scan

    def delta_scan(self, policies: list | None = None) -> ScanResult:
        """Incremental pass: apply any policy update, then re-evaluate
        only (a) the changed/added policies' rule columns against the
        memoized flatten rows and (b) the rows dirtied by resource watch
        events against the full set, splicing both into the persisted
        verdict matrix. Emits responses only for the affected
        (resource, policy) pairs. Falls back to :meth:`scan` when
        incremental compilation is off, under a mesh, or before any full
        pass has seeded the state."""
        refresh = self.update_policies(policies) if policies is not None \
            else {}
        if self._inc is None or self._state is None or \
                self.mesh is not None:
            return self.scan()
        rec = tracing.recorder()
        tr = rec.start("delta_scan")
        tok = tracing.bind(tr) if tr is not None else None
        try:
            result = self._delta_scan_seeded(refresh, rec, tr)
            if tr is not None:
                tr.labels.update(cols=result.cols_evaluated,
                                 rows=result.rows_evaluated)
            return result
        finally:
            if tok is not None:
                tracing.unbind(tok)
            rec.finish(tr)

    def _delta_scan_seeded(self, refresh: dict, rec, tr) -> ScanResult:
        start = time.monotonic()
        state = self._state
        result = ScanResult(delta=True)
        self.delta_stats["delta_scans"] += 1

        current_names = {p.name for p in self.policies}
        new_cols = {(ref.policy.name, ref.rule.name)
                    for ref in self.cps.rule_refs}

        # ---- policy-side dirt: recompiled segments + columns the matrix
        # has never seen (fresh policies, first delta after fallback)
        changed_keys = set(refresh.get("recompiled_keys", []))
        changed_policies = []
        for p in self.policies:
            key = self._inc._policy_key(p)
            missing = any(ck not in state["cols"] for ck in new_cols
                          if ck[0] == p.name)
            if key in changed_keys or missing:
                changed_policies.append(p)
        changed_names = {p.name for p in changed_policies}

        # ---- resource-side dirt: consume watch events
        events, self._events = self._events, []
        dirty: list[tuple] = []
        for event, resource in events:
            key = self._res_key(resource)
            if event == "DELETED":
                if key in state["resources"]:
                    idx = state["keys"].index(key)
                    state["keys"].pop(idx)
                    state["resources"].pop(key, None)
                    state["memos"].pop(key, None)
                    for ck in state["cols"]:
                        state["cols"][ck] = np.delete(state["cols"][ck],
                                                      idx)
                    if self.report_gen is not None:
                        self.report_gen.prune_resource(key[0], key[1],
                                                       key[2])
                if key in dirty:
                    dirty.remove(key)
                continue
            if key not in state["resources"]:
                state["keys"].append(key)
                for ck in state["cols"]:
                    state["cols"][ck] = np.append(
                        state["cols"][ck],
                        np.int8(Verdict.NOT_APPLICABLE))
            state["resources"][key] = resource
            # content changed: the memo row is for the old body
            state["memos"].pop(key, None)
            if key not in dirty:
                dirty.append(key)

        # ---- column pass: changed policies x all memoized rows, over a
        # sub-set assembled from the same dictionary (rows splice as-is)
        if changed_policies and state["keys"]:
            from ..models.flatten import (MemoRow, flatten_one_row,
                                          refresh_packed_row,
                                          splice_packed_rows)

            c0 = time.perf_counter()
            sub = self._inc.subset(changed_policies)
            rows = []
            for key in state["keys"]:
                resource = state["resources"][key]
                memo = state["memos"].get(key)
                refreshed = None
                if memo is not None:
                    refreshed, _ = refresh_packed_row(memo, resource,
                                                      sub.tensors)
                if refreshed is None:
                    refreshed = MemoRow(
                        row=flatten_one_row(resource, sub.tensors),
                        n_paths=sub.tensors.n_paths,
                        epoch=sub.tensors.dict_epoch)
                state["memos"][key] = refreshed
                rows.append(refreshed.row)
            v = np.asarray(sub.evaluate_device(splice_packed_rows(rows)))
            if (v == int(Verdict.HOST)).any():
                # column-pass host cells: resolved (memoized) before the
                # verdicts persist, so the delta matrix stays comparable
                # with the full-scan matrix bit for bit
                bodies = [state["resources"][k] for k in state["keys"]]
                v = sub.resolve_host_cells(bodies, v)
            for ref in sub.rule_refs:
                state["cols"][(ref.policy.name, ref.rule.name)] = \
                    v[:, ref.rule_index].astype(np.int8)
                result.cols_evaluated += 1
            rec.add_span(tr, "column_pass", c0, time.perf_counter(),
                         cols=result.cols_evaluated,
                         policies=len(changed_policies))

        # ---- drop columns of removed policies / removed rules
        for ck in list(state["cols"]):
            if ck in new_cols:
                continue
            if ck[0] not in current_names or ck[0] in changed_names:
                del state["cols"][ck]
        for key in refresh.get("dropped_keys", []):
            if self.report_gen is not None:
                self.report_gen.prune_policy(key.split("/")[-1])

        # ---- row pass: dirty resources x the full set
        dirty = [k for k in dirty if k in state["resources"]]
        if dirty:
            from ..models.flatten import MemoRow, split_packed_rows

            w0 = time.perf_counter()
            tensors = self.cps.tensors
            bodies = [state["resources"][k] for k in dirty]
            batch = self.cps.flatten_packed(bodies)
            v = np.asarray(self.cps.evaluate_device(batch))
            if (v == int(Verdict.HOST)).any():
                v = self.cps.resolve_host_cells(bodies, v)
            split = split_packed_rows(batch)
            for j, key in enumerate(dirty):
                idx = state["keys"].index(key)
                for ref in self.cps.rule_refs:
                    state["cols"][(ref.policy.name, ref.rule.name)][idx] = \
                        np.int8(v[j, ref.rule_index])
                state["memos"][key] = MemoRow(
                    row=split[j], n_paths=tensors.n_paths,
                    epoch=tensors.dict_epoch)
                result.rows_evaluated += 1
            rec.add_span(tr, "row_pass", w0, time.perf_counter(),
                         rows=result.rows_evaluated)

        # ---- emit only the affected (resource, policy) responses; the
        # report store's freshest-wins merge keeps everything else
        dirty_set = set(dirty)
        refs = self.cps.rule_refs
        for key in state["keys"]:
            names = (current_names if key in dirty_set
                     else changed_names)
            if not names:
                continue
            idx = state["keys"].index(key)
            per_policy = self._row_responses(
                state["resources"][key],
                lambda ref, idx=idx: state["cols"][
                    (ref.policy.name, ref.rule.name)][idx],
                refs, result, policy_filter=names)
            result.responses.extend(per_policy.values())

        result.resources_scanned = len(state["keys"])
        self.delta_stats["cols_evaluated"] += result.cols_evaluated
        self.delta_stats["rows_evaluated"] += result.rows_evaluated
        if self.report_gen is not None:
            self.report_gen.add(*result.responses)
        result.duration_s = time.monotonic() - start
        return result

    def state_fingerprint(self) -> str:
        """Digest of the persisted scan state: row keys in order, body
        digests, every verdict column byte-for-byte, pending events and
        the segment-cache keys of the incremental compiler. A dry-run
        (isolated candidate compile + copy-resolved evaluation) must
        leave this identical — the quiescent probe in replay_smoke
        asserts exactly that."""
        import hashlib
        import json as _json

        h = hashlib.sha256()
        if self._state is not None:
            state = self._state
            for key in state["keys"]:
                h.update(repr(key).encode())
                body = state["resources"].get(key)
                h.update(hashlib.sha256(
                    _json.dumps(body, sort_keys=True,
                                default=str).encode()).digest())
            for ck in sorted(state["cols"]):
                h.update(repr(ck).encode())
                h.update(np.ascontiguousarray(state["cols"][ck]).tobytes())
        h.update(str(len(self._events)).encode())
        if self._inc is not None:
            h.update(repr(sorted(self._inc._segments)).encode())
        return h.hexdigest()[:16]

    def verdict_matrix(self):
        """(row keys, column keys, matrix) snapshot of the persisted scan
        state — the parity surface the delta-vs-full property tests
        compare bit-for-bit. None before any full pass."""
        if self._state is None:
            return None
        state = self._state
        ckeys = sorted(state["cols"])
        n = len(state["keys"])
        if ckeys:
            mat = np.stack([state["cols"][c] for c in ckeys], axis=1)
        else:
            mat = np.zeros((n, 0), dtype=np.int8)
        return list(state["keys"]), ckeys, mat
