"""Background scan: validate existing resources against the policy set.

Mirrors /root/reference/pkg/policy (processExistingResources,
existing.go:20) with the TPU twist: instead of the reference's serial
per-resource loop on 2 workers, the whole snapshot is flattened once and
scored as a policy x resource matrix on device (CompiledPolicySet), with
the CPU oracle lane for host-only rules — the mesh-scale replay of
BASELINE.md config [5]. Results feed the report pipeline.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field

from ..engine.response import (
    EngineResponse,
    PolicyResponse,
    PolicySpecSummary,
    ResourceSpec,
    RuleResponse,
    RuleStatus,
    RuleType,
)
from ..models import CompiledPolicySet, Verdict
from .reports import ReportGenerator

_VERDICT_TO_STATUS = {
    Verdict.PASS: RuleStatus.PASS,
    Verdict.FAIL: RuleStatus.FAIL,
    Verdict.SKIP: RuleStatus.SKIP,
    Verdict.ERROR: RuleStatus.ERROR,
}


@dataclass
class ScanResult:
    resources_scanned: int = 0
    rules_evaluated: int = 0
    violations: int = 0
    duration_s: float = 0.0
    responses: list[EngineResponse] = field(default_factory=list)


class ResourceManager:
    """existing.go:125 ResourceManager: TTL'd dedup of scanned resources."""

    def __init__(self, ttl_s: float = 3600.0):
        self.ttl_s = ttl_s
        self._seen: dict[str, float] = {}

    def process_resource(self, policy: str, kind: str, namespace: str,
                         name: str, rv: str) -> bool:
        key = f"{policy}/{kind}/{namespace}/{name}/{rv}"
        now = time.monotonic()
        stamp = self._seen.get(key)
        if stamp is not None and now - stamp < self.ttl_s:
            return False
        self._seen[key] = now
        return True

    def drop(self) -> None:
        self._seen.clear()


class BackgroundScanner:
    """PolicyController's scan half (policy_controller.go:119 + existing.go)."""

    def __init__(self, policies: list, client=None,
                 report_gen: ReportGenerator | None = None, mesh=None):
        self.policies = [p for p in policies if p.spec.background]
        self.client = client
        self.report_gen = report_gen
        self.mesh = mesh
        self.resource_manager = ResourceManager()
        self.cps = CompiledPolicySet(self.policies)

    def kinds(self) -> list[str]:
        out: list[str] = []
        for ir in self.cps.rule_irs:
            for kind in ir.kinds:
                bare = kind.split("/")[-1]
                if bare not in out:
                    out.append(bare)
        return out

    def snapshot(self) -> list[dict]:
        """getResourcesPerNamespace via the client (existing.go:214)."""
        if self.client is None:
            return []
        resources = []
        for kind in self.kinds():
            if kind == "*":
                continue
            resources.extend(self.client.list_resource("", kind))
        return resources

    def scan(self, resources: list[dict] | None = None) -> ScanResult:
        start = time.monotonic()
        resources = resources if resources is not None else self.snapshot()
        result = ScanResult(resources_scanned=len(resources))
        if not resources:
            return result

        if self.mesh is not None:
            from ..parallel import sharded_scan

            verdicts, _, _ = sharded_scan(self.cps, resources, self.mesh)
        else:
            from ..models.flatten import pipeline_enabled
            from ..parallel.mesh import DEFAULT_CHUNK

            if len(resources) <= DEFAULT_CHUNK:
                verdicts = self.cps.evaluate(resources)
            elif pipeline_enabled():
                # scan-chunk prefetch: flatten chunk k+1 while the device
                # scores chunk k (KTPU_FLATTEN_PIPELINE=0 falls back to
                # the serial chunk loop below)
                verdicts = self.cps.evaluate_pipelined(resources,
                                                       chunk=DEFAULT_CHUNK)
            else:
                # chunk huge snapshots so flatten memory stays bounded
                import numpy as _np

                verdicts = _np.concatenate([
                    self.cps.evaluate(resources[i:i + DEFAULT_CHUNK])
                    for i in range(0, len(resources), DEFAULT_CHUNK)])

        for b, resource in enumerate(resources):
            meta = resource.get("metadata") or {}
            per_policy: dict[str, EngineResponse] = {}
            for ref in self.cps.rule_refs:
                verdict = Verdict(verdicts[b, ref.rule_index])
                if verdict is Verdict.NOT_APPLICABLE:
                    continue
                status = _VERDICT_TO_STATUS.get(verdict)
                if status is None:
                    continue
                result.rules_evaluated += 1
                if status is RuleStatus.FAIL:
                    result.violations += 1
                resp = per_policy.get(ref.policy.name)
                if resp is None:
                    resp = EngineResponse(policy_response=PolicyResponse(
                        policy=PolicySpecSummary(name=ref.policy.name),
                        resource=ResourceSpec(
                            kind=resource.get("kind", ""),
                            api_version=resource.get("apiVersion", ""),
                            namespace=meta.get("namespace", ""),
                            name=meta.get("name", ""),
                        ),
                    ))
                    per_policy[ref.policy.name] = resp
                resp.policy_response.rules.append(RuleResponse(
                    name=ref.rule.name, type=RuleType.VALIDATION, status=status,
                    message=f"validation rule '{ref.rule.name}' "
                            f"{'passed' if status is RuleStatus.PASS else status.value}",
                ))
            result.responses.extend(per_policy.values())

        if self.report_gen is not None:
            self.report_gen.add(*result.responses)
        result.duration_s = time.monotonic() - start
        return result
