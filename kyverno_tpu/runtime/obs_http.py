"""Shared observability HTTP surface: /metrics, /healthz, /debug/traces.

One routing function serves both front doors — the admission webhook
(runtime/webhook.py mounts it inside its existing handler, so the
kube-apiserver-facing port also answers scrapes) and a standalone
:class:`ObservabilityServer` for processes with no webhook listener
(the background scanner). Endpoints:

``/metrics``
    Prometheus text 0.0.4 exposition from the metrics registry —
    including the ``kyverno_stage_duration_seconds`` bucket histograms
    the trace recorder feeds, so per-stage p50/p99 are scrapeable.
``/healthz``
    JSON liveness snapshot: build version, trace-recorder counters,
    uptime.
``/debug/traces``
    Flight-recorder dump (JSON). Query params: ``n`` (max traces,
    default 32), ``slowest=1`` (the K-slowest set instead of the
    newest), ``format=chrome`` (Chrome ``trace_event`` JSON for
    chrome://tracing / Perfetto instead of the plain schema).
"""

from __future__ import annotations

import json
import re
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

from . import metrics as metrics_mod
from . import tracing

_started_at = time.time()


def handle_obs_get(path: str, registry=None):
    """Route one GET. Returns ``(status, body_bytes, content_type)`` or
    ``None`` when ``path`` is not an observability endpoint (the caller
    falls through to its own routes / 404)."""
    parsed = urlparse(path)
    # normalize: collapse duplicate slashes ("//healthz" is a classic
    # reverse-proxy artifact) and drop trailing ones before matching.
    # Work from the raw request target, not parsed.path — urlparse
    # reads a leading "//" as an authority and empties the path.
    raw = path.split("?", 1)[0].split("#", 1)[0]
    route = re.sub(r"/{2,}", "/", raw).rstrip("/") or "/"
    if route == "/metrics":
        # settle the recorder's deferred histogram feed before exposing
        tracing.recorder().feed_metrics()
        reg = registry if registry is not None else metrics_mod.registry()
        return 200, reg.expose().encode(), "text/plain; version=0.0.4"
    if route == "/healthz":
        rec = tracing.recorder()
        rec.feed_metrics()
        body = json.dumps({
            "status": "ok",
            "uptime_s": round(time.time() - _started_at, 3),
            "tracing_enabled": tracing.trace_enabled(),
            "traces": dict(rec.stats),
            "lanes": tracing.killswitch_lanes(),
        }).encode()
        return 200, body, "application/json"
    if route == "/debug/traces":
        q = parse_qs(parsed.query)

        def _qint(name: str, default: int) -> int:
            try:
                return max(0, int(q[name][0]))
            except (KeyError, IndexError, ValueError):
                return default

        n = _qint("n", 32)
        slowest = q.get("slowest", ["0"])[0] not in ("0", "", "false")
        rec = tracing.recorder()
        if q.get("format", [""])[0] == "chrome":
            payload = rec.chrome_trace(n, slowest=slowest)
        else:
            payload = {"enabled": tracing.trace_enabled(),
                       "slowest": slowest,
                       "stats": dict(rec.stats),
                       "traces": rec.export(n, slowest=slowest)}
        return 200, json.dumps(payload).encode(), "application/json"
    return None


class ObservabilityServer:
    """Standalone /metrics /healthz /debug/traces listener for
    processes that don't run the webhook server (background scanner,
    bench drivers). Port 0 picks a free port; read it back from
    ``server_port`` after :meth:`start`."""

    def __init__(self, registry=None, host: str = "127.0.0.1",
                 port: int = 9464):
        self.registry = registry
        self.host = host
        self.port = port
        self._httpd: ThreadingHTTPServer | None = None
        self._thread: threading.Thread | None = None

    @property
    def server_port(self) -> int | None:
        return self._httpd.server_address[1] if self._httpd else None

    def start(self) -> ThreadingHTTPServer:
        outer = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *args):
                pass

            def do_GET(self):
                out = handle_obs_get(self.path, outer.registry)
                if out is None:
                    out = (404, b"not found", "text/plain")
                status, body, ctype = out
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        class Httpd(ThreadingHTTPServer):
            daemon_threads = True

        self._httpd = Httpd((self.host, self.port), Handler)
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True,
                                        name="ktpu-obs-http")
        self._thread.start()
        return self._httpd

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
