"""Shared observability HTTP surface: /metrics, /healthz, /debug/traces.

One routing function serves both front doors — the admission webhook
(runtime/webhook.py mounts it inside its existing handler, so the
kube-apiserver-facing port also answers scrapes) and a standalone
:class:`ObservabilityServer` for processes with no webhook listener
(the background scanner). Endpoints:

``/metrics``
    Prometheus text 0.0.4 exposition from the metrics registry —
    including the ``kyverno_stage_duration_seconds`` bucket histograms
    the trace recorder feeds, so per-stage p50/p99 are scrapeable.
``/healthz``
    JSON liveness snapshot: ``ok``/``degraded`` status (the SLO
    watchdog's verdict), trace-recorder counters, the kill-switch lane
    matrix, stream-plane state (open streams, inflight batch fill,
    continuous flag), and the SLO burn-rate snapshot.
``/debug/traces``
    Flight-recorder dump (JSON). Query params: ``n`` (max traces,
    default 32), ``slowest=1`` (the K-slowest set instead of the
    newest), ``format=chrome`` (Chrome ``trace_event`` JSON for
    chrome://tracing / Perfetto instead of the plain schema).
``/debug/policies``
    Per-policy attribution snapshot: labelled top-K (policy, rule)
    pairs with verdict breakdowns, the exact-total overflow tail, and
    per-tenant rollups. ``n`` caps the pair rows.
``/debug/profile``
    On-demand device profiling: paramless GET = capture status plus a
    device-memory snapshot; ``?seconds=N`` starts a bounded
    jax.profiler window capture (409 while one is running).
``/debug/dryrun``
    Policy-rollout dry-run (workload/dryrun.py): GET = service status,
    POST ``{"policy": <ClusterPolicy doc>}`` = blast-radius report for
    the candidate against the registered scan corpus, with zero live
    impact. 403 while KTPU_DRYRUN=0.
"""

from __future__ import annotations

import json
import re
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

from . import featureplane
from . import metrics as metrics_mod
from . import tracing

_started_at = time.time()

# version stamp on the /debug/* JSON payloads — replay-manifest diffing
# across PRs keys on it instead of sniffing the layout
DEBUG_SCHEMA_VERSION = 1


def _stream_enabled() -> bool:
    """Continuous-batching lane flag, without importing batch at module
    load (obs_http must stay importable from anything)."""
    try:
        from .batch import stream_enabled

        return stream_enabled()
    except Exception:
        return False


def handle_obs_get(path: str, registry=None):
    """Route one GET. Returns ``(status, body_bytes, content_type)`` or
    ``None`` when ``path`` is not an observability endpoint (the caller
    falls through to its own routes / 404)."""
    parsed = urlparse(path)
    # normalize: collapse duplicate slashes ("//healthz" is a classic
    # reverse-proxy artifact) and drop trailing ones before matching.
    # Work from the raw request target, not parsed.path — urlparse
    # reads a leading "//" as an authority and empties the path.
    raw = path.split("?", 1)[0].split("#", 1)[0]
    route = re.sub(r"/{2,}", "/", raw).rstrip("/") or "/"
    if route == "/metrics":
        # settle the recorder's deferred histogram feed before exposing
        tracing.recorder().feed_metrics()
        reg = registry if registry is not None else metrics_mod.registry()
        return 200, reg.expose().encode(), "text/plain; version=0.0.4"
    if route == "/healthz":
        rec = tracing.recorder()
        rec.feed_metrics()
        reg = registry if registry is not None else metrics_mod.registry()
        from . import sloactions
        from .slo import watchdog

        slo = watchdog().snapshot()
        # degradation controller: a scrape doubles as a tick so the
        # state machine (and the state-seconds counter) advances even
        # on an idle replica; report() carries the action ladder, the
        # explicit shed set, and the replica scale hint
        try:
            ctl = sloactions.controller()
            ctl.maybe_tick()
            slo_actions = ctl.report()
        except Exception:
            slo_actions = {"enabled": False, "state": "unknown"}
        body = json.dumps({
            "status": "degraded" if slo.get("degraded") else "ok",
            "uptime_s": round(time.time() - _started_at, 3),
            "tracing_enabled": tracing.trace_enabled(),
            "traces": dict(rec.stats),
            "lanes": tracing.killswitch_lanes(),
            # PR 7 stream-plane fill state, next to the lane matrix
            "streams": {
                "open_streams": int(reg.gauge_value(
                    "kyverno_stream_open_streams") or 0),
                "inflight_batch_fill": reg.gauge_value(
                    "kyverno_stream_inflight_batch_fill") or 0.0,
                "continuous": _stream_enabled(),
            },
            "slo": slo,
            "slo_actions": slo_actions,
            # scan-plane mesh geometry (PR 14): selected axes, device
            # inventory, per-shard rule distribution
            "mesh": metrics_mod.mesh_geometry_snapshot(),
            # fleet plane (PR 15): fabric hub/client counters and scan
            # partition coordinator state
            "fleet": metrics_mod.fleet_snapshot(),
        }).encode()
        return 200, body, "application/json"
    if route == "/debug/policies":
        q = parse_qs(parsed.query)
        try:
            limit = max(0, int(q.get("n", ["0"])[0]))
        except ValueError:
            limit = 0
        payload = metrics_mod.attribution_snapshot(limit=limit)
        payload["schema_version"] = DEBUG_SCHEMA_VERSION
        payload["attrib_enabled"] = tracing.attrib_enabled()
        reg = registry if registry is not None else metrics_mod.registry()
        payload.update(metrics_mod.lint_findings_snapshot(reg))
        return 200, json.dumps(payload).encode(), "application/json"
    if route == "/debug/profile":
        from . import profiling

        q = parse_qs(parsed.query)
        svc = profiling.capture_service()
        seconds_arg = q.get("seconds", [None])[0]
        if seconds_arg is None:
            payload = {"status": "idle", **svc.status(),
                       "device_memory": profiling.device_memory_snapshot()}
            return 200, json.dumps(payload).encode(), "application/json"
        try:
            seconds = float(seconds_arg)
        except ValueError:
            return (400, json.dumps({"error": "seconds must be a "
                                     "number"}).encode(),
                    "application/json")
        out = svc.start(seconds)
        status = 409 if out.get("status") == "busy" else 200
        return status, json.dumps(out).encode(), "application/json"
    if route == "/debug/traces":
        q = parse_qs(parsed.query)

        def _qint(name: str, default: int) -> int:
            try:
                return max(0, int(q[name][0]))
            except (KeyError, IndexError, ValueError):
                return default

        n = _qint("n", 32)
        slowest = q.get("slowest", ["0"])[0] not in ("0", "", "false")
        rec = tracing.recorder()
        if q.get("format", [""])[0] == "chrome":
            payload = rec.chrome_trace(n, slowest=slowest)
        else:
            payload = {"schema_version": DEBUG_SCHEMA_VERSION,
                       "enabled": tracing.trace_enabled(),
                       "slowest": slowest,
                       "stats": dict(rec.stats),
                       "traces": rec.export(n, slowest=slowest)}
        return 200, json.dumps(payload).encode(), "application/json"
    if route == "/debug/dryrun":
        from ..workload import dryrun as dryrun_mod

        payload = {"schema_version": dryrun_mod.DRYRUN_SCHEMA_VERSION,
                   "enabled": featureplane.enabled("KTPU_DRYRUN"),
                   "scan_source": dryrun_mod.scan_source() is not None,
                   "usage": 'POST {"policy": <ClusterPolicy doc>, '
                            '"sample_limit": 5}'}
        return 200, json.dumps(payload).encode(), "application/json"
    return None


def handle_obs_post(path: str, body: bytes, registry=None):
    """Route one POST. Same contract as :func:`handle_obs_get` —
    ``None`` means "not an observability endpoint". Currently one
    route: ``/debug/dryrun`` evaluates a candidate policy's blast
    radius against the registered scan source without touching live
    decisions (workload/dryrun.py; 403 while KTPU_DRYRUN=0)."""
    raw = path.split("?", 1)[0].split("#", 1)[0]
    route = re.sub(r"/{2,}", "/", raw).rstrip("/") or "/"
    if route != "/debug/dryrun":
        return None
    from ..workload import dryrun as dryrun_mod

    try:
        req = json.loads(body or b"{}")
    except ValueError:
        return (400, json.dumps({"error": "body must be JSON"}).encode(),
                "application/json")
    doc = req.get("policy") if isinstance(req, dict) else None
    if not isinstance(doc, dict):
        return (400, json.dumps(
            {"error": 'missing "policy" (a ClusterPolicy doc)'}).encode(),
            "application/json")
    try:
        sample_limit = int(req.get("sample_limit", 5))
    except (TypeError, ValueError):
        sample_limit = 5
    try:
        report = dryrun_mod.dry_run(doc, sample_limit=sample_limit)
    except dryrun_mod.DryRunDisabled as e:
        return (403, json.dumps({"error": str(e)}).encode(),
                "application/json")
    except ValueError as e:
        # no registered scan corpus (or an unloadable candidate)
        return (503, json.dumps({"error": str(e)}).encode(),
                "application/json")
    except Exception as e:
        return (500, json.dumps(
            {"error": f"{type(e).__name__}: {e}"}).encode(),
            "application/json")
    return 200, json.dumps(report).encode(), "application/json"


class ObservabilityServer:
    """Standalone /metrics /healthz /debug/traces listener for
    processes that don't run the webhook server (background scanner,
    bench drivers). Port 0 picks a free port; read it back from
    ``server_port`` after :meth:`start`."""

    def __init__(self, registry=None, host: str = "127.0.0.1",
                 port: int = 9464):
        self.registry = registry
        self.host = host
        self.port = port
        self._httpd: ThreadingHTTPServer | None = None
        self._thread: threading.Thread | None = None

    @property
    def server_port(self) -> int | None:
        return self._httpd.server_address[1] if self._httpd else None

    def start(self) -> ThreadingHTTPServer:
        outer = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *args):
                pass

            def _reply(self, out):
                if out is None:
                    out = (404, b"not found", "text/plain")
                status, body, ctype = out
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                self._reply(handle_obs_get(self.path, outer.registry))

            def do_POST(self):
                length = int(self.headers.get("Content-Length") or 0)
                body = self.rfile.read(length) if length else b""
                self._reply(handle_obs_post(self.path, body,
                                            outer.registry))

        class Httpd(ThreadingHTTPServer):
            daemon_threads = True

        self._httpd = Httpd((self.host, self.port), Handler)
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True,
                                        name="ktpu-obs-http")
        self._thread.start()
        return self._httpd

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
