"""SLO watchdog: rolling burn-rate tracking of admission latency against
the webhook deadline budget.

A Kubernetes ValidatingWebhookConfiguration gives the webhook at most
``timeoutSeconds`` (10s max) before the API server fails open or closed;
the fleet-level question is not "did one request blow it" but "is the
p99 *trending* into the budget". The watchdog keeps two rolling windows
(multi-window burn rate, the SRE-workbook alerting recipe: a short
window that reacts fast and a long window that suppresses blips) of
admission durations, computes p99 per window, and derives

    burn_rate = window_p99 / budget

1.0 means the window's p99 sits exactly at the deadline. ``degraded``
flips when BOTH windows burn past their thresholds — the short window
alone is noise, the long window alone is stale. Queue-depth and
inflight-fill pressure gauges (read back from the metrics registry) ride
along so an operator sees *why* the burn rose.

Observation only: the watchdog never touches a verdict. The batcher may
consult :func:`annotation` for load-shed *annotations* (labels on
flush traces/stats); acting on them is future work. ``KTPU_SLO=0``
turns the whole thing off — ``observe`` becomes a no-op and ``/healthz``
reports ``slo: {"enabled": false}`` with status ``ok``.

Knobs (all dynamic):

- ``KTPU_SLO_BUDGET_S``         deadline budget, default 10.0
- ``KTPU_SLO_WINDOW_SHORT_S``   short window, default 60
- ``KTPU_SLO_WINDOW_LONG_S``    long window, default 600
- ``KTPU_SLO_BURN_DEGRADED``    burn threshold for degraded, default 1.0
- ``KTPU_SLO_MIN_SAMPLES``      samples before a window votes, default 8

Same deferred-settle design as the trace recorder: ``observe()`` is a
lock-free deque append on the admission path; window eviction, p99, and
the ``kyverno_slo_*`` gauge updates all happen in :meth:`snapshot` on
the reader's thread (scrape, /healthz, watchdog consumers).
"""

from __future__ import annotations

import threading
import time
from collections import deque

from . import featureplane
from . import metrics as metrics_mod
from .tracing import slo_enabled


def _env_f(name: str, default: float) -> float:
    try:
        return float(featureplane.raw(name))
    except ValueError:
        return default


def budget_s() -> float:
    return max(1e-9, _env_f("KTPU_SLO_BUDGET_S", 10.0))


def _p99(durations: list) -> float:
    if not durations:
        return 0.0
    xs = sorted(durations)
    return xs[min(len(xs) - 1, int(0.99 * len(xs)))]


class SLOWatchdog:
    """Rolling multi-window admission-latency burn tracker."""

    def __init__(self):
        # (monotonic timestamp, duration_s); appends are GIL-atomic, so
        # the admission path never takes a lock here
        self._samples: deque = deque(maxlen=65536)
        self._lock = threading.Lock()      # snapshot/evict only
        self._last_snap: tuple = (0.0, None)   # (monotonic, snapshot)
        self.stats = {"observed": 0, "degraded_snapshots": 0}

    # --------------------------------------------------------- hot path

    def observe(self, duration_s: float) -> None:
        """One finished admission (webhook review or stream frame).
        Lock-free; no-op under KTPU_SLO=0."""
        if not slo_enabled():
            return
        self._samples.append((time.monotonic(), duration_s))
        self.stats["observed"] += 1

    # ------------------------------------------------------- settle/read

    def snapshot(self) -> dict:
        """Settle and report: evict expired samples, compute per-window
        p99/burn, read pressure gauges, update kyverno_slo_* gauges.
        Runs on the reader's thread (scrape / healthz / batcher hook)."""
        if not slo_enabled():
            return {"enabled": False, "degraded": False}
        short_s = max(1.0, _env_f("KTPU_SLO_WINDOW_SHORT_S", 60.0))
        long_s = max(short_s, _env_f("KTPU_SLO_WINDOW_LONG_S", 600.0))
        threshold = _env_f("KTPU_SLO_BURN_DEGRADED", 1.0)
        min_n = max(1, int(_env_f("KTPU_SLO_MIN_SAMPLES", 8)))
        b = budget_s()
        now = time.monotonic()
        with self._lock:
            while self._samples and now - self._samples[0][0] > long_s:
                self._samples.popleft()
            snap = list(self._samples)
        short = [d for t, d in snap if now - t <= short_s]
        long_ = [d for _, d in snap]
        p99_short, p99_long = _p99(short), _p99(long_)
        burn_short, burn_long = p99_short / b, p99_long / b
        degraded = (len(short) >= min_n and burn_short >= threshold
                    and burn_long >= threshold)
        if degraded:
            self.stats["degraded_snapshots"] += 1

        reg = metrics_mod.registry()
        queue_depth = reg.gauge_value(
            "kyverno_admission_flush_queue_depth") or 0.0
        inflight_fill = reg.gauge_value(
            "kyverno_stream_inflight_batch_fill") or 0.0
        try:
            metrics_mod.record_slo_gauges(
                reg, p99_short=p99_short, p99_long=p99_long,
                burn_short=burn_short, burn_long=burn_long,
                queue_pressure=queue_depth, inflight_fill=inflight_fill,
                degraded=degraded, budget_s=b)
        except Exception:
            pass
        return {
            "enabled": True,
            "degraded": degraded,
            "budget_s": b,
            "burn_rate": {"short": round(burn_short, 4),
                          "long": round(burn_long, 4),
                          "threshold": threshold},
            "p99_s": {"short": round(p99_short, 6),
                      "long": round(p99_long, 6)},
            "windows_s": {"short": short_s, "long": long_s},
            "samples": {"short": len(short), "long": len(long_),
                        "min_for_vote": min_n},
            "pressure": {"flush_queue_depth": queue_depth,
                         "inflight_batch_fill": inflight_fill},
        }

    def cached_snapshot(self, max_age_s: float = 1.0) -> dict:
        """:meth:`snapshot`, amortized for per-flush consumers: reuse
        the last settle when it's younger than ``max_age_s`` so the
        flush hot path never re-sorts the sample windows."""
        now = time.monotonic()
        ts, snap = self._last_snap
        if snap is not None and now - ts <= max_age_s:
            return snap
        snap = self.snapshot()
        self._last_snap = (now, snap)
        return snap

    def degraded(self) -> bool:
        return bool(self.snapshot().get("degraded"))

    def annotation(self, max_age_s: float = 0.0) -> dict | None:
        """Load-shed annotation for the batcher: a small label dict when
        the fleet is degraded, else None. Annotate-only — callers stamp
        it on flush traces/stats and change no behavior. Positive
        ``max_age_s`` serves from the snapshot cache."""
        snap = (self.cached_snapshot(max_age_s) if max_age_s > 0
                else self.snapshot())
        if not snap.get("degraded"):
            return None
        return {"slo": "degraded",
                "slo_burn_short": snap["burn_rate"]["short"]}

    def clear(self) -> None:
        with self._lock:
            self._samples.clear()
        self._last_snap = (0.0, None)
        self.stats["observed"] = 0
        self.stats["degraded_snapshots"] = 0


_watchdog: SLOWatchdog | None = None
_watchdog_lock = threading.Lock()


def watchdog() -> SLOWatchdog:
    global _watchdog
    if _watchdog is None:
        with _watchdog_lock:
            if _watchdog is None:
                _watchdog = SLOWatchdog()
    return _watchdog
