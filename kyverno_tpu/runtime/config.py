"""Dynamic configuration (hot-reloaded ConfigMap).

Mirrors /root/reference/pkg/config/dynamicconfig.go: ``resourceFilters``
([kind,namespace,name] tuples skipped at admission), ``excludeGroupRole``,
``excludeUsername``, ``webhooks`` narrowing, ``generateSuccessEvents`` —
parsed from the kyverno ConfigMap's data and swapped atomically; observers
get change notifications (the reconcile channels of cmd/kyverno/main.go:260).
"""

from __future__ import annotations

import re
import threading
from dataclasses import dataclass, field

from ..utils.wildcard import wildcard_match

# dynamicconfig.go:24-30 defaults
DEFAULT_EXCLUDE_GROUP_ROLE = ["system:serviceaccounts:kube-system", "system:nodes", "system:kube-scheduler"]

_FILTER_RE = re.compile(r"\[([^\[\]]*)\]")


@dataclass(frozen=True)
class ResourceFilter:
    """config.go k8Resource: [Kind,namespace,name] with wildcards."""

    kind: str = "*"
    namespace: str = "*"
    name: str = "*"


def parse_kinds(raw: str) -> list[ResourceFilter]:
    """dynamicconfig.go:372 parseKinds: "[Kind,ns,name][Kind2,...]"."""
    out = []
    for m in _FILTER_RE.finditer(raw or ""):
        parts = [p.strip() for p in m.group(1).split(",")]
        parts += ["*"] * (3 - len(parts))
        out.append(ResourceFilter(*(p or "*" for p in parts[:3])))
    return out


def parse_rbac(raw: str) -> list[str]:
    """dynamicconfig.go:392 parseRbac: comma-separated role list."""
    return [p.strip() for p in (raw or "").split(",") if p.strip()]


@dataclass
class WebhookConfig:
    namespace_selector: dict | None = None
    object_selector: dict | None = None


class ConfigData:
    """dynamicconfig.go:32 ConfigData."""

    def __init__(self, configmap_data: dict | None = None):
        self._lock = threading.RLock()
        self._filters: list[ResourceFilter] = []
        self._exclude_group_role: list[str] = list(DEFAULT_EXCLUDE_GROUP_ROLE)
        self._exclude_username: list[str] = []
        self._webhooks: list[WebhookConfig] = []
        self._generate_success_events: bool = False
        self._observers: list = []
        if configmap_data is not None:
            self.load(configmap_data)

    # ------------------------------------------------------------ reads

    def to_filter(self, kind: str, namespace: str, name: str) -> bool:
        """dynamicconfig.go:49 ToFilter: True => skip this resource."""
        with self._lock:
            for f in self._filters:
                if (
                    wildcard_match(f.kind, kind)
                    and wildcard_match(f.namespace, namespace)
                    and wildcard_match(f.name, name)
                ):
                    return True
            # kyverno's own namespace is always filtered (config.go)
            if namespace == "kyverno":
                return True
        return False

    def get_exclude_group_role(self) -> list[str]:
        with self._lock:
            return list(self._exclude_group_role)

    def get_exclude_username(self) -> list[str]:
        with self._lock:
            return list(self._exclude_username)

    def get_webhooks(self) -> list[WebhookConfig]:
        with self._lock:
            return list(self._webhooks)

    def generate_success_events(self) -> bool:
        with self._lock:
            return self._generate_success_events

    # ------------------------------------------------------------ writes

    def load(self, data: dict) -> None:
        """dynamicconfig.go:233 load: swap config from ConfigMap data."""
        import json

        with self._lock:
            self._filters = parse_kinds(data.get("resourceFilters", ""))
            if "excludeGroupRole" in data:
                self._exclude_group_role = (
                    parse_rbac(data["excludeGroupRole"]) + DEFAULT_EXCLUDE_GROUP_ROLE
                )
            else:
                self._exclude_group_role = list(DEFAULT_EXCLUDE_GROUP_ROLE)
            self._exclude_username = parse_rbac(data.get("excludeUsername", ""))
            self._generate_success_events = (
                str(data.get("generateSuccessEvents", "false")).lower() == "true"
            )
            webhooks = []
            raw = data.get("webhooks", "")
            if raw:
                try:
                    for entry in json.loads(raw):
                        webhooks.append(WebhookConfig(
                            namespace_selector=entry.get("namespaceSelector"),
                            object_selector=entry.get("objectSelector"),
                        ))
                except (ValueError, AttributeError):
                    pass
            self._webhooks = webhooks
            observers = list(self._observers)
        for notify in observers:
            notify()

    def on_change(self, callback) -> None:
        with self._lock:
            self._observers.append(callback)
