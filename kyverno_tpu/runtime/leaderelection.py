"""Leader election over a Lease object.

Mirrors /root/reference/pkg/leaderelection/leaderelection.go (client-go
lease-based election; 15s lease / 10s renew deadline): replicas race to
acquire/renew a coordination.k8s.io Lease through the client; the holder
runs the leader-only controllers (background scan, generate controller,
webhook registration), everyone serves webhooks.

One elector can guard *multiple named leases* (fleet/scanparts.py uses
this for per-partition scan-range ownership): the constructor ``name``
is the primary lease — ``is_leader()``/``on_started_leading``/
``on_stopped_leading`` keep their historical single-lease semantics —
and :meth:`add_lease`/:meth:`drop_lease` enroll secondary names renewed
by the same acquire/renew loop. Secondary transitions are reported
through ``on_lease_acquired(name)``/``on_lease_lost(name)`` (which also
fire for the primary, after the legacy callbacks).
"""

from __future__ import annotations

import threading
import time
import uuid

LEASE_DURATION_S = 15.0
RENEW_DEADLINE_S = 10.0
RETRY_PERIOD_S = 2.0


class LeaderElector:
    def __init__(self, client, name: str = "kyverno", namespace: str = "kyverno",
                 identity: str | None = None,
                 on_started_leading=None, on_stopped_leading=None,
                 on_lease_acquired=None, on_lease_lost=None):
        self.client = client
        self.name = name
        self.namespace = namespace
        self.identity = identity or f"{name}-{uuid.uuid4().hex[:8]}"
        self.on_started_leading = on_started_leading
        self.on_stopped_leading = on_stopped_leading
        self.on_lease_acquired = on_lease_acquired
        self.on_lease_lost = on_lease_lost
        self._leading = False
        self._names: set[str] = {name}
        self._held: set[str] = set()
        self._names_lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # ------------------------------------------------------- lease roster

    def add_lease(self, name: str) -> None:
        """Enroll a secondary named lease; the next election round (and
        every one after) tries to acquire/renew it."""
        with self._names_lock:
            self._names.add(name)

    def drop_lease(self, name: str, release: bool = True) -> None:
        """Stop renewing a named lease. ``release`` clears our holder
        identity so another replica can take it immediately instead of
        waiting out the lease duration. The primary lease cannot be
        dropped — stop() the elector instead."""
        if name == self.name:
            raise ValueError("cannot drop the primary lease; use stop()")
        with self._names_lock:
            self._names.discard(name)
            held = name in self._held
            self._held.discard(name)
        if held:
            if release:
                self._release(name)
            if self.on_lease_lost:
                self.on_lease_lost(name)

    def held(self) -> frozenset:
        """Names of every lease this elector currently holds."""
        with self._names_lock:
            return frozenset(self._held)

    def is_leader(self, name: str | None = None) -> bool:
        if name is None:
            return self._leading
        with self._names_lock:
            return name in self._held

    # --------------------------------------------------------- one round

    def _lease(self, name: str | None = None) -> dict | None:
        return self.client.get_resource(
            "coordination.k8s.io/v1", "Lease", self.namespace,
            name or self.name)

    def try_acquire_or_renew(self) -> bool:
        """One election round over every enrolled lease; returns primary
        leadership (the historical contract)."""
        with self._names_lock:
            names = sorted(self._names)
        now = time.time()
        for name in names:
            try:
                self._try_one(name, now)
            except Exception:
                self._transition(name, False)
        return self._leading

    def _try_one(self, name: str, now: float) -> bool:
        """One acquire/renew attempt for one named lease.

        Updates are compare-and-swap: the observed resourceVersion rides
        along and a Conflict means another replica won the race — treat it
        as a lost election (client-go's resourceVersion-guarded lease
        update semantics), then confirm holdership by re-reading.
        """
        from .client import ConflictError

        lease = self._lease(name)
        if lease is None:
            try:
                self.client.create_resource({
                    "apiVersion": "coordination.k8s.io/v1",
                    "kind": "Lease",
                    "metadata": {"name": name, "namespace": self.namespace},
                    "spec": {
                        "holderIdentity": self.identity,
                        "leaseDurationSeconds": int(LEASE_DURATION_S),
                        "renewTime": now,
                    },
                })
            except ConflictError:
                # another replica created the lease first; re-read to
                # confirm holdership (it may still be us on a retry race)
                lease = self._lease(name)
                holder = ((lease or {}).get("spec") or {}).get(
                    "holderIdentity", "")
                return self._transition(name, holder == self.identity)
            return self._transition(name, True)

        spec = lease.get("spec") or {}
        holder = spec.get("holderIdentity", "")
        renew_time = float(spec.get("renewTime") or 0)
        expired = now - renew_time > LEASE_DURATION_S

        if holder == self.identity or expired or not holder:
            spec["holderIdentity"] = self.identity
            spec["renewTime"] = now
            lease["spec"] = spec
            try:
                # carries the observed metadata.resourceVersion -> CAS; a
                # successful guarded write proves holdership, no re-read
                self.client.update_resource(lease)
            except ConflictError:
                return self._transition(name, False)
            return self._transition(name, True)
        return self._transition(name, False)

    def _transition(self, name: str, leading: bool) -> bool:
        with self._names_lock:
            was = name in self._held
            if leading:
                self._held.add(name)
            else:
                self._held.discard(name)
        if leading and not was:
            if name == self.name:
                self._leading = True
                if self.on_started_leading:
                    self.on_started_leading()
            if self.on_lease_acquired:
                self.on_lease_acquired(name)
        elif not leading and was:
            if name == self.name:
                self._leading = False
                if self.on_stopped_leading:
                    self.on_stopped_leading()
            if self.on_lease_lost:
                self.on_lease_lost(name)
        return leading

    def _demote_all(self) -> None:
        for name in list(self.held()):
            self._transition(name, False)

    def run(self, retry_period_s: float = RETRY_PERIOD_S) -> None:
        def loop():
            while not self._stop.wait(retry_period_s):
                try:
                    self.try_acquire_or_renew()
                except Exception:
                    self._demote_all()

        self.try_acquire_or_renew()
        self._thread = threading.Thread(target=loop, name="leader-elector", daemon=True)
        self._thread.start()

    def _release(self, name: str) -> None:
        """Clear our holder identity from one lease (best-effort CAS)."""
        from .client import ConflictError

        lease = self._lease(name)
        if lease is not None and (lease.get("spec") or {}).get(
            "holderIdentity"
        ) == self.identity:
            lease["spec"]["holderIdentity"] = ""
            try:
                self.client.update_resource(lease)
            except ConflictError:
                pass  # someone else already took the lease

    def stop(self) -> None:
        self._stop.set()
        for name in list(self.held()):
            self._release(name)
            self._transition(name, False)
